"""Round-3 prototype C: kernel-v2 sweep with per-round threshold skip.

Sweep = 1 self round (full tournament per block) + 2k-1 cross rounds
(mod-b pairing across block pairs), each round gated by a fresh Gram
coupling stat (rounds below the target tolerance are skipped via lax.cond).
"""

from __future__ import annotations

import sys
from functools import partial

import jax
import jax.numpy as jnp

from svd_jacobi_tpu.ops import blockwise, pallas_jacobi2 as pj2
from svd_jacobi_tpu.parallel import schedule as sched

HI = jax.lax.Precision.HIGHEST


def _einsum(a, b, spec):
    return jnp.einsum(spec, a, b, precision=HI, preferred_element_type=jnp.float32)


def _polish(q):
    """One Newton-Schulz step: restore Q orthogonality to the f32 floor."""
    n2 = q.shape[-1]
    g = _einsum(q, q, "kij,kil->kjl")
    return _einsum(q, 1.5 * jnp.eye(n2, dtype=q.dtype) - 0.5 * g, "kij,kjl->kil")


def _skip_stat(g):
    """UNMASKED max scaled coupling — the round-skip gate. Unlike the
    convergence stat it does NOT deflate small columns: a sub-noise-floor
    column still deserves its rotations (they keep U orthogonal), it just
    cannot be allowed to block loop termination. Exactly-zero (padding)
    columns contribute 0/tiny = 0."""
    acc = jnp.float32
    g = g.astype(acc)
    n2 = g.shape[-1]
    d = jnp.sqrt(jnp.maximum(jnp.diagonal(g, axis1=-2, axis2=-1), 0.0))
    denom = jnp.maximum(d[:, :, None] * d[:, None, :], jnp.finfo(acc).tiny)
    c = jnp.abs(g) / denom
    return jnp.max(c * (1.0 - jnp.eye(n2, dtype=acc))[None])


def _self_round(blocks, vblocks, dmax2, rtol, interpret, polish, passes=1):
    g = _einsum(blocks, blocks, "kmi,kmj->kij")
    stat, _ = blockwise.off_diag_stats(g, g.shape[-1] // 2, dmax2, "rel")
    skip = _skip_stat(g)

    def do(args):
        blocks, vblocks = args
        q = pj2.self_rotations(g, interpret=interpret, passes=passes)
        if polish:
            q = _polish(q)
        blocks = _einsum(blocks, q, "kmi,kij->kmj")
        if vblocks is not None:
            vblocks = _einsum(vblocks, q, "kmi,kij->kmj")
        return blocks, vblocks

    blocks, vblocks = jax.lax.cond(skip > rtol, do, lambda a: a,
                                   (blocks, vblocks))
    return blocks, vblocks, stat


def _cross_round(top, bot, vtop, vbot, dmax2, rtol, interpret, polish, passes=1):
    b = top.shape[-1]
    x = jnp.concatenate([top, bot], axis=-1)
    g = _einsum(x, x, "kmi,kmj->kij")
    stat, _ = blockwise.off_diag_stats(g, b, dmax2, "rel")
    skip = _skip_stat(g)

    def do(args):
        top, bot, vtop, vbot = args
        q = pj2.cross_rotations(g, interpret=interpret, passes=passes)
        if polish:
            q = _polish(q)
        xn = _einsum(jnp.concatenate([top, bot], axis=-1), q, "kmi,kij->kmj")
        top, bot = xn[..., :b], xn[..., b:]
        if vtop is not None:
            vn = _einsum(jnp.concatenate([vtop, vbot], axis=-1), q, "kmi,kij->kmj")
            vtop, vbot = vn[..., :b], vn[..., b:]
        return top, bot, vtop, vbot

    top, bot, vtop, vbot = jax.lax.cond(skip > rtol, do, lambda a: a,
                                        (top, bot, vtop, vbot))
    return top, bot, vtop, vbot, stat


def _sweep(top, bot, vtop, vbot, dmax2, rtol, interpret, polish, passes=1):
    k, m, b = top.shape
    with_v = vtop is not None
    blocks = jnp.concatenate([top, bot], axis=0)
    vblocks = jnp.concatenate([vtop, vbot], axis=0) if with_v else None
    blocks, vblocks, rel_self = _self_round(blocks, vblocks, dmax2, rtol,
                                            interpret, polish, passes)
    top, bot = blocks[:k], blocks[k:]
    if with_v:
        vtop, vbot = vblocks[:k], vblocks[k:]

    def body(carry, _):
        top, bot, vtop, vbot, mx = carry
        top, bot, vtop, vbot, stat = _cross_round(
            top, bot, vtop, vbot, dmax2, rtol, interpret, polish, passes)
        top, bot = sched.rotate_blocks(top, bot)
        if with_v:
            vtop, vbot = sched.rotate_blocks(vtop, vbot)
        return (top, bot, vtop, vbot, jnp.maximum(mx, stat)), None

    if not with_v:
        vtop = vbot = jnp.zeros((k, 0, b), top.dtype)
    init = (top, bot, vtop, vbot, rel_self.astype(jnp.float32))
    (top, bot, vtop, vbot, off), _ = jax.lax.scan(
        body, init, None, length=sched.num_rounds(2 * k))
    return top, bot, (vtop if with_v else None), (vbot if with_v else None), off


@partial(jax.jit, static_argnames=("nblocks", "tol", "max_sweeps", "compute_v",
                                   "interpret", "polish"))
def proto_svd(a, *, nblocks, tol, max_sweeps, compute_v=True, interpret=False,
              polish=True):
    from svd_jacobi_tpu import solver as slv

    m, n = a.shape
    top, bot = slv._blockify(a, n, nblocks)
    if compute_v:
        vtop, vbot = slv._blockify(jnp.eye(n, dtype=a.dtype), n, nblocks)
    else:
        vtop = vbot = None

    def cond(state):
        _, _, _, _, off, sweeps = state
        return jnp.logical_and(sweeps < max_sweeps, off > tol)

    def body(state):
        top, bot, vtop, vbot, _, sweeps = state
        dmax2 = slv._global_dmax2(top, bot)
        top, bot, nvt, nvb, off = _sweep(top, bot,
                                         vtop if compute_v else None,
                                         vbot if compute_v else None,
                                         dmax2, tol, interpret, polish)
        if compute_v:
            vtop, vbot = nvt, nvb
        return (top, bot, vtop, vbot, off, sweeps + 1)

    inf = jnp.float32(jnp.inf)
    state = (top, bot, vtop, vbot, inf, jnp.int32(0))
    top, bot, vtop, vbot, off, sweeps = jax.lax.while_loop(cond, body, state)
    a_work = slv._deblockify(top, bot)
    v_work = slv._deblockify(vtop, vbot)[:n, :] if compute_v else None
    u, s, v = slv._postprocess(a_work, v_work, n, compute_u=True,
                               full_u=False, dtype=a.dtype)
    return u, s, v, sweeps, off
