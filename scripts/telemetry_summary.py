#!/usr/bin/env python
"""Render or diff `obs.manifest` run records.

A manifest is a JSONL stream — one schema-versioned record per CLI/bench
run (see `svd_jacobi_tpu/obs/manifest.py`). This tool is the human end of
it:

    # render every record of a manifest (newest last)
    python scripts/telemetry_summary.py reports/manifest.jsonl

    # render only the last record / only one kind
    python scripts/telemetry_summary.py reports/manifest.jsonl --last
    python scripts/telemetry_summary.py reports/manifest.jsonl --kind serve

    # SLO report reconstructed from the "serve" records (per-bucket
    # p50/p99, deadline-miss/shed counts, error-budget burn)
    python scripts/telemetry_summary.py reports/manifest.jsonl --slo

    # roofline observatory records (a `cli --profile` run or
    # `python -m svd_jacobi_tpu.perf report --emit` appends them):
    # per-scope ms / GFLOP/s / %-of-roof with device-constant provenance
    python scripts/telemetry_summary.py reports/manifest.jsonl --kind perf

    # diff two records (by index into one file, or across two files);
    # negative indices count from the end, like Python
    python scripts/telemetry_summary.py reports/manifest.jsonl --diff -2 -1
    python scripts/telemetry_summary.py a.jsonl b.jsonl --diff -1 -1

Runs entirely on the host — no jax import, so it works on machines without
an accelerator stack.
"""

from __future__ import annotations

import argparse
import importlib.util
import sys
from pathlib import Path

# Load obs/manifest.py (and obs/registry.py, for --slo) directly by file
# path: importing the package would execute svd_jacobi_tpu/__init__.py,
# which pulls in the solver and jax — exactly the dependency this
# host-side tool promises not to need. Both modules are stdlib-only.
_OBS_DIR = (Path(__file__).resolve().parent.parent / "svd_jacobi_tpu"
            / "obs")


def _load(name: str, filename: str):
    spec = importlib.util.spec_from_file_location(name, _OBS_DIR / filename)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


manifest = _load("_svdj_manifest", "manifest.py")
registry = _load("_svdj_registry", "registry.py")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Render or diff svd_jacobi_tpu run manifests (JSONL).")
    p.add_argument("manifest", help="manifest file (JSONL)")
    p.add_argument("manifest_b", nargs="?", default=None,
                   help="second manifest for a cross-file --diff")
    p.add_argument("--last", action="store_true",
                   help="render only the newest record")
    p.add_argument("--kind", default=None, metavar="KIND",
                   help="render only records of this kind (one of "
                        "the registered manifest kinds, e.g. serve / "
                        "fleet / cache / coldstart / tune)")
    p.add_argument("--slo", action="store_true",
                   help="render the SLO report reconstructed from the "
                        "manifest's 'serve' records (per-bucket p50/p99 "
                        "latency, deadline-miss/shed counts, rolling "
                        "error-budget burn)")
    p.add_argument("--slo-objective", type=float, default=0.99,
                   help="availability objective for the --slo burn rate")
    p.add_argument("--diff", nargs=2, type=int, metavar=("I", "J"),
                   help="diff record I against record J (indices into the "
                        "manifest; with two files, I indexes the first and "
                        "J the second; negative = from the end)")
    p.add_argument("--validate", action="store_true",
                   help="schema-validate every record and exit non-zero on "
                        "the first violation")
    args = p.parse_args(argv)

    records = manifest.load(args.manifest)
    if not records:
        print(f"{args.manifest}: empty manifest", file=sys.stderr)
        return 1

    if args.slo:
        snap = registry.slo_from_records(records,
                                         objective=args.slo_objective)
        if not snap["buckets"]:
            print(f"{args.manifest}: no 'serve' records to build an SLO "
                  f"report from", file=sys.stderr)
            return 1
        print(registry.render_slo(snap))
        return 0

    if args.kind is not None:
        known = sorted(manifest.KINDS)
        if args.kind not in known:
            print(f"unknown --kind {args.kind!r} (registered kinds: "
                  f"{known})", file=sys.stderr)
            return 2
        records = [r for r in records if r.get("kind") == args.kind]
        if not records:
            print(f"{args.manifest}: no {args.kind!r} records",
                  file=sys.stderr)
            return 1

    if args.validate:
        for i, rec in enumerate(records):
            try:
                manifest.validate(rec)
            except ValueError as e:
                print(f"{args.manifest}[{i}]: {e}", file=sys.stderr)
                return 1
        print(f"{args.manifest}: {len(records)} valid record(s)")
        return 0

    if args.diff is not None:
        i, j = args.diff
        records_b = (manifest.load(args.manifest_b)
                     if args.manifest_b else records)
        try:
            a, b = records[i], records_b[j]
        except IndexError:
            print(f"record index out of range ({len(records)} and "
                  f"{len(records_b)} records)", file=sys.stderr)
            return 1
        print(manifest.diff(a, b))
        return 0

    for rec in (records[-1:] if args.last else records):
        print(manifest.summarize(rec))
        print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
