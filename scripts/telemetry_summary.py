#!/usr/bin/env python
"""Render or diff `obs.manifest` run records.

A manifest is a JSONL stream — one schema-versioned record per CLI/bench
run (see `svd_jacobi_tpu/obs/manifest.py`). This tool is the human end of
it:

    # render every record of a manifest (newest last)
    python scripts/telemetry_summary.py reports/manifest.jsonl

    # render only the last record
    python scripts/telemetry_summary.py reports/manifest.jsonl --last

    # diff two records (by index into one file, or across two files);
    # negative indices count from the end, like Python
    python scripts/telemetry_summary.py reports/manifest.jsonl --diff -2 -1
    python scripts/telemetry_summary.py a.jsonl b.jsonl --diff -1 -1

Runs entirely on the host — no jax import, so it works on machines without
an accelerator stack.
"""

from __future__ import annotations

import argparse
import importlib.util
import sys
from pathlib import Path

# Load obs/manifest.py directly by file path: importing the package would
# execute svd_jacobi_tpu/__init__.py, which pulls in the solver and jax —
# exactly the dependency this host-side tool promises not to need.
_MANIFEST = (Path(__file__).resolve().parent.parent / "svd_jacobi_tpu"
             / "obs" / "manifest.py")
_spec = importlib.util.spec_from_file_location("_svdj_manifest", _MANIFEST)
manifest = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(manifest)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Render or diff svd_jacobi_tpu run manifests (JSONL).")
    p.add_argument("manifest", help="manifest file (JSONL)")
    p.add_argument("manifest_b", nargs="?", default=None,
                   help="second manifest for a cross-file --diff")
    p.add_argument("--last", action="store_true",
                   help="render only the newest record")
    p.add_argument("--diff", nargs=2, type=int, metavar=("I", "J"),
                   help="diff record I against record J (indices into the "
                        "manifest; with two files, I indexes the first and "
                        "J the second; negative = from the end)")
    p.add_argument("--validate", action="store_true",
                   help="schema-validate every record and exit non-zero on "
                        "the first violation")
    args = p.parse_args(argv)

    records = manifest.load(args.manifest)
    if not records:
        print(f"{args.manifest}: empty manifest", file=sys.stderr)
        return 1

    if args.validate:
        for i, rec in enumerate(records):
            try:
                manifest.validate(rec)
            except ValueError as e:
                print(f"{args.manifest}[{i}]: {e}", file=sys.stderr)
                return 1
        print(f"{args.manifest}: {len(records)} valid record(s)")
        return 0

    if args.diff is not None:
        i, j = args.diff
        records_b = (manifest.load(args.manifest_b)
                     if args.manifest_b else records)
        try:
            a, b = records[i], records_b[j]
        except IndexError:
            print(f"record index out of range ({len(records)} and "
                  f"{len(records_b)} records)", file=sys.stderr)
            return 1
        print(manifest.diff(a, b))
        return 0

    for rec in (records[-1:] if args.last else records):
        print(manifest.summarize(rec))
        print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
