"""Config search for proto4 on chip. Usage: python scripts/time_proto4.py [N]"""
import sys
import time

sys.path.insert(0, "scripts")

import jax
import jax.numpy as jnp
import numpy as np

import proto4

N = int(sys.argv[1]) if len(sys.argv) > 1 else 2048

key = jax.random.PRNGKey(0)
a = jax.random.normal(key, (N, N), jnp.float32)
tol = float(np.sqrt(N) * np.finfo(np.float32).eps)
an = np.asarray(a, np.float64)
s_ref = np.linalg.svd(an, compute_uv=False)


def _force(tree):
    leaves = [x for x in jax.tree_util.tree_leaves(tree) if x is not None]
    return float(np.asarray(sum(jnp.sum(jnp.abs(x).astype(jnp.float32)) for x in leaves)))


def run(f, *args, reps=2):
    out = f(*args)
    _force(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        _force(f(*args))
        best = min(best, time.perf_counter() - t0)
    return best, out


t_x, _ = run(lambda x: jnp.linalg.svd(x), a)
print(f"xla svd: {t_x:.4f}s", flush=True)

for name, kw in [
    ("b128 hi pre", dict(nblocks=N // 128)),
    ("b128 hi nopolish", dict(nblocks=N // 128, polish=False)),
    ("b128 auto pre", dict(nblocks=N // 128, gprec="auto")),
]:
    t_p, out = run(lambda x, kw=kw: proto4.proto_svd(
        x, tol=tol, max_sweeps=30, **kw), a)
    u, s, v, sweeps, off = out
    un, sn, vn = (np.asarray(u, np.float64), np.asarray(s, np.float64),
                  np.asarray(v, np.float64))
    res = np.linalg.norm(un @ np.diag(sn) @ vn.T - an) / np.linalg.norm(an)
    uo = np.max(np.abs(un.T @ un - np.eye(N)))
    serr = np.max(np.abs(sn - s_ref)) / s_ref[0]
    print(f"{name:18s} {t_p:.4f}s ({int(sweeps)} sw, off {float(off):.1e}) "
          f"x{t_x/t_p:.3f} serr {serr:.1e} uorth {uo:.1e} res {res:.1e}",
          flush=True)
