#!/usr/bin/env bash
# One-shot round-close measurement run: the full BASELINE.md sweep plus the
# mesh-of-1 parity row, each in its own subprocess (compile caches and HBM
# do not leak across sizes). Writes JSON lines to reports/final_sweep.jsonl.
set -u
cd "$(dirname "$0")/.."
out=reports/final_sweep.jsonl
: > "$out"
echo "== bench --sweep =="
python -u bench.py --sweep 2>&1 | grep -v WARNING | tee -a "$out"
echo "== mesh-of-1 2048^2 parity =="
python -u - << 'EOF' 2>&1 | grep -v WARNING | tee -a reports/final_sweep.jsonl
import json, time
import jax, jax.numpy as jnp
from svd_jacobi_tpu.parallel import sharded
from svd_jacobi_tpu.utils import matgen
from svd_jacobi_tpu.utils._exec import force
a = matgen.random_dense(2048, 2048, dtype=jnp.float32)
mesh = sharded.make_mesh(jax.devices()[:1])
f = lambda: sharded.svd(a, mesh=mesh)
r = f(); force(tuple(r[:3]))
best = 1e9
for _ in range(3):
    t0 = time.perf_counter(); force(tuple(f()[:3]))
    best = min(best, time.perf_counter() - t0)
print(json.dumps({"metric": "mesh1_svd_2048_f32_time_s",
                  "value": round(best, 4), "unit": "s",
                  "sweeps": int(r.sweeps)}))
EOF
echo "done: $out"
