"""Time proto_svd vs jnp.linalg.svd on the attached chip.

Usage: python scripts/time_proto.py [N] [b] [precond(0/1)]
"""
import sys
import time

sys.path.insert(0, "scripts")

import jax
import jax.numpy as jnp
import numpy as np

import proto3 as ps

N = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
B = int(sys.argv[2]) if len(sys.argv) > 2 else 128
PRE = bool(int(sys.argv[3])) if len(sys.argv) > 3 else False

key = jax.random.PRNGKey(0)
a = jax.random.normal(key, (N, N), jnp.float32)
nblocks = max(2, N // B)
tol = float(np.sqrt(N) * np.finfo(np.float32).eps)


def _force(tree):
    leaves = [x for x in jax.tree_util.tree_leaves(tree) if x is not None]
    return float(np.asarray(sum(jnp.sum(jnp.abs(x).astype(jnp.float32)) for x in leaves)))


def run(f, *args, reps=2):
    out = f(*args)
    _force(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        _force(f(*args))
        best = min(best, time.perf_counter() - t0)
    return best, out


t_p, out = run(lambda x: ps.proto_svd(
    x, nblocks=nblocks, tol=tol, max_sweeps=30), a)
u, s, v, sweeps, off = out
t_x, _ = run(lambda x: jnp.linalg.svd(x), a)

s_ref = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)
serr = float(np.max(np.abs(np.asarray(s, np.float64) - s_ref)) / s_ref[0])
un = np.asarray(u, np.float64)
uorth = float(np.linalg.norm(un.T @ un - np.eye(N)))
res = float(np.linalg.norm(un @ np.diag(np.asarray(s, np.float64)) @ np.asarray(v, np.float64).T
                           - np.asarray(a, np.float64)) / np.linalg.norm(np.asarray(a, np.float64)))
print(f"N={N} b={B} pre={PRE}: proto {t_p:.4f}s ({int(sweeps)} sweeps, off {float(off):.2e}) "
      f"xla {t_x:.4f}s speedup {t_x/t_p:.3f} serr {serr:.2e} uorth {uorth:.2e} res {res:.2e}",
      flush=True)
