"""Phase breakdown of the mixed bf16-bulk regime on the attached chip.

Times each stage of solver._svd_pallas's mixed path separately (bulk
sweeps / NS + reconstitution / f32 polish) and reports per-phase sweep
counts, so MIXED_TOL, the storage regime (SVDConfig.mixed_store), and the
NS step count can be tuned against the single-jit end-to-end number.

Before timing, each phase's jaxpr is screened with the shared
dtype-boundary pass (`analysis.jaxpr_checks.check_dtype_boundaries`) —
the mixed regime's whole point is that ONLY the declared bf16<->f32
boundaries appear, and an accidental upcast in a hand-built probe stage
silently un-mixes the measurement (this used to be eyeballed).

Usage:

    python scripts/mixed_diag.py [N] [store] [mixed_tol] [ns_steps]

store: f32 (x3 split applies, f32-stored stacks), bf16 (bf16-STORED X
stacks), bf16g (X and the rotation product G both bf16-stored).
"""

import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np

from svd_jacobi_tpu import SVDConfig, solver
from svd_jacobi_tpu.analysis import jaxpr_checks, render_findings
from svd_jacobi_tpu.ops import pallas_blocks as pb
from svd_jacobi_tpu.ops import rounds
from svd_jacobi_tpu.utils import matgen

# Compiled kernels on chip; interpreter bodies elsewhere (same trace
# structure — the dtype-boundary screen is identical), mirroring solver.
INTERPRET = not pb.supported()


def timed(fn, *args):
    from svd_jacobi_tpu.utils._exec import force
    out = fn(*args)
    force(out)
    t0 = time.perf_counter()
    out = fn(*args)
    force(out)
    return time.perf_counter() - t0, out


def check_boundaries(name, fn, *args):
    """Screen one probe stage with the shared jaxpr dtype-boundary pass
    (f32 working dtype: bf16<->f32 moves are the only declared mix)."""
    findings = jaxpr_checks.check_dtype_boundaries(
        jax.make_jaxpr(fn)(*args), f"mixed_diag.{name}", jnp.float32)
    if findings:
        print(render_findings(findings,
                              header=f"{name}: dtype-boundary violations:"))
        sys.exit(1)


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
    store = sys.argv[2] if len(sys.argv) > 2 else "f32"
    assert store in ("f32", "bf16", "bf16g"), store
    mixed_tol = float(sys.argv[3]) if len(sys.argv) > 3 else rounds.MIXED_TOL
    ns_steps = (int(sys.argv[4]) if len(sys.argv) > 4
                else (4 if store == "bf16g" else 2))
    a = matgen.random_dense(n, n, dtype=jnp.float32)
    cfg_b, k = solver._plan(n, 1, SVDConfig())
    nblocks, n_pad = 2 * k, 2 * k * cfg_b
    print(f"n={n} b={cfg_b} k={k} store={store} mixed_tol={mixed_tol} "
          f"ns={ns_steps}")

    t_pre, (q1, r, order, work) = timed(jax.jit(solver._precondition_qr), a)

    @jax.jit
    def bulk(work):
        top, bot = solver._blockify(work, n_pad, nblocks)
        vt, vb = solver._blockify(jnp.eye(n_pad, dtype=work.dtype),
                                  n_pad, nblocks)
        if store in ("bf16", "bf16g"):
            top, bot = top.astype(jnp.bfloat16), bot.astype(jnp.bfloat16)
        if store == "bf16g":
            vt, vb = vt.astype(jnp.bfloat16), vb.astype(jnp.bfloat16)
        _, _, vt, vb, off, sweeps = rounds.iterate_phase(
            top, bot, vt, vb, stop_tol=jnp.float32(mixed_tol),
            rtol=mixed_tol, max_sweeps=32, interpret=INTERPRET, polish=True,
            bf16_gram=True, apply_x3=True,
            stall_gate=10 * mixed_tol, stall_shrink=0.5)
        return vt, vb, off, sweeps

    check_boundaries("bulk", bulk, work)
    t_bulk, (vt, vb, boff, bsweeps) = timed(bulk, work)
    print(f"precond {t_pre:.3f}s | bulk {t_bulk:.3f}s sweeps={int(bsweeps)} "
          f"off={float(boff):.3e}")

    @jax.jit
    def reconstitute(work, vt, vb):
        g = solver._ns_orthogonalize(
            solver._deblockify(vt, vb).astype(jnp.float32), ns_steps)
        x = jnp.matmul(work.astype(g.dtype), g[:work.shape[1], :],
                       precision=jax.lax.Precision.HIGHEST)
        top, bot = solver._blockify(x, n_pad, nblocks)
        gt, gb = solver._blockify(g, n_pad, nblocks)
        return top, bot, gt, gb

    check_boundaries("reconstitute", reconstitute, work, vt, vb)
    t_rec, (top, bot, gt, gb) = timed(reconstitute, work, vt, vb)
    # orthogonality of G pre/post NS
    g_raw = solver._deblockify(vt, vb).astype(jnp.float32)
    gram = jnp.matmul(g_raw.T, g_raw, precision=jax.lax.Precision.HIGHEST)
    e0 = float(jnp.max(jnp.abs(gram - jnp.eye(n_pad))))
    print(f"reconstitute+NS {t_rec:.3f}s (G orth err pre-NS {e0:.3e})")

    @jax.jit
    def polish(top, bot, gt, gb):
        tol = float(np.sqrt(n) * np.finfo(np.float32).eps)
        return rounds.iterate(top, bot, gt, gb, tol=tol, max_sweeps=32,
                              interpret=INTERPRET, polish=True,
                              bulk_bf16=False)

    check_boundaries("polish", polish, top, bot, gt, gb)
    t_pol, (_, _, _, _, poff, psweeps) = timed(polish, top, bot, gt, gb)
    print(f"polish {t_pol:.3f}s sweeps={int(psweeps)} off={float(poff):.3e}")
    total = t_pre + t_bulk + t_rec + t_pol
    print(f"total (stage sum) {total:.3f}s")


if __name__ == "__main__":
    main()
