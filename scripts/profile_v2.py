"""Kernel v2 cost on chip. Usage: python scripts/profile_v2.py"""
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from svd_jacobi_tpu.ops import pallas_jacobi2 as pj2

R = 30
key = jax.random.PRNGKey(0)
HI = jax.lax.Precision.HIGHEST


def t(name, body, init):
    @functools.partial(jax.jit, static_argnames=("reps",))
    def loop(c, reps):
        c = jax.lax.fori_loop(0, reps, lambda i, cc: body(cc), c)
        leaves = jax.tree_util.tree_leaves(c)
        return sum(jnp.sum(jnp.abs(x).astype(jnp.float32)) for x in leaves)

    def run(reps):
        float(np.asarray(loop(init, reps)))
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            float(np.asarray(loop(init, reps)))
            best = min(best, time.perf_counter() - t0)
        return best

    per = (run(4 * R) - run(R)) / (3 * R)
    print(f"{name:56s} {per*1e3:9.3f} ms/iter", flush=True)
    return per


print(f"== on {jax.devices()[0]} ==", flush=True)

for (k, n2) in [(8, 256), (16, 128), (32, 64), (64, 32), (128, 16)]:
    xg = jax.random.normal(key, (k, 512, n2), jnp.float32)
    g0 = jnp.einsum("kmi,kmj->kij", xg, xg, precision=HI)

    def _v2(gg):
        q = pj2.cross_rotations(gg)
        return gg + q * 1e-9

    t(f"cross v2 ({k},{n2},{n2}) {n2//2} steps", _v2, g0)

from svd_jacobi_tpu.ops import pallas_blocks as pb

for (k, n2) in [(8, 256), (16, 256), (16, 128), (32, 64)]:
    xg = jax.random.normal(key, (k, 512, n2), jnp.float32)
    g0 = jnp.einsum("kmi,kmj->kij", xg, xg, precision=HI)

    def _v3(gg):
        q = pb.cross_rotations(gg)
        return gg + q * 1e-9

    t(f"cross v3 4arr ({k},{n2},{n2}) {n2//2} steps", _v3, g0)
