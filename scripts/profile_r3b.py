"""Part 2 of the round-3 on-chip measurements (see profile_r3.py)."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

N = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
K = int(sys.argv[2]) if len(sys.argv) > 2 else 8


def _scalarize(f):
    def g(*args):
        out = f(*args)
        leaves = [x for x in jax.tree_util.tree_leaves(out) if x is not None]
        return sum(jnp.sum(jnp.abs(x).astype(jnp.float32)) for x in leaves)
    return g


def t(name, f, *args, reps=K):
    g = jax.jit(_scalarize(f))
    float(np.asarray(g(*args)))  # compile + warm

    def run(j):
        t0 = time.perf_counter()
        for _ in range(j - 1):
            g(*args)
        float(np.asarray(g(*args)))
        return time.perf_counter() - t0

    t1 = min(run(1) for _ in range(2))
    tK = min(run(reps) for _ in range(2))
    per = (tK - t1) / (reps - 1)
    print(f"{name:56s} {per*1e3:10.3f} ms/call", flush=True)
    return per


key = jax.random.PRNGKey(0)
a = jax.random.normal(key, (N, N), jnp.float32)
HI = jax.lax.Precision.HIGHEST
print(f"== N={N} f32 on {jax.devices()[0]}, K={K} ==", flush=True)

t("jnp.linalg.svd", lambda x: jnp.linalg.svd(x), a, reps=3)
t("qr reduced", lambda x: jnp.linalg.qr(x), a, reps=3)
t("qr r-only", lambda x: jnp.linalg.qr(x, mode="r"), a, reps=3)
t("cholesky(A^TA/N+2I)", lambda x: jnp.linalg.cholesky(
    jnp.matmul(x.T, x, precision=HI) / N + 2 * jnp.eye(N)), a, reps=3)

from svd_jacobi_tpu.ops import blockwise
from svd_jacobi_tpu import solver

for b in (64, 128):
    n2 = 2 * b
    k = max(1, N // n2 // 2)
    g0 = jax.random.normal(key, (2 * k, n2, n2), jnp.float32)
    g0 = jnp.einsum("kij,kil->kjl", g0, g0, precision=HI) + 2 * jnp.eye(n2)
    t(f"batched cholesky (2k={2*k},{n2},{n2})", jnp.linalg.cholesky, g0)
    t(f"batched eigh     (2k={2*k},{n2},{n2})", jnp.linalg.eigh, g0, reps=3)
    top = jax.random.normal(key, (k, N, b), jnp.float32)
    bot = jax.random.normal(key, (k, N, b), jnp.float32)
    t(f"batched qr-r     (k={k},{N},{n2})",
      lambda tp, bt: jnp.linalg.qr(jnp.concatenate([tp, bt], -1), mode="r"),
      top, bot, reps=3)
    vt = jax.random.normal(key, (k, N, b), jnp.float32)
    vb = jax.random.normal(key, (k, N, b), jnp.float32)
    for method, crit in [("gram-eigh", "abs"), ("qr-svd", "rel")]:
        t(f"one ROUND {method} b={b} +V",
          lambda tp, bt, v1, v2, me=method, cr=crit: blockwise.orthogonalize_pairs(
              tp, bt, v1, v2, precision="highest", gram_dtype=jnp.float32,
              method=me, criterion=cr, dmax2=jnp.float32(N))[:4],
          top, bot, vt, vb, reps=4)
    t(f"one SWEEP gram-eigh b={b} +V",
      lambda tp, bt, v1, v2: solver._sweep(
          tp, bt, v1, v2, precision="highest", gram_dtype=jnp.float32,
          method="gram-eigh", criterion="abs", dmax2=jnp.float32(N))[:4],
      top, bot, vt, vb, reps=3)
