"""Round-3 prototype D: preconditioned kernel-v3 solver.

Pipeline: norm-sort columns -> QR -> one-sided block Jacobi on L = R^T with
the 4-array Pallas kernels -> U = Q1 @ V_L, V = P @ U_L.

Parameterized for on-chip config search: block width, apply/gram precision.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from svd_jacobi_tpu.ops import blockwise, pallas_blocks as pb
from svd_jacobi_tpu.parallel import schedule as sched

HI = jax.lax.Precision.HIGHEST
PREC = {"highest": jax.lax.Precision.HIGHEST, "high": jax.lax.Precision.HIGH,
        "default": jax.lax.Precision.DEFAULT}


def _einsum(a, b, spec, prec=HI):
    if prec == "bf16":
        return jnp.einsum(spec, a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
                          preferred_element_type=jnp.float32)
    return jnp.einsum(spec, a, b, precision=prec,
                      preferred_element_type=jnp.float32)


def _polish(q):
    n2 = q.shape[-1]
    g = _einsum(q, q, "kij,kil->kjl")
    return _einsum(q, 1.5 * jnp.eye(n2, dtype=q.dtype) - 0.5 * g, "kij,kjl->kil")


def _stats(g, dmax2):
    """(masked_rel, unmasked) max scaled coupling, one fused pass."""
    f32 = jnp.float32
    g = g.astype(f32)
    n2 = g.shape[-1]
    eps = jnp.finfo(f32).eps
    d2 = jnp.diagonal(g, axis1=-2, axis2=-1)
    inv = 1.0 / jnp.maximum(d2, jnp.finfo(f32).tiny)          # (k, n2) divs only
    r2 = (g * g) * inv[:, :, None] * inv[:, None, :] * (1.0 - jnp.eye(n2, dtype=f32))[None]
    unmasked = jnp.sqrt(jnp.max(r2))
    null2 = dmax2.astype(f32) * (n2 * eps) ** 2
    live = d2 > null2
    pair = live[:, :, None] & live[:, None, :]
    masked = jnp.sqrt(jnp.max(jnp.where(pair, r2, 0.0)))
    return masked, unmasked


def _self_round(blocks, vblocks, dmax2, rtol, interpret, polish, gprec):
    g = _einsum(blocks, blocks, "kmi,kmj->kij",
                "bf16" if gprec == "bf16" else PREC[gprec])
    stat, skip = _stats(g, dmax2)

    def do(args):
        blocks, vblocks = args
        q = pb.self_rotations(g, interpret=interpret, polish=polish)
        blocks = _einsum(blocks, q, "kmi,kij->kmj")
        if vblocks is not None:
            vblocks = _einsum(vblocks, q, "kmi,kij->kmj")
        return blocks, vblocks

    blocks, vblocks = jax.lax.cond(skip > rtol, do, lambda a: a,
                                   (blocks, vblocks))
    return blocks, vblocks, stat


def _cross_round(top, bot, vtop, vbot, dmax2, rtol, interpret, polish,
                 gprec, aprec):
    b = top.shape[-1]
    x = jnp.concatenate([top, bot], axis=-1)
    g = _einsum(x, x, "kmi,kmj->kij",
                "bf16" if gprec == "bf16" else PREC[gprec])
    stat, skip = _stats(g, dmax2)

    def do(args):
        top, bot, vtop, vbot = args
        q = pb.cross_rotations(g, interpret=interpret, polish=polish)
        xn = _einsum(jnp.concatenate([top, bot], axis=-1), q, "kmi,kij->kmj",
                     PREC[aprec])
        top, bot = xn[..., :b], xn[..., b:]
        if vtop is not None:
            vn = _einsum(jnp.concatenate([vtop, vbot], axis=-1), q,
                         "kmi,kij->kmj", PREC[aprec])
            vtop, vbot = vn[..., :b], vn[..., b:]
        return top, bot, vtop, vbot

    top, bot, vtop, vbot = jax.lax.cond(skip > rtol, do, lambda a: a,
                                        (top, bot, vtop, vbot))
    return top, bot, vtop, vbot, stat


def _sweep(top, bot, vtop, vbot, dmax2, rtol, interpret, polish, gprec, aprec):
    k, m, b = top.shape
    with_v = vtop is not None
    blocks = jnp.concatenate([top, bot], axis=0)
    vblocks = jnp.concatenate([vtop, vbot], axis=0) if with_v else None
    blocks, vblocks, rel_self = _self_round(blocks, vblocks, dmax2, rtol,
                                            interpret, polish, gprec)
    top, bot = blocks[:k], blocks[k:]
    if with_v:
        vtop, vbot = vblocks[:k], vblocks[k:]

    def body(carry, _):
        top, bot, vtop, vbot, mx = carry
        top, bot, vtop, vbot, stat = _cross_round(
            top, bot, vtop, vbot, dmax2, rtol, interpret, polish, gprec, aprec)
        top, bot = sched.rotate_blocks(top, bot)
        if with_v:
            vtop, vbot = sched.rotate_blocks(vtop, vbot)
        return (top, bot, vtop, vbot, jnp.maximum(mx, stat)), None

    if not with_v:
        vtop = vbot = jnp.zeros((k, 0, b), top.dtype)
    init = (top, bot, vtop, vbot, rel_self.astype(jnp.float32))
    (top, bot, vtop, vbot, off), _ = jax.lax.scan(
        body, init, None, length=sched.num_rounds(2 * k))
    return top, bot, (vtop if with_v else None), (vbot if with_v else None), off


@partial(jax.jit, static_argnames=("nblocks", "tol", "max_sweeps",
                                   "interpret", "polish", "gprec", "aprec",
                                   "precond"))
def proto_svd(a, *, nblocks, tol, max_sweeps, interpret=False, polish=True,
              gprec="highest", aprec="highest", precond=True):
    from svd_jacobi_tpu import solver as slv

    m, n = a.shape
    q1 = None
    order = None
    if precond:
        norms = jnp.sum(a.astype(jnp.float32) ** 2, axis=0)
        order = jnp.argsort(-norms)
        q1, r = jnp.linalg.qr(jnp.take(a, order, axis=1))
        a = r.T  # L: Jacobi on the lower-triangular factor's columns
        m = n

    top, bot = slv._blockify(a, n, nblocks)
    vtop, vbot = slv._blockify(jnp.eye(n, dtype=a.dtype), n, nblocks)

    bulk_tol = 3e-2

    def mk(gp, stop_tol, rtol):
        def cond(state):
            _, _, _, _, off, sweeps = state
            return jnp.logical_and(sweeps < max_sweeps, off > stop_tol)

        def body(state):
            top, bot, vtop, vbot, _, sweeps = state
            dmax2 = slv._global_dmax2(top, bot)
            top, bot, vtop, vbot, off = _sweep(top, bot, vtop, vbot,
                                               dmax2, rtol, interpret, polish,
                                               gp, aprec)
            return (top, bot, vtop, vbot, off, sweeps + 1)
        return cond, body

    inf = jnp.float32(jnp.inf)
    state = (top, bot, vtop, vbot, inf, jnp.int32(0))
    if gprec == "auto":
        # Phase A: bf16 Gram panels (angles/stats only see ~4e-3 noise,
        # harmless above bulk_tol; the APPLY matmuls stay full f32 so no
        # backward error enters X or V). Phase B: full-precision grams.
        ca, ba = mk("bf16", bulk_tol, bulk_tol)
        state = jax.lax.while_loop(ca, ba, state)
        cb, bb = mk("highest", tol, tol)
        top, bot, vtop, vbot, off, sweeps = jax.lax.while_loop(cb, bb, state)
    else:
        c1, b1 = mk(gprec, tol, tol)
        top, bot, vtop, vbot, off, sweeps = jax.lax.while_loop(c1, b1, state)
    a_work = slv._deblockify(top, bot)
    v_work = slv._deblockify(vtop, vbot)[:n, :]
    # One-sided Jacobi on L: L = U_L S V_L^T with U_L = normalized columns,
    # V_L = accumulated rotations.
    u_l, s, v_l = slv._postprocess(a_work, v_work, n, compute_u=True,
                                   full_u=False, dtype=a.dtype)
    if precond:
        # A P = Q1 R = Q1 L^T = Q1 (V_L S U_L^T)^T ... A = U S V^T with
        # U = Q1 V_L and V = P U_L (P = the sort permutation on columns).
        u = jnp.matmul(q1, v_l, precision=HI)
        v = jnp.zeros_like(u_l).at[order, :].set(u_l)
        return u, s, v, sweeps, off
    return u_l, s, v_l, sweeps, off
