"""Scratch Pallas kernel variants for the round-3 perf push.

Variant A: batched-k congruence tournament (current kernel's math, but all k
panels processed inside ONE kernel body so per-step vector-op overhead is
amortized over the whole batch).

Variant B: one-sided tournament on the Cholesky factors R of the Gram panels
(R^T R = G): rotations act on R's columns only (no row transform), alpha is a
true dot product, beta/gamma are carried in closed form — roughly half the
per-step passes of the congruence form.

The winner is folded into svd_jacobi_tpu/ops/pallas_jacobi.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _shift_cols(top, bot):
    """Circle-method tournament shift on the last axis (slot 0 fixed)."""
    if top.shape[-1] == 1:
        return top, bot
    new_top = jnp.concatenate([top[..., :1], bot[..., :1], top[..., 1:-1]], axis=-1)
    new_bot = jnp.concatenate([bot[..., 1:], top[..., -1:]], axis=-1)
    return new_top, new_bot


def _shift_rows(top, bot):
    if top.shape[-2] == 1:
        return top, bot
    new_top = jnp.concatenate([top[..., :1, :], bot[..., :1, :], top[..., 1:-1, :]], axis=-2)
    new_bot = jnp.concatenate([bot[..., 1:, :], top[..., -1:, :]], axis=-2)
    return new_top, new_bot


def _rutishauser(alpha, beta, gamma):
    f32 = jnp.float32
    tiny = jnp.finfo(f32).tiny
    safe_a = jnp.where(jnp.abs(alpha) > tiny, alpha, jnp.ones_like(alpha))
    tau = (gamma - beta) / (2.0 * safe_a)
    sgn = jnp.where(tau >= 0, f32(1.0), f32(-1.0))
    t = sgn / (jnp.abs(tau) + jnp.sqrt(1.0 + tau * tau))
    c = jax.lax.rsqrt(1.0 + t * t)
    s = t * c
    rot = jnp.abs(alpha) > tiny
    c = jnp.where(rot, c, f32(1.0))
    s = jnp.where(rot, s, f32(0.0))
    return c, s


# --------------------------------------------------------------------------
# Variant A: batched congruence


def _body_a(g, dmax2, *, n_steps):
    k, n2, _ = g.shape
    b2 = n2 // 2
    f32 = jnp.float32
    eps = jnp.finfo(f32).eps
    tiny = jnp.finfo(f32).tiny
    null_thresh = dmax2 * (n2 * eps) ** 2

    q0 = jnp.broadcast_to(jnp.eye(n2, dtype=f32), (k, n2, n2))
    diag_mask = (jax.lax.broadcasted_iota(jnp.int32, (b2, b2), 0)
                 == jax.lax.broadcasted_iota(jnp.int32, (b2, b2), 1)).astype(f32)[None]

    def step(_, carry):
        g, q, max_rel = carry
        alpha = jnp.sum(g[:, :b2, b2:] * diag_mask, axis=1)[:, None, :]  # (k,1,b2)
        beta = jnp.sum(g[:, :b2, :b2] * diag_mask, axis=1)[:, None, :]
        gamma = jnp.sum(g[:, b2:, b2:] * diag_mask, axis=1)[:, None, :]
        denom = jnp.sqrt(jnp.maximum(beta, tiny)) * jnp.sqrt(jnp.maximum(gamma, tiny))
        rel = jnp.abs(alpha) / jnp.maximum(denom, tiny)
        live = (beta > null_thresh) & (gamma > null_thresh)
        max_rel = jnp.maximum(max_rel, jnp.max(jnp.where(live, rel, 0.0)))
        c, s = _rutishauser(alpha, beta, gamma)
        g = jnp.concatenate(
            [c * g[..., :b2] - s * g[..., b2:], s * g[..., :b2] + c * g[..., b2:]],
            axis=-1)
        cT, sT = c.transpose(0, 2, 1), s.transpose(0, 2, 1)
        g = jnp.concatenate(
            [cT * g[:, :b2] - sT * g[:, b2:], sT * g[:, :b2] + cT * g[:, b2:]],
            axis=-2)
        q = jnp.concatenate(
            [c * q[..., :b2] - s * q[..., b2:], s * q[..., :b2] + c * q[..., b2:]],
            axis=-1)
        gt, gb = _shift_cols(g[..., :b2], g[..., b2:])
        g = jnp.concatenate([gt, gb], axis=-1)
        gt, gb = _shift_rows(g[:, :b2], g[:, b2:])
        g = jnp.concatenate([gt, gb], axis=-2)
        qt, qb = _shift_cols(q[..., :b2], q[..., b2:])
        q = jnp.concatenate([qt, qb], axis=-1)
        return g, q, max_rel

    _, q, max_rel = jax.lax.fori_loop(0, n_steps, step, (g, q0, jnp.zeros((), f32)))
    return q, max_rel


def _kernel_a(g_ref, dmax2_ref, q_ref, stat_ref, *, n_steps):
    q, max_rel = _body_a(g_ref[...], dmax2_ref[0], n_steps=n_steps)
    q_ref[...] = q
    stat_ref[0] = max_rel


@functools.partial(jax.jit, static_argnames=("interpret",))
def rotations_a(g, dmax2, *, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    k, n2, _ = g.shape
    kernel = functools.partial(_kernel_a, n_steps=max(n2 - 1, 1))
    q, stat = pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_shape=[jax.ShapeDtypeStruct((k, n2, n2), jnp.float32),
                   jax.ShapeDtypeStruct((1,), jnp.float32)],
        interpret=interpret,
    )(g.astype(jnp.float32), jnp.reshape(dmax2.astype(jnp.float32), (1,)))
    return q, stat[0]


# --------------------------------------------------------------------------
# Variant B: one-sided on Cholesky factors


def _body_b(r, dmax2, *, n_steps):
    k, n2, _ = r.shape
    b2 = n2 // 2
    f32 = jnp.float32
    eps = jnp.finfo(f32).eps
    tiny = jnp.finfo(f32).tiny
    null_thresh = dmax2 * (n2 * eps) ** 2

    q0 = jnp.broadcast_to(jnp.eye(n2, dtype=f32), (k, n2, n2))
    rt, rb = r[..., :b2], r[..., b2:]
    qt, qb = q0[..., :b2], q0[..., b2:]
    beta = jnp.sum(rt * rt, axis=-2)[:, None, :]    # (k,1,b2)
    gamma = jnp.sum(rb * rb, axis=-2)[:, None, :]

    def step(_, carry):
        rt, rb, qt, qb, beta, gamma, max_rel = carry
        alpha = jnp.sum(rt * rb, axis=-2)[:, None, :]
        denom = jnp.sqrt(jnp.maximum(beta, tiny)) * jnp.sqrt(jnp.maximum(gamma, tiny))
        rel = jnp.abs(alpha) / jnp.maximum(denom, tiny)
        live = (beta > null_thresh) & (gamma > null_thresh)
        max_rel = jnp.maximum(max_rel, jnp.max(jnp.where(live, rel, 0.0)))
        c, s = _rutishauser(alpha, beta, gamma)
        rt, rb = c * rt - s * rb, s * rt + c * rb
        qt, qb = c * qt - s * qb, s * qt + c * qb
        # Closed-form norm updates (alpha is the pre-rotation coupling).
        cc, ss, cs2 = c * c, s * s, 2.0 * c * s
        beta, gamma = (cc * beta - cs2 * alpha + ss * gamma,
                       ss * beta + cs2 * alpha + cc * gamma)
        rt, rb = _shift_cols(rt, rb)
        qt, qb = _shift_cols(qt, qb)
        beta, gamma = _shift_cols(beta, gamma)
        return rt, rb, qt, qb, beta, gamma, max_rel

    rt, rb, qt, qb, beta, gamma, max_rel = jax.lax.fori_loop(
        0, n_steps, step, (rt, rb, qt, qb, beta, gamma, jnp.zeros((), f32)))
    return jnp.concatenate([qt, qb], axis=-1), max_rel


def _kernel_b(r_ref, dmax2_ref, q_ref, stat_ref, *, n_steps):
    q, max_rel = _body_b(r_ref[...], dmax2_ref[0], n_steps=n_steps)
    q_ref[...] = q
    stat_ref[0] = max_rel


@functools.partial(jax.jit, static_argnames=("interpret",))
def rotations_b(r, dmax2, *, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    k, n2, _ = r.shape
    kernel = functools.partial(_kernel_b, n_steps=max(n2 - 1, 1))
    q, stat = pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_shape=[jax.ShapeDtypeStruct((k, n2, n2), jnp.float32),
                   jax.ShapeDtypeStruct((1,), jnp.float32)],
        interpret=interpret,
    )(r.astype(jnp.float32), jnp.reshape(dmax2.astype(jnp.float32), (1,)))
    return q, stat[0]


# --------------------------------------------------------------------------
# Variant C: cross-only rotation round (gridded over panels).
#
# One call annihilates exactly the b2*b2 cross-block couplings of each
# [I | J] panel: b2 steps, each rotating the b2 disjoint pairs (i, b2+i),
# then cyclically rolling block J's columns/rows by one so every (i, j)
# cross pair is met exactly once. Within-block pairs are NOT re-annihilated
# (they are handled once per sweep by the self-tournament kernel) — this
# removes the ~50% redundant work of a full 2b-tournament per round.
# beta/gamma are carried in closed form (no per-step diagonal reductions);
# the convergence stat is max'd into a (1, b2) vector and reduced once.


def _body_cross(g, dmax2, *, n_steps):
    n2 = g.shape[-1]
    b2 = n2 // 2
    f32 = jnp.float32
    eps = jnp.finfo(f32).eps
    tiny = jnp.finfo(f32).tiny
    null_thresh = dmax2 * (n2 * eps) ** 2

    rows = jax.lax.broadcasted_iota(jnp.int32, (n2, n2), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (n2, n2), 1)
    q0 = (rows == cols).astype(f32)
    diag_mask = (jax.lax.broadcasted_iota(jnp.int32, (b2, b2), 0)
                 == jax.lax.broadcasted_iota(jnp.int32, (b2, b2), 1)).astype(f32)

    def step(_, carry):
        g, q, rel_acc = carry
        alpha = jnp.sum(g[:b2, b2:] * diag_mask, axis=0)[None, :]   # (1, b2)
        beta = jnp.sum(g[:b2, :b2] * diag_mask, axis=0)[None, :]
        gamma = jnp.sum(g[b2:, b2:] * diag_mask, axis=0)[None, :]
        denom = jnp.sqrt(jnp.maximum(beta, tiny)) * jnp.sqrt(jnp.maximum(gamma, tiny))
        rel = jnp.abs(alpha) / jnp.maximum(denom, tiny)
        live = (beta > null_thresh) & (gamma > null_thresh)
        rel_acc = jnp.maximum(rel_acc, jnp.where(live, rel, f32(0.0)))

        c, s = _rutishauser(alpha, beta, gamma)

        g = jnp.concatenate(
            [c * g[:, :b2] - s * g[:, b2:], s * g[:, :b2] + c * g[:, b2:]], axis=1)
        cT, sT = c.T, s.T
        g = jnp.concatenate(
            [cT * g[:b2] - sT * g[b2:], sT * g[:b2] + cT * g[b2:]], axis=0)
        q = jnp.concatenate(
            [c * q[:, :b2] - s * q[:, b2:], s * q[:, :b2] + c * q[:, b2:]], axis=1)

        # Roll block J by one: its columns, its rows, its Q columns, gamma.
        g = jnp.concatenate(
            [g[:, :b2], g[:, b2 + 1:], g[:, b2:b2 + 1]], axis=1)
        g = jnp.concatenate([g[:b2], g[b2 + 1:], g[b2:b2 + 1]], axis=0)
        q = jnp.concatenate(
            [q[:, :b2], q[:, b2 + 1:], q[:, b2:b2 + 1]], axis=1)
        return g, q, rel_acc

    g, q, rel_acc = jax.lax.fori_loop(
        0, n_steps, step, (g, q0, jnp.zeros((1, b2), f32)))
    return q, jnp.max(rel_acc)


def _kernel_cross(g_ref, dmax2_ref, q_ref, stat_ref, *, n_steps):
    from jax.experimental import pallas as pl

    q, max_rel = _body_cross(g_ref[0].astype(jnp.float32), dmax2_ref[0],
                             n_steps=n_steps)
    q_ref[0] = q.astype(q_ref.dtype)
    stat_ref[pl.program_id(0)] = max_rel


@functools.partial(jax.jit, static_argnames=("interpret",))
def rotations_cross(g, dmax2, *, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    k, n2, _ = g.shape
    kernel = functools.partial(_kernel_cross, n_steps=n2 // 2)
    q, stat = pl.pallas_call(
        kernel,
        grid=(k,),
        in_specs=[pl.BlockSpec((1, n2, n2), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[pl.BlockSpec((1, n2, n2), lambda i: (i, 0, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_shape=[jax.ShapeDtypeStruct((k, n2, n2), jnp.float32),
                   jax.ShapeDtypeStruct((k,), jnp.float32)],
        interpret=interpret,
    )(g.astype(jnp.float32), jnp.reshape(dmax2.astype(jnp.float32), (1,)))
    return q, jnp.max(stat)
