"""Round-3 part 3: per-round einsum cost vs precision/dtype + kernel cost.

Usage: python scripts/profile_r3c.py [N] [K]
"""
import sys
import time

sys.path.insert(0, "scripts")

import jax
import jax.numpy as jnp
import numpy as np

N = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
K = int(sys.argv[2]) if len(sys.argv) > 2 else 16


def _scalarize(f):
    def g(*args):
        out = f(*args)
        leaves = [x for x in jax.tree_util.tree_leaves(out) if x is not None]
        return sum(jnp.sum(jnp.abs(x).astype(jnp.float32)) for x in leaves)
    return g


def t(name, f, *args, reps=K, flops=None):
    g = jax.jit(_scalarize(f))
    float(np.asarray(g(*args)))

    def run(j):
        t0 = time.perf_counter()
        for _ in range(j - 1):
            g(*args)
        float(np.asarray(g(*args)))
        return time.perf_counter() - t0

    t1 = min(run(1) for _ in range(3))
    tK = min(run(reps) for _ in range(3))
    per = (tK - t1) / (reps - 1)
    extra = f"  {flops/per/1e12:8.2f} TF/s" if flops else ""
    print(f"{name:52s} {per*1e3:9.3f} ms/call{extra}", flush=True)
    return per


key = jax.random.PRNGKey(0)
print(f"== N={N} on {jax.devices()[0]}, K={K} ==", flush=True)

b = 128
n2 = 2 * b
k = N // n2
x = jax.random.normal(key, (k, N, n2), jnp.float32)
q = jax.random.normal(key, (k, n2, n2), jnp.float32) * 0.1
gf_gram = 2 * k * N * n2 * n2

for prec in ("default", "high", "highest"):
    p = dict(default=jax.lax.Precision.DEFAULT, high=jax.lax.Precision.HIGH,
             highest=jax.lax.Precision.HIGHEST)[prec]
    t(f"gram einsum f32 {prec} (k={k},{N},{n2})",
      lambda xx, pp=p: jnp.einsum("kmi,kmj->kij", xx, xx, precision=pp,
                                  preferred_element_type=jnp.float32),
      x, flops=gf_gram)
    t(f"apply einsum f32 {prec}",
      lambda xx, qq, pp=p: jnp.einsum("kmi,kij->kmj", xx, qq, precision=pp,
                                      preferred_element_type=jnp.float32),
      x, q, flops=gf_gram)

xb = x.astype(jnp.bfloat16)
qb = q.astype(jnp.bfloat16)
t("gram einsum bf16->f32", lambda xx: jnp.einsum(
    "kmi,kmj->kij", xx, xx, preferred_element_type=jnp.float32), xb, flops=gf_gram)
t("apply einsum bf16->f32", lambda xx, qq: jnp.einsum(
    "kmi,kij->kmj", xx, qq, preferred_element_type=jnp.float32), xb, qb, flops=gf_gram)
t("cast f32->bf16 (k,m,n2)", lambda xx: xx.astype(jnp.bfloat16), x)

# Kernel costs at the shapes the solver uses.
import kernel_variants as kv
from svd_jacobi_tpu.ops import pallas_jacobi

g0 = jnp.einsum("kmi,kmj->kij", x, x, precision="highest")
dmax2 = jnp.max(jnp.diagonal(g0, axis1=-2, axis2=-1))
t(f"cross kernel ({k},{n2},{n2}) {n2//2} steps",
  lambda gg, dd: kv.rotations_cross(gg, dd), g0, dmax2)
t(f"full tournament kernel ({k},{n2},{n2}) {n2-1} steps",
  lambda gg, dd: pallas_jacobi.rotations(gg, dd), g0, dmax2)
blocks = jax.random.normal(key, (2 * k, N, b), jnp.float32)
gs = jnp.einsum("kmi,kmj->kij", blocks, blocks, precision="highest")
t(f"self kernel ({2*k},{b},{b}) {b-1} steps",
  lambda gg, dd: pallas_jacobi.rotations(gg, dd), gs, dmax2)

# Fused round at two precisions (gram + kernel + apply X,V in one jit).
v = jax.random.normal(key, (k, N, n2), jnp.float32)


def round_f32(xx, vv, prec):
    g = jnp.einsum("kmi,kmj->kij", xx, xx, precision=prec,
                   preferred_element_type=jnp.float32)
    d = jnp.max(jnp.diagonal(g, axis1=-2, axis2=-1))
    qq, _ = kv.rotations_cross(g, d)
    xn = jnp.einsum("kmi,kij->kmj", xx, qq, precision=prec,
                    preferred_element_type=jnp.float32)
    vn = jnp.einsum("kmi,kij->kmj", vv, qq, precision=prec,
                    preferred_element_type=jnp.float32)
    return xn, vn


def round_bf16(xx, vv):
    g = jnp.einsum("kmi,kmj->kij", xx.astype(jnp.bfloat16), xx.astype(jnp.bfloat16),
                   preferred_element_type=jnp.float32)
    d = jnp.max(jnp.diagonal(g, axis1=-2, axis2=-1))
    qq, _ = kv.rotations_cross(g, d)
    qq16 = qq.astype(jnp.bfloat16)
    xn = jnp.einsum("kmi,kij->kmj", xx.astype(jnp.bfloat16), qq16,
                    preferred_element_type=jnp.float32)
    vn = jnp.einsum("kmi,kij->kmj", vv.astype(jnp.bfloat16), qq16,
                    preferred_element_type=jnp.float32)
    return xn, vn


t("ROUND f32 highest (gram+kernel+applyXV)",
  lambda xx, vv: round_f32(xx, vv, jax.lax.Precision.HIGHEST), x, v)
t("ROUND f32 default", lambda xx, vv: round_f32(xx, vv, jax.lax.Precision.DEFAULT), x, v)
t("ROUND bf16-in f32-acc", round_bf16, x, v)
