#!/usr/bin/env bash
# Multi-host launch recipe — successor of the reference's SLURM scripts
# (/root/reference/build/buildSVDMPICUDA.slurm, runSVDMPICUDA.slurm,
# runSVDMPICUDAWithoutCMake.slurm: 2 nodes x 1 GPU, mpiexec --map-by
# ppr:1:node, OMP_NUM_THREADS=36).
#
# On a Cloud TPU pod slice there is no mpiexec: every host runs the SAME
# command, and jax.distributed.initialize() (called by
# svd_jacobi_tpu.parallel.launch.initialize, which the CLI invokes under
# --distributed) auto-discovers the coordinator from the TPU metadata:
#
#   gcloud compute tpus tpu-vm ssh $TPU_NAME --worker=all --command \
#     "cd svd-jacobi-tpu && python -m svd_jacobi_tpu.cli 16384 --distributed"
#
# On SLURM clusters (CPU/GPU backends), one task per node, like the
# reference's --tasks-per-node=1:
#
#   #SBATCH -N 2 --tasks-per-node=1
#   srun python -m svd_jacobi_tpu.cli 16384 --distributed
#
# (jax.distributed.initialize auto-detects SLURM via SLURM_* env vars.)
#
# For a local smoke test of the multi-process path without any cluster,
# emulate N virtual devices on CPU — this is what this script runs:

set -euo pipefail
N=${1:-1024}
DEVICES=${2:-8}

XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=${DEVICES}" \
JAX_PLATFORMS=cpu \
python -m svd_jacobi_tpu.cli "${N}" --distributed --no-selftest "${@:3}"
