"""On-chip measurements driving the round-3 perf work (RTT-amortized).

The axon tunnel costs ~140 ms per dispatch+readback, so per-op device time
is measured as (t_K - t_1)/(K - 1) with K queued dispatches and one scalar
readback (methodology of profile_parts2.py). Results + conclusions are
recorded in PROFILE.md.

Usage: python scripts/profile_r3.py [N] [K]
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

N = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
K = int(sys.argv[2]) if len(sys.argv) > 2 else 8


def _scalarize(f):
    def g(*args):
        out = f(*args)
        leaves = [x for x in jax.tree_util.tree_leaves(out) if x is not None]
        return sum(jnp.sum(jnp.abs(x).astype(jnp.float32)) for x in leaves)
    return g


def t(name, f, *args, reps=K):
    g = jax.jit(_scalarize(f))
    float(np.asarray(g(*args)))  # compile + warm

    def run(j):
        t0 = time.perf_counter()
        for _ in range(j - 1):
            g(*args)
        float(np.asarray(g(*args)))
        return time.perf_counter() - t0

    t1 = min(run(1) for _ in range(2))
    tK = min(run(reps) for _ in range(2))
    per = (tK - t1) / (reps - 1)
    print(f"{name:56s} {per*1e3:10.3f} ms/call", flush=True)
    return per


key = jax.random.PRNGKey(0)
print(f"== N={N} f32 on {jax.devices()[0]}, K={K} ==", flush=True)

from svd_jacobi_tpu.ops import pallas_jacobi

for b in (64, 128, 256):
    n2 = 2 * b
    k = max(1, N // n2)
    x = jax.random.normal(key, (k, N, n2), jnp.float32)
    g0 = jnp.einsum("kmi,kmj->kij", x, x, precision="highest")
    dmax2 = jnp.max(jnp.diagonal(g0, axis1=-2, axis2=-1))
    t(f"pallas rotations b={b} (k={k},{n2},{n2})",
      lambda gg, dd: pallas_jacobi.rotations(gg, dd), g0, dmax2)

HI = jax.lax.Precision.HIGHEST
a = jax.random.normal(key, (N, N), jnp.float32)
t("full matmul highest", lambda x: jnp.matmul(x, x, precision=HI), a)
t("full matmul default", lambda x: jnp.matmul(x, x), a)
t("jnp.linalg.svd", lambda x: jnp.linalg.svd(x), a, reps=3)
t("qr reduced", lambda x: jnp.linalg.qr(x), a, reps=3)
t("cholesky(A^TA+I)", lambda x: jnp.linalg.cholesky(
    jnp.matmul(x.T, x, precision=HI) / N + 2 * jnp.eye(N)), a, reps=3)
