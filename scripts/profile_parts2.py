"""Profile primitives with in-jit repetition so tunnel RTT cancels.

For each op f we time scan-of-R-applications minus scan-of-1, divided by
R-1 — the per-application device time free of dispatch/readback overhead.
Usage: python scripts/profile_parts2.py [N] [R]
"""
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

N = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
R = int(sys.argv[2]) if len(sys.argv) > 2 else 5


def _loop(f, reps, *args):
    def body(i, acc):
        # Perturb input per iteration so XLA cannot CSE the calls.
        bumped = tuple(a + jnp.float32(i) * jnp.finfo(jnp.float32).tiny
                       for a in args)
        out = f(*bumped)
        leaves = [x for x in jax.tree_util.tree_leaves(out) if x is not None]
        return acc + sum(jnp.sum(jnp.abs(x).astype(jnp.float32)) for x in leaves)
    return jax.lax.fori_loop(0, reps, body, jnp.float32(0.0))


def t(name, f, *args, reps=R):
    f1 = jax.jit(partial(_loop, f, 1))
    fR = jax.jit(partial(_loop, f, reps))
    float(np.asarray(f1(*args)))
    float(np.asarray(fR(*args)))
    t1 = tR = float("inf")
    for _ in range(3):
        t0 = time.perf_counter(); float(np.asarray(f1(*args)))
        t1 = min(t1, time.perf_counter() - t0)
        t0 = time.perf_counter(); float(np.asarray(fR(*args)))
        tR = min(tR, time.perf_counter() - t0)
    per = (tR - t1) / (reps - 1)
    print(f"{name:52s} {per*1e3:10.2f} ms/call")
    return per


key = jax.random.PRNGKey(0)
a = jax.random.normal(key, (N, N), jnp.float32)
HI = jax.lax.Precision.HIGHEST

print(f"== N={N} f32 on {jax.devices()[0]}, R={R} ==")
t("jnp.linalg.svd", lambda x: jnp.linalg.svd(x), a)
t("jnp.linalg.svd novec", lambda x: jnp.linalg.svd(x, compute_uv=False), a)
t("jnp.linalg.eigh(sym)", lambda x: jnp.linalg.eigh(x + x.T), a)
t("gram n^3 highest", lambda x: jnp.einsum("mi,mj->ij", x, x, precision=HI), a)
t("gram n^3 default", lambda x: jnp.einsum("mi,mj->ij", x, x), a)
t("matmul highest", lambda x: jnp.matmul(x, x, precision=HI), a)
t("matmul default", lambda x: jnp.matmul(x, x), a)
t("qr full", lambda x: jnp.linalg.qr(x), a)
t("qr r-only", lambda x: jnp.linalg.qr(x, mode="r"), a)

b2 = 256
k = max(1, N // b2)
panels = jax.random.normal(key, (k, b2, b2), jnp.float32)
tall = jax.random.normal(key, (k, N, b2), jnp.float32)
t(f"batched eigh ({k},{b2},{b2})",
  lambda p: jnp.linalg.eigh(p + p.transpose(0, 2, 1)), panels)
t(f"batched svd  ({k},{b2},{b2})", lambda p: jnp.linalg.svd(p), panels)
t(f"batched qr-r ({k},{N},{b2})", lambda p: jnp.linalg.qr(p, mode="r"), tall)
t(f"batched mm   ({k},{N},{b2})@...",
  lambda x: jnp.einsum("kmi,kij->kmj", x[:, :b2 * (N // b2)].reshape(k, N // b2 * b2, b2)[:, :N],
                       jnp.einsum("kmi,kmj->kij", x, x, precision=HI),
                       precision=HI), tall)

sys.path.insert(0, "/root/repo")
from svd_jacobi_tpu.ops import blockwise
t(f"givens_cleanup ({k},{b2},{b2})",
  lambda p: blockwise.givens_cleanup_sweep(p, jnp.float32(1.0))[0], panels)

from svd_jacobi_tpu import solver
kk = max(1, k // 2)
top = jax.random.normal(key, (kk, N, b2), jnp.float32)
bot = jax.random.normal(key, (kk, N, b2), jnp.float32)
vt = jax.random.normal(key, (kk, N, b2), jnp.float32)
vb = jax.random.normal(key, (kk, N, b2), jnp.float32)
for method, crit in [("gram-eigh", "abs"), ("qr-svd", "rel")]:
    t(f"one ROUND {method}",
      lambda tp, bt: blockwise.orthogonalize_pairs(
          tp, bt, None, None, precision="highest", gram_dtype=jnp.float32,
          method=method, criterion=crit, dmax2=jnp.float32(N))[0],
      top, bot, reps=R)
    t(f"one SWEEP {method} (k={kk}, 2b={b2})",
      lambda tp, bt, v1, v2: solver._sweep(
          tp, bt, v1, v2, precision="highest", gram_dtype=jnp.float32,
          method=method, criterion=crit, dmax2=jnp.float32(N))[0],
      top, bot, vt, vb, reps=3)
