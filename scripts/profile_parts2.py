"""Profile primitives by K queued dispatches + one readback (RTT amortized).

Per-op device time = (t_K - t_1) / (K - 1) where t_j times j dispatches of
the same jitted function followed by a single scalar readback.
Usage: python scripts/profile_parts2.py [N] [K]
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

N = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
K = int(sys.argv[2]) if len(sys.argv) > 2 else 8


def _scalarize(f):
    def g(*args):
        out = f(*args)
        leaves = [x for x in jax.tree_util.tree_leaves(out) if x is not None]
        return sum(jnp.sum(jnp.abs(x).astype(jnp.float32)) for x in leaves)
    return g


def t(name, f, *args, reps=K):
    g = jax.jit(_scalarize(f))
    float(np.asarray(g(*args)))  # compile + warm

    def run(j):
        t0 = time.perf_counter()
        for _ in range(j - 1):
            g(*args)
        float(np.asarray(g(*args)))
        return time.perf_counter() - t0

    t1 = min(run(1) for _ in range(2))
    tK = min(run(reps) for _ in range(2))
    per = (tK - t1) / (reps - 1)
    print(f"{name:52s} {per*1e3:10.2f} ms/call", flush=True)
    return per


key = jax.random.PRNGKey(0)
a = jax.random.normal(key, (N, N), jnp.float32)
HI = jax.lax.Precision.HIGHEST

print(f"== N={N} f32 on {jax.devices()[0]}, K={K} ==", flush=True)
t("matmul highest", lambda x: jnp.matmul(x, x, precision=HI), a)
t("matmul default", lambda x: jnp.matmul(x, x), a)
t("gram n^3 highest", lambda x: jnp.einsum("mi,mj->ij", x, x, precision=HI), a)
t("jnp.linalg.svd", lambda x: jnp.linalg.svd(x), a)
t("jnp.linalg.svd novec", lambda x: jnp.linalg.svd(x, compute_uv=False), a)
t("jnp.linalg.eigh(sym)", lambda x: jnp.linalg.eigh(x + x.T), a)
t("qr full", lambda x: jnp.linalg.qr(x), a)
t("qr r-only", lambda x: jnp.linalg.qr(x, mode="r"), a)

b2 = 256
k = max(1, N // b2)
panels = jax.random.normal(key, (k, b2, b2), jnp.float32)
tall = jax.random.normal(key, (k, N, b2), jnp.float32)
t(f"batched eigh ({k},{b2},{b2})",
  lambda p: jnp.linalg.eigh(p + p.transpose(0, 2, 1)), panels)
t(f"batched svd  ({k},{b2},{b2})", lambda p: jnp.linalg.svd(p), panels)
t(f"batched qr-r ({k},{N},{b2})", lambda p: jnp.linalg.qr(p, mode="r"), tall)
t(f"batched update mm ({k},{N},{b2})",
  lambda x, q: jnp.einsum("kmi,kij->kmj", x, q, precision=HI), tall,
  jax.random.normal(key, (k, b2, b2), jnp.float32))

sys.path.insert(0, "/root/repo")
from svd_jacobi_tpu.ops import blockwise
t(f"givens_cleanup ({k},{b2},{b2})",
  lambda p: blockwise.givens_cleanup_sweep(p, jnp.float32(1.0))[0], panels)

from svd_jacobi_tpu import solver
kk = max(1, k // 2)
top = jax.random.normal(key, (kk, N, b2), jnp.float32)
bot = jax.random.normal(key, (kk, N, b2), jnp.float32)
vt = jax.random.normal(key, (kk, N, b2), jnp.float32)
vb = jax.random.normal(key, (kk, N, b2), jnp.float32)
for method, crit in [("gram-eigh", "abs"), ("qr-svd", "rel")]:
    t(f"one ROUND {method} noV",
      lambda tp, bt: blockwise.orthogonalize_pairs(
          tp, bt, None, None, precision="highest", gram_dtype=jnp.float32,
          method=method, criterion=crit, dmax2=jnp.float32(N))[0],
      top, bot)
    t(f"one SWEEP {method}+V (k={kk}, 2b={b2})",
      lambda tp, bt, v1, v2: solver._sweep(
          tp, bt, v1, v2, precision="highest", gram_dtype=jnp.float32,
          method=method, criterion=crit, dmax2=jnp.float32(N))[0],
      top, bot, vt, vb, reps=4)
