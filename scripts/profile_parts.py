"""Profile the building blocks of the solver on the attached accelerator.

Times each candidate primitive so perf decisions are measured, not guessed.
Usage: python scripts/profile_parts.py [N]
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

N = int(sys.argv[1]) if len(sys.argv) > 1 else 2048


def _scalarize(f):
    def g(*args):
        out = f(*args)
        leaves = [x for x in jax.tree_util.tree_leaves(out) if x is not None]
        return sum(jnp.sum(jnp.abs(x).astype(jnp.float32)) for x in leaves)
    return g


def t(name, f, *args, reps=3):
    f_j = jax.jit(_scalarize(f))
    float(np.asarray(f_j(*args)))  # compile + warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        float(np.asarray(f_j(*args)))
        best = min(best, time.perf_counter() - t0)
    print(f"{name:48s} {best*1e3:10.2f} ms")
    return best


key = jax.random.PRNGKey(0)
a = jax.random.normal(key, (N, N), jnp.float32)

print(f"== N={N} f32 on {jax.devices()[0]} ==")
t("jnp.linalg.svd", lambda x: jnp.linalg.svd(x), a)
t("jnp.linalg.svd novec", lambda x: jnp.linalg.svd(x, compute_uv=False), a)
t("jnp.linalg.eigh", lambda x: jnp.linalg.eigh(x @ x.T), a)
t("gram n^3 highest", lambda x: jnp.einsum("mi,mj->ij", x, x,
                                           precision=jax.lax.Precision.HIGHEST), a)
t("gram n^3 default", lambda x: jnp.einsum("mi,mj->ij", x, x,
                                           precision=jax.lax.Precision.DEFAULT), a)
t("matmul n^3 highest", lambda x: x @ x, a)
t("qr full", lambda x: jnp.linalg.qr(x), a)

# batched small-panel ops at b=128 (2b=256), k=N/256 panels
b2 = 256
k = max(1, N // b2)
panels = jax.random.normal(key, (k, b2, b2), jnp.float32)
tall = jax.random.normal(key, (k, N, b2), jnp.float32)
t(f"batched eigh ({k},{b2},{b2})", lambda p: jnp.linalg.eigh(p @ p.transpose(0, 2, 1)), panels)
t(f"batched svd  ({k},{b2},{b2})", lambda p: jnp.linalg.svd(p), panels)
t(f"batched qr-r ({k},{N},{b2})", lambda p: jnp.linalg.qr(p, mode="r"), tall)
t(f"batched mm   ({k},{N},{b2})@({k},{b2},{b2})",
  lambda x, q: jnp.einsum("kmi,kij->kmj", x, q,
                          precision=jax.lax.Precision.HIGHEST), tall, panels)

# the sequential givens cleanup scan
sys.path.insert(0, "/root/repo")
from svd_jacobi_tpu.ops import blockwise
t(f"givens_cleanup_sweep ({k},{b2},{b2})",
  lambda p: blockwise.givens_cleanup_sweep(p, jnp.float32(1.0))[0], panels)

# one full sweep, each method
from svd_jacobi_tpu import solver
top = jax.random.normal(key, (k // 2 if k >= 2 else 1, N, b2), jnp.float32)
kk = top.shape[0]
bot = jax.random.normal(key, (kk, N, b2), jnp.float32)
vtop = jax.random.normal(key, (kk, N, b2), jnp.float32)
vbot = jax.random.normal(key, (kk, N, b2), jnp.float32)

for method, crit in [("gram-eigh", "abs"), ("qr-svd", "rel")]:
    t(f"one sweep {method} (k={kk}, b={b2})",
      lambda tp, bt, vt, vb: solver._sweep(
          tp, bt, vt, vb, precision="highest", gram_dtype=jnp.float32,
          method=method, criterion=crit, dmax2=jnp.float32(N))[0],
      top, bot, vtop, vbot)

# end-to-end current solver
import svd_jacobi_tpu as sj
r = sj.svd(a)
print("sweeps:", int(r.sweeps), "off_rel:", float(r.off_rel))
t("sj.svd end-to-end", lambda x: tuple(sj.svd(x)[:3]), a, reps=2)
