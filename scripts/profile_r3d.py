"""Round-3 part 4: INTRA-JIT component costs via fori_loop(reps) in one jit.

Each measurement jits a loop of `reps` iterations of one component and
divides wall time by reps — per-dispatch tunnel overhead (~2-3 ms/call,
see profile_r3c.py) amortizes to noise.

Usage: python scripts/profile_r3d.py [N] [b] [reps]
"""
import functools
import sys
import time

sys.path.insert(0, "scripts")

import jax
import jax.numpy as jnp
import numpy as np

N = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
B = int(sys.argv[2]) if len(sys.argv) > 2 else 128
R = int(sys.argv[3]) if len(sys.argv) > 3 else 50

key = jax.random.PRNGKey(0)
HI = jax.lax.Precision.HIGHEST
DEF = jax.lax.Precision.DEFAULT


def t(name, body, init, flops_per=None):
    """body: carry -> carry; differential timing (4R vs R loops in one jit)
    cancels the per-call dispatch+readback RTT of the tunnel."""
    @functools.partial(jax.jit, static_argnames=("reps",))
    def loop(c, reps):
        c = jax.lax.fori_loop(0, reps, lambda i, cc: body(cc), c)
        leaves = jax.tree_util.tree_leaves(c)
        return sum(jnp.sum(jnp.abs(x).astype(jnp.float32)) for x in leaves)

    def run(reps):
        float(np.asarray(loop(init, reps)))  # compile+warm
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            float(np.asarray(loop(init, reps)))
            best = min(best, time.perf_counter() - t0)
        return best

    per = (run(4 * R) - run(R)) / (3 * R)
    extra = f"  {flops_per/per/1e12:8.2f} TF/s" if flops_per else ""
    print(f"{name:56s} {per*1e3:9.3f} ms/iter{extra}", flush=True)
    return per


n2 = 2 * B
k = max(1, N // n2)
x = jax.random.normal(key, (k, N, n2), jnp.float32)
v = jax.random.normal(key, (k, N, n2), jnp.float32)
g0 = jnp.einsum("kmi,kmj->kij", x, x, precision=HI)
dmax2 = jnp.max(jnp.diagonal(g0, axis1=-2, axis2=-1))
gf = 2 * k * N * n2 * n2

print(f"== N={N} b={B} reps={R} on {jax.devices()[0]} ==", flush=True)

import kernel_variants as kv
from svd_jacobi_tpu.ops import pallas_jacobi

def _carry_x(xx):
    g = jnp.einsum("kmi,kmj->kij", xx, xx, precision=HI,
                   preferred_element_type=jnp.float32)
    return xx + g[:, :1, :] * 1e-9


t("gram f32 highest (carried)", _carry_x, x, flops_per=gf)


def _carry_x_def(xx):
    g = jnp.einsum("kmi,kmj->kij", xx, xx, precision=DEF,
                   preferred_element_type=jnp.float32)
    return xx + g[:, :1, :] * 1e-9


t("gram f32 default (carried)", _carry_x_def, x, flops_per=gf)


def _carry_x_bf(xx):
    xb = xx.astype(jnp.bfloat16)
    g = jnp.einsum("kmi,kmj->kij", xb, xb, preferred_element_type=jnp.float32)
    return xx + g[:, :1, :] * 1e-9


t("gram bf16->f32 (carried)", _carry_x_bf, x, flops_per=gf)


def _apply(xx, prec):
    q = g0 * 1e-4
    return jnp.einsum("kmi,kij->kmj", xx, q, precision=prec,
                      preferred_element_type=jnp.float32) * 0.99


t("apply f32 highest (carried)", lambda xx: _apply(xx, HI), x, flops_per=gf)
t("apply f32 default (carried)", lambda xx: _apply(xx, DEF), x, flops_per=gf)


def _apply_bf(xx):
    q = (g0 * 1e-4).astype(jnp.bfloat16)
    return jnp.einsum("kmi,kij->kmj", xx.astype(jnp.bfloat16), q,
                      preferred_element_type=jnp.float32) * 0.99


t("apply bf16->f32 (carried)", _apply_bf, x, flops_per=gf)


def _kernel_cross(gg):
    q, _ = kv.rotations_cross(gg, dmax2)
    return gg + q * 1e-9


t(f"cross kernel {n2//2} steps (carried)", _kernel_cross, g0)


def _kernel_full(gg):
    q, _ = pallas_jacobi.rotations(gg, dmax2)
    return gg + q * 1e-9


t(f"full kernel {n2-1} steps (carried)", _kernel_full, g0)


def _round(state, prec, bf16):
    xx, vv = state
    if bf16:
        xb = xx.astype(jnp.bfloat16)
        g = jnp.einsum("kmi,kmj->kij", xb, xb, preferred_element_type=jnp.float32)
    else:
        g = jnp.einsum("kmi,kmj->kij", xx, xx, precision=prec,
                       preferred_element_type=jnp.float32)
    d = jnp.max(jnp.diagonal(g, axis1=-2, axis2=-1))
    q, _ = kv.rotations_cross(g, d)
    if bf16:
        qb = q.astype(jnp.bfloat16)
        xn = jnp.einsum("kmi,kij->kmj", xx.astype(jnp.bfloat16), qb,
                        preferred_element_type=jnp.float32)
        vn = jnp.einsum("kmi,kij->kmj", vv.astype(jnp.bfloat16), qb,
                        preferred_element_type=jnp.float32)
    else:
        xn = jnp.einsum("kmi,kij->kmj", xx, q, precision=prec,
                        preferred_element_type=jnp.float32)
        vn = jnp.einsum("kmi,kij->kmj", vv, q, precision=prec,
                        preferred_element_type=jnp.float32)
    return xn, vn


t("ROUND f32 highest (carried)", lambda s: _round(s, HI, False), (x, v),
  flops_per=3 * gf)
t("ROUND f32 default (carried)", lambda s: _round(s, DEF, False), (x, v),
  flops_per=3 * gf)
t("ROUND bf16 (carried)", lambda s: _round(s, None, True), (x, v),
  flops_per=3 * gf)
