"""Round-3 part 5: gridded-per-panel vs batched-in-body kernel cost.

Differential intra-jit timing (see profile_r3d.py).
Usage: python scripts/profile_r3e.py [N]
"""
import functools
import sys
import time

sys.path.insert(0, "scripts")

import jax
import jax.numpy as jnp
import numpy as np

N = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
R = 30

key = jax.random.PRNGKey(0)
HI = jax.lax.Precision.HIGHEST


def t(name, body, init):
    @functools.partial(jax.jit, static_argnames=("reps",))
    def loop(c, reps):
        c = jax.lax.fori_loop(0, reps, lambda i, cc: body(cc), c)
        leaves = jax.tree_util.tree_leaves(c)
        return sum(jnp.sum(jnp.abs(x).astype(jnp.float32)) for x in leaves)

    def run(reps):
        float(np.asarray(loop(init, reps)))
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            float(np.asarray(loop(init, reps)))
            best = min(best, time.perf_counter() - t0)
        return best

    per = (run(4 * R) - run(R)) / (3 * R)
    print(f"{name:56s} {per*1e3:9.3f} ms/iter", flush=True)
    return per


import kernel_variants as kv
from svd_jacobi_tpu.ops import pallas_jacobi

print(f"== N={N} on {jax.devices()[0]} ==", flush=True)

for (k, n2) in [(8, 256), (16, 128), (32, 64)]:
    x = jax.random.normal(key, (k, N, n2), jnp.float32)
    g0 = jnp.einsum("kmi,kmj->kij", x, x, precision=HI)
    dmax2 = jnp.max(jnp.diagonal(g0, axis1=-2, axis2=-1))

    def _grid(gg, kk=k, nn=n2):
        q, _ = pallas_jacobi.rotations(gg, dmax2)
        return gg + q * 1e-9

    def _batched(gg, kk=k, nn=n2):
        q, _ = kv.rotations_a(gg, dmax2)
        return gg + q * 1e-9

    def _cross_grid(gg, kk=k, nn=n2):
        q, _ = kv.rotations_cross(gg, dmax2)
        return gg + q * 1e-9

    t(f"full gridded  ({k},{n2},{n2}) {n2-1} steps", _grid, g0)
    t(f"full batched  ({k},{n2},{n2}) {n2-1} steps", _batched, g0)
    t(f"cross gridded ({k},{n2},{n2}) {n2//2} steps", _cross_grid, g0)


from svd_jacobi_tpu.ops import pallas_jacobi2 as pj2

for (k, n2) in [(8, 256), (16, 128), (32, 64), (64, 32), (128, 16)]:
    x = jax.random.normal(key, (k, N, min(n2, 256)), jnp.float32)[:, :, :n2] \
        if n2 <= 256 else None
    xg = jax.random.normal(key, (k, 512, n2), jnp.float32)
    g0 = jnp.einsum("kmi,kmj->kij", xg, xg, precision=HI)

    def _v2(gg, kk=k, nn=n2):
        q = pj2.cross_rotations(g0)
        return gg + q * 1e-9

    t(f"cross v2 ({k},{n2},{n2}) {n2//2} steps", _v2, g0)
