"""Differential intra-jit component timing on the attached chip.

THE methodology behind PROFILE.md's component table: time a `lax.fori_loop`
of 4R vs R iterations of one component inside ONE jit and divide the time
difference by 3R. This cancels the tunnel's per-call dispatch+readback RTT
(~80 ms) and its per-dispatch overhead, which swamp naive per-op timing
(the retired scripts/profile_parts2.py queued-dispatch approach measured
negative numbers).

Each iteration perturbs its input from the loop carry so XLA cannot hoist
the body out of the loop, and the carry keeps a live data dependency so
iterations serialize.

Usage:
    python scripts/profile_intrajit.py [component ...]
    python scripts/profile_intrajit.py --list
    python scripts/profile_intrajit.py --n 8192        # all, at that size

Components default to the PROFILE.md table shapes (N=2048, b=128 panels
(8, 2048, 256)); --n scales the panel stacks to that matrix size.
"""

from __future__ import annotations

import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np

HI = jax.lax.Precision.HIGHEST


def _diff_time(body, init, r: int = 8, factor: int = 4):
    """Seconds per iteration of ``body`` by the 4R-vs-R differential."""
    from svd_jacobi_tpu.utils._exec import force

    def loop(reps):
        @jax.jit
        def run(x):
            return jax.lax.fori_loop(0, reps, body, x)
        return run

    short, long_ = loop(r), loop(factor * r)
    force(short(init))   # compile + warm
    force(long_(init))
    ts = te = 1e9
    for _ in range(3):
        t0 = time.perf_counter(); force(short(init)); ts = min(ts, time.perf_counter() - t0)
        t0 = time.perf_counter(); force(long_(init)); te = min(te, time.perf_counter() - t0)
    return max(0.0, (te - ts) / ((factor - 1) * r))


def _perturb(i, x):
    # data-dependent nudge: keeps the loop body live without changing scale
    return x * (1.0 + jnp.float32(1e-7) * jnp.float32(i))


def _dep(x, y):
    # carry-shaped output that DEPENDS on the measured component's result
    # (so it cannot be dead-code-eliminated) at ~one elementwise pass cost.
    # NB the factor must be nonzero: XLA constant-folds `0.0 * y` and then
    # eliminates y's producer entirely (observed: gram_einsum rows reading
    # 0 ms). 1e-30 * y is numerically invisible but keeps the edge.
    return x * (1.0 + jnp.float32(1e-30) * y.ravel()[0].astype(jnp.float32))


def components(n: int, b: int = 128):
    """name -> (body, init) registry at matrix size n (panels (k, n, 2b))."""
    from svd_jacobi_tpu.ops import pallas_apply as pa
    from svd_jacobi_tpu.ops import pallas_blocks as pb
    from svd_jacobi_tpu.ops import pallas_gram as pg
    from svd_jacobi_tpu.ops import rounds

    k = max(1, n // (2 * b))
    rng = np.random.default_rng(0)
    top = jnp.asarray(rng.standard_normal((k, n, b)), jnp.float32)
    bot = jnp.asarray(rng.standard_normal((k, n, b)), jnp.float32)
    x2 = jnp.concatenate([top, bot], axis=-1)
    g = jnp.einsum("kmi,kmj->kij", x2, x2, precision=HI)
    q = jnp.asarray(np.stack([np.linalg.qr(
        rng.standard_normal((2 * b, 2 * b)))[0] for _ in range(k)]),
        jnp.float32)

    reg = {}

    def add(name, body, init):
        reg[name] = (body, init)

    add("gram_einsum_f32_hi",
        lambda i, x: _dep(x, jnp.einsum("kmi,kmj->kij", _perturb(i, x), x,
                                        precision=HI)), x2)
    add("gram_einsum_bf16",
        lambda i, x: _dep(x, jnp.einsum("kmi,kmj->kij",
                                        _perturb(i, x).astype(jnp.bfloat16),
                                        x.astype(jnp.bfloat16),
                                        preferred_element_type=jnp.float32)),
        x2)
    add("gram_kernel_f32",
        lambda i, x: _dep(x, pg.gram_pairs(_perturb(i, x)[..., :b],
                                           x[..., b:])), x2)
    add("gram_kernel_bf16",
        lambda i, x: _dep(x, pg.gram_pairs(_perturb(i, x)[..., :b],
                                           x[..., b:], bf16=True)), x2)
    add("apply_einsum_f32_hi",
        lambda i, x: jnp.einsum("kmi,kij->kmj", _perturb(i, x), q,
                                precision=HI,
                                preferred_element_type=jnp.float32), x2)
    add("apply_einsum_bf16",
        lambda i, x: jnp.einsum("kmi,kij->kmj",
                                _perturb(i, x).astype(jnp.bfloat16),
                                q.astype(jnp.bfloat16),
                                preferred_element_type=jnp.float32), x2)
    add("apply_einsum_x3",
        lambda i, x: rounds._einsum(_perturb(i, x), q, "kmi,kij->kmj",
                                    x3=True), x2)

    def fused(i, st, **kw):
        t, b_ = st
        t, b_ = pa.apply_exchange(_perturb(i, t), b_, q, **kw)
        return t, b_

    add("apply_kernel_f32_hi", lambda i, st: fused(i, st), (top, bot))
    add("apply_kernel_x3", lambda i, st: fused(i, st, x3=True), (top, bot))
    # bf16-STORED stacks (SVDConfig.mixed_store="bf16"/"bf16g"): half the
    # HBM bytes per round AND one native MXU pass instead of 3/6.
    tb16, bb16 = top.astype(jnp.bfloat16), bot.astype(jnp.bfloat16)
    add("apply_kernel_bf16st", lambda i, st: fused(i, st), (tb16, bb16))

    def fused_gram(i, st, **kw):
        t, b_ = st
        t, b_, gg = pa.apply_exchange(_perturb(i, t), b_, q, with_gram=True,
                                      **kw)
        return _dep(t, gg), b_

    add("apply_kernel_withgram", fused_gram, (top, bot))
    add("apply_kernel_withgram_bf16st",
        lambda i, st: fused_gram(i, st, gram_bf16=True), (tb16, bb16))
    add("rot_kernel_cross",
        lambda i, gg: pb.cross_rotations(_perturb(i, gg)), g)
    return reg


def main():
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    flags = [a for a in sys.argv[1:] if a.startswith("--")]
    n, b = 2048, 128
    for f in flags:
        if f.startswith("--n"):
            n = int(f.split("=", 1)[1]) if "=" in f else int(args.pop(0))
        if f.startswith("--b"):
            b = int(f.split("=", 1)[1]) if "=" in f else int(args.pop(0))
    reg = components(n, b)
    if "--list" in flags:
        print("\n".join(reg))
        return
    names = args or list(reg)
    print(f"n={n} b={b}: differential intra-jit ms/iter "
          f"(device {jax.devices()[0]})")
    for name in names:
        body, init = reg[name]
        ms = _diff_time(body, init) * 1e3
        print(f"  {name:24s} {ms:8.3f} ms")


if __name__ == "__main__":
    main()
