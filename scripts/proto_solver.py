"""Prototype round-3 solver: cross+self Pallas kernels + optional QR precond.

Sweep structure (all pairs exactly once per sweep):
  1. self round: every width-b block self-orthogonalized by the full
     tournament kernel (within-block pairs);
  2. 2k-1 cross rounds: each [I | J] panel's b*b cross pairs annihilated by
     the cross kernel (b cyclic steps), then the outer tournament rotates
     block pairings.

Convergence stat: dgesvj scaled coupling from each round's *fresh* Gram
panel (covers within-block couplings too), plus the self-round kernel stat.
"""

from __future__ import annotations

import sys
from functools import partial

sys.path.insert(0, "scripts")

import jax
import jax.numpy as jnp

import kernel_variants as kv
from svd_jacobi_tpu.ops import blockwise, pallas_jacobi
from svd_jacobi_tpu.parallel import schedule as sched

HI = jax.lax.Precision.HIGHEST


def _einsum(a, b, spec):
    return jnp.einsum(spec, a, b, precision=HI, preferred_element_type=jnp.float32)


def _self_round(blocks, vblocks, dmax2, interpret):
    g = _einsum(blocks, blocks, "kmi,kmj->kij")
    q, rel = pallas_jacobi.rotations(g, dmax2, interpret=interpret)
    blocks = _einsum(blocks, q, "kmi,kij->kmj")
    if vblocks is not None:
        vblocks = _einsum(vblocks, q, "kmi,kij->kmj")
    return blocks, vblocks, rel


def _cross_round(top, bot, vtop, vbot, dmax2, interpret):
    b = top.shape[-1]
    x = jnp.concatenate([top, bot], axis=-1)
    g = _einsum(x, x, "kmi,kmj->kij")
    stat, _ = blockwise.off_diag_stats(g, b, dmax2, "rel")
    q, _ = kv.rotations_cross(g, dmax2, interpret=interpret)
    xn = _einsum(x, q, "kmi,kij->kmj")
    top, bot = xn[..., :b], xn[..., b:]
    if vtop is not None:
        v = jnp.concatenate([vtop, vbot], axis=-1)
        vn = _einsum(v, q, "kmi,kij->kmj")
        vtop, vbot = vn[..., :b], vn[..., b:]
    return top, bot, vtop, vbot, stat


def _sweep(top, bot, vtop, vbot, dmax2, interpret):
    k, m, b = top.shape
    with_v = vtop is not None
    blocks = jnp.concatenate([top, bot], axis=0)
    vblocks = jnp.concatenate([vtop, vbot], axis=0) if with_v else None
    blocks, vblocks, rel_self = _self_round(blocks, vblocks, dmax2, interpret)
    top, bot = blocks[:k], blocks[k:]
    if with_v:
        vtop, vbot = vblocks[:k], vblocks[k:]

    def body(carry, _):
        top, bot, vtop, vbot, mx = carry
        top, bot, vtop, vbot, stat = _cross_round(
            top, bot, vtop, vbot, dmax2, interpret)
        top, bot = sched.rotate_blocks(top, bot)
        if with_v:
            vtop, vbot = sched.rotate_blocks(vtop, vbot)
        return (top, bot, vtop, vbot, jnp.maximum(mx, stat)), None

    if not with_v:
        vtop = vbot = jnp.zeros((k, 0, b), top.dtype)
    init = (top, bot, vtop, vbot, rel_self.astype(jnp.float32))
    (top, bot, vtop, vbot, off), _ = jax.lax.scan(
        body, init, None, length=sched.num_rounds(2 * k))
    return top, bot, (vtop if with_v else None), (vbot if with_v else None), off


@partial(jax.jit, static_argnames=("nblocks", "tol", "max_sweeps", "compute_v",
                                   "interpret", "precondition"))
def proto_svd(a, *, nblocks, tol, max_sweeps, compute_v=True, interpret=False,
              precondition=False):
    from svd_jacobi_tpu import solver as slv

    m, n = a.shape
    q_pre = None
    if precondition:
        q_pre, a = jnp.linalg.qr(a)
        m = n
    top, bot = slv._blockify(a, n, nblocks)
    if compute_v:
        vtop, vbot = slv._blockify(jnp.eye(n, dtype=a.dtype), n, nblocks)
    else:
        vtop = vbot = None

    def cond(state):
        _, _, _, _, off, sweeps = state
        return jnp.logical_and(sweeps < max_sweeps, off > tol)

    def body(state):
        top, bot, vtop, vbot, _, sweeps = state
        dmax2 = slv._global_dmax2(top, bot)
        top, bot, nvt, nvb, off = _sweep(top, bot,
                                         vtop if compute_v else None,
                                         vbot if compute_v else None,
                                         dmax2, interpret)
        if compute_v:
            vtop, vbot = nvt, nvb
        return (top, bot, vtop, vbot, off, sweeps + 1)

    inf = jnp.float32(jnp.inf)
    state = (top, bot, vtop, vbot, inf, jnp.int32(0))
    top, bot, vtop, vbot, off, sweeps = jax.lax.while_loop(cond, body, state)
    a_work = slv._deblockify(top, bot)
    v_work = slv._deblockify(vtop, vbot)[:n, :] if compute_v else None
    u, s, v = slv._postprocess(a_work, v_work, n, compute_u=True,
                               full_u=False, dtype=a.dtype)
    if q_pre is not None and u is not None:
        u = jnp.matmul(q_pre, u, precision=HI)
    return u, s, v, sweeps, off
