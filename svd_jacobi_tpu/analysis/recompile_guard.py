"""Retrace budget guard: fail when an entry point compiles more than its
declared budget across a solve sequence.

The fused solvers are only fast because the whole sweep loop compiles ONCE
per problem key (shape x dtype x static config). The failure this guard
exists for: something dynamic leaks into a jit cache key — the Brent-Luk
round schedule as a fresh array/object per call, an unhashable config
sneaking into static_argnames, a per-sweep Python value — and every solve
(or worse, every SWEEP) retraces, turning seconds into minutes without a
single wrong number. `config.RETRACE_BUDGETS` declares compiles-per-
distinct-problem (1 everywhere: a repeated solve never retraces); the
guard measures two ways and cross-checks:

  * per-entry jit cache sizes (`PjitFunction._cache_size`) — exact
    attribution of which entry grew;
  * JAX's compilation monitoring stream
    (`/jax/core/compile/backend_compile_duration` via
    `jax.monitoring.register_event_duration_secs_listener`) — the global
    backend-compile count, catching retraces in entries nobody declared.

Usage (also wired as the `-m sanitized` lane's fixture and the CLI pass):

    with RecompileGuard() as guard:
        guard.expect("solver._svd_pallas", problems=2)
        for n in (64, 96):
            svd(matgen.random_dense(n, n))   # first solves: compile
            svd(matgen.random_dense(n, n))   # repeats: MUST be cache hits
    findings = guard.check()                 # [] or RETRACE001 findings
"""

from __future__ import annotations

from typing import Dict, List, Optional

from . import Finding
from .. import config as _config


def default_entries() -> Dict[str, object]:
    """The declared entry points (keys of config.RETRACE_BUDGETS) resolved
    to their live jit objects — delegated to the serving entry registry
    (`serve.registry.jit_entries`), the ONE authoritative name map; the
    AOT001 analysis pass asserts it covers the budget keys exactly in
    both directions."""
    from ..serve import registry as _registry
    return _registry.jit_entries()


def _cache_size(jit_fn) -> int:
    try:
        return int(jit_fn._cache_size())
    except AttributeError:
        # Older/newer jax spelling; treat as unobservable rather than
        # failing the guard itself.
        return 0


class RecompileGuard:
    """Context manager measuring compiles per entry over its lifetime."""

    def __init__(self, budgets: Optional[Dict[str, int]] = None,
                 entries: Optional[Dict[str, object]] = None):
        self.budgets = dict(_config.RETRACE_BUDGETS if budgets is None
                            else budgets)
        self.entries = default_entries() if entries is None else dict(entries)
        self.expected: Dict[str, int] = {}
        # The compile/cache-hit event counting (why "fresh" is the
        # compiles-minus-hits difference, the private-API unregistration
        # dance) lives in ONE place: serve.registry.CompileCounter. The
        # guard keeps a counter across its lifetime — counts stay
        # readable after __exit__.
        from ..serve.registry import CompileCounter
        self._counter = CompileCounter()
        self._start: Dict[str, int] = {}

    @property
    def backend_compiles(self) -> int:
        return self._counter.backend_compiles

    @property
    def cache_hits(self) -> int:
        """Persistent-compilation-cache hits inside the guard window: the
        backend-compile duration event fires on cache HITS too (it wraps
        compile_or_get_cached), so "fresh compilations" — the cold-start
        cost the AOT/persistent-cache lane eliminates — is the
        difference (`fresh_backend_compiles`)."""
        return self._counter.cache_hits

    def expect(self, name: str, problems: int = 1) -> None:
        """Declare that ``problems`` distinct problem keys will be solved
        through entry ``name`` inside the guard."""
        if name not in self.entries:
            raise KeyError(f"unknown entry {name!r}; known: "
                           f"{sorted(self.entries)}")
        self.expected[name] = self.expected.get(name, 0) + int(problems)

    def __enter__(self) -> "RecompileGuard":
        self._start = {n: _cache_size(f) for n, f in self.entries.items()}
        self._counter.__enter__()
        return self

    def __exit__(self, *exc) -> None:
        self._counter.__exit__(*exc)


    # -- results ------------------------------------------------------------
    def new_traces(self) -> Dict[str, int]:
        """Entry -> cache entries added since __enter__."""
        return {n: _cache_size(f) - self._start.get(n, 0)
                for n, f in self.entries.items()}

    def fresh_backend_compiles(self) -> int:
        """Backend compiles the persistent compilation cache did NOT
        serve — the real cold-start cost (zero on a fully warm cache:
        the restart acceptance criterion)."""
        return self._counter.fresh

    def report(self) -> dict:
        return {"new_traces": self.new_traces(),
                "backend_compiles": self.backend_compiles,
                "cache_hits": self.cache_hits,
                "fresh_backend_compiles": self.fresh_backend_compiles(),
                "expected": dict(self.expected)}

    def check(self) -> List[Finding]:
        """RETRACE001 findings for every entry that out-compiled its
        budget (declared problems x budget-per-problem)."""
        findings = []
        for name, problems in self.expected.items():
            budget = self.budgets.get(name, 1) * problems
            got = self.new_traces().get(name, 0)
            if got > budget:
                findings.append(Finding(
                    code="RETRACE001", where=name,
                    message=(f"entry retraced {got}x for {problems} "
                             f"distinct problem(s) (budget {budget}) — "
                             f"something dynamic is in the jit cache key"),
                    suggestion=("check that every static argument is "
                                "hashable and value-stable across calls "
                                "(schedules, configs, tolerances)")))
        return findings


def run_default_sequence() -> tuple:
    """The CLI's retrace pass: a multi-size, repeated-solve sequence over
    the single-device entries (and the mesh entry when a mesh exists);
    every repeat must be a cache hit. Returns (findings, report)."""
    import jax
    import jax.numpy as jnp

    from .. import solver
    from ..config import SVDConfig
    from ..utils import matgen

    sizes = (32, 48)
    pallas_cfg = SVDConfig(pair_solver="pallas", max_sweeps=8)
    hybrid_cfg = SVDConfig(pair_solver="hybrid", max_sweeps=8)
    mesh_ok = len(jax.devices()) >= 2
    with RecompileGuard() as guard:
        guard.expect("solver._svd_pallas", problems=len(sizes))
        guard.expect("solver._svd_padded", problems=len(sizes))
        for n in sizes:
            a = matgen.random_dense(n, n, seed=n, dtype=jnp.float32)
            for _ in range(2):  # second pass must not retrace
                solver.svd(a, config=pallas_cfg)
                solver.svd(a, config=hybrid_cfg)
        if mesh_ok:
            from ..parallel import sharded
            guard.expect("sharded._svd_sharded_jit", problems=1)
            am = matgen.random_dense(96, 96, seed=96, dtype=jnp.float32)
            for _ in range(2):
                sharded.svd(am, config=SVDConfig(max_sweeps=8))
        findings = guard.check()
        report = guard.report()
    return findings, report


# The serving layer's compile-cache contract: requests pad to a static
# bucket set, so the stepper-path entries compile once per BUCKET and
# never per request. The sequence feeds several DISTINCT request shapes
# into each bucket — a leak of the request shape (instead of the bucket
# shape) into any jit key blows the budget immediately.
_SERVE_SEQUENCE_BUCKETS = ((64, 48, "float32"), (96, 64, "float32"))
_SERVE_SEQUENCE_SHAPES = (
    # bucket (64, 48): exact fit, strictly smaller, wide (service
    # transposes to tall before routing).
    (64, 48), (60, 40), (33, 50),
    # bucket (96, 64): exact fit, smaller, taller-than-the-first.
    (96, 64), (90, 50), (70, 60),
)
_SERVE_ENTRIES = ("solver._precondition_qr_jit",
                  "solver._sweep_step_pallas_jit",
                  "solver._finish_pallas_jit",
                  "solver._nonfinite_probe_jit")


# Batched (coalesced-dispatch) contract: batch sizes snap to the static
# tier set, so the batched entries compile once per (bucket, tier) — a
# MIXED batch-size sequence (a full tier-4 batch, then a 2-member batch
# that pads to the same tier) must be one compile per bucket. Bucket
# (64, 48) routes the XLA batched stepper (n < 64 -> hybrid), (96, 64)
# the Pallas stacked stepper, so both lanes are under contract.
_SERVE_BATCH_SHAPES = {
    (64, 48): ((64, 48), (60, 40), (33, 50), (50, 44), (58, 30), (40, 40)),
    (96, 64): ((96, 64), (90, 50), (70, 60), (64, 66), (80, 44), (96, 30)),
}
_SERVE_BATCH_ENTRIES_XLA = ("solver._sweep_step_xla_batched_jit",
                            "solver._finish_xla_batched_jit",
                            "solver._nonfinite_probe_batched_jit")
_SERVE_BATCH_ENTRIES_PALLAS = ("solver._precondition_qr_batched_jit",
                               "solver._sweep_step_pallas_batched_jit",
                               "solver._finish_pallas_batched_jit",
                               "solver._nonfinite_probe_batched_jit")


def run_serve_sequence() -> tuple:
    """The CLI's serve retrace pass: a two-bucket `serve.SVDService` fed
    three distinct request shapes per bucket; every serving-path entry
    must compile once per bucket (RETRACE001 otherwise). Then the BATCHED
    lane: a coalescing service (max_batch=4, tiers (1, 4)) dispatches a
    full tier-4 batch followed by a 2-member batch padding to the SAME
    tier per bucket — the batched stepper entries must compile once per
    (bucket, tier), never per observed batch size. Returns
    (findings, report)."""
    import jax.numpy as jnp

    from ..config import SVDConfig
    from ..serve import ServeConfig, SVDService
    from ..utils import matgen

    cfg = ServeConfig(
        buckets=_SERVE_SEQUENCE_BUCKETS,
        solver=SVDConfig(pair_solver="pallas"),
        max_queue_depth=len(_SERVE_SEQUENCE_SHAPES) + 2,
        # Brownout pinned OFF (>1 disables a rung): a sigma-only-degraded
        # submit flips STATIC compute flags and would add a legitimate
        # extra trace, turning the measurement into a false RETRACE001.
        brownout_sigma_only_at=2.0, brownout_shed_at=2.0)
    with RecompileGuard() as guard:
        for entry in _SERVE_ENTRIES:
            guard.expect(entry, problems=len(_SERVE_SEQUENCE_BUCKETS))
        with SVDService(cfg) as svc:
            tickets = [
                svc.submit(matgen.random_dense(m, n, seed=m * 1000 + n,
                                               dtype=jnp.float32))
                for m, n in _SERVE_SEQUENCE_SHAPES]
            statuses = [t.result(timeout=600.0).status for t in tickets]
        findings = guard.check()
        report = guard.report()
    report["serve_statuses"] = [getattr(s, "name", None) for s in statuses]
    if any(s is None or s.name != "OK" for s in statuses):
        findings.append(Finding(
            code="RETRACE001", where="serve.run_serve_sequence",
            message=(f"serve sequence produced non-OK statuses "
                     f"{report['serve_statuses']} — the retrace "
                     f"measurement is not trustworthy on a failing solve"),
            suggestion="fix the serving solve path first"))
    b_findings, b_report = _run_serve_batched_case()
    findings += b_findings
    report["batched"] = b_report
    f_findings, f_report = run_serve_fleet_case()
    findings += f_findings
    report["fleet"] = f_report
    r_findings, r_report = run_serve_rank_case()
    findings += r_findings
    report["rank"] = r_report
    p_findings, p_report = run_serve_promote_case()
    findings += p_findings
    report["promote"] = p_report
    return findings, report


# Top-k / tall bucket family contract: the sketch width is BUCKET-static
# (bucket.k + oversample) and the TSQR chunk bucket-resolved, so the
# stage jits and the core steppers compile once per bucket — a request-k
# or request-shape leak into any of those keys blows the budget. The
# request stream mixes shapes AND k values per bucket to prove it.
_RANK_BUCKETS = ((256, 32, "float32", "tall"), (96, 96, "float32", "topk", 8))
# (shape, top_k) per submit; top_k None routes the tall family.
_RANK_REQUESTS = (
    ((256, 32), None), ((200, 20), None), ((256, 24), None),
    ((96, 96), 8), ((80, 64), 4), ((90, 90), 6),
)
_RANK_ENTRIES = ("solver._tsqr_jit", "solver._sketch_project_jit",
                 "solver._lift_q_jit", "solver._precondition_qr_jit",
                 "solver._sweep_step_pallas_jit",
                 "solver._finish_pallas_jit",
                 "solver._nonfinite_probe_jit")


def run_serve_rank_case(expected_problems: Optional[int] = None,
                        buckets: Optional[tuple] = None,
                        requests: Optional[tuple] = None) -> tuple:
    """The rank-family half of the serve retrace contract: one tall and
    one top-k bucket, fed several distinct request shapes and — on the
    top-k bucket — several distinct request k values, everything
    repeated. The stage jits (`_tsqr_jit` / `_sketch_project_jit` /
    `_lift_q_jit`) and the core steppers must compile once per bucket
    family usage, never per request or per k (RETRACE001 otherwise) —
    the "no per-request or per-k retrace" acceptance of the truncated
    workload lane.

    Entry budget derivation for the default sequence: each of the two
    buckets drives the shared core stepper entries once (problems=2);
    `_tsqr_jit` is the tall bucket's alone and `_sketch_project_jit`
    the top-k bucket's (problems=1 each); `_lift_q_jit` sees the tall
    lift (m, n)x(n, n) and the top-k lift (m, l)x(l, k) — two distinct
    shapes (problems=2); `_precondition_qr_jit` runs inside the core
    stepper per bucket (problems=2).

    ``expected_problems`` under-declares every budget and ``buckets``/
    ``requests`` substitute FRESH problems — the seeded failing fixture
    (tests prove the guard fires; a warm cache would mask a leak)."""
    import jax.numpy as jnp

    from ..config import SVDConfig
    from ..serve import ServeConfig, SVDService
    from ..utils import matgen

    buckets = _RANK_BUCKETS if buckets is None else tuple(buckets)
    requests = _RANK_REQUESTS if requests is None else tuple(requests)
    budgets = {
        "solver._tsqr_jit": 1,
        "solver._sketch_project_jit": 1,
        "solver._lift_q_jit": 2,
        "solver._precondition_qr_jit": 2,
        "solver._sweep_step_pallas_jit": 2,
        "solver._finish_pallas_jit": 2,
        "solver._nonfinite_probe_jit": 2,
    }
    cfg = ServeConfig(
        buckets=buckets,
        solver=SVDConfig(pair_solver="pallas"),
        max_queue_depth=len(requests) + 2,
        brownout_sigma_only_at=2.0, brownout_shed_at=2.0)
    statuses = []
    with RecompileGuard() as guard:
        for entry in _RANK_ENTRIES:
            guard.expect(entry, problems=(budgets[entry]
                                          if expected_problems is None
                                          else int(expected_problems)))
        with SVDService(cfg) as svc:
            for _ in range(2):   # repeats must be pure cache hits
                tickets = [
                    svc.submit(matgen.random_dense(m, n, seed=m * 131 + n,
                                                   dtype=jnp.float32),
                               top_k=k)
                    for (m, n), k in requests]
                statuses += [t.result(timeout=600.0).status
                             for t in tickets]
        findings = guard.check()
        report = guard.report()
    report["serve_statuses"] = [getattr(s, "name", None) for s in statuses]
    if any(s is None or s.name != "OK" for s in statuses):
        findings.append(Finding(
            code="RETRACE001", where="serve.run_serve_rank_case",
            message=(f"rank-family serve sequence produced non-OK "
                     f"statuses {report['serve_statuses']} — the retrace "
                     f"measurement is not trustworthy on a failing solve"),
            suggestion="fix the tall/top-k serving path first"))
    return findings, report


def run_serve_fleet_case(expected_problems: Optional[int] = None,
                         buckets: Optional[tuple] = None) -> tuple:
    """The per-LANE half of the serve retrace contract (fleet mode).

    With ``lanes = 2`` each lane pins its working set to its own device,
    so a lane's first dispatch of a bucket compiles that lane's own
    executable — the per-lane jit cache. The contract: each lane
    compiles once per (bucket, variant), and an AFFINITY MOVE (a bucket
    served by a non-home lane after its home is quarantined, or via
    stealing) costs at most ONE extra compile on the receiving lane —
    repeats there must be cache hits. The sequence: serve each bucket on
    its home lane (2 distinct shapes each), quarantine bucket 0's home
    lane, serve bucket 0 twice more (now on lane 1 — the affinity move),
    and expect exactly 3 compile-problems per serving entry: bucket 0 on
    lane 0, bucket 1 on lane 1, bucket 0 on lane 1. On a single-device
    host the lanes share one executable cache and come in UNDER budget —
    over-budget is the only failure either way.

    ``expected_problems`` under-declares the budget and ``buckets``
    substitutes a FRESH (never-compiled) bucket pair for the seeded
    failing fixture (tests prove the guard actually fires on a
    per-request/per-dispatch leak — a warm cache would mask it)."""
    import jax.numpy as jnp

    from ..config import SVDConfig
    from ..serve import LaneState, ServeConfig, SVDService
    from ..utils import matgen

    problems = 3 if expected_problems is None else int(expected_problems)
    buckets = _SERVE_SEQUENCE_BUCKETS if buckets is None else tuple(buckets)
    # Two distinct request shapes per bucket: exact fit + strictly
    # smaller (both pad to the bucket — the once-per-bucket claim).
    shapes = [((m, n), (m - 4, n - 8)) for m, n, _ in buckets]
    cfg = ServeConfig(
        buckets=buckets,
        solver=SVDConfig(pair_solver="pallas"),
        max_queue_depth=8, lanes=2, steal=False,
        # The supervisor must not probe the deliberately-quarantined
        # lane back to ACTIVE mid-measurement.
        lane_probe_interval_s=600.0,
        brownout_sigma_only_at=2.0, brownout_shed_at=2.0)
    statuses = []

    def _serve(svc, bucket_shapes, seed0):
        tickets = [svc.submit(matgen.random_dense(m, n, seed=seed0 + i,
                                                  dtype=jnp.float32))
                   for i, (m, n) in enumerate(bucket_shapes)]
        statuses.extend(t.result(timeout=600.0).status for t in tickets)

    with RecompileGuard() as guard:
        for entry in _SERVE_ENTRIES:
            guard.expect(entry, problems=problems)
        with SVDService(cfg) as svc:
            # Home-lane phase: 2 distinct shapes per bucket, repeated —
            # repeats are cache hits on the home lane.
            for _ in range(2):
                _serve(svc, shapes[0], seed0=7000)
                _serve(svc, shapes[1], seed0=7100)
            # Affinity move: quarantine bucket 0's home lane; its
            # traffic fails over to lane 1 (one compile there), repeats
            # stay cache hits.
            svc.fleet.evict(svc.fleet.lanes[0], "analysis_forced")
            assert svc.fleet.lanes[0].state is LaneState.QUARANTINED
            for _ in range(2):
                _serve(svc, shapes[0], seed0=7200)
        findings = guard.check()
        report = guard.report()
    report["serve_statuses"] = [getattr(s, "name", None) for s in statuses]
    if any(s is None or s.name != "OK" for s in statuses):
        findings.append(Finding(
            code="RETRACE001", where="serve.run_serve_fleet_case",
            message=(f"fleet serve sequence produced non-OK statuses "
                     f"{report['serve_statuses']} — the retrace "
                     f"measurement is not trustworthy on a failing solve"),
            suggestion="fix the fleet serving solve path first"))
    return findings, report


# Two-phase (σ-then-promote) contract: a sigma-phase dispatch runs the
# SAME sweep entries as a full one but terminates through the
# sigma-first extraction (`solver._sigma_from_state_jit`, bucket-shaped
# key), and `Ticket.promote` resumes the retained stage through the SAME
# finish jits a full dispatch would have compiled — so the whole
# σ/promote traffic pattern stays once-per-bucket: one sigma-extraction
# compile and one finish compile per bucket, never per request, never
# per promote.
_PROMOTE_ENTRIES = ("solver._precondition_qr_jit",
                    "solver._sweep_step_pallas_jit",
                    "solver._sigma_from_state_jit",
                    "solver._finish_pallas_jit",
                    "solver._nonfinite_probe_jit")


def run_serve_promote_case(expected_problems: Optional[int] = None,
                           buckets: Optional[tuple] = None) -> tuple:
    """The two-phase half of the serve retrace contract: a two-bucket
    service fed two distinct request shapes per bucket, each submitted
    ``phase="sigma"`` and then PROMOTED to full U/V, everything
    repeated — the sigma-extraction jit and the finish jits must compile
    once per bucket (RETRACE001 otherwise; repeats and promotes are pure
    cache hits). This is the compile-cache side of the promote
    acceptance: a promote is never a fresh solve, so it can never be a
    fresh compile either once its bucket is warm.

    ``expected_problems`` under-declares every budget and ``buckets``
    substitutes FRESH problems — the seeded failing fixture (tests prove
    the guard fires; a warm cache would mask a leak)."""
    import jax.numpy as jnp

    from ..config import SVDConfig
    from ..serve import ServeConfig, SVDService
    from ..utils import matgen

    buckets = _SERVE_SEQUENCE_BUCKETS if buckets is None else tuple(buckets)
    problems = (len(buckets) if expected_problems is None
                else int(expected_problems))
    shapes = [((m, n), (m - 4, n - 8)) for m, n, _ in buckets]
    cfg = ServeConfig(
        buckets=buckets,
        solver=SVDConfig(pair_solver="pallas"),
        max_queue_depth=8,
        brownout_sigma_only_at=2.0, brownout_shed_at=2.0)
    statuses = []
    with RecompileGuard() as guard:
        for entry in _PROMOTE_ENTRIES:
            guard.expect(entry, problems=problems)
        with SVDService(cfg) as svc:
            for _ in range(2):   # repeats must be pure cache hits
                for group in shapes:
                    tickets = [
                        svc.submit(matgen.random_dense(
                            m, n, seed=m * 313 + n, dtype=jnp.float32),
                            phase="sigma")
                        for m, n in group]
                    for t in tickets:
                        statuses.append(t.result(timeout=600.0).status)
                        statuses.append(t.promote(timeout=600.0).status)
        findings = guard.check()
        report = guard.report()
    report["serve_statuses"] = [getattr(s, "name", None) for s in statuses]
    if any(s is None or s.name != "OK" for s in statuses):
        findings.append(Finding(
            code="RETRACE001", where="serve.run_serve_promote_case",
            message=(f"σ-then-promote serve sequence produced non-OK "
                     f"statuses {report['serve_statuses']} — the retrace "
                     f"measurement is not trustworthy on a failing solve"),
            suggestion="fix the two-phase serving path first"))
    return findings, report


def _run_serve_batched_case() -> tuple:
    """The mixed batch-size half of the serve pass (see
    `run_serve_sequence`)."""
    import jax.numpy as jnp

    from ..config import SVDConfig
    from ..serve import ServeConfig, SVDService
    from ..utils import matgen

    buckets = tuple(_SERVE_BATCH_SHAPES)
    # pair_solver left on "auto" so bucket (64, 48) resolves to the
    # hybrid XLA batched stepper and (96, 64) to the Pallas stacked one —
    # both batched lanes under one contract.
    cfg = ServeConfig(
        buckets=tuple(b + ("float32",) for b in buckets),
        solver=SVDConfig(),
        max_queue_depth=16, max_batch=4, batch_window_s=2.0,
        batch_tiers=(1, 4),
        brownout_sigma_only_at=2.0, brownout_shed_at=2.0)
    statuses = []
    with RecompileGuard() as guard:
        # Bucket (64, 48), hybrid: the sweep step compiles once per
        # STAGE (gram-eigh/abs bulk + qr-svd/rel polish are distinct
        # static method keys), the finish once.
        guard.expect("solver._sweep_step_xla_batched_jit", problems=2)
        guard.expect("solver._finish_xla_batched_jit", problems=1)
        # Bucket (96, 64), Pallas stacked lane.
        for entry in ("solver._precondition_qr_batched_jit",
                      "solver._sweep_step_pallas_batched_jit",
                      "solver._finish_pallas_batched_jit"):
            guard.expect(entry, problems=1)
        # The per-member nonfinite probe runs at finish on BOTH buckets.
        guard.expect("solver._nonfinite_probe_batched_jit", problems=2)
        with SVDService(cfg) as svc:
            for bucket in buckets:
                shapes = _SERVE_BATCH_SHAPES[bucket]
                # One full tier-4 batch, then a 2-member batch that pads
                # to the SAME tier (must be pure cache hits).
                for group in (shapes[:4], shapes[4:]):
                    mats = [matgen.random_dense(m, n, seed=m * 997 + n,
                                                dtype=jnp.float32)
                            for m, n in group]
                    tickets = [svc.submit(a) for a in mats]
                    statuses += [t.result(timeout=600.0).status
                                 for t in tickets]
        findings = guard.check()
        report = guard.report()
    report["serve_statuses"] = [getattr(s, "name", None) for s in statuses]
    if any(s is None or s.name != "OK" for s in statuses):
        findings.append(Finding(
            code="RETRACE001", where="serve.run_serve_batched_case",
            message=(f"batched serve sequence produced non-OK statuses "
                     f"{report['serve_statuses']} — the retrace "
                     f"measurement is not trustworthy on a failing solve"),
            suggestion="fix the batched serving solve path first"))
    return findings, report
