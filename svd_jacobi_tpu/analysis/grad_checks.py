"""GRAD001: the differentiable-solver contract.

The gradient path is the easiest place for this package's whole value
proposition to silently leak away: one refactor that drops the custom
rule and `jax.grad(loss)` either dies in the sweep while_loop or — worse
— somebody "fixes" it by swapping in `jnp.linalg.svd`, and every
training loop quietly stops using the kernels this repo exists for.
This pass checks the REAL grad traces (``jax.make_jaxpr(jax.grad(...))``
over representative losses through `solver.svd` / `svd_topk`) the way
the other passes check the forward artifacts:

  * the trace must contain OUR solver's sweep machinery — the fused
    ``while`` loop the Jacobi solve runs (a rule-less fallback trace has
    none);
  * the trace must contain NO ``svd`` primitive applied at the probe's
    full input shape — the signature of `jnp.linalg.svd`'s rule running
    the whole problem. (The qr-svd pair solver's legitimate small-block
    `svd` calls are (2b, 2b)-shaped and batched; probe shapes are chosen
    so the two can never collide.);
  * the whole forward+backward trace must be free of host-callback
    primitives (`jaxpr_checks.HOST_CALLBACK_PRIMS`) — a callback in the
    backward pass would serialize every training step on the host link;
  * every jitted gradient entry (`grad.rules.jit_entries`) must carry a
    `config.RETRACE_BUDGETS` budget — an unbudgeted grad jit is an
    unguarded compile surface on the training hot path (AOT001's
    registry equality covers the reverse direction).

Seeded failing fixtures are parameter injection
(tests/fixtures/grad_fixtures.py + tests/test_grad.py): a loss built
directly on `jnp.linalg.svd` (the silent-fallback trace) makes the trace
checks fire, and a budgets dict missing a grad key makes the budget
check fire.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from . import Finding
from .. import config as _config
from .jaxpr_checks import HOST_CALLBACK_PRIMS, iter_eqns


def _grad_jaxpr(loss_fn: Callable, shape, dtype):
    """The closed jaxpr of ``jax.grad(loss_fn)`` at a zeros probe input
    (tracing is shape/dtype-driven; no solve executes)."""
    import jax
    import jax.numpy as jnp
    a = jnp.zeros(shape, jnp.dtype(dtype))
    return jax.make_jaxpr(jax.grad(loss_fn))(a)


def check_grad_trace(loss_fn: Optional[Callable] = None,
                     shape=(96, 64), dtype="float32",
                     where: str = "svd.grad[96x64,f32]",
                     expect_while: bool = True) -> List[Finding]:
    """The three trace contracts over one grad probe. ``loss_fn``
    substitutes the seeded silent-fallback fixture; the default is
    grad-of-nuclear-norm through `solver.svd`."""
    if loss_fn is None:
        import jax.numpy as jnp
        from .. import solver

        def loss_fn(a):
            return jnp.sum(solver.svd(a).s)

    closed = _grad_jaxpr(loss_fn, shape, dtype)
    findings: List[Finding] = []
    full_shapes = {tuple(shape), tuple(shape)[::-1]}
    saw_while = False
    fallback_hits = 0
    callback_prims = set()
    for eqn, _ in iter_eqns(closed.jaxpr):
        name = eqn.primitive.name
        # The sweep machinery's signature is the `while` primitive (every
        # solve lane's convergence loop; lax.fori_loop would lower to
        # while/scan, never to a primitive of its own).
        if name == "while":
            saw_while = True
        if name in HOST_CALLBACK_PRIMS:
            callback_prims.add(name)
        if name == "svd" and eqn.invars:
            opshape = tuple(eqn.invars[0].aval.shape)
            if len(opshape) >= 2 and opshape[-2:] in full_shapes:
                fallback_hits += 1
    if fallback_hits:
        findings.append(Finding(
            code="GRAD001", where=where,
            message=(f"the grad trace contains {fallback_hits} full-"
                     f"input-shape `svd` primitive(s) ({shape}) — the "
                     f"signature of a silent fallback to "
                     f"jnp.linalg.svd's AD rule (the whole problem "
                     f"solved off our kernel lanes)"),
            suggestion=("route the solve through solver.svd's custom "
                        "VJP/JVP rules (grad_rule != 'off'), not "
                        "jnp.linalg.svd")))
    if expect_while and not saw_while:
        findings.append(Finding(
            code="GRAD001", where=where,
            message=("the grad trace contains no `while` loop — our "
                     "solver's sweep machinery is absent from the "
                     "forward pass of the differentiated program"),
            suggestion=("the primal of the custom rule must run the "
                        "package's own solve entry points")))
    if callback_prims:
        findings.append(Finding(
            code="GRAD001", where=where,
            message=(f"host callback primitive(s) "
                     f"{sorted(callback_prims)} in the grad trace — a "
                     f"callback in the forward/backward pass serializes "
                     f"every training step on the host link"),
            suggestion=("keep the rule bodies callback-free (telemetry "
                        "must stay statically off in differentiated "
                        "programs)")))
    return findings


def check_budget_coverage(budgets: Optional[Dict[str, int]] = None
                          ) -> List[Finding]:
    """Every grad jit entry must be budgeted (GRAD001 otherwise);
    ``budgets`` substitutes the seeded unbudgeted-grad-jit fixture."""
    from ..grad import rules as _rules
    budgets = dict(_config.RETRACE_BUDGETS if budgets is None else budgets)
    findings = []
    for name in sorted(_rules.jit_entries()):
        if name not in budgets:
            findings.append(Finding(
                code="GRAD001", where=name,
                message=(f"grad jit entry {name!r} carries no "
                         f"config.RETRACE_BUDGETS budget — an unguarded "
                         f"compile surface on the training hot path"),
                suggestion="declare a RETRACE_BUDGETS entry for it"))
    return findings


def _default_probes():
    """(where, loss builder, shape, dtype) per covered lane. Shapes keep
    the pair-solver's legitimate small-block `svd` calls (2b, 2b) well
    away from the full probe shape, so the fallback detector cannot
    false-positive on the qr-svd/hybrid lanes."""
    import jax.numpy as jnp
    from .. import solver
    from ..config import SVDConfig

    def nuclear(config=None, **kw):
        def loss(a):
            return jnp.sum(solver.svd(a, config=config, **kw).s)
        return loss

    def topk_loss(a):
        return jnp.sum(solver.svd_topk(a, 8).s)

    def tall_loss(a):
        return jnp.sum(solver.svd_tall(a).s)

    probes = [
        # The f32 kernel lane (the default route for this shape class).
        ("svd.nuclear[96x64,f32]", nuclear(), (96, 64), "float32"),
        # sigma-only: the no-F-matrix rule over the factor-computing twin.
        ("svd.sigma_only[96x64,f32]",
         nuclear(compute_u=False, compute_v=False), (96, 64), "float32"),
        # The explicit custom_vjp mode (reverse rule + chaos guard).
        ("svd.vjp_rule[96x64,f32]",
         nuclear(config=SVDConfig(grad_rule="vjp")), (96, 64), "float32"),
        # Truncated lane: the thin-SVD rule over the sketch pipeline.
        ("svd_topk.nuclear[96x64,k8,f32]", topk_loss, (96, 64), "float32"),
        # Tall lane: the economy rule over the TSQR pipeline.
        ("svd_tall.nuclear[160x16,f32]", tall_loss, (160, 16), "float32"),
    ]
    import jax
    if jax.config.jax_enable_x64:
        # The f64 qr-svd lane — its small-block svd calls are the case
        # the full-shape fallback detector must NOT flag.
        probes.append(("svd.nuclear[48x32,f64]", nuclear(),
                       (48, 32), "float64"))
    return probes


def run_all() -> tuple:
    """The CLI's ``grad`` pass: every probe's trace contracts plus the
    budget coverage. Returns ``(findings, report)``."""
    findings: List[Finding] = []
    probed = []
    for where, loss, shape, dtype in _default_probes():
        findings += check_grad_trace(loss, shape=shape, dtype=dtype,
                                     where=where)
        probed.append(where)
    findings += check_budget_coverage()
    from ..grad import rules as _rules
    report = {"probes": probed,
              "grad_entries": sorted(_rules.jit_entries())}
    return findings, report
