"""OBS002 — the serving flight recorder is FREE when off.

PR 11 gives the serve layer a live metrics registry, span timelines, and
SLO accounting (`obs.registry` / `obs.spans`), all behind
``ServeConfig.metrics`` (off by default). This pass proves the
off-by-default guarantee three ways, each a checkable contract rather
than a promise in a docstring:

  1. **Metrics-off HLO byte-identity** — the recorder is host-side only
     and must never leak into a trace: every entry probe's telemetry-off
     lowering is byte-identical whether or not a live `MetricsRegistry`
     + `SpanRecorder` exist and are being mutated at trace time. This
     EXTENDS the existing telemetry equivalence pass (HLO003, which the
     check also re-runs per probe): HLO003 proves the in-graph event
     stream is a static-flag property; OBS002 proves the NEW host-side
     recorder adds no trace dependency on top.
  2. **Zero registry mutations on the metrics-off hot path** — every
     registry mutation (any instance) bumps a process-global counter
     (`obs.registry.mutation_total`); a metrics-off serve sequence
     (admit -> dispatch -> solve -> finalize, plus a rejected submit)
     must leave it unmoved. ``seed_leak=True`` is the seeded failing
     fixture: it runs the SAME sequence with the recorder secretly
     enabled, and the detector MUST fire (tests prove the check can
     fail, not just that it passes).
  3. **Idle-overhead budget** — with the recorder ON, the observability
     surface itself must stay cheap: a registry mutation is budgeted at
     ``MUTATION_BUDGET_S`` and a full /metrics scrape (collectors +
     render) at ``SCRAPE_BUDGET_S``, both measured here. Generous
     CPU-CI budgets — the REAL overhead number is measured end-to-end
     by ``bench.py --serve-metrics-overhead`` (PROFILE.md item 28);
     this check is the regression tripwire in the analysis sequence.
"""

from __future__ import annotations

import time
from typing import List, Optional

from . import Finding

# Generous single-op budgets (CPU CI with noisy neighbors): a registry
# mutation is a dict update under one lock; a scrape renders ~100 series
# plus collector refreshes. Regressions worth catching are 10-100x.
MUTATION_BUDGET_S = 200e-6
SCRAPE_BUDGET_S = 0.25


def _probes():
    from . import entries
    # Two representative entries are enough for byte-identity: the
    # Pallas kernel path and the padded XLA path (HLO003 already runs
    # over every probe in the hlo pass).
    probes = entries.single_device_probes(include_f64=False)
    by_name = {p.name: p for p in probes}
    picked = [by_name[n] for n in ("pallas", "padded_qr") if n in by_name]
    return picked or probes[:2]


def check_metrics_off_hlo() -> List[Finding]:
    """OBS002 check 1: metrics-off HLO byte-identity (see module
    docstring) + the HLO003 telemetry equivalence re-run per probe."""
    from ..obs.registry import MetricsRegistry
    from ..obs.spans import SpanRecorder
    from . import hlo_checks

    findings: List[Finding] = []
    for probe in _probes():
        off = probe.with_kwargs(
            **({probe.telemetry_key: False} if probe.telemetry_key
               else {}))
        baseline = off.lower().as_text()
        # A live, actively-mutated recorder must not perturb lowering.
        reg = MetricsRegistry()
        reg.inc("svdj_obs002_probe_total", bucket="x")
        reg.observe("svdj_obs002_probe_seconds", 0.001)
        rec = SpanRecorder()
        rec.event("obs002", "admit")
        with_recorder = off.lower().as_text()
        if with_recorder != baseline:
            findings.append(Finding(
                code="OBS002", where=probe.name,
                message=("metrics-off lowering changed while a live "
                         "MetricsRegistry/SpanRecorder existed — the "
                         "flight recorder leaked into the trace"),
                suggestion=("the recorder is host-side only; remove "
                            "whatever reads registry/span state inside "
                            "a traced function")))
        findings += [
            Finding(code="OBS002", where=f.where, message=f.message,
                    suggestion=f.suggestion)
            for f in hlo_checks.check_telemetry_invariance(probe)]
    return findings


def run_metrics_off_case(seed_leak: bool = False) -> tuple:
    """OBS002 check 2: a metrics-off serve sequence performs ZERO
    registry mutations (process-global counter delta). ``seed_leak``
    flips the recorder ON for the same sequence — the seeded failing
    fixture proving the detector fires. Returns (findings, report)."""
    import jax.numpy as jnp

    from ..config import SVDConfig
    from ..obs import registry as obsreg
    from ..serve import AdmissionError, ServeConfig, SVDService
    from ..utils import matgen

    cfg = ServeConfig(
        buckets=((32, 32, "float64"),), solver=SVDConfig(block_size=4),
        max_queue_depth=4, metrics=bool(seed_leak),
        brownout_sigma_only_at=2.0, brownout_shed_at=2.0)
    before = obsreg.mutation_total()
    statuses = []
    with SVDService(cfg) as svc:
        for seed in (11, 12):
            a = matgen.random_dense(30, 30, seed=seed, dtype=jnp.float64)
            statuses.append(
                svc.submit(a).result(timeout=600.0).status)
        try:
            # A rejected submit crosses the admission instrumentation
            # sites too — the off path must stay silent there as well.
            svc.submit(jnp.zeros((3000, 3000), jnp.float64))
        except AdmissionError:
            pass
        text = svc.metrics_text()
    delta = obsreg.mutation_total() - before
    report = {"mutation_delta": delta, "seed_leak": bool(seed_leak),
              "statuses": [getattr(s, "name", None) for s in statuses],
              "metrics_text_head": text.splitlines()[0] if text else ""}
    findings: List[Finding] = []
    if delta != 0:
        # Fires on the seeded fixture too (seed_leak simulates exactly
        # the unguarded-instrumentation leak this detector exists for —
        # tests prove the check CAN fail, not just that it passes).
        findings.append(Finding(
            code="OBS002", where="serve.metrics_off",
            message=(f"metrics-off serve sequence performed {delta} "
                     f"registry mutation(s) — the flight recorder is "
                     f"not free when off"),
            suggestion=("every instrumentation site must guard on "
                        "`self.metrics is not None`; find the unguarded "
                        "one")))
    if seed_leak and delta == 0:
        findings.append(Finding(
            code="OBS002", where="serve.metrics_off",
            message=("seeded leak fixture produced zero mutations — the "
                     "detector itself is broken (a real leak would pass "
                     "unnoticed)"),
            suggestion="check obs.registry.mutation_total accounting"))
    if any(getattr(s, "name", None) != "OK" for s in statuses):
        findings.append(Finding(
            code="OBS002", where="serve.metrics_off",
            message=(f"metrics-off sequence produced non-OK statuses "
                     f"{report['statuses']} — the measurement is not "
                     f"trustworthy on a failing solve"),
            suggestion="fix the serving solve path first"))
    return findings, report


def check_idle_overhead(mutations: int = 20_000, scrapes: int = 20
                        ) -> tuple:
    """OBS002 check 3: the recorder-ON surface stays within its measured
    budgets (per-mutation and per-scrape). Returns (findings, report)."""
    import jax.numpy as jnp

    from ..config import SVDConfig
    from ..serve import ServeConfig, SVDService
    from ..utils import matgen

    cfg = ServeConfig(
        buckets=((32, 32, "float64"),), solver=SVDConfig(block_size=4),
        metrics=True, brownout_sigma_only_at=2.0, brownout_shed_at=2.0)
    findings: List[Finding] = []
    with SVDService(cfg) as svc:
        # One real request so the scrape renders a populated registry.
        a = matgen.random_dense(24, 24, seed=13, dtype=jnp.float64)
        svc.submit(a).result(timeout=600.0)
        t0 = time.perf_counter()
        for i in range(mutations):
            svc.metrics.inc("svdj_obs002_idle_total", lane=i % 4)
        per_mutation = (time.perf_counter() - t0) / mutations
        t0 = time.perf_counter()
        for _ in range(scrapes):
            text = svc.metrics_text()
        per_scrape = (time.perf_counter() - t0) / scrapes
        series = sum(1 for ln in text.splitlines()
                     if ln and not ln.startswith("#"))
    report = {"per_mutation_s": per_mutation, "per_scrape_s": per_scrape,
              "series_rendered": series,
              "mutation_budget_s": MUTATION_BUDGET_S,
              "scrape_budget_s": SCRAPE_BUDGET_S}
    if per_mutation > MUTATION_BUDGET_S:
        findings.append(Finding(
            code="OBS002", where="registry.mutation",
            message=(f"registry mutation costs {per_mutation * 1e6:.1f} "
                     f"us (budget {MUTATION_BUDGET_S * 1e6:.0f} us) — "
                     f"the hot-path tax regressed"),
            suggestion=("keep mutations one dict update under one lock; "
                        "move derived values to scrape-time collectors")))
    if per_scrape > SCRAPE_BUDGET_S:
        findings.append(Finding(
            code="OBS002", where="registry.scrape",
            message=(f"/metrics scrape costs {per_scrape:.3f} s (budget "
                     f"{SCRAPE_BUDGET_S} s) over {series} series"),
            suggestion="check the scrape-time collectors for heavy work"))
    return findings, report


def run_all() -> tuple:
    """The OBS002 pass body (analysis.__main__ 'obs'): all three checks.
    Returns (findings, report)."""
    findings = check_metrics_off_hlo()
    off_findings, off_report = run_metrics_off_case()
    findings += off_findings
    idle_findings, idle_report = check_idle_overhead()
    findings += idle_findings
    return findings, {"metrics_off": off_report, "idle": idle_report}
