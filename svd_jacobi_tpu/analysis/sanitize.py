"""Runtime sanitizers: JAX's nan/inf debug checks + the transfer guard,
as one restorable context.

This is the configuration behind the ``-m sanitized`` pytest lane
(tests/test_sanitized.py) and the CLI's ``--sanitized`` flag:

  * ``jax_debug_nans`` / ``jax_debug_infs`` — recheck jitted outputs for
    NaN/Inf and re-run de-optimized to locate the producing primitive.
    The solver's loop carries use +inf SENTINELS (the off-norm comparator
    inits) deliberately; those live inside the fused loops and never
    reach jit outputs, so debug_infs stays usable — a regression that
    leaks a sentinel into a result will trip it.
  * ``jax_transfer_guard_device_to_host="disallow"`` — implicit
    device->host transfers inside the guarded region raise. The fused
    solves keep the matrix resident on device by contract; a mid-solve
    host read becomes a hard error instead of a silent per-sweep PCIe/ICI
    round trip. Only the d2h direction is guarded: implicit HOST-to-device
    transfers are idiomatic JAX (every Python scalar operand of an eager
    op is one), so guarding them rejects correct library code.

Note the flags are jit-cache-relevant state: entering the context
retraces the entries it touches (expected; the sanitized lane carries its
own compile budget).
"""

from __future__ import annotations

import contextlib


@contextlib.contextmanager
def sanitized(*, nans: bool = True, infs: bool = True,
              transfer_guard: str = "disallow"):
    """Enable the runtime sanitizers, restoring previous state on exit.

    ``transfer_guard`` applies to the device->host direction only (see
    module docstring); pass "" to disable it.
    """
    import jax

    prev_nans = jax.config.jax_debug_nans
    prev_infs = jax.config.jax_debug_infs
    stack = contextlib.ExitStack()
    try:
        jax.config.update("jax_debug_nans", bool(nans))
        jax.config.update("jax_debug_infs", bool(infs))
        if transfer_guard:
            stack.enter_context(
                jax.transfer_guard_device_to_host(transfer_guard))
        yield
    finally:
        stack.close()
        jax.config.update("jax_debug_nans", prev_nans)
        jax.config.update("jax_debug_infs", prev_infs)
