"""Custom AST lint for traced-code hygiene — the GRAFT0xx rules.

Jaxpr/HLO passes check the artifacts; these rules check the SOURCE
properties that decide whether the artifacts stay checkable: a `float()`
on a traced value doesn't appear in any jaxpr — it either crashes the
trace or silently host-syncs a host-stepped path — so it has to be caught
in the AST. Rules:

  GRAFT001  host materialization of a traced value in solver library code
            (`float()`/`int()`/`bool()`/`.item()`/`np.asarray()` on values
            inferred traced, and any `.addressable_shards` poke — the
            solver.py:184 pattern). Fix: read scalars through
            `svd_jacobi_tpu.utils._exec.host_scalar`, which handles
            non-fully-addressable arrays and empty-shard processes.
  GRAFT002  Python `if`/`while`/`assert` on a traced boolean — a
            TracerBoolConversionError at best, a silent trace-time
            constant at worst. Fix: `jax.lax.cond`/`jnp.where`.
  GRAFT003  `jax.numpy` computation at module import time — builds device
            arrays (and may initialize the backend) on import, breaking
            backend selection and multi-process bootstrap ordering.
  GRAFT004  jit cache-key hygiene: every `static_argnames` entry must name
            a real parameter, and static parameters must not default to
            unhashable values (an unhashable static arg raises at call
            time; a misspelled static name silently becomes a traced arg
            and every distinct value RETRACES — the schedule-in-the-jit-key
            failure the recompile guard measures at runtime).
  GRAFT005  named-scope coverage of the PROFILE.md hot regions
            (`config.HOT_SCOPES`): every declared hot function must
            contain its `with scope("<name>")` annotation, so profiler
            traces stay mappable to the measured component rows.

GRAFT001/002 need to know what is "traced"; the inference is deliberately
conservative (names assigned from `jnp.`/`lax.` calls, and parameters of
jit-decorated functions) so the real package lints clean without a pragma
forest. Intentional host reads are suppressed per line with
``# graftcheck: ok`` (all rules) or ``# graftcheck: ok GRAFT001``.
Rules GRAFT001/002 apply only to the traced library modules
(`TRACED_MODULES`); host-side drivers (cli, bench, utils/checkpoint) are
exempt by construction.
"""

from __future__ import annotations

import ast
import io
import tokenize
from pathlib import Path
from typing import Dict, List, Optional, Set

from . import Finding
from .. import config as _config

RULES = {
    "GRAFT001": "host materialization of a traced value in library code",
    "GRAFT002": "Python control flow on a traced boolean",
    "GRAFT003": "jax.numpy computation at module import time",
    "GRAFT004": "jit cache-key hygiene (static_argnames)",
    "GRAFT005": "missing named_scope on a declared hot region",
}

# Modules whose code runs under jit tracing (GRAFT001/002 scope); paths
# relative to the package root. grad/ is traced code too: the rule
# bodies run inside jvp/vjp traces of user training steps.
TRACED_MODULES = ("solver.py", "ops/", "parallel/", "grad/")

# jnp/lax attribute calls that return host metadata, not traced arrays.
_METADATA_FNS = frozenset({
    "finfo", "iinfo", "dtype", "promote_types", "result_type", "shape",
    "ndim", "issubdtype", "can_cast",
})
_CAST_BUILTINS = frozenset({"float", "int", "bool", "complex"})
_NP_MATERIALIZERS = frozenset({"asarray", "array", "ascontiguousarray"})

_PKG_ROOT = Path(__file__).resolve().parent.parent


def _pragmas(source: str) -> Dict[int, Set[str]]:
    """line -> suppressed rule codes ({'*'} = all) from graftcheck pragmas."""
    out: Dict[int, Set[str]] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            text = tok.string.lstrip("#").strip()
            if not text.startswith("graftcheck:"):
                continue
            rest = text[len("graftcheck:"):].strip()
            if rest.startswith("ok"):
                codes = set(rest[2:].split()) or {"*"}
                out.setdefault(tok.start[0], set()).update(codes)
    except tokenize.TokenError:
        pass
    return out


def _attr_chain(node: ast.AST) -> Optional[List[str]]:
    """['jax', 'lax', 'cond'] for jax.lax.cond; None for non-name chains."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def _is_jnp_rooted(chain: Optional[List[str]]) -> bool:
    if not chain:
        return False
    if chain[0] in ("jnp", "lax"):
        return chain[-1] not in _METADATA_FNS
    if chain[0] == "jax" and len(chain) >= 2 and chain[1] in ("numpy", "lax"):
        return chain[-1] not in _METADATA_FNS
    return False


def _is_jit_decorator(dec: ast.AST) -> bool:
    """jax.jit / jit / partial(jax.jit, ...) / functools.partial(jax.jit, ...)."""
    chain = _attr_chain(dec)
    if chain and chain[-1] == "jit":
        return True
    if isinstance(dec, ast.Call):
        fchain = _attr_chain(dec.func)
        if fchain and fchain[-1] == "jit":
            return True
        if fchain and fchain[-1] == "partial" and dec.args:
            achain = _attr_chain(dec.args[0])
            return bool(achain and achain[-1] == "jit")
    return False


# Array attributes that are host metadata, not traced values.
_METADATA_ATTRS = frozenset({
    "shape", "ndim", "dtype", "size", "sharding", "is_fully_addressable",
    "weak_type", "itemsize", "nbytes",
})


def _decorator_static_names(fn: ast.FunctionDef,
                            module_consts: Dict[str, List[str]]) -> Set[str]:
    """static_argnames declared on a function's jit decorator(s)."""
    names: Set[str] = set()
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call) and _is_jit_decorator(dec):
            for kw in dec.keywords:
                if kw.arg == "static_argnames":
                    resolved = _resolve_static_names(kw.value, module_consts)
                    names.update(resolved or [])
    return names


class _TracedInference:
    """Per-function traced-name inference (conservative)."""

    def __init__(self, fn: ast.FunctionDef,
                 module_consts: Optional[Dict[str, List[str]]] = None):
        self.traced: Set[str] = set()
        if any(_is_jit_decorator(d) for d in fn.decorator_list):
            static = _decorator_static_names(fn, module_consts or {})
            args = fn.args
            for a in (list(args.posonlyargs) + list(args.args)
                      + list(args.kwonlyargs)):
                # static_argnames params are trace-time constants.
                if a.arg not in static:
                    self.traced.add(a.arg)
        # One forward pass over assignments is enough for our code shape.
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and self.is_traced(node.value):
                for tgt in node.targets:
                    self._add_target(tgt)
            elif (isinstance(node, ast.AugAssign)
                  and self.is_traced(node.value)):
                self._add_target(node.target)

    def _add_target(self, tgt: ast.AST) -> None:
        if isinstance(tgt, ast.Name):
            self.traced.add(tgt.id)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._add_target(el)

    def is_traced(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.traced
        if isinstance(node, ast.Call):
            return _is_jnp_rooted(_attr_chain(node.func))
        if isinstance(node, ast.Attribute):
            # x.shape / x.dtype / ... are host metadata even on tracers.
            if node.attr in _METADATA_ATTRS:
                return False
            return self.is_traced(node.value)
        if isinstance(node, (ast.Subscript, ast.Starred)):
            return self.is_traced(node.value)
        if isinstance(node, ast.BinOp):
            return self.is_traced(node.left) or self.is_traced(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_traced(node.operand)
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None` is legitimate static structure
            # dispatch on tracers, not a traced boolean.
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False
            return (self.is_traced(node.left)
                    or any(self.is_traced(c) for c in node.comparators))
        if isinstance(node, ast.BoolOp):
            return any(self.is_traced(v) for v in node.values)
        if isinstance(node, ast.IfExp):
            return self.is_traced(node.body) or self.is_traced(node.orelse)
        return False


def _import_time_calls(tree: ast.Module):
    """Call nodes executed at import time: module body + class bodies,
    PRUNING function/lambda bodies and `if __name__ == '__main__'` guards
    (ast.walk cannot prune, so this is a manual traversal)."""
    stack = list(tree.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.If):
            t = node.test
            if (isinstance(t, ast.Compare) and isinstance(t.left, ast.Name)
                    and t.left.id == "__name__"):
                continue  # driver-script __main__ guard
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def _resolve_static_names(node: ast.AST,
                          module_consts: Dict[str, List[str]]
                          ) -> Optional[List[str]]:
    """static_argnames value -> list of names (tuple/list literal, single
    string, or a module-level Name bound to one)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        names = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                names.append(el.value)
            else:
                return None
        return names
    if isinstance(node, ast.Name):
        return module_consts.get(node.id)
    return None


_UNHASHABLE_NODES = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                     ast.DictComp, ast.SetComp)


def _check_jit_hygiene(tree: ast.Module, rel: str) -> List[Finding]:
    findings: List[Finding] = []
    fns: Dict[str, ast.FunctionDef] = {}
    module_consts = _module_consts(tree)
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            fns.setdefault(node.name, node)

    def check_pair(static_node: ast.AST, fn: Optional[ast.FunctionDef],
                   line: int) -> None:
        names = _resolve_static_names(static_node, module_consts)
        if names is None or fn is None:
            return
        args = fn.args
        params = [a.arg for a in (list(args.posonlyargs) + list(args.args)
                                  + list(args.kwonlyargs))]
        defaults: Dict[str, ast.AST] = {}
        pos = list(args.posonlyargs) + list(args.args)
        for a, d in zip(pos[len(pos) - len(args.defaults):], args.defaults):
            defaults[a.arg] = d
        for a, d in zip(args.kwonlyargs, args.kw_defaults):
            if d is not None:
                defaults[a.arg] = d
        for name in names:
            if name not in params:
                findings.append(Finding(
                    code="GRAFT004", where=f"{rel}:{line}",
                    message=(f"static_argnames entry {name!r} is not a "
                             f"parameter of {fn.name}() — it silently "
                             f"becomes a traced argument and every "
                             f"distinct value retraces"),
                    suggestion="fix the name or drop it"))
            elif isinstance(defaults.get(name), _UNHASHABLE_NODES):
                findings.append(Finding(
                    code="GRAFT004", where=f"{rel}:{line}",
                    message=(f"static parameter {name!r} of {fn.name}() "
                             f"defaults to an unhashable value — the jit "
                             f"cache key cannot hash it"),
                    suggestion="use a hashable default (tuple, str, None)"))

    for node in ast.walk(tree):
        # @partial(jax.jit, static_argnames=...) / @jax.jit decorators
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) and _is_jit_decorator(dec):
                    for kw in dec.keywords:
                        if kw.arg == "static_argnames":
                            check_pair(kw.value, node, dec.lineno)
        # x = partial(jax.jit, static_argnames=...)(fn) wrappers
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Call)
                and _is_jit_decorator(node.func) and node.args
                and isinstance(node.args[0], ast.Name)):
            for kw in node.func.keywords:
                if kw.arg == "static_argnames":
                    check_pair(kw.value, fns.get(node.args[0].id),
                               node.lineno)
        # jax.jit(fn, static_argnames=...) direct wrapping
        if isinstance(node, ast.Call) and not isinstance(node.func, ast.Call):
            chain = _attr_chain(node.func)
            if chain and chain[-1] == "jit" and node.args \
                    and isinstance(node.args[0], ast.Name):
                for kw in node.keywords:
                    if kw.arg == "static_argnames":
                        check_pair(kw.value, fns.get(node.args[0].id),
                                   node.lineno)
    return findings


def _module_consts(tree: ast.Module) -> Dict[str, List[str]]:
    """Module-level names bound to string tuples (static_argnames refs)."""
    consts: Dict[str, List[str]] = {}
    for stmt in tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)):
            names = _resolve_static_names(stmt.value, {})
            if names is not None:
                consts[stmt.targets[0].id] = names
    return consts


def _check_traced_rules(tree: ast.Module, rel: str) -> List[Finding]:
    """GRAFT001 + GRAFT002 over every function of a traced module."""
    findings: List[Finding] = []
    consts = _module_consts(tree)

    for fn in [n for n in ast.walk(tree) if isinstance(n, ast.FunctionDef)]:
        inf = _TracedInference(fn, consts)
        for node in ast.walk(fn):
            # GRAFT001: casts / materializers on traced values
            if isinstance(node, ast.Call):
                func = node.func
                if (isinstance(func, ast.Name)
                        and func.id in _CAST_BUILTINS and node.args
                        and inf.is_traced(node.args[0])):
                    findings.append(Finding(
                        code="GRAFT001", where=f"{rel}:{node.lineno}",
                        message=(f"{func.id}() on a traced value host-syncs "
                                 f"(and raises on non-fully-addressable "
                                 f"arrays)"),
                        suggestion=("read device scalars through "
                                    "utils._exec.host_scalar")))
                chain = _attr_chain(func)
                if (chain and chain[0] == "np" and len(chain) == 2
                        and chain[1] in _NP_MATERIALIZERS and node.args
                        and inf.is_traced(node.args[0])):
                    findings.append(Finding(
                        code="GRAFT001", where=f"{rel}:{node.lineno}",
                        message=(f"np.{chain[1]}() on a traced value "
                                 f"forces a device->host transfer"),
                        suggestion=("keep the value on device, or read it "
                                    "through utils._exec.host_scalar")))
                if (isinstance(func, ast.Attribute) and func.attr == "item"
                        and not node.args):
                    findings.append(Finding(
                        code="GRAFT001", where=f"{rel}:{node.lineno}",
                        message=".item() host-syncs the array",
                        suggestion=("read device scalars through "
                                    "utils._exec.host_scalar")))
            if (isinstance(node, ast.Attribute)
                    and node.attr == "addressable_shards"):
                findings.append(Finding(
                    code="GRAFT001", where=f"{rel}:{node.lineno}",
                    message=("ad-hoc .addressable_shards host read — "
                             "breaks on empty-shard processes"),
                    suggestion=("use utils._exec.host_scalar (handles "
                                "non-fully-addressable arrays and "
                                "empty-shard processes)")))
            # GRAFT002: python control flow on traced booleans
            if isinstance(node, (ast.If, ast.While)):
                if inf.is_traced(node.test):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    findings.append(Finding(
                        code="GRAFT002", where=f"{rel}:{node.lineno}",
                        message=(f"Python `{kind}` on a traced boolean — "
                                 f"raises under jit (or freezes a "
                                 f"trace-time constant)"),
                        suggestion="use jax.lax.cond / jnp.where"))
            if isinstance(node, ast.Assert) and inf.is_traced(node.test):
                findings.append(Finding(
                    code="GRAFT002", where=f"{rel}:{node.lineno}",
                    message="assert on a traced boolean",
                    suggestion=("use checkify / debug.check, or move the "
                                "assert to host-side values")))
    return findings


def _check_import_time(tree: ast.Module, rel: str) -> List[Finding]:
    findings: List[Finding] = []
    for node in _import_time_calls(tree):
        if _is_jnp_rooted(_attr_chain(node.func)):
            findings.append(Finding(
                code="GRAFT003", where=f"{rel}:{node.lineno}",
                message=("jax.numpy call at module import time — "
                         "creates device buffers (and can pin the "
                         "backend) before main() configures it"),
                suggestion=("build constants lazily inside the "
                            "function that uses them")))
    return findings


def check_scope_coverage(hot_scopes: Optional[dict] = None,
                         root: Optional[Path] = None) -> List[Finding]:
    """GRAFT005: every declared hot region carries its named scope."""
    hot_scopes = _config.HOT_SCOPES if hot_scopes is None else hot_scopes
    root = _PKG_ROOT if root is None else Path(root)
    findings: List[Finding] = []
    parsed: Dict[Path, ast.Module] = {}
    for scope_name, (rel, fn_name) in sorted(hot_scopes.items()):
        path = root / rel
        if path not in parsed:
            try:
                parsed[path] = ast.parse(path.read_text())
            except (OSError, SyntaxError) as e:
                findings.append(Finding(
                    code="GRAFT005", where=str(rel),
                    message=f"cannot parse declared hot module: {e}",
                    suggestion="fix config.HOT_SCOPES"))
                continue
        tree = parsed[path]
        fn = next((n for n in ast.walk(tree)
                   if isinstance(n, ast.FunctionDef) and n.name == fn_name),
                  None)
        if fn is None:
            findings.append(Finding(
                code="GRAFT005", where=str(rel),
                message=(f"declared hot function {fn_name}() not found "
                         f"(scope '{scope_name}')"),
                suggestion="update config.HOT_SCOPES after the refactor"))
            continue
        covered = False
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            is_scope = (chain and (chain[-1] == "scope"
                                   or chain[-1] == "named_scope"))
            if is_scope and node.args and isinstance(node.args[0],
                                                     ast.Constant):
                arg = str(node.args[0].value)
                if arg == scope_name or arg.endswith(f"/{scope_name}"):
                    covered = True
                    break
        if not covered:
            findings.append(Finding(
                code="GRAFT005", where=f"{rel}:{fn.lineno}",
                message=(f"{fn_name}() lost its scope(\"{scope_name}\") "
                         f"annotation — profiler traces no longer map to "
                         f"the PROFILE.md component row"),
                suggestion=f'wrap the hot region in scope("{scope_name}")'))
    return findings


def _is_traced_module(rel: str) -> bool:
    return any(rel == m or rel.startswith(m) for m in TRACED_MODULES)


def lint_file(path, *, rel: Optional[str] = None,
              traced: Optional[bool] = None) -> List[Finding]:
    """All per-file rules on one source file. ``traced`` forces GRAFT001/2
    on (fixture corpora) or off; default follows TRACED_MODULES."""
    path = Path(path)
    if rel is None:
        try:
            rel = str(path.resolve().relative_to(_PKG_ROOT))
        except ValueError:
            rel = path.name
    source = path.read_text()
    tree = ast.parse(source)
    if traced is None:
        traced = _is_traced_module(rel)
    findings: List[Finding] = []
    if traced:
        findings += _check_traced_rules(tree, rel)
    findings += _check_import_time(tree, rel)
    findings += _check_jit_hygiene(tree, rel)
    pragmas = _pragmas(source)
    kept = []
    for f in findings:
        try:
            line = int(f.where.rsplit(":", 1)[1])
        except (IndexError, ValueError):
            line = -1
        codes = pragmas.get(line, set())
        if "*" in codes or f.code in codes:
            continue
        kept.append(f)
    return kept


def lint_package(root: Optional[Path] = None) -> List[Finding]:
    """Lint every module of the package + the hot-scope coverage check —
    the pass the CLI and the tier-1 fail-fast hook run."""
    root = _PKG_ROOT if root is None else Path(root)
    findings: List[Finding] = []
    for path in sorted(root.rglob("*.py")):
        rel = str(path.relative_to(root))
        findings += lint_file(path, rel=rel)
    findings += check_scope_coverage(root=root)
    return findings
