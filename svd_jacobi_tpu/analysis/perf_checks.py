"""PERF001 — the analytic cost model agrees with XLA's own accounting.

The roofline observatory (obs.costmodel / obs.attribution / obs.perf)
divides measured per-scope durations by ANALYTIC FLOP counts. An
analytic model nobody checks drifts silently — a refactor moves work
between phases, a new entry lands unmodeled, and every roofline
percentage quietly becomes fiction. This pass pins the model to a
ground truth XLA computes for free:

  1. **Model agreement** — for every registry entry probe
     (`analysis.entries.single_device_probes`), the model's
     ``convention="xla"`` FLOP count (`obs.costmodel.entry_flops`) must
     agree with ``probe.lower().compile().cost_analysis()["flops"]``
     within ``MODEL_TOL_FACTOR`` either way. The xla convention mirrors
     `cost_analysis` semantics (while/scan bodies counted once,
     LAPACK-style custom calls ~zero, matmuls 2mnk), so the residual
     ratio is structure error — exactly what drift looks like. The
     seeded fixture (``drift_factor`` ~9x, a lost n^3 term) MUST fire:
     tests prove the detector can fail, not just that it passes.
  2. **Scope-phase join coverage** — `config.SCOPE_PHASES` (the
     attribution join table) keys must equal `config.HOT_SCOPES` keys
     EXACTLY, and every mapped phase must be a canonical
     `obs.costmodel.PHASES` name: a new profiler scope cannot land
     unattributable, and a typo'd phase cannot silently drop its model.
  3. **Perf-off HLO byte-identity** — the OBS002 discipline extended to
     the observatory: importing obs.perf, exercising a
     `ConvergenceRecorder`, and resolving roofline device constants is
     host-side only and must not perturb any entry's lowering.
"""

from __future__ import annotations

from typing import List

from . import Finding

# Agreement tolerance, either direction (model/xla in
# [1/2.5, 2.5]). Measured headroom on the current probe census:
# ratios 0.70-1.35 on the f32 entries, 1.79 on the f64 qr-svd lane
# (cost_analysis gives its LAPACK custom calls ~zero flops while the
# model keeps the loop-visible matmuls). A lost or doubled n^3 term
# moves the ratio well past 2.5; dtype/shape bookkeeping errors scale
# worse.
MODEL_TOL_FACTOR = 2.5


def _probe_model_flops(probe, *, drift_factor: float = 1.0) -> float:
    """The model's xla-convention FLOPs for one entry probe, parameters
    read off the probe itself (shape, dtype, sketch kwargs)."""
    from ..obs import costmodel

    a = probe.args[0]
    batch = 1
    shape = tuple(a.shape)
    if probe.name == "pallas_batched":
        batch, m, n = shape
    else:
        m, n = shape
    kw = dict(block_size=costmodel.default_block_size(n),
              dtype=str(a.dtype), batch=batch, convention="xla")
    if probe.name == "sketch_project":
        kw["sketch_width"] = int(probe.kwargs.get("l", 0))
        kw["power_iters"] = int(probe.kwargs.get("power_iters", 0))
        kw["chunk"] = probe.kwargs.get("chunk")
    elif probe.name == "tsqr_tall":
        kw["chunk"] = probe.kwargs.get("chunk")
    return costmodel.entry_flops(probe.name, m, n, **kw) * drift_factor


def _xla_flops(probe) -> float:
    ca = probe.lower().compile().cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    return float(ca.get("flops", 0.0))


def check_model_agreement(*, drift_factor: float = 1.0) -> tuple:
    """PERF001 check 1 (see module docstring). ``drift_factor``
    multiplies the model — the seeded drifted-model fixture. Returns
    (findings, report rows)."""
    from . import entries

    findings: List[Finding] = []
    rows = []
    for probe in entries.single_device_probes():
        model = _probe_model_flops(probe, drift_factor=drift_factor)
        xla = _xla_flops(probe)
        ratio = model / xla if xla > 0 else float("inf")
        rows.append({"entry": probe.name, "model_flops": model,
                     "xla_flops": xla, "ratio": round(ratio, 3)})
        if not (1.0 / MODEL_TOL_FACTOR <= ratio <= MODEL_TOL_FACTOR):
            findings.append(Finding(
                code="PERF001", where=probe.name,
                message=(f"analytic model disagrees with XLA "
                         f"cost_analysis: model {model:.3e} vs xla "
                         f"{xla:.3e} FLOPs (ratio {ratio:.2f}, "
                         f"tolerance {MODEL_TOL_FACTOR}x either way)"),
                suggestion=("re-derive obs.costmodel.entry_flops for "
                            "this entry against its HLO dot census — "
                            "a phase's term was lost, doubled, or the "
                            "entry's composition changed")))
    return findings, rows


def check_scope_phase_join() -> List[Finding]:
    """PERF001 check 2: SCOPE_PHASES covers HOT_SCOPES exactly and maps
    into the canonical phase vocabulary."""
    from .. import config
    from ..obs import costmodel

    findings: List[Finding] = []
    scopes = set(config.HOT_SCOPES)
    mapped = set(config.SCOPE_PHASES)
    for missing in sorted(scopes - mapped):
        findings.append(Finding(
            code="PERF001", where=f"config.SCOPE_PHASES[{missing!r}]",
            message=(f"HOT_SCOPES scope {missing!r} has no phase "
                     f"mapping — its trace time would attribute to "
                     f"'other' with no roofline"),
            suggestion="add the scope to config.SCOPE_PHASES"))
    for stale in sorted(mapped - scopes):
        findings.append(Finding(
            code="PERF001", where=f"config.SCOPE_PHASES[{stale!r}]",
            message=(f"SCOPE_PHASES maps {stale!r}, which is not a "
                     f"HOT_SCOPES scope — stale join entry"),
            suggestion="remove it or add the scope to HOT_SCOPES"))
    for scope, phase in sorted(config.SCOPE_PHASES.items()):
        if phase not in costmodel.PHASES:
            findings.append(Finding(
                code="PERF001",
                where=f"config.SCOPE_PHASES[{scope!r}]",
                message=(f"maps to unknown phase {phase!r} (canonical: "
                         f"{list(costmodel.PHASES)})"),
                suggestion="use a costmodel.PHASES name"))
    return findings


def check_perf_off_hlo() -> List[Finding]:
    """PERF001 check 3: the observatory is host-side only — importing
    and exercising it must leave a probe's perf-off lowering
    byte-identical (the OBS002 discipline)."""
    from . import entries

    probes = entries.single_device_probes(include_f64=False)
    by_name = {p.name: p for p in probes}
    picked = [by_name[n] for n in ("pallas", "padded_hybrid")
              if n in by_name] or probes[:2]

    findings: List[Finding] = []
    for probe in picked:
        off = probe.with_kwargs(
            **({probe.telemetry_key: False} if probe.telemetry_key
               else {}))
        baseline = off.lower().as_text()
        # Exercise the whole observatory surface between lowerings.
        from ..obs import costmodel
        from ..obs.perf import ConvergenceRecorder, device_block
        rec = ConvergenceRecorder(spectrum="perf001")
        rec.record(0.5, "bulk")
        rec.record(1e-7, "polish")
        rec.record_rounds(3, 4)
        rec.block(tol=1e-6)
        device_block("cpu")
        costmodel.solve_costs(48, 32, block_size=4)
        after = off.lower().as_text()
        if after != baseline:
            findings.append(Finding(
                code="PERF001", where=probe.name,
                message=("perf-off lowering changed after exercising "
                         "the perf observatory — it leaked into the "
                         "trace"),
                suggestion=("costmodel/attribution/perf must stay "
                            "host-side: nothing there may run under a "
                            "jax trace")))
    return findings


def run_all() -> tuple:
    """The PERF001 pass body (analysis.__main__ 'perf'). Returns
    (findings, report)."""
    findings, rows = check_model_agreement()
    findings += check_scope_phase_join()
    findings += check_perf_off_hlo()
    # Seeded drifted-model fixture: a model off by ~9x (one lost n^3
    # term's magnitude) MUST trip the detector.
    drift_findings, _ = check_model_agreement(drift_factor=9.0)
    if not drift_findings:
        findings.append(Finding(
            code="PERF001", where="drift_fixture",
            message=("seeded 9x model drift produced zero findings — "
                     "the agreement detector itself is broken (real "
                     "drift would pass unnoticed)"),
            suggestion="check check_model_agreement's ratio math"))
    report = {"model": rows, "tolerance_factor": MODEL_TOL_FACTOR,
              "drift_fixture_fired": bool(drift_findings)}
    return findings, report
