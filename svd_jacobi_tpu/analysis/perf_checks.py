"""PERF001 — the analytic cost model agrees with XLA's own accounting.

The roofline observatory (obs.costmodel / obs.attribution / obs.perf)
divides measured per-scope durations by ANALYTIC FLOP counts. An
analytic model nobody checks drifts silently — a refactor moves work
between phases, a new entry lands unmodeled, and every roofline
percentage quietly becomes fiction. This pass pins the model to a
ground truth XLA computes for free:

  1. **Model agreement** — for every registry entry probe
     (`analysis.entries.single_device_probes`), the model's
     ``convention="xla"`` FLOP count (`obs.costmodel.entry_flops`) must
     agree with ``probe.lower().compile().cost_analysis()["flops"]``
     within ``MODEL_TOL_FACTOR`` either way. The xla convention mirrors
     `cost_analysis` semantics (while/scan bodies counted once,
     LAPACK-style custom calls ~zero, matmuls 2mnk), so the residual
     ratio is structure error — exactly what drift looks like. The
     seeded fixture (``drift_factor`` ~9x, a lost n^3 term) MUST fire:
     tests prove the detector can fail, not just that it passes.
  2. **Scope-phase join coverage** — `config.SCOPE_PHASES` (the
     attribution join table) keys must equal `config.HOT_SCOPES` keys
     EXACTLY, and every mapped phase must be a canonical
     `obs.costmodel.PHASES` name: a new profiler scope cannot land
     unattributable, and a typo'd phase cannot silently drop its model.
  3. **Perf-off HLO byte-identity** — the OBS002 discipline extended to
     the observatory: importing obs.perf, exercising a
     `ConvergenceRecorder`, and resolving roofline device constants is
     host-side only and must not perturb any entry's lowering.

VMEM001 — static VMEM-budget check (rides the same pass). Every Pallas
lane declares a per-grid-step working-set model
(`ops.pallas_apply._pick_chunk`, `ops.pallas_resident.footprint`); this
check evaluates it for every geometry the repo SHIPS — the declared
serve buckets under the current backend's routing, and the tuning
table's TPU kernel-lane rows at their class-representative shapes — and
fails loudly with the offending (m, b, R, dtype) when a routed,
kernel-eligible lane cannot pick a usable row chunk, instead of letting
Mosaic error (or the runtime guard silently fall back) at solve time.
The resident lane's factor stacks grow as R*k*(2b)^2, so each resident
row also reports its engagement envelope (the largest n_pad whose
footprint still fits at the row's (b, R)) — a shipped row whose envelope
sits below its own size class can never engage and is a finding too.
The seeded over-budget fixture (R doubled past the budget at the large
class geometry) MUST fire, proving the detector can fail.
"""

from __future__ import annotations

from typing import List

from . import Finding

# Agreement tolerance, either direction (model/xla in
# [1/2.5, 2.5]). Measured headroom on the current probe census:
# ratios 0.70-1.35 on the f32 entries, 1.79 on the f64 qr-svd lane
# (cost_analysis gives its LAPACK custom calls ~zero flops while the
# model keeps the loop-visible matmuls). A lost or doubled n^3 term
# moves the ratio well past 2.5; dtype/shape bookkeeping errors scale
# worse.
MODEL_TOL_FACTOR = 2.5


def _probe_model_flops(probe, *, drift_factor: float = 1.0) -> float:
    """The model's xla-convention FLOPs for one entry probe, parameters
    read off the probe itself (shape, dtype, sketch kwargs)."""
    from ..obs import costmodel

    a = probe.args[0]
    batch = 1
    shape = tuple(a.shape)
    if probe.name == "pallas_batched":
        batch, m, n = shape
    else:
        m, n = shape
    kw = dict(block_size=costmodel.default_block_size(n),
              dtype=str(a.dtype), batch=batch, convention="xla")
    if probe.name == "sketch_project":
        kw["sketch_width"] = int(probe.kwargs.get("l", 0))
        kw["power_iters"] = int(probe.kwargs.get("power_iters", 0))
        kw["chunk"] = probe.kwargs.get("chunk")
    elif probe.name == "tsqr_tall":
        kw["chunk"] = probe.kwargs.get("chunk")
    return costmodel.entry_flops(probe.name, m, n, **kw) * drift_factor


def _xla_flops(probe) -> float:
    ca = probe.lower().compile().cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    return float(ca.get("flops", 0.0))


def check_model_agreement(*, drift_factor: float = 1.0) -> tuple:
    """PERF001 check 1 (see module docstring). ``drift_factor``
    multiplies the model — the seeded drifted-model fixture. Returns
    (findings, report rows)."""
    from . import entries

    findings: List[Finding] = []
    rows = []
    for probe in entries.single_device_probes():
        model = _probe_model_flops(probe, drift_factor=drift_factor)
        xla = _xla_flops(probe)
        ratio = model / xla if xla > 0 else float("inf")
        rows.append({"entry": probe.name, "model_flops": model,
                     "xla_flops": xla, "ratio": round(ratio, 3)})
        if not (1.0 / MODEL_TOL_FACTOR <= ratio <= MODEL_TOL_FACTOR):
            findings.append(Finding(
                code="PERF001", where=probe.name,
                message=(f"analytic model disagrees with XLA "
                         f"cost_analysis: model {model:.3e} vs xla "
                         f"{xla:.3e} FLOPs (ratio {ratio:.2f}, "
                         f"tolerance {MODEL_TOL_FACTOR}x either way)"),
                suggestion=("re-derive obs.costmodel.entry_flops for "
                            "this entry against its HLO dot census — "
                            "a phase's term was lost, doubled, or the "
                            "entry's composition changed")))
    return findings, rows


def check_scope_phase_join() -> List[Finding]:
    """PERF001 check 2: SCOPE_PHASES covers HOT_SCOPES exactly and maps
    into the canonical phase vocabulary."""
    from .. import config
    from ..obs import costmodel

    findings: List[Finding] = []
    scopes = set(config.HOT_SCOPES)
    mapped = set(config.SCOPE_PHASES)
    for missing in sorted(scopes - mapped):
        findings.append(Finding(
            code="PERF001", where=f"config.SCOPE_PHASES[{missing!r}]",
            message=(f"HOT_SCOPES scope {missing!r} has no phase "
                     f"mapping — its trace time would attribute to "
                     f"'other' with no roofline"),
            suggestion="add the scope to config.SCOPE_PHASES"))
    for stale in sorted(mapped - scopes):
        findings.append(Finding(
            code="PERF001", where=f"config.SCOPE_PHASES[{stale!r}]",
            message=(f"SCOPE_PHASES maps {stale!r}, which is not a "
                     f"HOT_SCOPES scope — stale join entry"),
            suggestion="remove it or add the scope to HOT_SCOPES"))
    for scope, phase in sorted(config.SCOPE_PHASES.items()):
        if phase not in costmodel.PHASES:
            findings.append(Finding(
                code="PERF001",
                where=f"config.SCOPE_PHASES[{scope!r}]",
                message=(f"maps to unknown phase {phase!r} (canonical: "
                         f"{list(costmodel.PHASES)})"),
                suggestion="use a costmodel.PHASES name"))
    return findings


def check_perf_off_hlo() -> List[Finding]:
    """PERF001 check 3: the observatory is host-side only — importing
    and exercising it must leave a probe's perf-off lowering
    byte-identical (the OBS002 discipline)."""
    from . import entries

    probes = entries.single_device_probes(include_f64=False)
    by_name = {p.name: p for p in probes}
    picked = [by_name[n] for n in ("pallas", "padded_hybrid")
              if n in by_name] or probes[:2]

    findings: List[Finding] = []
    for probe in picked:
        off = probe.with_kwargs(
            **({probe.telemetry_key: False} if probe.telemetry_key
               else {}))
        baseline = off.lower().as_text()
        # Exercise the whole observatory surface between lowerings.
        from ..obs import costmodel
        from ..obs.perf import ConvergenceRecorder, device_block
        rec = ConvergenceRecorder(spectrum="perf001")
        rec.record(0.5, "bulk")
        rec.record(1e-7, "polish")
        rec.record_rounds(3, 4)
        rec.block(tol=1e-6)
        device_block("cpu")
        costmodel.solve_costs(48, 32, block_size=4)
        after = off.lower().as_text()
        if after != baseline:
            findings.append(Finding(
                code="PERF001", where=probe.name,
                message=("perf-off lowering changed after exercising "
                         "the perf observatory — it leaked into the "
                         "trace"),
                suggestion=("costmodel/attribution/perf must stay "
                            "host-side: nothing there may run under a "
                            "jax trace")))
    return findings


# Kernel-path lanes: a geometry routed to one of these engages compiled
# Pallas kernels when its block width is lane-aligned (b % 128 == 0).
_KERNEL_LANES = ("pallas", "block_rotation", "resident", "hybrid")

# Class-representative shapes for the tuning table's TPU rows: the floor
# of each kernel-relevant size class plus the medium ceiling (k doubles
# across the class while b stays fixed, so the ceiling is the in-class
# worst case for the resident factor stacks).
_TABLE_SHAPES = (2048, 4096, 8190, 8192)
_TABLE_DEVICES = (("tpu", "tpu-v5-lite"),)


def _kernel_geometry(n: int, b: int) -> tuple:
    """(b, k, n_pad) the kernel path would use: the even-b fix-up and the
    pair-count round-up of solver._plan. Kernel sweeps run on the
    QR-preconditioned n_pad x n_pad triangle, so n_pad is also the row
    count the apply kernels see."""
    if b % 2:
        b += 1
    k = max(1, -(-n // (2 * b)))
    return b, k, 2 * k * b


def _resident_envelope(b: int, r: int) -> int:
    """Largest n_pad = 2*k*b whose resident footprint still fits at
    (b, r) — the lane's engagement envelope; beyond it the runtime guard
    (`pallas_resident.supported`) falls back to the XLA twin."""
    from ..ops import pallas_resident as _resident

    k, last = 1, 0
    while _resident.footprint(2 * k * b, b, k, r)["fits"]:
        last = 2 * k * b
        k += 1
        if k > 4096:
            break
    return last


def _vmem_rows(source: str, n: int, dtype: str, resolved) -> list:
    """Footprint rows for one routed geometry: the shared exchange/apply
    kernel (rides every kernel lane) and, when routed, the resident
    megakernel."""
    from ..ops import pallas_apply as pa
    from ..ops import pallas_resident as _resident

    b, k, n_pad = _kernel_geometry(n, resolved.block_size)
    lane = resolved.pair_solver or "pallas"
    eligible = bool(b % 128 == 0 and lane in _KERNEL_LANES)
    chunk = int(pa._pick_chunk(n_pad, b, 6, pa._gram_fixed_bytes(b)))
    rows = [{
        "source": source, "lane": "pallas_apply.apply_exchange",
        "n": n, "m": n_pad, "b": b, "k": k, "r": 1, "dtype": dtype,
        "row_chunk": chunk, "fits": bool(chunk > 0), "eligible": eligible,
        "routed_solver": lane,
    }]
    if lane == "resident":
        r = int(resolved.rounds_resident or _resident.DEFAULT_ROUNDS)
        r = max(1, min(r, 2 * k - 1))
        fp = _resident.footprint(n_pad, b, k, r)
        fp.update(source=source, n=n, dtype=dtype, eligible=eligible,
                  routed_solver=lane,
                  envelope_n=_resident_envelope(b, r))
        rows.append(fp)
    return rows


def check_vmem_budget(*, fixture_oversize: bool = False) -> tuple:
    """VMEM001 (see module docstring). Returns (findings, report rows).
    ``fixture_oversize`` appends a deliberately over-budget geometry
    (the large-class shape with R forced past the factor-stack budget)
    that MUST produce a finding — the seeded-fixture proof."""
    from .. import config as _config
    from ..ops import pallas_resident as _resident
    from ..tune import tables as _tables

    rows: list = []
    # 1. The declared serve buckets under the CURRENT backend's routing
    #    (on a CPU host these resolve small, kernel-ineligible block
    #    widths — informational; on a TPU serve host they are the actual
    #    shipped compile geometries).
    for bucket in _config.DEFAULT_SERVE_BUCKETS:
        m, n, dtype = bucket[0], bucket[1], bucket[2]
        res = _tables.resolve(n, m, dtype)
        rows += _vmem_rows(f"serve_bucket[{m}x{n}]", n, dtype, res)
    # 2. The tuning table's TPU kernel-lane rows at class-representative
    #    shapes — static, so a CPU-only CI still validates what the
    #    table promises a v5-lite host.
    for backend, kind in _TABLE_DEVICES:
        for n in _TABLE_SHAPES:
            res = _tables.resolve(n, n, "float32", backend=backend,
                                  device_kind=kind)
            if (res.pair_solver or "pallas") in _KERNEL_LANES:
                rows += _vmem_rows(f"table[{kind} {n}x{n}]", n,
                                   "float32", res)
    if fixture_oversize:
        b, k, n = 256, 16, 8192
        r = 4 * _resident.DEFAULT_ROUNDS
        fp = _resident.footprint(2 * k * b, b, k, r)
        fp.update(source="fixture_oversize", n=n, dtype="float32",
                  eligible=True, routed_solver="resident",
                  envelope_n=_resident_envelope(b, r))
        rows.append(fp)

    findings: List[Finding] = []
    for row in rows:
        if not (row["eligible"] and not row["fits"]):
            continue
        where = f"{row['source']}:{row['lane']}"
        findings.append(Finding(
            code="VMEM001", where=where,
            message=(f"per-grid-step VMEM footprint over budget: lane "
                     f"{row['lane']} at m={row['m']} b={row['b']} "
                     f"R={row['r']} dtype={row['dtype']} picks no usable "
                     f"row chunk (step_bytes "
                     f"{row.get('step_bytes', 0):,} at the minimum "
                     f"chunk) — Mosaic would reject this geometry or "
                     f"the runtime guard would silently fall back"),
            suggestion=("lower rounds_resident (the factor stacks are "
                        "R*k*(2b)^2*4 bytes) or route the class to "
                        "pair_solver='block_rotation' / 'pallas'")))
    # A shipped resident row whose envelope can't reach its own class
    # floor would never engage — dead configuration, also a finding.
    for row in rows:
        if (row["lane"] != "pallas_resident.apply_group"
                or row["source"].startswith("fixture")
                or not row["eligible"] or not row["fits"]):
            continue
        if row["envelope_n"] < row["n"]:
            findings.append(Finding(
                code="VMEM001", where=f"{row['source']}:{row['lane']}",
                message=(f"resident row engages nominally but its "
                         f"envelope (n_pad <= {row['envelope_n']}) sits "
                         f"below the checked shape n={row['n']}"),
                suggestion="lower rounds_resident for this class"))
    return findings, rows


def run_all() -> tuple:
    """The PERF001 + VMEM001 pass body (analysis.__main__ 'perf').
    Returns (findings, report)."""
    findings, rows = check_model_agreement()
    findings += check_scope_phase_join()
    findings += check_perf_off_hlo()
    vmem_findings, vmem_rows = check_vmem_budget()
    findings += vmem_findings
    # Seeded drifted-model fixture: a model off by ~9x (one lost n^3
    # term's magnitude) MUST trip the detector.
    drift_findings, _ = check_model_agreement(drift_factor=9.0)
    if not drift_findings:
        findings.append(Finding(
            code="PERF001", where="drift_fixture",
            message=("seeded 9x model drift produced zero findings — "
                     "the agreement detector itself is broken (real "
                     "drift would pass unnoticed)"),
            suggestion="check check_model_agreement's ratio math"))
    # Seeded over-budget VMEM fixture: R forced 4x past the shipped
    # large-class grouping MUST trip the footprint detector.
    vmem_fixture_findings, _ = check_vmem_budget(fixture_oversize=True)
    vmem_fixture_fired = any(f.where.startswith("fixture_oversize")
                             for f in vmem_fixture_findings)
    if not vmem_fixture_fired:
        findings.append(Finding(
            code="VMEM001", where="fixture_oversize",
            message=("seeded over-budget resident geometry produced "
                     "zero findings — the VMEM footprint detector "
                     "itself is broken (a real overflow would reach "
                     "Mosaic as a compile error)"),
            suggestion=("check pallas_resident.footprint / "
                        "_pick_chunk's budget math")))
    report = {"model": rows, "tolerance_factor": MODEL_TOL_FACTOR,
              "drift_fixture_fired": bool(drift_findings),
              "vmem": vmem_rows,
              "vmem_fixture_fired": vmem_fixture_fired}
    return findings, report
