"""HLO contract checks: compile/lower the hot paths and assert properties
of the artifacts XLA actually receives.

Three contracts (codes HLO001-003):

  * HLO001 — collective budget. The sharded round loop's only legal
    communication is the tournament ring exchange (2 `collective_permute`
    hops per stack per round) plus the pmax'd convergence machinery
    (`all_reduce`); an `all_gather` would mean some step materializes a
    gathered matrix. Counted on the LOWERED StableHLO module — shard_map
    collectives are explicit there, while-loop bodies appear exactly once
    (not unrolled), and the GSPMD postprocessing outside the loop has not
    yet been partitioned into collectives — so the module count IS the
    sweep loop's budget. Exact equality against
    `config.COLLECTIVE_BUDGET`, so nothing rides in silently.
  * HLO002 — buffer donation. `SVDConfig.donate_input` must survive all
    the way down: the donated entry's lowered module marks the input
    donated (`tf.aliasing_output`/`jax.buffer_donor`) and the compiled
    executable reports input-output aliasing; the undonated twin must
    mark nothing (donating by accident invalidates caller arrays).
  * HLO003 — telemetry-off HLO equivalence (the generalization of
    tests/test_obs.py's original check, which tested one entry): for every
    fused entry, the telemetry-off lowering contains no callback custom
    call, is byte-identical whether or not the host-side enable flag is
    set, and differs from the telemetry-on lowering (proving the flag is
    real, not dead).
  * HLO004 — chaos injection gate. The `resilience.chaos` NaN-injection
    hook rides the fused entries as a static `chaos_nan_sweep` argument
    (like the telemetry flag): the production planner must resolve it to
    None (unarmed), and arming it must change the lowering (the gate is
    real) — so fault injection can never ride a production program, and
    the chaos lane cannot silently test a no-op.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional

from . import Finding
from .. import config as _config

COLLECTIVE_OPS = ("collective_permute", "all_gather", "all_reduce",
                  "all_to_all", "reduce_scatter")
# Markers of a donated parameter in lowered StableHLO (jax spells it either
# way across versions) and of realized aliasing in a compiled executable.
DONATION_MARKERS = ("tf.aliasing_output", "jax.buffer_donor")
ALIAS_MARKER = "input_output_alias"


def collective_counts(lowered_text: str) -> Dict[str, int]:
    """Static occurrence count of each collective op in a lowered module."""
    return {op: len(re.findall(rf"stablehlo\.{op}\b", lowered_text))
            for op in COLLECTIVE_OPS}


def check_collective_budget(probe, budget: Optional[Dict[str, int]] = None
                            ) -> List[Finding]:
    """HLO001 for one mesh probe. ``budget`` defaults to the declared
    `config.COLLECTIVE_BUDGET[probe.name]`."""
    if budget is None:
        budget = _config.COLLECTIVE_BUDGET.get(probe.name)
        if budget is None:
            return [Finding(
                code="HLO001", where=probe.name,
                message=("no declared collective budget for this entry — "
                         "declare it in config.COLLECTIVE_BUDGET"),
                suggestion="add exact per-op counts with a derivation")]
    text = probe.lower().as_text()
    counts = collective_counts(text)
    findings = []
    for op, expected in budget.items():
        got = counts.get(op, 0)
        if got != expected:
            findings.append(Finding(
                code="HLO001", where=probe.name,
                message=(f"collective budget violated: {got} "
                         f"stablehlo.{op} ops in the lowered module, "
                         f"declared {expected}"),
                suggestion=("if the change is intentional, update "
                            "config.COLLECTIVE_BUDGET with the new "
                            "derivation; otherwise find the op that "
                            "snuck into the sweep loop")))
    return findings


def check_donation(donated_probe, plain_probe) -> List[Finding]:
    """HLO002: donation marks the donated entry (and only it), and
    survives compilation to input-output aliasing."""
    findings = []
    donated_lowered = donated_probe.lower()
    donated_text = donated_lowered.as_text()
    plain_text = plain_probe.lower().as_text()
    if not any(m in donated_text for m in DONATION_MARKERS):
        findings.append(Finding(
            code="HLO002", where=donated_probe.name,
            message=("donate_input entry lowered WITHOUT a donation "
                     "marker — XLA will keep the caller's input buffer "
                     "alive and the largest sizes OOM"),
            suggestion=("check donate_argnums on the jit wrapper "
                        "(solver._svd_pallas_donated)")))
    if any(m in plain_text for m in DONATION_MARKERS):
        findings.append(Finding(
            code="HLO002", where=plain_probe.name,
            message=("undonated entry carries a donation marker — the "
                     "caller's array would be invalidated without "
                     "donate_input"),
            suggestion="remove the stray donate_argnums"))
    if not findings:
        compiled = donated_lowered.compile().as_text()
        if ALIAS_MARKER not in compiled:
            findings.append(Finding(
                code="HLO002", where=donated_probe.name,
                message=("donation did not survive compilation: no "
                         "input_output_alias in the executable (the "
                         "donated buffer is copied, not reused)"),
                suggestion=("the donated shape/dtype must match an "
                            "output's exactly for XLA to alias it")))
    return findings


def check_telemetry_invariance(probe) -> List[Finding]:
    """HLO003 for one entry: telemetry-off lowering is callback-free,
    independent of the host-side enable flag, and distinct from the
    telemetry-on lowering."""
    from ..obs import metrics

    if not probe.telemetry_key:
        return []
    key = probe.telemetry_key
    prev = metrics.enabled()
    try:
        # Baseline under a DISABLED module flag — with ambient enable
        # state the flag-independence comparison would compare two
        # identically-enabled lowerings and could never fail.
        metrics.disable()
        off = probe.with_kwargs(**{key: False}).lower().as_text()
        metrics.enable()
        off_enabled = probe.with_kwargs(**{key: False}).lower().as_text()
        on = probe.with_kwargs(**{key: True}).lower().as_text()
    finally:
        metrics.enable() if prev else metrics.disable()
    findings = []
    if "callback" in off:
        findings.append(Finding(
            code="HLO003", where=probe.name,
            message=("telemetry-off lowering contains a callback custom "
                     "call — the zero-telemetry program is no longer the "
                     "seed program"),
            suggestion=("an emit call site lost its static telemetry "
                        "gate; see obs.metrics design notes")))
    if off != off_enabled:
        findings.append(Finding(
            code="HLO003", where=probe.name,
            message=("telemetry-off lowering depends on the host-side "
                     "enable flag — telemetry must be a static trace "
                     "property, not runtime state"),
            suggestion=("something reads obs.metrics.enabled() inside "
                        "the traced function instead of threading it as "
                        "a static argument")))
    if on == off:
        findings.append(Finding(
            code="HLO003", where=probe.name,
            message=("telemetry-on lowering is identical to telemetry-off "
                     "— the telemetry flag is dead on this entry"),
            suggestion="thread the flag into the sweep loop's emit sites"))
    return findings


def check_chaos_gate(probe) -> List[Finding]:
    """HLO004 for one entry carrying the `chaos_nan_sweep` static: the
    production plan resolves it unarmed (None), and arming it changes the
    lowering."""
    if "chaos_nan_sweep" not in probe.kwargs:
        return []
    findings: List[Finding] = []
    if probe.kwargs["chaos_nan_sweep"] is not None:
        findings.append(Finding(
            code="HLO004", where=probe.name,
            message=("entry planner resolved chaos_nan_sweep="
                     f"{probe.kwargs['chaos_nan_sweep']!r} — fault "
                     "injection is ARMED in a production plan"),
            suggestion=("never leave resilience.chaos.nan_at_sweep armed "
                        "outside a chaos-lane test")))
        return findings
    off = probe.lower().as_text()
    armed = probe.with_kwargs(chaos_nan_sweep=1).lower().as_text()
    if armed == off:
        findings.append(Finding(
            code="HLO004", where=probe.name,
            message=("arming chaos_nan_sweep does not change the lowering "
                     "— the injection gate is dead and the chaos lane "
                     "tests a no-op"),
            suggestion=("thread chaos_nan_sweep into the entry's sweep "
                        "loop (resilience.chaos.poison)")))
    return findings


def check_default_entries(include_mesh: bool = True) -> List[Finding]:
    """The full HLO pass over the declared probes: telemetry invariance
    and the chaos-injection gate on every entry, donation on the
    donated/plain pallas pair, collective budgets on every mesh probe."""
    from . import entries

    findings: List[Finding] = []
    singles = {p.name: p for p in entries.single_device_probes()}
    for probe in singles.values():
        findings += check_telemetry_invariance(probe)
        findings += check_chaos_gate(probe)
    if "pallas_donated" in singles and "pallas" in singles:
        findings += check_donation(singles["pallas_donated"],
                                   singles["pallas"])
    # Zero-collective budgets on the single-device entries that declare
    # one: the batched pair-axis stack (pure data layout) and the
    # sketch/TSQR stage jits of the top-k/tall lanes (matmul/QR chains —
    # any collective here would be hand-written, never legitimate).
    for name in ("pallas_batched", "pallas_block_rotation",
                 "pallas_resident", "sketch_project", "tsqr_tall"):
        if name in singles:
            findings += check_collective_budget(singles[name])
    if include_mesh:
        for probe in entries.mesh_probes():
            findings += check_collective_budget(probe)
            findings += check_telemetry_invariance(probe)
            findings += check_chaos_gate(probe)
    return findings
