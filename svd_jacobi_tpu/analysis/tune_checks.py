"""TUNE001 — the autotuner contract pass.

Three claims, all machine-checked against the real artifacts:

  1. SHIPPED TABLES VALIDATE: every ``tune/tables/*.json`` passes the
     schema + content-hash validation (`tune.tables.TuningTable
     .from_payload`). A shipped table that silently fails would make
     every "auto" knob fall back to the generic heuristics — legal at
     runtime (the fallback is the design), but a shipped default that
     never applies is a packaging bug this pass exists to catch.
  2. BUCKET COVERAGE: every ``config.DEFAULT_SERVE_BUCKETS`` entry
     resolves through a NON-generic row of the shipped table
     (``Resolved.generic_only`` False) — the declared serving surface
     must be covered by measured rows, not by the catch-all.
  3. NO NEW RETRACES: tuning-table resolution is a pure function, so a
     service whose per-bucket configs came through the table must keep
     the once-per-bucket compile contract. A `RecompileGuard` sequence
     (two buckets x two request shapes each, repeated — repeats must be
     cache hits) proves it on the serving-path jit entries.

The seeded failing fixture (tests/fixtures/tune_bad_table.json — edited
without re-hashing) demonstrates rule 1 actually fires; an under-declared
guard budget on the rule-3 sequence is exercised by tests/test_tune.py.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import List, Optional, Sequence, Tuple

from . import Finding

CODE = "TUNE001"


def check_tables(paths: Optional[Sequence] = None) -> List[Finding]:
    """Rule 1: schema/hash-validate the shipped tables (or ``paths``)."""
    from ..tune import tables
    if paths is None:
        paths = sorted(tables.shipped_table_dir().glob("*.json"))
        if not paths:
            return [Finding(
                code=CODE, where=str(tables.shipped_table_dir()),
                message="no shipped tuning table found — every 'auto' "
                        "knob would fall back to the generic heuristics",
                suggestion="restore tune/tables/default.json (regenerate "
                           "with `python -m svd_jacobi_tpu.tune`)")]
    findings = []
    for path in paths:
        try:
            tables.load_table(path)
        except (tables.TableError, OSError, json.JSONDecodeError) as e:
            findings.append(Finding(
                code=CODE, where=str(path),
                message=f"tuning table failed validation: {e}",
                suggestion="regenerate with `python -m svd_jacobi_tpu."
                           "tune` (hand edits must be re-hashed via "
                           "tune.tables.save_table)"))
    return findings


def check_bucket_resolution(table=None,
                            buckets: Optional[Sequence] = None
                            ) -> List[Finding]:
    """Rule 2: the declared serving buckets resolve via measured rows —
    for every family. Bucket specs coerce through `serve.as_bucket`
    (tuples of any arity, strings, Buckets), a "topk" bucket resolves
    with its rank class, and the top-k family additionally requires the
    SKETCH knobs (oversample/power_iters/tsqr_chunk) to come from a
    non-generic row (``Resolved.sketch_generic_only``) — the truncated
    lane's accuracy/speed trade must be a measured verdict, not the
    catch-all default."""
    from .. import config as _config
    from ..serve import as_bucket
    from ..tune import tables
    if table is None:
        try:
            table = tables.load_table(tables.shipped_table_path())
        except Exception:
            # Rule 1 reports the load failure; this rule would only
            # duplicate it against the builtin fallback.
            return []
    findings = []
    for spec in (buckets if buckets is not None
                 else _config.DEFAULT_SERVE_BUCKETS):
        b = as_bucket(spec)
        r = tables.resolve(b.n, m=b.m, dtype=b.dtype,
                           k=(b.k if b.kind == "topk" else None),
                           table=table)
        if r.generic_only:
            findings.append(Finding(
                code=CODE, where=f"DEFAULT_SERVE_BUCKETS[{b.name}]",
                message=(f"bucket resolves only through the generic "
                         f"fallback row of table {table.table_id!r} — the "
                         f"declared serving surface is not covered by "
                         f"measured rows"),
                suggestion="add a measured row for this (n_class, aspect, "
                           "dtype) to the shipped table"))
        elif b.kind == "topk" and r.sketch_generic_only:
            findings.append(Finding(
                code=CODE, where=f"DEFAULT_SERVE_BUCKETS[{b.name}]",
                message=(f"top-k bucket's SKETCH knobs (oversample/"
                         f"power_iters/tsqr_chunk) resolve only through "
                         f"the generic fallback of table "
                         f"{table.table_id!r} — the truncated lane's "
                         f"rank class is not covered by measured rows"),
                suggestion="add a k_class row pinning the sketch knobs "
                           "for this rank class to the shipped table"))
    return findings


_RESOLVED_BUCKETS = ((64, 48, "float32"), (96, 64, "float32"))
_RESOLVED_SHAPES = ((64, 48), (52, 40), (96, 64), (80, 56))
_RESOLVED_ENTRIES = ("solver._precondition_qr_jit",
                     "solver._sweep_step_pallas_jit",
                     "solver._finish_pallas_jit",
                     "solver._nonfinite_probe_jit")


def run_resolved_serve_case(expected_problems: Optional[int] = None,
                            buckets: Optional[Sequence] = None,
                            shapes: Optional[Sequence] = None
                            ) -> Tuple[List[Finding], dict]:
    """Rule 3: a service with table-resolved per-bucket configs keeps the
    once-per-bucket compile contract. Two buckets, two distinct request
    shapes each, every submit repeated — the repeats and the second
    shapes must be cache hits on the bucket's entry (RETRACE001-style
    over the serving jits, reusing `RecompileGuard`).

    ``expected_problems`` under-declares the budget and ``buckets``/
    ``shapes`` substitute FRESH (never-compiled) problems for the seeded
    failing fixture — tests prove the guard fires, not just passes (a
    warm jit cache would mask an under-declared budget)."""
    import jax.numpy as jnp

    from ..config import SVDConfig
    from ..serve import ServeConfig, SVDService
    from ..utils import matgen
    from .recompile_guard import RecompileGuard

    buckets = (_RESOLVED_BUCKETS if buckets is None else tuple(buckets))
    shapes = (_RESOLVED_SHAPES if shapes is None
              else tuple(tuple(s) for s in shapes))
    problems = (len(buckets) if expected_problems is None
                else int(expected_problems))
    cfg = ServeConfig(
        buckets=buckets,
        solver=SVDConfig(pair_solver="pallas"),
        max_queue_depth=2 * len(shapes) + 2,
        brownout_sigma_only_at=2.0, brownout_shed_at=2.0)
    statuses = []
    with RecompileGuard() as guard:
        for entry in _RESOLVED_ENTRIES:
            guard.expect(entry, problems=problems)
        with SVDService(cfg) as svc:
            for _ in range(2):
                tickets = [
                    svc.submit(matgen.random_dense(m, n, seed=m * 31 + n,
                                                   dtype=jnp.float32))
                    for m, n in shapes]
                statuses += [t.result(timeout=600.0).status
                             for t in tickets]
            resolved = {b.name: {
                "block_size": c.block_size,
                "mixed_store": c.mixed_store,
            } for b, c in svc._bucket_solver.items()}
        findings = guard.check()
        report = guard.report()
    report["resolved_configs"] = resolved
    report["serve_statuses"] = [getattr(s, "name", None) for s in statuses]
    if any(s is None or s.name != "OK" for s in statuses):
        findings.append(Finding(
            code=CODE, where="tune.run_resolved_serve_case",
            message=(f"resolved-config serve sequence produced non-OK "
                     f"statuses {report['serve_statuses']} — the retrace "
                     f"measurement is not trustworthy on a failing solve"),
            suggestion="fix the resolved-config serving path first"))
    # Rebrand the guard's RETRACE001 findings under this pass's code so a
    # failure reads as the tuning layer's contract, with the retrace
    # detail preserved in the message.
    findings = [
        f if f.code == CODE else Finding(
            code=CODE, where=f.where,
            message=f"table-resolved serving config retraced: {f.message}",
            suggestion=f.suggestion)
        for f in findings]
    return findings, report


def run_all() -> Tuple[List[Finding], dict]:
    """The `python -m svd_jacobi_tpu.analysis` "tune" pass."""
    findings = check_tables()
    findings += check_bucket_resolution()
    serve_findings, report = run_resolved_serve_case()
    findings += serve_findings
    return findings, report
