"""ROUTE001 — the federated-router contract pass.

Two load-bearing claims of `serve.router`, machine-checked against the
real artifacts (the ring implementation and a live two-replica rescue):

  1. ROUTING IS DETERMINISTIC: the consistent-hash ring is a pure
     function of (replica set, vnodes, bucket, digest) — two
     independently-built rings agree on every preference order, every
     preference is a full permutation of the replica set (failover can
     always walk somewhere), placement is SHA-256-positioned (identical
     across processes and PYTHONHASHSEED), ownership spreads over the
     replicas, and removing one replica remaps ONLY the keys it owned
     (the consistent-hashing minimal-disruption property — a quarantine
     must not reshuffle the healthy replicas' cache/compile locality).
     Byte-identical resubmits (same `serve.cache.input_digest`) map to
     the same owner, which is what keeps the result-cache admission
     fast-path a sub-millisecond hit behind the router.
  2. RESCUE KEEPS THE COMPILE CONTRACT: a replica-death rescue re-admits
     the dead replica's journal debt onto the receiving replica, and
     that dispatch must be a jit-cache HIT — the receiving replica
     already compiled the bucket (shared persistent namespace + static
     bucket shapes), so a rescue adds ZERO fresh traces. Proven live
     under `RecompileGuard`: warm both replicas of a two-replica
     in-process router on one bucket, kill the owner with a request
     still queued, let the supervisor rescue it, and hold every
     serving-path entry to a once-per-bucket budget across the WHOLE
     sequence (warm + kill + rescue + re-serve).

``run_all(seed_skew=True)`` is the seeded-violation fixture: it
compares rings built with DIFFERENT vnode counts (a mis-deployed router
fleet) and must fire rule 1 — demonstrated by tests/test_router.py.
"""

from __future__ import annotations

import hashlib
from typing import List, Optional, Sequence

from . import Finding

CODE = "ROUTE001"

_SAMPLE_BUCKETS = ("64x48:float32", "96x64:float32", "1024x1024:float32",
                   "2048x256:float32:tall", "96x96:float32:topk8")


def _sample_digests(n: int) -> List[str]:
    return [hashlib.sha256(f"route001-sample-{i}".encode()).hexdigest()
            for i in range(n)]


def check_ring_determinism(replicas: Sequence[int] = (0, 1, 2),
                           vnodes: int = 64, samples: int = 48,
                           seed_skew: bool = False) -> List[Finding]:
    """Rule 1 (see module docstring). ``seed_skew`` builds the second
    ring with a different vnode count — the seeded violation the tests
    prove this pass catches."""
    from ..serve.router import HashRing
    findings: List[Finding] = []
    replicas = tuple(replicas)
    ring_a = HashRing(replicas, vnodes=vnodes)
    ring_b = HashRing(replicas,
                      vnodes=(vnodes + 1) if seed_skew else vnodes)
    digests = _sample_digests(samples)
    owners: dict = {r: 0 for r in replicas}
    mismatches = 0
    for bi, bucket in enumerate(_SAMPLE_BUCKETS):
        for digest in digests:
            pa = ring_a.preference(bucket, digest)
            pb = ring_b.preference(bucket, digest)
            if pa != pb:
                mismatches += 1
            if sorted(pa) != sorted(replicas):
                findings.append(Finding(
                    code=CODE, where="serve.router.HashRing.preference",
                    message=f"preference {pa} for ({bucket}, "
                            f"{digest[:12]}) is not a permutation of the "
                            f"replica set {replicas} — failover could "
                            f"dead-end",
                    suggestion="preference() must visit every replica "
                               "exactly once in ring-walk order"))
                break
            owners[pa[0]] += 1
        # Affinity fallback (no digest) must be deterministic too.
        if ring_a.preference(bucket) != ring_b.preference(bucket):
            mismatches += 1
    if mismatches:
        findings.append(Finding(
            code=CODE, where="serve.router.HashRing",
            message=f"two rings over the same replica set disagree on "
                    f"{mismatches} of {len(_SAMPLE_BUCKETS) * samples} "
                    f"sampled keys — routing is NOT a pure function of "
                    f"(replica set, vnodes, bucket, digest)",
            suggestion="ring construction must be deterministic "
                       "(SHA-256 positions, no process state, identical "
                       "vnode counts across the router fleet)"))
    starved = [r for r, n in owners.items() if n == 0]
    if starved and not findings:
        findings.append(Finding(
            code=CODE, where="serve.router.HashRing",
            message=f"replicas {starved} own ZERO of "
                    f"{len(_SAMPLE_BUCKETS) * samples} sampled keys — "
                    f"the ring is not spreading ownership",
            suggestion="raise ring_vnodes (placement variance shrinks "
                       "as vnodes grow)"))
    # Minimal-disruption: drop one replica; every key it did NOT own
    # must keep its owner (quarantine must not reshuffle the healthy
    # replicas' locality).
    if len(replicas) > 1 and not seed_skew:
        dropped = replicas[0]
        reduced = HashRing([r for r in replicas if r != dropped],
                           vnodes=vnodes)
        moved = sum(
            1 for bucket in _SAMPLE_BUCKETS for digest in digests
            if ring_a.owner(bucket, digest) != dropped
            and reduced.owner(bucket, digest) != ring_a.owner(bucket,
                                                              digest))
        if moved:
            findings.append(Finding(
                code=CODE, where="serve.router.HashRing",
                message=f"removing replica {dropped} remapped {moved} "
                        f"keys it never owned — consistent hashing's "
                        f"minimal-disruption property is broken",
                suggestion="only keys owned by the departed replica may "
                           "move"))
    return findings


def check_resubmit_affinity() -> List[Finding]:
    """Byte-identical resubmits compute the same digest and therefore
    the same owner — the property that keeps the admission fast-path a
    cache HIT behind the router (no numpy-copy or layout drift may leak
    into the key)."""
    import numpy as np

    from ..serve.cache import input_digest
    from ..serve.router import HashRing
    findings: List[Finding] = []
    ring = HashRing((0, 1, 2), vnodes=64)
    rng1 = np.random.default_rng(7)
    rng2 = np.random.default_rng(7)
    a1 = rng1.standard_normal((48, 32)).astype(np.float32)
    a2 = rng2.standard_normal((48, 32)).astype(np.float32)
    d1, d2 = input_digest(a1), input_digest(np.asarray(a2, order="F"))
    if d1 != d2:
        findings.append(Finding(
            code=CODE, where="serve.cache.input_digest",
            message="byte-identical matrices (different memory layouts) "
                    "digested differently — resubmits would miss their "
                    "owner",
            suggestion="input_digest must canonicalize layout "
                       "(ascontiguousarray) before hashing"))
    elif ring.owner("64x48:float32", d1) != ring.owner("64x48:float32",
                                                       d2):
        findings.append(Finding(
            code=CODE, where="serve.router.HashRing",
            message="equal digests routed to different owners",
            suggestion="owner() must be a pure function of the key"))
    return findings


def run_rescue_case() -> tuple:
    """Rule 2: the live two-replica rescue drill under `RecompileGuard`
    (module docstring). Returns (findings, report)."""
    import tempfile
    import time

    import jax.numpy as jnp

    from ..config import SVDConfig
    from ..resilience import chaos
    from ..serve import ReplicaRouter, RouterConfig, ServeConfig
    from ..utils import matgen
    from .recompile_guard import RecompileGuard, _SERVE_ENTRIES

    bucket = (64, 48, "float32")
    cfg = RouterConfig(
        replicas=2,
        serve=ServeConfig(
            buckets=(bucket,), solver=SVDConfig(pair_solver="pallas"),
            max_queue_depth=16,
            brownout_sigma_only_at=2.0, brownout_shed_at=2.0),
        state_dir=tempfile.mkdtemp(prefix="route001-"),
        supervise_interval_s=0.02,
        heartbeat_timeout_s=1.0,
        # No probe may run inside the guard window: a factor-free probe
        # solve flips STATIC compute flags — a legitimate extra trace
        # that would read as a false RETRACE001.
        probe_interval_s=600.0)
    findings: List[Finding] = []
    report: dict = {}
    with RecompileGuard() as guard:
        for entry in _SERVE_ENTRIES:
            # ONE bucket, shared in-process jit cache: every entry
            # compiles once across BOTH replicas AND the rescue.
            guard.expect(entry, problems=1)
        router = ReplicaRouter(cfg).start()
        try:
            # Warm each replica: draw seeded matrices until both ring
            # owners served one (deterministic — the ring is).
            warmed = set()
            seed = 0
            while len(warmed) < 2 and seed < 64:
                seed += 1
                a = matgen.random_dense(60, 40, seed=seed,
                                        dtype=jnp.float32)
                t = router.submit(a, deadline_s=120.0)
                res = t.result(timeout=600.0)
                # The route record names the replica authoritatively.
                routed = [rec for rec in router.records()
                          if rec.get("event") == "route"
                          and rec.get("request_id") == t.request_id]
                warmed.add(routed[-1]["replica"])
                if res.status is None or res.status.name != "OK":
                    findings.append(Finding(
                        code=CODE, where="route_checks.run_rescue_case",
                        message=f"warm solve {t.request_id} not OK "
                                f"({res.status}/{res.error})",
                        suggestion="fix the serving path first"))
            report["warmed_replicas"] = sorted(warmed)
            # Kill the owner of one more matrix while its request is
            # still queued behind a slowed solve; the supervisor must
            # rescue it onto the surviving replica as a jit-cache HIT.
            a_hold = matgen.random_dense(64, 48, seed=777,
                                         dtype=jnp.float32)
            a_kill = matgen.random_dense(62, 44, seed=778,
                                         dtype=jnp.float32)
            with chaos.slow_solve(0.25, shots=2):
                t_hold = router.submit(a_hold, deadline_s=120.0)
                t_kill = router.submit(a_kill, deadline_s=120.0)
                routed = [rec for rec in router.records()
                          if rec.get("event") == "route"]
                victim_idx = routed[-1]["replica"]
                time.sleep(0.05)
                router.replicas[victim_idx].simulate_kill()
                res_hold = t_hold.result(timeout=600.0)
                res_kill = t_kill.result(timeout=600.0)
            report["rescues"] = router.total_rescues
            report["victim"] = victim_idx
            for name, res in (("held", res_hold), ("killed", res_kill)):
                ok = (res.error is None and res.status is not None
                      and res.status.name == "OK")
                report[f"{name}_status"] = (res.status.name
                                            if res.status else res.error)
                if not ok:
                    findings.append(Finding(
                        code=CODE, where="route_checks.run_rescue_case",
                        message=f"{name} request did not survive the "
                                f"replica death rescued-OK "
                                f"(status={report[f'{name}_status']})",
                        suggestion="the rescue must re-admit journal "
                                   "debt on a healthy replica"))
        finally:
            router.stop(drain=True, timeout=60.0)
        findings += guard.check()
        report.update(guard.report())
    return findings, report


def run_idempotency_case() -> tuple:
    """Rule 3 — RPC IDEMPOTENCY (the ``net`` pass family): a retried
    submit after a lost ACK admits EXACTLY once. A live in-process
    `serve.transport.HttpReplicaServer` serves one request over the
    wire; the identical record is then re-sent (the lost-ACK retry as
    the client would replay it) and must come back
    ``{"ok": true, "dup": true}`` — and the journal, read RAW (record
    lines, not collapsed ids), must hold exactly ONE admit and ONE
    finalize for the id. Returns (findings, report)."""
    import json
    import tempfile
    import time
    from pathlib import Path

    import jax.numpy as jnp
    import numpy as np

    from ..config import SVDConfig
    from ..serve import ServeConfig
    from ..serve.cache import input_digest
    from ..serve.transport import (WIRE_VERSION, HttpReplica,
                                   HttpReplicaServer)
    from ..utils import matgen

    findings: List[Finding] = []
    report: dict = {}
    rid = "net-idem-0"
    tmp = Path(tempfile.mkdtemp(prefix="route001-net-"))
    cfg = ServeConfig(
        buckets=((32, 32, "float32"),),
        solver=SVDConfig(pair_solver="pallas"),
        journal_path=str(tmp / "journal.jsonl"),
        compute_digest=True, max_queue_depth=16,
        brownout_sigma_only_at=2.0, brownout_shed_at=2.0)
    server = HttpReplicaServer(cfg).start()
    try:
        replica = HttpReplica(0, server.address, cfg.journal_path)
        a = np.asarray(matgen.random_dense(32, 32, seed=3,
                                           dtype=jnp.float32))
        sub = replica.submit(a, request_id=rid, deadline_s=300.0,
                             digest=input_digest(a))
        # The lost-ACK retry: the same idempotency key again. The
        # server dedupes BEFORE decoding (live bookkeeping + its
        # write-ahead journal), so the payload may be elided.
        dup = replica._rpc("submit", "/v1/submit", body={
            "kind": "submit", "wire_version": WIRE_VERSION, "id": rid,
            "t_wall": time.time(), "input": None})
        report["dup_ack"] = dup
        if not (dup.get("ok") and dup.get("dup")):
            findings.append(Finding(
                code=CODE, where="serve.transport.HttpReplicaServer",
                message=f"retried submit of {rid!r} was not ACKed as a "
                        f"duplicate (got {dup}) — a lost-ACK retry "
                        f"would double-admit",
                suggestion="dedupe submits against outstanding/results "
                           "and the write-ahead journal"))
        res = None
        t0 = time.time()
        while res is None and time.time() - t0 < 300:
            res = sub.poll(0.1)
        ok = (res is not None and res.error is None
              and res.status is not None and res.status.name == "OK")
        report["status"] = (None if res is None else
                            (res.status.name if res.status else res.error))
        if not ok:
            findings.append(Finding(
                code=CODE, where="route_checks.run_idempotency_case",
                message=f"wire-served solve {rid!r} not OK "
                        f"({report['status']})",
                suggestion="fix the HTTP serving path first"))
    finally:
        server.stop(drain=True, timeout=60.0)
    # Exactly-once, proven from the RAW journal stream: one admit
    # record, one finalize record for the id (id-keyed scan views
    # would collapse a double-admit silently).
    kinds = {"admit": 0, "finalize": 0}
    for line in Path(cfg.journal_path).read_text().splitlines():
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if rec.get("id") == rid and rec.get("kind") in kinds:
            kinds[rec["kind"]] += 1
    report["journal_records"] = dict(kinds)
    for kind, n in kinds.items():
        if n != 1:
            findings.append(Finding(
                code=CODE, where="serve.transport.HttpReplicaServer",
                message=f"journal holds {n} {kind} record(s) for "
                        f"{rid!r} after a retried submit — exactly-once "
                        f"is broken at the wire seam",
                suggestion="the receiver must admit each idempotency "
                           "key at most once (journal write-ahead + "
                           "rid dedupe)"))
    return findings, report


def run_all(seed_skew: bool = False) -> tuple:
    """The whole ROUTE001 pass. Returns (findings, report)."""
    findings = check_ring_determinism(seed_skew=seed_skew)
    findings += check_resubmit_affinity()
    report: dict = {"seed_skew": bool(seed_skew)}
    rescue_findings, rescue_report = run_rescue_case()
    findings += rescue_findings
    report["rescue"] = rescue_report
    return findings, report


def run_net() -> tuple:
    """The ``net`` pass family: ROUTE001's wire-transport extension
    (RPC idempotency over a live HTTP replica)."""
    findings, report = run_idempotency_case()
    return findings, {"idempotency": report}
