"""QOS001 — the multi-tenant front door keeps its three promises.

The tenancy PR threads caller identity through admission, dispatch,
records and metrics (serve.queue.TenantTable / ServeConfig.tenants).
This pass turns its three load-bearing contracts into checkable facts:

  1. **Tenant attribution is total** — every per-request serving metric
     family carries a ``tenant`` label on EVERY series, live and in the
     offline manifest reconstruction (`obs.registry.registry_from_manifest`),
     and the live per-tenant SLO trackers agree with
     `obs.registry.tenant_slo_from_records` on the same traffic. A
     single unlabeled series means some code path lost the identity —
     exactly the path an adversarial tenant would hide behind. (The
     per-sweep convergence histogram is per-BUCKET by design: a live
     coalesced batch mixes tenants in one dispatch.)
  2. **Weighted-fair dequeue is fair, work-conserving and
     starvation-free** — a deterministic seeded schedule drives
     `TenantTable` + `AdmissionQueue` directly (no service, no clock
     dependence in the assertions): shares track declared weights,
     cost-weighting (`buckets.admission_cost`) makes fairness fair in
     WORK not request count, no tenant starves while backlogged, the
     queue never idles while work is queued, and a rejected admission
     never consumes a rate token (the budget-leak audit).
  3. **Tenancy adds ZERO new jit entries** — tenant identity is
     host-side bookkeeping and must never reach a trace: a mixed
     multi-tenant request stream (EDF ordering on, weights, a
     rate-limited rejection in the middle) compiles each serving entry
     once per bucket, same as the single-tenant contract
     (`recompile_guard.run_serve_sequence`). ``seed_leak=True`` is the
     seeded failing fixture: it under-declares every budget against a
     fresh bucket, so the detector MUST fire (tests prove the check can
     fail, not just that it passes).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from . import Finding

# Per-request serving families that must carry a tenant label on every
# series. Live-only families (admitted) and reconstruction-only views
# are split below; svdj_deadline_miss_total and the sweeps histogram
# are intentionally absent (miss-only / per-bucket by design).
_LIVE_TENANT_FAMILIES = (
    "svdj_requests_admitted_total",
    "svdj_requests_rejected_total",
    "svdj_requests_finalized_total",
    "svdj_queue_wait_seconds",
    "svdj_solve_seconds",
    "svdj_request_latency_seconds",
)
# Families registry_from_manifest rebuilds from serve records (admitted
# and live gauges are not reconstructable — absent, not unlabeled).
_OFFLINE_TENANT_FAMILIES = (
    "svdj_requests_rejected_total",
    "svdj_requests_finalized_total",
    "svdj_queue_wait_seconds",
    "svdj_solve_seconds",
)
# Families the label case must actually populate — an empty registry
# would pass the "every series is labeled" scan vacuously.
_REQUIRED_LIVE = ("svdj_requests_admitted_total",
                  "svdj_requests_rejected_total",
                  "svdj_requests_finalized_total")


def _unlabeled(snapshot: dict, families) -> Dict[str, List[str]]:
    """family -> series label-strings missing a tenant label."""
    out: Dict[str, List[str]] = {}
    for fam in families:
        entry = snapshot.get(fam)
        if entry is None:
            continue
        bad = [lbl for lbl in entry["series"]
               if "tenant=" not in lbl]
        if bad:
            out[fam] = bad
    return out


def _slo_totals(snap: dict) -> Dict[str, int]:
    """Aggregate outcome counts of one SLO snapshot across buckets —
    the clock-independent view live and offline must agree on (latency
    quantiles depend on reservoir order; counts do not)."""
    tot = {"served": 0, "ok": 0, "deadline_miss": 0, "error": 0,
           "shed": 0}
    for c in snap["buckets"].values():
        for k in tot:
            tot[k] += int(c.get(k, 0))
    return tot


def run_tenant_label_case() -> tuple:
    """QOS001 check 1: drive a real multi-tenant serve sequence (token
    identity, a rate-limited rejection, a plain pre-tenancy submit) and
    assert total tenant attribution — live registry, reconstructed
    registry, and live-vs-offline per-tenant SLO agreement. Returns
    (findings, report)."""
    import jax.numpy as jnp

    from ..config import SVDConfig
    from ..obs.registry import (registry_from_manifest,
                                tenant_slo_from_records)
    from ..serve import AdmissionError, ServeConfig, SVDService
    from ..utils import matgen

    cfg = ServeConfig(
        buckets=((32, 32, "float64"),), solver=SVDConfig(block_size=4),
        max_queue_depth=8, metrics=True,
        tenants={"alice": {"weight": 3.0},
                 "bob": {"weight": 1.0},
                 # burst=1: the second mallory submit is RATE_LIMITED —
                 # the rejected path must be tenant-labeled too.
                 "mallory": {"rate": 0.001, "burst": 1.0}},
        api_tokens={"tok-alice": "alice"},
        brownout_sigma_only_at=2.0, brownout_shed_at=2.0)
    statuses, rejected = [], []
    with SVDService(cfg) as svc:
        mats = [matgen.random_dense(28, 28, seed=s, dtype=jnp.float64)
                for s in (21, 22, 23, 24, 25)]
        plan = [  # (tenant kwarg, api_token kwarg, matrix)
            (None, "tok-alice", mats[0]),     # token-resolved identity
            ("bob", None, mats[1]),
            (None, None, mats[2]),            # pre-tenancy surface
            ("mallory", None, mats[3]),
            ("mallory", None, mats[4]),       # over burst -> RATE_LIMITED
        ]
        for tenant, token, a in plan:
            try:
                t = svc.submit(a, tenant=tenant, api_token=token)
                statuses.append(t.result(timeout=600.0).status)
            except AdmissionError as e:
                rejected.append(e.reason.value)
    # Post-close reads (workers joined): a ticket unblocks BEFORE its
    # finalize bookkeeping lands, so snapshots settle only at stop.
    live_snap = svc.metrics.snapshot()
    health = svc.healthz()
    records = svc.records()

    findings: List[Finding] = []
    report = {"statuses": [getattr(s, "name", None) for s in statuses],
              "rejected": rejected}
    if any(getattr(s, "name", None) != "OK" for s in statuses):
        findings.append(Finding(
            code="QOS001", where="serve.tenant_labels",
            message=(f"multi-tenant sequence produced non-OK statuses "
                     f"{report['statuses']} — attribution checks are "
                     f"not trustworthy on a failing solve"),
            suggestion="fix the serving solve path first"))
    if rejected != ["rate_limited"]:
        findings.append(Finding(
            code="QOS001", where="serve.tenant_labels",
            message=(f"expected exactly one RATE_LIMITED rejection from "
                     f"the over-burst tenant, got {rejected}"),
            suggestion=("check TenantTable token accounting and the "
                        "admit-order contract (token consumed last)")))

    missing = [f for f in _REQUIRED_LIVE if f not in live_snap]
    if missing:
        findings.append(Finding(
            code="QOS001", where="serve.tenant_labels",
            message=(f"live registry is missing families {missing} "
                     f"after a mixed admit/reject/serve sequence — the "
                     f"label scan would be vacuous"),
            suggestion="check the serve instrumentation sites"))
    for scope, snap, fams in (
            ("live", live_snap, _LIVE_TENANT_FAMILIES),
            ("offline", registry_from_manifest(records).snapshot(),
             _OFFLINE_TENANT_FAMILIES)):
        bad = _unlabeled(snap, fams)
        report[f"{scope}_unlabeled"] = bad
        if bad:
            findings.append(Finding(
                code="QOS001", where=f"serve.tenant_labels.{scope}",
                message=(f"{scope} series without a tenant label: "
                         f"{bad} — some code path lost the caller "
                         f"identity"),
                suggestion=("thread the request's tenant through every "
                            "metric site (and registry_from_manifest's "
                            "serve branch for the offline twin)")))

    # Live healthz per-tenant SLO trackers vs the offline manifest
    # reconstruction: same traffic, same outcome counts per tenant.
    live_tenants = {t: _slo_totals(info["slo"])
                    for t, info in health.get("tenants", {}).items()
                    if info.get("slo")}
    off_tenants = {t: _slo_totals(snap) for t, snap in
                   tenant_slo_from_records(records).items()}
    report["live_slo"] = live_tenants
    report["offline_slo"] = off_tenants
    if live_tenants != off_tenants:
        findings.append(Finding(
            code="QOS001", where="serve.tenant_slo_agreement",
            message=(f"live per-tenant SLO counts {live_tenants} != "
                     f"offline reconstruction {off_tenants} — the "
                     f"fairness drills would assert against a lying "
                     f"substrate"),
            suggestion=("keep serve.service's live tenant-SLO feed and "
                        "obs.registry.tenant_slo_from_records (incl. "
                        "_SHED_STATUSES) in lockstep")))
    return findings, report


def check_wfq_schedule() -> tuple:
    """QOS001 check 2: deterministic WFQ schedule facts (module
    docstring item 2), driven directly against `AdmissionQueue` +
    `TenantTable` with no service and no clock-dependent assertions.
    Returns (findings, report)."""
    from ..serve.buckets import Bucket
    from ..serve.queue import (AdmissionError, AdmissionQueue, Request,
                               TenantTable)

    findings: List[Finding] = []
    report: dict = {}
    small = Bucket(64, 64, "float32")      # admission_cost == 1.0
    big = Bucket(128, 128, "float32")      # admission_cost == 8.0

    def mk(rid: int, tenant: str, bucket: Bucket = small,
           deadline: Optional[float] = None) -> Request:
        return Request(
            id=f"q-{rid}", a=None, m=bucket.m, n=bucket.n,
            orig_shape=(bucket.m, bucket.n), transposed=False,
            bucket=bucket, compute_u=True, compute_v=True,
            degraded=False, deadline=deadline, deadline_s=None,
            submitted=float(rid), tenant=tenant)

    def fail(where: str, message: str, suggestion: str) -> None:
        findings.append(Finding(code="QOS001", where=where,
                                message=message, suggestion=suggestion))

    # (a) Weighted shares + starvation bound. alice:bob declared 3:1,
    # equal-cost requests, 40 each interleaved: while both are
    # backlogged alice must take ~3/4 of the dequeues, and bob's gap
    # between consecutive dequeues stays small (the WFQ virtual clock
    # serves it every ~4th pop; 6 is a generous determinism-safe band).
    table = TenantTable({"alice": {"weight": 3.0},
                         "bob": {"weight": 1.0}}, now=0.0)
    q = AdmissionQueue(max_depth=80, qos=table)
    for i in range(40):
        q.admit(mk(2 * i, "alice"))
        q.admit(mk(2 * i + 1, "bob"))
    order = [q.pop(timeout=0.1).tenant for _ in range(80)]
    head = order[:40]
    report["share_head"] = {"alice": head.count("alice"),
                            "bob": head.count("bob")}
    if not 27 <= head.count("alice") <= 33:
        fail("queue.wfq_share",
             f"with weights 3:1 alice took {head.count('alice')}/40 "
             f"dequeues while both tenants were backlogged (expected "
             f"~30)",
             "check TenantTable.charge / pick virtual-time arithmetic")
    bob_gaps = [j - i for i, j in zip(
        [i for i, t in enumerate(head) if t == "bob"][:-1],
        [i for i, t in enumerate(head) if t == "bob"][1:])]
    report["bob_max_gap"] = max(bob_gaps, default=None)
    if bob_gaps and max(bob_gaps) > 6:
        fail("queue.wfq_starvation",
             f"backlogged tenant bob waited {max(bob_gaps)} dequeues "
             f"between services (weights 3:1 bound ~4)",
             "check the vfloor clamp — idle credit must not bank")
    # Work conservation across the tail: once alice drains, every
    # remaining pop is bob's, immediately — 80 admitted, 80 popped.
    if order.count("alice") != 40 or order.count("bob") != 40:
        fail("queue.wfq_work_conserving",
             f"80 admitted but popped {len([t for t in order if t])} "
             f"({order.count('alice')} alice / {order.count('bob')} "
             f"bob) — WFQ idled or dropped with work queued",
             "pick() must only rank tenants that HAVE queued work")

    # (b) Cost-weighted fairness: equal weights, one tenant submitting
    # 8x-cost buckets — fair in WORK means the small-bucket tenant gets
    # ~8 dequeues per big one.
    table2 = TenantTable({"fine": {"weight": 1.0},
                          "coarse": {"weight": 1.0}}, now=0.0)
    q2 = AdmissionQueue(max_depth=40, qos=table2)
    for i in range(30):
        q2.admit(mk(100 + i, "fine"))
    for i in range(6):
        q2.admit(mk(200 + i, "coarse", bucket=big))
    head2 = [q2.pop(timeout=0.1).tenant for _ in range(18)]
    report["cost_head"] = {"fine": head2.count("fine"),
                           "coarse": head2.count("coarse")}
    if head2.count("fine") < 14:
        fail("queue.wfq_cost",
             f"equal-weight tenants, 8x cost ratio: the small-bucket "
             f"tenant got only {head2.count('fine')}/18 dequeues — "
             f"fairness is counting requests, not work",
             "charge admission_cost(bucket), not 1, per dequeue")

    # (c) Single-live-tenant degeneration: with one tenant queued the
    # pick must be plain FIFO head regardless of its virtual clock
    # (work-conserving; also the tenancy-off byte-compat shape).
    q3 = AdmissionQueue(max_depth=8, qos=table)  # alice vtime is huge
    for i in range(5):
        q3.admit(mk(300 + i, "alice"))
    solo = [q3.pop(timeout=0.1).id for _ in range(5)]
    report["solo_fifo"] = solo
    if solo != [f"q-{300 + i}" for i in range(5)]:
        fail("queue.wfq_solo",
             f"single live tenant dequeued out of FIFO order: {solo}",
             "_select must return index 0 when policy cannot differ")

    # (d) EDF ordering: earliest absolute deadline first, deadline-less
    # last, ties FIFO — across the whole queue when no table is live.
    q4 = AdmissionQueue(max_depth=8, ordering="edf")
    q4.admit(mk(400, "default", deadline=30.0))
    q4.admit(mk(401, "default", deadline=10.0))
    q4.admit(mk(402, "default"))
    q4.admit(mk(403, "default", deadline=20.0))
    edf = [q4.pop(timeout=0.1).id for _ in range(4)]
    report["edf_order"] = edf
    if edf != ["q-401", "q-403", "q-400", "q-402"]:
        fail("queue.edf",
             f"EDF dequeue order {edf} != deadline order "
             f"['q-401', 'q-403', 'q-400', 'q-402']",
             "check _select's deadline key (None sorts last, ties FIFO)")

    # (e) Budget-leak audit at the queue tier: a rejection for ANY
    # earlier reason must not consume a rate token (token taken LAST).
    table5 = TenantTable({"carol": {"rate": 1.0, "burst": 2.0}}, now=0.0)
    q5 = AdmissionQueue(max_depth=1, qos=table5)
    q5.admit(mk(500, "filler"))
    try:
        q5.admit(mk(501, "carol"))
        fail("queue.token_leak", "expected QUEUE_FULL, got admission",
             "max_depth=1 with one queued request must reject")
    except AdmissionError as e:
        report["leak_reason"] = e.reason.value
        tokens = table5.snapshot(now=0.0)["carol"]["tokens"]
        report["carol_tokens"] = tokens
        if e.reason.value != "queue_full" or tokens != 2.0:
            fail("queue.token_leak",
                 f"rejection ({e.reason.value}) left carol with "
                 f"{tokens} tokens (burst 2.0) — a rejection consumed "
                 f"rate budget",
                 "consume the token strictly after every other "
                 "admission rule has passed")
    return findings, report


# Fresh buckets, used nowhere else in the analysis suite: the compile
# contract needs COLD entries (a warm cache would mask a leak), and the
# seeded fixture must be guaranteed at least one fresh trace to detect.
_QOS_BUCKET = ((48, 32, "float32"),)
_QOS_LEAK_BUCKET = ((40, 24, "float32"),)
# Exact fit, strictly smaller, wide (the service transposes) — three
# distinct request shapes per tenant into ONE bucket.
_QOS_SHAPES = ((48, 32), (40, 30), (24, 44))
_QOS_LEAK_SHAPES = ((40, 24), (36, 20), (18, 30))
_QOS_ENTRIES = ("solver._precondition_qr_jit",
                "solver._sweep_step_pallas_jit",
                "solver._finish_pallas_jit",
                "solver._nonfinite_probe_jit")


def run_compile_contract_case(seed_leak: bool = False) -> tuple:
    """QOS001 check 3: tenancy adds zero new jit entries. A
    tenants-declared, EDF-ordered service serves three distinct shapes
    per tenant (x2 repeats — the warm pass must be all cache hits) plus
    a mid-stream RATE_LIMITED rejection; every serving entry compiles
    once per bucket, exactly the single-tenant budget. ``seed_leak``
    under-declares every budget (problems=0) against a fresh bucket —
    the seeded failing fixture proving the guard fires. Returns
    (findings, report)."""
    import jax.numpy as jnp

    from ..config import SVDConfig
    from ..serve import AdmissionError, ServeConfig, SVDService
    from ..utils import matgen
    from .recompile_guard import RecompileGuard

    cfg = ServeConfig(
        buckets=_QOS_LEAK_BUCKET if seed_leak else _QOS_BUCKET,
        solver=SVDConfig(pair_solver="pallas"),
        max_queue_depth=16, queue_ordering="edf",
        tenants={"alice": {"weight": 3.0}, "bob": {"weight": 1.0},
                 "mallory": {"rate": 0.001, "burst": 1.0}},
        # Brownout pinned OFF: a sigma-only-degraded submit flips
        # static compute flags — a legitimate extra trace that would
        # false-positive the measurement (same as run_serve_sequence).
        brownout_sigma_only_at=2.0, brownout_shed_at=2.0)
    statuses, rejected = [], []
    with RecompileGuard() as guard:
        for entry in _QOS_ENTRIES:
            guard.expect(entry, problems=0 if seed_leak else 1)
        with SVDService(cfg) as svc:
            shapes = _QOS_LEAK_SHAPES if seed_leak else _QOS_SHAPES
            for rep in range(2):
                for i, (m, n) in enumerate(shapes):
                    for tenant in ("alice", "bob"):
                        a = matgen.random_dense(
                            m, n, seed=1000 * m + n, dtype=jnp.float32)
                        statuses.append(svc.submit(
                            a, tenant=tenant).result(timeout=600.0)
                            .status)
                # Rejection paths are host-side too: mallory's token
                # bucket is dry after its first admit and must shed
                # without adding a trace.
                try:
                    statuses.append(svc.submit(
                        matgen.random_dense(32, 24, seed=7,
                                            dtype=jnp.float32),
                        tenant="mallory").result(timeout=600.0).status)
                except AdmissionError as e:
                    rejected.append(e.reason.value)
        findings = guard.check()
        report = guard.report()
    report["statuses"] = [getattr(s, "name", None) for s in statuses]
    report["rejected"] = rejected
    report["seed_leak"] = bool(seed_leak)
    if seed_leak and not findings:
        findings.append(Finding(
            code="QOS001", where="serve.tenant_compile_contract",
            message=("seeded under-budget fixture produced zero "
                     "RETRACE001 findings — the detector itself is "
                     "broken (a tenant-keyed retrace would pass "
                     "unnoticed)"),
            suggestion="check RecompileGuard entry wiring and that the "
                       "fixture bucket is cold in this process"))
    if not seed_leak and any(
            s is None or s.name != "OK" for s in statuses):
        findings.append(Finding(
            code="QOS001", where="serve.tenant_compile_contract",
            message=(f"multi-tenant sequence produced non-OK statuses "
                     f"{report['statuses']} — the retrace measurement "
                     f"is not trustworthy on a failing solve"),
            suggestion="fix the serving solve path first"))
    return findings, report


def run_all() -> tuple:
    """The QOS001 pass body (analysis.__main__ 'qos'): all three
    checks, plus the seeded failing fixture proving check 3 can fire.
    Returns (findings, report)."""
    findings, label_report = run_tenant_label_case()
    wfq_findings, wfq_report = check_wfq_schedule()
    findings += wfq_findings
    compile_findings, compile_report = run_compile_contract_case()
    findings += compile_findings
    leak_findings, leak_report = run_compile_contract_case(seed_leak=True)
    # The fixture SHOULD produce RETRACE001 findings — only the
    # detector-broken meta-finding (QOS001) escalates.
    findings += [f for f in leak_findings if f.code == "QOS001"]
    leak_report["fired"] = any(
        f.code == "RETRACE001" for f in leak_findings)
    return findings, {"labels": label_report, "wfq": wfq_report,
                      "compile": compile_report,
                      "seeded_leak": leak_report}
