"""Jaxpr contract checks: traverse the closed jaxprs of the fused entry
points and fail on structural violations no source linter can see.

Three contracts (codes JAXPR001-003):

  * JAXPR001 — host callbacks present when telemetry is statically off.
    `obs.metrics.emit` inserts a `debug_callback` primitive into the
    trace; the telemetry-off program must contain NONE (the byte-identical
    HLO guarantee starts here). An ungated emit call site — one not behind
    the static ``telemetry`` flag — shows up as exactly this violation
    whenever the module enable flag happens to be on at trace time.
  * JAXPR002 — float-widening `convert_element_type` outside the declared
    mixed-precision boundaries. The allowed set is
    `config.MIXED_PRECISION_BOUNDARIES` plus anything no wider than the
    solve's declared accumulation dtype, promote_types(input, float32) —
    a silent f32 -> f64 upcast in an f32 solve (the classic Jacobi
    accuracy-story killer: 2x bytes, no MXU) is the target.
  * JAXPR003 — host-transfer primitives (callbacks, host-bound
    device_put) inside `while_loop`/`scan` bodies: a transfer per sweep
    serializes the fused loop on the host link.

The traversal recurses through every sub-jaxpr (pjit, while, scan, cond
branches, custom_*, pallas_call kernel bodies), so nothing hides inside
control flow.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

import numpy as np

from . import Finding
from .. import config as _config

# Primitives that call back into the host at runtime.
HOST_CALLBACK_PRIMS = frozenset({
    "debug_callback", "pure_callback", "io_callback", "outside_call",
    "host_callback_call", "debug_print",
})
# Primitives that move buffers between memories/hosts.
TRANSFER_PRIMS = frozenset({"device_put", "copy_to_host_async"})
# Primitives whose bodies execute repeatedly (per sweep / per round).
LOOP_PRIMS = frozenset({"while", "scan", "fori_loop"})


def _sub_jaxprs(value) -> Iterator:
    """Yield every (Closed)Jaxpr reachable from an eqn param value."""
    from jax.core import ClosedJaxpr, Jaxpr
    if isinstance(value, ClosedJaxpr):
        yield value.jaxpr
    elif isinstance(value, Jaxpr):
        yield value
    elif isinstance(value, (list, tuple)):
        for v in value:
            yield from _sub_jaxprs(v)
    elif isinstance(value, dict):
        for v in value.values():
            yield from _sub_jaxprs(v)


def iter_eqns(jaxpr, in_loop: bool = False) -> Iterator[Tuple[object, bool]]:
    """(eqn, inside_loop_body) over the whole jaxpr tree."""
    for eqn in jaxpr.eqns:
        yield eqn, in_loop
        sub_in_loop = in_loop or eqn.primitive.name in LOOP_PRIMS
        for p in eqn.params.values():
            for sub in _sub_jaxprs(p):
                yield from iter_eqns(sub, sub_in_loop)


def _float_width(dtype) -> Optional[int]:
    """Bit width for float dtypes (incl. the ml_dtypes extension floats,
    whose numpy ``kind`` is not 'f'); None for non-floats."""
    try:
        dt = np.dtype(dtype)
    except TypeError:
        return None
    if dt.kind == "f" or "float" in dt.name:
        return dt.itemsize * 8
    return None


def check_host_callbacks(closed_jaxpr, entry_name: str) -> List[Finding]:
    """JAXPR001: no host-callback primitive may appear anywhere in a
    telemetry-off trace. Callers must trace with the telemetry flag off."""
    findings = []
    for eqn, _ in iter_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name in HOST_CALLBACK_PRIMS:
            findings.append(Finding(
                code="JAXPR001", where=entry_name,
                message=(f"host callback primitive "
                         f"'{eqn.primitive.name}' in a telemetry-off "
                         f"trace — the zero-telemetry path must compile "
                         f"to callback-free HLO"),
                suggestion=("gate the obs.metrics.emit call site behind "
                            "the static telemetry flag threaded through "
                            "the jitted entry point")))
    return findings


def check_dtype_boundaries(closed_jaxpr, entry_name: str,
                           input_dtype) -> List[Finding]:
    """JAXPR002: every float-widening convert_element_type must stay within
    the declared mixed-precision boundaries."""
    import jax.numpy as jnp
    findings = []
    acc_width = _float_width(jnp.promote_types(input_dtype, jnp.float32))
    allowed = _config.MIXED_PRECISION_BOUNDARIES
    seen = set()
    for eqn, _ in iter_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        src = eqn.invars[0].aval.dtype
        dst = eqn.outvars[0].aval.dtype
        sw, dw = _float_width(src), _float_width(dst)
        if sw is None or dw is None or dw <= sw:
            continue  # not a float-to-float widening
        pair = (str(src), str(dst))
        if pair in allowed or (acc_width is not None and dw <= acc_width):
            continue
        if pair in seen:
            continue
        seen.add(pair)
        findings.append(Finding(
            code="JAXPR002", where=entry_name,
            message=(f"undeclared float upcast {pair[0]} -> {pair[1]} "
                     f"(declared accumulation width for a {input_dtype} "
                     f"solve is {acc_width} bits)"),
            suggestion=("keep arithmetic at the working dtype, or declare "
                        "the boundary in "
                        "config.MIXED_PRECISION_BOUNDARIES")))
    return findings


def check_transfers_in_loops(closed_jaxpr, entry_name: str) -> List[Finding]:
    """JAXPR003: no transfer/callback primitive inside a loop body."""
    findings = []
    for eqn, in_loop in iter_eqns(closed_jaxpr.jaxpr):
        if not in_loop:
            continue
        name = eqn.primitive.name
        if name in TRANSFER_PRIMS:
            findings.append(Finding(
                code="JAXPR003", where=entry_name,
                message=(f"transfer primitive '{name}' inside a "
                         f"while_loop/scan body — a host/device hop per "
                         f"sweep serializes the fused loop"),
                suggestion=("hoist the transfer out of the loop or keep "
                            "the value resident on device")))
    return findings


def check_probe(probe, *, telemetry_off: bool = True) -> List[Finding]:
    """Run every jaxpr pass on one entry probe (telemetry forced off
    unless the probe has no telemetry flag)."""
    if telemetry_off and probe.telemetry_key:
        probe = probe.with_kwargs(**{probe.telemetry_key: False})
    closed = probe.closed_jaxpr()
    findings = check_host_callbacks(closed, probe.name)
    findings += check_dtype_boundaries(closed, probe.name, probe.input_dtype)
    findings += check_transfers_in_loops(closed, probe.name)
    return findings


def check_default_entries(include_mesh: bool = True) -> List[Finding]:
    """The pass the CLI and the tier-1 fail-fast hook run: every declared
    entry probe, telemetry statically off."""
    from . import entries
    findings: List[Finding] = []
    for probe in entries.all_probes(include_mesh=include_mesh):
        findings += check_probe(probe)
    return findings
