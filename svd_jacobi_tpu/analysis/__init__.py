"""graftcheck — static analysis + sanitizers for the fused Jacobi hot paths.

The reference CUDA/MPI code could lean on compiler warnings and
`cuda-memcheck`; a JAX port has neither, and its core invariants live in
artifacts no text linter sees — the traced jaxpr and the lowered StableHLO.
This package checks the REAL compiled artifacts of the production entry
points (resolved through `solver._plan_entry` / `parallel.sharded._plan_entry`,
so the probes are exactly the programs `svd()` dispatches), plus the source
properties that decide whether those artifacts stay sane:

  * `jaxpr_checks`  — traverses the closed jaxprs of every public entry
    point: no host callbacks when telemetry is statically off, no float
    upcasts beyond the declared mixed-precision boundaries
    (`config.MIXED_PRECISION_BOUNDARIES`), no host-transfer primitives
    inside `while_loop`/`scan` bodies.
  * `hlo_checks`    — lowers/compiles the hot paths: the sharded round
    loop's collective budget (`config.COLLECTIVE_BUDGET` — exact
    `collective_permute`/`all_reduce` counts, zero `all_gather`), buffer
    donation surviving to input-output aliasing, and the telemetry-off
    HLO-equivalence guarantee (generalized from tests/test_obs.py).
  * `ast_lint`      — custom AST rules with GRAFT0xx codes: host
    materialization of traced values (GRAFT001), Python control flow on
    traced booleans (GRAFT002), `jnp` computation at import time
    (GRAFT003), jit cache-key hygiene (GRAFT004), and named-scope coverage
    of the PROFILE.md hot regions (GRAFT005, `config.HOT_SCOPES`).
  * `recompile_guard` — hooks JAX's compilation monitoring
    (`/jax/core/compile/backend_compile_duration`) plus per-entry jit
    cache sizes, and fails when an entry point retraces beyond its
    declared budget (`config.RETRACE_BUDGETS`) across a multi-size solve
    sequence — the Brent-Luk schedule leaking into a jit key is exactly
    this failure.
  * `sanitize`      — the runtime-sanitizer context (jax_debug_nans,
    jax_debug_infs, jax_transfer_guard) behind the `-m sanitized` pytest
    lane and the CLI's `--sanitized` flag.
  * `tune_checks`   — the autotuner contract (TUNE001): shipped tuning
    tables pass schema + content-hash validation, every declared serve
    bucket resolves through a measured (non-generic) table row, and
    table-resolved serving configs keep the once-per-bucket compile
    contract (reusing `recompile_guard` over a resolved-config serve
    sequence).
  * `route_checks`  — the federated-router contract (ROUTE001):
    consistent-hash routing is a pure function of (ring, bucket, input
    digest) with the minimal-disruption property, byte-identical
    resubmits map to the replica owning the cached result, and a
    replica-death rescue keeps the once-per-bucket compile contract on
    the receiving replica (a live two-replica kill-and-rescue drill
    under `recompile_guard`).
  * `grad_checks`   — the differentiable-solver contract (GRAD001):
    `jax.grad` traces through `solver.svd`/`svd_topk`/`svd_tall` contain
    the package's own sweep machinery (no silent fallback to
    `jnp.linalg.svd`'s rule at the full input shape), no host callbacks
    in the forward/backward trace, and every jitted gradient entry
    (`grad.rules.jit_entries`) carries a retrace budget.
  * `concurrency`   — graftlock (CONC001-003): the threaded serving
    stack's lock discipline. A declared lock inventory + partial order
    in `config.LOCK_ORDER` (router -> service/fleet -> queue/journal ->
    cache/breaker -> obs), an AST lint for order inversions across call
    boundaries, guarded-by races, and blocking calls under hot locks
    (CONC001); an opt-in runtime lock-graph sanitizer whose acquisition
    graph must stay acyclic under the chaos soaks (CONC002); and
    condition-variable discipline — predicate-looped, bounded waits,
    notify under the owning lock (CONC003). `# graftlock: ok(reason)`
    pragmas, reason mandatory.
  * `aot_checks`    — the entry-registry contract (AOT001):
    `config.RETRACE_BUDGETS` and the serving entry registry
    (`serve.registry.jit_entries`) enumerate EXACTLY the same entry
    set, and every jit the registry's AOT warmup plan can dispatch is
    budgeted — a new jit entry cannot ship unbudgeted, a stale budget
    cannot linger undeclared.

`python -m svd_jacobi_tpu.analysis` runs every pass and appends one
schema-versioned "analysis" record to the run manifest (`obs.manifest`);
tests/conftest.py runs the cheap passes (AST lint + jaxpr + the static
CONC lock-discipline rules) before every tier-1 pytest session so
contract violations fail fast.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional


@dataclasses.dataclass(frozen=True)
class Finding:
    """One contract violation, from any pass.

    ``code`` namespaces the rule: GRAFT0xx (AST lint), JAXPR0xx,
    HLO0xx, RETRACE0xx. ``where`` is "path:line" for source findings and
    the probe entry name for artifact findings.
    """

    code: str
    where: str
    message: str
    suggestion: str = ""

    def render(self) -> str:
        s = f"{self.where}: {self.code} {self.message}"
        if self.suggestion:
            s += f" [fix: {self.suggestion}]"
        return s

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def render_findings(findings: List[Finding], header: Optional[str] = None) -> str:
    lines = [header] if header else []
    lines += [f.render() for f in findings]
    return "\n".join(lines)


__all__ = ["Finding", "render_findings"]
