"""Probe registry: the entry points the artifact passes check.

Each `EntryProbe` wraps one fused jitted entry point with concrete example
arguments, resolved through the SAME planning helpers production uses
(`solver._plan_entry`, `parallel.sharded._plan_entry`) — so a probe is
byte-for-byte the program `svd()` / `sharded.svd()` would dispatch for that
(input, config), and the contract checks cannot drift from reality the way
hand-rebuilt call signatures would.

Probes are tiny (toy shapes) because the contracts under test are
STRUCTURAL — which primitives/collectives appear, where dtypes widen — and
structure is shape-independent here: the same trace code paths run at
32 columns and at 32768 (the jit keys differ only in shape).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..config import SVDConfig


@dataclasses.dataclass
class EntryProbe:
    """One checkable entry point: ``fn(*args, **kwargs)`` is the real call.

    ``telemetry_key``: name of the static kwarg gating `obs.metrics`
    emission (every fused entry has one); None for entries without the
    flag. ``entry_id``: the `config.RETRACE_BUDGETS` key of the underlying
    jit object, for compile-count attribution.
    """

    name: str
    fn: Any
    args: tuple
    kwargs: Dict[str, Any]
    entry_id: str = ""
    telemetry_key: Optional[str] = "telemetry"

    def with_kwargs(self, **over) -> "EntryProbe":
        return dataclasses.replace(self, kwargs={**self.kwargs, **over})

    def lower(self):
        return self.fn.lower(*self.args, **self.kwargs)

    def closed_jaxpr(self):
        """The probe's closed jaxpr. Wrapping the jit call under
        `make_jaxpr` yields one pjit eqn whose params carry the full inner
        jaxpr — the checkers recurse through it."""
        kwargs = self.kwargs
        return jax.make_jaxpr(lambda *xs: self.fn(*xs, **kwargs))(*self.args)

    @property
    def input_dtype(self):
        return self.args[0].dtype


def _single_probe(name: str, a, config: SVDConfig, *, compute_u=True,
                  compute_v=True, full_matrices=False) -> EntryProbe:
    from .. import solver
    entry, fn, a_in, kwargs = solver._plan_entry(
        a, config, compute_u=compute_u, compute_v=compute_v,
        full_matrices=full_matrices)
    entry_id = {"padded": "solver._svd_padded",
                "pallas": ("solver._svd_pallas_donated" if config.donate_input
                           else "solver._svd_pallas"),
                "block_rotation": ("solver._svd_block_rotation_donated"
                                   if config.donate_input
                                   else "solver._svd_block_rotation"),
                "resident": ("solver._svd_resident_donated"
                             if config.donate_input
                             else "solver._svd_resident")}[entry]
    return EntryProbe(name=name, fn=fn, args=(a_in,), kwargs=kwargs,
                      entry_id=entry_id)


def _batched_probe(name: str, a, config: SVDConfig, *, compute_u=True,
                   compute_v=True) -> EntryProbe:
    from .. import solver
    entry, fn, a_in, kwargs = solver._plan_entry_batched(
        a, config, compute_u=compute_u, compute_v=compute_v)
    entry_id = {"pallas_batched": "solver._svd_pallas_batched",
                "block_rotation_batched": "solver._svd_block_rotation_batched",
                "resident_batched": "solver._svd_resident_batched",
                "padded_batched": "solver._svd_padded_batched"}[entry]
    return EntryProbe(name=name, fn=fn, args=(a_in,), kwargs=kwargs,
                      entry_id=entry_id, telemetry_key=None)


def single_device_probes(include_f64: Optional[bool] = None) -> List[EntryProbe]:
    """Probes for every single-device fused entry/regime. ``include_f64``
    defaults to whether x64 is enabled (the f64 qr-svd path needs it)."""
    if include_f64 is None:
        include_f64 = bool(jax.config.jax_enable_x64)
    a32 = jnp.zeros((48, 32), jnp.float32)
    probes = [
        # The production kernel path (QR-preconditioned; Pallas interpret
        # mode on CPU backends — same trace structure as the compiled
        # kernels modulo the pallas_call bodies).
        _single_probe("pallas", a32, SVDConfig(pair_solver="pallas")),
        # The north-star mixed regime: bf16 bulk + f32 reconstitute+polish
        # — the path with the most dtype boundaries to get wrong.
        _single_probe("pallas_mixed", a32,
                      SVDConfig(pair_solver="pallas", mixed_bulk=True)),
        # Buffer-donating twin (checked for input-output aliasing).
        _single_probe("pallas_donated", a32,
                      SVDConfig(pair_solver="pallas", donate_input=True)),
        # Sigma-only fast path (gram-eigh, abs criterion).
        _single_probe("padded_novec", a32, SVDConfig(pair_solver="gram-eigh"),
                      compute_u=False, compute_v=False),
        # XLA block-solver path (hybrid: bulk + polish phase loops).
        _single_probe("padded_hybrid", a32, SVDConfig(pair_solver="hybrid")),
        # The batched (coalesced-dispatch) fused entry: 3 matrices stacked
        # along the pair axis with the block-diagonal tournament. Its
        # collective budget is declared ZERO everywhere
        # (config.COLLECTIVE_BUDGET["pallas_batched"]) — pure data layout
        # must introduce no collectives. No telemetry flag (the batched
        # lane emits no in-graph events).
        _batched_probe("pallas_batched", jnp.zeros((3, 48, 32), jnp.float32),
                       SVDConfig(pair_solver="pallas")),
        # The MXU-native blocked-rotation lane (eigh-accumulated bulk
        # rounds + kernel polish): single-device — its collective budget
        # is declared ZERO (config.COLLECTIVE_BUDGET
        # ["pallas_block_rotation"]).
        _single_probe("pallas_block_rotation", a32,
                      SVDConfig(pair_solver="block_rotation")),
        # The VMEM-resident grouped-round lane (carried-Gram factor
        # solves + one fused panel visit per R rounds): single-device —
        # its collective budget is declared ZERO
        # (config.COLLECTIVE_BUDGET["pallas_resident"]).
        _single_probe("pallas_resident", a32,
                      SVDConfig(pair_solver="resident")),
    ]
    probes += sketch_probes()
    if include_f64:
        a64 = jnp.zeros((48, 32), jnp.float64)
        probes.append(_single_probe("padded_f64_qr", a64, SVDConfig()))
    return probes


def sketch_probes() -> List[EntryProbe]:
    """Probes for the top-k/tall lane stage jits (ops/sketch.py wrapped
    by solver): the randomized range finder + projection and the blocked
    TSQR. The explicit small ``chunk`` forces the CHUNKED tree (the
    structure under contract — zero collectives, no host callbacks, no
    upcasts) even at the probe's toy shape. No telemetry flag (the
    sketch stages emit no in-graph events)."""
    from .. import solver
    a_tall = jnp.zeros((256, 24), jnp.float32)   # m >= 8n: the tall class
    return [
        EntryProbe(name="sketch_project", fn=solver._sketch_project_jit,
                   args=(a_tall,),
                   kwargs=dict(l=8, power_iters=1, chunk=64, seed=0),
                   entry_id="solver._sketch_project_jit",
                   telemetry_key=None),
        EntryProbe(name="tsqr_tall", fn=solver._tsqr_jit, args=(a_tall,),
                   kwargs=dict(chunk=64),
                   entry_id="solver._tsqr_jit", telemetry_key=None),
    ]


def mesh_probes(mesh=None) -> List[EntryProbe]:
    """Probes for the sharded entry point. Names here key
    `config.COLLECTIVE_BUDGET`; geometry comes from the production planner
    so the even-b kernel fix-up and per-device pair slots are included.
    Returns [] when fewer than 2 devices are attached (the CLI and the
    tests provide an 8-device virtual CPU mesh)."""
    from ..parallel import sharded

    if mesh is None:
        if len(jax.devices()) < 2:
            return []
        mesh = sharded.make_mesh()
    n = 96
    a = jnp.zeros((n, n), jnp.float32)

    def probe(name, config, **solve_kw):
        kwargs = sharded._plan_entry(a, mesh, config, **solve_kw)
        return EntryProbe(name=name, fn=sharded._svd_sharded_jit,
                          args=(a,), kwargs=kwargs,
                          entry_id="sharded._svd_sharded_jit")

    a_tall = jnp.zeros((8 * n, n), jnp.float32)

    def probe_tall(name, config, **solve_kw):
        kwargs = sharded._plan_entry(a_tall, mesh, config, **solve_kw)
        return EntryProbe(name=name, fn=sharded._svd_sharded_jit,
                          args=(a_tall,), kwargs=kwargs,
                          entry_id="sharded._svd_sharded_jit")

    return [
        probe("sharded_pallas", SVDConfig(pair_solver="pallas")),
        probe("sharded_pallas_novec", SVDConfig(pair_solver="pallas"),
              compute_u=False, compute_v=False),
        probe("sharded_hybrid", SVDConfig(pair_solver="hybrid")),
        # Tall (m >= 8n) mesh solve: the chunked-TSQR preconditioner
        # (engaged by the aspect threshold — m/8-scaled chunks, so the
        # tree is real even at probe scale) runs under GSPMD OUTSIDE the
        # shard_map sweep loop, so its budget must equal the square
        # entry's — a collective difference here means the QR tree
        # leaked into the fused loop.
        probe_tall("sharded_pallas_tall", SVDConfig(pair_solver="pallas")),
    ]


def all_probes(include_mesh: bool = True) -> List[EntryProbe]:
    probes = single_device_probes()
    if include_mesh:
        probes += mesh_probes()
    return probes
