"""`python -m svd_jacobi_tpu.analysis` — run every graftcheck pass.

Runs on a deterministic 8-virtual-device CPU backend (mirroring
tests/conftest.py) regardless of attached hardware: the contracts under
check are trace/lowering-structural, and an analysis run must never dial
an accelerator. Exit 0 iff every pass is clean; one schema-versioned
"analysis" record is appended to ``<report-dir>/manifest.jsonl``
(render with ``scripts/telemetry_summary.py``).

    python -m svd_jacobi_tpu.analysis                    # all passes
    python -m svd_jacobi_tpu.analysis --passes ast,jaxpr # fail-fast subset
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

PASS_NAMES = ("ast", "jaxpr", "hlo", "recompile", "serve", "tune", "aot",
              "obs", "route", "grad", "perf", "conc", "net", "qos")


def _parse_args(argv):
    p = argparse.ArgumentParser(
        prog="svd-graftcheck",
        description="Static analysis + sanitizer contract checks for the "
                    "fused Jacobi hot paths.")
    p.add_argument("--passes", default=",".join(PASS_NAMES),
                   help=f"comma-separated subset of {PASS_NAMES}")
    p.add_argument("--report-dir", default="reports",
                   help="manifest directory (one 'analysis' JSONL record "
                        "appended per run); 'off' disables the record")
    p.add_argument("--json", action="store_true",
                   help="print the full report record to stdout as JSON")
    return p.parse_args(argv)


def _setup_backend() -> None:
    """Deterministic virtual-CPU backend, BEFORE anything touches XLA."""
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
    import jax
    jax.config.update("jax_platforms", "cpu")
    # x64 on so the f64 qr-svd entry is probed too (mirrors tests).
    jax.config.update("jax_enable_x64", True)


def main(argv=None) -> int:
    args = _parse_args(sys.argv[1:] if argv is None else argv)
    selected = [s.strip() for s in args.passes.split(",") if s.strip()]
    unknown = sorted(set(selected) - set(PASS_NAMES))
    if unknown:
        print(f"unknown passes: {unknown} (known: {list(PASS_NAMES)})",
              file=sys.stderr)
        return 2
    _setup_backend()

    from . import render_findings
    from .. import obs

    def run_pass(name):
        from . import ast_lint, hlo_checks, jaxpr_checks, recompile_guard
        if name == "ast":
            return ast_lint.lint_package(), None
        if name == "jaxpr":
            return jaxpr_checks.check_default_entries(), None
        if name == "hlo":
            return hlo_checks.check_default_entries(), None
        if name == "serve":
            # The serving layer's compile-cache contract: the bucket set
            # compiles once per bucket, never per request (RETRACE001).
            findings, report = recompile_guard.run_serve_sequence()
            return findings, report
        if name == "tune":
            # The autotuner contract (TUNE001): shipped tables validate,
            # the declared serve buckets resolve via measured rows, and
            # table-resolved configs introduce no new retraces.
            from . import tune_checks
            findings, report = tune_checks.run_all()
            return findings, report
        if name == "aot":
            # The entry-registry contract (AOT001): RETRACE_BUDGETS and
            # serve.registry.jit_entries agree exactly, and every jit
            # the registry's AOT plan dispatches is budgeted.
            from . import aot_checks
            findings, report = aot_checks.run_all()
            return findings, report
        if name == "route":
            # The federated-router contract (ROUTE001): consistent-hash
            # routing is deterministic given the ring + digest, and a
            # replica-death rescue keeps the once-per-bucket compile
            # contract on the receiving replica under RecompileGuard.
            from . import route_checks
            findings, report = route_checks.run_all()
            return findings, report
        if name == "obs":
            # The serving flight recorder's free-when-off contract
            # (OBS002): metrics-off HLO byte-identical, zero registry
            # mutations on the metrics-off hot path, idle-overhead
            # budget.
            from . import obs_checks
            findings, report = obs_checks.run_all()
            return findings, report
        if name == "perf":
            # The roofline observatory contract (PERF001): the analytic
            # cost model agrees with compiled.cost_analysis() on every
            # registry entry, the SCOPE_PHASES join covers HOT_SCOPES
            # exactly, and the perf-off hot path stays byte-identical.
            # Plus the static VMEM-budget check (VMEM001): every shipped
            # Pallas-lane geometry (serve buckets + the tuning table's
            # TPU kernel rows) fits its per-grid-step footprint model,
            # and the seeded over-budget fixture fires.
            from . import perf_checks
            findings, report = perf_checks.run_all()
            return findings, report
        if name == "conc":
            # The graftlock contract (CONC001-003): the full static
            # lock-discipline lint (order inversions, guarded-by,
            # blocking-under-lock, CV discipline, inventory
            # completeness) plus a chaos soak under the CONC002
            # instrumented locks whose acquisition graph must be
            # acyclic.
            from . import concurrency
            findings, report = concurrency.run_all()
            return findings, report
        if name == "net":
            # ROUTE001's wire-transport extension: a retried submit
            # after a lost ACK admits exactly once on a live HTTP
            # replica (idempotency keys + journal-proven exactly-once).
            from . import route_checks
            findings, report = route_checks.run_net()
            return findings, report
        if name == "qos":
            # The multi-tenant front-door contract (QOS001): every
            # per-request serving metric is tenant-labeled (live and in
            # the manifest reconstruction, SLO twins agreeing), WFQ
            # dequeue is fair/work-conserving/starvation-free on a
            # seeded schedule, and tenancy adds ZERO new jit entries
            # (host-side identity never reaches a trace key).
            from . import qos_checks
            findings, report = qos_checks.run_all()
            return findings, report
        if name == "grad":
            # The differentiable-solver contract (GRAD001): grad traces
            # run our sweep machinery (no silent jnp.linalg.svd
            # fallback), stay host-callback-free, and every grad jit is
            # budgeted.
            from . import grad_checks
            findings, report = grad_checks.run_all()
            return findings, report
        findings, report = recompile_guard.run_default_sequence()
        return findings, report

    passes = []
    ok = True
    for name in selected:
        t0 = time.perf_counter()
        findings, extra = run_pass(name)
        dt = time.perf_counter() - t0
        entry = {"name": name, "ok": not findings,
                 "findings": [f.as_dict() for f in findings],
                 "time_s": dt}
        if extra is not None:
            entry["detail"] = extra
        passes.append(entry)
        status = "ok" if not findings else f"{len(findings)} finding(s)"
        print(f"pass {name:<9} {status}  ({dt:.2f} s)", file=sys.stderr)
        if findings:
            ok = False
            print(render_findings(findings), file=sys.stderr)

    record = obs.manifest.build_analysis(
        passes=passes, argv=list(sys.argv[1:] if argv is None else argv))
    if args.report_dir != "off":
        path = obs.manifest.append(
            Path(args.report_dir) / "manifest.jsonl", record)
        print(f"manifest: {path}", file=sys.stderr)
    if args.json:
        print(json.dumps(record, sort_keys=True))
    else:
        print(json.dumps({"ok": ok,
                          "findings_total": record["findings_total"],
                          "passes": {p["name"]: p["ok"] for p in passes}}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
