"""The lock inventory: every `threading.Lock/RLock/Condition`
construction site in the package, resolved against the declared tiers
in `config.LOCK_ORDER` (rule CONC001, the completeness half).

The scan is pure AST — no imports of the scanned modules — so it runs
in the conftest fail-fast hook before jax is touched. Completeness is
checked BOTH ways, like the AOT001 two-way ledger: a construction site
with no declared tier fails (a future lock cannot be added without
deciding where it sits in the order), and a declared row whose site no
longer exists fails too (the inventory cannot go stale silently).
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from .. import Finding

_LOCK_KINDS = ("Lock", "RLock", "Condition")


@dataclasses.dataclass(frozen=True)
class LockSite:
    """One `threading.<kind>()` construction site."""

    rel: str          # module path relative to the package root
    lineno: int
    qualname: str     # "Class.attr" | module-level name | "func.local"
    kind: str         # "Lock" | "RLock" | "Condition"


def package_root() -> Path:
    return Path(__file__).resolve().parent.parent.parent


def _lock_kind(call: ast.Call) -> Optional[str]:
    """"Lock"/"RLock"/"Condition" when `call` constructs a threading
    primitive (`threading.X(...)` or a bare `X(...)` from-import)."""
    fn = call.func
    if (isinstance(fn, ast.Attribute) and fn.attr in _LOCK_KINDS
            and isinstance(fn.value, ast.Name)
            and fn.value.id == "threading"):
        return fn.attr
    if isinstance(fn, ast.Name) and fn.id in _LOCK_KINDS:
        return fn.id
    return None


def _assign_qualname(node: ast.AST, scopes: List[ast.AST]) -> str:
    """The construction site's qualified name from its assignment
    context: `self.X = ...` inside class C -> "C.X"; a module-level
    `X = ...` -> "X"; a function-local `X = ...` -> "func.X"."""
    targets: List[ast.expr] = []
    if isinstance(node, ast.Assign):
        targets = list(node.targets)
    elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
        targets = [node.target]
    cls = next((s.name for s in reversed(scopes)
                if isinstance(s, ast.ClassDef)), None)
    fn = next((s.name for s in reversed(scopes)
               if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))),
              None)
    for t in targets:
        if (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                and t.value.id == "self" and cls is not None):
            return f"{cls}.{t.attr}"
        if isinstance(t, ast.Name):
            if fn is not None:
                return f"{fn}.{t.id}"
            return t.id
    # No named target (e.g. a lock passed straight into a call): fall
    # back to the enclosing scope so the row is still declarable.
    if fn is not None:
        return f"{fn}.<expr>"
    return "<module>.<expr>"


class _SiteVisitor(ast.NodeVisitor):
    def __init__(self, rel: str):
        self.rel = rel
        self.sites: List[LockSite] = []
        self._scopes: List[ast.AST] = []
        self._stmt: List[ast.stmt] = []

    def _walk_body(self, node):
        self._scopes.append(node)
        self.generic_visit(node)
        self._scopes.pop()

    visit_ClassDef = _walk_body
    visit_FunctionDef = _walk_body
    visit_AsyncFunctionDef = _walk_body

    def generic_visit(self, node):
        is_stmt = isinstance(node, ast.stmt)
        if is_stmt:
            self._stmt.append(node)
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        if is_stmt:
            self._stmt.pop()

    def visit_Call(self, node: ast.Call):
        kind = _lock_kind(node)
        if kind is not None:
            stmt = self._stmt[-1] if self._stmt else node
            self.sites.append(LockSite(
                rel=self.rel, lineno=node.lineno,
                qualname=_assign_qualname(stmt, self._scopes), kind=kind))
        self.generic_visit(node)


def scan_source(source: str, rel: str) -> List[LockSite]:
    tree = ast.parse(source, filename=rel)
    v = _SiteVisitor(rel)
    v.visit(tree)
    return v.sites


def scan_file(path, rel: str) -> List[LockSite]:
    return scan_source(Path(path).read_text(), rel)


def scan_package(root=None) -> List[LockSite]:
    root = Path(root) if root is not None else package_root()
    sites: List[LockSite] = []
    for path in sorted(root.rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        sites += scan_file(path, rel)
    return sites


def declared_order(order=None) -> Dict[Tuple[str, str], Tuple[str, str]]:
    """`config.LOCK_ORDER` flattened to
    (rel, qualname) -> (declared name, tier name)."""
    if order is None:
        from ... import config
        order = config.LOCK_ORDER
    return {(rel, qual): (name, tier)
            for name, (rel, qual, tier) in order.items()}


def site_names(sites=None, order=None) -> Dict[Tuple[str, int], str]:
    """(rel, construction lineno) -> declared lock name, for the CONC002
    sanitizer's frame-based name inference."""
    sites = scan_package() if sites is None else sites
    decl = declared_order(order)
    out: Dict[Tuple[str, int], str] = {}
    for s in sites:
        row = decl.get((s.rel, s.qualname))
        if row is not None:
            out[(s.rel, s.lineno)] = row[0]
    return out


def check_inventory(sites=None, order=None, *,
                    pragmas: Optional[Dict[str, Dict[int, str]]] = None
                    ) -> List[Finding]:
    """The two-way completeness check: every construction site declared,
    every declaration backed by a live site. ``pragmas`` maps rel ->
    {line: reason} (`static_lint._pragmas`) so a deliberate undeclared
    lock can be suppressed with a justification."""
    sites = scan_package() if sites is None else sites
    decl = declared_order(order)
    findings: List[Finding] = []
    seen: set = set()
    for s in sites:
        key = (s.rel, s.qualname)
        if key in decl:
            seen.add(key)
            continue
        file_pragmas = (pragmas or {}).get(s.rel, {})
        if file_pragmas.get(s.lineno) or file_pragmas.get(s.lineno - 1):
            continue
        findings.append(Finding(
            code="CONC001",
            where=f"{s.rel}:{s.lineno}",
            message=(f"threading.{s.kind} constructed at {s.qualname!r} "
                     f"has no declared tier in config.LOCK_ORDER — the "
                     f"lock inventory must cover every lock in the "
                     f"package"),
            suggestion=("add a config.LOCK_ORDER row "
                        f"('<name>': ({s.rel!r}, {s.qualname!r}, "
                        f"'<tier>')) placing it in the partial order, or "
                        "justify it per line with "
                        "`# graftlock: ok(reason)`")))
    for key, (name, tier) in sorted(decl.items()):
        if key not in seen:
            findings.append(Finding(
                code="CONC001",
                where=f"{key[0]}:0",
                message=(f"config.LOCK_ORDER declares {name!r} at "
                         f"({key[0]}, {key[1]}) but no such construction "
                         f"site exists — stale inventory row"),
                suggestion="update or remove the LOCK_ORDER row"))
    return findings
