"""graftlock — concurrency lock-discipline analysis for the threaded
serving stack (the 12th graftcheck pass family).

The reference parallelizes with MPI/OpenMP/CUDA; our reproduction's
analogous concurrency surface is ~20 `threading.Lock/RLock/Condition`
instances across `serve/` and `obs/` coordinating admission, batching,
lane eviction/rescue, journal recovery, and federation. Their
correctness rested on soak tests alone; graftlock makes the discipline
a checked contract, in the lockdep/ThreadSanitizer tradition:

* **CONC001** (`static_lint`, `inventory`) — the static lock-discipline
  lint against the declared inventory and partial order in
  `config.LOCK_ORDER` (router -> service/fleet -> queue/journal ->
  cache/breaker -> obs): nested acquisitions that invert the order
  (directly or across call boundaries, via the same conservative
  name-inference style as `ast_lint`), guarded-by inference (an
  attribute mutated under the class lock in one method and bare in
  another is a flagged data race), blocking calls — jit dispatch,
  `block_until_ready`, fsync, socket sends, `.result()`/`.join()` —
  while holding a router/service/fleet-tier lock, and
  inventory completeness (every lock construction site in the package
  must carry a declared tier, both ways).
* **CONC002** (`sanitizer`) — the opt-in runtime lock-graph sanitizer:
  `sanitizer.capture()` wraps every lock constructed inside it,
  records per-thread held-sets and acquisition-order edges into a
  process-global graph, and `find_cycle()` reports a potential
  deadlock with the stacks of both closing edges. Zero-cost when off:
  outside a capture the stdlib factories are untouched and the
  mutation counter proves it (the OBS002 discipline).
* **CONC003** (`static_lint`) — condition-variable discipline:
  `Condition.wait` must be predicate-looped and bounded (a timeout
  argument, so shutdown paths cannot hang), `notify`/`notify_all`
  under the owning lock. `serve/queue.py` is the conforming corpus.

Deliberate exceptions are suppressed per line with
`# graftlock: ok(reason)` — the reason is mandatory; an empty pragma is
itself a finding. Seeded violation fixtures live under
`tests/fixtures/conc_violations/` and `tests/test_concurrency.py`
proves every rule demonstrably fires.
"""

from __future__ import annotations

from typing import List, Tuple

from . import inventory, sanitizer, static_lint  # noqa: F401


def run_all() -> Tuple[List, dict]:
    """The `conc` pass of `python -m svd_jacobi_tpu.analysis`: the full
    static lint (CONC001 + CONC003 + inventory completeness) over the
    real package, then a small chaos soak — a 2-lane service with a
    mid-stream lane kill — under the CONC002 instrumented locks, whose
    final acquisition graph must be acyclic."""
    findings = static_lint.lint_package()
    soak_findings, report = sanitizer.run_soak_probe()
    return findings + soak_findings, report
