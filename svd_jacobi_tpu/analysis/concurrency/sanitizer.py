"""CONC002: the runtime lock-graph sanitizer.

`capture()` swaps the `threading.Lock/RLock/Condition` factories for
instrumenting ones. Every lock constructed inside the capture records,
per thread, the set of locks held at each acquisition; first-time
acquisition-order edges (held L, acquiring M) go into a process-global
`LockGraph` with the acquiring stack and the holder's acquisition site.
A cycle in that graph is a potential deadlock — two threads that
interleave at the wrong moment wedge forever — and is reported with
both sides' stacks, lockdep-style: the soak does not need to *hit* the
deadlock window, only to traverse both orders once.

Locks are named by their construction site, resolved through the
declared inventory (`config.LOCK_ORDER` via `inventory.site_names`) so
graph nodes carry the same names the static lint uses; foreign locks
(jax internals, stdlib) fall back to `file.py:lineno` keys and
participate in cycle detection all the same.

Zero-cost when off (the OBS002 discipline): outside a capture the
stdlib factories are untouched — `threading.Lock is` the original —
and `mutation_count()` stays flat, which `tests/test_concurrency.py`
asserts. Captures are process-global state: one at a time, tests and
`cli serve-demo --lock-sanitizer` only.
"""

from __future__ import annotations

import contextlib
import sys
import threading
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

from .. import Finding
from . import inventory

_REAL = {
    "Lock": threading.Lock,
    "RLock": threading.RLock,
    "Condition": threading.Condition,
}

_MUTATIONS = 0            # incremented on every instrumented-path op
_ACTIVE: Optional["LockGraph"] = None
_TLS = threading.local()  # .held: [(key, site)] per thread


def mutation_count() -> int:
    """Sanitizer-path operation count — the zero-cost-when-off guard:
    this must not move while no capture is active."""
    return _MUTATIONS


def _held() -> List[Tuple[str, str]]:
    held = getattr(_TLS, "held", None)
    if held is None:
        held = _TLS.held = []
    return held


def _site(depth: int) -> Tuple[str, int]:
    """(filename, lineno) of the lock construction site, ``depth``
    frames above the factory."""
    f = sys._getframe(depth)
    return f.f_code.co_filename, f.f_lineno


def _stack(skip: int = 2, limit: int = 8) -> List[str]:
    """Compact acquiring stack: frame-walk only, no linecache I/O."""
    out: List[str] = []
    f: Any = sys._getframe(skip)
    while f is not None and len(out) < limit:
        code = f.f_code
        if "analysis/concurrency" not in code.co_filename.replace("\\", "/"):
            out.append(f"{Path(code.co_filename).name}:{f.f_lineno} "
                       f"in {code.co_name}")
        f = f.f_back
    return out


class LockGraph:
    """The process-global acquisition-order graph of one capture."""

    def __init__(self, names: Optional[Dict[Tuple[str, int], str]] = None):
        # Constructed BEFORE the factories are patched, so this is a
        # real threading.Lock even mid-capture.
        self._lock = threading.Lock()
        self._names = names or {}
        self._root = str(inventory.package_root())
        # (src, dst) -> {count, threads, src_site, dst_stack}
        self.edges: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self.nodes: set = set()
        self.acquisitions = 0

    def key_for(self, filename: str, lineno: int) -> str:
        if filename.startswith(self._root):
            rel = Path(filename).as_posix()[len(self._root):].lstrip("/")
            return self._names.get((rel, lineno), f"{rel}:{lineno}")
        return f"{Path(filename).name}:{lineno}"

    def _on_acquire(self, key: str, blocking: bool = True) -> None:
        global _MUTATIONS
        held = _held()
        first = all(k != key for k, _ in held)
        site = _stack(skip=3, limit=1)
        site_s = site[0] if site else "?"
        if first and held:
            srcs = []
            seen = set()
            for k, s in held:
                if k != key and k not in seen:
                    seen.add(k)
                    srcs.append((k, s))
            with self._lock:
                _MUTATIONS += 1
                self.acquisitions += 1
                self.nodes.add(key)
                for src, src_site in srcs:
                    edge = self.edges.get((src, key))
                    if edge is None:
                        self.edges[(src, key)] = {
                            "count": 1,
                            "threads": {threading.current_thread().name},
                            "src_site": src_site,
                            "dst_stack": _stack(skip=3),
                        }
                    else:
                        edge["count"] += 1
                        edge["threads"].add(
                            threading.current_thread().name)
        else:
            with self._lock:
                _MUTATIONS += 1
                self.acquisitions += 1
                self.nodes.add(key)
        held.append((key, site_s))

    def _on_release(self, key: str) -> None:
        global _MUTATIONS
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == key:
                del held[i]
                break
        with self._lock:
            _MUTATIONS += 1

    def find_cycle(self) -> Optional[List[str]]:
        """A node sequence [a, b, ..., a] closing a cycle, or None."""
        adj: Dict[str, List[str]] = {}
        with self._lock:
            for (src, dst) in self.edges:
                adj.setdefault(src, []).append(dst)
        color: Dict[str, int] = {}  # 1 = on stack, 2 = done
        parent: Dict[str, str] = {}

        def dfs(node: str) -> Optional[List[str]]:
            color[node] = 1
            for nxt in adj.get(node, ()):
                if color.get(nxt) == 1:
                    cyc = [nxt, node]
                    cur = node
                    while cur != nxt:
                        cur = parent[cur]
                        cyc.append(cur)
                    return list(reversed(cyc))
                if nxt not in color:
                    parent[nxt] = node
                    hit = dfs(nxt)
                    if hit is not None:
                        return hit
            color[node] = 2
            return None

        for node in sorted(adj):
            if node not in color:
                hit = dfs(node)
                if hit is not None:
                    return hit
        return None

    def describe_cycle(self, cycle: List[str]) -> str:
        lines = [" -> ".join(cycle)]
        with self._lock:
            for a, b in zip(cycle, cycle[1:]):
                edge = self.edges.get((a, b))
                if edge is None:
                    continue
                lines.append(f"  {a} -> {b} (x{edge['count']} on "
                             f"{', '.join(sorted(edge['threads']))}); "
                             f"{a} taken at {edge['src_site']}; "
                             f"{b} taken via: "
                             + " | ".join(edge["dst_stack"][:4]))
        return "\n".join(lines)

    def summary(self) -> dict:
        with self._lock:
            return {
                "locks": sorted(self.nodes),
                "edge_count": len(self.edges),
                "acquisitions": self.acquisitions,
                "edges": sorted(f"{a} -> {b}" for (a, b) in self.edges),
            }


class _InstrumentedLock:
    """Wraps a real Lock/RLock; records acquire/release order into the
    capture's graph. Unknown attributes delegate to the inner lock, so
    `Condition`'s `_release_save`-family protocol reaches the real
    RLock directly (a waiting thread is blocked and records no edges,
    so the held-set staying intact across the wait is correct)."""

    __slots__ = ("_inner", "_graft_key", "_graph")

    def __init__(self, inner, key: str, graph: LockGraph):
        self._inner = inner
        self._graft_key = key
        self._graph = graph

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._graph._on_acquire(self._graft_key)
        return got

    def release(self) -> None:
        self._inner.release()
        self._graph._on_release(self._graft_key)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<graftlock {self._graft_key!r} wrapping {self._inner!r}>"

    def __getattr__(self, name: str):
        return getattr(self._inner, name)


@contextlib.contextmanager
def capture(names: Optional[Dict[Tuple[str, int], str]] = None):
    """Patch the threading lock factories; yield the `LockGraph` that
    every lock constructed inside the block reports into. One capture
    at a time, process-global."""
    global _ACTIVE
    if _ACTIVE is not None:
        raise RuntimeError("graftlock capture already active")
    if names is None:
        names = inventory.site_names()
    graph = LockGraph(names)
    _ACTIVE = graph

    def _make(kind: str, filename: str, lineno: int) -> _InstrumentedLock:
        key = graph.key_for(filename, lineno)
        return _InstrumentedLock(_REAL[kind](), key, graph)

    def _lock_factory():
        return _make("Lock", *_site(2))

    def _rlock_factory():
        return _make("RLock", *_site(2))

    def _condition_factory(lock=None):
        if lock is None:
            lock = _make("RLock", *_site(2))
        return _REAL["Condition"](lock)

    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = _condition_factory
    try:
        yield graph
    finally:
        threading.Lock = _REAL["Lock"]
        threading.RLock = _REAL["RLock"]
        threading.Condition = _REAL["Condition"]
        _ACTIVE = None


def run_soak_probe() -> Tuple[List[Finding], dict]:
    """The `conc` pass's dynamic half: a 2-lane service under the
    instrumented locks, a lane killed mid-stream so the
    eviction/rescue/probe protocol runs, every ticket terminal, and
    the final acquisition graph acyclic."""
    import jax.numpy as jnp  # deferred: the static half must not need jax

    from ...config import SVDConfig
    from ...resilience import chaos
    from ...serve import ServeConfig, SVDService
    from ...utils import matgen

    findings: List[Finding] = []
    with capture() as graph:
        cfg = ServeConfig(buckets=((16, 16, "float32"),),
                          solver=SVDConfig(block_size=4),
                          lanes=2, max_queue_depth=32)
        with SVDService(cfg) as svc:
            mats = [matgen.random_dense(12, 12, seed=70 + i,
                                        dtype=jnp.float32)
                    for i in range(6)]
            with chaos.kill_lane(0):
                tickets = [svc.submit(a) for a in mats]
                results = [t.result(timeout=600.0) for t in tickets]
    non_terminal = sum(1 for r in results if r is None)
    if non_terminal:
        findings.append(Finding(
            code="CONC002", where="analysis/concurrency/sanitizer.py:0",
            message=(f"soak probe: {non_terminal} tickets never became "
                     "terminal under the instrumented locks"),
            suggestion="run tests/test_concurrency.py chaos soak"))
    cycle = graph.find_cycle()
    if cycle is not None:
        findings.append(Finding(
            code="CONC002", where="analysis/concurrency/sanitizer.py:0",
            message=("lock acquisition graph has a cycle (potential "
                     "deadlock):\n" + graph.describe_cycle(cycle)),
            suggestion=("fix the inverted acquisition or declare the "
                        "order in config.LOCK_ORDER and restructure")))
    report = dict(graph.summary(), cycle=cycle,
                  statuses=sorted({str(getattr(r, "status", None))
                                   for r in results}))
    return findings, report
