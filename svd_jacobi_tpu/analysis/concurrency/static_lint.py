"""CONC001 + CONC003: the static half of graftlock.

The lint is pure AST, in the `ast_lint` tradition of conservative
name inference: it resolves lock expressions (`self._lock`,
`self.fleet._lock`, a module-level `_lock`, `self.journal.exclusive()`)
against the declared inventory in `config.LOCK_ORDER`, walks each
function with the currently-held lock set, and checks

* **lock order** — an acquisition of M while holding L is legal iff
  rank(tier(L)) < rank(tier(M)), or L is M and the lock is re-entrant
  (RLock/Condition). The check crosses call boundaries: per-function
  summaries of transitively-acquired locks are propagated to a fixpoint
  over the intra-package call graph, so `recover()` holding the journal
  and calling a method that takes the service lock is flagged at the
  call site.
* **blocking-under-lock** — no jit dispatch, `block_until_ready`,
  fsync, socket send, `.result()`, `.join()`, sleep, or condition wait
  while holding a router/service/fleet-tier lock (the worker-wedge
  class the PR 6 watchdog only catches after the fact). Also
  propagated transitively.
* **guarded-by** — an attribute assigned under the class's own
  declared lock in one method and bare in another is a flagged data
  race (``__init__``-family methods are exempt: pre-publication).
* **CONC003** — `Condition.wait` must sit in a predicate loop, carry a
  timeout, and hold the owning lock; `notify`/`notify_all` must hold
  the owning lock.

Unresolvable expressions and call targets are skipped, never guessed.
Deliberate exceptions: `# graftlock: ok(reason)` on the flagged line;
the reason is mandatory (an empty pragma is itself a finding).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path, PurePosixPath
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ... import config
from .. import Finding
from . import inventory

_PRAGMA_RE = re.compile(r"graftlock:\s*ok\(([^)]*)\)")

# Terminal attribute / function names that block (or dispatch work that
# blocks) — attr-name heuristics, same conservatism as ast_lint.
_BLOCKING_ATTRS = {
    "block_until_ready": "device sync (block_until_ready)",
    "effects_barrier": "device sync (effects_barrier)",
    "fsync": "fsync",
    "sendall": "socket send",
    "recv": "socket recv",
    "result": ".result() on a future/ticket",
    "join": ".join() on a thread",
    "sleep": "sleep",
    "urlopen": "network I/O",
    "wait": "wait on a condition/event",
    "_solve_base": "jit dispatch",
    "_solve_batched": "jit dispatch",
    "_solve_ladder": "jit dispatch",
}

# Lock tiers inside which blocking calls are forbidden (CONC001c): the
# hot serving locks whose holders stall admission/dispatch for everyone.
_SCOPED_TIERS = ("router", "service", "fleet")

# Attribute types the one-pass constructor inference cannot see
# (assigned from a constructor parameter, usually to avoid a circular
# import). (rel, Class, attr) -> (rel, Class).
_EXTRA_ATTR_TYPES: Dict[Tuple[str, str, str], Tuple[str, str]] = {
    ("serve/fleet.py", "Fleet", "service"): ("serve/service.py", "SVDService"),
    ("serve/fleet.py", "Lane", "service"): ("serve/service.py", "SVDService"),
    ("serve/router.py", "Replica", "service"): ("serve/service.py", "SVDService"),
}

# Callables that hand their caller a declared lock. Methods key as
# (rel, Class, method); module functions as (rel, None, func).
_RETURNS_LOCK: Dict[Tuple[str, Optional[str], str], Tuple[str, str]] = {
    ("serve/journal.py", "Journal", "exclusive"):
        ("serve/journal.py", "Journal._lock"),
    ("obs/manifest.py", None, "_append_lock"):
        ("obs/manifest.py", "_append_lock.lock"),
}

_INIT_METHODS = ("__init__", "__new__", "__post_init__")


def _pragmas(source: str) -> Dict[int, str]:
    """line -> pragma reason (possibly empty) for every
    `# graftlock: ok(reason)` comment in ``source``."""
    out: Dict[int, str] = {}
    try:
        toks = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in toks:
            if tok.type == tokenize.COMMENT:
                m = _PRAGMA_RE.search(tok.string)
                if m is not None:
                    out[tok.start[0]] = m.group(1).strip()
    except tokenize.TokenError:
        pass
    return out


def _attr_chain(node: ast.AST) -> Optional[List[str]]:
    """`self.fleet._lock` -> ["self", "fleet", "_lock"]; None when the
    expression is not a plain name/attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


class _Module:
    """One parsed file: symbol tables for resolution."""

    def __init__(self, rel: str, source: str):
        self.rel = rel
        self.source = source
        self.tree = ast.parse(source, filename=rel)
        self.pragmas = _pragmas(source)
        self.classes: Dict[str, ast.ClassDef] = {}
        # qualname ("Class.method" | "func") -> (node, class name | None)
        self.functions: Dict[str, Tuple[ast.AST, Optional[str]]] = {}
        self.mod_aliases: Dict[str, Tuple[str, str]] = {}  # ("mod"|"pkg", path)
        self.sym_aliases: Dict[str, Tuple[str, str]] = {}  # (rel, symbol)
        for node in self.tree.body:
            if isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self.functions[f"{node.name}.{sub.name}"] = (sub, node.name)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = (node, None)


class _Linter:
    def __init__(self, files: Dict[str, str], order=None):
        self.mods = {rel: _Module(rel, src) for rel, src in files.items()}
        self.decl = inventory.declared_order(order)
        # declared name -> (tier, rank)
        self.tier = {name: tier for (name, tier) in self.decl.values()}
        self.rank = {name: config.LOCK_TIER_RANK.get(tier, 99)
                     for name, tier in self.tier.items()}
        self.sites: List[inventory.LockSite] = []
        for mod in self.mods.values():
            self.sites += inventory.scan_source(mod.source, mod.rel)
        self.kinds: Dict[str, str] = {}
        for s in self.sites:
            row = self.decl.get((s.rel, s.qualname))
            if row is not None:
                self.kinds.setdefault(row[0], s.kind)
        # (rel, Class, attr) -> (rel, Class)
        self.attr_types: Dict[Tuple[str, str, str], Tuple[str, str]] = {}
        self.findings: List[Finding] = []
        # per-function summaries, keyed (rel, qualname)
        self.acquires: Dict[Tuple[str, str], Dict[str, int]] = {}
        self.blocking: Dict[Tuple[str, str], Dict[str, int]] = {}
        # call sites: fkey -> [(callee key, lineno, held names at site)]
        self.calls: Dict[Tuple[str, str],
                         List[Tuple[Tuple[str, str], int, Tuple[str, ...]]]] = {}
        # (rel, Class, attr) -> [(method, lineno, under_class_lock)]
        self.mutations: Dict[Tuple[str, str, str],
                             List[Tuple[str, int, bool]]] = {}

    # ---------------- symbol resolution ----------------

    def _resolve_imports(self, mod: _Module) -> None:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ImportFrom):
                continue
            base_parts = list(PurePosixPath(mod.rel).parent.parts)
            if node.level:
                up = node.level - 1
                base_parts = base_parts[:len(base_parts) - up] if up else base_parts
            elif node.module and node.module.split(".")[0] == "svd_jacobi_tpu":
                base_parts = []
                node_module = ".".join(node.module.split(".")[1:])
                base_parts += node_module.split(".") if node_module else []
                self._bind_imports(mod, "/".join(base_parts), node.names)
                continue
            else:
                continue  # external import
            if node.module:
                base_parts += node.module.split(".")
            self._bind_imports(mod, "/".join(base_parts), node.names)

    def _bind_imports(self, mod: _Module, base: str, names) -> None:
        for alias in names:
            name, asname = alias.name, alias.asname or alias.name
            sub = f"{base}/{name}" if base else name
            if f"{sub}.py" in self.mods:
                mod.mod_aliases[asname] = ("mod", f"{sub}.py")
            elif f"{base}.py" in self.mods:
                mod.sym_aliases[asname] = (f"{base}.py", name)
            elif any(r.startswith(f"{sub}/") for r in self.mods):
                mod.mod_aliases[asname] = ("pkg", sub)

    def _class_of(self, mod: _Module, name: str) -> Optional[Tuple[str, str]]:
        """Resolve a bare name used as a constructor to (rel, Class)."""
        if name in mod.classes:
            return (mod.rel, name)
        sym = mod.sym_aliases.get(name)
        if sym is not None:
            rel, symbol = sym
            target = self.mods.get(rel)
            if target is not None and symbol in target.classes:
                return (rel, symbol)
        return None

    def _callee_class(self, mod: _Module, call: ast.Call) -> Optional[Tuple[str, str]]:
        """(rel, Class) when ``call`` constructs a known package class."""
        fn = call.func
        if isinstance(fn, ast.Name):
            return self._class_of(mod, fn.id)
        chain = _attr_chain(fn)
        if chain is None:
            return None
        state = self._chain_state(mod, None, {}, chain[:-1])
        if state is not None and state[0] == "mod":
            target = self.mods.get(state[1])
            if target is not None and chain[-1] in target.classes:
                return (state[1], chain[-1])
        return None

    def _infer_attr_types(self) -> None:
        self.attr_types.update(_EXTRA_ATTR_TYPES)
        for mod in self.mods.values():
            for qual, (fn, cls) in mod.functions.items():
                if cls is None:
                    continue
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Assign):
                        continue
                    value = node.value
                    if isinstance(value, ast.IfExp):
                        value = (value.body if isinstance(value.body, ast.Call)
                                 else value.orelse)
                    if not isinstance(value, ast.Call):
                        continue
                    typ = self._callee_class(mod, value)
                    if typ is None:
                        continue
                    for t in node.targets:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            self.attr_types.setdefault(
                                (mod.rel, cls, t.attr), typ)

    def _local_types(self, mod: _Module, cls: Optional[str],
                     fn: ast.AST) -> Dict[str, Tuple[str, str]]:
        """Flow-insensitive local-variable class types inside ``fn``:
        `j = Journal(...)`, `j = self.journal`, `svc = replica.service`."""
        out: Dict[str, Tuple[str, str]] = {}
        for _ in range(2):  # two passes so var-via-var chains settle
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    continue
                name = node.targets[0].id
                if isinstance(node.value, ast.Call):
                    typ = self._callee_class(mod, node.value)
                    if typ is not None:
                        out[name] = typ
                    continue
                chain = _attr_chain(node.value)
                if chain is not None:
                    state = self._chain_state(mod, cls, out, chain)
                    if state is not None and state[0] == "cls":
                        out[name] = (state[1], state[2])
        return out

    def _chain_state(self, mod: _Module, cls: Optional[str],
                     local_types: Dict[str, Tuple[str, str]],
                     chain: Sequence[str]):
        """Walk a name chain to ("cls", rel, Class) | ("mod", rel) |
        None. The first element binds self / a typed local / a module
        alias; each further element follows attribute types."""
        if not chain:
            return None
        head, rest = chain[0], chain[1:]
        if head == "self" and cls is not None:
            state = ("cls", mod.rel, cls)
        elif head in local_types:
            rel, c = local_types[head]
            state = ("cls", rel, c)
        elif head in mod.mod_aliases:
            kind, path = mod.mod_aliases[head]
            state = ("mod", path) if kind == "mod" else ("pkg", path)
        else:
            return None
        for seg in rest:
            if state[0] == "cls":
                typ = self.attr_types.get((state[1], state[2], seg))
                if typ is None:
                    return None
                state = ("cls", typ[0], typ[1])
            elif state[0] == "pkg":
                nxt = f"{state[1]}/{seg}.py"
                if nxt in self.mods:
                    state = ("mod", nxt)
                elif any(r.startswith(f"{state[1]}/{seg}/") for r in self.mods):
                    state = ("pkg", f"{state[1]}/{seg}")
                else:
                    return None
            else:  # "mod": attributes of a module are terminal symbols
                return None
        return state

    def _resolve_lock(self, mod: _Module, cls: Optional[str],
                      local_types: Dict[str, Tuple[str, str]],
                      expr: ast.AST,
                      local_locks: Optional[Dict[str, str]] = None,
                      fn_base: Optional[str] = None) -> Optional[str]:
        """Declared lock name for a lock-valued expression, or None."""
        if isinstance(expr, ast.Call):
            if isinstance(expr.func, ast.Name):
                key = _RETURNS_LOCK.get((mod.rel, None, expr.func.id))
                if key is not None:
                    row = self.decl.get(key)
                    return row[0] if row is not None else None
                return None
            chain = _attr_chain(expr.func)
            if chain is None or len(chain) < 2:
                return None
            state = self._chain_state(mod, cls, local_types, chain[:-1])
            if state is not None and state[0] == "cls":
                key = _RETURNS_LOCK.get((state[1], state[2], chain[-1]))
                if key is not None:
                    row = self.decl.get(key)
                    return row[0] if row is not None else None
            return None
        chain = _attr_chain(expr)
        if chain is None:
            return None
        if len(chain) == 1:
            if local_locks is not None and chain[0] in local_locks:
                return local_locks[chain[0]]
            if fn_base is not None:
                row = self.decl.get((mod.rel, f"{fn_base}.{chain[0]}"))
                if row is not None:
                    return row[0]
            row = self.decl.get((mod.rel, chain[0]))
            return row[0] if row is not None else None
        state = self._chain_state(mod, cls, local_types, chain[:-1])
        if state is None:
            return None
        if state[0] == "cls":
            row = self.decl.get((state[1], f"{state[2]}.{chain[-1]}"))
        elif state[0] == "mod":
            row = self.decl.get((state[1], chain[-1]))
        else:
            row = None
        return row[0] if row is not None else None

    def _resolve_call(self, mod: _Module, cls: Optional[str],
                      local_types: Dict[str, Tuple[str, str]],
                      call: ast.Call) -> Optional[Tuple[str, str]]:
        """(rel, qualname) of an intra-package call target, or None."""
        fn = call.func
        if isinstance(fn, ast.Name):
            if fn.id in mod.functions:
                return (mod.rel, fn.id)
            sym = mod.sym_aliases.get(fn.id)
            if sym is not None and sym[1] in self.mods.get(sym[0], mod).functions:
                return sym
            return None
        chain = _attr_chain(fn)
        if chain is None or len(chain) < 2:
            return None
        state = self._chain_state(mod, cls, local_types, chain[:-1])
        if state is None:
            return None
        if state[0] == "cls":
            rel, c = state[1], state[2]
            target = self.mods.get(rel)
            if target is not None and f"{c}.{chain[-1]}" in target.functions:
                return (rel, f"{c}.{chain[-1]}")
        elif state[0] == "mod":
            target = self.mods.get(state[1])
            if target is not None and chain[-1] in target.functions:
                return (state[1], chain[-1])
        return None

    # ---------------- the per-function walk ----------------

    def _summarize_function(self, mod: _Module, qual: str,
                            fn: ast.AST, cls: Optional[str]) -> None:
        fkey = (mod.rel, qual)
        acq = self.acquires.setdefault(fkey, {})
        blk = self.blocking.setdefault(fkey, {})
        calls = self.calls.setdefault(fkey, [])
        local_types = self._local_types(mod, cls, fn)
        method = qual.rsplit(".", 1)[-1]
        # Locals holding a resolved lock: `lock = _append_lock(path)`,
        # `j = self.journal.exclusive()` — flow-insensitive, two passes.
        local_locks: Dict[str, str] = {}
        for _ in range(2):
            for node in ast.walk(fn):
                if (isinstance(node, ast.Assign) and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    got = self._resolve_lock(mod, cls, local_types,
                                             node.value, local_locks, method)
                    if got is not None:
                        local_locks[node.targets[0].id] = got

        def resolve(expr: ast.AST) -> Optional[str]:
            return self._resolve_lock(mod, cls, local_types, expr,
                                      local_locks, method)

        def class_locked(held: Sequence[str]) -> bool:
            for name in held:
                for (rel, lq), (dname, _tier) in self.decl.items():
                    if (dname == name and rel == mod.rel and cls is not None
                            and lq.startswith(f"{cls}.")):
                        return True
            return False

        def check_edge(held_name: str, new_name: str, lineno: int,
                       via: Optional[str] = None) -> None:
            via_txt = f" (via call to {via})" if via else ""
            if held_name == new_name:
                if self.kinds.get(new_name, "Lock") == "Lock":
                    self.findings.append(Finding(
                        code="CONC001",
                        where=f"{mod.rel}:{lineno}",
                        message=(f"lock {new_name!r} re-acquired while "
                                 f"already held{via_txt} — it is a plain "
                                 "threading.Lock, so this self-deadlocks"),
                        suggestion=("make it an RLock (and declare that "
                                    "in the inventory) or hoist the "
                                    "acquisition")))
                return
            lr, nr = self.rank.get(held_name, 99), self.rank.get(new_name, 99)
            if lr < nr:
                return
            rel_word = ("inverts the declared order"
                        if lr > nr else "has no declared order")
            self.findings.append(Finding(
                code="CONC001",
                where=f"{mod.rel}:{lineno}",
                message=(f"acquiring {new_name!r} (tier "
                         f"{self.tier.get(new_name, '?')}) while holding "
                         f"{held_name!r} (tier "
                         f"{self.tier.get(held_name, '?')}){via_txt} "
                         f"{rel_word} in config.LOCK_ORDER — a thread "
                         "taking the same pair in declared order "
                         "deadlocks against this one"),
                suggestion=("release the outer lock first, reorder the "
                            "acquisitions, or justify with "
                            "`# graftlock: ok(reason)`")))

        def handle_call(call: ast.Call, held: Tuple[str, ...],
                        loops: int) -> None:
            chain = _attr_chain(call.func)
            attr = (chain[-1] if chain else
                    (call.func.attr if isinstance(call.func, ast.Attribute)
                     else None))
            # CONC003: condition-variable discipline.
            cv = None
            if chain is not None and len(chain) >= 2:
                owner = resolve(call.func.value)
                if owner is not None and self.kinds.get(owner) == "Condition":
                    cv = owner
            if cv is not None and attr in ("wait", "wait_for"):
                if cv not in held:
                    self.findings.append(Finding(
                        code="CONC003", where=f"{mod.rel}:{call.lineno}",
                        message=(f"{cv!r}.{attr}() without holding the "
                                 "owning lock — raises RuntimeError at "
                                 "runtime"),
                        suggestion=f"wrap in `with <{cv}>:`"))
                if attr == "wait" and loops == 0:
                    self.findings.append(Finding(
                        code="CONC003", where=f"{mod.rel}:{call.lineno}",
                        message=(f"{cv!r}.wait() outside a predicate "
                                 "loop — spurious wakeups and stolen "
                                 "notifies make a bare wait incorrect"),
                        suggestion=("re-check the predicate in a "
                                    "`while` around the wait")))
                if not call.args and not any(
                        kw.arg == "timeout" for kw in call.keywords):
                    self.findings.append(Finding(
                        code="CONC003", where=f"{mod.rel}:{call.lineno}",
                        message=(f"{cv!r}.{attr}() with no timeout — an "
                                 "unbounded wait cannot observe "
                                 "shutdown/deadline and hangs stop()"),
                        suggestion="pass a bounded timeout and re-loop"))
                return
            if cv is not None and attr in ("notify", "notify_all"):
                if cv not in held:
                    self.findings.append(Finding(
                        code="CONC003", where=f"{mod.rel}:{call.lineno}",
                        message=(f"{cv!r}.{attr}() without holding the "
                                 "owning lock"),
                        suggestion=f"wrap in `with <{cv}>:`"))
                return
            # CONC001c: blocking call under a scoped-tier lock.
            label = _BLOCKING_ATTRS.get(attr or "")
            if label is None and isinstance(call.func, ast.Name):
                label = _BLOCKING_ATTRS.get(call.func.id)
            if label is not None:
                blk.setdefault(label, call.lineno)
                scoped = [h for h in held
                          if self.tier.get(h) in _SCOPED_TIERS]
                if scoped:
                    self.findings.append(Finding(
                        code="CONC001", where=f"{mod.rel}:{call.lineno}",
                        message=(f"blocking call ({label}) while holding "
                                 f"{scoped[-1]!r} (tier "
                                 f"{self.tier.get(scoped[-1])}) — stalls "
                                 "every thread contending on that lock "
                                 "(the worker-wedge class)"),
                        suggestion=("move the blocking work outside the "
                                    "lock, or justify with "
                                    "`# graftlock: ok(reason)`")))
            callee = self._resolve_call(mod, cls, local_types, call)
            if callee is not None:
                calls.append((callee, call.lineno, held))

        def visit_expr(expr: ast.AST, held: Tuple[str, ...],
                       loops: int) -> None:
            for node in ast.walk(expr):
                if isinstance(node, ast.Call):
                    handle_call(node, held, loops)

        def record_mutations(st: ast.stmt, held: Tuple[str, ...]) -> None:
            if cls is None or not isinstance(st, (ast.Assign, ast.AugAssign,
                                                  ast.AnnAssign)):
                return
            targets = (st.targets if isinstance(st, ast.Assign)
                       else [st.target])
            for t in targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    self.mutations.setdefault(
                        (mod.rel, cls, t.attr), []).append(
                            (method, t.lineno, class_locked(held)))

        def walk_block(stmts: Sequence[ast.stmt], held: Tuple[str, ...],
                       loops: int) -> None:
            extra: List[str] = []  # .acquire()d within this block
            for st in stmts:
                cur = held + tuple(extra)
                # explicit acquire()/release() at statement level
                if isinstance(st, ast.Expr) and isinstance(st.value, ast.Call):
                    chain = _attr_chain(st.value.func)
                    if chain and len(chain) >= 2 and chain[-1] in (
                            "acquire", "release"):
                        name = resolve(st.value.func.value)
                        if name is not None:
                            if chain[-1] == "acquire":
                                acq.setdefault(name, st.lineno)
                                for h in cur:
                                    check_edge(h, name, st.lineno)
                                extra.append(name)
                            elif name in extra:
                                extra.remove(name)
                            continue
                walk_stmt(st, cur, loops)

        def walk_stmt(st: ast.stmt, held: Tuple[str, ...],
                      loops: int) -> None:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                return  # nested defs are not executed inline
            record_mutations(st, held)
            if isinstance(st, (ast.With, ast.AsyncWith)):
                new = list(held)
                for item in st.items:
                    visit_expr(item.context_expr, tuple(new), loops)
                    name = resolve(item.context_expr)
                    if name is not None:
                        acq.setdefault(name, item.context_expr.lineno)
                        for h in new:
                            check_edge(h, name, st.lineno)
                        new.append(name)
                walk_block(st.body, tuple(new), loops)
                return
            if isinstance(st, (ast.While, ast.For, ast.AsyncFor)):
                if isinstance(st, ast.While):
                    visit_expr(st.test, held, loops)
                else:
                    visit_expr(st.iter, held, loops)
                walk_block(st.body, held, loops + 1)
                walk_block(st.orelse, held, loops)
                return
            for field in ast.iter_fields(st):
                value = field[1]
                if isinstance(value, list):
                    if value and isinstance(value[0], ast.stmt):
                        walk_block(value, held, loops)
                    else:
                        for v in value:
                            if isinstance(v, ast.AST):
                                visit_expr(v, held, loops)
                elif isinstance(value, ast.AST):
                    visit_expr(value, held, loops)

        walk_block(fn.body, (), 0)

    # ---------------- cross-function propagation ----------------

    def _fixpoint(self) -> Tuple[Dict, Dict]:
        """Propagate acquired-lock and blocking summaries over the call
        graph: trans[fkey] maps lock name / blocking label -> the
        immediate callee it came through (None when direct)."""
        trans_acq = {f: {m: None for m in acq}
                     for f, acq in self.acquires.items()}
        trans_blk = {f: {b: None for b in blk}
                     for f, blk in self.blocking.items()}
        changed = True
        while changed:
            changed = False
            for f, sites in self.calls.items():
                for callee, _lineno, _held in sites:
                    for m in trans_acq.get(callee, {}):
                        if m not in trans_acq[f]:
                            trans_acq[f][m] = callee[1]
                            changed = True
                    for b in trans_blk.get(callee, {}):
                        if b not in trans_blk[f]:
                            trans_blk[f][b] = callee[1]
                            changed = True
        return trans_acq, trans_blk

    def _check_call_sites(self, trans_acq, trans_blk) -> None:
        for fkey, sites in self.calls.items():
            mod = self.mods[fkey[0]]
            for callee, lineno, held in sites:
                if not held:
                    continue
                via = callee[1]
                for m in trans_acq.get(callee, {}):
                    for h in held:
                        self._edge_at(mod, h, m, lineno, via)
                scoped = [h for h in held
                          if self.tier.get(h) in _SCOPED_TIERS]
                if scoped:
                    for label in trans_blk.get(callee, {}):
                        self.findings.append(Finding(
                            code="CONC001", where=f"{mod.rel}:{lineno}",
                            message=(f"call to {via} blocks ({label}) "
                                     f"while holding {scoped[-1]!r} "
                                     f"(tier {self.tier.get(scoped[-1])})"),
                            suggestion=("move the call outside the lock "
                                        "or justify with "
                                        "`# graftlock: ok(reason)`")))

    def _edge_at(self, mod: _Module, held_name: str, new_name: str,
                 lineno: int, via: str) -> None:
        if held_name == new_name:
            if self.kinds.get(new_name, "Lock") == "Lock":
                self.findings.append(Finding(
                    code="CONC001", where=f"{mod.rel}:{lineno}",
                    message=(f"lock {new_name!r} re-acquired while held "
                             f"(via call to {via}) — plain Lock, "
                             "self-deadlock"),
                    suggestion="make it re-entrant or hoist the call"))
            return
        lr, nr = self.rank.get(held_name, 99), self.rank.get(new_name, 99)
        if lr < nr:
            return
        rel_word = ("inverts the declared order" if lr > nr
                    else "has no declared order")
        self.findings.append(Finding(
            code="CONC001", where=f"{mod.rel}:{lineno}",
            message=(f"call to {via} acquires {new_name!r} (tier "
                     f"{self.tier.get(new_name, '?')}) while holding "
                     f"{held_name!r} (tier {self.tier.get(held_name, '?')}) "
                     f"— {rel_word} in config.LOCK_ORDER"),
            suggestion=("restructure so the inner lock is taken first "
                        "or alone, or justify with "
                        "`# graftlock: ok(reason)`")))

    def _check_guarded_by(self) -> None:
        for (rel, cls, attr), muts in sorted(self.mutations.items()):
            body = [(m, ln, lk) for (m, ln, lk) in muts
                    if m not in _INIT_METHODS]
            locked = {m for (m, _ln, lk) in body if lk}
            bare = [(m, ln) for (m, ln, lk) in body if not lk]
            if not locked or not bare:
                continue
            for m, ln in bare:
                if m in locked:
                    continue  # mixed within one method: assume staging
                self.findings.append(Finding(
                    code="CONC001", where=f"{rel}:{ln}",
                    message=(f"attribute self.{attr} of {cls} is written "
                             f"under the class lock in "
                             f"{', '.join(sorted(locked))} but bare in "
                             f"{m} — unsynchronized read-modify-write "
                             "races the locked writers"),
                    suggestion=("take the class lock around this write "
                                "or justify with "
                                "`# graftlock: ok(reason)`")))

    # ---------------- driver ----------------

    def run(self, *, check_inventory: bool = True) -> List[Finding]:
        for mod in self.mods.values():
            self._resolve_imports(mod)
        self._infer_attr_types()
        if check_inventory:
            pragmas = {mod.rel: mod.pragmas for mod in self.mods.values()}
            self.findings += inventory.check_inventory(
                self.sites, {name: (rel, q, tier) for (rel, q), (name, tier)
                             in self.decl.items()},
                pragmas=pragmas)
        for mod in self.mods.values():
            for qual, (fn, cls) in mod.functions.items():
                self._summarize_function(mod, qual, fn, cls)
        trans_acq, trans_blk = self._fixpoint()
        self._check_call_sites(trans_acq, trans_blk)
        self._check_guarded_by()
        return self._apply_pragmas()

    def _apply_pragmas(self) -> List[Finding]:
        out: List[Finding] = []
        seen: Set[Tuple[str, str, str]] = set()
        for f in self.findings:
            rel, _, line = f.where.rpartition(":")
            mod = self.mods.get(rel)
            reason = None
            if mod is not None and line.isdigit():
                # Same line, or a standalone pragma comment just above.
                reason = (mod.pragmas.get(int(line))
                          or mod.pragmas.get(int(line) - 1))
            if reason:
                continue  # justified
            key = (f.code, f.where, f.message)
            if key in seen:
                continue
            seen.add(key)
            out.append(f)
        for mod in self.mods.values():
            for line, reason in sorted(mod.pragmas.items()):
                if not reason:
                    out.append(Finding(
                        code="CONC001", where=f"{mod.rel}:{line}",
                        message=("`# graftlock: ok()` pragma with no "
                                 "reason — the justification is the "
                                 "point of the pragma"),
                        suggestion="state why the exception is safe"))
        out.sort(key=lambda f: (f.where.rpartition(":")[0],
                                int(f.where.rpartition(":")[2] or 0)))
        return out


def lint_sources(files: Dict[str, str], order=None, *,
                 check_inventory: bool = True) -> List[Finding]:
    return _Linter(files, order=order).run(check_inventory=check_inventory)


def lint_file(path, rel: Optional[str] = None, order=None, *,
              check_inventory: bool = True) -> List[Finding]:
    """Lint one file (the fixture entry point). ``order`` is a
    LOCK_ORDER-shaped dict; defaults to the package's."""
    path = Path(path)
    rel = rel or path.name
    return lint_sources({rel: path.read_text()}, order=order,
                        check_inventory=check_inventory)


def lint_package(root=None, order=None) -> List[Finding]:
    """The real-package lint: every module under ``root`` (default: the
    installed package), full CONC001 + CONC003 + inventory
    completeness."""
    root = Path(root) if root is not None else inventory.package_root()
    files = {p.relative_to(root).as_posix(): p.read_text()
             for p in sorted(root.rglob("*.py"))}
    return lint_sources(files, order=order)
