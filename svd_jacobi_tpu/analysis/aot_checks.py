"""AOT001: the entry registry and the retrace budgets must agree.

`serve.registry.jit_entries()` is the authoritative ``entry name -> live
jit object`` map (the AOT warmup lane compiles through it; the retrace
guard resolves its probes through it), and `config.RETRACE_BUDGETS` is
the declared compile-budget ledger. The two grew independently before
the registry existed; this pass pins them together:

  * every `RETRACE_BUDGETS` key must be enumerated by the registry — a
    budget for an entry the registry cannot name is dead declaration
    (nothing AOT-compiles it, nothing can guard it);
  * every registry name must carry a budget — an entry the registry
    compiles but nobody budgeted is an unguarded compile surface (a
    retrace leak there would be invisible to RETRACE001).

Additionally, every jit call the registry PLANS for a representative
service configuration (all three bucket families + batched tiers, via
`EntryRegistry.aot_plan` — pure `jax.eval_shape`, no compiles) must
resolve to a declared entry name, so the AOT warmup can never compile a
program the budgets don't know about.

The seeded failing fixture is parameter injection (tests): an extra
budget key / a dropped registry name makes `check_budget_coverage` fire,
and an undeclared plan name makes `check_plan_names` fire.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from . import Finding
from .. import config as _config


def check_budget_coverage(budgets: Optional[Dict[str, int]] = None,
                          entries: Optional[Dict[str, object]] = None
                          ) -> List[Finding]:
    """Two-way set equality of `config.RETRACE_BUDGETS` keys vs the
    registry's `jit_entries()` names (AOT001 findings otherwise).
    ``budgets``/``entries`` substitute the seeded failing fixtures."""
    from ..serve import registry as _registry
    budgets = dict(_config.RETRACE_BUDGETS if budgets is None else budgets)
    entries = (_registry.jit_entries() if entries is None
               else dict(entries))
    findings = []
    for name in sorted(set(budgets) - set(entries)):
        findings.append(Finding(
            code="AOT001", where=name,
            message=(f"RETRACE_BUDGETS declares {name!r} but the entry "
                     f"registry (serve.registry.jit_entries) does not "
                     f"enumerate it — a budget nothing can AOT-compile "
                     f"or guard"),
            suggestion=("add the entry to serve.registry.jit_entries() "
                        "or drop the stale budget")))
    for name in sorted(set(entries) - set(budgets)):
        findings.append(Finding(
            code="AOT001", where=name,
            message=(f"the entry registry enumerates {name!r} but "
                     f"config.RETRACE_BUDGETS carries no budget for it "
                     f"— an unguarded compile surface"),
            suggestion="declare a RETRACE_BUDGETS entry for it"))
    return findings


# A representative configuration covering all three bucket families AND
# the batched tiers, so the plan walk exercises every stepper lane the
# serving layer can dispatch (single + batched, pallas + hybrid XLA,
# tall TSQR, top-k sketch, factor lifts).
_PLAN_BUCKETS = ((64, 48, "float32"), (96, 64, "float32"),
                 (256, 32, "float32", "tall"),
                 (96, 96, "float32", "topk", 8))


def check_plan_names(budgets: Optional[Dict[str, int]] = None,
                     buckets=None, max_batch: int = 4) -> List[Finding]:
    """Every jit call the registry plans for a representative service
    must be a declared budget key (AOT001 otherwise). Pure
    `jax.eval_shape` — nothing compiles."""
    from ..config import SVDConfig
    from ..serve.buckets import BucketSet
    from ..serve.registry import EntryRegistry
    budgets = dict(_config.RETRACE_BUDGETS if budgets is None else budgets)
    bucket_set = BucketSet(_PLAN_BUCKETS if buckets is None else buckets)
    base = SVDConfig()
    solver_map = bucket_set.resolve_solver_configs(base)
    tiers = (1, 4)
    reg = EntryRegistry(bucket_set, solver_map,
                        {b: tiers for b in bucket_set}, base,
                        max_batch=max_batch, lanes=1,
                        default_tiers=tiers)
    findings = []
    planned: Dict[str, List[str]] = {}
    for key in reg.entries():
        for name, _, _, _ in reg.aot_plan(key):
            planned.setdefault(name, []).append(key.name)
    for name, where in sorted(planned.items()):
        if name not in budgets:
            findings.append(Finding(
                code="AOT001", where=name,
                message=(f"the registry's AOT plan dispatches {name!r} "
                         f"(for {where[:3]}) but RETRACE_BUDGETS does "
                         f"not declare it — the warmup would compile an "
                         f"unbudgeted program"),
                suggestion="declare a RETRACE_BUDGETS entry for it"))
    return findings


def run_all() -> tuple:
    """The CLI's ``aot`` pass: both coverage checks plus a registry
    report. Returns ``(findings, report)``."""
    from ..serve import registry as _registry
    findings = check_budget_coverage()
    findings += check_plan_names()
    report = {
        "registry_entries": sorted(_registry.jit_entries()),
        "budget_entries": sorted(_config.RETRACE_BUDGETS),
    }
    return findings, report
