"""Experiment driver CLI — reference-driver parity, JSON reports.

TPU-native replacement for the reference's `main()` experiment harness
(reference: main.cu:1426-1676), which: parses one CLI arg N (square only),
runs a fixed 1000x1000 single-process warm-up solve on every rank
(main.cu:1461-1534), generates a seeded upper-triangular N x N matrix
(seed 1000000, main.cu:1445, 1558-1567), runs the MPI solver, recomputes
the residual ||A - U S V^T||_F (main.cu:1640-1662), and writes timing +
residual to `reporte-dimension-<N>-time-<timestamp>.txt` (main.cu:1667-1669).

Here: rectangular sizes, reproducible warm-up (the reference's warm-up is
unseeded, quirk #9), orthogonality checks the reference lacks, sweeps /
convergence diagnostics, optional mesh-distributed solve and profiler trace,
and a schema-versioned run manifest: every run appends ONE JSONL record
(`obs.manifest`) to `<report-dir>/manifest.jsonl` — device topology, config
hash, per-stage wall times, solve metrics, and (with --telemetry) the
in-graph per-sweep event stream from the FUSED solve. Render or diff
records with `scripts/telemetry_summary.py`.

Usage:
    python -m svd_jacobi_tpu.cli N [M] [--dtype f32] [--distributed]
        [--matrix triangular|dense] [--no-selftest] [--report-dir DIR]
        [--profile DIR] [--oracle] [--telemetry]

    python -m svd_jacobi_tpu.cli serve-demo [--requests N] [--clients K]
        [--seed S] [--bucket MxN:dtype ...] [--tight-frac F]
        [--lock-sanitizer] ...
        — seeded closed-loop clients against a live `serve.SVDService`
        (deadlines, admission control, brownout; one "serve" manifest
        record per request). --lock-sanitizer runs the demo under the
        graftlock CONC002 lock-graph sanitizer and exits non-zero on an
        acquisition cycle (analysis.concurrency.sanitizer).

    python -m svd_jacobi_tpu.cli tune [--smoke] [--shapes ...] [--out PATH]
        — the measured autotuner: benchmark the knob grid on the attached
        backend and write a versioned tuning table (tune.search; pin the
        result with --tuning-table=PATH on any run).

    python -m svd_jacobi_tpu.cli metrics reports/manifest.jsonl
        [--slo] [--timeline REQUEST_ID]
        — one-shot flight-recorder dump reconstructed OFFLINE from the
        manifest records: Prometheus text exposition by default, the SLO
        report with --slo, one request's span timeline with --timeline
        (obs.registry / obs.spans; the live equivalents are the
        service's /metrics listener and SVDService.timeline()).
"""

from __future__ import annotations

import argparse
import contextlib
import json
import sys
import time
from pathlib import Path

import numpy as np


def _parse_args(argv):
    p = argparse.ArgumentParser(
        prog="svd-bench",
        description="One-sided block-Jacobi SVD experiment driver (TPU).")
    p.add_argument("n", type=int, help="matrix columns (reference: dimension)")
    p.add_argument("m", type=int, nargs="?", default=None,
                   help="matrix rows (default: n, square like the reference)")
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "float64", "bfloat16"])
    p.add_argument("--matrix", default="triangular",
                   choices=["triangular", "dense"],
                   help="triangular = the reference's benchmark input "
                        "(main.cu:1558-1567); dense = its #ifdef TESTS path")
    p.add_argument("--seed", type=int, default=1_000_000,
                   help="RNG seed (reference's fixed seed, main.cu:1445)")
    p.add_argument("--distributed", action="store_true",
                   help="solve over a mesh of all visible devices")
    p.add_argument("--pair-solver", default="auto",
                   choices=["auto", "pallas", "block_rotation", "resident",
                            "qr-svd", "gram-eigh", "hybrid"])
    p.add_argument("--precondition", default="auto",
                   choices=["auto", "on", "off", "double"],
                   help="QR preconditioning mode (Pallas path; 'double' = "
                        "dgejsv-style second QR for graded spectra)")
    p.add_argument("--mixed-bulk", default="auto",
                   choices=["auto", "on", "off"],
                   help="bf16x3 bulk sweeps + f32 polish (the mixed "
                        "bf16-compute/f32-accumulate regime; see "
                        "SVDConfig.mixed_bulk — auto is currently off)")
    p.add_argument("--sigma-refine", default="auto",
                   choices=["auto", "on", "off"],
                   help="post-convergence sigma refinement (recompute the "
                        "rotated columns from the solve's working matrix "
                        "at HIGHEST + compensated norms; auto = on when "
                        "factors are computed)")
    p.add_argument("--jobu", default="some", choices=["all", "some", "none"],
                   help="left-factor job option (the reference driver's "
                        "SVD_OPTIONS, main.cu:1587, lib/JacobiMethods.cuh:"
                        "25-29): all = full (m, m) U, some = economy, "
                        "none = sigma-only")
    p.add_argument("--jobv", default="some", choices=["all", "some", "none"],
                   help="right-factor job option (see --jobu)")
    p.add_argument("--max-sweeps", type=int, default=32)
    p.add_argument("--tol", type=float, default=None)
    p.add_argument("--block-size", type=int, default=None)
    p.add_argument("--top-k", type=int, default=None, metavar="K",
                   help="truncated top-K solve via the randomized "
                        "range-finder lane (solver.svd_topk): only the "
                        "top-K factors are computed, in O(mnK)-class "
                        "flops instead of the full O(n^3); exits "
                        "non-zero on status != OK like the full solve")
    p.add_argument("--oversample", type=int, default=None,
                   help="sketch oversampling columns beyond K "
                        "(default: tuning table, generic 8)")
    p.add_argument("--power-iters", type=int, default=None,
                   help="TSQR-stabilized power iterations of the sketch "
                        "(default: tuning table, generic 1)")
    p.add_argument("--no-selftest", action="store_true",
                   help="skip the built-in warm-up self-test "
                        "(reference runs one unconditionally, main.cu:1461)")
    p.add_argument("--selftest-n", type=int, default=256,
                   help="warm-up self-test size (reference: 1000)")
    p.add_argument("--oracle", action="store_true",
                   help="also compare sigma against numpy.linalg.svd (host)")
    p.add_argument("--report-dir", default="reports",
                   help="directory of the run manifest (one JSONL record "
                        "per run appended to <dir>/manifest.jsonl)")
    p.add_argument("--tuning-table", default=None, metavar="PATH|off",
                   help="pin a measured tuning table for this run's "
                        "'auto' knob resolution (tune.tables; 'off' = "
                        "builtin hand-picked heuristics; default = the "
                        "shipped table / SVDJ_TUNING_TABLE)")
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="capture a jax.profiler trace of the solve into DIR "
                        "(obs.trace: creates the dir, warns instead of "
                        "raising when the profiler is unavailable)")
    p.add_argument("--telemetry", action="store_true",
                   help="record the in-graph per-sweep event stream "
                        "(obs.metrics) of the timed solve into the "
                        "manifest; the solve is retraced with "
                        "jax.debug.callback emission baked in")
    p.add_argument("--sanitized", action="store_true",
                   help="run the solves under JAX runtime sanitizers "
                        "(jax_debug_nans + jax_debug_infs + device-to-host "
                        "transfer guard — analysis.sanitize, the CI "
                        "'-m sanitized' lane's configuration); timings are "
                        "NOT comparable to unsanitized runs")
    return p.parse_args(argv)


def _force(tree):
    from svd_jacobi_tpu.utils._exec import force
    return force(tree)


def _solve(a, args, config, mesh):
    """Run the solver with the driver's jobu/jobv mapped exactly as
    `lapack.gesvd` maps SVD_OPTIONS (NoVec -> compute_*=False, AllVec ->
    full_matrices) so sigma-only and AllVec runs are reproducible from the
    CLI alone (reference: main.cu:1587). ``--top-k`` routes the one-shot
    truncated lane (`solver.svd_topk`)."""
    import svd_jacobi_tpu as sj
    cu, cv = args.jobu != "none", args.jobv != "none"
    full = args.jobu == "all" or args.jobv == "all"
    if getattr(args, "top_k", None):
        from svd_jacobi_tpu.solver import svd_topk
        return svd_topk(a, args.top_k, compute_u=cu, compute_v=cv,
                        config=config)
    if mesh is not None:
        from svd_jacobi_tpu.parallel import sharded
        return sharded.svd(a, mesh=mesh, compute_u=cu, compute_v=cv,
                           full_matrices=full, config=config)
    return sj.svd(a, compute_u=cu, compute_v=cv, full_matrices=full,
                  config=config)


def _self_test(args, config, log) -> dict:
    """Built-in warm-up solve — the reference's unconditional 1000x1000
    single-process test (main.cu:1461-1534), made reproducible and checked
    against tolerances instead of just printed."""
    import jax.numpy as jnp
    from svd_jacobi_tpu.utils import matgen, validation

    n = args.selftest_n
    a = matgen.random_dense(n, n, seed=args.seed + 1, dtype=jnp.dtype(args.dtype))
    # The self-test checks the residual, so it always computes economy
    # factors regardless of the main run's jobu/jobv.
    st_args = argparse.Namespace(**{**vars(args), "jobu": "some",
                                    "jobv": "some", "top_k": None})
    t0 = time.perf_counter()
    r = _solve(a, st_args, config, None)
    _force(tuple(r[:3]))
    dt = time.perf_counter() - t0
    rep = validation.validate(a, r)
    ok = float(rep.residual_rel) < (1e-3 if args.dtype == "bfloat16" else 1e-4)
    log(f"self-test {n}x{n}: residual={float(rep.residual_rel):.3e} "
        f"sweeps={int(r.sweeps)} time={dt:.3f}s -> {'OK' if ok else 'FAIL'}")
    return {"n": n, "time_s": dt, "residual_rel": float(rep.residual_rel),
            "sweeps": int(r.sweeps), "ok": ok}


def _parse_serve_args(argv):
    p = argparse.ArgumentParser(
        prog="svd-serve-demo",
        description="Seeded closed-loop client demo against an in-process "
                    "deadline-aware SVD service (serve.SVDService).")
    p.add_argument("--requests", type=int, default=24,
                   help="total requests across all clients")
    p.add_argument("--clients", type=int, default=4,
                   help="closed-loop client threads (each waits for its "
                        "result before submitting the next request)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--bucket", action="append", default=None,
                   metavar="MxN:dtype",
                   help="declared shape bucket (repeatable; also "
                        "'MxN:dtype:tall' / 'MxN:dtype:topkK'); default: "
                        "64x48:float32 + 96x64:float32 (CPU-friendly)")
    p.add_argument("--topk-mix", action="store_true",
                   help="seeded full + tall + top-k request mix: adds a "
                        "tall and a topk bucket to the default set, draws "
                        "~25%% of requests as tall shapes and ~25%% as "
                        "top-k submits (top_k within the bucket's rank "
                        "class); exits non-zero if any untimed-out "
                        "request ends with status != OK")
    p.add_argument("--deadline-s", type=float, default=60.0,
                   help="per-request deadline for ordinary requests")
    p.add_argument("--tight-frac", type=float, default=0.2,
                   help="fraction of requests given a deliberately "
                        "unmeetable deadline (they must return DEADLINE, "
                        "loudly, not hang)")
    p.add_argument("--tight-ms", type=float, default=1.0,
                   help="the unmeetable deadline, in milliseconds")
    p.add_argument("--queue-depth", type=int, default=16)
    p.add_argument("--max-batch", type=int, default=1,
                   help="coalesce up to this many same-bucket requests "
                        "into one batched solve dispatch (1 = serial)")
    p.add_argument("--batch-window-ms", type=float, default=20.0,
                   help="bounded batching window: max wait for same-"
                        "bucket followers after the first pop (only with "
                        "--max-batch > 1)")
    p.add_argument("--replicas", type=int, default=1,
                   help="service REPLICAS behind a federated "
                        "ReplicaRouter (each its own fault domain with "
                        "its own journal; consistent-hash routing, "
                        "replica-death journal rescue). 1 = a plain "
                        "single service (default)")
    p.add_argument("--transport", default="local",
                   choices=["local", "http"],
                   help="replica transport with --replicas > 1: 'local' "
                        "= in-process handles (default); 'http' = every "
                        "replica behind the versioned HTTP wire protocol "
                        "(serve.transport) — RPC retries with jittered "
                        "backoff, deadline-budget decay, idempotency "
                        "keys, leases and fencing tokens, end to end")
    p.add_argument("--net-chaos", action="store_true",
                   help="with --transport=http: put each replica behind "
                        "a fault-injecting TCP proxy "
                        "(resilience.netfault) with one dropped and one "
                        "delayed request armed per replica — the demo "
                        "must still close every request (retries + "
                        "idempotency absorb the chaos)")
    p.add_argument("--lanes", type=int, default=1,
                   help="solve lanes (fleet mode when > 1): one worker "
                        "per lane, per-lane fault domains with bucket-"
                        "affinity routing, work stealing, and lane "
                        "eviction/rescue/probe recovery")
    # --- multi-tenancy & QoS (tenant-aware admission + WFQ dequeue) ------
    p.add_argument("--tenants", type=int, default=0, metavar="N",
                   help="declare N equal-weight tenants "
                        "(tenant-0..tenant-(N-1)) and spread the request "
                        "plan round-robin across them; the summary gains "
                        "a per-tenant SLO section reconstructed from "
                        "validated serve records. 0 = the single-tenant "
                        "legacy surface (default)")
    p.add_argument("--adversary", default=None, metavar="MODE",
                   choices=["flood", "burst", "resubmit",
                            "deadline_abuse"],
                   help="fairness drill (needs --tenants >= 2): replay "
                        "the seeded resilience.chaos.adversarial_tenant "
                        "schedule — the SAME schedule the '-m chaos' "
                        "tenancy tests replay for a given seed — victim "
                        "'alice' (weight 4) against abuser 'mallory' "
                        "(rate-limited; budget-capped under "
                        "deadline_abuse), plus N-2 equal-weight "
                        "bystanders; exits non-zero on any fairness-band "
                        "violation (a victim or bystander request not "
                        "OK, the abuser never shed, or anyone but the "
                        "abuser rejected)")
    p.add_argument("--adversary-victims", type=int, default=8,
                   help="victim submits in the drill schedule (the "
                        "abuser floods 4x that)")
    p.add_argument("--report-dir", default="reports",
                   help="manifest directory (per-request 'serve' JSONL "
                        "records appended to <dir>/manifest.jsonl); "
                        "'off' disables")
    p.add_argument("--tuning-table", default=None, metavar="PATH|off",
                   help="pin a measured tuning table for the service's "
                        "per-bucket knob resolution ('off' = builtin "
                        "hand-picked heuristics)")
    # --- restart survivability (serve.registry / serve.journal) ----------
    p.add_argument("--journal", default=None, metavar="PATH",
                   help="durable request journal (write-ahead JSONL, "
                        "fsync per record): admitted requests survive a "
                        "process kill and are re-admitted on restart")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="persistent executable cache root: warmup "
                        "compiles land in <DIR>/<config-hash>/ so a "
                        "restarted process warms from cache hits "
                        "instead of fresh compiles")
    p.add_argument("--warmup", action="store_true",
                   help="run SVDService.warmup() before the clients "
                        "(AOT + zero-solve phases when --compile-cache "
                        "is set); per-entry timing lands in a "
                        "'coldstart' manifest record and the summary")
    p.add_argument("--restart-drill", action="store_true",
                   help="kill-and-restart drill: serve under load in a "
                        "child process, SIGKILL it mid-load, restart "
                        "it, and report cold-start latency + resumed "
                        "request count; exits non-zero on ANY lost "
                        "request")
    p.add_argument("--drill-requests", type=int, default=6,
                   help="requests the restart drill pushes through the "
                        "child (kept small: each is slowed so the kill "
                        "window is wide)")
    p.add_argument("--lock-sanitizer", action="store_true",
                   help="run the whole demo under the graftlock CONC002 "
                        "runtime lock-graph sanitizer (instrumented "
                        "threading.Lock/RLock/Condition): the summary "
                        "gains a 'lock_graph' section and the demo exits "
                        "non-zero if the acquisition graph has a cycle "
                        "(a potential deadlock)")
    # Internal drill plumbing (the orchestrator spawns serve-demo
    # children with these; not for direct use).
    p.add_argument("--_drill-resume", action="store_true",
                   dest="drill_resume", help=argparse.SUPPRESS)
    p.add_argument("--_drill-slow-ms", type=float, default=0.0,
                   dest="drill_slow_ms", help=argparse.SUPPRESS)
    return p.parse_args(argv)


def serve_demo(argv) -> int:
    """`serve-demo` subcommand: run a seeded closed-loop client fleet
    against a live service and report aggregate behavior. Exit 0 iff
    every request reached a terminal outcome and none errored — DEADLINE
    and admission rejections are EXPECTED outcomes here (the demo
    deliberately provokes them), not failures."""
    args = _parse_serve_args(argv)
    if args.restart_drill:
        if args.lock_sanitizer:
            raise SystemExit(
                "--lock-sanitizer instruments THIS process's locks, but "
                "the restart drill runs its load in child processes — "
                "pass it to a plain serve-demo run instead")
        return _restart_drill(args)
    if not args.lock_sanitizer:
        return _serve_demo_run(args)
    # CONC002: patch the lock factories BEFORE the service is built so
    # every lock it mints (service, fleet, queue, journal, breaker,
    # caches, obs) is instrumented for the whole run.
    from svd_jacobi_tpu.analysis.concurrency import sanitizer
    with sanitizer.capture() as graph:
        return _serve_demo_run(args, lock_graph=graph)


def _serve_demo_run(args, lock_graph=None) -> int:
    import os
    import threading

    import jax
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp

    from svd_jacobi_tpu import SVDConfig
    from svd_jacobi_tpu.serve import AdmissionError, ServeConfig, SVDService
    from svd_jacobi_tpu.utils import matgen

    if args.tuning_table:
        from svd_jacobi_tpu import tune
        tune.set_active_table(args.tuning_table)

    def log(msg):
        print(msg, file=sys.stderr)

    from svd_jacobi_tpu.serve import as_bucket
    buckets = tuple(args.bucket or ("64x48:float32", "96x64:float32"))
    if args.topk_mix:
        # The three workload families in one service instance: EXTEND
        # the effective set (explicit --bucket included — the mix must
        # never become a silent no-op) with a tall and a top-k bucket
        # when the set declares none (CPU-friendly sizes; tall needs
        # m >= 8n).
        kinds = {as_bucket(b).kind for b in buckets}
        if "tall" not in kinds:
            buckets += ("256x24:float32:tall",)
        if "topk" not in kinds:
            buckets += ("96x96:float32:topk8",)
    bucket_set = [as_bucket(b) for b in buckets]
    if any(b.dtype == "float64" for b in bucket_set):
        # Declared f64 buckets (under any dtype spelling — as_bucket
        # normalizes) need x64 BEFORE any array is built, or matgen
        # silently truncates to f32 and nothing routes.
        jax.config.update("jax_enable_x64", True)
    manifest_path = (None if args.report_dir == "off"
                     else str(Path(args.report_dir) / "manifest.jsonl"))
    # Multi-tenant front door: named tenants get declared QoS policies
    # and the plan (or the adversarial drill schedule) carries tenant
    # identity on every submit. --tenants 0 keeps the exact pre-tenancy
    # single-caller surface.
    tenant_names = []
    tenancy_kw = {}
    if args.adversary:
        if args.tenants < 2:
            raise SystemExit("--adversary needs --tenants >= 2 "
                             "(victim + abuser; extras are bystanders)")
        if args.replicas > 1:
            raise SystemExit(
                "--adversary needs --replicas 1: the drill's token/"
                "budget arithmetic is per-replica, and the federated "
                "fairness path is covered by the '-m chaos' tenancy "
                "tests (tests/test_tenancy.py)")
        bystanders = [f"tenant-{i}" for i in range(2, args.tenants)]
        tenants_cfg = {"alice": {"weight": 4.0}}
        if args.adversary == "deadline_abuse":
            # The abuser's hour-long deadline promises blow its 10%
            # share of the deadline budget immediately.
            tenants_cfg["mallory"] = {"budget_share": 0.1}
            tenancy_kw["max_deadline_budget_s"] = 120.0
        else:
            tenants_cfg["mallory"] = {"rate": 0.5, "burst": 2.0}
        for name in bystanders:
            tenants_cfg[name] = {"weight": 1.0}
        tenancy_kw["tenants"] = tenants_cfg
        tenancy_kw["queue_ordering"] = "edf"
    elif args.tenants > 0:
        tenant_names = [f"tenant-{i}" for i in range(args.tenants)]
        tenancy_kw["tenants"] = {t: {"weight": 1.0} for t in tenant_names}
    cfg = ServeConfig(buckets=buckets, solver=SVDConfig(),
                      max_queue_depth=args.queue_depth,
                      manifest_path=manifest_path,
                      max_batch=max(1, args.max_batch),
                      batch_window_s=max(0.0, args.batch_window_ms) / 1e3,
                      lanes=max(1, args.lanes),
                      journal_path=args.journal,
                      compile_cache_dir=args.compile_cache,
                      **tenancy_kw)
    replicas = max(1, args.replicas)
    http_servers = []      # --transport=http: in-process replica servers
    http_proxies = []      # --net-chaos: fault proxies on the wire
    if replicas > 1:
        # Federated mode: N in-process service replicas behind the
        # consistent-hash router, each with its OWN journal under the
        # state dir (an explicit --journal names a single-replica path
        # and would be a shared-journal hazard — the router derives
        # per-replica paths instead).
        import tempfile
        if args.drill_resume:
            raise SystemExit("--replicas > 1 is incompatible with the "
                             "restart-drill resume phase (each replica "
                             "recovers its own journal at boot)")
        if args.journal:
            raise SystemExit(
                "--journal names ONE journal path, but every replica "
                "needs its own (shared paths are refused by the "
                "journal's exclusivity lock) — with --replicas > 1 the "
                "router derives per-replica journals under "
                "<report-dir>/router-state/replica-<i>/ instead")
        from svd_jacobi_tpu.serve import ReplicaRouter, RouterConfig
        state_dir = (Path(args.report_dir) / "router-state"
                     if args.report_dir != "off"
                     else Path(tempfile.mkdtemp(prefix="svdj-router-")))
        rcfg = RouterConfig(replicas=replicas, serve=cfg,
                            state_dir=str(state_dir),
                            manifest_path=manifest_path)
        if args.transport == "http":
            # Federation over the wire: every replica is a live
            # in-process HTTP server (its own journal + fence token
            # under the state dir) and the router only ever talks to it
            # through `HttpReplica` RPCs — optionally through the
            # fault-injecting proxy (--net-chaos).
            import dataclasses as _dc
            from svd_jacobi_tpu.serve.transport import (HttpReplica,
                                                        HttpReplicaServer)
            handles = []
            for i in range(replicas):
                rdir = Path(state_dir) / f"replica-{i}"
                rc = _dc.replace(
                    cfg, journal_path=str(rdir / "journal.jsonl"),
                    compute_digest=True, manifest_path=manifest_path)
                server = HttpReplicaServer(rc).start()
                http_servers.append(server)
                addr = server.address
                if args.net_chaos:
                    from svd_jacobi_tpu.resilience.netfault import \
                        FaultyProxy
                    proxy = FaultyProxy(addr).start()
                    proxy.arm("drop", shots=1)
                    proxy.arm("delay", shots=1, value=0.2)
                    http_proxies.append(proxy)
                    addr = proxy.address
                handles.append(HttpReplica(
                    i, addr, rc.journal_path,
                    manifest_path=manifest_path))
            svc = ReplicaRouter(rcfg, replicas=handles)
        else:
            if args.net_chaos:
                raise SystemExit("--net-chaos needs --transport=http "
                                 "(the fault proxy sits on the wire)")
            svc = ReplicaRouter(rcfg)
    else:
        if args.transport == "http" or args.net_chaos:
            raise SystemExit("--transport=http needs --replicas > 1 "
                             "(the wire protocol federates replicas)")
        svc = SVDService(cfg)

    if args.drill_resume:
        # Restart-drill phase 2 (spawned by `_restart_drill`): recover
        # the journal, serve every resumed request, report cold-start
        # latency — no fresh client load.
        t_proc = time.perf_counter()
        tickets = svc.recover()
        svc.start()
        if args.warmup:
            svc.warmup(timeout=600.0)
        first_s = None
        results = {}
        for rid, t in sorted(tickets.items()):
            res = t.result(timeout=600.0)
            if first_s is None:
                first_s = time.perf_counter() - t_proc
            results[rid] = (res.status.name if res.status is not None
                            else "ERROR")
        svc.stop(drain=True, timeout=60.0)
        cold = [r for r in svc.records() if r.get("kind") == "coldstart"]
        print(json.dumps({
            "resumed": len(results), "results": results,
            "cold_start_s": first_s,
            "coldstart": (None if not cold else {
                "fresh_compiles": cold[-1]["fresh_compiles"],
                "cache_hits": cold[-1]["cache_hits"],
                "total_s": cold[-1]["total_s"]}),
        }))
        return 0 if all(s in ("OK", "DEADLINE") for s in results.values()) \
            else 1

    if args.adversary:
        # Fairness drill: replay the seeded adversarial-tenant schedule
        # instead of the closed-loop plan (single replica, asserted
        # above) and judge the band from validated serve records.
        return _adversary_drill_run(args, svc, bucket_set[0], log)

    # Seeded request plan, built up front so the run is reproducible: a
    # shape drawn within a random bucket, plus the deadline class. A
    # draw from a "topk" bucket ALWAYS submits with top_k (a full
    # request never routes into a truncated bucket), so the mix
    # exercises all three workload families end to end.
    rng = np.random.default_rng(args.seed)
    bs = bucket_set
    plan = []
    for i in range(args.requests):
        b = bs[int(rng.integers(len(bs)))]
        m = int(rng.integers(max(2, b.m // 2), b.m + 1))
        n = int(rng.integers(max(1, min(m, b.n) // 2), min(m, b.n) + 1))
        top_k = (int(rng.integers(1, b.k + 1)) if b.kind == "topk"
                 else None)
        tight = bool(rng.random() < args.tight_frac)
        plan.append((m, n, b.dtype, tight, int(rng.integers(2 ** 31)),
                     top_k))

    outcomes = []
    out_lock = threading.Lock()
    next_i = [0]

    def client(cid):
        while True:
            with out_lock:
                if next_i[0] >= len(plan):
                    return
                i = next_i[0]
                next_i[0] += 1
            m, n, dtype, tight, seed, top_k = plan[i]
            tenant = (tenant_names[i % len(tenant_names)]
                      if tenant_names else None)
            a = matgen.random_dense(m, n, seed=seed, dtype=jnp.dtype(dtype))
            deadline = (args.tight_ms / 1e3) if tight else args.deadline_s
            try:
                t = svc.submit(a, deadline_s=deadline, top_k=top_k,
                               tenant=tenant)
            except AdmissionError as e:
                with out_lock:
                    outcomes.append({"i": i, "terminal": True, "tight": tight,
                                     "status": f"REJECTED_{e.reason.name}"})
                continue
            try:
                res = t.result(timeout=600.0)
                out = {"i": i, "terminal": True, "tight": tight,
                       "top_k": top_k,
                       "status": ("ERROR" if res.error else res.status.name),
                       "queue_wait_s": res.queue_wait_s,
                       "solve_time_s": res.solve_time_s,
                       "error": res.error}
            except TimeoutError:
                out = {"i": i, "terminal": False, "tight": tight,
                       "status": "HUNG"}
            with out_lock:
                outcomes.append(out)

    t0 = time.perf_counter()
    svc.start()
    warmup_s = None
    if args.warmup:
        t_w = time.perf_counter()
        svc.warmup(timeout=600.0)
        warmup_s = time.perf_counter() - t_w
    if args.drill_slow_ms > 0:
        # Restart-drill phase 1: slow every dispatch so the parent's
        # kill window (journaled but unfinalized requests exist) is wide.
        from svd_jacobi_tpu.resilience import chaos
        chaos.slow_solve(args.drill_slow_ms / 1e3, shots=10 ** 6).__enter__()
    threads = [threading.Thread(target=client, args=(c,), daemon=True)
               for c in range(max(1, args.clients))]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=900.0)
    health = svc.healthz()   # live snapshot, BEFORE the shutdown flips it
    svc.stop(drain=True, timeout=60.0)
    for server in http_servers:
        server.stop(drain=True, timeout=30.0)
    for proxy in http_proxies:
        proxy.stop()
    wall = time.perf_counter() - t0

    by_status = {}
    for o in outcomes:
        by_status[o["status"]] = by_status.get(o["status"], 0) + 1
    waits = sorted(o["queue_wait_s"] for o in outcomes
                   if o.get("queue_wait_s") is not None)
    solves = sorted(o["solve_time_s"] for o in outcomes
                    if o.get("solve_time_s") is not None)
    p50 = lambda xs: xs[len(xs) // 2] if xs else None
    summary = {
        "requests": len(plan),
        "outcomes": by_status,
        "terminal": sum(1 for o in outcomes if o["terminal"]),
        "errors": sum(1 for o in outcomes if o.get("error")),
        "queue_wait_p50_s": p50(waits),
        "solve_time_p50_s": p50(solves),
        "wall_s": wall,
        "health": health,
    }
    if args.topk_mix:
        summary["topk_requests"] = sum(1 for p in plan if p[5] is not None)
    if tenant_names:
        # Per-tenant SLO totals reconstructed from VALIDATED serve
        # records — the same offline path `cli.py metrics --slo` walks,
        # so the summary's numbers are the manifest's, not in-process
        # counters.
        from svd_jacobi_tpu.obs.registry import tenant_slo_from_records
        all_records = list(svc.records())
        if replicas > 1:
            for rep in svc.replicas:
                if hasattr(rep, "service"):     # local handles only
                    all_records += rep.service.records()
        summary["tenants"] = {
            t: _tenant_totals(snap)
            for t, snap in tenant_slo_from_records(all_records).items()}
    if replicas > 1:
        summary["replicas"] = replicas
        summary["rescues"] = svc.total_rescues
        summary["transport"] = args.transport
        if http_servers:
            # Per-replica net-discipline stats (retries, failovers,
            # quarantines — the same families the manifest records).
            summary["net"] = {r.index: dict(r.net_stats)
                              for r in svc.replicas}
        if http_proxies:
            summary["net_chaos"] = {
                "stats": [dict(p.stats) for p in http_proxies],
                "unconsumed": [p.unconsumed() for p in http_proxies]}
    if warmup_s is not None:
        summary["warmup_s"] = warmup_s
        all_records = list(svc.records())
        if replicas > 1:
            for rep in svc.replicas:
                if hasattr(rep, "service"):     # local handles only
                    all_records += rep.service.records()
        cold = [r for r in all_records if r.get("kind") == "coldstart"]
        if cold:
            summary["coldstart"] = {
                "fresh_compiles": cold[-1]["fresh_compiles"],
                "cache_hits": cold[-1]["cache_hits"],
                "total_s": cold[-1]["total_s"],
            }
    if lock_graph is not None:
        # CONC002: the run executed under instrumented locks — publish
        # the acquisition graph and fail loudly below on any cycle.
        cycle = lock_graph.find_cycle()
        summary["lock_graph"] = dict(lock_graph.summary(), cycle=cycle)
    if manifest_path:
        log(f"manifest: {manifest_path}")
    print(json.dumps(summary))
    if lock_graph is not None and summary["lock_graph"]["cycle"]:
        log("exit 1: lock acquisition graph has a cycle (potential "
            "deadlock):\n"
            + lock_graph.describe_cycle(summary["lock_graph"]["cycle"]))
        return 1
    ok = (summary["terminal"] == len(plan) and summary["errors"] == 0
          and len(outcomes) == len(plan))
    if ok and args.topk_mix:
        # The mix's acceptance: every request that was given a meetable
        # deadline must come back OK — a tall/top-k lane that quietly
        # degrades or stalls fails the demo loudly.
        bad = [o for o in outcomes
               if not o.get("tight") and o["status"] != "OK"]
        if bad:
            log(f"exit 1: {len(bad)} non-tight request(s) with status != "
                f"OK: {[o['status'] for o in bad]}")
            return 1
    if not ok:
        log("exit 1: non-terminal or errored requests "
            f"({len(plan) - summary['terminal']} non-terminal, "
            f"{summary['errors']} errors)")
    return 0 if ok else 1


def _tenant_totals(snap):
    """Collapse one tenant's per-bucket SLO snapshot to flat totals."""
    tot = {"served": 0, "ok": 0, "deadline_miss": 0, "error": 0,
           "shed": 0}
    for counts in snap["buckets"].values():
        for key in tot:
            tot[key] += int(counts.get(key, 0))
    return tot


def _adversary_drill_run(args, svc, bucket, log) -> int:
    """``serve-demo --tenants N --adversary MODE``: the fairness drill.

    Replays the seeded ``resilience.chaos.adversarial_tenant`` schedule
    (the SAME schedule the ``-m chaos`` tenancy tests replay for this
    seed) against the live service: victim "alice" (weight 4) plus N-2
    equal-weight bystanders submit alongside abuser "mallory", whose
    policy caps it per mode (token-bucket rate for flood/burst/resubmit,
    a 10% deadline-budget share under deadline_abuse). Submits are
    sequential — determinism lives in the token/budget arithmetic, not
    in sleeps — and the band is judged from VALIDATED serve records
    (`obs.registry.tenant_slo_from_records`), not in-process counters.

    Exit non-zero on any fairness-band violation: a victim or bystander
    submit not served OK, the abuser never shed, or a rejection landing
    on anyone but the abuser (or with the wrong reason)."""
    import jax.numpy as jnp

    from svd_jacobi_tpu.obs.registry import tenant_slo_from_records
    from svd_jacobi_tpu.resilience import chaos
    from svd_jacobi_tpu.serve import AdmissionError
    from svd_jacobi_tpu.utils import matgen

    n_victim = max(1, args.adversary_victims)
    events = chaos.adversarial_tenant(args.adversary, n_victim=n_victim,
                                      abuse_factor=4, seed=args.seed)
    bystanders = [f"tenant-{i}" for i in range(2, args.tenants)]

    def mat(seed):
        return matgen.random_dense(bucket.m, bucket.n, seed=seed,
                                   dtype=jnp.dtype(bucket.dtype))

    submits: dict = {}
    rejections = []
    errors = 0

    def fire(tenant, seed, deadline_s):
        nonlocal errors
        submits[tenant] = submits.get(tenant, 0) + 1
        try:
            t = svc.submit(mat(seed), tenant=tenant,
                           deadline_s=deadline_s)
        except AdmissionError as e:
            rejections.append({"tenant": tenant, "reason": e.reason.name})
            return
        res = t.result(timeout=600.0)
        if res.error:
            errors += 1

    t0 = time.perf_counter()
    svc.start()
    if args.warmup:
        svc.warmup(timeout=600.0)
    for ev in events:
        deadline = ev["deadline_s"]
        if args.adversary == "deadline_abuse" and ev["tenant"] == "alice":
            # Victim deadlines are generous-but-finite; the abuser's
            # hour-long promises are the attack.
            deadline = 60.0
        fire(ev["tenant"], ev["mat_seed"], deadline)
        if ev["tenant"] == "alice":
            # Bystander load rides alongside every victim submit, so
            # the band also proves innocent third parties stay whole.
            for bi, name in enumerate(bystanders):
                fire(name, 30_000 + 1_000 * bi + ev["mat_seed"] % 1_000,
                     deadline)
    health = svc.healthz()   # live snapshot, BEFORE the shutdown flips it
    svc.stop(drain=True, timeout=60.0)
    wall = time.perf_counter() - t0

    totals = {t: _tenant_totals(snap) for t, snap in
              tenant_slo_from_records(svc.records()).items()}
    expected_reason = ("DEADLINE_BUDGET"
                       if args.adversary == "deadline_abuse"
                       else "RATE_LIMITED")
    violations = []
    for name in ["alice"] + bystanders:
        tot = totals.get(name, {"ok": 0, "shed": 0})
        want = submits.get(name, 0)
        if tot["ok"] != want or tot["shed"] != 0:
            violations.append(
                f"{name}: ok={tot['ok']}/{want} shed={tot['shed']} — "
                "every victim/bystander submit must be served OK")
    if totals.get("mallory", {}).get("shed", 0) < 1:
        violations.append("mallory: never shed — the abuse was not "
                          "contained")
    bad = [r for r in rejections
           if r["tenant"] != "mallory" or r["reason"] != expected_reason]
    if bad:
        violations.append(f"unexpected rejections: {bad}")
    if errors:
        violations.append(f"{errors} errored request(s)")

    by_reason: dict = {}
    for r in rejections:
        key = f"{r['tenant']}:{r['reason']}"
        by_reason[key] = by_reason.get(key, 0) + 1
    print(json.dumps({
        "adversary": args.adversary,
        "seed": args.seed,
        "events": len(events),
        "submits": submits,
        "tenants": totals,
        "rejections": by_reason,
        "fairness_ok": not violations,
        "violations": violations,
        "wall_s": wall,
        "health_tenants": health.get("tenants"),
    }))
    if violations:
        log("exit 1: fairness band violated:\n  - "
            + "\n  - ".join(violations))
        return 1
    return 0


def _restart_drill(args) -> int:
    """``serve-demo --restart-drill``: the kill-and-restart acceptance
    drill. Phase 1 serves the request load in a CHILD process (slowed
    dispatches, durable journal, persistent compile cache); once the
    journal shows at least one finalized AND one still-unfinalized
    request, the child takes a real SIGKILL. Phase 2 restarts serve-demo
    in resume mode on the same journal/cache and reports cold-start
    latency and the resumed-request count. Exit non-zero if ANY
    journaled unfinalized request is not resumed-and-terminal — a lost
    request is the one unacceptable outcome."""
    import os
    import signal
    import subprocess
    import tempfile

    from svd_jacobi_tpu.serve import Journal

    def log(msg):
        print(msg, file=sys.stderr)

    workdir = tempfile.mkdtemp(prefix="svdj-drill-")
    journal = args.journal or os.path.join(workdir, "journal.jsonl")
    cache = args.compile_cache or os.path.join(workdir, "compile-cache")
    base = [sys.executable, "-m", "svd_jacobi_tpu.cli", "serve-demo",
            "--journal", journal, "--compile-cache", cache,
            "--seed", str(args.seed),
            "--queue-depth", str(max(args.queue_depth,
                                     args.drill_requests + 2)),
            "--report-dir", args.report_dir]
    if args.tuning_table:
        base += ["--tuning-table", args.tuning_table]
    for b in (args.bucket or ()):
        base += ["--bucket", b]
    phase1_cmd = base + ["--requests", str(args.drill_requests),
                         "--clients", "2", "--tight-frac", "0",
                         "--deadline-s", "600",
                         "--_drill-slow-ms", "250"]
    log(f"drill phase 1 (serve + SIGKILL): journal={journal}")
    child = subprocess.Popen(phase1_cmd, stdout=subprocess.DEVNULL,
                             stderr=subprocess.DEVNULL)
    killed = False
    deadline = time.monotonic() + 300.0
    # Incremental kill-window poll: admit lines carry the full base64
    # input payload (megabytes at real bucket sizes), so a full
    # Journal.scan() per 50 ms tick would be O(journal bytes x polls).
    # Read only the NEW bytes each tick, holding back the (possibly
    # half-flushed, in-flight) unterminated tail line in `buf` — each
    # journal byte is parsed at most once, and a torn tail is simply
    # not yet a line, never a quarantine.
    admitted: set = set()
    finalized: set = set()
    offset, buf = 0, b""
    try:
        while time.monotonic() < deadline and child.poll() is None:
            if Path(journal).exists():
                with open(journal, "rb") as jf:
                    jf.seek(offset)
                    chunk = jf.read()
                offset += len(chunk)
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    rid = rec.get("id")
                    if rid is None:
                        continue
                    if rec.get("kind") == "admit":
                        admitted.add(rid)
                    elif rec.get("kind") == "finalize":
                        finalized.add(rid)
                if finalized and admitted - finalized:
                    os.kill(child.pid, signal.SIGKILL)
                    killed = True
                    break
            time.sleep(0.05)
    finally:
        if child.poll() is None and not killed:
            child.kill()
    child.wait(timeout=30.0)
    if not killed:
        log("drill: never reached a kill window (finalized + pending "
            "requests) — nothing was proven")
        return 1
    st = Journal(journal).scan()
    debt = [r["id"] for r in st.unfinalized]
    log(f"drill: SIGKILL'd pid {child.pid} with "
        f"{len(st.finalized)} finalized / {len(debt)} unfinalized "
        f"({debt})")
    if not debt:
        # The worker finalized its remaining in-flight requests between
        # the poll that observed the kill window and the SIGKILL landing:
        # a resume with nothing to resume proves nothing, same as never
        # reaching a kill window.
        log("drill: kill landed after every request finalized — nothing "
            "was proven")
        return 1
    phase2_cmd = base + ["--_drill-resume", "--warmup"]
    out = subprocess.run(phase2_cmd, capture_output=True, text=True,
                         timeout=600.0)
    try:
        resumed = json.loads(out.stdout.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError):
        log(f"drill: resume phase produced no JSON "
            f"(rc={out.returncode}):\n{out.stderr[-2000:]}")
        return 1
    results = resumed.get("results", {})
    lost = sorted(set(debt) - set(results))
    summary = {
        "killed_pid": child.pid,
        "finalized_before_kill": len(st.finalized),
        "unfinalized_at_kill": debt,
        "resumed": len(results),
        "results": results,
        "lost": lost,
        "cold_start_s": resumed.get("cold_start_s"),
        "coldstart": resumed.get("coldstart"),
        "journal": journal,
        "cache": cache,
    }
    print(json.dumps(summary))
    if lost:
        log(f"exit 1: {len(lost)} journaled request(s) LOST across the "
            f"restart: {lost}")
        return 1
    if out.returncode != 0:
        log(f"exit 1: resume phase exited {out.returncode}")
        return 1
    log(f"drill OK: {len(results)} request(s) resumed exactly-once, "
        f"first result {summary['cold_start_s']:.2f}s after restart "
        f"(fresh compiles: "
        f"{(resumed.get('coldstart') or {}).get('fresh_compiles')})")
    return 0


def metrics_dump(argv) -> int:
    """`metrics` subcommand: render the flight recorder's view of a
    manifest OFFLINE — the Prometheus exposition (default), the SLO
    report (--slo), or one request's reconstructed span timeline
    (--timeline ID). Host-side work only: the registry/span modules are
    stdlib-only and the records are plain JSONL."""
    p = argparse.ArgumentParser(
        prog="svd-metrics",
        description="One-shot flight-recorder dump from a JSONL manifest "
                    "(obs.registry.registry_from_manifest).")
    p.add_argument("manifest", help="manifest file (JSONL)")
    p.add_argument("--slo", action="store_true",
                   help="render the SLO report instead of the Prometheus "
                        "exposition")
    p.add_argument("--slo-objective", type=float, default=0.99)
    p.add_argument("--timeline", default=None, metavar="REQUEST_ID",
                   help="render one request's span timeline "
                        "reconstructed from the manifest records")
    args = p.parse_args(argv)
    from svd_jacobi_tpu.obs import manifest as _manifest
    from svd_jacobi_tpu.obs import registry as _registry
    records = _manifest.load(args.manifest)
    if not records:
        print(f"{args.manifest}: empty manifest", file=sys.stderr)
        return 1
    if args.timeline is not None:
        from svd_jacobi_tpu.obs import spans as _spans
        events = _spans.timeline_from_manifest(records, args.timeline)
        if not events:
            print(f"{args.manifest}: no events for request "
                  f"{args.timeline!r}", file=sys.stderr)
            return 1
        t0 = events[0]["t_wall"]
        print(f"request {args.timeline} timeline ({len(events)} event(s), "
              f"reconstructed offline):")
        for ev in events:
            extra = " ".join(f"{k}={v}" for k, v in ev.items()
                             if k not in ("name", "t_wall") and v is not None)
            print(f"  +{(ev['t_wall'] - t0) * 1e3:9.2f}ms "
                  f"{ev['name']:<10}{(' ' + extra) if extra else ''}")
        return 0
    if args.slo:
        snap = _registry.slo_from_records(records,
                                          objective=args.slo_objective)
        if not snap["buckets"]:
            print(f"{args.manifest}: no 'serve' records to build an SLO "
                  f"report from", file=sys.stderr)
            return 1
        print(_registry.render_slo(snap))
        return 0
    sys.stdout.write(_registry.registry_from_manifest(records).render())
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "serve-demo":
        return serve_demo(argv[1:])
    if argv and argv[0] == "metrics":
        return metrics_dump(argv[1:])
    if argv and argv[0] == "tune":
        # `cli.py tune ...` — the measured-autotuner subcommand
        # (regenerates a tuning table; see `python -m svd_jacobi_tpu.tune`).
        from svd_jacobi_tpu.tune.__main__ import main as tune_main
        return tune_main(argv[1:])
    args = _parse_args(argv)

    import os

    import jax

    # Some TPU attachment plugins register themselves unconditionally and
    # ignore JAX_PLATFORMS from the environment; honor it through the config
    # API so `JAX_PLATFORMS=cpu python -m svd_jacobi_tpu.cli ...` (e.g. the
    # scripts/run_multihost.sh virtual-device smoke test) works everywhere.
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import jax.numpy as jnp
    import svd_jacobi_tpu as sj
    from svd_jacobi_tpu.utils import matgen, validation

    def log(msg):
        print(msg, file=sys.stderr)

    if args.tuning_table:
        from svd_jacobi_tpu import tune
        table = tune.set_active_table(args.tuning_table)
        log(f"tuning table: {table.table_id} ({table.sha256[:12]})")

    m = args.m if args.m is not None else args.n
    n = args.n
    if args.matrix == "triangular" and m != n:
        # Reject the invalid combination up front, before the warm-up
        # self-test spends a full solve.
        log("triangular input requires m == n; use --matrix dense")
        return 2
    if args.distributed and args.precondition == "double":
        # Knowable at parse time: a single-device-only mode (the mesh
        # solver would raise the same rejection mid-run).
        log("--precondition double is a single-device mode; "
            "not supported with --distributed")
        return 2
    if args.distributed and args.mixed_bulk == "on":
        log("--mixed-bulk on is a single-device mode; "
            "not supported with --distributed")
        return 2
    if (args.precondition in ("on", "double") or args.mixed_bulk == "on") \
            and (args.pair_solver in ("hybrid", "qr-svd", "gram-eigh")
                 or args.dtype == "float64"):
        # Also knowable at parse time: preconditioning / the mixed bulk
        # are Pallas-path features; these combinations resolve to the XLA
        # block solvers, which reject them mid-run (solver.svd) — fail
        # before the warm-up self-test spends a solve.
        log("--precondition on/double and --mixed-bulk on require the "
            "Pallas pair solver (auto/pallas, non-f64 dtype)")
        return 2
    if args.mixed_bulk == "on" and args.dtype == "bfloat16":
        log("--mixed-bulk on requires a float32 input")
        return 2
    if args.top_k is not None and args.top_k < 1:
        log("--top-k must be >= 1")
        return 2
    if args.top_k is not None and args.distributed:
        # The truncated lane is single-controller today (the sketch jits
        # are not mesh entries); fail at parse time like the other
        # single-device modes.
        log("--top-k is a single-device lane; not supported with "
            "--distributed")
        return 2
    if args.top_k is not None and (args.jobu == "all" or args.jobv == "all"):
        # AllVec promises a full (m, m)/(n, n) factor; a truncated solve
        # returns k columns by construction — reject instead of silently
        # dropping the documented SVD_OPTIONS mapping.
        log("--top-k returns truncated (m, K)/(n, K) factors; "
            "--jobu/--jobv all (AllVec) is not satisfiable — use 'some'")
        return 2
    dtype = jnp.dtype(args.dtype)
    tristate = {"auto": None, "on": True, "off": False}
    config = sj.SVDConfig(block_size=args.block_size, max_sweeps=args.max_sweeps,
                          tol=args.tol, pair_solver=args.pair_solver,
                          precondition=args.precondition,
                          mixed_bulk=tristate[args.mixed_bulk],
                          sigma_refine=tristate[args.sigma_refine],
                          oversample=args.oversample,
                          power_iters=args.power_iters)

    mesh = None
    ctx = None
    if args.distributed:
        # Multi-host bootstrap MUST run before anything touches the XLA
        # backend (jax.devices() below included): jax.distributed.initialize
        # raises "must be called before any JAX calls" otherwise, and the
        # program would silently degrade to independent single-host solves.
        from svd_jacobi_tpu.parallel import launch, sharded
        ctx = launch.initialize()
        if ctx.process_count > 1:
            log(f"process {ctx.process_index}/{ctx.process_count}, "
                f"{ctx.local_device_count} local / "
                f"{ctx.global_device_count} global devices")

    devices = jax.devices()
    log(f"devices: {devices}")

    if args.distributed:
        mesh = sharded.make_mesh()
        log(f"mesh: {mesh}")

    # Extra (schema-open) manifest fields + per-stage wall times. The
    # CLI-level job options ride in `extra` (they are driver surface, not
    # SVDConfig fields — the config hash stays comparable with bench runs).
    extra = {
        "matrix": args.matrix,
        "seed": args.seed,
        "distributed": bool(mesh),
        "jobu": args.jobu, "jobv": args.jobv,
    }
    if args.top_k:
        extra["top_k"] = int(args.top_k)
    if args.sanitized:
        extra["sanitized"] = True
    stages = []

    def san_ctx():
        """Fresh sanitizer context per solve region (self-test, warm-up,
        timed run) under --sanitized: NaN/Inf screening + the d2h transfer
        guard, the `-m sanitized` CI lane's configuration. A context per
        region (not one process-wide stack) so sanitizer state is restored
        even when the solve raises — which is exactly what the sanitizers
        are armed to do."""
        if not args.sanitized:
            return contextlib.nullcontext()
        from svd_jacobi_tpu.analysis.sanitize import sanitized
        return sanitized()

    if not args.no_selftest:
        t0 = time.perf_counter()
        with san_ctx():
            extra["self_test"] = _self_test(args, config, log)
        stages.append({"name": "self_test",
                       "time_s": time.perf_counter() - t0})

    if mesh is not None:
        # Generate directly into the mesh sharding: no host materializes the
        # full matrix (replaces the reference's root-rank generation +
        # scatter, main.cu:1548-1567).
        from svd_jacobi_tpu.parallel import launch
        a = launch.sharded_input(m, n, mesh, seed=args.seed, dtype=dtype,
                                 kind=args.matrix)
    elif args.matrix == "triangular":
        a = matgen.random_upper_triangular(n, seed=args.seed, dtype=dtype)
    else:
        a = matgen.random_dense(m, n, seed=args.seed, dtype=dtype)

    from svd_jacobi_tpu import obs

    # Compile outside the timed region (the reference's timing also excludes
    # setup; its warm-up test additionally pre-warms the CUDA context). With
    # --telemetry the warm-up also runs telemetered — the emission sites are
    # part of the jit cache key, so the timed run reuses this compilation.
    t0 = time.perf_counter()
    with san_ctx():
        with (obs.metrics.capture() if args.telemetry
              else contextlib.nullcontext([])):
            _force(tuple(_solve(a, args, config, mesh)[:3]))
    stages.append({"name": "warmup_compile",
                   "time_s": time.perf_counter() - t0})

    profile_ctx = (obs.trace(args.profile) if args.profile
                   else contextlib.nullcontext())
    with profile_ctx, san_ctx():
        with (obs.metrics.capture() if args.telemetry
              else contextlib.nullcontext([])) as events:
            # Timed region innermost: trace start/stop (stop serializes
            # the trace to disk) and the capture-exit flush barrier must
            # not inflate the reported solve time.
            t0 = time.perf_counter()
            r = _solve(a, args, config, mesh)
            _force(tuple(r[:3]))
            solve_time = time.perf_counter() - t0
    stages.append({"name": "solve", "time_s": solve_time})
    if args.profile:
        extra["profile_dir"] = args.profile

    from svd_jacobi_tpu.solver import SolveStatus  # noqa: F401 (decode)
    status_name = r.status_enum().name
    if args.top_k:
        # Truncated solve: the full-reconstruction residual equals the
        # DISCARDED tail energy, so it is not a correctness metric here.
        # Report the per-vector subspace residual ||A v_i - s_i u_i||
        # instead (zero for exact top-k factors), plus factor
        # orthogonality — the truncated lane's accuracy surface.
        solve = {
            "time_s": solve_time,
            "sweeps": int(r.sweeps),
            "off_norm": float(r.off_rel),
            "status": status_name,
            "residual_rel": None,
            "k": int(args.top_k),
            "u_orth": (float(validation.orthogonality_error(r.u))
                       if r.u is not None else None),
            "v_orth": (float(validation.orthogonality_error(r.v))
                       if r.v is not None else None),
        }
        if r.u is not None and r.v is not None:
            an = np.asarray(a, np.float64)
            un, sn, vn = (np.asarray(r.u, np.float64),
                          np.asarray(r.s, np.float64),
                          np.asarray(r.v, np.float64))
            solve["topk_subspace_residual"] = float(
                np.linalg.norm(an @ vn - un * sn[None, :])
                / max(np.linalg.norm(an), 1e-300))
        log(f"solve {m}x{n} top-{args.top_k}: time={solve_time:.3f}s "
            f"sweeps={int(r.sweeps)} status={status_name}")
    else:
        rep = validation.validate(a, r).as_dict()
        solve = {
            "time_s": solve_time,
            "sweeps": int(r.sweeps),
            "off_norm": float(r.off_rel),
            # The in-graph health word: anything but "OK" makes this run
            # exit non-zero (a NaN-poisoned or non-converged solve must
            # not look like a success to the harness driving this CLI).
            "status": status_name,
            # None where the job options suppressed a factor (e.g.
            # sigma-only); jobu/jobv themselves ride at manifest top
            # level with the other CLI-surface options.
            "residual_rel": rep["residual_rel"],
            "u_orth": rep["u_orth"],
            "u_orth_live": rep["u_orth_live"],
            "v_orth": rep["v_orth"],
        }
        res_str = ("n/a (factor suppressed)" if rep["residual_rel"] is None
                   else f"{rep['residual_rel']:.3e}")
        log(f"solve {m}x{n}: time={solve_time:.3f}s sweeps={int(r.sweeps)} "
            f"residual={res_str} status={status_name}")

    multiproc = ctx is not None and ctx.process_count > 1
    if args.oracle:
        if multiproc:
            # The global matrix is not fully addressable on any one process;
            # np.asarray(a) would raise. (Gatherable via multihost_utils, but
            # the host oracle at pod scale is not meaningful anyway.)
            log("--oracle skipped: not supported with multi-process runs")
        else:
            s_ref = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)
            if args.top_k:
                s_ref = s_ref[:int(args.top_k)]
            solve["sigma_err"] = float(validation.sigma_error(r.s, s_ref))
            log(f"sigma_err vs numpy: {solve['sigma_err']:.3e}")

    # Run manifest — schema-versioned JSONL successor of the reference's
    # `reporte-dimension-<N>-time-<timestamp>.txt` (main.cu:1667-1669) and
    # of this driver's own timestamped report-dimension-*.json dumps.
    # Only the coordinator writes (every process would race on the same
    # file otherwise); all processes still print their solve line.
    record = obs.manifest.build(
        "cli", m=m, n=n, dtype=args.dtype, config=config, solve=solve,
        stages=stages, telemetry=(list(events) if args.telemetry else None),
        **extra)
    if ctx is None or ctx.is_coordinator:
        path = obs.manifest.append(
            Path(args.report_dir) / "manifest.jsonl", record)
        log(f"manifest: {path}")

    # --profile runs additionally emit the roofline observatory's "perf"
    # record: the capture just serialized is joined with the analytic
    # cost model through the SAME obs.perf.build_report code path the
    # offline `python -m svd_jacobi_tpu.perf report` uses, so the table
    # printed here and the one rebuilt later from the manifest + trace
    # are equal by construction. Best-effort: a capture without device
    # events (profiler unavailable) must not fail the solve run.
    if args.profile and (ctx is None or ctx.is_coordinator):
        from svd_jacobi_tpu.obs import perf as obs_perf
        try:
            workload = {
                "m": m, "n": n, "dtype": args.dtype,
                "block_size": config.block_size,
                "pair_solver": config.pair_solver,
                "sweeps": float(r.sweeps),
                "compute_u": args.jobu != "none",
                "compute_v": args.jobv != "none",
                "top_k": int(args.top_k) if args.top_k else None,
                "oversample": config.oversample,
                "power_iters": config.power_iters,
            }
            device = obs_perf.device_block(devices[0].device_kind)
            perf_record = obs_perf.build_report(
                args.profile, workload, device, source="cli")
            perf_path = obs.manifest.append(
                Path(args.report_dir) / "manifest.jsonl", perf_record)
            log(obs_perf.render_report(perf_record))
            log(f"perf manifest: {perf_path}")
        except Exception as e:
            log(f"perf attribution skipped: {e}")
    print(json.dumps(solve))
    # Exit code carries solve health (the reference exits 0 no matter
    # what): non-zero when the warm-up self-test missed its tolerance or
    # the timed solve's status is anything but OK.
    selftest_ok = bool(extra.get("self_test", {"ok": True}).get("ok", True))
    if not selftest_ok:
        log("exit 1: warm-up self-test exceeded tolerance")
        return 1
    if status_name != "OK":
        log(f"exit 1: solve status {status_name}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
