"""Solver configuration.

TPU-native replacement for the reference's scattered compile-time constants
(reference: lib/global.cuh:9 TOLERANCE, lib/JacobiMethods.cu:234 maxIterations,
lib/JacobiMethods.cu:200 threadsPerBlock, main.cu:1431 36-thread pin) — one
dataclass surfaced through every public entry point and the CLI.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class SVDConfig:
    """Static configuration for the one-sided block-Jacobi SVD solver.

    Attributes:
      block_size: width ``b`` of a column block. Columns are padded to
        ``2k*b`` and grouped into ``2k`` blocks; each sweep runs ``2k-1``
        tournament rounds of ``k`` disjoint block pairs. ``None`` picks a
        TPU-friendly width automatically (multiple of 128 when n is large).
      max_sweeps: hard cap on Jacobi sweeps. The reference hard-codes a single
        sweep and ignores its own convergence estimate
        (lib/JacobiMethods.cu:234,462); we instead iterate to convergence.
      tol: convergence threshold on the scaled coupling
        ``max_{i<j} |a_i . a_j| / (|a_i| |a_j|)`` over every column pair met
        in a sweep (the dgesvj criterion; numerically-null columns are
        deflated from the statistic). ``None`` -> ``sqrt(m) * eps`` of the
        input dtype, the roundoff floor of an m-term dot product.
      gram_dtype: dtype in which Gram matrices / rotations are *computed*
        (storage dtype is taken from the input array). E.g. keep A in
        bfloat16 but accumulate Gram products in float32.
      matmul_precision: JAX precision for the Gram/update matmuls
        ("highest" | "high" | "default"). On TPU "default" f32 matmuls go
        through bf16 passes; "highest" keeps full f32.
    """

    block_size: Optional[int] = None
    max_sweeps: int = 32
    tol: Optional[float] = None
    # "auto": the Pallas device-kernel path ("pallas") for f32/bf16 inputs
    # that are large enough to block (the TPU fast path; runs under the
    # Pallas interpreter on CPU), qr-svd for f64 (gesvj-class high relative
    # accuracy) and for tiny inputs; the tuning tables may route eligible
    # classes to "block_rotation" (the MXU-native blocked-rotation lane:
    # eigh-accumulated bulk rounds + kernel polish, ops/block_rotate.py) or
    # "resident" (the VMEM-resident grouped-round lane: R tournament
    # rounds' factors solved against a carried Gram and applied per panel
    # visit, ops/pallas_resident.py — ~R x less sweep HBM traffic).
    pair_solver: str = "auto"  # "auto" | "pallas" | "block_rotation" |
    #                            "resident" | "qr-svd" | "gram-eigh" |
    #                            "hybrid"
    # Residency depth R of the "resident" lane: how many consecutive
    # tournament rounds are solved against the carried Gram and applied
    # in ONE VMEM visit of the panel stacks. Larger R amortizes more HBM
    # traffic (the apply bytes scale ~1/R) but holds R*k (2b)^2 rotation
    # factors resident, shrinking the usable row chunk. None = tuning
    # table, falling back to ops.pallas_resident.DEFAULT_ROUNDS; clamped
    # to the sweep's 2k-1 rounds.
    rounds_resident: Optional[int] = None
    # --- Pallas-path options (pair_solver="pallas") ---
    # QR preconditioning: norm-sort columns, factor A P = Q1 R, run Jacobi
    # on L = R^T (Drmac-style: graded triangular factors converge in ~25%
    # fewer sweeps), then U = Q1 V_L, V = P U_L. "double" adds dgejsv's
    # second QR (of R^T) and runs Jacobi on R2^T — fewer sweeps again on
    # graded spectra, at the price of the extra n^3-scale QR (worthwhile
    # only when it saves >= 2 sweeps; measured NOT worthwhile on random
    # input, see PROFILE.md). "auto" = "on".
    precondition: str = "auto"  # "auto" | "on" | "off" | "double"
    # One in-kernel Newton-Schulz step on each accumulated rotation Q
    # (restores orthogonality to the f32 floor; protects the residual over
    # hundreds of applied rotations for ~5% kernel cost).
    kernel_polish: bool = True
    # bf16 Gram panels for the bulk phase (angles/stats only; applies stay
    # f32). None = auto (currently OFF at every size: the noisier angles
    # cost ~2 extra sweeps, which outweighs the cheaper grams — measured at
    # 2048^2: 0.22 s / 13 sweeps with vs 0.21 s / 11 without; same shape of
    # result at 8192^2). Kept as an option for bandwidth-starved setups.
    # Single-chip path only; the sharded solve runs full-precision grams.
    bulk_bf16: Optional[bool] = None
    # Mixed-precision bulk (the BASELINE.json north-star regime: "mixed
    # bf16 compute / fp32 accumulate", f32-class results). Three stages:
    #   1. bulk sweeps on bf16 copies of the stacks — Gram panels AND
    #      rotation applies run native bf16-in/f32-accumulate on the MXU
    #      (measured 138 vs 25 TF/s for the apply matmuls) — down to the
    #      bf16 drift floor (ops.rounds.MIXED_TOL);
    #   2. the accumulated rotation product G is re-orthogonalized in f32
    #      (Newton-Schulz) and the working matrix is RECONSTITUTED as
    #      X = L @ G at HIGHEST precision — this deletes the bf16 rounding
    #      drift between X and G, which is a backward error no amount of
    #      later polishing could remove;
    #   3. standard f32 sweeps polish to the f32 tolerance.
    # The accuracy contract is therefore the same f32 class as the pure-f32
    # path (residual/sigma set by stage 3's arithmetic; measured residual
    # is in fact ~2x BETTER — the reconstitution deletes the sweep loop's
    # accumulated drift). None = auto: currently OFF — on v5e the fused
    # apply kernel is HBM-traffic-bound, not FLOP-bound, so the cheaper
    # bulk arithmetic cannot pay for the bulk+polish sweep overhead
    # (measured at 2048/4096/8192; see PROFILE.md). The bulk stage always
    # accumulates G — it is the reconstitution map. Single-chip path only.
    mixed_bulk: Optional[bool] = None
    # Storage regime for the mixed bulk phase's block stacks. The fused
    # apply kernel is HBM-traffic-bound (~21 flops/byte vs the f32 ridge
    # ~30 — PROFILE.md item 12), so the lever is BYTES, not MXU passes:
    #   "f32"   — f32-stored stacks, bf16x3 split applies (the round-4
    #             regime: cheaper arithmetic, unchanged traffic);
    #   "bf16"  — the X stacks are STORED bf16 (halving the dominant X
    #             apply+gram traffic; X is discarded at reconstitution, so
    #             its storage rounding is absorbed by the tolerated
    #             MIXED_TOL drift) while the rotation product G stays
    #             f32-stored with x3 applies;
    #   "bf16g" — G stored bf16 as well (halving its traffic too); G's
    #             storage rounding random-walks ~1e-1 off orthogonal over a
    #             solve, paid back by two extra Newton-Schulz steps at
    #             reconstitution.
    # "auto" = "f32", the measured end-to-end best on v5e: the bf16 modes
    # make the bulk monotonically faster (4.19/3.51/2.76 s at 8192^2) but
    # each byte saved costs f32 polish sweeps (4/6/8) — storage rounding
    # degrades the reconstituted state (PROFILE.md item 17). The bf16
    # modes stay selectable for chips with a different cost structure.
    mixed_store: str = "auto"  # "auto" | "f32" | "bf16" | "bf16g"
    # Post-convergence sigma refinement: recompute the rotated columns
    # W = work @ V_norm (or work^T @ U) at HIGHEST against the solve's
    # WORKING matrix — the n x n QR triangle L on the preconditioned
    # paths (sigma(L) = sigma(A) to QR's tiny backward error; 2n^3 flops
    # instead of touching the m x n input), A itself otherwise — and read
    # sigma off compensated column norms. Removes the ~sqrt(m)*eps drift
    # the sweep loop accumulates (measured: sigma-err 1.2e-6 -> 1.2e-7 at
    # 2048^2 f32) for ~one small matmul. None = auto: ON whenever a
    # factor is computed (every solver path); False to skip.
    sigma_refine: Optional[bool] = None
    # Convergence criterion: "rel" = dgesvj scaled coupling (relative
    # accuracy even for tiny sigmas), "abs" = coupling / sigma_max^2
    # (LAPACK-dgesvd class). "auto" follows the pair solver.
    criterion: str = "auto"  # "auto" | "rel" | "abs"
    gram_dtype: Optional[str] = None
    matmul_precision: str = "highest"
    # Stop when an endgame sweep fails to keep shrinking the coupling
    # (roundoff floor reached; thresholds per criterion, see
    # solver._should_continue). Disable to run until tol or max_sweeps.
    stall_detection: bool = True
    # Donate the input buffer to the solve (XLA donation on the Pallas
    # path, m >= n): the caller's device array is CONSUMED — invalidated
    # after the call — freeing its n*m*4 bytes for the sweep loop's
    # working set. This is the difference between fitting and OOM at the
    # chip's largest sizes (30000^2 sigma-only needs it on 16 GB HBM).
    donate_input: bool = False
    # --- truncated / tall workload knobs (solver.svd_topk / svd_tall) ---
    # Randomized range-finder sketch width beyond k: the sketch solves a
    # (k + oversample)-wide projection; larger oversampling tightens the
    # Halko tail bound at O(mn) extra flops per column. None = tuning
    # table (generic 8).
    oversample: Optional[int] = None
    # TSQR-stabilized power iterations A (A^T Q(Y)) before the range
    # basis is taken: each iteration sharpens the sketch's spectral
    # separation ((s_{l+1}/s_k)^(2q+1)-class tail), needed for
    # spectral-decay-poor inputs. None = tuning table (generic 1).
    power_iters: Optional[int] = None
    # Rows per chunk of the blocked TSQR stages of svd_tall / svd_topk
    # (solver._tsqr_jit / _sketch_project_jit). None = tuning table
    # (generic: the sketch.default_chunk heuristic). NOTE: the Drmac
    # preconditioner inside the core (`solver._precondition_qr`) also
    # routes tall inputs through the chunked TSQR but always uses the
    # heuristic chunk — it is a config-free shared helper (its jit
    # signature is fixed), so this knob does not reach it.
    tsqr_chunk: Optional[int] = None
    # --- differentiable-solver knobs (svd_jacobi_tpu.grad) ---
    # Which AD rule attaches to svd/svd_topk/svd_tall:
    #   "auto"/"jvp" — one transposable jax.custom_jvp rule (the
    #                  F-matrix tangent is linear in the input tangent,
    #                  so JAX derives reverse mode by transposition):
    #                  both jax.jvp AND jax.grad work;
    #   "vjp"        — the explicit jax.custom_vjp pair (grad/rules.py
    #                  _svd_vjp), whose backward pass additionally zeroes
    #                  NON-FINITE cotangents (the grad-under-chaos guard
    #                  — nonlinear in the cotangent, which only a
    #                  custom_vjp may be). Reverse mode only: jax.jvp
    #                  raises JAX's standard custom_vjp error;
    #   "off"        — no rule (the historical opaque while_loop
    #                  failure; escape hatch for trace-sensitive
    #                  debugging).
    # Host-level routing only: never part of any jit key.
    grad_rule: str = "auto"  # "auto" | "jvp" | "vjp" | "off"
    # Degenerate-sigma classification band of the gradient safeguards:
    # a pair whose sigma^2 gap is <= rtol * sigma_max^2 is CLUSTERED and
    # its F-matrix term is masked to 0 (grad/fmatrix.py — finite
    # gradients on tied/clustered spectra; exact for cluster-invariant
    # losses). None = the per-dtype tuning-table row (f32 needs a wider
    # band than f64 — its sigma^2 gaps carry ~eps_f32 * sigma_max^2 of
    # solve noise), falling back to 8 * eps of the accumulation dtype.
    grad_degenerate_rtol: Optional[float] = None

    def pick_block_size(self, n: int, m: Optional[int] = None,
                        dtype=None) -> int:
        """Block width ``b`` for an (m, n) tall-oriented problem.

        Explicit ``block_size`` wins; otherwise the width resolves
        through the active tuning table (`tune.resolve` — the measured
        replacement for the old if-ladder, whose hand-picked values
        survive as the table machinery's generic fallback, so a missing
        or bypassed table reproduces the historical defaults exactly).
        ``m``/``dtype`` refine the lookup (aspect/dtype classes); omitted
        they default to square/float32 — the historical n-only behavior.
        """
        if self.block_size is not None:
            if self.block_size < 1:
                raise ValueError(f"block_size must be >= 1, got {self.block_size}")
            return self.block_size
        from .tune import tables as _tables
        return _tables.resolve(
            n, m=m, dtype="float32" if dtype is None else dtype).block_size


# ---------------------------------------------------------------------------
# Declared static-analysis contracts — the machine-checked invariants that
# `svd_jacobi_tpu.analysis` enforces against the REAL compiled artifacts
# (jaxprs / lowered StableHLO), not source text. They live here, next to the
# solver configuration they constrain, so a solver change that moves a
# boundary has to move the declaration in the same review.

# Float-to-float conversions the solver is ALLOWED to introduce beyond the
# working dtype's accumulation width. The accumulation contract is
# promote_types(input_dtype, float32) — bf16 inputs accumulate Gram panels /
# rotations / postprocessing in f32 (SVDConfig.gram_dtype's default), which
# is the single declared mixed-precision boundary. Anything ELSE that widens
# a float (e.g. a silent f32 -> f64 upcast sneaking into an f32 solve — the
# classic accuracy-story-destroying bug in Jacobi codes) is a contract
# violation flagged by analysis.jaxpr_checks.check_dtype_boundaries.
MIXED_PRECISION_BOUNDARIES = frozenset({
    ("bfloat16", "float32"),
    ("float16", "float32"),
})

# Collective budget of the sharded round loop, counted on the LOWERED
# StableHLO module of `parallel.sharded._svd_sharded_jit` (the shard_map
# sweep body appears exactly once in the module — scan/while bodies are not
# unrolled — so a static op count IS the per-sweep budget). Counts are per
# probe entry (see analysis.entries):
#   * collective_permute: the tournament ring exchange — 2 hops (one block
#     right, one left) per stack; the V stacks double it when a factor is
#     accumulated. The reference moved O(n) columns through rank 0 per
#     round (lib/JacobiMethods.cu:334-432); 2 hops/stack/round is the
#     floor, and any regression above it re-introduces transport cost.
#   * all_reduce: the pmax'd convergence machinery — per sweep-loop body:
#     dmax2 (1) + sweep-end off-norm (1), plus the kernel path's round-skip
#     gates (self round 1 + cross round 1; the XLA block solvers have no
#     skip gate). The hybrid XLA path carries two phase loops (bulk +
#     polish), so its static per-loop counts appear twice. The in-graph
#     HEALTH WORD (resilience PR: the while-carry nonfinite flag decoded
#     into SVDResult.status) adds NO collectives by construction — it is
#     `isfinite` of the already-pmax'd dmax2/off-norm scalars, so the
#     counts below are unchanged from the pre-health derivation.
#   * all_gather / all_to_all / reduce_scatter: the sweep loop must never
#     materialize a gathered matrix — budget zero, always.
# analysis.hlo_checks.check_collective_budget asserts EXACT equality so a
# new collective cannot ride in silently.
COLLECTIVE_BUDGET = {
    # The single-device BATCHED entry (solver._svd_pallas_batched, the
    # serving layer's coalesced-dispatch lane): stacking B matrices along
    # the pair axis is pure data layout — it must introduce NO collectives
    # of any kind (a collective sneaking into the batched sweep loop would
    # mean the block-diagonal schedule leaked across members). Asserted on
    # the lowered module like the mesh budgets.
    "pallas_batched": {"collective_permute": 0, "all_reduce": 0,
                       "all_gather": 0, "all_to_all": 0,
                       "reduce_scatter": 0},
    # The single-device blocked-rotation entry (solver._svd_block_rotation
    # — the MXU-native accumulate-into-J + rank-2b-GEMM lane): its bulk
    # and polish phase loops are single-device matmul/eigh/kernel chains;
    # a collective of any kind appearing here would mean mesh machinery
    # leaked into the fused lane. Asserted on the lowered module like the
    # batched entry.
    "pallas_block_rotation": {"collective_permute": 0, "all_reduce": 0,
                              "all_gather": 0, "all_to_all": 0,
                              "reduce_scatter": 0},
    # The single-device VMEM-resident entry (solver._svd_resident — the
    # grouped-round lane: R rounds' factors solved against the carried
    # Gram, applied in one panel visit): like the block-rotation lane, a
    # single-device kernel/matmul chain — zero collectives of any kind,
    # always.
    "pallas_resident": {"collective_permute": 0, "all_reduce": 0,
                        "all_gather": 0, "all_to_all": 0,
                        "reduce_scatter": 0},
    # The sketch/TSQR stage jits of the top-k and tall lanes
    # (solver._sketch_project_jit / _tsqr_jit): single-device matmul/QR
    # chains — zero collectives of any kind, always (on a mesh the
    # chunked-QR communication is GSPMD-inserted OUTSIDE these fused
    # entries, never hand-written into them).
    "sketch_project": {"collective_permute": 0, "all_reduce": 0,
                       "all_gather": 0, "all_to_all": 0,
                       "reduce_scatter": 0},
    "tsqr_tall": {"collective_permute": 0, "all_reduce": 0,
                  "all_gather": 0, "all_to_all": 0, "reduce_scatter": 0},
    "sharded_pallas": {"collective_permute": 4, "all_reduce": 4,
                       "all_gather": 0, "all_to_all": 0, "reduce_scatter": 0},
    "sharded_pallas_novec": {"collective_permute": 2, "all_reduce": 4,
                             "all_gather": 0, "all_to_all": 0,
                             "reduce_scatter": 0},
    "sharded_hybrid": {"collective_permute": 8, "all_reduce": 4,
                       "all_gather": 0, "all_to_all": 0, "reduce_scatter": 0},
    # Tall (m >= 8n) mesh solve: the chunked-TSQR preconditioner runs
    # under GSPMD outside the shard_map sweep loop, where the lowered
    # StableHLO carries sharding annotations but no explicit collectives
    # — so the tall entry's budget equals the square one's (the ring
    # exchange + pmax'd convergence machinery, nothing else). A
    # collective appearing here would mean the QR tree leaked INTO the
    # fused loop.
    "sharded_pallas_tall": {"collective_permute": 4, "all_reduce": 4,
                            "all_gather": 0, "all_to_all": 0,
                            "reduce_scatter": 0},
}

# Compilation budget per fused entry point: how many times an entry may
# compile per DISTINCT problem key (shape x dtype x static config). 1 means
# "a repeated solve of the same problem never retraces" — the invariant the
# Brent-Luk schedule leaking into the jit key would break (a retrace per
# sweep turns a 2 s solve into minutes). Enforced by
# analysis.recompile_guard.RecompileGuard over a multi-size sequence.
RETRACE_BUDGETS = {
    "solver._svd_padded": 1,
    "solver._svd_pallas": 1,
    "solver._svd_pallas_donated": 1,
    # Blocked-rotation lane (pair_solver="block_rotation"): the fused
    # entries and the host-stepped bulk-sweep twins. Same once-per-
    # problem-key contract as the pallas lane; a block_rotation bucket
    # legitimately counts TWO sweep-entry problems (its bulk entry here
    # plus the shared pallas polish entry), which the serve registry
    # enumerates.
    "solver._svd_block_rotation": 1,
    "solver._svd_block_rotation_donated": 1,
    "solver._svd_block_rotation_batched": 1,
    "solver._sweep_step_block_jit": 1,
    "solver._sweep_step_block_batched_jit": 1,
    # VMEM-resident lane (pair_solver="resident"): the fused entries and
    # the host-stepped bulk-sweep twins. Same once-per-problem-key
    # contract as the block-rotation lane (the resident bucket also
    # counts the shared pallas polish entry, which the serve registry
    # enumerates); the residency depth r_rounds is a STATIC tuning-table
    # value per bucket, so it cannot leak per-request retraces.
    "solver._svd_resident": 1,
    "solver._svd_resident_donated": 1,
    "solver._svd_resident_batched": 1,
    "solver._sweep_step_resident_jit": 1,
    "solver._sweep_step_resident_batched_jit": 1,
    "sharded._svd_sharded_jit": 1,
    # Serving-layer entries — the host-stepped kernel sweeps that
    # `serve.SVDService` drives. Every request is padded to one of the
    # declared (m, n, dtype) buckets BEFORE the stepper is built, so the
    # problem key is the bucket, not the request: these entries must
    # compile once per BUCKET and never per request (the invariant
    # analysis.recompile_guard.run_serve_sequence proves — a per-request
    # retrace would put a multi-second compile on the serving hot path).
    "solver._precondition_qr_jit": 1,
    "solver._sweep_step_pallas_jit": 1,
    "solver._finish_pallas_jit": 1,
    "solver._nonfinite_probe_jit": 1,
    # The XLA-block-solver stepper twins (tiny-n / f64 serving buckets
    # resolve to the hybrid method, whose host-stepped sweeps run these
    # instead of the Pallas kernels). Budget 1 per distinct problem key —
    # the hybrid's bulk and polish stages are DISTINCT static keys
    # (method/criterion), so a hybrid bucket legitimately counts two
    # problems for the sweep entry, which the serve registry enumerates
    # (serve.registry / analysis pass AOT001).
    "solver._sweep_step_jit": 1,
    "solver._finish_jit": 1,
    # Batched (coalesced-dispatch) lane: the fused entry and the stepper
    # entries `serve.SVDService` drives when max_batch > 1. The problem
    # key is (bucket x batch TIER) — batch sizes snap to the small static
    # `ServeConfig.batch_tiers` set with zero-padded tail slots, so the
    # compile cache stays bounded at |buckets| x |tiers| x variants and a
    # request-count leak into any jit key blows the budget immediately
    # (analysis.recompile_guard.run_serve_sequence's batched case).
    "solver._svd_pallas_batched": 1,
    "solver._svd_padded_batched": 1,
    "solver._precondition_qr_batched_jit": 1,
    "solver._sweep_step_pallas_batched_jit": 1,
    "solver._sweep_step_xla_batched_jit": 1,
    "solver._finish_pallas_batched_jit": 1,
    "solver._finish_xla_batched_jit": 1,
    "solver._nonfinite_probe_batched_jit": 1,
    # Top-k / tall lane stages (ops/sketch.py wrapped by solver): the
    # sketch width l, power-iteration count, TSQR chunk and seed are all
    # static and BUCKET-derived in serving, so one compile per distinct
    # problem key — a request-k or per-request leak into any of these
    # keys blows the budget (analysis.recompile_guard.run_serve_rank_case
    # proves it over mixed-k request streams).
    "solver._tsqr_jit": 1,
    "solver._tsqr_batched_jit": 1,
    "solver._sketch_project_jit": 1,
    "solver._sketch_project_batched_jit": 1,
    "solver._lift_q_jit": 1,
    "solver._lift_q_batched_jit": 1,
    # Warm-start lane (solver.svd(v0=...) / svd_update): the pre-rotation
    # B = A @ V0 and the factor composition V = V0 @ W — one compile per
    # problem shape, never per update (a prior-factor leak into either
    # key would retrace every incremental solve).
    "solver._apply_v0_jit": 1,
    "solver._compose_v0_jit": 1,
    # Two-phase serving's sigma-first extraction (serve.SVDService
    # phase="sigma"): sigma read straight off the retained sweep state,
    # deferring the finish stage until promotion. Bucket-shaped like the
    # stepper entries — once per bucket, never per request
    # (analysis.recompile_guard.run_serve_promote_case proves it over
    # sigma-then-promote request streams).
    "solver._sigma_from_state_jit": 1,
    "solver._sigma_from_state_batched_jit": 1,
    # Differentiable-solver entries (svd_jacobi_tpu.grad.rules): the
    # jitted gradient math the custom VJP/JVP rules dispatch — the
    # F-matrix tangent/cotangent and the sigma-only fast path. The
    # degenerate-band rtol rides as a TRACED operand (never a static
    # arg), so the problem key is the factor shapes alone: one compile
    # per differentiated problem shape, never per knob value or per
    # training step (a per-step leak into any of these keys would put a
    # compile on every optimizer iteration). Enumerated by
    # serve.registry.jit_entries via grad.rules.jit_entries, and proven
    # budgeted by the GRAD001 analysis pass.
    "grad._svd_jvp_jit": 1,
    "grad._svd_vjp_jit": 1,
    "grad._sigma_jvp_jit": 1,
    "grad._sigma_vjp_jit": 1,
}

# Batch-size tiers of the serving layer's coalesced dispatch
# (`serve.ServeConfig.batch_tiers`): a popped same-bucket batch snaps UP to
# the smallest tier holding it (zero-padding the tail slots — exact for the
# SVD, an all-zero member deflates in one sweep), so the batched stepper
# entries compile once per (bucket, tier) instead of once per observed
# batch size. Small and static by design — every tier is a compile.
DEFAULT_BATCH_TIERS = (1, 4, 16)

# Default shape buckets of the serving layer (`serve.ServeConfig.buckets`):
# the small static set of tall (m >= n) padded shapes requests are rounded
# up to so the jit caches above are hit after one warmup per bucket.
# Zero-padding is exact for the SVD — padded columns deflate (exactly-zero
# sigma, sorted to the back) and padded rows are preserved zero by the
# column rotations — so factors of the original shape are recovered by
# slicing. Deployments declare their own set; these defaults cover the
# bench's small/medium regimes. Entries are (m, n, dtype-name).
DEFAULT_SERVE_BUCKETS = (
    (256, 256, "float32"),
    (1024, 512, "float32"),
    (2048, 2048, "float32"),
    # Tall bucket family (kind "tall", m >= 8n): dispatches through the
    # blocked-TSQR lane — chunked QR, Jacobi on the n x n triangle —
    # instead of padding a genuinely rectangular request to a square
    # bucket's full solve.
    (2048, 256, "float32", "tall"),
    # Top-k bucket family (kind "topk", k-classed): requests submitted
    # with top_k route here; the randomized range-finder solves a
    # (k + oversample)-wide projection. The bucket's k bounds the
    # admissible request k (the sketch width is BUCKET-static, so the
    # compile contract holds across request k values).
    (1024, 1024, "float32", "topk", 64),
)

# PROFILE.md hot-region coverage: every component row of the cost tables
# must keep its `jax.named_scope` annotation (obs.scopes) so profiler
# traces stay mappable to the measured numbers. scope name ->
# (module path relative to the package root, function that must contain
# `with scope("<name>")`). Enforced by analysis.ast_lint rule GRAFT005.
HOT_SCOPES = {
    "gram": ("ops/rounds.py", "self_round"),
    "rotations": ("ops/rounds.py", "_rotations"),
    "apply": ("ops/rounds.py", "self_round"),
    "apply_exchange": ("ops/rounds.py", "cross_round_fused"),
    "exchange": ("ops/rounds.py", "sweep"),
    "pair_solve": ("ops/blockwise.py", "orthogonalize_pairs"),
    "precondition_qr": ("solver.py", "_precondition_qr"),
    "reconstitute": ("solver.py", "_svd_pallas_impl"),
    "ns_orthogonalize": ("solver.py", "_ns_orthogonalize"),
    "postprocess": ("solver.py", "_postprocess"),
    "sigma_refine": ("solver.py", "_refine_from_work"),
    "recombine": ("solver.py", "_recombine_precondition"),
    # The in-graph health word's status decode (svdj/health): a handful of
    # scalar ops, but keeping it scoped proves in any profile that the
    # resilience layer costs ~nothing on the hot path (PROFILE.md).
    "health": ("solver.py", "_status_word"),
    # Top-k / tall lane stages: the chunked TSQR tree, the randomized
    # range-finder sketch, and the Q-basis factor lift — the three new
    # hot regions of the rectangular/truncated workload lanes.
    "tsqr": ("ops/sketch.py", "tsqr"),
    "sketch": ("ops/sketch.py", "sketch_project"),
    "lift": ("solver.py", "_lift_q"),
    # The blocked-rotation lane's subproblem solve (accumulate the inner
    # Jacobi cycle's rotations into one orthogonal factor J): the hot
    # region that replaces the latency-bound per-step rotation chain
    # during the bulk phase.
    "block_solve": ("ops/block_rotate.py", "accumulate"),
    # The VMEM-resident lane's two hot regions: solving a residency
    # group's R rounds of 2b x 2b factors against the carried Gram
    # (resident_solve) and the one fused panel visit that applies all R
    # rounds (resident_apply — the traffic the lane exists to collapse).
    "resident_solve": ("ops/pallas_resident.py", "group_factors"),
    "resident_apply": ("ops/pallas_resident.py", "apply_group"),
    # Differentiable-solver hot regions (svd_jacobi_tpu.grad): the
    # safeguarded F-matrix construction and the full/sigma-only
    # cotangent recombinations — the backward-pass cost a training-loop
    # profile must be able to attribute.
    "grad_fmatrix": ("grad/fmatrix.py", "fmatrix"),
    "grad_cotangent": ("grad/rules.py", "_svd_vjp"),
    "grad_sigma": ("grad/rules.py", "_sigma_vjp"),
}

# The serving stack's declared lock inventory and partial order
# ("graftlock", analysis.concurrency rule CONC001). Every
# `threading.Lock/RLock/Condition` the package constructs must appear
# here with a tier; a thread may only acquire a lock whose tier RANK is
# strictly greater than every lock it already holds (outermost first):
#
#     router -> service/fleet -> queue/journal -> cache/breaker -> obs
#
# The five tier groups above are refined into distinct ranks per lock
# family (LOCK_TIER_RANK) so same-group nesting — e.g. the service lock
# held across `Fleet.start` — still has a defined direction. Acquiring
# against the order (or nesting two locks of equal rank) is a CONC001
# finding unless the line carries a `# graftlock: ok(reason)` pragma;
# a lock constructed anywhere in the package without a row here fails
# the inventory-completeness half of CONC001, so a future lock cannot
# be added without declaring where it sits. Entries are
# name -> (module path relative to the package root, construction-site
# qualname — "Class.attr", a module-level variable, or "func.local" for
# a function-local — and the tier name).
LOCK_TIER_RANK = {
    "router": 10,     # federation front door (serve/router.py)
    "service": 20,    # service-wide state (serve/service.py)
    "fleet": 22,      # lane supervisor state (serve/fleet.py)
    "queue": 30,      # per-lane admission queues (serve/queue.py)
    "journal": 32,    # write-ahead journal appends/rewrite (serve/journal.py)
    "cache": 40,      # leaf stores: caches, breaker, ticket finalize
    "obs": 50,        # observability leaves: metrics, spans, manifest
}

LOCK_ORDER = {
    "router": ("serve/router.py", "ReplicaRouter._lock", "router"),
    "service": ("serve/service.py", "SVDService._lock", "service"),
    "fleet": ("serve/fleet.py", "Fleet._lock", "fleet"),
    "queue": ("serve/queue.py", "AdmissionQueue._cond", "queue"),
    "journal": ("serve/journal.py", "Journal._lock", "journal"),
    "ticket_finalize": ("serve/service.py", "Ticket._finalize_lock",
                        "cache"),
    "router_ticket": ("serve/router.py", "RouterTicket._lock", "cache"),
    # Multi-tenant QoS table: one per service, SHARED by every lane's
    # admission queue (rates/fairness are per-service promises). A leaf
    # by construction — acquired under a queue's condition (queue 30 ->
    # cache 40), never held across anything that blocks.
    "tenant_table": ("serve/queue.py", "TenantTable._lock", "cache"),
    "promotion_store": ("serve/cache.py", "PromotionStore._lock", "cache"),
    "result_cache": ("serve/cache.py", "ResultCache._lock", "cache"),
    "breaker": ("serve/breaker.py", "CircuitBreaker._lock", "cache"),
    "metrics_module": ("obs/metrics.py", "_lock", "obs"),
    "spans": ("obs/spans.py", "SpanRecorder._lock", "obs"),
    "registry_mutation": ("obs/registry.py", "_MUTATION_LOCK", "obs"),
    "registry": ("obs/registry.py", "MetricsRegistry._lock", "obs"),
    "slo": ("obs/registry.py", "SLOTracker._lock", "obs"),
    "manifest_guard": ("obs/manifest.py", "_APPEND_LOCKS_GUARD", "obs"),
    "manifest_path": ("obs/manifest.py", "_append_lock.lock", "obs"),
    "chaos": ("resilience/chaos.py", "_lock", "obs"),
    # HTTP transport bookkeeping: both sides are leaf-adjacent — the
    # server lock guards only the outstanding/result dicts (never held
    # across a service call or journal I/O), the client lock guards the
    # breaker/lease counters (never held across a network round trip).
    "transport_server": ("serve/transport.py",
                         "HttpReplicaServer._lock", "cache"),
    "transport_client": ("serve/transport.py", "HttpReplica._lock",
                         "cache"),
    # Fault-proxy counters: a pure leaf (armed-shot/stat bookkeeping).
    "netfault": ("resilience/netfault.py", "FaultyProxy._lock", "obs"),
    "cli_out": ("cli.py", "_serve_demo_run.out_lock", "obs"),
    # The CONC002 sanitizer's own edge-graph lock: a leaf by
    # construction (never held while acquiring anything else).
    "sanitizer_graph": ("analysis/concurrency/sanitizer.py",
                        "LockGraph._lock", "obs"),
}

# Roofline attribution join: every HOT_SCOPES profiler scope maps onto
# one canonical phase of `obs.costmodel.PHASES`, so a trace's per-scope
# durations can be divided by that phase's analytic FLOP/HBM-byte cost
# (obs.attribution.attribute). Total coverage — keys here must equal
# HOT_SCOPES' exactly — is enforced by the PERF001 analysis pass: a new
# hot scope without a phase assignment would silently fall into the
# model-less "other" bucket of every perf report.
SCOPE_PHASES = {
    "gram": "sweep.gram",
    "rotations": "sweep.rotations",
    "pair_solve": "sweep.rotations",
    "block_solve": "sweep.rotations",
    "resident_solve": "sweep.rotations",
    "resident_apply": "sweep.apply",
    "apply": "sweep.apply",
    "apply_exchange": "sweep.apply",
    "exchange": "sweep.exchange",
    "precondition_qr": "precondition",
    "tsqr": "precondition",
    "sketch": "sketch",
    "reconstitute": "finish",
    "ns_orthogonalize": "finish",
    "postprocess": "finish",
    "sigma_refine": "finish",
    "recombine": "finish",
    "lift": "finish",
    "health": "health",
    "grad_fmatrix": "grad",
    "grad_cotangent": "grad",
    "grad_sigma": "grad",
}
