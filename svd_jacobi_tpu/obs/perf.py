"""Perf observatory: roofline reports, the analytic model table, and
bench regression gating — the `python -m svd_jacobi_tpu.perf` entry.

Three subcommands:

  * ``report`` — join a `jax.profiler` capture (an ``.xplane.pb[.gz]``
    file or the log_dir holding one — PR 11 `XprofWindow` output and
    plain ``--profile`` traces both qualify) with the analytic cost
    model (obs.costmodel) into a per-scope roofline table, and
    optionally append the schema-versioned "perf" manifest record.
    Workload parameters come from a manifest record (a prior "perf"
    record, or any cli/bench solve record's dimension/dtype block) with
    CLI flags overriding.
  * ``model`` — print the analytic phase table (FLOPs, HBM bytes,
    arithmetic intensity, roofline ceiling) for a workload with no
    trace at all: the planning view.
  * ``check`` — load the BENCH_*.json history, fit a per-metric noise
    band from repeated rows, and exit non-zero when the candidate row
    regresses beyond it. `bench.py --check-against` runs the same gate
    in-process so a bench run can append and gate in one go.

Stdlib-only BY CONTRACT (the `registry_from_manifest` discipline): the
offline read side — `perf report` on a checked-in CPU trace + manifest,
`perf check` on the BENCH history — must run on a machine with no jax.
`scripts/telemetry_summary.py` loads this file by path, beside
costmodel.py / attribution.py / manifest.py under their bare names.
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import statistics
import sys
from typing import Dict, List, Optional, Tuple

try:
    from . import attribution, costmodel, manifest
except ImportError:                                   # file-path load
    import attribution  # type: ignore
    import costmodel  # type: ignore
    import manifest  # type: ignore


def load_scope_phases() -> Dict[str, str]:
    """`config.SCOPE_PHASES` through whichever door is open: the package
    (live), a sibling bare module (telemetry_summary's loader), or a
    direct file-path load of config.py (stdlib at module level) relative
    to this file — the fully offline case."""
    try:
        from ..config import SCOPE_PHASES
        return dict(SCOPE_PHASES)
    except ImportError:
        pass
    try:
        from config import SCOPE_PHASES  # type: ignore
        return dict(SCOPE_PHASES)
    except ImportError:
        pass
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        os.pardir, "config.py")
    spec = importlib.util.spec_from_file_location("_svdj_config", path)
    mod = importlib.util.module_from_spec(spec)
    assert spec.loader is not None
    # Registered BEFORE exec: config.py defines dataclasses, and the
    # dataclass machinery resolves field types through
    # sys.modules[cls.__module__].
    sys.modules.setdefault("_svdj_config", mod)
    spec.loader.exec_module(mod)
    return dict(mod.SCOPE_PHASES)


# --------------------------------------------------------------------------
# Workload / device blocks.
# --------------------------------------------------------------------------

def device_block(device_kind: str, *, peak_flops: Optional[float] = None,
                 hbm_bw: Optional[float] = None) -> dict:
    """The "perf" record's device block: roofline constants WITH
    provenance ("table" for a tabulated kind, "peak_est"/"bw_est" for
    the fallback estimate) so a roofline percentage can never silently
    rest on the CPU stand-in."""
    if peak_flops is None:
        peak, peak_est = costmodel.peak_flops(device_kind)
    else:
        peak, peak_est = float(peak_flops), False
    if hbm_bw is None:
        bw, bw_est = costmodel.hbm_bandwidth(device_kind)
    else:
        bw, bw_est = float(hbm_bw), False
    return {
        "device_kind": costmodel.normalize_device_kind(device_kind),
        "peak_flops": peak,
        "peak_flops_source": "peak_est" if peak_est else "table",
        "hbm_bw": bw,
        "hbm_bw_source": "bw_est" if bw_est else "table",
    }


def workload_from_record(record: dict) -> Optional[dict]:
    """Extract cost-model parameters from a manifest record: a "perf"
    record carries them verbatim; a cli/bench solve record yields them
    from its dimension/dtype/solve blocks. None if the record has
    neither shape."""
    if record.get("kind") == "perf" and isinstance(record.get("workload"),
                                                   dict):
        return dict(record["workload"])
    dim = record.get("dimension")
    if not isinstance(dim, dict) or "n" not in dim:
        return None
    solve = record.get("solve") or {}
    wl = {
        "m": int(dim.get("m", dim["n"])),
        "n": int(dim["n"]),
        "dtype": str(record.get("dtype") or "float32"),
    }
    if isinstance(solve.get("sweeps"), (int, float)):
        wl["sweeps"] = float(solve["sweeps"])
    return wl


def last_workload(manifest_path: str) -> Tuple[Optional[dict],
                                               Optional[str]]:
    """(workload, device_kind) from the LAST usable record of a manifest
    JSONL — latest wins, like `registry_from_manifest`."""
    wl = kind = None
    for rec in manifest.load(manifest_path):
        got = workload_from_record(rec)
        if got is not None:
            wl = got
            if rec.get("kind") == "perf":
                kind = (rec.get("device") or {}).get("device_kind")
            else:
                kind = (rec.get("environment") or {}).get("device_kind")
    return wl, kind


def phase_costs_for(workload: dict, *,
                    convention: str = "algorithm") -> Dict[str, object]:
    """The attribution join table for one workload dict (keys: m, n,
    and optionally dtype/block_size/pair_solver/sweeps/bulk_sweeps/
    compute_u/compute_v/mixed_store/top_k/oversample/power_iters)."""
    m, n = int(workload["m"]), int(workload["n"])
    kw = dict(convention=convention)
    for key in ("dtype", "pair_solver", "mixed_store", "oversample",
                "power_iters"):
        if workload.get(key) is not None:
            kw[key] = workload[key]
    for key in ("sweeps", "bulk_sweeps"):
        if workload.get(key) is not None:
            kw[key] = float(workload[key])
    for key in ("compute_u", "compute_v"):
        if workload.get(key) is not None:
            kw[key] = bool(workload[key])
    if workload.get("top_k") is not None:
        kw["top_k"] = int(workload["top_k"])
    kw["block_size"] = int(workload.get("block_size")
                           or costmodel.default_block_size(n))
    return costmodel.solve_costs(m, n, **kw)


def build_report(trace: str, workload: dict, device: dict, *,
                 convergence: Optional[dict] = None,
                 source: str = "trace") -> dict:
    """Parse a trace, join it with the cost model, and return the
    validated "perf" manifest record — the ONE code path behind both
    the live (``cli.py --profile``) and offline (``perf report``)
    tables, so offline-equals-live is true by construction."""
    attr = attribution.scope_durations(trace)
    rows = attribution.attribute(
        attr, phase_costs_for(workload),
        scope_phases=load_scope_phases(),
        peak_flops=device["peak_flops"], hbm_bw=device["hbm_bw"],
        estimated=(device.get("peak_flops_source") != "table"
                   or device.get("hbm_bw_source") != "table"))
    return manifest.build_perf(
        source=source, workload=dict(workload), device=dict(device),
        scopes=rows, unscoped_s=attr.unscoped_s,
        unattributed_s=attr.unattributed_s, convergence=convergence,
        trace=os.path.basename(attr.trace_path))


def render_report(record: dict) -> str:
    wl, dev = record.get("workload", {}), record.get("device", {})
    title = (f"per-scope roofline — {wl.get('m')}x{wl.get('n')} "
             f"{wl.get('dtype', 'float32')} on "
             f"{dev.get('device_kind', '?')} "
             f"(peak {dev.get('peak_flops', 0) / 1e9:.0f} GFLOP/s "
             f"[{dev.get('peak_flops_source', '?')}], bw "
             f"{dev.get('hbm_bw', 0) / 1e9:.0f} GB/s "
             f"[{dev.get('hbm_bw_source', '?')}])")
    out = attribution.render_table(
        record.get("scopes") or [],
        unscoped_s=record.get("unscoped_s", 0.0),
        unattributed_s=record.get("unattributed_s", 0.0), title=title)
    conv = record.get("convergence")
    if conv:
        curve = conv.get("off_rel") or []
        line = f"convergence [{conv.get('spectrum', '?')}]: "
        line += f"{len(curve)} sweep(s)"
        if curve:
            line += f", off_rel {curve[0]:.3e} -> {curve[-1]:.3e}"
        if conv.get("sweeps_to_tol") is not None:
            line += f", sweeps_to_tol={conv['sweeps_to_tol']}"
        if conv.get("rotations_skipped_frac") is not None:
            line += (f", rotations skipped "
                     f"{conv['rotations_skipped_frac']:.1%}")
        out += "\n" + line
    return out


# --------------------------------------------------------------------------
# Per-sweep convergence telemetry (tentpole part 3).
# --------------------------------------------------------------------------

class ConvergenceRecorder:
    """Per-sweep convergence series with ZERO extra device readback: it
    is fed the `off_rel` scalar the host-stepped sweep loop ALREADY
    pulls for its stopping decision (`SweepStepper.should_continue`),
    plus the rotations-skipped counts the fused path already emits as
    telemetry events. ``spectrum`` labels the series so "sweeps-to-tol
    per spectrum class" is a tracked series across runs."""

    def __init__(self, spectrum: str = "default") -> None:
        self.spectrum = spectrum
        self.off_rel: List[float] = []
        self.stages: List[str] = []
        self.rounds_rotated = 0
        self.rounds_total = 0

    def record(self, off_rel: float, stage: str = "bulk") -> None:
        self.off_rel.append(float(off_rel))
        self.stages.append(str(stage))

    def record_rounds(self, rotated: int, total: int) -> None:
        self.rounds_rotated += int(rotated)
        self.rounds_total += int(total)

    def sweeps_to_tol(self, tol: float) -> Optional[int]:
        """1-based index of the first sweep at or under ``tol`` (None:
        the curve never got there)."""
        for i, v in enumerate(self.off_rel):
            if v <= tol:
                return i + 1
        return None

    def block(self, *, tol: Optional[float] = None) -> Optional[dict]:
        """The "perf" record's convergence block (None if no sweeps were
        recorded — a fast path that never entered the host loop)."""
        if not self.off_rel:
            return None
        skipped = None
        if self.rounds_total > 0:
            skipped = 1.0 - self.rounds_rotated / self.rounds_total
        return {
            "spectrum": self.spectrum,
            "off_rel": list(self.off_rel),
            "stages": list(self.stages),
            "sweeps": len(self.off_rel),
            "tol": tol,
            "sweeps_to_tol": (self.sweeps_to_tol(tol)
                              if tol is not None else None),
            "rotations_skipped_frac": skipped,
        }


# --------------------------------------------------------------------------
# Bench regression gating (`perf check`).
# --------------------------------------------------------------------------

# A consecutive pair of history values this close counts as a REPEAT of
# the same configuration (noise), not an improvement step; the band is
# fit from repeats only, so a real 7x jump (r02 -> r03) never inflates it.
_REPEAT_REL = 0.20
# Band = max(_BAND_WIDEN x median repeat gap, _BAND_FLOOR), falling back
# to _BAND_DEFAULT when the history holds no repeated pair yet.
_BAND_WIDEN = 3.0
_BAND_FLOOR = 0.02
_BAND_DEFAULT = 0.05


def fit_noise_band(values: List[float], *,
                   repeat_rel: float = _REPEAT_REL) -> float:
    """Relative regression band for one metric, fit from its history:
    the median relative gap among consecutive repeated measurements,
    widened x3 and floored at 2% (default 5% when the history has no
    repeats to learn from)."""
    gaps = []
    for a, b in zip(values, values[1:]):
        if a > 0 and b > 0:
            rel = abs(b - a) / max(a, b)
            if rel <= repeat_rel:
                gaps.append(rel)
    if not gaps:
        return _BAND_DEFAULT
    return max(_BAND_WIDEN * statistics.median(gaps), _BAND_FLOOR)


def _bench_rows(path: str) -> List[dict]:
    """BENCH_*.json holds one round dict today; tolerate a list of them
    (a future consolidated history file) by flattening."""
    with open(path) as f:
        data = json.load(f)
    return data if isinstance(data, list) else [data]


def _metric_value(row: dict) -> Tuple[Optional[str], Optional[float]]:
    parsed = row.get("parsed") or {}
    metric = parsed.get("metric")
    value = parsed.get("value")
    return (metric, float(value) if isinstance(value, (int, float))
            else None)


def _lower_is_better(metric: str) -> bool:
    return metric.endswith(("_time_s", "_seconds", "_s", "_err",
                            "_error", "_sweeps"))


def check_rows(candidate: dict, history: List[dict]) -> Tuple[bool,
                                                              List[str]]:
    """Gate one candidate bench row against its history. Returns
    (ok, report lines). Fails when the candidate's metric regresses
    beyond the fitted noise band from the best prior value — or when
    the candidate carries no measurement at all (an errored round can
    not demonstrate the absence of a regression)."""
    metric, value = _metric_value(candidate)
    lines: List[str] = []
    if metric is None:
        return False, ["candidate row has no parsed.metric — not a "
                       "bench row?"]
    prior = []
    for row in history:
        h_metric, h_value = _metric_value(row)
        if h_metric == metric and h_value is not None:
            prior.append(h_value)
    if value is None:
        err = (candidate.get("parsed") or {}).get("error")
        return False, [f"FAIL {metric}: candidate has no measurement"
                       + (f" (error: {err})" if err else "")]
    if not prior:
        return True, [f"pass {metric}: {value:.4g} (no history yet — "
                      f"nothing to regress from)"]
    band = fit_noise_band(prior)
    lower = _lower_is_better(metric)
    best = min(prior) if lower else max(prior)
    if lower:
        limit = best * (1.0 + band)
        regressed = value > limit
        head = f"{metric}: {value:.4g} vs best prior {best:.4g}"
    else:
        limit = best * (1.0 - band)
        regressed = value < limit
        head = f"{metric}: {value:.4g} vs best prior {best:.4g}"
    detail = (f"noise band {band:.1%} from {len(prior)} prior row(s) "
              f"-> limit {limit:.4g}")
    if regressed:
        lines.append(f"FAIL {head} — beyond the {detail}")
        return False, lines
    lines.append(f"pass {head} ({detail})")
    return True, lines


def check_files(against: str, *, row: Optional[str] = None,
                history: Optional[List[str]] = None) -> Tuple[bool,
                                                              List[str]]:
    """File-level `perf check`. ``against`` names the round being gated
    (or, with ``row``, the last known-good round the new row extends).
    History defaults to every BENCH_*.json beside ``against``; rounds at
    or after the candidate (and the candidate's own file) are excluded
    so the gate never checks a round against its own future."""
    cand_path = row or against
    cand = _bench_rows(cand_path)[-1]
    if history:
        paths = list(history)
    else:
        paths = sorted(_glob.glob(os.path.join(
            os.path.dirname(os.path.abspath(against)) or ".",
            "BENCH_*.json")))
    cand_n = cand.get("n")
    rows: List[dict] = []
    for p in paths:
        if os.path.abspath(p) == os.path.abspath(cand_path):
            continue
        for r in _bench_rows(p):
            if (row is None and isinstance(cand_n, int)
                    and isinstance(r.get("n"), int)
                    and r["n"] >= cand_n):
                continue
            rows.append(r)
    rows.sort(key=lambda r: (r.get("n") is None, r.get("n")))
    ok, lines = check_rows(cand, rows)
    lines.insert(0, f"perf check: {os.path.basename(cand_path)} against "
                    f"{len(rows)} prior row(s)")
    return ok, lines


# --------------------------------------------------------------------------
# CLI.
# --------------------------------------------------------------------------

def _add_workload_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--m", type=int, help="work rows")
    p.add_argument("--n", type=int, help="work cols")
    p.add_argument("--dtype", help="float32/float64/bfloat16")
    p.add_argument("--block-size", type=int, help="tournament block "
                   "width b (default: the n/8 ladder)")
    p.add_argument("--sweeps", type=float, help="total sweeps executed")
    p.add_argument("--bulk-sweeps", type=float,
                   help="sweeps run in the bulk regime")
    p.add_argument("--pair-solver",
                   help="pallas | block_rotation | gram-eigh | qr-svd")
    p.add_argument("--mixed-store", action="store_true", default=None)
    p.add_argument("--top-k", type=int, help="top-k sketch lane rank")
    p.add_argument("--device-kind", help="roofline device kind "
                   "(default: from the manifest, else cpu)")


def _workload_from_args(args, base: Optional[dict]) -> dict:
    wl = dict(base or {})
    for key in ("m", "n", "dtype", "block_size", "sweeps", "bulk_sweeps",
                "pair_solver", "mixed_store", "top_k"):
        v = getattr(args, key)
        if v is not None:
            wl[key] = v
    if "m" not in wl and "n" in wl:
        wl["m"] = wl["n"]
    if "m" not in wl or "n" not in wl:
        raise SystemExit("no workload: pass --manifest with a usable "
                         "record, or --m/--n explicitly")
    return wl


def _cmd_report(args) -> int:
    base = kind = None
    if args.manifest:
        base, kind = last_workload(args.manifest)
        if base is None:
            print(f"warning: no usable workload record in "
                  f"{args.manifest}", file=sys.stderr)
    workload = _workload_from_args(args, base)
    device = device_block(args.device_kind or kind or "cpu")
    record = build_report(args.trace, workload, device)
    if args.json:
        print(json.dumps(record, indent=2))
    else:
        print(render_report(record))
    if args.emit:
        manifest.append(args.emit, record)
        print(f"\nappended perf record to {args.emit}", file=sys.stderr)
    return 0


def _cmd_model(args) -> int:
    workload = _workload_from_args(args, None)
    device = device_block(args.device_kind or "cpu")
    phases = phase_costs_for(workload, convention=args.convention)
    peak, bw = device["peak_flops"], device["hbm_bw"]
    ridge = peak / bw
    print(f"analytic model [{args.convention}] — "
          f"{workload['m']}x{workload['n']} "
          f"{workload.get('dtype', 'float32')} on "
          f"{device['device_kind']} (peak {peak / 1e9:.0f} GFLOP/s "
          f"[{device['peak_flops_source']}], bw {bw / 1e9:.0f} GB/s "
          f"[{device['hbm_bw_source']}], ridge {ridge:.1f} FLOP/B)")
    head = (f"{'phase':<18} {'GFLOP':>10} {'GB':>9} {'AI':>8} "
            f"{'ceiling GFLOP/s':>16} {'bound':<9}")
    print(head)
    print("-" * len(head))
    for name in costmodel.PHASES:
        cost = phases.get(name)
        if cost is None:
            continue
        ai = cost.intensity
        ceiling = min(peak, ai * bw) if ai > 0 else bw
        bound = ("compute" if ai >= ridge else "bandwidth")
        print(f"{name:<18} {cost.flops / 1e9:>10.3f} "
              f"{cost.hbm_bytes / 1e9:>9.3f} {ai:>8.2f} "
              f"{ceiling / 1e9:>16.1f} {bound:<9}")
    total = costmodel.total_cost(phases)
    print("-" * len(head))
    print(f"{'total':<18} {total.flops / 1e9:>10.3f} "
          f"{total.hbm_bytes / 1e9:>9.3f} {total.intensity:>8.2f}")
    return 0


def _cmd_check(args) -> int:
    ok, lines = check_files(args.against, row=args.row,
                            history=args.history or None)
    print("\n".join(lines))
    return 0 if ok else 1


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m svd_jacobi_tpu.perf",
        description="Roofline performance observatory (stdlib-only "
                    "read side: no jax, no device).")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("report", help="per-scope roofline table from a "
                       "profiler trace + manifest")
    p.add_argument("--trace", required=True,
                   help=".xplane.pb[.gz] file or a profiler log_dir")
    p.add_argument("--manifest", help="manifest JSONL supplying the "
                   "workload (perf or cli/bench records)")
    _add_workload_flags(p)
    p.add_argument("--emit", help="append the perf record to this "
                   "manifest JSONL")
    p.add_argument("--json", action="store_true",
                   help="print the record instead of the table")
    p.set_defaults(fn=_cmd_report)

    p = sub.add_parser("model", help="analytic phase table, no trace")
    _add_workload_flags(p)
    p.add_argument("--convention", default="algorithm",
                   choices=("algorithm", "xla"))
    p.set_defaults(fn=_cmd_model)

    p = sub.add_parser("check", help="gate a bench row against the "
                       "BENCH_*.json history's noise band")
    p.add_argument("--against", required=True,
                   help="the round being gated (or with --row, the "
                   "last known-good round)")
    p.add_argument("--row", help="candidate row file (default: "
                   "--against itself, gated against earlier rounds)")
    p.add_argument("--history", nargs="*",
                   help="explicit history files (default: BENCH_*.json "
                   "beside --against)")
    p.set_defaults(fn=_cmd_check)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
