"""Per-request trace timelines — one causal story per served request.

The manifest stream records WHAT happened (one "serve" record per
terminal, "cache"/"fleet" events around it); this module records WHEN,
as one ordered timeline per request covering every lifecycle edge the
serve layer crosses:

    admit -> queued -> dispatch -> sweep* -> finish -> finalize
    (sigma flow additionally: retain -> promote)

Two reconstruction paths, both yielding the same event vocabulary so
tests can assert they agree:

  * **live** — `SpanRecorder`: the service emits point events as they
    happen (wall + monotonic clocks); `timeline(request_id)` returns
    them ordered, `phases(request_id)` pairs them into named durations
    (queued = admit..dispatch, solve = dispatch..finish), `render` makes
    a human timeline. Bounded: the recorder keeps the last
    ``max_requests`` request timelines (LRU) so a long-lived service
    cannot grow without bound.
  * **offline** — `timeline_from_manifest(records, request_id)`: the
    same ordered timeline rebuilt from the JSONL manifest records that
    already exist (the serve record's finalize timestamp anchored back
    through queue_wait_s / solve_time_s, plus the request's cache
    events), so a request's life reconstructs on any host, long after
    the process died.

`XprofWindow` is the `jax.profiler` trace-session hook: the service arms
one per request id so the dispatch..finish window of EXACTLY that
request runs under an XLA profiler trace — a targeted XProf capture
instead of tracing a whole serving session. Start/stop degrade to
warnings (profiler unavailable, trace already active, lane quarantined
mid-arm), never exceptions: observe-only code must not kill the solve it
observes.

Stdlib-only at import; jax is imported lazily inside `XprofWindow`.
"""

from __future__ import annotations

import collections
import datetime
import threading
import time
import warnings
from typing import Dict, List, Optional

# The canonical lifecycle vocabulary, in causal order — the tie-break
# rank for offline reconstruction, where several events can share one
# reconstructed timestamp. "sweep" repeats; "retain"/"promote" only
# appear on the sigma flow; "cache_hit" replaces the dispatch chain on a
# result-cache hit (and so must rank between admit and finalize).
# "route"/"rescue" are federation edges (serve.router): the ring verdict
# precedes the replica's own admit, a journal rescue re-routes the
# request mid-life onto another replica.
EVENT_ORDER = ("route", "admit", "queued", "rescue", "cache_hit",
               "dispatch", "sweep", "finish", "retain", "finalize",
               "promote")


class SpanRecorder:
    """Bounded per-request event timeline store (see module docstring)."""

    def __init__(self, max_requests: int = 256, max_events: int = 4096):
        self._lock = threading.Lock()
        self._events: "collections.OrderedDict[str, List[dict]]" = \
            collections.OrderedDict()
        self.max_requests = int(max_requests)
        self.max_events = int(max_events)   # per request (sweep storms)

    def event(self, request_id: str, name: str, **meta) -> None:
        """Record one point event for a request (both clocks stamped:
        wall for cross-process correlation with manifest timestamps,
        monotonic for intra-process durations)."""
        ev = {"name": str(name), "t_wall": time.time(),
              "t_mono": time.monotonic()}
        if meta:
            ev.update(meta)
        with self._lock:
            lst = self._events.get(request_id)
            if lst is None:
                lst = self._events[request_id] = []
                while len(self._events) > self.max_requests:
                    self._events.popitem(last=False)
            if len(lst) < self.max_events:
                lst.append(ev)

    def request_ids(self) -> List[str]:
        with self._lock:
            return list(self._events)

    def timeline(self, request_id: str) -> List[dict]:
        """The request's events, ordered by monotonic time (stable for
        equal stamps — insertion order breaks ties, which is already
        causal order at the emission sites)."""
        with self._lock:
            events = list(self._events.get(request_id, ()))
        return sorted(events, key=lambda e: e["t_mono"])

    def phases(self, request_id: str) -> List[dict]:
        """Named durations derived from the point events:
        ``queued`` (admit -> dispatch), ``solve`` (dispatch -> finish),
        ``finalize`` (finish -> finalize), ``promote`` (promote span is
        a point; duration 0 unless meta carries one)."""
        tl = self.timeline(request_id)
        at = {}
        for ev in tl:
            at.setdefault(ev["name"], ev["t_mono"])
        out = []
        for name, start, end in (("queued", "admit", "dispatch"),
                                 ("solve", "dispatch", "finish"),
                                 ("finalize", "finish", "finalize")):
            if start in at and end in at:
                out.append({"phase": name,
                            "start_mono": at[start], "end_mono": at[end],
                            "duration_s": at[end] - at[start]})
        return out

    def render(self, request_id: str) -> str:
        """Human timeline: offsets from the first event, one line per
        event, sweeps collapsed into one counted line."""
        tl = self.timeline(request_id)
        if not tl:
            return f"{request_id}: no recorded events"
        t0 = tl[0]["t_mono"]
        lines = [f"request {request_id} timeline "
                 f"({len(tl)} event(s)):"]
        sweeps = [e for e in tl if e["name"] == "sweep"]
        for ev in tl:
            if ev["name"] == "sweep":
                continue
            extra = " ".join(f"{k}={v}" for k, v in ev.items()
                             if k not in ("name", "t_wall", "t_mono"))
            lines.append(f"  +{(ev['t_mono'] - t0) * 1e3:9.2f}ms "
                         f"{ev['name']:<10}{(' ' + extra) if extra else ''}")
            if ev["name"] == "dispatch" and sweeps:
                span = sweeps[-1]["t_mono"] - sweeps[0]["t_mono"]
                lines.append(f"  +{(sweeps[0]['t_mono'] - t0) * 1e3:9.2f}ms "
                             f"sweep      x{len(sweeps)} "
                             f"over {span * 1e3:.2f}ms")
        return "\n".join(lines)


def _parse_ts(ts: str) -> Optional[float]:
    """ISO-8601 manifest timestamp -> epoch seconds (None if unparseable)."""
    try:
        return datetime.datetime.fromisoformat(ts).timestamp()
    except (TypeError, ValueError):
        return None


def timeline_from_manifest(records: List[dict], request_id: str
                           ) -> List[dict]:
    """Rebuild a request's ordered timeline OFFLINE, from manifest
    records alone (see module docstring). Event names match the live
    recorder's vocabulary; wall timestamps are reconstructed from each
    serve record's finalize timestamp anchored back through its
    queue_wait_s / solve_time_s, so the order (and the durations the
    record carries) survive even though the intermediate stamps were
    never persisted. A "promote" serve record (phase="promote",
    promoted_from=<rid>) attaches to the SIGMA request's timeline, so a
    sigma-then-promote pair reads as one causal story."""
    events: List[dict] = []
    for rec in records:
        kind = rec.get("kind")
        if kind == "serve":
            rid = (rec.get("request") or {}).get("id")
            promoted_from = rec.get("promoted_from")
            if rid != request_id and promoted_from != request_id:
                continue
            t_end = _parse_ts(rec.get("timestamp", "")) or 0.0
            wait = float(rec.get("queue_wait_s") or 0.0)
            solve = rec.get("solve_time_s")
            status = str(rec.get("status", "?"))
            if rec.get("phase") == "promote":
                events.append({"name": "promote", "t_wall": t_end,
                               "status": status, "request_id": rid,
                               "promoted_from": promoted_from})
                continue
            if status.startswith("REJECTED_"):
                events.append({"name": "admit", "t_wall": t_end,
                               "status": status, "rejected": True})
                continue
            if rec.get("path") == "cache":
                events.append({"name": "admit", "t_wall": t_end})
                events.append({"name": "cache_hit", "t_wall": t_end})
                events.append({"name": "finalize", "t_wall": t_end,
                               "status": status})
                continue
            t_dispatch = t_end - (float(solve) if solve is not None else 0.0)
            t_admit = t_dispatch - wait
            events.append({"name": "admit", "t_wall": t_admit})
            events.append({"name": "queued", "t_wall": t_admit,
                           "wait_s": wait})
            if solve is not None:
                events.append({"name": "dispatch", "t_wall": t_dispatch,
                               "lane": rec.get("lane"),
                               "path": rec.get("path")})
                if rec.get("sweeps"):
                    events.append({"name": "sweep", "t_wall": t_dispatch,
                                   "count": int(rec["sweeps"])})
                events.append({"name": "finish", "t_wall": t_end,
                               "status": status})
            events.append({"name": "finalize", "t_wall": t_end,
                           "status": status,
                           "phase": rec.get("phase", "full")})
        elif kind == "router":
            # Federation edges: the ring verdict ("route" — which
            # replica, was it a failover) and a journal rescue that
            # re-homed this request after its replica died.
            t = _parse_ts(rec.get("timestamp", "")) or 0.0
            if (rec.get("event") == "route"
                    and rec.get("request_id") == request_id):
                events.append({"name": "route", "t_wall": t,
                               "replica": rec.get("replica"),
                               "failover": rec.get("failover")})
            elif (rec.get("event") == "rescue"
                    and request_id in (rec.get("request_ids") or ())):
                events.append({"name": "rescue", "t_wall": t,
                               "from_replica": rec.get("replica"),
                               "cause": rec.get("cause")})
        elif kind == "cache" and rec.get("request_id") == request_id:
            t = _parse_ts(rec.get("timestamp", "")) or 0.0
            ev = rec.get("event")
            # "hit" and "promote" are NOT re-emitted here: the serve
            # records (path="cache", phase="promote") already
            # reconstruct both with richer context, and the cache
            # record's slightly-earlier timestamp would sort a
            # duplicate ahead of them.
            if ev == "retain":
                events.append({"name": ev, "t_wall": t,
                               "store": rec.get("store")})
    # Stable order: wall time, then causal vocabulary rank (events that
    # reconstruct to the same instant — finish/finalize — keep their
    # lifecycle order).
    rank = {n: i for i, n in enumerate(EVENT_ORDER)}
    return sorted(events, key=lambda e: (e["t_wall"],
                                         rank.get(e["name"], 99)))


class XprofWindow:
    """One armed `jax.profiler` trace session around a single request's
    dispatch..finish window (see module docstring). All failure modes
    degrade to `RuntimeWarning`s."""

    def __init__(self, log_dir):
        from pathlib import Path
        self.log_dir = Path(log_dir)
        self.started = False

    def start(self) -> bool:
        try:
            import jax
            self.log_dir.mkdir(parents=True, exist_ok=True)
            jax.profiler.start_trace(str(self.log_dir))
            self.started = True
        except Exception as e:
            warnings.warn(
                f"obs.spans.XprofWindow: profiler unavailable, request "
                f"runs untraced ({type(e).__name__}: {e})", RuntimeWarning,
                stacklevel=2)
        return self.started

    def stop(self) -> None:
        if not self.started:
            return
        self.started = False
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as e:
            warnings.warn(f"obs.spans.XprofWindow: stop_trace failed "
                          f"({type(e).__name__}: {e})", RuntimeWarning,
                          stacklevel=2)
