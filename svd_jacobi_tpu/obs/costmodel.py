"""Analytic FLOP / HBM-byte cost model for the solver's phases.

The roofline layer's ground truth (Williams et al., "Roofline: an
insightful visual performance model", CACM 2009): every phase of a solve
— preconditioning QR, the sweep rounds (Gram panels, rotation solves,
stack applies, tournament exchanges), the block-rotation bulk's eigh
subproblem + rank-2b GEMMs, the TSQR/sketch stages of the tall/top-k
lanes, and the finish/lift epilogue — gets an analytic FLOP count and an
HBM traffic estimate parameterized by (m, n, b, dtype, mixed_store).
Dividing a measured per-scope duration (obs.attribution) by these yields
achieved GFLOP/s and GB/s; comparing the phase's arithmetic intensity
against the device ridge point (peak_flops / hbm_bandwidth) classifies it
compute- or bandwidth-bound and gives the %-of-roofline headroom number
every kernel PR must report.

Two counting conventions, selected per call:

  * ``convention="algorithm"`` — the true arithmetic of the method,
    factorization terms included (QR at 2mn^2 - 2n^3/3, eigh at ~9n^3),
    loop bodies multiplied by their actual trip counts. This is the
    roofline numerator: what the hardware really executed.
  * ``convention="xla"`` — XLA's `compiled.cost_analysis()` accounting,
    which the PERF001 analysis pass validates this model against:
    LAPACK-style custom calls (geqrf/orgqr, syevd, gesdd) are counted as
    ~ZERO flops (measured: qr(48x32) = 2078 "flops" — boundary
    elementwise only — vs 76k algorithmic), and `while`/`scan` bodies
    are counted ONCE regardless of trip count (measured: a 5-trip
    fori_loop of a 64^3 matmul = 524290 vs 524288 for one). Matmuls
    count exactly 2mnk in every dtype.

Stdlib-only BY CONTRACT (like obs/manifest.py, obs/registry.py): the
offline `python -m svd_jacobi_tpu.perf report` path must render a
roofline table from a checked-in trace on a machine with no jax.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Tuple

# --------------------------------------------------------------------------
# Device tables.
# --------------------------------------------------------------------------

def normalize_device_kind(kind: str) -> str:
    """Mirror of `tune.tables.normalize_device_kind` (stdlib-only copy —
    this module must import without jax): lowercase, spaces/underscores
    to dashes, so "TPU v5 lite" matches the table keys."""
    return str(kind).strip().lower().replace(" ", "-").replace("_", "-")


# HBM bandwidth in bytes/s, keyed like bench's `_PEAK_FLOPS` (bench.py
# imports THIS table so the two stay next to each other on the read side).
# Sources: published per-chip HBM specs — v4 1228 GB/s, v5e 819 GB/s,
# v5p 2765 GB/s, v6e (Trillium) 1638 GB/s. The "cpu" row is a deliberately
# round order-of-magnitude stand-in for the dev machines the CPU backend
# runs on; `hbm_bandwidth` flags it (and any unknown kind) as estimated so
# bench rows can carry `hbm_bw_source` provenance and a roofline number can
# never silently rest on the fallback.
HBM_BW: Dict[str, float] = {
    "tpu-v4": 1.2288e12,
    "tpu-v5-lite": 8.19e11,
    "tpu-v5e": 8.19e11,
    "tpu-v5p": 2.765e12,
    "tpu-v6-lite": 1.638e12,
    "tpu-v6e": 1.638e12,
    "cpu": 5.0e10,
}

_CPU_FALLBACK_BW = 5.0e10


def hbm_bandwidth(device_kind: str) -> Tuple[float, bool]:
    """(bytes/s, estimated?) for a device kind. ``estimated`` is True for
    the cpu stand-in and for kinds missing from the table — the same
    two-state provenance contract as bench's `_peak_flops`."""
    kind = normalize_device_kind(device_kind)
    if kind in HBM_BW:
        return HBM_BW[kind], kind == "cpu"
    return _CPU_FALLBACK_BW, True


# f32-effective peak FLOP/s by device kind — the authoritative copy of
# what was bench.py's `_PEAK_FLOPS` (bench aliases this table so MFU and
# roofline rows can never disagree on the denominator). TPU entries are
# the chip's bf16 MXU peak / 6: the solver's f32-HIGHEST matmuls run as
# bf16x6 passes. The "cpu" entry is a DOCUMENTED ROUGH ESTIMATE for the
# 2-core bench container (2 cores x ~8 f32 FLOP/cycle x ~3 GHz).
PEAK_FLOPS: Dict[str, float] = {
    "tpu-v5-lite": 197e12 / 6,
    "tpu-v5e": 197e12 / 6,
    "tpu-v5p": 459e12 / 6,
    "tpu-v4": 275e12 / 6,
    "tpu-v6-lite": 918e12 / 6,
    "tpu-v6e": 918e12 / 6,
    "cpu": 48e9,
}


def peak_flops(device_kind: str) -> Tuple[float, bool]:
    """(FLOP/s, estimated?) for a device kind — estimated for the cpu
    stand-in and for kinds that fall back to it."""
    kind = normalize_device_kind(device_kind)
    if kind in PEAK_FLOPS:
        return PEAK_FLOPS[kind], kind == "cpu"
    return PEAK_FLOPS["cpu"], True


_DTYPE_BYTES = {
    "float64": 8, "f64": 8, "float32": 4, "f32": 4,
    "bfloat16": 2, "bf16": 2, "float16": 2, "f16": 2,
}


def dtype_bytes(dtype: str) -> int:
    try:
        return _DTYPE_BYTES[str(dtype).lower()]
    except KeyError:
        raise ValueError(f"unknown dtype {dtype!r} (expected one of "
                         f"{sorted(set(_DTYPE_BYTES))})") from None


def default_block_size(n: int) -> int:
    """The untuned block-width default (n/8, clamped to [4, 128]) — a
    stdlib mirror of `SVDConfig.pick_block_size`'s generic ladder for
    offline use. Live callers pass the true resolved width instead."""
    return max(4, min(128, n // 8))


# --------------------------------------------------------------------------
# Phase vocabulary and the HOT_SCOPES join.
# --------------------------------------------------------------------------

# Canonical phase names, in pipeline order. `config.SCOPE_PHASES` maps
# every `config.HOT_SCOPES` profiler scope onto one of these (checked by
# PERF001), so a trace's per-scope durations can be joined with the model.
PHASES = (
    "precondition",       # QR / chunked-TSQR preconditioning of tall inputs
    "sweep.gram",         # pair Gram panels X^T X (MXU)
    "sweep.rotations",    # 2b x 2b rotation solves (kernel / eigh / qr-svd)
    "sweep.apply",        # rank-2b rotation applies to the U/V stacks (MXU)
    "sweep.exchange",     # tournament block exchange (pure data movement)
    "sketch",             # randomized range-finder projection (top-k lane)
    "finish",             # reconstitute / sigma / NS-polish / lift epilogue
    "grad",               # differentiable-solver backward hot regions
    "health",             # in-graph health word (budgeted ~zero)
)


@dataclasses.dataclass(frozen=True)
class PhaseCost:
    """Analytic cost of one phase over a whole solve (or one loop trip,
    under the "xla" convention). ``flops`` may be 0.0 for pure-movement
    phases (exchange) — arithmetic intensity is then 0 and the phase is
    bandwidth-bound by construction."""

    phase: str
    flops: float
    hbm_bytes: float

    @property
    def intensity(self) -> float:
        """Arithmetic intensity in FLOP/byte (0 when no traffic is
        modeled — degenerate, treated as compute-bound)."""
        return self.flops / self.hbm_bytes if self.hbm_bytes > 0 else 0.0


# Calibration constants for terms without a closed-form flop count.
# EIGH_FLOPS_PER_N3: tridiagonalization + implicit-QL work of a dense
# symmetric eigensolve, the standard ~9n^3 (Golub & Van Loan §8.3).
EIGH_FLOPS_PER_N3 = 9.0
# KERNEL_ROT_FLOPS_PER_N3: the pallas scalar-Jacobi rotation solve on a
# 2b x 2b subproblem — ~(2b-1) inner rounds x b column pairs x ~24b flops
# per pair (dots, Rutishauser angle, two rank-1 updates) ≈ 6 (2b)^3.
KERNEL_ROT_FLOPS_PER_N3 = 6.0
# Newton-Schulz polish of a near-orthogonal q: two n^2-by-n matmuls.
_NS_FLOPS_PER_N3 = 4.0
# The mixed-store entry's inter-loop bf16->f32 reconstitution + polish
# chain, in units of n^3 (~5 matmuls of the work triangle; calibrated
# against the probe entry's HLO dot census — see PERF001).
_MIXED_RECONSTITUTE_N3 = 10.0


def _pad_geometry(n: int, b: int) -> Tuple[int, int, int]:
    """(n_pad, pairs, rounds_per_sweep) of the blocked tournament:
    columns pad to 2b * k, giving 2k block columns swept in one self
    round plus 2k-1 cross rounds."""
    width = 2 * b
    k = max(1, math.ceil(n / width))
    return k * width, k, 2 * k  # 1 self + (2k - 1) cross rounds


def sweep_costs(m: int, n: int, *, block_size: Optional[int] = None,
                dtype: str = "float32", pair_solver: str = "pallas",
                accumulate_v: bool = True, sweeps: float = 1.0,
                gram_dtype: Optional[str] = None,
                rounds_resident: Optional[int] = None,
                convention: str = "algorithm") -> Dict[str, PhaseCost]:
    """Costs of ``sweeps`` full sweeps on an m x n working matrix.

    ``pair_solver`` picks the rotation-solve term: "pallas" (scalar
    kernel), "gram-eigh"/"hybrid" (batched eigh + NS polish),
    "block_rotation" (eigh-accumulated factors applied as rank-2b GEMMs),
    "resident" (the VMEM-resident grouped-round lane — per-round factors
    solved against a CARRIED Gram and applied to the panel stacks once
    per group of ``rounds_resident`` rounds, so the dominant apply/
    exchange traffic amortizes ~1/R; see the phase notes inline).
    ``gram_dtype`` models the mixed_store regime (bf16 Gram panels while
    applies stay in the store dtype). Under ``convention="xla"`` the trip
    count collapses to one round (scan/while bodies counted once) and
    custom-call eigh terms drop to zero.
    """
    b = block_size or default_block_size(n)
    ds = dtype_bytes(dtype)
    gs = dtype_bytes(gram_dtype or dtype)
    n_pad, k, rounds = _pad_geometry(n, b)
    xla = convention == "xla"
    trips = 1.0 if xla else float(sweeps) * rounds
    per_sweep = 1.0 if xla else float(sweeps)     # once-per-sweep terms
    w = 2 * b                                     # pair width
    apply_rows = m + (n_pad if accumulate_v else 0)

    if pair_solver == "resident":
        # The resident lane (ops/pallas_resident.py) restructures the
        # sweep's data flow, so its byte model is NOT the generic one:
        #   gram — ONE full n_pad x n_pad bootstrap Gram per sweep
        #     (2 m n_pad^2 flops, one m x n_pad pass + the Gram write),
        #     then per ROUND the carried-Gram advance G <- J^T G J (two
        #     block-diagonal w-wide GEMMs, 4 n_pad w^2 k flops each
        #     round) reading+writing the n_pad^2 carry. No per-round
        #     panel re-streaming: the diagonal 2b x 2b subproblems are
        #     EXTRACTED from the carry.
        #   rotations — same eigh-accumulated factor solve as the
        #     block_rotation lane, per round.
        #   apply — identical FLOPs (R quadrant GEMMs per visit == one
        #     per round) but the panel stacks are loaded/stored once per
        #     GROUP of R rounds: bytes divide by R. This is the traffic
        #     collapse the lane exists for.
        #   exchange — FREE: inside a group the exchange is slot
        #     renaming at kernel trace time; at group boundaries the
        #     permutation rides the apply write-out and the static Gram
        #     reordering. Zero modeled bytes.
        r = max(1, int(rounds_resident if rounds_resident else 4))
        # Under the xla convention the sweep WHILE body still counts
        # once, but the resident lane's group/round loops inside it are
        # Python-unrolled (group boundaries and the tournament
        # permutation are static), so every per-round term appears
        # ``rounds`` times in the counted-once body — unlike the other
        # lanes, whose round loop is a lax loop the census sees once.
        rtrips = float(rounds) if xla else trips
        gram_flops = (per_sweep * 2.0 * m * n_pad * n_pad
                      + rtrips * 4.0 * n_pad * w * w * k)
        gram_bytes = (per_sweep * (m * n_pad + n_pad * n_pad) * gs
                      + rtrips * 2.0 * n_pad * n_pad * gs)
        eigh_term = 0.0 if xla else EIGH_FLOPS_PER_N3 * w ** 3
        rot_flops = rtrips * k * (eigh_term + _NS_FLOPS_PER_N3 * w ** 3)
        rot_bytes = rtrips * k * 3.0 * w * w * ds
        apply_flops = rtrips * 8.0 * apply_rows * b * b * k
        apply_bytes = rtrips * 2.0 * apply_rows * n_pad * ds / r
        exch_bytes = 0.0
        return {
            "sweep.gram": PhaseCost("sweep.gram", gram_flops, gram_bytes),
            "sweep.rotations": PhaseCost("sweep.rotations", rot_flops,
                                         rot_bytes),
            "sweep.apply": PhaseCost("sweep.apply", apply_flops,
                                     apply_bytes),
            "sweep.exchange": PhaseCost("sweep.exchange", 0.0, exch_bytes),
        }

    # Gram: k pairs of (m x 2b) panels -> (2b x 2b) Gram blocks.
    gram_flops = trips * 8.0 * m * b * b * k
    gram_bytes = trips * (m * n_pad * gs + k * w * w * gs)

    # Rotation solves on the k subproblems.
    if pair_solver in ("gram-eigh", "hybrid", "block_rotation"):
        eigh_term = 0.0 if xla else EIGH_FLOPS_PER_N3 * w ** 3
        rot_flops = trips * k * (eigh_term + _NS_FLOPS_PER_N3 * w ** 3)
    elif pair_solver == "qr-svd":
        # QR + small SVD per pair: LAPACK custom calls, ~zero under the
        # XLA accounting; the scalar Givens cleanup sweep that follows is
        # elementwise (no dots) and rides the same bucket.
        rot_flops = 0.0 if xla else trips * k * (
            qr_flops(w, w, form_q=True) + EIGH_FLOPS_PER_N3 * w ** 3)
    else:                                         # pallas scalar kernel
        rot_flops = trips * k * KERNEL_ROT_FLOPS_PER_N3 * w ** 3
    rot_bytes = trips * k * 3.0 * w * w * ds

    # Applies: rank-2b GEMMs onto the X stack (m rows) and, when V is
    # accumulated, onto the V stack (n_pad rows). The block_rotation
    # bulk's one-GEMM-per-pair apply has the same count — that lane's
    # win is arithmetic intensity, not fewer flops.
    apply_flops = trips * 8.0 * apply_rows * b * b * k
    apply_bytes = trips * 2.0 * apply_rows * n_pad * ds

    # Tournament exchange: pure permutation traffic of both stacks.
    exch_bytes = trips * 2.0 * apply_rows * n_pad * ds

    return {
        "sweep.gram": PhaseCost("sweep.gram", gram_flops, gram_bytes),
        "sweep.rotations": PhaseCost("sweep.rotations", rot_flops, rot_bytes),
        "sweep.apply": PhaseCost("sweep.apply", apply_flops, apply_bytes),
        "sweep.exchange": PhaseCost("sweep.exchange", 0.0, exch_bytes),
    }


def qr_flops(m: int, n: int, *, form_q: bool = False) -> float:
    """Householder QR of m x n (m >= n): 2mn^2 - 2n^3/3, doubled when the
    thin Q is explicitly formed (orgqr has the same count)."""
    base = 2.0 * m * n * n - 2.0 * n ** 3 / 3.0
    return base * (2.0 if form_q else 1.0)


def precondition_costs(m: int, n: int, *, dtype: str = "float32",
                       form_q: bool = True, tall_chunks: int = 1,
                       convention: str = "algorithm") -> PhaseCost:
    """QR (or chunked-TSQR) preconditioning of the m x n input. The TSQR
    tree's extra stacked-R factorizations add ~2n^3/3 per chunk level —
    second order next to 2mn^2 for the m >= 8n shapes the tall lane
    admits. Under "xla" the geqrf/orgqr custom calls count ~zero and a
    chunked tree's scan is counted once."""
    ds = dtype_bytes(dtype)
    if convention == "xla":
        flops = 0.0
        m_eff = m / max(1, tall_chunks)     # one scan trip of the tree
        bytes_ = 2.0 * m_eff * n * ds
    else:
        flops = qr_flops(m, n, form_q=form_q) + (
            (tall_chunks - 1) * 2.0 * n ** 3 / 3.0)
        bytes_ = (2.0 + (1.0 if form_q else 0.0)) * m * n * ds
    return PhaseCost("precondition", flops, bytes_)


def tsqr_fixup_flops(m: int, n: int, chunk: int) -> float:
    """Counted matmul work of the recursive blocked TSQR: each level's
    per-chunk reduced QR is a (zero-counted) custom call, but stitching
    Q <- Q_chunk @ Q_next IS a dot — 2 * (c * chunk) * n^2 per level.
    The chunk blocks are a Python loop (reshape + batched QR), NOT a
    scan, so every level counts under both conventions."""
    total, rows = 0.0, float(m)
    while rows > max(chunk, 2 * n):
        c = math.ceil(rows / chunk)
        total += 2.0 * c * chunk * n * n
        rows = c * n
    return total


def sketch_costs(m: int, n: int, sketch_width: int, *,
                 power_iters: int = 0, dtype: str = "float32",
                 chunk: Optional[int] = None,
                 convention: str = "algorithm") -> PhaseCost:
    """Randomized range-finder of the top-k lane: Y = A @ Omega (2mnl),
    each power iteration A(A^T Q(Y)) (4mnl), the projection B = Q^T A
    (2mnl), and one TSQR orthonormalization of the m x l panel per
    range-finder pass (its stitch matmuls, ~2ml^2 per pass — the QR
    itself is custom-call-zero under "xla" but second order under
    "algorithm" too at l << n). The chunked tree is unrolled Python, so
    both conventions count every chunk."""
    ds = dtype_bytes(dtype)
    l = sketch_width
    flops = 2.0 * m * n * l * (2.0 + 2.0 * power_iters)
    flops += (1.0 + power_iters) * tsqr_fixup_flops(m, l, chunk or m)
    if convention != "xla":
        flops += (1.0 + power_iters) * qr_flops(m, l, form_q=True)
    bytes_ = (2.0 + 2.0 * power_iters) * m * n * ds + 2.0 * m * l * ds
    return PhaseCost("sketch", flops, bytes_)


def finish_costs(m: int, n: int, *, dtype: str = "float32",
                 compute_u: bool = True, compute_v: bool = True,
                 preconditioned: bool = False, refine: bool = False,
                 lift: bool = False, work_rows: Optional[int] = None,
                 convention: str = "algorithm") -> PhaseCost:
    """Epilogue: sigma column norms (2*wr*n), U reconstitution from the
    rotated work stack against the accumulated V (2*wr*n^2), the
    optional Newton-Schulz + sigma-refinement chain (algorithm
    convention only — on the probe entries those land as elementwise
    ops, not dots), the Q1 recombination of a preconditioned solve
    (2mn^2), and the tall/top-k lane's Q-basis lift (2mn^2).
    ``work_rows`` is the row count of the swept stacks: n for the
    QR-preconditioned kernel lanes (the sweep ran on the triangle), m
    for the padded XLA lanes."""
    ds = dtype_bytes(dtype)
    wr = work_rows if work_rows is not None else (n if preconditioned
                                                  else m)
    flops = 2.0 * wr * n                      # sigma norms
    bytes_ = 2.0 * wr * n * ds
    if compute_u or compute_v:
        flops += 2.0 * wr * n * n             # reconstitute
        bytes_ += 2.0 * wr * n * ds
        if refine and convention != "xla":
            flops += _NS_FLOPS_PER_N3 * n ** 3 + 2.0 * wr * n * n
            bytes_ += 2.0 * wr * n * ds
    if preconditioned and compute_u:
        flops += 2.0 * m * n * n              # Q1 @ U_r recombine
        bytes_ += 2.0 * m * n * ds
    if lift and compute_u:
        flops += 2.0 * m * n * n              # Q @ U_small
        bytes_ += 2.0 * m * n * ds
    return PhaseCost("finish", flops, bytes_)


def solve_costs(m: int, n: int, *, block_size: Optional[int] = None,
                dtype: str = "float32", pair_solver: str = "pallas",
                sweeps: float = 8.0, bulk_sweeps: float = 0.0,
                compute_u: bool = True, compute_v: bool = True,
                mixed_store: bool = False, top_k: Optional[int] = None,
                oversample: int = 8, power_iters: int = 0,
                rounds_resident: Optional[int] = None,
                convention: str = "algorithm") -> Dict[str, PhaseCost]:
    """Full-solve cost by phase, the attribution join table.

    The sweep phases run on the n x n preconditioned work triangle (the
    kernel lanes QR-precondition every input; a square input's QR is the
    identity-cost case m == n). ``bulk_sweeps`` of the total ``sweeps``
    run in the bulk regime (block_rotation or mixed bf16 Gram), the rest
    in the polish kernel. ``top_k`` switches the sweep work onto the
    (k + oversample)-wide sketch projection of the top-k lane.
    """
    accumulate_v = compute_u or compute_v
    out: Dict[str, PhaseCost] = {}
    tall = (top_k is None) and m >= 8 * n

    if top_k is not None:
        l = min(n, top_k + oversample)
        out["sketch"] = sketch_costs(m, n, l, power_iters=power_iters,
                                     dtype=dtype, convention=convention)
        sweep_m, sweep_n = l, l
        out["precondition"] = precondition_costs(
            m, l, dtype=dtype, form_q=True, convention=convention)
    else:
        sweep_m, sweep_n = n, n
        out["precondition"] = precondition_costs(
            m, n, dtype=dtype, form_q=compute_u,
            tall_chunks=max(1, m // (8 * n)) if tall else 1,
            convention=convention)

    def _acc(phases: Dict[str, PhaseCost]) -> None:
        for name, c in phases.items():
            prev = out.get(name)
            out[name] = PhaseCost(
                name, c.flops + (prev.flops if prev else 0.0),
                c.hbm_bytes + (prev.hbm_bytes if prev else 0.0))

    polish_sweeps = max(0.0, sweeps - bulk_sweeps)
    if bulk_sweeps > 0:
        bulk_solver = ("block_rotation" if pair_solver == "block_rotation"
                       else pair_solver)
        _acc(sweep_costs(sweep_m, sweep_n, block_size=block_size,
                         dtype=dtype, pair_solver=bulk_solver,
                         accumulate_v=accumulate_v, sweeps=bulk_sweeps,
                         gram_dtype="bfloat16" if mixed_store else None,
                         rounds_resident=rounds_resident,
                         convention=convention))
    if polish_sweeps > 0 or bulk_sweeps == 0:
        _acc(sweep_costs(sweep_m, sweep_n, block_size=block_size,
                         dtype=dtype,
                         pair_solver="pallas" if pair_solver in
                         ("pallas", "block_rotation", "resident")
                         else pair_solver,
                         accumulate_v=accumulate_v,
                         sweeps=max(polish_sweeps, 1.0),
                         convention=convention))

    out["finish"] = finish_costs(
        m if top_k is None else m, sweep_n if top_k is None else l,
        dtype=dtype, compute_u=compute_u, compute_v=compute_v,
        preconditioned=True, refine=compute_u or compute_v,
        lift=tall or top_k is not None, convention=convention)
    return out


def total_cost(phases: Dict[str, PhaseCost]) -> PhaseCost:
    return PhaseCost("total", sum(c.flops for c in phases.values()),
                     sum(c.hbm_bytes for c in phases.values()))


# --------------------------------------------------------------------------
# Per-registry-entry composition (the PERF001 contract surface).
# --------------------------------------------------------------------------

def entry_flops(kind: str, m: int, n: int, *, block_size: int,
                dtype: str = "float32", batch: int = 1,
                sketch_width: int = 0, power_iters: int = 0,
                chunk: Optional[int] = None,
                convention: str = "xla") -> float:
    """Model FLOPs of one fused registry entry, by probe kind.

    ``kind`` matches `analysis.entries` probe names ("pallas",
    "pallas_mixed", "padded_hybrid", ...). The default "xla" convention
    is what PERF001 compares against `compiled.cost_analysis()`:
    while/scan bodies once, custom calls ~zero. A second program stage
    (mixed bulk + polish, hybrid bulk + polish, block bulk + kernel
    polish) contributes its own counted-once loop body.
    """
    kw = dict(block_size=block_size, dtype=dtype, convention=convention)

    def stage(pair_solver, *, gram_dtype=None, mm=n, accumulate_v=True,
              rounds_resident=None):
        return sum(c.flops for c in sweep_costs(
            mm, n, pair_solver=pair_solver, gram_dtype=gram_dtype,
            accumulate_v=accumulate_v, rounds_resident=rounds_resident,
            **kw).values())

    def fin(**over):
        fkw = dict(m=m, n=n, dtype=dtype, preconditioned=True,
                   convention=convention)
        fkw.update(over)
        return finish_costs(**fkw).flops

    pre = precondition_costs(m, n, dtype=dtype, form_q=True,
                             convention=convention).flops

    if kind in ("pallas", "pallas_donated"):
        per = pre + stage("pallas") + fin()
    elif kind == "pallas_mixed":
        # Two sweep loops in one program: bf16 bulk + f32 polish. The
        # bulk loop's applies land on BOTH the bf16 shadow stacks and
        # the f32 masters (the mixed_store contract: angles from bf16
        # Gram panels, applies at store precision) — one extra apply
        # term — and the bf16->f32 reconstitution + NS/refine chain
        # between the loops is ~5 n^3-class matmuls (measured on the
        # probe HLO: 6 n^3 dots vs the plain entry's 1).
        per = (pre + stage("pallas", gram_dtype="bfloat16")
               + stage("pallas")
               + sweep_costs(n, n, pair_solver="pallas",
                             **kw)["sweep.apply"].flops
               + _MIXED_RECONSTITUTE_N3 * float(n) ** 3 + fin())
    elif kind == "pallas_batched":
        per = pre + stage("pallas") + fin()
    elif kind == "pallas_block_rotation":
        per = pre + stage("block_rotation") + stage("pallas") + fin()
    elif kind == "pallas_resident":
        # Resident bulk loop (grouped rounds against the carried Gram)
        # + the shared pallas polish loop, like the block lane's two
        # phases. R only moves BYTES, not flops, so the counted-once
        # "xla" loop body is R-independent.
        per = pre + stage("resident") + stage("pallas") + fin()
    elif kind == "padded_hybrid":
        # Padded XLA lane: no QR precondition — sweeps run on the full
        # m-row stacks; bulk gram-eigh loop + polish qr-svd loop.
        per = (stage("gram-eigh", mm=m) + stage("qr-svd", mm=m)
               + fin(preconditioned=False))
    elif kind in ("padded_novec", "padded_f64_qr"):
        solver = "gram-eigh" if kind == "padded_novec" else "qr-svd"
        vec = kind != "padded_novec"
        per = (stage(solver, mm=m, accumulate_v=vec)
               + fin(preconditioned=False, compute_u=vec, compute_v=vec))
    elif kind == "sketch_project":
        per = sketch_costs(m, n, sketch_width, power_iters=power_iters,
                           dtype=dtype, chunk=chunk,
                           convention=convention).flops
    elif kind == "tsqr_tall":
        per = (precondition_costs(m, n, dtype=dtype, form_q=True,
                                  convention=convention).flops
               + tsqr_fixup_flops(m, n, chunk or m))
    else:
        raise ValueError(f"unknown entry kind {kind!r}")
    return per * batch


# --------------------------------------------------------------------------
# Roofline.
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Roofline:
    """One phase's position under the device roofline. ``attainable`` is
    min(peak, AI * bw) in FLOP/s; ``frac_of_roof`` the achieved fraction
    of that ceiling; ``bound`` which ceiling binds ("compute" |
    "bandwidth"); ``estimated`` whether either device constant came from
    a fallback estimate rather than the table."""

    phase: str
    seconds: float
    flops: float
    hbm_bytes: float
    intensity: float
    achieved_flops: float          # FLOP/s
    achieved_bytes: float          # byte/s
    attainable: float
    frac_of_roof: float
    bound: str
    estimated: bool

    def as_record(self) -> Dict[str, object]:
        d = dataclasses.asdict(self)
        d["gflops"] = self.achieved_flops / 1e9
        d["gbytes"] = self.achieved_bytes / 1e9
        return d


def roofline(phase: str, seconds: float, cost: PhaseCost, *,
             peak_flops: float, hbm_bw: float,
             estimated: bool = False) -> Roofline:
    """Place one measured phase duration under the roofline built from
    ``peak_flops`` (FLOP/s) and ``hbm_bw`` (byte/s)."""
    if seconds <= 0:
        raise ValueError(f"non-positive duration for {phase}: {seconds}")
    ai = cost.intensity
    ridge = peak_flops / hbm_bw
    if cost.flops <= 0:
        # Pure-movement phase: the ceiling is bandwidth itself.
        achieved_b = cost.hbm_bytes / seconds
        return Roofline(phase, seconds, 0.0, cost.hbm_bytes, 0.0, 0.0,
                        achieved_b, hbm_bw,
                        min(1.0, achieved_b / hbm_bw) if hbm_bw else 0.0,
                        "bandwidth", estimated)
    attainable = min(peak_flops, ai * hbm_bw) if ai > 0 else peak_flops
    achieved = cost.flops / seconds
    return Roofline(
        phase, seconds, cost.flops, cost.hbm_bytes, ai, achieved,
        cost.hbm_bytes / seconds, attainable,
        achieved / attainable if attainable else 0.0,
        "compute" if ai >= ridge else "bandwidth", estimated)
