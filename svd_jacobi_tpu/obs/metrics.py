"""Jit-safe in-graph metrics: a host-side event stream fed by
`jax.debug.callback` from inside the fused solve loops.

Design constraints (why this is not a logging module):

  * Events are emitted from INSIDE `lax.while_loop`/`lax.scan` bodies of
    jitted programs — the only mechanism that can observe the fused solve
    without host-stepping it (which measures a different program; see
    `utils/profiling.instrumented_svd` and PROFILE.md's intra-jit
    methodology) is a runtime callback.
  * The zero-telemetry path must compile to IDENTICAL HLO: emission sites
    are gated by a static `telemetry` argument threaded through the jitted
    entry points, so the flag is part of the jit cache key and the
    disabled trace contains no callback (and no counter carries) at all.
    `emit` additionally no-ops when the module flag is off, as a guard
    against an ungated call site.
  * Under `shard_map` a callback fires once per LOCAL device with
    identical (pmax-replicated) values; the dispatcher deduplicates by
    counting ``replicas`` occurrences of each event identity, and only
    process 0 of a multi-process run records — so the sharded path
    reports each sweep exactly once.

Usage (host side):

    with obs.metrics.capture() as events:
        r = sj.svd(a)                 # retraces with telemetry baked in
    # events == [{"event": "sweep", "stage": ..., "off_rel": ...}, ...]
"""

from __future__ import annotations

import contextlib
import itertools
import threading
from typing import Callable, Dict, List

import numpy as np

_lock = threading.RLock()
_enabled = False
_sinks: List[Callable[[dict], None]] = []
_pending: Dict[tuple, int] = {}
_site_counter = itertools.count()


def enabled() -> bool:
    """Trace-time telemetry flag — solver entry points pass this as the
    static `telemetry` argument of their jits, so toggling it retraces."""
    return _enabled


def enable() -> None:
    global _enabled
    with _lock:
        _enabled = True


def disable() -> None:
    global _enabled
    with _lock:
        _enabled = False


@contextlib.contextmanager
def capture():
    """Enable telemetry and collect events into the yielded list.

    Nesting is allowed (each capture sees events emitted while it is
    active); the enabled flag is restored on exit. Exit drains the
    runtime's callback queue (`jax.effects_barrier`) first — deliveries
    are asynchronous, and without the barrier events from a solve that
    just returned would race the sink removal and be lost.
    """
    global _enabled
    events: List[dict] = []
    with _lock:
        prev = _enabled
        _sinks.append(events.append)
        enable()
    try:
        yield events
    finally:
        try:
            flush()
        finally:
            # The barrier re-raises deferred callback/runtime errors; the
            # sink removal and flag restore must survive them or telemetry
            # stays globally on (and the dead list keeps growing).
            with _lock:
                _sinks.remove(events.append)
                _enabled = prev


def flush() -> None:
    """Block until every already-dispatched callback has been delivered."""
    import jax
    jax.effects_barrier()


def add_sink(fn: Callable[[dict], None]) -> Callable[[], None]:
    """Register a persistent event sink; returns a remover. Sinks receive
    plain-dict events on the runtime callback thread (keep them cheap)."""
    with _lock:
        _sinks.append(fn)

    def remove():
        with _lock:
            if fn in _sinks:
                _sinks.remove(fn)
    return remove


def _scalar(v):
    """numpy scalar/0-d array -> plain python int/float/bool."""
    a = np.asarray(v)
    if a.dtype.kind in "iu":
        return int(a)
    if a.dtype.kind == "b":
        return bool(a)
    return float(a)


def _dispatch(site: int, replicas: int, record: dict) -> None:
    import jax
    if jax.process_index() != 0:
        return
    with _lock:
        if replicas > 1:
            # Replicated emission (shard_map): every local device delivers
            # the same values; count occurrences of this exact event and
            # forward only the first of each cycle of ``replicas``.
            key = (site, tuple(sorted((k, repr(v))
                                      for k, v in record.items())))
            n = _pending.get(key, 0) + 1
            if n >= replicas:
                _pending.pop(key, None)
            else:
                _pending[key] = n
            if n > 1:
                return
        for sink in list(_sinks):
            sink(record)


def emit(event: str, *, meta: dict | None = None, replicas: int = 1,
         **fields) -> None:
    """Emit one event from inside a jitted computation.

    ``event``/``meta`` are trace-time constants (strings, ints); ``fields``
    are traced scalars delivered at runtime. ``replicas``: how many times
    the runtime will deliver this callback per logical event (= local
    device count when emitting replicated values under shard_map; the
    dispatcher forwards one).

    Call sites MUST be gated by a static telemetry flag — `emit` inserts a
    `jax.debug.callback` into the trace, and the telemetry-off path must
    stay HLO-identical. The `_enabled` check here is a second line of
    defense, not the gate.
    """
    if not _enabled:
        return
    import jax
    site = next(_site_counter)
    static = dict(meta or {})
    static["event"] = event

    def _cb(**kw):
        record = dict(static)
        record.update((k, _scalar(v)) for k, v in kw.items())
        _dispatch(site, replicas, record)

    jax.debug.callback(_cb, **fields)
