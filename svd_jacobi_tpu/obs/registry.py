"""Live metrics registry — the serving flight recorder's numeric core.

The serving stack's observability so far was POST-HOC: schema-versioned
JSONL manifests reconstruct what happened after the fact, but nothing
answers "what is the service doing RIGHT NOW" — the ROADMAP's
multi-tenant front-door item explicitly requires "Prometheus-style
metrics export" before a network API can ship. This module is that
surface, three pieces:

  * `MetricsRegistry` — a lock-cheap in-process registry of counters,
    gauges, and explicit-bucket histograms, labeled by whatever the call
    site declares (bucket/lane/op/phase/path). Mutations are one dict
    update under one lock (no allocation on the repeat path); gauges
    that DERIVE from live state (queue depths, lane states, cache
    sizes) refresh through registered collectors at scrape time instead
    of taxing the hot path. `render()` emits Prometheus text exposition
    format 0.0.4.
  * SLO accounting — `SLOTracker` keeps per-bucket latency quantiles
    (p50/p99 off a bounded reservoir), deadline-miss / shed / error
    counters, and a rolling error-budget burn rate
    (miss_rate / (1 - objective) over the last `window` requests: 1.0 =
    burning exactly the budget, >1 = on course to blow the SLO).
  * Offline reconstruction — `registry_from_manifest` and
    `slo_from_records` rebuild the same series from the JSONL manifest
    records that already exist (serve/fleet/cache/coldstart), so a
    manifest can be rendered as a Prometheus dump or an SLO report on a
    host with no service (and no jax) at all.

Free when off: the registry only exists when `ServeConfig.metrics` is
True — the off path holds None and never constructs one. Every mutation
additionally bumps a module-global counter (`mutation_total`), which is
how the OBS002 analysis pass PROVES the metrics-off hot path performs
zero registry mutations (the counter is monotonic across all instances;
a zero delta over an off-path serve sequence is the guarantee).

Deliberately stdlib-only (no jax, no numpy): `scripts/telemetry_summary.py`
loads this module by file path on hosts without an accelerator stack.
"""

from __future__ import annotations

import collections
import math
import threading
from typing import Callable, Dict, List, Optional, Tuple

# Monotonic across every registry instance in the process: the OBS002
# "zero registry mutations on the metrics-off hot path" check snapshots
# this, runs a metrics-off serve sequence, and asserts a zero delta.
_MUTATION_LOCK = threading.Lock()
_MUTATION_TOTAL = 0


def _count_mutation() -> None:
    global _MUTATION_TOTAL
    with _MUTATION_LOCK:
        _MUTATION_TOTAL += 1


def mutation_total() -> int:
    """Process-wide count of registry mutations (all instances)."""
    with _MUTATION_LOCK:
        return _MUTATION_TOTAL


# Latency-oriented default histogram buckets (seconds): sub-ms cache
# hits through minutes-class cold compiles.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)


def _label_key(labels: dict) -> Tuple[Tuple[str, str], ...]:
    """Canonical (sorted, stringified) label identity of one series."""
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape(value: str) -> str:
    return (value.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


class _Hist:
    """One histogram series: cumulative-bucket counts + sum + count."""

    __slots__ = ("bounds", "counts", "total", "count")

    def __init__(self, bounds: Tuple[float, ...]):
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)   # +Inf tail bucket
        self.total = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        i = 0
        for i, b in enumerate(self.bounds):
            if value <= b:
                break
        else:
            i = len(self.bounds)
        self.counts[i] += 1
        self.total += value
        self.count += 1

    def quantile(self, q: float) -> Optional[float]:
        """Upper-bound estimate of the q-quantile from the bucket counts
        (the standard Prometheus histogram_quantile approximation, minus
        the intra-bucket interpolation — good enough for a health
        snapshot). None when empty."""
        if self.count == 0:
            return None
        rank = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank and c:
                return (self.bounds[i] if i < len(self.bounds)
                        else math.inf)
        return math.inf


class _Family:
    __slots__ = ("name", "kind", "help", "series")

    def __init__(self, name: str, kind: str, help_: str):
        self.name = name
        self.kind = kind      # "counter" | "gauge" | "histogram"
        self.help = help_
        self.series: Dict[tuple, object] = {}


class MetricsRegistry:
    """Thread-safe in-process metrics registry (see module docstring).

    Families are created lazily at first mutation; a name reused with a
    different kind raises loudly (a counter silently becoming a gauge
    would corrupt every scrape after it)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: "collections.OrderedDict[str, _Family]" = \
            collections.OrderedDict()
        self._collectors: List[Callable[["MetricsRegistry"], None]] = []
        self._mutations = 0

    # -- mutation API -------------------------------------------------------

    def _family(self, name: str, kind: str, help_: Optional[str]) -> _Family:
        fam = self._families.get(name)
        if fam is None:
            fam = self._families[name] = _Family(name, kind, help_ or "")
        elif fam.kind != kind:
            raise ValueError(
                f"metric {name!r} already registered as {fam.kind}, "
                f"cannot use as {kind}")
        elif help_ and not fam.help:
            fam.help = help_
        return fam

    def inc(self, name: str, amount: float = 1.0, *,
            help: Optional[str] = None, **labels) -> None:
        """Increment a counter series (created at first use)."""
        key = _label_key(labels)
        with self._lock:
            fam = self._family(name, "counter", help)
            fam.series[key] = float(fam.series.get(key, 0.0)) + amount
            self._mutations += 1
        _count_mutation()

    def set(self, name: str, value: float, *,
            help: Optional[str] = None, **labels) -> None:
        """Set a gauge series to an absolute value."""
        key = _label_key(labels)
        with self._lock:
            fam = self._family(name, "gauge", help)
            fam.series[key] = float(value)
            self._mutations += 1
        _count_mutation()

    def observe(self, name: str, value: float, *,
                buckets: Optional[Tuple[float, ...]] = None,
                help: Optional[str] = None, **labels) -> None:
        """Observe one value into a histogram series."""
        key = _label_key(labels)
        with self._lock:
            fam = self._family(name, "histogram", help)
            h = fam.series.get(key)
            if h is None:
                h = fam.series[key] = _Hist(tuple(buckets or DEFAULT_BUCKETS))
            h.observe(float(value))
            self._mutations += 1
        _count_mutation()

    # -- collectors ---------------------------------------------------------

    def add_collector(self, fn: Callable[["MetricsRegistry"], None]
                      ) -> Callable[[], None]:
        """Register a scrape-time refresher for DERIVED gauges (queue
        depths, lane states, cache sizes): called on every `render` /
        `snapshot`, so live state is sampled when someone looks instead
        of taxing the hot path on every change. Returns a remover. A
        collector that raises is dropped from that scrape only (the
        scrape must stay serviceable mid-chaos — a dead lane's collector
        error must not take /metrics down with it)."""
        with self._lock:
            self._collectors.append(fn)

        def remove():
            with self._lock:
                if fn in self._collectors:
                    self._collectors.remove(fn)
        return remove

    def _collect(self) -> List[str]:
        with self._lock:
            collectors = list(self._collectors)
        errors = []
        for fn in collectors:
            try:
                fn(self)
            except Exception as e:   # scrape must survive a sick collector
                errors.append(f"{type(e).__name__}: {e}")
        return errors

    # -- views --------------------------------------------------------------

    @property
    def mutations(self) -> int:
        with self._lock:
            return self._mutations

    def value(self, name: str, **labels) -> Optional[float]:
        """Current value of one counter/gauge series (None if absent)."""
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                return None
            v = fam.series.get(_label_key(labels))
            return None if v is None or isinstance(v, _Hist) else float(v)

    def snapshot(self) -> dict:
        """{name: {"kind", "series": {label-string: value-or-hist-dict}}}
        after running the collectors."""
        self._collect()
        out = {}
        with self._lock:
            for fam in self._families.values():
                series = {}
                for key, v in fam.series.items():
                    lbl = ",".join(f"{k}={val}" for k, val in key)
                    if isinstance(v, _Hist):
                        series[lbl] = {"count": v.count, "sum": v.total,
                                       "p50": v.quantile(0.50),
                                       "p99": v.quantile(0.99)}
                    else:
                        series[lbl] = v
                out[fam.name] = {"kind": fam.kind, "series": series}
        return out

    def render(self) -> str:
        """Prometheus text exposition (format 0.0.4) of every family,
        collectors refreshed first. Collector failures surface as a
        comment line, never an exception — the scrape stays serviceable
        under fleet chaos."""
        errors = self._collect()
        lines: List[str] = []
        with self._lock:
            for fam in self._families.values():
                if fam.help:
                    lines.append(f"# HELP {fam.name} {fam.help}")
                lines.append(f"# TYPE {fam.name} {fam.kind}")
                for key, v in sorted(fam.series.items()):
                    base_lbl = ",".join(
                        f'{k}="{_escape(val)}"' for k, val in key)
                    if isinstance(v, _Hist):
                        cum = 0
                        for i, b in enumerate(v.bounds):
                            cum += v.counts[i]
                            le = ((base_lbl + ",") if base_lbl else "")
                            lines.append(
                                f'{fam.name}_bucket{{{le}le="{_fmt(b)}"}}'
                                f' {cum}')
                        le = ((base_lbl + ",") if base_lbl else "")
                        lines.append(
                            f'{fam.name}_bucket{{{le}le="+Inf"}} {v.count}')
                        suffix = f"{{{base_lbl}}}" if base_lbl else ""
                        lines.append(f"{fam.name}_sum{suffix} "
                                     f"{_fmt(v.total)}")
                        lines.append(f"{fam.name}_count{suffix} {v.count}")
                    else:
                        suffix = f"{{{base_lbl}}}" if base_lbl else ""
                        lines.append(f"{fam.name}{suffix} {_fmt(v)}")
        for e in errors:
            lines.append(f"# collector error: {e}")
        return "\n".join(lines) + "\n"


# -- SLO accounting ---------------------------------------------------------


class SLOTracker:
    """Per-bucket latency/outcome accounting for the serving layer.

    ``objective`` is the availability target (fraction of requests that
    must finish OK within their deadline); the rolling error-budget burn
    is miss_rate / (1 - objective) over the last ``window`` outcomes —
    the standard burn-rate framing: 1.0 means the service is spending
    its budget exactly as fast as it accrues."""

    def __init__(self, objective: float = 0.99, window: int = 512,
                 reservoir: int = 512):
        if not (0.0 < objective < 1.0):
            raise ValueError(f"objective must be in (0, 1), got {objective}")
        self.objective = float(objective)
        self._lock = threading.Lock()
        self._lat: Dict[str, collections.deque] = {}
        self._counts: Dict[str, Dict[str, int]] = {}
        self._window: collections.deque = collections.deque(maxlen=window)
        self._reservoir = int(reservoir)

    def _bucket_counts(self, bucket: str) -> Dict[str, int]:
        c = self._counts.get(bucket)
        if c is None:
            c = self._counts[bucket] = {
                "served": 0, "ok": 0, "deadline_miss": 0, "error": 0,
                "shed": 0}
        return c

    def observe(self, bucket: str, latency_s: float, *, ok: bool,
                deadline_miss: bool = False, error: bool = False) -> None:
        """One finalized request: end-to-end latency + outcome class."""
        with self._lock:
            lat = self._lat.get(bucket)
            if lat is None:
                lat = self._lat[bucket] = collections.deque(
                    maxlen=self._reservoir)
            lat.append(float(latency_s))
            c = self._bucket_counts(bucket)
            c["served"] += 1
            if ok:
                c["ok"] += 1
            if deadline_miss:
                c["deadline_miss"] += 1
            if error:
                c["error"] += 1
            self._window.append(1 if (ok and not deadline_miss) else 0)

    def shed(self, bucket: Optional[str] = None) -> None:
        """One request rejected at admission for load (shed/queue-full/
        budget): burns the error budget without a latency sample."""
        with self._lock:
            self._bucket_counts(bucket or "_rejected")["shed"] += 1
            self._window.append(0)

    # A q-quantile estimate needs at least ceil(1/(1-q)) samples (p50 ->
    # 2, p99 -> 100): below that the "p99" of a reservoir is just the
    # max of 2-3 points — a misleading number. Quantiles under the
    # minimum report None; healthz carries this table per snapshot
    # (``quantile_min_samples``) so a null field is self-explaining.
    QUANTILE_MIN_SAMPLES = {0.50: 2, 0.99: 100}

    @classmethod
    def _quantile(cls, sorted_vals: List[float],
                  q: float) -> Optional[float]:
        need = cls.QUANTILE_MIN_SAMPLES.get(q) or math.ceil(
            1.0 / (1.0 - q))
        if len(sorted_vals) < need:
            return None
        i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
        return sorted_vals[i]

    def burn_rate(self) -> float:
        """Rolling error-budget burn (0 = clean, 1 = at budget)."""
        with self._lock:
            if not self._window:
                return 0.0
            miss = 1.0 - (sum(self._window) / len(self._window))
        return miss / (1.0 - self.objective)

    def snapshot(self) -> dict:
        """Per-bucket p50/p99/outcome counts + the rolling burn gauge."""
        with self._lock:
            buckets = {}
            for b, c in self._counts.items():
                lat = sorted(self._lat.get(b, ()))
                buckets[b] = {
                    **c,
                    "latency_p50_s": self._quantile(lat, 0.50),
                    "latency_p99_s": self._quantile(lat, 0.99),
                    "samples": len(lat),
                }
            window = list(self._window)
        miss = (1.0 - sum(window) / len(window)) if window else 0.0
        return {
            "objective": self.objective,
            "window": len(window),
            "error_budget_burn": miss / (1.0 - self.objective),
            # Why a latency_p*_s field can be null: fewer samples than
            # the quantile supports (see QUANTILE_MIN_SAMPLES).
            "quantile_min_samples": {
                "p50": self.QUANTILE_MIN_SAMPLES[0.50],
                "p99": self.QUANTILE_MIN_SAMPLES[0.99]},
            "buckets": buckets,
        }

    def export_to(self, reg: MetricsRegistry) -> None:
        """Refresh the SLO gauges into a registry (collector body)."""
        snap = self.snapshot()
        reg.set("svdj_slo_error_budget_burn", snap["error_budget_burn"],
                help="rolling error-budget burn rate (1.0 = at budget)")
        for b, c in snap["buckets"].items():
            for q in ("p50", "p99"):
                v = c[f"latency_{q}_s"]
                if v is not None:
                    reg.set("svdj_slo_latency_seconds", v, bucket=b,
                            quantile=q,
                            help="per-bucket end-to-end latency quantile")
            reg.set("svdj_slo_deadline_miss_total",
                    c["deadline_miss"], bucket=b,
                    help="requests finalized DEADLINE per bucket")
            reg.set("svdj_slo_shed_total", c["shed"], bucket=b,
                    help="requests shed at admission per bucket")


# -- offline reconstruction from manifest records ---------------------------


# The load-class rejection statuses (offline mirror of the live
# `slo.shed` gate in serve.service.submit — keep the two lists in
# lockstep; the tenancy analysis pass cross-checks agreement on real
# traffic).
_SHED_STATUSES = ("REJECTED_BROWNOUT_SHED", "REJECTED_QUEUE_FULL",
                  "REJECTED_DEADLINE_BUDGET", "REJECTED_RATE_LIMITED",
                  "REJECTED_NO_LANE")


def slo_from_records(records: List[dict], *, objective: float = 0.99
                     ) -> dict:
    """SLO snapshot reconstructed from "serve" manifest records alone —
    the same shape `SLOTracker.snapshot` reports live, so
    `scripts/telemetry_summary.py --slo` works on any host."""
    slo = SLOTracker(objective=objective, window=2 ** 31 - 1,
                     reservoir=2 ** 20)
    # Load-class rejections burn the error budget; client errors
    # (NO_BUCKET, NONFINITE_INPUT, UNKNOWN_TENANT) and shutdown do not
    # — mirrors the live SLOTracker feed in serve.service exactly, so a
    # live healthz()["slo"] and this reconstruction agree on the same
    # traffic. RATE_LIMITED is load-class: the service chose to reject
    # it under the tenant's QoS contract. (Bucket attribution of sheds
    # differs by design: rejected serve records carry bucket=None, so
    # offline sheds land under "_rejected".)
    for rec in records:
        if rec.get("kind") != "serve":
            continue
        status = str(rec.get("status", ""))
        bucket = rec.get("bucket") or "_rejected"
        if status.startswith("REJECTED_"):
            if status in _SHED_STATUSES:
                slo.shed(bucket)
            continue
        wait = rec.get("queue_wait_s") or 0.0
        solve = rec.get("solve_time_s") or 0.0
        slo.observe(bucket, float(wait) + float(solve),
                    ok=(status == "OK"),
                    deadline_miss=(status == "DEADLINE"),
                    error=(status == "ERROR"))
    return slo.snapshot()


def tenant_slo_from_records(records: List[dict], *,
                            objective: float = 0.99) -> dict:
    """Per-tenant SLO snapshots reconstructed from "serve" manifest
    records alone: ``{tenant: snapshot}`` with the same snapshot shape
    as `SLOTracker.snapshot` — the offline twin of the live
    ``healthz()["tenants"][t]["slo"]`` trackers, and the substrate the
    adversarial-tenant fairness drills assert against (records, not
    timers). A pre-tenancy record without a "tenant" field lands under
    "default", so old streams reconstruct unchanged."""
    trackers: Dict[str, SLOTracker] = {}
    for rec in records:
        if rec.get("kind") != "serve":
            continue
        tenant = str(rec.get("tenant", "default"))
        slo = trackers.get(tenant)
        if slo is None:
            slo = trackers[tenant] = SLOTracker(
                objective=objective, window=2 ** 31 - 1,
                reservoir=2 ** 20)
        status = str(rec.get("status", ""))
        bucket = rec.get("bucket") or "_rejected"
        if status.startswith("REJECTED_"):
            if status in _SHED_STATUSES:
                slo.shed(bucket)
            continue
        wait = rec.get("queue_wait_s") or 0.0
        solve = rec.get("solve_time_s") or 0.0
        slo.observe(bucket, float(wait) + float(solve),
                    ok=(status == "OK"),
                    deadline_miss=(status == "DEADLINE"),
                    error=(status == "ERROR"))
    return {t: slo.snapshot() for t, slo in sorted(trackers.items())}


def render_slo(snap: dict) -> str:
    """Human rendering of an SLO snapshot (live or reconstructed)."""
    lines = [
        f"SLO objective {snap['objective']:.3%}  "
        f"error-budget burn {snap['error_budget_burn']:.2f}x  "
        f"(window {snap['window']})",
    ]
    fmt_ms = lambda v: "n/a" if v is None else f"{v * 1e3:8.1f}ms"
    for b, c in sorted(snap["buckets"].items()):
        lines.append(
            f"  {b:<20} served={c['served']:>5} ok={c['ok']:>5} "
            f"miss={c['deadline_miss']:>4} shed={c['shed']:>4} "
            f"err={c['error']:>3}  p50={fmt_ms(c['latency_p50_s'])} "
            f"p99={fmt_ms(c['latency_p99_s'])}")
    return "\n".join(lines)


def registry_from_manifest(records: List[dict]) -> MetricsRegistry:
    """Rebuild the flight recorder's counter/histogram series from the
    JSONL manifest records that already exist (serve / fleet / router /
    cache / coldstart) — the ROADMAP's "Prometheus-style metrics export rendered
    from the manifest records" item, usable with zero live service (and
    zero jax): `python -m svd_jacobi_tpu.cli metrics reports/manifest.jsonl`.
    Gauges that only exist live (queue depth, breaker state) are not
    reconstructable and are simply absent."""
    reg = MetricsRegistry()
    for rec in records:
        kind = rec.get("kind")
        if kind == "serve":
            status = str(rec.get("status", "?"))
            bucket = rec.get("bucket") or "none"
            # Pre-tenancy records carry no tenant field -> "default",
            # matching the live emit sites' label set exactly.
            tenant = str(rec.get("tenant", "default"))
            if status.startswith("REJECTED_"):
                reg.inc("svdj_requests_rejected_total",
                        reason=status[len("REJECTED_"):].lower(),
                        tenant=tenant,
                        help="requests rejected at admission")
                continue
            reg.inc("svdj_requests_finalized_total", status=status,
                    path=str(rec.get("path", "?")),
                    phase=str(rec.get("phase", "full")), tenant=tenant,
                    help="requests reaching a terminal status")
            if rec.get("queue_wait_s") is not None:
                reg.observe("svdj_queue_wait_seconds",
                            float(rec["queue_wait_s"]), bucket=bucket,
                            tenant=tenant,
                            help="admission-to-dispatch queue wait")
            if rec.get("solve_time_s") is not None:
                reg.observe("svdj_solve_seconds",
                            float(rec["solve_time_s"]), bucket=bucket,
                            tenant=tenant,
                            help="dispatch-to-finish solve time")
            if rec.get("sweeps") is not None:
                reg.inc("svdj_sweeps_total", float(rec["sweeps"]),
                        bucket=bucket,
                        help="solver sweeps executed")
        elif kind == "fleet":
            event = str(rec.get("event", "?"))
            lane = rec.get("lane")
            if event == "lane_transition":
                reg.inc("svdj_lane_transitions_total",
                        lane="" if lane is None else str(lane),
                        to_state=str(rec.get("to_state", "?")),
                        help="lane state transitions")
            elif event == "steal":
                reg.inc("svdj_steals_total",
                        lane="" if lane is None else str(lane),
                        help="requests stolen by an idle lane")
            elif event == "rescue":
                reg.inc("svdj_rescued_total",
                        float(rec.get("count", 0) or 0),
                        lane="" if lane is None else str(lane),
                        help="requests rescued off an evicted lane")
            elif event == "probe":
                reg.inc("svdj_probes_total",
                        ok=str(bool(rec.get("ok"))).lower(),
                        lane="" if lane is None else str(lane),
                        help="quarantined-lane recovery probes")
        elif kind == "router":
            event = str(rec.get("event", "?"))
            rep = rec.get("replica")
            rep_l = "" if rep is None else str(rep)
            if event == "replica_transition":
                reg.inc("svdj_replica_transitions_total", replica=rep_l,
                        to_state=str(rec.get("to_state", "?")),
                        help="replica state transitions")
            elif event == "rescue":
                reg.inc("svdj_replica_rescued_total",
                        float(rec.get("count", 0) or 0), replica=rep_l,
                        help="requests rescued off a dead replica")
            elif event == "route":
                reg.inc("svdj_router_routes_total", replica=rep_l,
                        bucket=str(rec.get("bucket", "?")),
                        help="requests routed to a replica")
            elif event == "probe":
                reg.inc("svdj_replica_probes_total",
                        ok=str(bool(rec.get("ok"))).lower(),
                        replica=rep_l,
                        help="quarantined-replica probes")
        elif kind == "net":
            event = str(rec.get("event", "?"))
            rep = rec.get("replica")
            rep_l = "" if rep is None else str(rep)
            op = str(rec.get("op", "") or "")
            if event == "rpc_retry":
                reg.inc("svdj_rpc_retries_total", op=op, replica=rep_l,
                        help="replica RPC attempts retried after a "
                             "transport error")
            elif event in ("rpc_timeout", "rpc_error"):
                reg.inc("svdj_rpc_failures_total", op=op, replica=rep_l,
                        cause=("timeout" if event == "rpc_timeout"
                               else "error"),
                        help="replica RPCs exhausted (deadline budget "
                             "or attempt cap)")
            elif event == "failover":
                reg.inc("svdj_rpc_failovers_total", op=op,
                        replica=rep_l,
                        help="submits failed over past an unreachable "
                             "host in ring order")
            elif event in ("lease_grant", "lease_expired"):
                reg.inc("svdj_replica_leases_total", replica=rep_l,
                        event=event,
                        help="replica lease grants and expiries")
            elif event in ("fence", "fence_refused"):
                reg.inc("svdj_fence_events_total", replica=rep_l,
                        event=event,
                        help="fencing-token bumps/deliveries and stale-"
                             "token refusals")
            elif event in ("quarantine", "heal", "partition_heal"):
                reg.inc("svdj_connection_quarantine_total",
                        replica=rep_l, event=event,
                        help="half-open connection breaker transitions")
        elif kind == "cache":
            reg.inc("svdj_cache_events_total",
                    store=str(rec.get("store", "?")),
                    event=str(rec.get("event", "?")),
                    help="result-cache / promotion-store events")
        elif kind == "coldstart":
            reg.inc("svdj_aot_backend_compiles_total",
                    float(rec.get("backend_compiles", 0) or 0),
                    help="AOT warmup backend compile requests")
            reg.inc("svdj_aot_cache_hits_total",
                    float(rec.get("cache_hits", 0) or 0),
                    help="AOT warmup persistent-cache hits")
            reg.inc("svdj_aot_fresh_compiles_total",
                    float(rec.get("fresh_compiles", 0) or 0),
                    help="AOT warmup compiles the cache did not serve")
    return reg


# Minimal structural validator of Prometheus text exposition — used by
# tests and the chaos-soak acceptance ("the scrape parses as valid
# Prometheus text"); intentionally strict about line shape, not about
# semantics.
import re as _re

_SERIES_RE = _re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})?'
    r' (-?[0-9]+(\.[0-9]+)?([eE][+-]?[0-9]+)?|[+-]Inf|NaN)$')


def parse_prometheus(text: str) -> Dict[str, float]:
    """Parse (and validate) a text exposition; raises ValueError on the
    first malformed line. Returns {series-with-labels: value}."""
    out: Dict[str, float] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip() or line.startswith("#"):
            continue
        m = _SERIES_RE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: not valid Prometheus text "
                             f"exposition: {line!r}")
        name_labels, _, value = line.rpartition(" ")
        out[name_labels] = float(value.replace("Inf", "inf"))
    return out
