"""Named-scope annotations for profiler traces.

`scope("gram")` is `jax.named_scope("svdj/gram")`: the scope name rides the
XLA metadata of every op traced inside it, so Perfetto/TensorBoard traces
(and HLO dumps) show `svdj/gram`, `svdj/rotations`, `svdj/apply_exchange`
instead of anonymous `fusion.123` regions. Scopes are always on — they are
pure metadata with zero runtime cost and do not change the computation —
unlike `obs.metrics`, which inserts callbacks and is therefore gated.

The scope names used across the solver stack map onto PROFILE.md's
component-cost rows:

    svdj/gram            Gram panel formation (einsum or Pallas kernel)
    svdj/rotations       the rotation generator (Pallas kernels / reference)
    svdj/apply           rotation apply matmuls (unfused form)
    svdj/apply_exchange  fused apply+exchange(+gram) kernel
    svdj/exchange        tournament block exchange (ring hop on mesh)
    svdj/precondition_qr Drmac QR preconditioning
    svdj/reconstitute    mixed-bulk Newton-Schulz + X = L @ G rebuild
    svdj/postprocess     sigma sort + factor normalization
    svdj/sigma_refine    post-convergence sigma refinement
    svdj/recombine       preconditioned-path factor recombination
    svdj/pair_solve      XLA block solvers (gram-eigh / qr-svd)
"""

from __future__ import annotations

import jax

PREFIX = "svdj"


def scope(name: str):
    """Context manager annotating ops traced inside with ``svdj/<name>``."""
    return jax.named_scope(f"{PREFIX}/{name}")
