"""Robust `jax.profiler` trace context.

Successor of `utils.profiling.trace` (which re-exports this): creates the
log directory if missing and degrades to a warning — instead of raising
mid-solve — when the profiler is unavailable on the backend (some CPU
jaxlibs and remote-attachment tunnels ship without profiler support, and a
failed `start_trace` used to kill the solve it was meant to observe).
"""

from __future__ import annotations

import contextlib
import warnings
from pathlib import Path


@contextlib.contextmanager
def trace(log_dir):
    """XLA profiler trace of the enclosed block (TensorBoard/Perfetto).

    Creates ``log_dir`` (parents included) if missing. If the profiler
    cannot start — backend without profiler support, or a trace already
    active — warns and runs the block untraced instead of raising.
    """
    import jax

    log_dir = Path(log_dir)
    started = False
    try:
        log_dir.mkdir(parents=True, exist_ok=True)
        jax.profiler.start_trace(str(log_dir))
        started = True
    except Exception as e:  # profiler unavailable: observe-only must not kill
        warnings.warn(f"obs.trace: profiler unavailable, running untraced "
                      f"({type(e).__name__}: {e})", RuntimeWarning,
                      stacklevel=3)
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception as e:
                warnings.warn(f"obs.trace: stop_trace failed "
                              f"({type(e).__name__}: {e})", RuntimeWarning,
                              stacklevel=3)
