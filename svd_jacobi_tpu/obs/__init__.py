"""Observability subsystem: in-graph metrics, named-scope tracing, and
structured run manifests.

Three pillars (successors of the reference's wall-clock bracket + free-text
report file, main.cu:1586-1669):

  * `obs.metrics` — a jit-safe event stream: the fused solve loops
    (`solver.py`, `ops/rounds.py`, `parallel/sharded.py`) emit per-sweep
    off-norm, stage transitions, and rotation-round counters through
    `jax.debug.callback` from INSIDE `lax.while_loop`/`lax.scan`, gated by
    a static flag so the telemetry-off path compiles to identical HLO.
    The sharded path emits already-pmax'd (replicated) values and the host
    sink reports once per event from process 0.
  * `obs.scopes` — `jax.named_scope` annotations on every hot region
    (Gram panels, rotation kernels, apply+exchange, QR precondition,
    polish, recombination), so `--profile` Perfetto/TensorBoard traces map
    to code instead of anonymous fusions. Always on: scopes are metadata
    only and cost nothing at runtime.
  * `obs.manifest` — schema-versioned JSONL run records (device topology,
    jaxlib/config hash, per-stage wall time, sweep telemetry, residuals)
    written by `cli.py` and `bench.py`; `scripts/telemetry_summary.py`
    renders or diffs them.

`obs.trace(dir)` wraps `jax.profiler` traces robustly (creates the dir,
warns instead of raising when the profiler is unavailable).

The serving flight recorder adds two live pillars on top (both
stdlib-only at import, loadable without jax):

  * `obs.registry` — the in-process metrics registry (counters / gauges
    / explicit-bucket histograms with Prometheus text exposition), SLO
    accounting (`SLOTracker`), and offline reconstruction of both from
    the manifest stream (`registry_from_manifest`, `slo_from_records`).
  * `obs.spans` — per-request trace timelines (`SpanRecorder` live,
    `timeline_from_manifest` offline) and the `XprofWindow` hook that
    captures a `jax.profiler` trace of exactly one request's
    dispatch..finish window.

The roofline observatory (`python -m svd_jacobi_tpu.perf`) closes the
loop from scopes to numbers, all stdlib-only on the read side:

  * `obs.costmodel` — analytic FLOP/HBM-byte model per phase and per
    registry entry (two conventions: true arithmetic for rooflines,
    XLA `cost_analysis` accounting for the PERF001 agreement check),
    plus the device peak-FLOP/HBM-bandwidth tables with provenance.
  * `obs.attribution` — stdlib parser for `jax.profiler` `.xplane.pb`
    captures: joins device-plane events to `svdj/` named scopes through
    the embedded HLO metadata and folds durations per `HOT_SCOPES` key.
  * `obs.perf` — the `report`/`model`/`check` CLI, `ConvergenceRecorder`
    (per-sweep off_rel series at zero extra readback), and the bench
    noise-band regression gate.
"""

from . import attribution, costmodel, manifest, metrics, perf, registry
from . import scopes, spans
from .metrics import capture, emit, enabled
from .perf import ConvergenceRecorder
from .registry import MetricsRegistry, SLOTracker
from .scopes import scope
from .spans import SpanRecorder
from .trace import trace

__all__ = ["attribution", "costmodel", "manifest", "metrics", "perf",
           "registry", "scopes", "spans",
           "capture", "emit", "enabled", "scope", "trace",
           "ConvergenceRecorder", "MetricsRegistry", "SLOTracker",
           "SpanRecorder"]
