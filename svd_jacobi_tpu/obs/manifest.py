"""Schema-versioned structured run manifests (JSONL).

Successor of the reference's free-text report file (main.cu:1667-1669) and
of this repo's own ad-hoc `report-dimension-*.json` dumps: every CLI/bench
run appends ONE self-describing JSON record to a `.jsonl` manifest, so runs
accumulate in a single greppable/diffable stream instead of littering
timestamped files. `scripts/telemetry_summary.py` renders a manifest or
diffs two records.

A record carries:

  * identity: ``schema_version``, ``kind`` ("cli" | "bench"), ``timestamp``;
  * environment: jax/jaxlib versions, backend, device kind/count/topology,
    process count — everything needed to know WHERE a number came from;
  * the solve spec: dimension, dtype, solver config + its content hash
    (``config_sha256`` — two records with equal hashes ran the same solver
    configuration, whatever the field spelling);
  * results: per-stage wall times, solve metrics (time, sweeps, off-norm,
    residual/orthogonality, sigma error), and — when telemetry was on —
    the in-graph per-sweep event stream from `obs.metrics`.

Validation is self-contained (`validate`): required keys and types are
checked against `SCHEMA`, unknown extra keys are allowed (forward
compatibility), and version mismatches fail loudly.
"""

from __future__ import annotations

import dataclasses
import datetime
import hashlib
import json
import threading
from collections import OrderedDict
from pathlib import Path
from typing import Any, Dict, List, NamedTuple, Optional

SCHEMA_VERSION = 1

# Required top-level fields and their types. Optional fields are listed
# with ``None`` allowed. Nested specs: dicts map field -> type-tuple.
# Records come in two shapes sharing the identity/environment base:
# solve records ("cli" | "bench") and static-analysis reports ("analysis",
# written by `python -m svd_jacobi_tpu.analysis`).
_NUM = (int, float)
_BASE_SCHEMA: Dict[str, Any] = {
    "schema_version": int,
    "kind": str,                      # "cli" | "bench" | "analysis"
    "timestamp": str,                 # ISO 8601
    "environment": {
        "jax": str,
        "jaxlib": str,
        "backend": str,               # "cpu" | "tpu" | ...
        "device_kind": str,
        "device_count": int,
        "process_count": int,
    },
}
_SOLVE_SCHEMA: Dict[str, Any] = {
    "dimension": {"m": int, "n": int},
    "dtype": str,
    "config": dict,
    "config_sha256": str,
    "stages": list,                   # [{"name": str, "time_s": float}]
    "solve": dict,                    # time_s/sweeps/off_norm/residual_rel...
    "telemetry": (list, type(None)),  # obs.metrics events, or None when off
}
_ANALYSIS_SCHEMA: Dict[str, Any] = {
    "passes": list,                   # [{"name", "ok", "findings", "time_s"}]
    "ok": bool,
    "findings_total": int,
}
# Escalation episodes ("retry", written by resilience.resilient_svd): one
# record per guarded solve that walked the retry ladder, attempts inline.
_RETRY_SCHEMA: Dict[str, Any] = {
    "dimension": {"m": int, "n": int},
    "dtype": str,
    "config": dict,                   # the BASE config the episode started from
    "config_sha256": str,
    "attempts": list,                 # [{"rung", "status", "time_s", ...}]
    "final_status": str,              # SolveStatus name of the last attempt
}
# Per-request serving records ("serve", written by serve.SVDService):
# one record per request — served, degraded, timed out, or REJECTED at
# admission — so the whole service history (breaker trips, brownout
# steps, shed load) reconstructs from the manifest stream alone.
_SERVE_SCHEMA: Dict[str, Any] = {
    "request": {"id": str, "m": int, "n": int, "dtype": str},
    "bucket": (str, type(None)),      # padded-shape bucket; None = rejected
    "queue_wait_s": _NUM,
    "solve_time_s": (*_NUM, type(None)),  # None = never solved
    "status": str,                    # SolveStatus name | ERROR | REJECTED_*
    "path": str,                      # "base" | "ladder" | "rejected"
    "breaker": str,                   # BreakerState value after the outcome
    "brownout": str,                  # Brownout level name at admission
    # batch_id/batch_size/batch_tier additionally identify a COALESCED
    # dispatch (micro-batched solve lane; all None on a single dispatch).
    # Optional-by-forward-compatibility: records written before the
    # batching lane lack them, so they ride as extra keys rather than
    # required schema fields. In fleet mode (ServeConfig.lanes > 1) a
    # ``lane`` extra key carries the dispatching lane index.
}
# Two-phase serving fields of the serve record — optional (pre-σ-first
# streams lack them) but type-checked when present (`validate`).
# ``digest`` is the oriented-input SHA-256 (the ResultCache / replica-
# router resubmit key), exposed per-request when digesting is on.
_SERVE_PHASE_FIELDS: Dict[str, Any] = {
    "phase": str,                       # "full" | "sigma" | "promote"
    "promoted_from": (str, type(None)),
    "digest": (str, type(None)),
    # The submitting tenant (multi-tenant front door). Optional so
    # pre-tenancy streams stay valid; type-checked when present — the
    # per-tenant SLO/fairness reconstruction keys on it.
    "tenant": str,
}
# Federation events ("router", written by serve.router): one record per
# replica state transition / journal rescue / routing decision / probe /
# healthz snapshot, so a federated deployment's whole replica-death ->
# rescue -> recovery history reconstructs from the manifest stream —
# the "fleet" kind's shape, one fault-domain ring up. ``replica`` is
# None for router-wide events.
_ROUTER_SCHEMA: Dict[str, Any] = {
    "event": str,                 # replica_transition | rescue | route |
                                  # probe | healthz
    "replica": (int, type(None)),
}
# Network-transport events ("net", written by serve.transport's
# HttpReplica client and HttpReplicaServer): one record per RPC retry /
# timeout / failover / lease grant-or-expiry / fence / fence refusal /
# quarantine transition / partition heal, so a multi-HOST deployment's
# whole unreliable-network history — who retried what, which lease
# lapsed, which fencing token refused whose stale finalization —
# reconstructs from the same manifest stream as everything else
# (`obs.registry.registry_from_manifest` rebuilds the svdj_rpc_*
# counters from exactly these records). ``replica`` is None for
# transport-wide events.
_NET_SCHEMA: Dict[str, Any] = {
    "event": str,                 # rpc_retry | rpc_timeout | rpc_error |
                                  # failover | lease_grant |
                                  # lease_expired | fence | fence_refused
                                  # | quarantine | heal | partition_heal
    "replica": (int, type(None)),
}
# Autotuner search records ("tune", written by tune.search per searched
# shape): the full measured grid — baseline knobs/time, every candidate
# point's knobs/time/ok, and the winning knob set — plus the id/hash of
# the table the run wrote, so a tuning table's provenance reconstructs
# from the record stream alone (which grid, which times, which verdict).
_TUNE_SCHEMA: Dict[str, Any] = {
    "dimension": {"m": int, "n": int},
    "dtype": str,
    "key": dict,                  # n_class/aspect/dtype/backend/device_kind
    "baseline": dict,             # {"knobs", "time_s", "reps", "ok", "note"}
    "grid": list,                 # [{"knobs", "time_s", "reps", "ok"}]
    "winner": dict,               # the knob set the table row encodes
    "table_id": str,
    "table_sha256": str,
}
# Fleet events ("fleet", written by serve.fleet in lanes mode): one
# record per lane state transition / rescue / steal / probe / healthz
# snapshot / ladder_overrun, so the whole eviction -> rescue -> recovery
# history of a multi-lane service reconstructs from the manifest stream
# alone. ``lane`` is None for fleet-wide events (e.g. healthz).
_FLEET_SCHEMA: Dict[str, Any] = {
    "event": str,                 # lane_transition | rescue | steal |
                                  # probe | healthz | ladder_overrun
    "lane": (int, type(None)),
}
# Cold-start reports ("coldstart", written by SVDService.warmup): one
# record per warmup — every registry entry's ahead-of-time compile time
# and whether the persistent executable cache served it
# (fresh_compiles == 0), so the cost of every restart is measurable from
# the manifest stream (warm restarts must read ~all cache hits).
_COLDSTART_SCHEMA: Dict[str, Any] = {
    "entries": list,              # [{"entry", "time_s", "cache_hit", ...}]
    "total_s": _NUM,
    "backend_compiles": int,
    "cache_hits": int,
    "fresh_compiles": int,
    "cache_dir": (str, type(None)),   # None = persistent cache disabled
    "config_sha256": (str, type(None)),
}
_COLDSTART_ENTRY_FIELDS = {"entry": str, "time_s": _NUM, "cache_hit": bool}
# Result-cache / promotion-store events ("cache", written by
# serve.SVDService around serve.cache): one record per store / hit /
# evict / invalidate of the content-addressed result cache and per
# retain / promote / release / evict / rescue of the sigma-phase
# promotion store, so the whole don't-recompute history (which request
# hit, what was evicted under the byte budget, when a client
# invalidated) reconstructs from the manifest stream. ``store`` names
# which store ("result" | "promotion"); ``digest``/``request_id`` are
# event-dependent (an invalidate-all has neither).
_CACHE_SCHEMA: Dict[str, Any] = {
    "store": str,                 # "result" | "promotion"
    "event": str,                 # store|hit|evict|invalidate|retain|
                                  # promote|release|rescue
    "request_id": (str, type(None)),
    "digest": (str, type(None)),  # SHA-256 input digest (result store)
    "bytes": (int, type(None)),   # entry size (store/retain/evict)
}
# Roofline attribution reports ("perf", written by `python -m
# svd_jacobi_tpu.perf`, `cli --profile`, and the serve capture path): one
# record per measured window — per-scope device time joined with the
# analytic cost model (obs.costmodel) into achieved GFLOP/s, GB/s,
# %-of-roofline and a compute/bandwidth bound classification — plus the
# per-sweep convergence telemetry of the window when the host-stepped
# loop recorded one. The SAME row shape is produced live (after a
# --profile solve) and offline (perf report over a checked-in trace), so
# the offline-equals-live contract is testable record-for-record.
# ``device`` carries the roofline constants WITH their provenance
# (peak_flops_source/hbm_bw_source: "table" | "peak_est" | "bw_est") so
# a % -of-roof number can never silently rest on an estimate.
_PERF_SCHEMA: Dict[str, Any] = {
    "source": str,                # "trace" | "live" | "convergence"
    "workload": dict,             # {"m", "n", "dtype", model params...}
    "device": dict,               # peak_flops/hbm_bw + *_source provenance
    "scopes": list,               # attribution rows (_PERF_SCOPE_FIELDS)
    "unscoped_s": _NUM,           # HLO time with no svdj scope
    "unattributed_s": _NUM,       # non-HLO (host/python) trace time
    "convergence": (dict, type(None)),  # per-sweep series, or None
}
_PERF_SCOPE_FIELDS = {"scope": str, "phase": str, "seconds": _NUM,
                      "events": int}
# Back-compat name: the solve-record schema as one flat dict.
SCHEMA: Dict[str, Any] = {**_BASE_SCHEMA, **_SOLVE_SCHEMA}

_STAGE_FIELDS = {"name": str, "time_s": _NUM}
_SOLVE_REQUIRED = {"time_s": _NUM, "sweeps": int, "off_norm": _NUM}
_EVENT_REQUIRED = {"event": str}
_PASS_FIELDS = {"name": str, "ok": bool, "findings": list, "time_s": _NUM}
_ATTEMPT_FIELDS = {"rung": str, "status": str, "time_s": _NUM}


def offline_environment() -> dict:
    """Environment block for READ-SIDE record builders (perf report over
    a checked-in trace on a machine without jax): schema-valid, loudly
    marked offline rather than pretending a runtime was attached."""
    return {"jax": "offline", "jaxlib": "offline", "backend": "offline",
            "device_kind": "offline", "device_count": 0,
            "process_count": 0}


def environment() -> dict:
    """Environment block: versions + device topology of THIS runtime."""
    import jax
    import jaxlib
    devices = jax.devices()
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": devices[0].platform if devices else "unknown",
        "device_kind": devices[0].device_kind if devices else "unknown",
        "device_count": len(devices),
        "process_count": jax.process_count(),
    }


def config_hash(config) -> str:
    """Content hash of a solver configuration (SVDConfig or plain dict):
    canonical-JSON SHA-256, so two runs with equal hashes solved under the
    same configuration regardless of how the record spells the fields."""
    if dataclasses.is_dataclass(config):
        config = dataclasses.asdict(config)
    canon = json.dumps(config, sort_keys=True, default=str)
    return hashlib.sha256(canon.encode()).hexdigest()


def build(kind: str, *, m: int, n: int, dtype: str, config,
          solve: dict, stages: Optional[List[dict]] = None,
          telemetry: Optional[List[dict]] = None, **extra) -> dict:
    """Assemble a schema-valid record. ``extra`` keys (seed, matrix,
    distributed, argv, self_test, ...) ride along at top level — the
    schema allows unknown keys so drivers can attach context freely."""
    if dataclasses.is_dataclass(config):
        config_dict = dataclasses.asdict(config)
    else:
        config_dict = dict(config)
    record = {
        "schema_version": SCHEMA_VERSION,
        "kind": kind,
        "timestamp": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "environment": environment(),
        "dimension": {"m": int(m), "n": int(n)},
        "dtype": str(dtype),
        "config": {k: (v if v is None or isinstance(v, (bool, int, float,
                                                        str)) else str(v))
                   for k, v in config_dict.items()},
        "config_sha256": config_hash(config_dict),
        "stages": list(stages or []),
        "solve": dict(solve),
        "telemetry": telemetry,
    }
    record.update(extra)
    validate(record)
    return record


def build_analysis(*, passes: List[dict], **extra) -> dict:
    """Assemble a schema-valid static-analysis record
    (`python -m svd_jacobi_tpu.analysis`). ``passes``:
    [{"name", "ok", "findings": [finding dicts], "time_s"}]; overall
    ``ok``/``findings_total`` are derived. ``extra`` rides along like in
    `build`."""
    passes = [dict(p) for p in passes]
    total = sum(len(p.get("findings") or []) for p in passes)
    record = {
        "schema_version": SCHEMA_VERSION,
        "kind": "analysis",
        "timestamp": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "environment": environment(),
        "passes": passes,
        "ok": all(p.get("ok", False) for p in passes),
        "findings_total": total,
    }
    record.update(extra)
    validate(record)
    return record


def build_retry(*, m: int, n: int, dtype: str, config, attempts: List[dict],
                final_status: str, **extra) -> dict:
    """Assemble a schema-valid escalation-episode record
    (`resilience.resilient_svd`). ``attempts``: one dict per ladder rung
    actually run ({"rung", "status", "time_s", "sweeps", "off_norm",
    "config_sha256"}); ``final_status`` is the last attempt's SolveStatus
    name. ``extra`` rides along like in `build`."""
    if dataclasses.is_dataclass(config):
        config_dict = dataclasses.asdict(config)
    else:
        config_dict = dict(config)
    record = {
        "schema_version": SCHEMA_VERSION,
        "kind": "retry",
        "timestamp": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "environment": environment(),
        "dimension": {"m": int(m), "n": int(n)},
        "dtype": str(dtype),
        "config": {k: (v if v is None or isinstance(v, (bool, int, float,
                                                        str)) else str(v))
                   for k, v in config_dict.items()},
        "config_sha256": config_hash(config_dict),
        "attempts": [dict(a) for a in attempts],
        "final_status": str(final_status),
    }
    record.update(extra)
    validate(record)
    return record


def build_serve(*, request_id: str, m: int, n: int, dtype: str,
                bucket: Optional[str], queue_wait_s: float,
                solve_time_s: Optional[float], status: str, path: str,
                breaker: str, brownout: str,
                batch_id: Optional[str] = None,
                batch_size: Optional[int] = None,
                batch_tier: Optional[int] = None,
                rank_mode: str = "full",
                k: Optional[int] = None,
                phase: str = "full",
                promoted_from: Optional[str] = None,
                digest: Optional[str] = None,
                tenant: str = "default", **extra) -> dict:
    """Assemble a schema-valid per-request serving record
    (`serve.SVDService`). ``batch_id``/``batch_size``/``batch_tier``
    identify a COALESCED dispatch (micro-batched solve lane): every
    member of one batched solve shares the batch_id, ``batch_size`` is
    the real member count and ``batch_tier`` the padded static tier it
    snapped to; all None for a single (uncoalesced) dispatch.
    ``rank_mode`` is the workload family the request dispatched through
    ("full" | "tall" | "topk") and ``k`` the requested top-k rank (None
    unless rank_mode is "topk") — together they make the truncated-
    workload traffic reconstructable from the stream. ``phase`` is the
    two-phase serving stage this record closes ("full" | "sigma" |
    "promote"); a "promote" record carries ``promoted_from`` — the
    sigma-phase request id whose retained solve state it resumed — so a
    σ-then-promote pair reconstructs from the stream. ``tenant`` is the
    submitting tenant ("default" on the single-caller surface) — it
    makes per-tenant SLO and fairness accounting reconstructable
    offline. ``extra`` (degraded, deadline_s, sweeps, error, ...) rides
    along like in `build`."""
    record = {
        "schema_version": SCHEMA_VERSION,
        "kind": "serve",
        "timestamp": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "environment": environment(),
        "request": {"id": str(request_id), "m": int(m), "n": int(n),
                    "dtype": str(dtype)},
        "bucket": None if bucket is None else str(bucket),
        "queue_wait_s": float(queue_wait_s),
        "solve_time_s": None if solve_time_s is None else float(solve_time_s),
        "status": str(status),
        "path": str(path),
        "breaker": str(breaker),
        "brownout": str(brownout),
        "batch_id": None if batch_id is None else str(batch_id),
        "batch_size": None if batch_size is None else int(batch_size),
        "batch_tier": None if batch_tier is None else int(batch_tier),
        "rank_mode": str(rank_mode),
        "k": None if k is None else int(k),
        "phase": str(phase),
        "promoted_from": (None if promoted_from is None
                          else str(promoted_from)),
        "digest": None if digest is None else str(digest),
        "tenant": str(tenant),
    }
    record.update(extra)
    validate(record)
    return record


def build_cache(*, store: str, event: str,
                request_id: Optional[str] = None,
                digest: Optional[str] = None,
                nbytes: Optional[int] = None, **extra) -> dict:
    """Assemble a schema-valid cache event record (`serve.cache` via
    `serve.SVDService`): see ``_CACHE_SCHEMA`` for the store/event
    vocabulary. ``extra`` (count, evicted_of, lane, ...) rides along
    like in `build`."""
    record = {
        "schema_version": SCHEMA_VERSION,
        "kind": "cache",
        "timestamp": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "environment": environment(),
        "store": str(store),
        "event": str(event),
        "request_id": None if request_id is None else str(request_id),
        "digest": None if digest is None else str(digest),
        "bytes": None if nbytes is None else int(nbytes),
    }
    record.update(extra)
    validate(record)
    return record


def build_tune(*, m: int, n: int, dtype: str, key: dict, baseline: dict,
               grid: List[dict], winner: dict, table_id: str,
               table_sha256: str, **extra) -> dict:
    """Assemble a schema-valid autotuner search record (`tune.search`):
    one per searched shape — the (class) key, the measured baseline, every
    grid point, the winning knob set, and the written table's identity.
    ``extra`` (tiers, smoke, argv, ...) rides along like in `build`."""
    record = {
        "schema_version": SCHEMA_VERSION,
        "kind": "tune",
        "timestamp": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "environment": environment(),
        "dimension": {"m": int(m), "n": int(n)},
        "dtype": str(dtype),
        "key": dict(key),
        "baseline": dict(baseline),
        "grid": [dict(p) for p in grid],
        "winner": dict(winner),
        "table_id": str(table_id),
        "table_sha256": str(table_sha256),
    }
    record.update(extra)
    validate(record)
    return record


def build_coldstart(*, entries: List[dict], total_s: float,
                    backend_compiles: int, cache_hits: int,
                    fresh_compiles: int, cache_dir: Optional[str],
                    config_sha256: Optional[str], **extra) -> dict:
    """Assemble a schema-valid cold-start record
    (`serve.SVDService.warmup`): the per-entry AOT compile timings of one
    warmup pass. ``entries``: one dict per registry entry
    ({"entry", "time_s", "cache_hit", "backend_compiles", "cache_hits",
    "fresh_compiles", "jits"}); the top-level counters aggregate them
    plus the zero-solve execution phase. ``cache_dir``/``config_sha256``
    identify the persistent cache namespace (None when disabled).
    ``extra`` (exec_s, aot_s, lanes, ...) rides along like in `build`."""
    record = {
        "schema_version": SCHEMA_VERSION,
        "kind": "coldstart",
        "timestamp": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "environment": environment(),
        "entries": [dict(e) for e in entries],
        "total_s": float(total_s),
        "backend_compiles": int(backend_compiles),
        "cache_hits": int(cache_hits),
        "fresh_compiles": int(fresh_compiles),
        "cache_dir": None if cache_dir is None else str(cache_dir),
        "config_sha256": (None if config_sha256 is None
                          else str(config_sha256)),
    }
    record.update(extra)
    validate(record)
    return record


def build_fleet(*, event: str, lane: Optional[int] = None, **extra) -> dict:
    """Assemble a schema-valid fleet event record (`serve.fleet`).

    ``event`` enumerates the fleet happenings worth reconstructing:
    ``lane_transition`` (with ``from_state``/``to_state``/``cause``
    extras), ``rescue`` (``count``/``request_ids``), ``steal``
    (``victim``/``request_id``), ``probe`` (``ok``/``request_id``),
    ``healthz`` (a fleet snapshot dict), and ``ladder_overrun`` (the
    escalation-ladder watchdog fired — ``elapsed_s``/``budget_s``).
    ``lane`` is the subject lane's index, or None for fleet-wide events.
    ``extra`` rides along like in `build`."""
    record = {
        "schema_version": SCHEMA_VERSION,
        "kind": "fleet",
        "timestamp": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "environment": environment(),
        "event": str(event),
        "lane": None if lane is None else int(lane),
    }
    record.update(extra)
    validate(record)
    return record


def build_router(*, event: str, replica: Optional[int] = None,
                 **extra) -> dict:
    """Assemble a schema-valid federation event record (`serve.router`).

    ``event`` enumerates the router happenings worth reconstructing:
    ``replica_transition`` (``from_state``/``to_state``/``cause``
    extras), ``rescue`` (``count``/``request_ids``/``targets`` — one
    per journal-rescue of a dead replica's debt), ``route`` (one per
    admitted request: ``request_id``/``bucket``/``digest``/``resubmit``
    — the consistent-hash verdict, so routing determinism is auditable
    from the stream), ``probe`` (``ok``/``request_id``), and
    ``healthz`` (a federation snapshot dict). ``replica`` is the
    subject replica's index, or None for router-wide events. ``extra``
    rides along like in `build`."""
    record = {
        "schema_version": SCHEMA_VERSION,
        "kind": "router",
        "timestamp": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "environment": environment(),
        "event": str(event),
        "replica": None if replica is None else int(replica),
    }
    record.update(extra)
    validate(record)
    return record


def build_net(*, event: str, replica: Optional[int] = None,
              **extra) -> dict:
    """Assemble a schema-valid network-transport event record
    (`serve.transport`).

    ``event`` enumerates the unreliable-network happenings worth
    reconstructing: ``rpc_retry`` (``op``/``attempt``/``delay_s``),
    ``rpc_timeout`` / ``rpc_error`` (``op``/``error`` — a budget- or
    attempt-exhausted RPC), ``failover`` (``op``/``from_replica`` — the
    ring walked past an unreachable host), ``lease_grant`` /
    ``lease_expired`` (``token``/``ttl_s``), ``fence`` (a fencing token
    bump or delivery, ``token``), ``fence_refused`` (a stale token
    refused, ``token``/``held_token``), ``quarantine`` / ``heal`` (the
    half-open connection breaker's transitions), and ``partition_heal``
    (a quarantined host answered again). ``replica`` is the subject
    replica's index, or None for transport-wide events. ``extra`` rides
    along like in `build`."""
    record = {
        "schema_version": SCHEMA_VERSION,
        "kind": "net",
        "timestamp": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "environment": environment(),
        "event": str(event),
        "replica": None if replica is None else int(replica),
    }
    record.update(extra)
    validate(record)
    return record


def build_perf(*, source: str, workload: dict, device: dict,
               scopes: List[dict], unscoped_s: float = 0.0,
               unattributed_s: float = 0.0,
               convergence: Optional[dict] = None, **extra) -> dict:
    """Assemble a schema-valid roofline attribution record ("perf").

    ``source``: "trace" (offline, from an .xplane.pb), "live" (emitted
    right after a profiled solve), or "convergence" (telemetry-only — no
    trace, scopes empty). ``workload``: the cost-model parameters the
    rows were computed under ({"m", "n", "dtype", "block_size",
    "sweeps", ...}). ``device``: roofline constants with provenance
    ({"device_kind", "peak_flops", "peak_flops_source", "hbm_bw",
    "hbm_bw_source"}). ``scopes``: `obs.attribution.attribute` rows.
    ``convergence``: per-sweep series ({"off_rel": [...], "stages":
    [...], "sweeps_to_tol", "rotations_skipped_frac", "spectrum"}).
    Builds without jax installed (read-side contract): the environment
    block degrades to `offline_environment`.
    """
    try:
        env = environment()
    except ImportError:
        env = offline_environment()
    record = {
        "schema_version": SCHEMA_VERSION,
        "kind": "perf",
        "timestamp": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "environment": env,
        "source": str(source),
        "workload": dict(workload),
        "device": dict(device),
        "scopes": [dict(s) for s in scopes],
        "unscoped_s": float(unscoped_s),
        "unattributed_s": float(unattributed_s),
        "convergence": None if convergence is None else dict(convergence),
    }
    record.update(extra)
    validate(record)
    return record


def _check(cond: bool, errors: List[str], msg: str) -> None:
    if not cond:
        errors.append(msg)


def _check_fields(obj, spec, where: str, errors: List[str]) -> None:
    if not isinstance(obj, dict):
        errors.append(f"{where}: expected object, got {type(obj).__name__}")
        return
    for key, typ in spec.items():
        if key not in obj:
            errors.append(f"{where}.{key}: missing")
        elif isinstance(typ, dict):
            _check_fields(obj[key], typ, f"{where}.{key}", errors)
        elif not isinstance(obj[key], typ):
            errors.append(f"{where}.{key}: expected "
                          f"{getattr(typ, '__name__', typ)}, got "
                          f"{type(obj[key]).__name__}")


def _validate_analysis(record: dict, errors: List[str]) -> None:
    _check_fields(record, _ANALYSIS_SCHEMA, "record", errors)
    for i, p in enumerate(record.get("passes") or []):
        _check_fields(p, _PASS_FIELDS, f"record.passes[{i}]", errors)


def _validate_retry(record: dict, errors: List[str]) -> None:
    _check_fields(record, _RETRY_SCHEMA, "record", errors)
    for i, at in enumerate(record.get("attempts") or []):
        _check_fields(at, _ATTEMPT_FIELDS, f"record.attempts[{i}]",
                      errors)


def _validate_serve(record: dict, errors: List[str]) -> None:
    _check_fields(record, _SERVE_SCHEMA, "record", errors)
    # Two-phase fields are optional-by-forward-compatibility
    # (records written before the σ-first lane lack them) but
    # type-checked when present: "phase" names the serving stage the
    # record closes, "promoted_from" the sigma request a promote
    # resumed.
    _check_fields({k: record[k] for k in _SERVE_PHASE_FIELDS
                   if k in record},
                  {k: t for k, t in _SERVE_PHASE_FIELDS.items()
                   if k in record}, "record", errors)


def _validate_tune(record: dict, errors: List[str]) -> None:
    _check_fields(record, _TUNE_SCHEMA, "record", errors)
    for i, p in enumerate(record.get("grid") or []):
        if not isinstance(p, dict) or not isinstance(p.get("knobs"),
                                                     dict):
            errors.append(f"record.grid[{i}]: expected an object with "
                          f"a 'knobs' dict")


def _validate_fleet(record: dict, errors: List[str]) -> None:
    _check_fields(record, _FLEET_SCHEMA, "record", errors)


def _validate_cache(record: dict, errors: List[str]) -> None:
    _check_fields(record, _CACHE_SCHEMA, "record", errors)


def _validate_router(record: dict, errors: List[str]) -> None:
    _check_fields(record, _ROUTER_SCHEMA, "record", errors)


def _validate_net(record: dict, errors: List[str]) -> None:
    _check_fields(record, _NET_SCHEMA, "record", errors)


def _validate_perf(record: dict, errors: List[str]) -> None:
    _check_fields(record, _PERF_SCHEMA, "record", errors)
    for i, s in enumerate(record.get("scopes") or []):
        _check_fields(s, _PERF_SCOPE_FIELDS, f"record.scopes[{i}]",
                      errors)


def _validate_coldstart(record: dict, errors: List[str]) -> None:
    _check_fields(record, _COLDSTART_SCHEMA, "record", errors)
    for i, e in enumerate(record.get("entries") or []):
        _check_fields(e, _COLDSTART_ENTRY_FIELDS,
                      f"record.entries[{i}]", errors)


def _validate_solve(record: dict, errors: List[str]) -> None:
    """The solve-record shape ("cli"/"bench" — and the forward-compat
    fallback for kinds this version does not know)."""
    _check_fields(record, _SOLVE_SCHEMA, "record", errors)
    for i, st in enumerate(record.get("stages") or []):
        _check_fields(st, _STAGE_FIELDS, f"record.stages[{i}]", errors)
    if isinstance(record.get("solve"), dict):
        _check_fields(record["solve"], _SOLVE_REQUIRED, "record.solve",
                      errors)
    tel = record.get("telemetry")
    if tel is not None:
        for i, ev in enumerate(tel):
            _check_fields(ev, _EVENT_REQUIRED, f"record.telemetry[{i}]",
                          errors)


def validate(record: dict) -> None:
    """Raise ValueError listing every schema violation (empty = valid).
    Per-kind validation dispatches through the `KINDS` registry; a kind
    the registry does not know falls back to the solve-record shape
    (forward compatibility — the original behavior, byte-for-byte)."""
    errors: List[str] = []
    _check(isinstance(record, dict), errors, "record: not an object")
    if not isinstance(record, dict):
        raise ValueError("; ".join(errors))
    _check_fields(record, _BASE_SCHEMA, "record", errors)
    if record.get("schema_version") not in (None, SCHEMA_VERSION):
        errors.append(f"record.schema_version: {record['schema_version']} "
                      f"!= supported {SCHEMA_VERSION}")
    kind = _kind_for(record)
    (kind.validator if kind is not None else _validate_solve)(record,
                                                              errors)
    if errors:
        raise ValueError("invalid manifest record: " + "; ".join(errors))


def _kind_for(record: dict):
    """The record's registered kind row, or None for the solve-shape
    fallback. A non-string (even unhashable — a list-valued "kind" is
    well-formed JSON) falls back like any unknown kind, matching the
    pre-registry if/elif behavior instead of raising TypeError."""
    kind = record.get("kind")
    return KINDS.get(kind) if isinstance(kind, str) else None


# Per-path append locks: concurrent appends from worker/client threads
# must serialize per file, or two large lines could interleave mid-line
# through the OS write path and BOTH come back torn. The guard is
# created at import: minting it lazily would itself race (two threads
# making the process's first appends could each see None and mint
# separate guards — and therefore separate per-path locks).
#
# The map is LRU-BOUNDED: a long-lived process appending to many
# distinct paths (per-run manifests, per-test journals) must not grow
# it forever. Eviction only ever removes an IDLE lock (not .locked()),
# so a writer mid-append keeps exclusivity; the map may exceed the cap
# while more than _APPEND_LOCKS_MAX locks are simultaneously held. Two
# threads appending to the same path need the same lock OBJECT only
# while both are in flight — an idle lock evicted and re-minted later
# still serializes correctly because nobody holds the old one.
_APPEND_LOCKS: "OrderedDict[str, Any]" = OrderedDict()
_APPEND_LOCKS_MAX = 64
_APPEND_LOCKS_GUARD = threading.Lock()


def _append_lock(path: str):
    with _APPEND_LOCKS_GUARD:
        lock = _APPEND_LOCKS.get(path)
        if lock is None:
            lock = _APPEND_LOCKS[path] = threading.Lock()
        _APPEND_LOCKS.move_to_end(path)
        while len(_APPEND_LOCKS) > _APPEND_LOCKS_MAX:
            victim = next((p for p in _APPEND_LOCKS
                           if p != path and not _APPEND_LOCKS[p].locked()),
                          None)
            if victim is None:
                break  # everything is held: allow temporary overshoot
            del _APPEND_LOCKS[victim]
        return lock


def append_jsonl(path, record: dict, *, fsync: bool = True) -> Path:
    """Crash-safe JSONL append: one record, one line, written as a
    SINGLE unbuffered ``os.write`` to an O_APPEND fd under a per-path
    lock (two threads appending large lines concurrently must not
    interleave fragments), fsync'd to stable storage before returning (a
    record this function returned for is never lost to a SIGKILL — the
    `utils.checkpoint` discipline, applied per line). If the file's
    current tail is a TORN line (a previous writer died mid-write,
    leaving no trailing newline), a newline is written first so the new
    record can never be concatenated into the torn fragment and parse
    as garbage. The shared low-level writer of the run manifest and the
    serving layer's durable request journal (`serve.journal`)."""
    import os
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    line = (json.dumps(record, sort_keys=True) + "\n").encode()
    with _append_lock(str(path)):
        # O_RDWR, not O_WRONLY: the torn-tail probe pread()s the last
        # byte, which needs read permission on the fd.
        fd = os.open(str(path), os.O_RDWR | os.O_APPEND | os.O_CREAT,
                     0o644)
        try:
            size = os.fstat(fd).st_size
            if size > 0 and os.pread(fd, 1, size - 1) != b"\n":
                os.write(fd, b"\n")
            os.write(fd, line)
            if fsync:
                os.fsync(fd)
        finally:
            os.close(fd)
    return path


def read_jsonl_tolerant(path, *, quarantine: bool = True):
    """Read a JSONL stream, tolerating torn lines: a line that fails to
    parse (the classic SIGKILL-mid-write artifact — most often the
    trailing line) is QUARANTINED to ``<path>.torn`` (appended verbatim,
    for forensics) with a loud `RuntimeWarning`, and every parseable
    record is still returned — one torn record must not take the whole
    stream's history with it. Returns ``(records, torn_count)``."""
    import warnings
    path = Path(path)
    records: List[dict] = []
    torn = 0
    sidecar = Path(str(path) + ".torn")
    # Already-quarantined lines: a torn line stays in the source stream
    # (appends self-repair around it, nothing rewrites it out), so
    # repeated loads would otherwise re-quarantine it — and re-warn —
    # forever. Dedupe against the sidecar's existing content.
    seen: set = set()
    if quarantine and sidecar.exists():
        try:
            seen = set(sidecar.read_text().splitlines())
        except OSError:
            pass
    with path.open() as f:
        for lineno, line in enumerate(f, 1):
            stripped = line.strip()
            if not stripped:
                continue
            try:
                records.append(json.loads(stripped))
            except json.JSONDecodeError:
                torn += 1
                if quarantine and line.rstrip("\n") not in seen:
                    seen.add(line.rstrip("\n"))
                    try:
                        with sidecar.open("a") as sf:
                            sf.write(line if line.endswith("\n")
                                     else line + "\n")
                        where = f"quarantined to {sidecar}"
                    except OSError as e:
                        # A read-only manifest location must still read.
                        where = f"NOT quarantined ({sidecar}: {e})"
                    warnings.warn(
                        f"{path}:{lineno}: torn/unparseable JSONL line "
                        f"{where} "
                        f"({stripped[:60]!r}...)", RuntimeWarning,
                        stacklevel=2)
    return records, torn


def append(path, record: dict) -> Path:
    """Validate and append one JSONL record (creating parent dirs).
    fsync'd per record (`append_jsonl`): a process kill right after a
    request finalizes cannot lose its serve record."""
    validate(record)
    return append_jsonl(path, record)


def load(path, *, quarantine: bool = True) -> List[dict]:
    """Read every record of a JSONL manifest (skipping blank lines). A
    torn trailing line — a writer killed mid-append — is quarantined to
    ``<path>.torn`` with a warning instead of failing the whole stream
    parse (`read_jsonl_tolerant`). Pass ``quarantine=False`` when
    reading a manifest a LIVE process may be appending to (the
    `Journal.scan` discipline): a half-flushed tail is an in-flight
    append, not a crash artifact, and must not be sidecarred."""
    records, _ = read_jsonl_tolerant(path, quarantine=quarantine)
    return records


def _summarize_analysis(record: dict) -> str:
    env = record.get("environment", {})
    lines = [
        f"analysis run @ {record.get('timestamp', '?')}  "
        f"backend={env.get('backend')} "
        f"({env.get('device_count')}x {env.get('device_kind')})",
    ]
    for p in record.get("passes") or []:
        n = len(p.get("findings") or [])
        lines.append(f"  pass {p.get('name', '?'):<10} "
                    f"{'ok' if p.get('ok') else 'FAIL':<4} "
                    f"{n} finding(s)  {p.get('time_s', 0.0):7.2f} s")
    lines.append(f"  overall: {'ok' if record.get('ok') else 'FAIL'} "
                 f"({record.get('findings_total', 0)} findings)")
    return "\n".join(lines)


def _summarize_retry(record: dict) -> str:
    dim = record.get("dimension", {})
    lines = [
        f"retry episode @ {record.get('timestamp', '?')}  "
        f"matrix {dim.get('m')}x{dim.get('n')} {record.get('dtype')}  "
        f"final={record.get('final_status')}",
    ]
    for at in record.get("attempts") or []:
        off = at.get("off_norm")
        off_s = f"{off:.3e}" if isinstance(off, float) else "n/a"
        lines.append(f"  attempt {at.get('rung', '?'):<18} "
                     f"{at.get('status', '?'):<11} "
                     f"sweeps={at.get('sweeps', '?'):>3} off={off_s}  "
                     f"{at.get('time_s', 0.0):7.2f} s")
    return "\n".join(lines)


def _summarize_tune(record: dict) -> str:
    dim = record.get("dimension", {})
    base = record.get("baseline", {})
    bt = base.get("time_s")
    lines = [
        f"tune search @ {record.get('timestamp', '?')}  "
        f"{dim.get('m')}x{dim.get('n')} {record.get('dtype')}  "
        f"table={record.get('table_id')} "
        f"({str(record.get('table_sha256', ''))[:12]})",
        f"  baseline {base.get('knobs', {})}  "
        + (f"{bt:.4f} s" if isinstance(bt, float) else "n/a"),
    ]
    for p in record.get("grid") or []:
        t = p.get("time_s")
        t_s = f"{t:.4f} s" if isinstance(t, float) else \
            (p.get("note") or "n/a")
        lines.append(f"  point {p.get('knobs', {})}  {t_s}")
    lines.append(f"  winner {record.get('winner', {})}")
    return "\n".join(lines)


def _summarize_coldstart(record: dict) -> str:
    hits = sum(1 for e in record.get("entries") or []
               if e.get("cache_hit"))
    total = len(record.get("entries") or [])
    lines = [
        f"coldstart @ {record.get('timestamp', '?')}  "
        f"{record.get('total_s', float('nan')):.2f} s  "
        f"entries {hits}/{total} cache-hit  "
        f"fresh_compiles={record.get('fresh_compiles', '?')}"
        + (f"  cache={record['cache_dir']}"
           if record.get("cache_dir") else "  (no persistent cache)"),
    ]
    for e in record.get("entries") or []:
        lines.append(
            f"  entry {e.get('entry', '?'):<36} "
            f"{e.get('time_s', float('nan')):7.3f} s  "
            f"{'hit' if e.get('cache_hit') else 'COMPILE'}")
    return "\n".join(lines)


def _summarize_fleet(record: dict) -> str:
    lane = record.get("lane")
    line = (f"fleet {record.get('event', '?')} @ "
            f"{record.get('timestamp', '?')}"
            + (f"  lane={lane}" if lane is not None else ""))
    if record.get("event") == "lane_transition":
        line += (f"  {record.get('from_state', '?')} -> "
                 f"{record.get('to_state', '?')} "
                 f"({record.get('cause', '?')})")
    elif record.get("event") == "rescue":
        line += (f"  {record.get('count', '?')} request(s) "
                 f"{record.get('request_ids', [])}")
    elif record.get("event") == "steal":
        line += (f"  {record.get('request_id', '?')} from lane "
                 f"{record.get('victim', '?')}")
    elif record.get("event") == "probe":
        line += (f"  {'ok' if record.get('ok') else 'FAILED'} "
                 f"({record.get('request_id', '?')})")
    elif record.get("event") == "ladder_overrun":
        line += (f"  elapsed={record.get('elapsed_s', float('nan')):.2f}s"
                 f" budget={record.get('budget_s', float('nan')):.2f}s")
    return line


def _summarize_router(record: dict) -> str:
    rep = record.get("replica")
    line = (f"router {record.get('event', '?')} @ "
            f"{record.get('timestamp', '?')}"
            + (f"  replica={rep}" if rep is not None else ""))
    if record.get("event") == "replica_transition":
        line += (f"  {record.get('from_state', '?')} -> "
                 f"{record.get('to_state', '?')} "
                 f"({record.get('cause', '?')})")
    elif record.get("event") == "rescue":
        line += (f"  {record.get('count', '?')} request(s) "
                 f"{record.get('request_ids', [])} -> "
                 f"{record.get('targets', [])}")
    elif record.get("event") == "route":
        line += (f"  {record.get('request_id', '?')} "
                 f"[{record.get('bucket', '?')}] "
                 f"digest={str(record.get('digest') or '')[:12]}")
        if record.get("resubmit"):
            line += " resubmit"
    elif record.get("event") == "probe":
        line += (f"  {'ok' if record.get('ok') else 'FAILED'} "
                 f"({record.get('request_id', '?')})")
    return line


def _summarize_net(record: dict) -> str:
    rep = record.get("replica")
    line = (f"net {record.get('event', '?')} @ "
            f"{record.get('timestamp', '?')}"
            + (f"  replica={rep}" if rep is not None else ""))
    if record.get("op") is not None:
        line += f"  op={record['op']}"
    if record.get("attempt") is not None:
        line += f"  attempt={record['attempt']}"
    if record.get("token") is not None:
        line += f"  token={record['token']}"
        if record.get("held_token") is not None:
            line += f"<held {record['held_token']}"
    if record.get("ttl_s") is not None:
        line += f"  ttl={record['ttl_s']}s"
    if record.get("error"):
        line += f"\n  error: {record['error']}"
    return line


def _summarize_cache(record: dict) -> str:
    line = (f"cache {record.get('store', '?')}/{record.get('event', '?')}"
            f" @ {record.get('timestamp', '?')}")
    if record.get("request_id") is not None:
        line += f"  req={record['request_id']}"
    if record.get("digest") is not None:
        line += f"  digest={str(record['digest'])[:12]}"
    if record.get("bytes") is not None:
        line += f"  {record['bytes']} B"
    if record.get("count") is not None:
        line += f"  count={record['count']}"
    return line


def _summarize_serve(record: dict) -> str:
    req = record.get("request", {})
    wait = record.get("queue_wait_s", float("nan"))
    solve_t = record.get("solve_time_s")
    solve_s = "n/a" if solve_t is None else f"{solve_t * 1e3:.1f}ms"
    line = (f"serve {req.get('id', '?')} @ {record.get('timestamp', '?')}"
            f"  {req.get('m')}x{req.get('n')} {req.get('dtype')}"
            f" -> {record.get('bucket') or 'no bucket'}"
            f" [{record.get('path', '?')}]"
            f" status={record.get('status', '?')}"
            f" breaker={record.get('breaker', '?')}"
            f" brownout={record.get('brownout', '?')}"
            f" wait={wait * 1e3:.1f}ms solve={solve_s}")
    if record.get("phase", "full") != "full":
        # Two-phase branch: a sigma-first request shows its phase; a
        # promote shows which sigma request's retained state it
        # resumed — the σ-then-promote pair pairs up in the stream.
        line += f" phase={record['phase']}"
        if record.get("promoted_from"):
            line += f"<-{record['promoted_from']}"
    if record.get("rank_mode", "full") != "full":
        # Top-k / tall workload branch: a truncated request shows its
        # rank, a tall one its TSQR routing — the summarizer's view
        # of the "Workloads" families.
        line += f" {record['rank_mode']}"
        if record.get("k") is not None:
            line += f"[k={record['k']}]"
    if record.get("batch_id"):
        line += (f" batch={record['batch_id']}"
                 f"[{record.get('batch_size', '?')}"
                 f"/{record.get('batch_tier', '?')}]")
    if record.get("error"):
        line += f"\n  error: {record['error']}"
    return line


def _summarize_perf(record: dict) -> str:
    wl = record.get("workload", {})
    dev = record.get("device", {})
    lines = [
        f"perf [{record.get('source', '?')}] @ "
        f"{record.get('timestamp', '?')}  "
        f"{wl.get('m')}x{wl.get('n')} {wl.get('dtype')}  "
        f"device={dev.get('device_kind', '?')} "
        f"(peak={dev.get('peak_flops_source', '?')}, "
        f"bw={dev.get('hbm_bw_source', '?')})",
    ]
    scopes = sorted(record.get("scopes") or [],
                    key=lambda s: -(s.get("seconds") or 0.0))
    for s in scopes:
        line = (f"  {s.get('scope', '?'):<16} "
                f"{(s.get('seconds') or 0.0) * 1e3:9.2f} ms  "
                f"[{s.get('phase', '?')}]")
        if s.get("gflops") is not None:
            line += f"  {s['gflops']:9.2f} GFLOP/s"
        if s.get("frac_of_roof") is not None:
            line += (f"  {s['frac_of_roof'] * 100.0:5.1f}% of roof "
                     f"({s.get('bound', '?')}-bound)")
        lines.append(line)
    lines.append(f"  unscoped {record.get('unscoped_s', 0.0) * 1e3:.2f} ms"
                 f"  unattributed "
                 f"{record.get('unattributed_s', 0.0) * 1e3:.2f} ms")
    conv = record.get("convergence")
    if conv:
        curve = conv.get("off_rel") or []
        tail = (f" off_rel {curve[0]:.2e} -> {curve[-1]:.2e}"
                if curve else "")
        skipped = conv.get("rotations_skipped_frac")
        lines.append(f"  convergence: {len(curve)} sweep(s)"
                     f" [{conv.get('spectrum', '?')}]" + tail
                     + (f"  skipped={skipped:.1%}"
                        if isinstance(skipped, float) else ""))
    return "\n".join(lines)


def _summarize_solve(record: dict) -> str:
    dim = record.get("dimension", {})
    env = record.get("environment", {})
    solve = record.get("solve", {})
    lines = [
        f"{record.get('kind', '?')} run @ {record.get('timestamp', '?')}",
        f"  matrix {dim.get('m')}x{dim.get('n')} {record.get('dtype')}  "
        f"backend={env.get('backend')} ({env.get('device_count')}x "
        f"{env.get('device_kind')}, {env.get('process_count')} proc)",
        f"  config {record.get('config_sha256', '')[:12]}  "
        f"jax {env.get('jax')} / jaxlib {env.get('jaxlib')}",
    ]
    for st in record.get("stages") or []:
        lines.append(f"  stage {st.get('name', '?'):<12} "
                     f"{st.get('time_s', float('nan')):9.3f} s")
    keys = ("time_s", "sweeps", "off_norm", "status", "residual_rel",
            "u_orth", "v_orth", "sigma_err", "gflops", "vs_baseline")
    kv = [f"{k}={solve[k]:.4g}" if isinstance(solve.get(k), float)
          else f"{k}={solve[k]}" for k in keys if solve.get(k) is not None]
    lines.append("  solve " + "  ".join(kv))
    tel = record.get("telemetry")
    if tel:
        sweeps = [e for e in tel if e.get("event") == "sweep"]
        lines.append(f"  telemetry: {len(tel)} events, {len(sweeps)} sweeps")
        for e in sweeps:
            extra = ""
            if "rounds_rotated" in e:
                extra = (f"  rounds {e['rounds_rotated']}"
                         f"/{e.get('rounds_total', '?')}")
            lines.append(f"    sweep {e.get('sweep', '?'):>3} "
                         f"[{e.get('path', '?')}/{e.get('stage', '?')}] "
                         f"off={e.get('off_rel', float('nan')):.3e}{extra}")
    return "\n".join(lines)


def summarize(record: dict) -> str:
    """One human-readable block per record (telemetry_summary's renderer).
    Dispatches through the `KINDS` registry; unknown kinds render through
    the generic solve-record block (the original behavior)."""
    kind = _kind_for(record)
    return (kind.summarizer if kind is not None
            else _summarize_solve)(record)


# -- the KINDS registry -----------------------------------------------------
# One row per manifest kind: name -> (builder, validator, summarizer).
# `validate` and `summarize` dispatch through this table instead of
# if/elif chains, so a NEW kind added without all three pieces is a loud
# error AT IMPORT (register_kind refuses a partial registration) — not a
# silent fall-through to the generic solve branch at first render.
# Unknown kinds (records from a NEWER writer) still fall back to the
# solve shape in both functions: forward compatibility is unchanged.

class _Kind(NamedTuple):
    builder: Any            # the build_* function producing this kind
    validator: Any          # fn(record, errors) appending violations
    summarizer: Any         # fn(record) -> str


KINDS: Dict[str, _Kind] = {}


def register_kind(name: str, *, builder, validator, summarizer) -> None:
    """Register one manifest kind. All three pieces are REQUIRED and the
    name must be fresh — a kind with a builder but no validator (or
    summarizer) would validate/render through the generic branch
    silently, which is exactly the failure mode this registry exists to
    make loud."""
    missing = [what for what, fn in (("builder", builder),
                                     ("validator", validator),
                                     ("summarizer", summarizer))
               if fn is None]
    if missing:
        raise KeyError(f"manifest kind {name!r} registered without "
                       f"{'/'.join(missing)} — every kind needs builder, "
                       f"validator, AND summarizer")
    if name in KINDS:
        raise KeyError(f"manifest kind {name!r} already registered")
    KINDS[name] = _Kind(builder, validator, summarizer)


def _build_cli(**kw) -> dict:
    return build("cli", **kw)


def _build_bench(**kw) -> dict:
    return build("bench", **kw)


for _name, _builder, _validator, _summarizer in (
        ("cli", _build_cli, _validate_solve, _summarize_solve),
        ("bench", _build_bench, _validate_solve, _summarize_solve),
        ("analysis", build_analysis, _validate_analysis,
         _summarize_analysis),
        ("retry", build_retry, _validate_retry, _summarize_retry),
        ("serve", build_serve, _validate_serve, _summarize_serve),
        ("tune", build_tune, _validate_tune, _summarize_tune),
        ("fleet", build_fleet, _validate_fleet, _summarize_fleet),
        ("router", build_router, _validate_router, _summarize_router),
        ("net", build_net, _validate_net, _summarize_net),
        ("cache", build_cache, _validate_cache, _summarize_cache),
        ("coldstart", build_coldstart, _validate_coldstart,
         _summarize_coldstart),
        ("perf", build_perf, _validate_perf, _summarize_perf),
):
    register_kind(_name, builder=_builder, validator=_validator,
                  summarizer=_summarizer)


def diff(a: dict, b: dict) -> str:
    """Field-level diff of two records' comparable metrics."""
    lines = []
    if a.get("config_sha256") != b.get("config_sha256"):
        lines.append("config differs:")
        ca, cb = a.get("config", {}), b.get("config", {})
        for k in sorted(set(ca) | set(cb)):
            if ca.get(k) != cb.get(k):
                lines.append(f"  {k}: {ca.get(k)!r} -> {cb.get(k)!r}")
    for section in ("environment", "dimension"):
        sa, sb = a.get(section, {}), b.get(section, {})
        for k in sorted(set(sa) | set(sb)):
            if sa.get(k) != sb.get(k):
                lines.append(f"{section}.{k}: {sa.get(k)!r} -> {sb.get(k)!r}")
    sa, sb = a.get("solve", {}), b.get("solve", {})
    for k in sorted(set(sa) | set(sb)):
        va, vb = sa.get(k), sb.get(k)
        if isinstance(va, _NUM) and isinstance(vb, _NUM) and va:
            lines.append(f"solve.{k}: {va:.6g} -> {vb:.6g} "
                         f"({(vb - va) / abs(va) * 100.0:+.1f}%)")
        elif va != vb:
            lines.append(f"solve.{k}: {va!r} -> {vb!r}")
    return "\n".join(lines) or "(records are metric-identical)"
