"""Per-scope device-time attribution from `jax.profiler` traces.

Reads the `.xplane.pb` (XSpace protobuf) a `--profile` run or a PR 11
`XprofWindow` capture leaves under ``<log_dir>/plugins/profile/<stamp>/``
and recovers how much device time each `config.HOT_SCOPES` named scope
consumed — WITHOUT tensorflow, tensorboard-plugin-profile, or even jax on
the read side. The whole module is stdlib-only by contract (like
`obs.manifest` / `obs.registry`): the offline `python -m
svd_jacobi_tpu.perf report` path must work from a checked-in trace on a
bare-python machine.

How the join works (verified against jax 0.4.x CPU and TPU captures):

  * An XSpace holds planes; device planes ("/host:CPU", "/device:TPU:N")
    carry one XEvent per executed HLO op, named by INSTRUCTION name
    ("broadcast_multiply_fusion.9") — the `svdj/<scope>` annotation is
    NOT on the event.
  * The "/host:metadata" plane's XEventMetadata entries carry each
    compiled module's serialized HloProto in an XStat. Each instruction's
    `metadata.op_name` there holds the full named_scope path
    ("jit(_svd_pallas_impl)/.../svdj/rotations/...").
  * So: parse the HloProtos into (module, instruction) -> op_name, then
    walk the device-plane events, join by instruction name (events that
    are not HLO ops — python frames, ThunkExecutor wrappers — simply
    don't join and are reported as host/unattributed time), and fold
    durations by the innermost `svdj/` path component.

Only the protobuf wire format is implemented (varints + length-delimited
fields — ~40 lines); field numbers follow tensorflow's xplane.proto and
openxla's hlo.proto and are pinned in `_F`.
"""

from __future__ import annotations

import dataclasses
import gzip
import os
from typing import Dict, Iterator, List, Optional, Tuple

SCOPE_PREFIX = "svdj/"     # mirrors obs.scopes.PREFIX (stdlib copy)


# --------------------------------------------------------------------------
# Protobuf wire format.
# --------------------------------------------------------------------------

def _varint(b: bytes, i: int) -> Tuple[int, int]:
    r = s = 0
    while True:
        x = b[i]
        i += 1
        r |= (x & 0x7F) << s
        if not x & 0x80:
            return r, i
        s += 7


def _fields(b: bytes) -> Iterator[Tuple[int, int, object]]:
    """Yield (field_number, wire_type, value) over one message's bytes.
    Length-delimited values come back as bytes; varints as int. Groups
    (wire types 3/4) are long-dead — a message using them is malformed
    for our purposes and raises."""
    i, n = 0, len(b)
    while i < n:
        tag, i = _varint(b, i)
        fn, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _varint(b, i)
        elif wt == 1:
            v = b[i:i + 8]
            i += 8
        elif wt == 2:
            ln, i = _varint(b, i)
            v = b[i:i + ln]
            i += ln
        elif wt == 5:
            v = b[i:i + 4]
            i += 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wt}")
        yield fn, wt, v


def _first(b: bytes, field: int) -> Optional[object]:
    for fn, _, v in _fields(b):
        if fn == field:
            return v
    return None


class _F:
    """Pinned field numbers (xplane.proto / hlo.proto)."""

    # XSpace
    SPACE_PLANES = 1
    # XPlane
    PLANE_NAME = 2
    PLANE_LINES = 3
    PLANE_EVENT_METADATA = 4        # map<int64, XEventMetadata>
    PLANE_STAT_METADATA = 5         # map<int64, XStatMetadata>
    # map entries
    MAP_KEY = 1
    MAP_VALUE = 2
    # XLine
    LINE_EVENTS = 4
    # XEvent
    EVENT_METADATA_ID = 1
    EVENT_DURATION_PS = 3
    EVENT_STATS = 4
    # XEventMetadata
    EMETA_NAME = 2
    EMETA_STATS = 5
    # XStatMetadata
    SMETA_NAME = 2
    # XStat
    STAT_METADATA_ID = 1
    STAT_UINT64 = 3
    STAT_INT64 = 4
    STAT_STR = 5
    STAT_BYTES = 6
    STAT_REF = 7
    # HloProto / HloModuleProto / HloComputationProto /
    # HloInstructionProto / OpMetadata
    HLO_MODULE = 1
    MODULE_NAME = 1
    MODULE_COMPUTATIONS = 3
    COMP_INSTRUCTIONS = 2
    INSTR_NAME = 1
    INSTR_METADATA = 7
    OPMETA_OP_NAME = 2


# --------------------------------------------------------------------------
# XSpace reading.
# --------------------------------------------------------------------------

def read_trace_bytes(path: str) -> bytes:
    """Raw XSpace bytes from a file path; transparently gunzips (fixture
    traces are checked in compressed)."""
    with open(path, "rb") as f:
        data = f.read()
    if data[:2] == b"\x1f\x8b":
        data = gzip.decompress(data)
    return data


def find_trace(path: str) -> str:
    """Resolve ``path`` to one ``.xplane.pb[.gz]`` file: accepts the file
    itself, a profile log_dir (the `trace`/`XprofWindow` argument — the
    newest capture under ``plugins/profile/*/`` wins), or any directory
    containing captures."""
    if os.path.isfile(path):
        return path
    hits: List[str] = []
    for root, _, names in os.walk(path):
        for name in names:
            if name.endswith((".xplane.pb", ".xplane.pb.gz")):
                hits.append(os.path.join(root, name))
    if not hits:
        raise FileNotFoundError(
            f"no .xplane.pb under {path!r} — pass a jax.profiler log_dir "
            f"(the directory given to --profile / obs.trace) or the "
            f"xplane.pb file itself")
    return max(hits, key=os.path.getmtime)


def _plane_name(plane: bytes) -> str:
    v = _first(plane, _F.PLANE_NAME)
    return v.decode("utf-8", "replace") if isinstance(v, bytes) else ""


def _op_name_map(data: bytes) -> Dict[str, str]:
    """(instruction name) -> op_name scope path, merged over every
    HloProto found in any plane's event metadata. Instruction names are
    unique within a module and modules share the map; a cross-module
    collision (same instruction name, different op_name) keeps the first
    seen — harmless for scope attribution since colliding names are
    near-identical boilerplate (params, copies) with no svdj scope."""
    ops: Dict[str, str] = {}
    for fn, _, plane in _fields(data):
        if fn != _F.SPACE_PLANES:
            continue
        for f2, _, entry in _fields(plane):
            if f2 != _F.PLANE_EVENT_METADATA:
                continue
            emeta = _first(entry, _F.MAP_VALUE)
            if not isinstance(emeta, bytes):
                continue
            for f3, _, stat in _fields(emeta):
                if f3 != _F.EMETA_STATS:
                    continue
                blob = _first(stat, _F.STAT_BYTES)
                if not isinstance(blob, bytes) or len(blob) < 8:
                    continue
                try:
                    _collect_hlo_ops(blob, ops)
                except (ValueError, IndexError):
                    continue          # stat bytes that are not an HloProto
    return ops


def _collect_hlo_ops(hlo_proto: bytes, out: Dict[str, str]) -> None:
    module = _first(hlo_proto, _F.HLO_MODULE)
    if not isinstance(module, bytes):
        return
    for fn, _, comp in _fields(module):
        if fn != _F.MODULE_COMPUTATIONS:
            continue
        for f2, _, instr in _fields(comp):
            if f2 != _F.COMP_INSTRUCTIONS:
                continue
            name = op_name = None
            for f3, _, v in _fields(instr):
                if f3 == _F.INSTR_NAME and isinstance(v, bytes):
                    name = v.decode("utf-8", "replace")
                elif f3 == _F.INSTR_METADATA and isinstance(v, bytes):
                    o = _first(v, _F.OPMETA_OP_NAME)
                    if isinstance(o, bytes):
                        op_name = o.decode("utf-8", "replace")
            if name and name not in out:
                out[name] = op_name or ""


def _device_events(data: bytes) -> Iterator[Tuple[str, str, int]]:
    """Yield (plane_name, event_name, duration_ps) for every event on
    every plane that has lines (device planes and the host op line)."""
    for fn, _, plane in _fields(data):
        if fn != _F.SPACE_PLANES:
            continue
        pname = _plane_name(plane)
        emeta: Dict[int, str] = {}
        lines: List[bytes] = []
        for f2, _, v in _fields(plane):
            if f2 == _F.PLANE_EVENT_METADATA:
                key = _first(v, _F.MAP_KEY)
                meta = _first(v, _F.MAP_VALUE)
                if isinstance(meta, bytes):
                    nm = _first(meta, _F.EMETA_NAME)
                    if isinstance(nm, bytes):
                        emeta[int(key or 0)] = nm.decode("utf-8", "replace")
            elif f2 == _F.PLANE_LINES:
                lines.append(v)
        for line in lines:
            for f3, _, ev in _fields(line):
                if f3 != _F.LINE_EVENTS:
                    continue
                mid = dur = 0
                for f4, _, v in _fields(ev):
                    if f4 == _F.EVENT_METADATA_ID:
                        mid = int(v)
                    elif f4 == _F.EVENT_DURATION_PS:
                        dur = int(v)
                name = emeta.get(mid)
                if name:
                    yield pname, name, dur


# --------------------------------------------------------------------------
# Scope attribution.
# --------------------------------------------------------------------------

@dataclasses.dataclass
class ScopeTime:
    """Accumulated device time of one profiler scope."""

    scope: str
    seconds: float = 0.0
    events: int = 0


@dataclasses.dataclass
class Attribution:
    """Per-scope device time recovered from one trace, plus the honesty
    buckets: ``unscoped_s`` is HLO-op time carrying no svdj scope
    (preconditioner custom calls, input copies, glue), ``unattributed_s``
    is event time that joined no instruction at all (python frames,
    executor wrappers) — reported, never silently folded in."""

    scopes: Dict[str, ScopeTime]
    unscoped_s: float
    unattributed_s: float
    trace_path: str = ""

    @property
    def scoped_s(self) -> float:
        return sum(s.seconds for s in self.scopes.values())

    @property
    def device_s(self) -> float:
        """Total HLO-op time (scoped + unscoped)."""
        return self.scoped_s + self.unscoped_s


def innermost_scope(op_name: str,
                    prefix: str = SCOPE_PREFIX) -> Optional[str]:
    """The innermost `svdj/<scope>` component of an op_name path, or
    None. Nested scopes attribute to the most specific annotation."""
    idx = op_name.rfind(prefix)
    if idx < 0:
        return None
    rest = op_name[idx + len(prefix):]
    return rest.split("/", 1)[0] or None


def scope_durations(trace: str, *,
                    prefix: str = SCOPE_PREFIX) -> Attribution:
    """Fold a capture's device time by profiler scope.

    ``trace``: a log_dir or an ``.xplane.pb[.gz]`` path (`find_trace`
    resolution). Durations SUM across threads/cores executing ops in
    parallel — this is device-time attribution (how the FLOP budget was
    spent), not wall-clock decomposition; shares are what matter.
    """
    path = find_trace(trace)
    data = read_trace_bytes(path)
    ops = _op_name_map(data)
    scopes: Dict[str, ScopeTime] = {}
    unscoped = unattributed = 0
    for _, name, dur_ps in _device_events(data):
        op_name = ops.get(name)
        if op_name is None:
            unattributed += dur_ps
            continue
        scope = innermost_scope(op_name, prefix)
        if scope is None:
            unscoped += dur_ps
            continue
        st = scopes.setdefault(scope, ScopeTime(scope))
        st.seconds += dur_ps * 1e-12
        st.events += 1
    return Attribution(scopes, unscoped * 1e-12, unattributed * 1e-12,
                       trace_path=path)


# --------------------------------------------------------------------------
# Joining measured time with the cost model.
# --------------------------------------------------------------------------

def attribute(attr: Attribution, phase_costs: Dict[str, object], *,
              scope_phases: Dict[str, str], peak_flops: float,
              hbm_bw: float, estimated: bool = False) -> List[dict]:
    """Join per-scope durations with per-phase analytic costs into the
    roofline rows of the "perf" manifest kind.

    A phase's modeled FLOPs/bytes are split across its scopes
    proportionally to measured time (e.g. `apply` and `apply_exchange`
    both land in "sweep.apply"). Scopes whose phase has no model (grad,
    health) — and phases modeled at zero flops (exchange) — still get a
    row with measured seconds and achieved GB/s, with roofline fields
    None. Rows are sorted by descending seconds.
    """
    try:
        from . import costmodel
    except ImportError:
        # Loaded standalone by file path (scripts/telemetry_summary.py
        # style) — costmodel.py is loaded beside us under its bare name.
        import costmodel  # type: ignore

    by_phase: Dict[str, List[ScopeTime]] = {}
    for st in attr.scopes.values():
        phase = scope_phases.get(st.scope, "other")
        by_phase.setdefault(phase, []).append(st)

    rows: List[dict] = []
    for phase, members in by_phase.items():
        phase_s = sum(st.seconds for st in members)
        cost = phase_costs.get(phase)
        for st in members:
            share = st.seconds / phase_s if phase_s > 0 else 0.0
            row = {
                "scope": st.scope, "phase": phase,
                "seconds": st.seconds, "events": st.events,
                "share_of_phase": share,
                "flops": None, "hbm_bytes": None, "intensity": None,
                "gflops": None, "gbytes_per_s": None,
                "attainable_gflops": None, "frac_of_roof": None,
                "bound": None,
            }
            if cost is not None and st.seconds > 0:
                sliced = costmodel.PhaseCost(
                    phase, cost.flops * share, cost.hbm_bytes * share)
                roof = costmodel.roofline(
                    phase, st.seconds, sliced, peak_flops=peak_flops,
                    hbm_bw=hbm_bw, estimated=estimated)
                row.update(
                    flops=sliced.flops, hbm_bytes=sliced.hbm_bytes,
                    intensity=roof.intensity,
                    gflops=roof.achieved_flops / 1e9,
                    gbytes_per_s=roof.achieved_bytes / 1e9,
                    attainable_gflops=roof.attainable / 1e9,
                    frac_of_roof=roof.frac_of_roof, bound=roof.bound)
            rows.append(row)
    rows.sort(key=lambda r: -r["seconds"])
    return rows


def render_table(rows: List[dict], *, unscoped_s: float = 0.0,
                 unattributed_s: float = 0.0,
                 title: str = "per-scope roofline") -> str:
    """Fixed-width table of attribution rows (the `perf report` body and
    the "perf" manifest summarizer's long form)."""
    head = (f"{'scope':<16} {'phase':<16} {'ms':>9} {'GFLOP/s':>9} "
            f"{'GB/s':>8} {'AI':>7} {'%roof':>6} {'bound':<9}")
    out = [title, head, "-" * len(head)]
    for r in rows:
        def fmt(v, spec):
            return format(v, spec) if v is not None else "-"
        out.append(
            f"{r['scope']:<16} {r['phase']:<16} "
            f"{r['seconds'] * 1e3:>9.3f} {fmt(r['gflops'], '>9.2f')} "
            f"{fmt(r['gbytes_per_s'], '>8.2f')} "
            f"{fmt(r['intensity'], '>7.2f')} "
            f"{fmt(None if r['frac_of_roof'] is None else 100 * r['frac_of_roof'], '>6.1f')} "
            f"{r['bound'] or '-':<9}")
    scoped = sum(r["seconds"] for r in rows)
    out.append("-" * len(head))
    out.append(f"scoped {scoped * 1e3:.3f} ms | unscoped HLO "
               f"{unscoped_s * 1e3:.3f} ms | unattributed (host) "
               f"{unattributed_s * 1e3:.3f} ms")
    return "\n".join(out)
