"""LAPACK-gesvd-shaped API surface.

Mirrors the reference's public solver contract
(reference: `SVD_OPTIONS {AllVec, SomeVec, NoVec}` and the dgesvd-style
signatures of `omp_mpi_cuda_dgesvd_local_matrices` /
`cuda_dgesvd_kernel`, lib/JacobiMethods.cuh:25-62), so a user of the
reference can switch with the same vocabulary:

    u, s, vt = gesvd(SVD_OPTIONS.SomeVec, SVD_OPTIONS.SomeVec, a)

Differences from the reference, by design:
  * returns ``v^T`` like LAPACK dgesvd proper (the reference returns V
    untransposed); `svd_jacobi_tpu.svd` returns V untransposed for parity
    with the reference's convention.
  * works for any m, n (the reference documents m >= n and in practice only
    square, SURVEY.md quirks #4/#7).
  * AllVec returns full square U (m, m) / Vt (n, n); SomeVec the economy
    factors — matching LAPACK jobu='A'/'S'. The reference treats AllVec ==
    SomeVec (its SomeVec branch is commented out, lib/JacobiMethods.cu:1165).
  * layout: the reference's col-major MATRIX_LAYOUT enum
    (lib/Utils.cuh:18-21) maps to the ``layout=`` kwarg: "row" (default)
    takes/returns ordinary row-major jax arrays; "col" makes the dgesvd
    drop-in literal — `a` is then the column-major IMAGE of the logical
    (m, n) matrix (i.e. the (n, m) array a col-major buffer reinterprets
    to), and the returned u / vt are themselves col-major images.
"""

from __future__ import annotations

import enum
from typing import Optional, Tuple

import jax

from .config import SVDConfig
from .solver import SVDResult, svd


class SVD_OPTIONS(enum.Enum):
    """Job options for U/V computation (lib/JacobiMethods.cuh:25-29)."""

    AllVec = "all"    # full square factor
    SomeVec = "some"  # economy factor (min(m, n) columns)
    NoVec = "none"    # do not compute


def gesvd(
    jobu: SVD_OPTIONS,
    jobv: SVD_OPTIONS,
    a,
    *,
    layout: str = "row",
    config: Optional[SVDConfig] = None,
    mesh=None,
) -> Tuple[Optional[jax.Array], jax.Array, Optional[jax.Array]]:
    """Compute ``a = u @ diag(s) @ vt`` (note: returns v TRANSPOSED).

    Args:
      jobu/jobv: SVD_OPTIONS for the left/right factors.
      a: (m, n) real matrix ("row" layout) — or, with ``layout="col"``,
        the (n, m) column-major image of the logical (m, n) matrix.
      layout: "row" (default) or "col" — the reference's MATRIX_LAYOUT
        enum (lib/Utils.cuh:18-21). LAPACK dgesvd is col-major native;
        with "col" both the input AND the returned u/vt are col-major
        images (transposes of the row-major factors), so a dgesvd caller
        can hand over its buffers unchanged.
      config: solver configuration.
      mesh: optional `jax.sharding.Mesh` — routes to the distributed solver
        (the reference's `omp_mpi_cuda_dgesvd_local_matrices` equivalent);
        None runs single-device (`cuda_dgesvd_kernel` equivalent).

    Returns:
      (u, s, vt); u/vt are None under NoVec. s is descending, length
      min(m, n). AllVec: u is (m, m), vt is (n, n); SomeVec: u is
      (m, min(m, n)), vt is (min(m, n), n) — each transposed under
      layout="col".
    """
    if layout not in ("row", "col"):
        raise ValueError(f"unknown layout {layout!r}; expected 'row'/'col'")
    if not isinstance(jobu, SVD_OPTIONS) or not isinstance(jobv, SVD_OPTIONS):
        raise TypeError("jobu/jobv must be SVD_OPTIONS members")
    if layout == "col":
        # The array is B = A^T (the col-major image). With
        # B = U_B S V_B^T, A = V_B S U_B^T — so U_A = V_B and
        # V_A^T = U_B^T: solve B row-major with the JOBS SWAPPED (jobu
        # governs U_A = V_B, i.e. B's V job), then the col-major images of
        # A's factors are exactly the row-major factors of B crosswise:
        # image(U_A) = U_A^T = V_B^T = vt_B and image(V_A^T) = V_A = u_B.
        u_b, s, vt_b = gesvd(jobv, jobu, a, layout="row", config=config,
                             mesh=mesh)
        return vt_b, s, u_b
    full = (jobu == SVD_OPTIONS.AllVec) or (jobv == SVD_OPTIONS.AllVec)
    r = _solve(a, jobu != SVD_OPTIONS.NoVec, jobv != SVD_OPTIONS.NoVec,
               full, config, mesh)
    u, s, v = r.u, r.s, r.v
    vt = None
    if v is not None:
        # full_matrices in the solver completes U; AllVec for V needs the
        # square V, which the solver returns as (n, min) unless n <= m and
        # full was requested via the transpose path. Complete here if short.
        if jobv == SVD_OPTIONS.AllVec and v.shape[1] < v.shape[0]:
            v = _complete_basis(v)
        vt = v.T
    if u is not None and jobu != SVD_OPTIONS.AllVec and u.shape[1] > s.shape[0]:
        u = u[:, : s.shape[0]]
    return u, s, vt


def _solve(a, compute_u, compute_v, full, config, mesh) -> SVDResult:
    if mesh is not None:
        from .parallel import sharded
        return sharded.svd(a, mesh=mesh, compute_u=compute_u,
                           compute_v=compute_v, full_matrices=full,
                           config=config)
    return svd(a, compute_u=compute_u, compute_v=compute_v,
               full_matrices=full, config=config)


def _complete_basis(q: jax.Array) -> jax.Array:
    """Extend an (n, r) orthonormal set to an (n, n) orthonormal basis."""
    import jax.numpy as jnp
    n, r = q.shape
    qq, rr = jnp.linalg.qr(q, mode="complete")
    signs = jnp.sign(jnp.diagonal(rr))
    signs = jnp.where(signs == 0, 1.0, signs)
    qq = qq.at[:, :r].multiply(signs[None, :])
    return qq
