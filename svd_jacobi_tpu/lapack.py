"""LAPACK-gesvd-shaped API surface.

Mirrors the reference's public solver contract
(reference: `SVD_OPTIONS {AllVec, SomeVec, NoVec}` and the dgesvd-style
signatures of `omp_mpi_cuda_dgesvd_local_matrices` /
`cuda_dgesvd_kernel`, lib/JacobiMethods.cuh:25-62), so a user of the
reference can switch with the same vocabulary:

    u, s, vt = gesvd(SVD_OPTIONS.SomeVec, SVD_OPTIONS.SomeVec, a)

Differences from the reference, by design:
  * returns ``v^T`` like LAPACK dgesvd proper (the reference returns V
    untransposed); `svd_jacobi_tpu.svd` returns V untransposed for parity
    with the reference's convention.
  * works for any m, n (the reference documents m >= n and in practice only
    square, SURVEY.md quirks #4/#7).
  * AllVec returns full square U (m, m) / Vt (n, n); SomeVec the economy
    factors — matching LAPACK jobu='A'/'S'. The reference treats AllVec ==
    SomeVec (its SomeVec branch is commented out, lib/JacobiMethods.cu:1165).
  * layout: arrays are row-major jax arrays; the reference's col-major
    MATRIX_LAYOUT enum (lib/Utils.cuh:18-21) is unnecessary — pass `a.T`
    for a col-major buffer.
"""

from __future__ import annotations

import enum
from typing import Optional, Tuple

import jax

from .config import SVDConfig
from .solver import SVDResult, svd


class SVD_OPTIONS(enum.Enum):
    """Job options for U/V computation (lib/JacobiMethods.cuh:25-29)."""

    AllVec = "all"    # full square factor
    SomeVec = "some"  # economy factor (min(m, n) columns)
    NoVec = "none"    # do not compute


def gesvd(
    jobu: SVD_OPTIONS,
    jobv: SVD_OPTIONS,
    a,
    *,
    config: Optional[SVDConfig] = None,
    mesh=None,
) -> Tuple[Optional[jax.Array], jax.Array, Optional[jax.Array]]:
    """Compute ``a = u @ diag(s) @ vt`` (note: returns v TRANSPOSED).

    Args:
      jobu/jobv: SVD_OPTIONS for the left/right factors.
      a: (m, n) real matrix.
      config: solver configuration.
      mesh: optional `jax.sharding.Mesh` — routes to the distributed solver
        (the reference's `omp_mpi_cuda_dgesvd_local_matrices` equivalent);
        None runs single-device (`cuda_dgesvd_kernel` equivalent).

    Returns:
      (u, s, vt); u/vt are None under NoVec. s is descending, length
      min(m, n). AllVec: u is (m, m), vt is (n, n); SomeVec: u is
      (m, min(m, n)), vt is (min(m, n), n).
    """
    if not isinstance(jobu, SVD_OPTIONS) or not isinstance(jobv, SVD_OPTIONS):
        raise TypeError("jobu/jobv must be SVD_OPTIONS members")
    full = (jobu == SVD_OPTIONS.AllVec) or (jobv == SVD_OPTIONS.AllVec)
    r = _solve(a, jobu != SVD_OPTIONS.NoVec, jobv != SVD_OPTIONS.NoVec,
               full, config, mesh)
    u, s, v = r.u, r.s, r.v
    vt = None
    if v is not None:
        # full_matrices in the solver completes U; AllVec for V needs the
        # square V, which the solver returns as (n, min) unless n <= m and
        # full was requested via the transpose path. Complete here if short.
        if jobv == SVD_OPTIONS.AllVec and v.shape[1] < v.shape[0]:
            v = _complete_basis(v)
        vt = v.T
    if u is not None and jobu != SVD_OPTIONS.AllVec and u.shape[1] > s.shape[0]:
        u = u[:, : s.shape[0]]
    return u, s, vt


def _solve(a, compute_u, compute_v, full, config, mesh) -> SVDResult:
    if mesh is not None:
        from .parallel import sharded
        return sharded.svd(a, mesh=mesh, compute_u=compute_u,
                           compute_v=compute_v, full_matrices=full,
                           config=config)
    return svd(a, compute_u=compute_u, compute_v=compute_v,
               full_matrices=full, config=config)


def _complete_basis(q: jax.Array) -> jax.Array:
    """Extend an (n, r) orthonormal set to an (n, n) orthonormal basis."""
    import jax.numpy as jnp
    n, r = q.shape
    qq, rr = jnp.linalg.qr(q, mode="complete")
    signs = jnp.sign(jnp.diagonal(rr))
    signs = jnp.where(signs == 0, 1.0, signs)
    qq = qq.at[:, :r].multiply(signs[None, :])
    return qq
