"""Single-device one-sided block-Jacobi SVD solver.

TPU-native replacement for the reference's two solver entry points
(reference: `cuda_dgesvd_kernel`, lib/JacobiMethods.cu:1177-1451 single
process, and `omp_mpi_cuda_dgesvd_local_matrices`, lib/JacobiMethods.cu:191-1175
distributed — the distributed path lives in parallel/sharded.py). Key
capability upgrades over the reference, per SURVEY.md section 7:

  * real convergence: `lax.while_loop` over sweeps driven by the relative
    off-norm — the reference hard-codes one sweep and discards its own
    convergence estimate (lib/JacobiMethods.cu:234, 462);
  * the matrix stays resident on device for the whole solve — no per-rotation
    host round-trips (cf. lib/JacobiMethods.cu:479-510);
  * rectangular m != n supported (the reference claims m >= n,
    lib/JacobiMethods.cu:13, but its driver is square-only, main.cu:1452-1453,
    and several paths break for m != n — SURVEY.md quirks #4, #7);
  * sigma sorted descending, U/V options, orthonormal full-U completion.
"""

from __future__ import annotations

import enum
import time
from functools import partial
from typing import Callable, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import SVDConfig
from .grad import rules as _grad
from .obs import metrics
from .obs.scopes import scope
from .ops import blockwise, rounds
from .ops import pallas_blocks as pb
from .ops import pallas_resident as _resident
from .ops import sketch as _sketch
from .parallel import schedule as sched
from .resilience import chaos as _chaos


class SolveStatus(enum.IntEnum):
    """Health word of a solve — how the sweep loop exited.

    The reference has no notion of solve health at all (its convergence
    estimate is computed and discarded, lib/JacobiMethods.cu:462,234); here
    every fused loop carries a cheap in-graph health word and decodes it
    into this enum (`SVDResult.status`):

      * OK          — converged to tolerance;
      * MAX_SWEEPS  — the sweep budget ran out above tolerance;
      * STAGNATED   — the stall detector stopped the loop above tolerance
                      (an endgame sweep failed to keep shrinking the
                      coupling — the criterion's roundoff floor sits above
                      the requested tol) without exhausting the budget;
      * NONFINITE   — NaN/Inf detected in the working state or the
                      convergence statistic. The deflation mask silently
                      DROPS NaN columns from the masked statistic, so
                      without this word a NaN-poisoned solve is
                      indistinguishable from a converged one;
      * DEADLINE    — the host-stepped loop stopped because the request's
                      deadline expired (cooperative check between sweeps:
                      `SweepStepper.set_control`). The result is a LOUD
                      partial — the factors reflect the sweeps that ran;
      * CANCELLED   — the host-stepped loop stopped because the caller
                      cancelled the request (same cooperative check).

    DEADLINE/CANCELLED are host-loop statuses (the serving layer's
    request control, `svd_jacobi_tpu.serve`): the fused while_loop entry
    points never produce them — a fused solve cannot be interrupted
    between sweeps.
    """

    OK = 0
    MAX_SWEEPS = 1
    STAGNATED = 2
    NONFINITE = 3
    DEADLINE = 4
    CANCELLED = 5


class SVDResult(NamedTuple):
    """Result of an SVD solve. ``u``/``v`` are None under NoVec options.

    ``sweeps``/``off_rel`` are the convergence diagnostics the reference
    computes but discards (lib/JacobiMethods.cu:462,234); the bench and
    checkpoint subsystems report them. ``status`` is the in-graph health
    word (int32 `SolveStatus` code; `status_enum()` decodes it on host).
    """

    u: Optional[jax.Array]
    s: jax.Array
    v: Optional[jax.Array]
    sweeps: jax.Array
    off_rel: jax.Array
    status: Optional[jax.Array] = None

    def status_enum(self) -> SolveStatus:
        """Host-side decode of ``status`` (one sanctioned scalar read)."""
        if self.status is None:
            raise ValueError("this SVDResult carries no status word")
        return SolveStatus(int(_host_scalar(self.status)))


def _default_tol(m: int, n: int, dtype, criterion: str = "rel") -> float:
    # "rel": dgesvj-style threshold for the scaled coupling
    # |a_i.a_j|/(|a_i||a_j|) — the roundoff floor of an m-term dot product
    # is ~sqrt(m)*eps. "abs": couplings are measured against sigma_max^2,
    # whose floor sits near 8*eps on the gram-eigh path (measured).
    eps = float(jnp.finfo(dtype).eps)
    if criterion == "abs":
        return 8.0 * eps
    return float(np.sqrt(m) * eps)


def _abs_phase_tol(dtype) -> float:
    """Phase-1 (bulk) tolerance for the hybrid method — shared by the
    single-device and sharded solvers so they cannot drift."""
    return _default_tol(1, 1, dtype, "abs")


def _tuned(n: int, m: Optional[int], dtype) -> "object":
    """The active tuning-table resolution for a tall-oriented (m, n)
    problem of ``dtype`` — the one lookup every "auto" knob below goes
    through (`tune.tables.resolve`: pure and deterministic, so it is
    jit/retrace-safe; the TUNE001 analysis pass checks it)."""
    from .tune import tables as _tables
    return _tables.resolve(n, m=m, dtype=jnp.dtype(dtype).name)


def _plan(n: int, n_devices: int, config: SVDConfig, m: Optional[int] = None,
          dtype=None):
    """Choose block width ``b`` and pair count ``k`` (columns pad to 2*k*b).

    On a multi-device mesh each device must hold k/P >= 2 pair slots (the
    ring exchange splices one incoming block per stream), and blocks are
    shrunk — even user-specified ones — so the padded width 2*k*b stays
    within ~2x of n instead of ballooning with the device count.
    ``m``/``dtype`` refine the tuning-table lookup behind the automatic
    width (aspect/dtype classes); omitted, the lookup assumes square f32
    — the historical n-only behavior, kept for direct callers.
    """
    b = config.pick_block_size(n, m=m, dtype=dtype)
    b = min(b, max(1, (n + 1) // 2))
    if n_devices > 1:
        b = min(b, max(1, -(-n // (4 * n_devices))))
    k = max(1, -(-n // (2 * b)))
    if n_devices > 1:
        k = max(k, 2 * n_devices)
        k = -(-k // n_devices) * n_devices  # round up to multiple of P
    return b, k


# The device-kernel solver lanes: all run the blockified sweep machinery
# of ops/rounds.py with f32 rotation math (f64 routes to qr-svd) and
# terminate on the rel statistic. "pallas" generates rotations with the
# latency-bound Pallas step kernels every round; "block_rotation" solves
# each round's full 2b x 2b Gram subproblem on-chip (ops/block_rotate —
# accumulate into one factor J, apply as one rank-2b matmul per pair) as
# an abs-statistic bulk phase and polishes with the pallas kernels;
# "resident" (ops/pallas_resident) runs that same bulk against a carried
# full Gram so R consecutive rounds' factors apply in ONE VMEM-resident
# panel pass, then polishes with the same pallas endgame.
_KERNEL_METHODS = ("pallas", "block_rotation", "resident")


def _resolve_mixed_store(config: SVDConfig, n: int, m: int, dtype) -> str:
    """The ONE validate-and-resolve of `SVDConfig.mixed_store` (shared by
    the pallas/mixed-bulk planner, both block-rotation planners, and the
    block-rotation steppers — the gate must read identically on every
    dispatch surface or fused and served solves of one bucket diverge):
    explicit values win, "auto" resolves through the tuning table."""
    if config.mixed_store not in ("auto", "f32", "bf16", "bf16g"):
        raise ValueError(f"unknown mixed_store mode: {config.mixed_store!r}")
    return (config.mixed_store if config.mixed_store != "auto"
            else _tuned(n, m, dtype).mixed_store)


# Bulk-phase exit for the blocked-rotation lane, as a multiple of the abs
# phase tolerance (so it scales with the input dtype's eps): 10x = ~1e-5
# for f32. MEASURED, not derived (1024^2 CPU, uniform + gaussian inputs):
# converging the eigh bulk all the way to 8*eps costs 2-3 extra bulk
# sweeps AND lengthens the polish — each late-bulk eigh factor carries
# backward error ~eps*sigma_max(panel)^2, which near the abs floor stops
# resolving structure and starts re-perturbing what the polish must then
# undo (14 total sweeps at 1x vs 11 at 10x; 4.40 s vs 2.71 s).
_BLOCK_BULK_TOL_FACTOR = 10.0


def _resolve_rounds_resident(config: SVDConfig, n: int, m: int, dtype,
                             n_rounds: int) -> int:
    """The ONE validate-and-resolve of the resident lane's residency depth
    R (rounds per VMEM-resident panel pass), shared by the fused planners
    and the steppers so every dispatch surface of a bucket runs the same
    group structure: explicit `SVDConfig.rounds_resident` wins, else the
    tuning table's row, else the lane default; clamped to the sweep's
    round count (a deeper residency than one sweep has rounds is just the
    whole sweep)."""
    r = config.rounds_resident
    if r is None:
        r = _tuned(n, m, dtype).rounds_resident
    if r is None:
        r = _resident.DEFAULT_ROUNDS
    r = int(r)
    if r < 1:
        raise ValueError(f"rounds_resident must be >= 1, got {r}")
    return max(1, min(r, int(n_rounds)))


def _resolve_options(a, config: SVDConfig, compute_uv: bool = True):
    """Shared option resolution for the single-device and sharded entry
    points: tolerance, Gram dtype, pair-solver method, and convergence
    criterion.

    "auto" picks qr-svd (gesvj-class relative accuracy) for f64 and "hybrid"
    for f32/bf16 when singular vectors are wanted: cheap all-matmul
    gram-eigh/abs sweeps do the bulk of the work, then qr-svd/rel sweeps
    polish — needed because one-sided Jacobi reads U off the rotated
    columns, so U orthogonality REQUIRES relative convergence (under "abs"
    alone, couplings between small-sigma columns stay O(1) and U is not
    orthogonal). With compute_uv=False there is no U to protect and auto
    stays on the fast gram-eigh/abs path.
    """
    m, n = a.shape
    method = config.pair_solver
    tuned = None
    if method == "auto" or config.criterion == "auto":
        tuned = _tuned(n, m, a.dtype)
    if method == "auto":
        # The tuning table proposes the solver family; the capability
        # guards below are the final word (they reproduce the historical
        # hand-picked routing when the table's generic row proposes
        # "pallas", and protect against a mis-tuned table ever selecting
        # an incompatible solver):
        #   * f64 computes rotations the Pallas kernel cannot (f32-only
        #     MXU) -> qr-svd (gesvj-class relative accuracy);
        #   * the kernel path needs min(m, n) >= 64 to block usefully,
        #     and measures only the rel statistic — an explicit abs
        #     criterion routes to the XLA block solvers instead ("auto"
        #     means "pick a compatible solver");
        #   * "hybrid" exists to protect U orthogonality; with
        #     compute_uv=False there is no U and the cheap gram-eigh/abs
        #     bulk path suffices.
        method = tuned.pair_solver
        if a.dtype == jnp.float64 and method in _KERNEL_METHODS:
            method = "qr-svd"
        if method in _KERNEL_METHODS and not (min(m, n) >= 64
                                              and config.criterion != "abs"):
            method = "hybrid"
        if method == "gram-eigh" and compute_uv:
            # gram-eigh alone cannot deliver an orthogonal U (abs-class
            # convergence only); a table may pin it for sigma-only
            # classes, but a factor-computing auto solve upgrades to
            # hybrid (gram-eigh bulk + qr-svd polish) — the guard the
            # search harness mirrors by never offering bare gram-eigh
            # for compute_uv grids.
            method = "hybrid"
        if method == "hybrid" and not compute_uv:
            method = "gram-eigh"
    if method in _KERNEL_METHODS and a.dtype == jnp.float64:
        raise ValueError(f"pair_solver={method!r} computes rotations in "
                         "float32; use 'qr-svd' (the auto choice) for "
                         "float64 inputs")
    if method not in ("pallas", "block_rotation", "resident", "qr-svd",
                      "gram-eigh", "hybrid"):
        raise ValueError(f"unknown pair solver method: {method!r}")
    criterion = config.criterion
    if criterion == "auto":
        # Table value "follow" (the generic default) = derive from the
        # resolved method: gram-eigh converges only to the absolute
        # (sigma_max-relative) class, everything else runs the dgesvj
        # rel statistic. A table may pin "rel"/"abs" outright, guarded
        # by the same compatibility rules as explicit user values
        # (pallas cannot measure abs; gram-eigh stalls under rel).
        tcrit = tuned.criterion if tuned is not None else "follow"
        if tcrit == "rel" and method != "gram-eigh":
            criterion = "rel"
        elif tcrit == "abs" and method not in _KERNEL_METHODS:
            criterion = "abs"
        else:
            criterion = "abs" if method == "gram-eigh" else "rel"
    if method in _KERNEL_METHODS:
        if criterion == "abs":
            # The kernel lanes TERMINATE on the rel (dgesvj scaled-
            # coupling) statistic only — pallas measures nothing else, and
            # block_rotation's abs statistic is an internal bulk-phase
            # control, not the final convergence contract. An abs-scale
            # tolerance would be compared against the wrong quantity and
            # could never be reached; an explicit abs request on an
            # explicit kernel lane is unsatisfiable — reject it loudly
            # (this file's policy for precondition / mixed_bulk) instead
            # of silently rewriting it to "rel".
            raise ValueError(
                f"criterion='abs' is not a termination criterion of the "
                f"kernel lanes (pair_solver={method!r} terminates on the "
                f"dgesvj scaled-coupling 'rel' statistic); use "
                f"criterion='rel' or an XLA pair solver "
                f"('gram-eigh'/'hybrid'/'qr-svd')")
        # (here criterion can only be "rel": "auto" resolved above, "abs"
        # just raised)
    if criterion not in ("rel", "abs"):
        raise ValueError(f"unknown convergence criterion: {criterion!r}")
    # For "hybrid", tol/criterion describe the FINAL (polish) phase; the abs
    # phase always runs with the abs default tolerance.
    tol = (config.tol if config.tol is not None
           else _default_tol(m, n, a.dtype, criterion))
    from .tune import tables as _tables
    gram_dtype = config.gram_dtype or _tables.default_gram_dtype(a.dtype)
    return float(tol), jnp.dtype(gram_dtype).name, method, criterion


def _resolve_xla_options(a, config: SVDConfig, compute_uv: bool = True):
    """Resolve options with the Pallas path mapped to its XLA-solver
    equivalent (hybrid) — used by entry points that run the XLA block
    solvers (the host-stepped SweepStepper family; the fused sharded solve
    resolves pallas natively), so tolerance and criterion always form a
    matched pair."""
    import dataclasses as _dc
    tol, gram, method, criterion = _resolve_options(a, config, compute_uv)
    if method in _KERNEL_METHODS:
        tol, gram, method, criterion = _resolve_options(
            a, _dc.replace(config, pair_solver="hybrid"), compute_uv)
    return tol, gram, method, criterion


def _resolve_grad_rtol(config: SVDConfig, n: int, m: int, dtype) -> float:
    """The degenerate-sigma classification band of the gradient
    safeguards (`grad.fmatrix`): explicit `SVDConfig.grad_degenerate_rtol`
    wins, else the per-dtype tuning-table row (the same `tune.tables`
    lookup as every other knob; the shipped table pins f32 ~8*eps_f32 and
    f64 ~8*eps_f64 — f32's band is ~1e9x wider, matching its solve
    noise), else 8*eps of the accumulation dtype."""
    if config.grad_degenerate_rtol is not None:
        rtol = float(config.grad_degenerate_rtol)
        if not rtol > 0:
            raise ValueError(f"grad_degenerate_rtol must be > 0, got "
                             f"{config.grad_degenerate_rtol!r}")
        return rtol
    tuned = _tuned(n, m, dtype).grad_degenerate_rtol
    if tuned is not None:
        return float(tuned)
    acc = jnp.promote_types(dtype, jnp.float32)
    return 8.0 * float(jnp.finfo(acc).eps)


def _should_continue(off_rel, prev_off, sweeps, *, tol, max_sweeps,
                     stall_detection=True, criterion="rel", nonfinite=None):
    """Criterion-aware wrapper over the ONE shared sweep-loop predicate
    (`ops.rounds.should_continue` — also used by `rounds.iterate_phase`
    and the mesh while_loops, so the stall logic cannot drift again):
    continue while above tol, under the sweep cap, and not stalled. The
    gate/shrink constants are measured, not derived (a mistuned threshold
    cost 100x sigma error):
      * "rel": gate 1e-4 (the endgame, close to the f32 coupling floor),
        shrink 0.25;
      * "abs": gate just above tol (tol is set near the floor) and a
        gentler 0.75 shrink — the abs path contracts only ~2-4x per sweep
        mid-range, so a 4x test there misfires sweeps early."""
    if criterion == "rel":
        gate, shrink = 1e-4, 0.25
    else:
        gate, shrink = 4.0 * tol, 0.75
    return rounds.should_continue(off_rel, prev_off, sweeps, tol=tol,
                                  max_sweeps=max_sweeps,
                                  stall_detection=stall_detection,
                                  stall_gate=gate, stall_shrink=shrink,
                                  nonfinite=nonfinite)


def _status_word(off_rel, sweeps, nonfinite, *, tol, max_sweeps):
    """Decode a finished sweep loop's exit into a `SolveStatus` code,
    in-graph. The inputs are exactly the loop's final carry, so this costs
    a handful of scalar ops — the health word rides the reductions the
    loop already pays for (see PROFILE.md). Order matters: non-finite
    trumps everything (a NaN off-norm can compare as "converged" through
    the deflation mask), tolerance-convergence is OK, an exhausted budget
    is MAX_SWEEPS, and the only remaining exit — the stall detector
    firing above tolerance — is STAGNATED. Callers decide how hard to
    react: `resilience.resilient_svd` escalates on any non-OK status, the
    CLI exits non-zero."""
    with scope("health"):
        nf = jnp.logical_or(jnp.asarray(nonfinite, jnp.bool_),
                            ~jnp.isfinite(off_rel))
        code = jnp.where(
            nf, jnp.int32(int(SolveStatus.NONFINITE)),
            jnp.where(off_rel <= tol, jnp.int32(int(SolveStatus.OK)),
                      jnp.where(sweeps >= max_sweeps,
                                jnp.int32(int(SolveStatus.MAX_SWEEPS)),
                                jnp.int32(int(SolveStatus.STAGNATED)))))
        return code.astype(jnp.int32)


# Max squared column norm over both stacks (the GLOBAL deflation scale; mesh
# callers additionally pmax this across devices). One definition, shared with
# the kernel-path sweep machinery.
_global_dmax2 = rounds._global_dmax2


# THE sanctioned host read for (possibly mesh-replicated) device scalars —
# one definition in utils/_exec.host_scalar, shared with utils.checkpoint
# and the multi-process test worker; the ad-hoc addressable_shards[0]
# pattern that used to live here is what analysis.ast_lint's GRAFT001 rule
# now rejects.
from .utils._exec import host_scalar as _host_scalar  # noqa: E402


def _blockify(a: jax.Array, n_pad: int, nblocks: int):
    """(m, n) -> top/bot stacks (k, m, b), zero-padding columns to n_pad."""
    m, n = a.shape
    if n_pad != n:
        a = jnp.pad(a, ((0, 0), (0, n_pad - n)))
    b = n_pad // nblocks
    blocks = a.reshape(m, nblocks, b).transpose(1, 0, 2)  # (2k, m, b)
    k = nblocks // 2
    return blocks[:k], blocks[k:]


def _deblockify(top: jax.Array, bot: jax.Array) -> jax.Array:
    """Inverse of `_blockify` (keeps padded columns; caller slices)."""
    blocks = jnp.concatenate([top, bot], axis=0)  # (2k, m, b)
    nblocks, m, b = blocks.shape
    return blocks.transpose(1, 0, 2).reshape(m, nblocks * b)


def _blockify_batched(a: jax.Array, n_pad: int, nblocks: int):
    """(B, m, n) -> per-member top/bot stacks (B, k, m, b): member s's
    blocks are exactly `_blockify(a[s])`. The Pallas batched lane reshapes
    the leading two axes flat to the stacked (B*k, m, b) layout (member-
    major segments, the layout `ops.rounds.sweep(batch=B)` pairs and
    rotates block-diagonally); the vmap XLA lane keeps them separate."""
    bsz, m, n = a.shape
    if n_pad != n:
        a = jnp.pad(a, ((0, 0), (0, 0), (0, n_pad - n)))
    b = n_pad // nblocks
    blocks = a.reshape(bsz, m, nblocks, b).transpose(0, 2, 1, 3)
    k = nblocks // 2
    return blocks[:, :k], blocks[:, k:]


def _stack_members(x: jax.Array) -> jax.Array:
    """(B, k, m, b) -> the stacked (B*k, m, b) pair-axis layout."""
    return x.reshape((-1,) + x.shape[2:])


def _deblockify_batched(top: jax.Array, bot: jax.Array,
                        batch: int) -> jax.Array:
    """Stacked (B*k, m, b) pairs -> (B, m, n_pad) (inverse of the
    blockify+stack composition; keeps padded columns)."""
    k = top.shape[0] // batch
    m, b = top.shape[1], top.shape[2]
    t = top.reshape(batch, k, m, b)
    bo = bot.reshape(batch, k, m, b)
    blocks = jnp.concatenate([t, bo], axis=1)       # (B, 2k, m, b)
    return blocks.transpose(0, 2, 1, 3).reshape(batch, m, 2 * k * b)


def _sweep(top, bot, vtop, vbot, *, precision, gram_dtype, method="qr-svd",
           criterion="rel", dmax2=None):
    """One full sweep: 2k-1 tournament rounds via lax.scan."""
    k = top.shape[0]
    n_rounds = sched.num_rounds(2 * k)
    with_v = vtop is not None

    def round_body(carry, _):
        top, bot, vtop, vbot, max_rel = carry
        top, bot, vtop, vbot, rel, _ = blockwise.orthogonalize_pairs(
            top, bot, vtop if with_v else None, vbot if with_v else None,
            precision=precision, gram_dtype=gram_dtype, method=method,
            criterion=criterion, dmax2=dmax2)
        if not with_v:
            vtop, vbot = carry[2], carry[3]
        top, bot = sched.rotate_blocks(top, bot)
        if with_v:
            vtop, vbot = sched.rotate_blocks(vtop, vbot)
        max_rel = jnp.maximum(max_rel, rel.astype(jnp.float32))
        return (top, bot, vtop, vbot, max_rel), None

    if vtop is None:
        vtop = vbot = jnp.zeros((k, 0, top.shape[2]), top.dtype)
    init = (top, bot, vtop, vbot, jnp.zeros((), jnp.float32))
    (top, bot, vtop, vbot, off_rel), _ = jax.lax.scan(
        round_body, init, None, length=n_rounds)
    # off_rel = max over every column pair met this sweep of the scaled
    # coupling |a_i.a_j|/(|a_i||a_j|), measured before that pair's rotation.
    return top, bot, vtop, vbot, off_rel


def _jacobi_iterate(top, bot, vtop, vbot, *, tol, max_sweeps, precision,
                    gram_dtype, method, criterion, stall_detection=True,
                    telemetry=False, stage="single", chaos_nan_sweep=None):
    """while_loop over sweeps until the scaled coupling drops below tol.

    Also stops on *stall* — see `_should_continue` — and on the health
    word tripping: the carry's ``nonfinite`` flag rides the dmax2/off-norm
    reductions each sweep already computes (NaN and Inf in the stacks both
    poison the max-of-squares), because the deflation mask silently drops
    NaN columns from the masked statistic. Returns the flag so the caller
    can decode `SolveStatus`. ``telemetry`` (static, baked into the
    caller's jit key): emit an `obs.metrics` "sweep" event per iteration;
    off keeps the trace identical to the untelemetered one.
    ``chaos_nan_sweep`` (static): `resilience.chaos` NaN injection hook;
    None (production) traces no injection code.
    """
    with_v = vtop is not None
    k = top.shape[0]
    if vtop is None:
        vtop = vbot = jnp.zeros((k, 0, top.shape[2]), top.dtype)

    def cond(state):
        _, _, _, _, off_rel, prev_off, sweeps, nonfinite = state
        return _should_continue(off_rel, prev_off, sweeps,
                                tol=tol, max_sweeps=max_sweeps,
                                stall_detection=stall_detection,
                                criterion=criterion, nonfinite=nonfinite)

    def body(state):
        top, bot, vtop, vbot, prev_off, _, sweeps, nonfinite = state
        if chaos_nan_sweep is not None:
            top = _chaos.poison(top, sweeps, chaos_nan_sweep)
        dmax2 = _global_dmax2(top, bot)
        top, bot, vtop, vbot, off_rel = _sweep(
            top, bot, vtop if with_v else None, vbot if with_v else None,
            precision=precision, gram_dtype=gram_dtype, method=method,
            criterion=criterion, dmax2=dmax2)
        nonfinite = (nonfinite | ~jnp.isfinite(dmax2)
                     | ~jnp.isfinite(off_rel))
        if telemetry:
            metrics.emit("sweep",
                         meta={"path": "xla", "stage": stage,
                               "method": method, "criterion": criterion},
                         sweep=sweeps + 1, off_rel=off_rel)
        if not with_v:
            vtop, vbot = state[2], state[3]
        return (top, bot, vtop, vbot, off_rel, prev_off, sweeps + 1,
                nonfinite)

    inf = jnp.float32(jnp.inf)
    init = (top, bot, vtop, vbot, inf, inf, jnp.int32(0),
            jnp.zeros((), jnp.bool_))
    (top, bot, vtop, vbot, off_rel, _, sweeps,
     nonfinite) = jax.lax.while_loop(cond, body, init)
    return (top, bot, (vtop if with_v else None),
            (vbot if with_v else None), off_rel, sweeps, nonfinite)


def _complete_orthonormal(u, n, dtype):
    """Complete an economy (m, n) orthonormal factor to (m, m): QR of the
    economy factor gives a basis whose leading columns equal u up to column
    signs (R is diagonal +-1 for orthonormal input); fix the signs."""
    acc = jnp.promote_types(dtype, jnp.float32)
    q, r = jnp.linalg.qr(u.astype(acc), mode="complete")
    signs = jnp.sign(jnp.diagonal(r))
    signs = jnp.where(signs == 0, 1.0, signs)
    q = q.at[:, :n].multiply(signs[None, :])
    return q.astype(dtype)


def _sigma_sort(a_work, n):
    """(sigma, column order, sorted columns) of the rotated column set:
    sigma = column norms sorted descending (padded columns are exactly zero
    and sort to the back; the [:n] slice drops them), columns in the
    accumulation dtype. Shared by `_postprocess` and the triangular-solve
    U recovery so the deflation/tie handling cannot diverge."""
    acc = jnp.promote_types(a_work.dtype, jnp.float32)
    s_all = jnp.linalg.norm(a_work.astype(acc), axis=0)  # (n_pad,)
    order = jnp.argsort(-s_all)[:n]
    s = s_all[order]
    a_sorted = jnp.take(a_work, order, axis=1).astype(acc)
    return s, order, a_sorted


def _normalize_cols(a_sorted, s, dtype):
    """Columns / sigma with the rank-deficiency guard (exact-zero sigma ->
    zero column, not inf)."""
    safe = jnp.maximum(s, jnp.finfo(a_sorted.dtype).tiny)
    cols = (a_sorted / safe[None, :]).astype(dtype)
    return jnp.where(s[None, :] > 0, cols, jnp.zeros_like(cols))


def _postprocess(a_work, v_work, n, *, compute_u, full_u, dtype):
    """sigma = column norms; sort descending; U = A_work * diag(1/sigma).

    Mirrors the reference's post-processing (sigma: lib/JacobiMethods.cu:1146-1154,
    U = A * Sigma^{-1}: lib/JacobiMethods.cu:1156-1173) plus the descending sort
    and rank-deficiency guard it lacks.
    """
    with scope("postprocess"):
        m = a_work.shape[0]
        s, order, a_sorted = _sigma_sort(a_work, n)
        u = v = None
        if v_work is not None:
            v = jnp.take(v_work, order, axis=1).astype(dtype)
        if compute_u:
            u = _normalize_cols(a_sorted, s, dtype)
            if full_u and m > n:
                u = _complete_orthonormal(u, n, dtype)
        return u, s.astype(dtype), v


_PADDED_STATIC = (
    "n", "compute_u", "compute_v", "full_u", "nblocks", "tol", "max_sweeps",
    "precision", "gram_dtype_name", "method", "criterion", "stall_detection",
    "telemetry", "chaos_nan_sweep")


def _svd_padded_impl(a, *, n, compute_u, compute_v, full_u, nblocks, tol,
                     max_sweeps, precision, gram_dtype_name, method,
                     criterion, stall_detection=True, telemetry=False,
                     chaos_nan_sweep=None):
    m, n_pad = a.shape
    dtype = a.dtype
    gram_dtype = jnp.dtype(gram_dtype_name)
    top, bot = _blockify(a, n_pad, nblocks)
    if compute_v:
        veye = jnp.eye(n_pad, dtype=dtype)
        vtop, vbot = _blockify(veye, n_pad, nblocks)
    else:
        vtop = vbot = None
    if method == "hybrid":
        # Phase 1: all-matmul gram-eigh sweeps to absolute (sigma_max-scaled)
        # convergence; phase 2: qr-svd sweeps to the relative criterion,
        # restoring U orthogonality / small-sigma relative accuracy. The
        # phase-2 loop starts from near-converged state, so it typically
        # adds only 1-3 sweeps.
        top, bot, vtop, vbot, off1, s1, nf1 = _jacobi_iterate(
            top, bot, vtop, vbot, tol=_abs_phase_tol(dtype),
            max_sweeps=max_sweeps,
            precision=precision, gram_dtype=gram_dtype, method="gram-eigh",
            criterion="abs", stall_detection=stall_detection,
            telemetry=telemetry, stage="bulk",
            chaos_nan_sweep=chaos_nan_sweep)
        if telemetry:
            metrics.emit("stage", meta={"path": "xla", "stage": "bulk"},
                         sweeps=s1, off_rel=off1)
        # max_sweeps stays a TOTAL budget across both phases.
        top, bot, vtop, vbot, off2, s2, nf2 = _jacobi_iterate(
            top, bot, vtop, vbot, tol=tol, max_sweeps=max_sweeps - s1,
            precision=precision, gram_dtype=gram_dtype, method="qr-svd",
            criterion=criterion, stall_detection=stall_detection,
            telemetry=telemetry, stage="polish")
        # A zero-iteration polish (bulk ate the budget) leaves its init
        # off = inf; report the bulk statistic instead.
        off_rel = jnp.where(s2 > 0, off2, off1)
        sweeps = s1 + s2
        nonfinite = nf1 | nf2
    else:
        top, bot, vtop, vbot, off_rel, sweeps, nonfinite = _jacobi_iterate(
            top, bot, vtop, vbot, tol=tol, max_sweeps=max_sweeps,
            precision=precision, gram_dtype=gram_dtype, method=method,
            criterion=criterion, stall_detection=stall_detection,
            telemetry=telemetry, stage="single",
            chaos_nan_sweep=chaos_nan_sweep)
    status = _status_word(off_rel, sweeps, nonfinite, tol=tol,
                          max_sweeps=max_sweeps)
    a_work = _deblockify(top, bot)
    v_work = _deblockify(vtop, vbot)[:n, :] if compute_v else None
    u, s, v = _postprocess(a_work, v_work, n, compute_u=compute_u,
                           full_u=full_u, dtype=dtype)
    return u, s, v, sweeps, off_rel, status


_svd_padded = partial(jax.jit, static_argnames=_PADDED_STATIC)(
    _svd_padded_impl)


def _svd_padded_batched_impl(a, *, n, compute_u, compute_v, full_u, nblocks,
                             tol, max_sweeps, precision, gram_dtype_name,
                             method, criterion, stall_detection=True,
                             telemetry=False, chaos_nan_sweep=None):
    """vmap twin of `_svd_padded` over a (B, m, n_pad) stack: under vmap
    the sweep while_loops run until every member's predicate clears with
    per-member carry masking, so sweeps/off/status come out per member —
    the XLA block solvers' batched-solve lane (f64 and tiny-n buckets,
    where the Pallas stacked lane does not apply)."""
    return jax.vmap(lambda x: _svd_padded_impl(
        x, n=n, compute_u=compute_u, compute_v=compute_v, full_u=full_u,
        nblocks=nblocks, tol=tol, max_sweeps=max_sweeps,
        precision=precision, gram_dtype_name=gram_dtype_name,
        method=method, criterion=criterion,
        stall_detection=stall_detection, telemetry=telemetry,
        chaos_nan_sweep=chaos_nan_sweep))(a)


_svd_padded_batched = partial(jax.jit, static_argnames=_PADDED_STATIC)(
    _svd_padded_batched_impl)


def _colnorms_compensated(w):
    """Column 2-norms with two-level compensated accumulation.

    A plain f32 sum of m squares carries ~sqrt(m)*eps relative error —
    exactly the sigma floor the refinement is trying to remove. Chunk the
    rows (per-chunk f32 partials, ~sqrt(m/C)*eps each) and combine the
    chunk partials with a Kahan scan (error ~eps), leaving ~sqrt(m/C)*eps/2
    total: ~1.7e-7 relative at m = 8192, C = 256."""
    m, n = w.shape
    acc = jnp.promote_types(w.dtype, jnp.float32)
    w = w.astype(acc)
    c = min(256, m)
    pad = (-m) % c
    if pad:
        w = jnp.pad(w, ((0, pad), (0, 0)))
    parts = jnp.sum((w * w).reshape(c, -1, n), axis=1)  # (c, n)

    def kahan(carry, p):
        s, comp = carry
        y = p - comp
        t = s + y
        comp = (t - s) - y
        return (t, comp), None

    zero = jnp.zeros((n,), acc)
    (s2, _), _ = jax.lax.scan(kahan, (zero, zero), parts)
    return jnp.sqrt(s2)


def _refine_from_work(work, cols, s, rot):
    """Sigma refinement against the solve's own WORKING matrix, applied
    before factor recombination: with X = work @ G converged and sorted,
    sigma_i = ||work @ rot_i|| through the re-normalized rotation product
    (preferred), or ||work^T @ cols_i|| when only the column factor
    exists. On the preconditioned paths work is the
    n x n triangle L with sigma(L) = sigma(A) up to QR's backward error
    (measured 6e-8 at 512^2), so the product costs 2n^3 instead of
    re-touching the m x n input (16x cheaper at 65536x4096 — the
    original A @ V form measurably ate the tall-skinny advantage).

    The probe factor must have UNIT column norms: a norm error eta in the
    probe is a FIRST-order sigma error (||work @ (1+eta) v|| =
    (1+eta) sigma). ``cols`` is normalized by construction; the
    accumulated ``rot`` drifts ~1e-5 off unit norm over a solve's applies
    (measured: refining through raw rot gave serr 4e-6 vs 1.6e-8 through
    cols), so the rot fallback re-normalizes with compensated norms
    first. Returns (cols, s, rot) re-permuted by the refined order; no-op
    when neither factor exists."""
    if cols is None and rot is None:
        return cols, s, rot
    with scope("sigma_refine"):
        acc = jnp.promote_types(work.dtype, jnp.float32)
        hi = jax.lax.Precision.HIGHEST
        if rot is not None:
            # Measured preference (512^2 CPU f32): work @ rot_normalized
            # gives serr ~1e-7 vs ~3.5e-7 for work^T @ cols.
            probe = rot.astype(acc)
            norms = jnp.maximum(_colnorms_compensated(probe),
                                jnp.finfo(acc).tiny)
            w = jnp.matmul(work.astype(acc), probe / norms[None, :],
                           precision=hi)
        else:
            w = jnp.matmul(work.T.astype(acc), cols.astype(acc),
                           precision=hi)
        s2 = _colnorms_compensated(w).astype(s.dtype)
        order = jnp.argsort(-s2)
        take = lambda x: None if x is None else jnp.take(x, order, axis=1)
        return take(cols), s2[order], take(rot)


def _precondition_qr(a):
    """Drmac-style preconditioning factorization, shared by the single-chip
    Pallas solve and the mesh solve so their bookkeeping cannot diverge:
    norm-sort the columns, factor A P = Q1 R, return
    (q1, r, order, work = R^T) — the sweep loop then runs on the graded
    lower-triangular L = R^T. QR in f32 at minimum: sub-f32 dtypes have no
    QR kernel (LAPACK or TPU), and the factorization must be exact at
    working precision.

    The factorization itself goes through the blocked TSQR
    (`ops.sketch.tsqr`): for modestly-tall shapes its base case IS one
    dense reduced QR (byte-equivalent to the historical behavior), and
    for genuinely tall m >= 8n inputs the chunked reduction tree keeps
    every intermediate at most chunk-rows tall — the tall path of the
    ROADMAP "rectangular workloads" item, and the structure GSPMD can
    partition chunk-wise on a mesh (the sharded solve calls this same
    helper outside its shard_map loop)."""
    with scope("precondition_qr"):
        m, n = a.shape
        norms = jnp.sum(a.astype(jnp.float32) ** 2, axis=0)
        order = jnp.argsort(-norms)
        acc = jnp.promote_types(a.dtype, jnp.float32)
        ap = jnp.take(a, order, axis=1).astype(acc)
    chunked = m >= _sketch.TALL_RATIO * n
    q1, r = _sketch.tsqr(ap, chunk=None if chunked else max(m, n))
    return q1, r, order, r.T.astype(a.dtype)


# Module-level jit of the preconditioning factorization: the host-stepped
# path (SweepStepper._precond_state) used to wrap it ad hoc per stepper,
# which compiled a fresh executable per REQUEST — death for the serving
# layer, where hundreds of steppers are built for the same bucket shape.
# One shared wrapper means one compile per (shape, dtype) problem key
# (config.RETRACE_BUDGETS entry "solver._precondition_qr_jit").
_precondition_qr_jit = jax.jit(_precondition_qr)


def _recombine_precondition(cols, rot, *, m, n, compute_u, compute_v,
                            full_u, dtype, q1, order):
    """(u, v) recombination for the single-QR bookkeeping (rotation
    product -> U, normalized columns -> V): with A P = Q1 L^T and
    L = U_L S V_L^T, A = (Q1 V_L) S (P U_L)^T — so U = Q1 @ rot and V
    scatters the normalized columns back through the norm-sort
    permutation. Shared by solver._svd_pallas and parallel.sharded."""
    with scope("recombine"):
        hi = jax.lax.Precision.HIGHEST
        u = v = None
        if compute_u:
            u = jnp.matmul(q1, rot, precision=hi).astype(dtype)
            if full_u and m > n:
                u = _complete_orthonormal(u, n, dtype)
        if compute_v:
            v = jnp.zeros_like(cols).at[order, :].set(cols)
        return u, v


def _ns_orthogonalize(g, steps: int = 3):
    """Newton-Schulz polar iteration ``g <- g (1.5 I - 0.5 g^T g)``.

    Quadratic contraction of the orthogonality error (valid for
    ||g^T g - I|| < 1): 3 steps take the bf16 bulk accumulator's ~1e-1
    error to the f32 floor. Padded identity rows/columns are exact fixed
    points (their Gram block is exactly I), so the padded structure the
    reconstitution relies on survives."""
    with scope("ns_orthogonalize"):
        hi = jax.lax.Precision.HIGHEST
        g = g.astype(jnp.promote_types(g.dtype, jnp.float32))
        eye = jnp.eye(g.shape[0], dtype=g.dtype)
        for _ in range(steps):
            gram = jnp.matmul(g.T, g, precision=hi)
            g = jnp.matmul(g, 1.5 * eye - 0.5 * gram, precision=hi)
        return g


_PALLAS_STATIC = (
    "n", "compute_u", "compute_v", "full_u", "nblocks", "n_pad", "tol",
    "max_sweeps", "precondition", "polish", "bulk_bf16", "mixed",
    "mixed_store", "interpret", "stall_detection", "refine", "telemetry",
    "chaos_nan_sweep")


def _svd_pallas_impl(a, *, n, compute_u, compute_v, full_u, nblocks, n_pad,
                     tol, max_sweeps, precondition, polish, bulk_bf16, mixed,
                     mixed_store="f32", interpret=False, stall_detection=True,
                     refine=False, telemetry=False, chaos_nan_sweep=None):
    """The Pallas device-kernel solve (pair_solver="pallas"), m >= n.

    With preconditioning (Drmac-style, dgejsv's structure): norm-sort the
    columns, factor A P = Q1 R, and run one-sided Jacobi on L = R^T — graded
    triangular factors converge in measurably fewer sweeps (15 -> 11 at
    2048^2 f32 on v5e) and the tail couplings collapse so the round-skip
    taper bites. Bookkeeping: L = U_L S V_L^T gives
    A = (Q1 V_L) S (P U_L)^T, so the ROTATION product becomes U and the
    normalized COLUMNS become V — the accumulation is only needed when U is
    wanted, and V comes free.

    ``mixed`` (SVDConfig.mixed_bulk — the north-star regime): bulk sweeps
    run on bf16 COPIES of the stacks (native bf16-in/f32-acc MXU passes)
    while always accumulating the rotation product G; at the bf16 floor
    (rounds.MIXED_TOL) the bf16 X is DISCARDED — its drift against L.G is
    an irreducible backward error — and the f32 state is reconstituted as
    X = L @ NS(G) at HIGHEST precision, from which standard f32 sweeps
    polish to ``tol``. Result accuracy is therefore the f32 class.
    """
    m = a.shape[0]
    dtype = a.dtype
    hi = jax.lax.Precision.HIGHEST
    if precondition in ("on", "double"):
        q1, r, order, work = _precondition_qr(a)
        acc = jnp.promote_types(dtype, jnp.float32)
        if precondition == "double":
            # Second preconditioning (dgejsv's QRF-then-LQF structure): QR
            # the transposed triangle again and run Jacobi on R2^T. With
            # A P = Q1 R and R^T = Q2 R2, W = R2^T G gives
            # A P = Q1 W G^T Q2^T = (Q1 U_w) S (Q2 G)^T — so now the
            # normalized COLUMNS become U and the rotation product becomes
            # V (the reverse of the single-precondition bookkeeping).
            q2, r2 = jnp.linalg.qr(r.T)
            work = r2.T.astype(dtype)    # L2: lower-triangular, (n, n)
            accumulate = compute_v       # rotations -> V
            want_cols = compute_u        # normalized columns -> U
        else:
            work = r.T.astype(dtype)     # L: lower-triangular, (n, n)
            accumulate = compute_u       # rotations -> U
            want_cols = compute_v        # normalized columns -> V
    else:
        work = a
        accumulate = compute_v
        want_cols = compute_u

    top, bot = _blockify(work, n_pad, nblocks)
    if accumulate:
        vtop, vbot = _blockify(jnp.eye(n_pad, dtype=dtype), n_pad, nblocks)
    else:
        vtop = vbot = None

    bulk_off = jnp.float32(jnp.inf)
    bulk_sweeps = jnp.int32(0)
    bulk_nf = None
    if mixed:
        # Stage 1 (bulk): cheap sweeps down to the bf16 drift floor. G is
        # ALWAYS accumulated here — it is the reconstitution map — even
        # when the caller wants no factors. ``mixed_store`` picks the
        # storage regime (the kernel is HBM-byte-bound, so bytes are the
        # lever — see SVDConfig.mixed_store):
        #   "f32":   f32-stored stacks, bf16x3 split applies + single-pass
        #            bf16 Gram panels (per-apply error ~eps_bf16^2);
        #   "bf16":  X stacks stored bf16 (native single-pass applies,
        #            half the X bytes; X is DISCARDED at reconstitution so
        #            its storage rounding — coupling noise
        #            ~eps_bf16/sqrt(n) per round, drift
        #            ~sqrt(rounds)*eps_bf16/sqrt(n) vs L.G — is absorbed
        #            by the MIXED_TOL contract), G still f32 + x3;
        #   "bf16g": G stored bf16 as well — its storage rounding
        #            random-walks G ~1e-1 off orthogonal, paid back by two
        #            extra Newton-Schulz steps on readback.
        if accumulate:
            gvt, gvb = vtop, vbot
        else:
            gvt, gvb = _blockify(jnp.eye(n_pad, dtype=dtype), n_pad, nblocks)
        bf16 = jnp.bfloat16
        xt, xb = top, bot
        if mixed_store in ("bf16", "bf16g"):
            xt, xb = top.astype(bf16), bot.astype(bf16)
        if mixed_store == "bf16g":
            gvt, gvb = gvt.astype(bf16), gvb.astype(bf16)
        _, _, gvt, gvb, bulk_off, bulk_sweeps, bulk_nf = rounds.iterate_phase(
            xt, xb, gvt, gvb, stop_tol=jnp.float32(rounds.MIXED_TOL),
            rtol=rounds.MIXED_TOL, max_sweeps=max_sweeps,
            interpret=interpret, polish=polish, bf16_gram=True,
            apply_x3=True, stall_detection=stall_detection,
            stall_gate=10.0 * rounds.MIXED_TOL, stall_shrink=0.5,
            telemetry=telemetry, stage="mixed_bulk",
            chaos_nan_sweep=chaos_nan_sweep)
        if telemetry:
            # No "path" tag here: the stage's own sweep events carry the
            # exact fused/kernel label (rounds.iterate_phase computes the
            # real kernel gate; duplicating an approximation of it here
            # could disagree within one record).
            metrics.emit("stage", meta={"stage": "mixed_bulk"},
                         sweeps=bulk_sweeps, off_rel=bulk_off)
        # Stage 2 (reconstitute): orthogonalize G in f32 (~1e-4 off after
        # the f32-accumulated regimes — 2 Newton-Schulz steps reach the
        # f32 floor; ~1e-1 off after bf16 storage — 4 steps), then rebuild
        # the stacks exactly as work @ G — the bulk X is DISCARDED,
        # deleting its X-vs-L.G drift (padded columns never mix — they
        # deflate in the kernel — so [work | 0] @ G == work @ G[:cols]).
        with scope("reconstitute"):
            g = _ns_orthogonalize(_deblockify(gvt, gvb).astype(jnp.float32),
                                  steps=4 if mixed_store == "bf16g" else 2)
            x = jnp.matmul(work.astype(g.dtype), g[:work.shape[1], :],
                           precision=hi).astype(dtype)
            top, bot = _blockify(x, n_pad, nblocks)
            if accumulate:
                vtop, vbot = _blockify(g.astype(dtype), n_pad, nblocks)

    # f32 sweeps (stage 3 of the mixed regime, or the whole solve).
    top, bot, vtop, vbot, off_rel, sweeps, nonfinite = rounds.iterate(
        top, bot, vtop, vbot, tol=tol, max_sweeps=max_sweeps,
        interpret=interpret, polish=polish, bulk_bf16=bulk_bf16,
        stall_detection=stall_detection, start_sweeps=bulk_sweeps,
        telemetry=telemetry, stage="polish" if mixed else "single",
        nonfinite0=bulk_nf, chaos_nan_sweep=chaos_nan_sweep)
    # Mixed budget-exhaustion: report the bulk statistic if the polish
    # never ran (cf. rounds.iterate's identical carry handling).
    off_rel = jnp.where(sweeps > bulk_sweeps, off_rel, bulk_off)
    status = _status_word(off_rel, sweeps, nonfinite, tol=tol,
                          max_sweeps=max_sweeps)

    a_work = _deblockify(top, bot)
    v_work = _deblockify(vtop, vbot)[:n, :] if accumulate else None
    cols, s, rot = _postprocess(a_work, v_work, n, compute_u=want_cols,
                                full_u=False, dtype=dtype)
    if refine:
        cols, s, rot = _refine_from_work(work, cols, s, rot)
    if precondition == "double":
        u = v = None
        if compute_u:
            u = jnp.matmul(q1, cols.astype(acc), precision=hi).astype(dtype)
            if full_u and m > n:
                u = _complete_orthonormal(u, n, dtype)
        if compute_v:
            v = jnp.matmul(q2, rot.astype(acc), precision=hi)
            v = jnp.zeros_like(v).at[order, :].set(v).astype(dtype)
        return u, s, v, sweeps, off_rel, status
    if precondition == "on":
        u, v = _recombine_precondition(
            cols, rot, m=m, n=n, compute_u=compute_u, compute_v=compute_v,
            full_u=full_u, dtype=dtype, q1=q1, order=order)
        return u, s, v, sweeps, off_rel, status
    u = cols
    if compute_u and full_u and m > n and u is not None:
        u = _complete_orthonormal(u, n, dtype)
    return u, s, rot, sweeps, off_rel, status


_svd_pallas = partial(jax.jit, static_argnames=_PALLAS_STATIC)(
    _svd_pallas_impl)
# Input-donating twin (SVDConfig.donate_input): same trace, but XLA may
# reuse the caller's input buffer — required headroom at the chip's
# largest sizes (the caller's array is invalidated).
_svd_pallas_donated = partial(jax.jit, static_argnames=_PALLAS_STATIC,
                              donate_argnums=(0,))(_svd_pallas_impl)


_PALLAS_BATCHED_STATIC = (
    "n", "compute_u", "compute_v", "nblocks", "n_pad", "tol", "max_sweeps",
    "precondition", "polish", "interpret", "stall_detection", "refine",
    "chaos_nan_sweep")


def _svd_pallas_batched_impl(a, *, n, compute_u, compute_v, nblocks, n_pad,
                             tol, max_sweeps, precondition, polish,
                             interpret=False, stall_detection=True,
                             refine=False, chaos_nan_sweep=None):
    """Batched Pallas kernel solve: B same-shaped (m, n) matrices in ONE
    fused sweep loop (`svd_batched`'s kernel lane). The matrices stack
    along the existing pair axis — (B, m, n) blockifies to (B*k, m, b)
    stacks with the tournament pairing block-diagonal per matrix
    (`ops.rounds.sweep(batch=B)`), so B matrices ride the SAME kernel
    launches and latency chain as one: the rotation kernel is
    latency-bound, not FLOP-bound (PROFILE.md item 1), which makes a
    batch of small solves cost close to one. Convergence bookkeeping,
    deflation scales, and the health word are all per member (one
    NaN-poisoned member reports NONFINITE without perturbing its
    neighbors' statistics — their blocks never meet).

    Per-member bookkeeping of `_svd_pallas_impl` minus the bulk regimes
    (mixed_bulk / bulk_bf16 / precondition="double" are fused-single-solve
    modes; the planner rejects them on the batched lane). Preconditioning
    and postprocessing vmap over members. Returns batched factors plus
    (B,) sweeps/off/status vectors.
    """
    batch, m = a.shape[0], a.shape[1]
    dtype = a.dtype
    if precondition:
        q1, _, order, work = jax.vmap(_precondition_qr)(a)
        accumulate = compute_u       # rotations -> U (per member)
        want_cols = compute_v        # normalized columns -> V
    else:
        q1 = order = None
        work = a
        accumulate = compute_v
        want_cols = compute_u

    top, bot = map(_stack_members,
                   _blockify_batched(work, n_pad, nblocks))
    if accumulate:
        eye = jnp.broadcast_to(jnp.eye(n_pad, dtype=dtype),
                               (batch, n_pad, n_pad))
        vtop, vbot = map(_stack_members,
                         _blockify_batched(eye, n_pad, nblocks))
    else:
        vtop = vbot = None

    top, bot, vtop, vbot, off, msweeps, nonfinite = rounds.iterate_batched(
        top, bot, vtop, vbot, batch=batch, tol=tol, max_sweeps=max_sweeps,
        interpret=interpret, polish=polish,
        stall_detection=stall_detection, chaos_nan_sweep=chaos_nan_sweep)
    status = _status_word(off, msweeps, nonfinite, tol=tol,
                          max_sweeps=max_sweeps)

    a_work = _deblockify_batched(top, bot, batch)
    v_work = (_deblockify_batched(vtop, vbot, batch)[:, :n, :]
              if accumulate else None)

    def post_one(aw, vw, wk):
        cols, s, rot = _postprocess(aw, vw, n, compute_u=want_cols,
                                    full_u=False, dtype=dtype)
        if refine:
            cols, s, rot = _refine_from_work(wk, cols, s, rot)
        return cols, s, rot

    cols, s, rot = jax.vmap(post_one)(a_work, v_work, work)
    if precondition:
        u, v = jax.vmap(lambda c, r, qq, oo: _recombine_precondition(
            c, r, m=m, n=n, compute_u=compute_u, compute_v=compute_v,
            full_u=False, dtype=dtype, q1=qq, order=oo))(cols, rot, q1,
                                                         order)
        return u, s, v, msweeps, off, status
    return cols, s, rot, msweeps, off, status


_svd_pallas_batched = partial(jax.jit,
                              static_argnames=_PALLAS_BATCHED_STATIC)(
    _svd_pallas_batched_impl)


_BLOCK_ROTATION_STATIC = (
    "n", "compute_u", "compute_v", "full_u", "nblocks", "n_pad", "tol",
    "max_sweeps", "precondition", "polish", "apply_x3", "interpret",
    "stall_detection", "refine", "telemetry", "chaos_nan_sweep")


def _svd_block_rotation_impl(a, *, n, compute_u, compute_v, full_u, nblocks,
                             n_pad, tol, max_sweeps, precondition, polish,
                             apply_x3=False, interpret=False,
                             stall_detection=True, refine=False,
                             telemetry=False, chaos_nan_sweep=None):
    """The MXU-native blocked-rotation solve (pair_solver=
    "block_rotation"), m >= n — the ROADMAP "attack the 1.7% MFU" lane.

    Two phases around the same preconditioning/postprocessing bookkeeping
    as `_svd_pallas_impl`:

      1. BULK (`rounds.iterate_block`): every tournament round solves its
         block pair's FULL 2b x 2b Gram subproblem on-chip — the inner
         Jacobi cycle runs as a batched eigendecomposition with the
         rotations accumulated into one orthogonal factor J
         (`ops.block_rotate.accumulate`) — and applies J to the m x b
         panels (and V) as ONE rank-2b matmul per pair, batched along the
         pair axis. The MXU sees stacked (m, 2b) x (2b, 2b) GEMMs instead
         of the pallas lane's per-round chain of b latency-bound rotation
         steps; ``apply_x3`` (the resolved mixed_store gate) runs those
         GEMMs as bf16x3 split products. The phase drives the ABS
         statistic — the class the eigh-quality subproblem solves
         converge — down to `_abs_phase_tol`.
      2. POLISH (`rounds.iterate` — the current kernel, kept as the
         fallback lane): scalar-accurate Rutishauser sweeps restore the
         dgesvj rel criterion (U orthogonality, small-sigma relative
         accuracy), starting from near-converged state where the
         round-skip taper bites.

    Result accuracy is therefore the same class as the pallas lane (the
    polish phase's arithmetic is identical); ``max_sweeps`` is a TOTAL
    budget across both phases.
    """
    m = a.shape[0]
    dtype = a.dtype
    if precondition:
        q1, _, order, work = _precondition_qr(a)
        accumulate = compute_u       # rotations -> U
        want_cols = compute_v        # normalized columns -> V
    else:
        q1 = order = None
        work = a
        accumulate = compute_v
        want_cols = compute_u

    top, bot = _blockify(work, n_pad, nblocks)
    if accumulate:
        vtop, vbot = _blockify(jnp.eye(n_pad, dtype=dtype), n_pad, nblocks)
    else:
        vtop = vbot = None

    top, bot, vtop, vbot, bulk_off, bulk_sweeps, bulk_nf = \
        rounds.iterate_block(
            top, bot, vtop, vbot,
            abs_tol=_BLOCK_BULK_TOL_FACTOR * _abs_phase_tol(dtype),
            max_sweeps=max_sweeps, interpret=interpret, apply_x3=apply_x3,
            stall_detection=stall_detection, telemetry=telemetry,
            chaos_nan_sweep=chaos_nan_sweep)
    if telemetry:
        metrics.emit("stage", meta={"stage": "block_bulk"},
                     sweeps=bulk_sweeps, off_rel=bulk_off)
    top, bot, vtop, vbot, off_rel, sweeps, nonfinite = rounds.iterate(
        top, bot, vtop, vbot, tol=tol, max_sweeps=max_sweeps,
        interpret=interpret, polish=polish, bulk_bf16=False,
        stall_detection=stall_detection, start_sweeps=bulk_sweeps,
        telemetry=telemetry, stage="polish", nonfinite0=bulk_nf,
        chaos_nan_sweep=chaos_nan_sweep)
    # Bulk budget-exhaustion: report the bulk statistic if the polish
    # never ran (cf. the hybrid XLA path's identical carry handling; the
    # scales differ — abs vs rel — exactly as they do there).
    off_rel = jnp.where(sweeps > bulk_sweeps, off_rel, bulk_off)
    status = _status_word(off_rel, sweeps, nonfinite, tol=tol,
                          max_sweeps=max_sweeps)

    a_work = _deblockify(top, bot)
    v_work = _deblockify(vtop, vbot)[:n, :] if accumulate else None
    cols, s, rot = _postprocess(a_work, v_work, n, compute_u=want_cols,
                                full_u=False, dtype=dtype)
    if refine:
        cols, s, rot = _refine_from_work(work, cols, s, rot)
    if precondition:
        u, v = _recombine_precondition(
            cols, rot, m=m, n=n, compute_u=compute_u, compute_v=compute_v,
            full_u=full_u, dtype=dtype, q1=q1, order=order)
        return u, s, v, sweeps, off_rel, status
    u = cols
    if compute_u and full_u and m > n and u is not None:
        u = _complete_orthonormal(u, n, dtype)
    return u, s, rot, sweeps, off_rel, status


_svd_block_rotation = partial(jax.jit,
                              static_argnames=_BLOCK_ROTATION_STATIC)(
    _svd_block_rotation_impl)
# Input-donating twin, mirroring _svd_pallas_donated.
_svd_block_rotation_donated = partial(
    jax.jit, static_argnames=_BLOCK_ROTATION_STATIC,
    donate_argnums=(0,))(_svd_block_rotation_impl)


_BLOCK_ROTATION_BATCHED_STATIC = (
    "n", "compute_u", "compute_v", "nblocks", "n_pad", "tol", "max_sweeps",
    "precondition", "polish", "apply_x3", "interpret", "stall_detection",
    "refine", "chaos_nan_sweep")


def _svd_block_rotation_batched_impl(a, *, n, compute_u, compute_v, nblocks,
                                     n_pad, tol, max_sweeps, precondition,
                                     polish, apply_x3=False, interpret=False,
                                     stall_detection=True, refine=False,
                                     chaos_nan_sweep=None):
    """Batched blocked-rotation solve: B same-shaped matrices stacked
    along the pair axis (`_svd_pallas_batched_impl`'s layout) through the
    bulk (`rounds.iterate_block_batched` — subproblem eigh batches over
    B*k panels, stats segment per member) and the kernel polish
    (`rounds.iterate_batched` continuing the per-member counters, so
    max_sweeps stays a total budget). Per-member off/sweeps/status, one
    NaN member decodes NONFINITE with OK neighbors."""
    batch, m = a.shape[0], a.shape[1]
    dtype = a.dtype
    if precondition:
        q1, _, order, work = jax.vmap(_precondition_qr)(a)
        accumulate = compute_u
        want_cols = compute_v
    else:
        q1 = order = None
        work = a
        accumulate = compute_v
        want_cols = compute_u

    top, bot = map(_stack_members,
                   _blockify_batched(work, n_pad, nblocks))
    if accumulate:
        eye = jnp.broadcast_to(jnp.eye(n_pad, dtype=dtype),
                               (batch, n_pad, n_pad))
        vtop, vbot = map(_stack_members,
                         _blockify_batched(eye, n_pad, nblocks))
    else:
        vtop = vbot = None

    (top, bot, vtop, vbot, bulk_off, bulk_sweeps, bulk_msweeps,
     bulk_nf) = rounds.iterate_block_batched(
        top, bot, vtop, vbot, batch=batch,
        abs_tol=_BLOCK_BULK_TOL_FACTOR * _abs_phase_tol(dtype),
        max_sweeps=max_sweeps, interpret=interpret, apply_x3=apply_x3,
        stall_detection=stall_detection, chaos_nan_sweep=chaos_nan_sweep)
    top, bot, vtop, vbot, off, msweeps, nonfinite = rounds.iterate_batched(
        top, bot, vtop, vbot, batch=batch, tol=tol, max_sweeps=max_sweeps,
        interpret=interpret, polish=polish,
        stall_detection=stall_detection, start_sweeps=bulk_sweeps,
        msweeps0=bulk_msweeps, nonfinite0=bulk_nf,
        chaos_nan_sweep=chaos_nan_sweep)
    # Members whose polish never swept (total budget exhausted in bulk)
    # report the bulk statistic, cf. the single-solve carry handling.
    off = jnp.where(msweeps > bulk_msweeps, off, bulk_off)
    status = _status_word(off, msweeps, nonfinite, tol=tol,
                          max_sweeps=max_sweeps)

    a_work = _deblockify_batched(top, bot, batch)
    v_work = (_deblockify_batched(vtop, vbot, batch)[:, :n, :]
              if accumulate else None)

    def post_one(aw, vw, wk):
        cols, s, rot = _postprocess(aw, vw, n, compute_u=want_cols,
                                    full_u=False, dtype=dtype)
        if refine:
            cols, s, rot = _refine_from_work(wk, cols, s, rot)
        return cols, s, rot

    cols, s, rot = jax.vmap(post_one)(a_work, v_work, work)
    if precondition:
        u, v = jax.vmap(lambda c, r, qq, oo: _recombine_precondition(
            c, r, m=m, n=n, compute_u=compute_u, compute_v=compute_v,
            full_u=False, dtype=dtype, q1=qq, order=oo))(cols, rot, q1,
                                                         order)
        return u, s, v, msweeps, off, status
    return cols, s, rot, msweeps, off, status


_svd_block_rotation_batched = partial(
    jax.jit, static_argnames=_BLOCK_ROTATION_BATCHED_STATIC)(
    _svd_block_rotation_batched_impl)


_RESIDENT_STATIC = (
    "n", "compute_u", "compute_v", "full_u", "nblocks", "n_pad", "tol",
    "max_sweeps", "r_rounds", "precondition", "polish", "apply_x3",
    "interpret", "stall_detection", "refine", "telemetry",
    "chaos_nan_sweep")


def _svd_resident_impl(a, *, n, compute_u, compute_v, full_u, nblocks,
                       n_pad, tol, max_sweeps, r_rounds, precondition,
                       polish, apply_x3=False, interpret=False,
                       stall_detection=True, refine=False,
                       telemetry=False, chaos_nan_sweep=None):
    """The VMEM-resident megakernel solve (pair_solver="resident"),
    m >= n: `_svd_block_rotation_impl`'s exact two-phase structure with
    the bulk swapped for `ops.pallas_resident.iterate_resident` — every
    group of ``r_rounds`` tournament rounds solves its 2b x 2b
    subproblems against the carried full Gram (n^2-scale, zero panel
    reads) and applies all R factor stacks in ONE panel pass (the Pallas
    megakernel on compiled TPU backends, the composed-GEMM / iterated
    XLA twin elsewhere). The polish phase, preconditioning and
    postprocessing bookkeeping are bitwise the block_rotation lane's —
    the accuracy contract (sigma exact, U orthonormal, v_orth_live
    clean) is inherited from the same unchanged pallas endgame."""
    m = a.shape[0]
    dtype = a.dtype
    if precondition:
        q1, _, order, work = _precondition_qr(a)
        accumulate = compute_u       # rotations -> U
        want_cols = compute_v        # normalized columns -> V
    else:
        q1 = order = None
        work = a
        accumulate = compute_v
        want_cols = compute_u

    top, bot = _blockify(work, n_pad, nblocks)
    if accumulate:
        vtop, vbot = _blockify(jnp.eye(n_pad, dtype=dtype), n_pad, nblocks)
    else:
        vtop = vbot = None

    top, bot, vtop, vbot, bulk_off, bulk_sweeps, bulk_nf = \
        _resident.iterate_resident(
            top, bot, vtop, vbot, r_rounds=r_rounds,
            abs_tol=_BLOCK_BULK_TOL_FACTOR * _abs_phase_tol(dtype),
            max_sweeps=max_sweeps, interpret=interpret, apply_x3=apply_x3,
            stall_detection=stall_detection, telemetry=telemetry,
            chaos_nan_sweep=chaos_nan_sweep)
    if telemetry:
        metrics.emit("stage", meta={"stage": "resident_bulk"},
                     sweeps=bulk_sweeps, off_rel=bulk_off)
    top, bot, vtop, vbot, off_rel, sweeps, nonfinite = rounds.iterate(
        top, bot, vtop, vbot, tol=tol, max_sweeps=max_sweeps,
        interpret=interpret, polish=polish, bulk_bf16=False,
        stall_detection=stall_detection, start_sweeps=bulk_sweeps,
        telemetry=telemetry, stage="polish", nonfinite0=bulk_nf,
        chaos_nan_sweep=chaos_nan_sweep)
    # Bulk budget-exhaustion: report the bulk statistic if the polish
    # never ran (cf. the block_rotation lane's identical carry handling).
    off_rel = jnp.where(sweeps > bulk_sweeps, off_rel, bulk_off)
    status = _status_word(off_rel, sweeps, nonfinite, tol=tol,
                          max_sweeps=max_sweeps)

    a_work = _deblockify(top, bot)
    v_work = _deblockify(vtop, vbot)[:n, :] if accumulate else None
    cols, s, rot = _postprocess(a_work, v_work, n, compute_u=want_cols,
                                full_u=False, dtype=dtype)
    if refine:
        cols, s, rot = _refine_from_work(work, cols, s, rot)
    if precondition:
        u, v = _recombine_precondition(
            cols, rot, m=m, n=n, compute_u=compute_u, compute_v=compute_v,
            full_u=full_u, dtype=dtype, q1=q1, order=order)
        return u, s, v, sweeps, off_rel, status
    u = cols
    if compute_u and full_u and m > n and u is not None:
        u = _complete_orthonormal(u, n, dtype)
    return u, s, rot, sweeps, off_rel, status


_svd_resident = partial(jax.jit, static_argnames=_RESIDENT_STATIC)(
    _svd_resident_impl)
# Input-donating twin, mirroring _svd_block_rotation_donated.
_svd_resident_donated = partial(
    jax.jit, static_argnames=_RESIDENT_STATIC,
    donate_argnums=(0,))(_svd_resident_impl)


_RESIDENT_BATCHED_STATIC = (
    "n", "compute_u", "compute_v", "nblocks", "n_pad", "tol", "max_sweeps",
    "r_rounds", "precondition", "polish", "apply_x3", "interpret",
    "stall_detection", "refine", "chaos_nan_sweep")


def _svd_resident_batched_impl(a, *, n, compute_u, compute_v, nblocks,
                               n_pad, tol, max_sweeps, r_rounds,
                               precondition, polish, apply_x3=False,
                               interpret=False, stall_detection=True,
                               refine=False, chaos_nan_sweep=None):
    """Batched resident solve: `_svd_block_rotation_batched_impl` with
    the bulk swapped for `pallas_resident.iterate_resident_batched` —
    per-member Gram carries (one matrix's couplings never enter a
    neighbor's factors), the same per-member freezing/health words, the
    same kernel polish continuing the per-member counters."""
    batch, m = a.shape[0], a.shape[1]
    dtype = a.dtype
    if precondition:
        q1, _, order, work = jax.vmap(_precondition_qr)(a)
        accumulate = compute_u
        want_cols = compute_v
    else:
        q1 = order = None
        work = a
        accumulate = compute_v
        want_cols = compute_u

    top, bot = map(_stack_members,
                   _blockify_batched(work, n_pad, nblocks))
    if accumulate:
        eye = jnp.broadcast_to(jnp.eye(n_pad, dtype=dtype),
                               (batch, n_pad, n_pad))
        vtop, vbot = map(_stack_members,
                         _blockify_batched(eye, n_pad, nblocks))
    else:
        vtop = vbot = None

    (top, bot, vtop, vbot, bulk_off, bulk_sweeps, bulk_msweeps,
     bulk_nf) = _resident.iterate_resident_batched(
        top, bot, vtop, vbot, batch=batch, r_rounds=r_rounds,
        abs_tol=_BLOCK_BULK_TOL_FACTOR * _abs_phase_tol(dtype),
        max_sweeps=max_sweeps, interpret=interpret, apply_x3=apply_x3,
        stall_detection=stall_detection, chaos_nan_sweep=chaos_nan_sweep)
    top, bot, vtop, vbot, off, msweeps, nonfinite = rounds.iterate_batched(
        top, bot, vtop, vbot, batch=batch, tol=tol, max_sweeps=max_sweeps,
        interpret=interpret, polish=polish,
        stall_detection=stall_detection, start_sweeps=bulk_sweeps,
        msweeps0=bulk_msweeps, nonfinite0=bulk_nf,
        chaos_nan_sweep=chaos_nan_sweep)
    # Members whose polish never swept (total budget exhausted in bulk)
    # report the bulk statistic, cf. the single-solve carry handling.
    off = jnp.where(msweeps > bulk_msweeps, off, bulk_off)
    status = _status_word(off, msweeps, nonfinite, tol=tol,
                          max_sweeps=max_sweeps)

    a_work = _deblockify_batched(top, bot, batch)
    v_work = (_deblockify_batched(vtop, vbot, batch)[:, :n, :]
              if accumulate else None)

    def post_one(aw, vw, wk):
        cols, s, rot = _postprocess(aw, vw, n, compute_u=want_cols,
                                    full_u=False, dtype=dtype)
        if refine:
            cols, s, rot = _refine_from_work(wk, cols, s, rot)
        return cols, s, rot

    cols, s, rot = jax.vmap(post_one)(a_work, v_work, work)
    if precondition:
        u, v = jax.vmap(lambda c, r, qq, oo: _recombine_precondition(
            c, r, m=m, n=n, compute_u=compute_u, compute_v=compute_v,
            full_u=False, dtype=dtype, q1=qq, order=oo))(cols, rot, q1,
                                                         order)
        return u, s, v, msweeps, off, status
    return cols, s, rot, msweeps, off, status


_svd_resident_batched = partial(
    jax.jit, static_argnames=_RESIDENT_BATCHED_STATIC)(
    _svd_resident_batched_impl)


def _plan_entry(a, config: SVDConfig, *, compute_u: bool = True,
                compute_v: bool = True, full_matrices: bool = False):
    """Resolve the fused jitted entry point a (input, config) pair
    dispatches to: ``(entry_name, jit_fn, prepared_input, kwargs)`` with
    ``entry_name`` in ``("pallas", "padded")`` and
    ``jit_fn(prepared_input, **kwargs)`` being exactly the call `svd()`
    makes. This is the ONE place the jit-call contract is built — shared
    with `svd_jacobi_tpu.analysis` (entries.py), whose jaxpr/HLO passes
    must probe the very programs production dispatches, not hand-rebuilt
    approximations that drift. Raises the same option-validation errors as
    `svd()`; requires m >= n (`svd()` transposes wide inputs first).
    """
    m, n = a.shape
    b, k = _plan(n, 1, config, m=m, dtype=a.dtype)
    n_pad = 2 * k * b
    tol, gram_dtype_name, method, criterion = _resolve_options(
        a, config, compute_uv=compute_u)
    if config.precondition not in ("auto", "on", "off", "double"):
        raise ValueError(f"unknown precondition mode: {config.precondition!r}")

    if method in ("block_rotation", "resident"):
        if b % 2:
            # The polish phase's self kernel splits blocks in half.
            b += 1
            k = max(1, -(-n // (2 * b)))
            n_pad = 2 * k * b
        if config.precondition == "double":
            raise ValueError(
                "precondition='double' is a pallas-lane fused mode; the "
                f"{method} lane supports 'auto'/'on'/'off'")
        if config.mixed_bulk or config.bulk_bf16:
            raise ValueError(
                "mixed_bulk/bulk_bf16 are pallas-lane bulk regimes; the "
                f"{method} lane runs its own eigh-accumulated bulk "
                "(its panel matmuls honor mixed_store instead)")
        precondition = (_tuned(n, m, a.dtype).precondition == "on"
                        if config.precondition == "auto"
                        else config.precondition == "on")
        # The mixed-store gate composes with the blocked-rotation lanes
        # through their bulk-phase panel GEMMs: a bf16 storage verdict
        # (table row or explicit) runs them as bf16x3 split products
        # (~eps_bf16^2 error, absorbed by the abs-phase contract — the
        # f32 polish re-converges from the applied state).
        mixed_store = _resolve_mixed_store(config, n, m, a.dtype)
        refine = (config.sigma_refine if config.sigma_refine is not None
                  else (compute_u or compute_v))
        kwargs = dict(
            n=n, compute_u=compute_u, compute_v=compute_v,
            full_u=full_matrices, nblocks=2 * k, n_pad=n_pad, tol=tol,
            max_sweeps=int(config.max_sweeps),
            precondition=bool(precondition),
            polish=bool(config.kernel_polish),
            apply_x3=mixed_store != "f32",
            interpret=not pb.supported(),
            stall_detection=bool(config.stall_detection),
            refine=bool(refine), telemetry=bool(metrics.enabled()),
            chaos_nan_sweep=_chaos.consume_nan_sweep())
        if method == "resident":
            kwargs["r_rounds"] = _resolve_rounds_resident(
                config, n, m, a.dtype, 2 * k - 1)
            solve = (_svd_resident_donated if config.donate_input
                     else _svd_resident)
            return "resident", solve, a, kwargs
        solve = (_svd_block_rotation_donated if config.donate_input
                 else _svd_block_rotation)
        return "block_rotation", solve, a, kwargs

    if method == "pallas":
        if b % 2:
            # The self kernel splits blocks in half: b must be even.
            b += 1
            k = max(1, -(-n // (2 * b)))
            n_pad = 2 * k * b
        # Auto resolves through the tuning table ("double" is never a
        # table value — dgejsv's second QR measured not worthwhile on
        # random input, PROFILE.md — so auto picks between on/off).
        precondition = (_tuned(n, m, a.dtype).precondition
                        if config.precondition == "auto"
                        else config.precondition)
        bulk_bf16 = (config.bulk_bf16 if config.bulk_bf16 is not None
                     else False)
        # The north-star mixed regime (SVDConfig.mixed_bulk): the bf16x3
        # split is an f32 construction, so explicit True on another dtype
        # is rejected. Auto resolves to OFF: measured on v5e the fused
        # apply kernel is HBM-traffic-bound (f32-HIGHEST 2.09 ms vs bf16x3
        # 1.95 ms per round at 8192^2 — PROFILE.md), so the cheaper bulk
        # arithmetic cannot pay for the bulk+polish sweep overhead
        # (2048^2: 0.234 vs 0.233 s; 4096^2: 0.96 vs 0.87; 8192^2: 6.3 vs
        # 5.7). The flag remains for compute-bound parts (larger b,
        # future chips with wider HBM).
        if config.mixed_bulk and a.dtype != jnp.float32:
            raise ValueError(
                "mixed_bulk (bf16x3 bulk sweeps + f32 polish) requires a "
                f"float32 input, got {a.dtype}")
        mixed = bool(config.mixed_bulk)
        if mixed and bulk_bf16:
            raise ValueError(
                "bulk_bf16 (bf16 Gram panels inside the f32 loop) and "
                "mixed_bulk (bf16x3 bulk sweeps + f32 polish) are mutually "
                "exclusive bulk strategies")
        # auto resolves through the tuning table; the shipped verdict is
        # "f32" (PROFILE.md item 17, measured at 8192^2 on v5e: the
        # byte-halved regimes make the bulk monotonically faster, 4.19 ->
        # 3.51 -> 2.76 s, but every byte saved costs polish sweeps 4 ->
        # 6 -> 8, so f32 storage + x3 applies stays the best END-TO-END
        # mixed mode, 6.27 vs 6.47 vs 6.66 s). The bf16 regimes remain
        # selectable — per table row, for chips whose polish-phase cost
        # structure differs, or explicitly.
        mixed_store = _resolve_mixed_store(config, n, m, a.dtype)
        refine = (config.sigma_refine if config.sigma_refine is not None
                  else (compute_u or compute_v))
        solve = _svd_pallas_donated if config.donate_input else _svd_pallas
        kwargs = dict(
            n=n, compute_u=compute_u, compute_v=compute_v,
            full_u=full_matrices, nblocks=2 * k, n_pad=n_pad, tol=tol,
            max_sweeps=int(config.max_sweeps), precondition=precondition,
            polish=bool(config.kernel_polish), bulk_bf16=bool(bulk_bf16),
            mixed=bool(mixed), mixed_store=mixed_store,
            interpret=not pb.supported(),
            stall_detection=bool(config.stall_detection),
            refine=bool(refine), telemetry=bool(metrics.enabled()),
            chaos_nan_sweep=_chaos.consume_nan_sweep())
        return "pallas", solve, a, kwargs

    if config.precondition in ("on", "double") or config.mixed_bulk:
        # Pallas-only modes explicitly requested on an XLA block-solver
        # path (f64 input, tiny n, or explicit pair_solver): raise instead
        # of silently ignoring them — mirroring the mesh solver's
        # rejection of unsupported modes (parallel/sharded.py).
        bad = ("mixed_bulk=True" if config.mixed_bulk
               else f"precondition={config.precondition!r}")
        raise ValueError(
            f"{bad} requires the Pallas kernel path "
            f"(pair_solver='pallas'/'auto'); this solve resolved to "
            f"pair_solver={method!r}")
    a_pad = jnp.pad(a, ((0, 0), (0, n_pad - n))) if n_pad != n else a
    kwargs = dict(
        n=n, compute_u=compute_u, compute_v=compute_v,
        full_u=full_matrices, nblocks=2 * k, tol=tol,
        max_sweeps=int(config.max_sweeps), precision=config.matmul_precision,
        gram_dtype_name=gram_dtype_name, method=method, criterion=criterion,
        stall_detection=bool(config.stall_detection),
        telemetry=bool(metrics.enabled()),
        chaos_nan_sweep=_chaos.consume_nan_sweep())
    return "padded", _svd_padded, a_pad, kwargs


def _plan_entry_batched(a, config: SVDConfig, *, compute_u: bool = True,
                        compute_v: bool = True):
    """Batched twin of `_plan_entry` for a (B, m, n) same-shape stack:
    ``(entry_name, jit_fn, prepared_input, kwargs)`` with ``entry_name``
    in ``("pallas_batched", "padded_batched")``. Shared with
    `svd_jacobi_tpu.analysis` so the batched lane's contract checks probe
    exactly what `svd_batched` dispatches. Requires m >= n (the public
    entry transposes wide stacks first)."""
    bsz, m, n = a.shape
    b, k = _plan(n, 1, config, m=m, dtype=a.dtype)
    n_pad = 2 * k * b
    tol, gram_dtype_name, method, criterion = _resolve_options(
        a[0], config, compute_uv=compute_u)
    if config.precondition not in ("auto", "on", "off", "double"):
        raise ValueError(f"unknown precondition mode: {config.precondition!r}")
    if config.donate_input:
        raise ValueError("donate_input is not supported on the batched "
                         "entry points (the stacked working set aliases "
                         "no single member's buffer)")
    if method in _KERNEL_METHODS:
        if b % 2:
            b += 1
            k = max(1, -(-n // (2 * b)))
            n_pad = 2 * k * b
        if config.precondition == "double":
            raise ValueError("precondition='double' is a fused single-"
                             "solve mode; the batched lane supports "
                             "'auto'/'on'/'off'")
        if config.mixed_bulk or config.bulk_bf16:
            raise ValueError("mixed_bulk/bulk_bf16 are fused single-solve "
                             "bulk regimes; the batched lane runs plain "
                             "f32 kernel sweeps")
        precondition = (_tuned(n, m, a.dtype).precondition == "on"
                        if config.precondition == "auto"
                        else config.precondition == "on")
        refine = (config.sigma_refine if config.sigma_refine is not None
                  else (compute_u or compute_v))
        kwargs = dict(
            n=n, compute_u=compute_u, compute_v=compute_v, nblocks=2 * k,
            n_pad=n_pad, tol=tol, max_sweeps=int(config.max_sweeps),
            precondition=bool(precondition),
            polish=bool(config.kernel_polish),
            interpret=not pb.supported(),
            stall_detection=bool(config.stall_detection),
            refine=bool(refine),
            chaos_nan_sweep=_chaos.consume_nan_sweep())
        if method == "block_rotation":
            kwargs["apply_x3"] = (
                _resolve_mixed_store(config, n, m, a.dtype) != "f32")
            return ("block_rotation_batched", _svd_block_rotation_batched,
                    a, kwargs)
        if method == "resident":
            kwargs["apply_x3"] = (
                _resolve_mixed_store(config, n, m, a.dtype) != "f32")
            kwargs["r_rounds"] = _resolve_rounds_resident(
                config, n, m, a.dtype, 2 * k - 1)
            return "resident_batched", _svd_resident_batched, a, kwargs
        return "pallas_batched", _svd_pallas_batched, a, kwargs
    if config.precondition in ("on", "double") or config.mixed_bulk:
        bad = ("mixed_bulk=True" if config.mixed_bulk
               else f"precondition={config.precondition!r}")
        raise ValueError(
            f"{bad} requires the Pallas kernel path "
            f"(pair_solver='pallas'/'auto'); this solve resolved to "
            f"pair_solver={method!r}")
    a_pad = (jnp.pad(a, ((0, 0), (0, 0), (0, n_pad - n)))
             if n_pad != n else a)
    kwargs = dict(
        n=n, compute_u=compute_u, compute_v=compute_v, full_u=False,
        nblocks=2 * k, tol=tol, max_sweeps=int(config.max_sweeps),
        precision=config.matmul_precision,
        gram_dtype_name=gram_dtype_name, method=method, criterion=criterion,
        stall_detection=bool(config.stall_detection), telemetry=False,
        chaos_nan_sweep=_chaos.consume_nan_sweep())
    return "padded_batched", _svd_padded_batched, a_pad, kwargs


def svd_batched(
    a,
    *,
    compute_u: bool = True,
    compute_v: bool = True,
    config: SVDConfig | None = None,
) -> SVDResult:
    """Batched SVD: B same-shaped matrices solved as ONE fused dispatch.

    ``a`` is (B, m, n); returns an `SVDResult` whose fields carry a
    leading batch axis — ``u (B, m, min(m,n))``, ``s (B, min(m,n))``,
    ``v (B, n, min(m,n))`` — plus PER-MEMBER ``sweeps``/``off_rel``/
    ``status`` vectors (decode member i with
    ``SolveStatus(int(r.status[i]))``; one poisoned member reports
    NONFINITE while its neighbors stay OK).

    Why not a loop of `svd` calls: the rotation kernel is latency-bound
    (PROFILE.md item 1 — ~constant µs/step regardless of panel count), so
    B small matrices stacked along the pair axis cost close to ONE solve
    — the cuSOLVER `gesvdjBatched` design point, and the unit of work the
    serving layer's request coalescing dispatches
    (`serve.SVDService` with ``max_batch > 1``). On the kernel path the
    stack rides the block-diagonal tournament of
    `ops.rounds.sweep(batch=B)`; XLA-block-solver configs (f64, tiny n)
    run the vmapped `_svd_padded` twin instead — same per-member
    semantics, minus the shared latency chain.

    Fused single-solve-only modes (mixed_bulk, bulk_bf16,
    precondition="double", donate_input, full_matrices) are rejected or
    unavailable. The loop exits when every member converged or stopped;
    members that finish early ride the remaining sweeps unchanged in
    status (their extra rotations are near-identity).
    """
    if config is None:
        config = SVDConfig()
    a = jnp.asarray(a)
    if a.ndim != 3:
        raise ValueError(f"expected a (B, m, n) matrix stack, got shape "
                         f"{a.shape}")
    if a.shape[0] < 1:
        raise ValueError("empty batch")
    _, m, n = a.shape
    if m < n:
        r = svd_batched(a.transpose(0, 2, 1), compute_u=compute_v,
                        compute_v=compute_u, config=config)
        return SVDResult(u=r.v, s=r.s, v=r.u, sweeps=r.sweeps,
                         off_rel=r.off_rel, status=r.status)
    _, solve, a_in, kwargs = _plan_entry_batched(
        a, config, compute_u=compute_u, compute_v=compute_v)
    run = lambda x: solve(x, **kwargs)
    if _grad.resolve_rule_mode(config) != "off":
        # No gradient rule on the stacked fused lane (its block-diagonal
        # tournament shares one latency chain across members — a
        # per-member F-matrix rule does not map onto it); fail loudly
        # with the supported spelling instead of the while_loop error.
        run = _grad.uncovered(
            run,
            "svd_batched has no gradient rule (the coalesced fused "
            "lane); differentiate jax.vmap(solver.svd) over the stack "
            "instead — vmap composes with svd's custom VJP/JVP rules")
    u, s, v, sweeps, off_rel, status = run(a_in)
    return SVDResult(u=u, s=s, v=v, sweeps=sweeps, off_rel=off_rel,
                     status=status)


def svd(
    a,
    *,
    compute_u: bool = True,
    compute_v: bool = True,
    full_matrices: bool = False,
    config: SVDConfig | None = None,
    v0=None,
) -> SVDResult:
    """One-sided block-Jacobi SVD: ``a = u @ diag(s) @ v.T``.

    Args:
      a: (m, n) real matrix (any m/n; wide matrices are handled by solving
        the transpose and swapping factors).
      compute_u / compute_v: LAPACK-style job options — see lapack.gesvd for
        the SVD_OPTIONS surface matching lib/JacobiMethods.cuh:25-29.
      full_matrices: return U as (m, m) instead of economy (m, min(m, n)).
      config: solver configuration (block size, tolerance, sweeps, dtypes).
      v0: optional (n, n) ORTHONORMAL warm-start right factor (a prior
        solve's ``v`` of a nearby matrix — see `svd_update`): the solve
        runs on ``A @ v0``, which enters near-diagonal, and the returned
        ``v`` composes ``v0`` back in exactly. Requires m >= n (wide
        warm starts go through `svd_update`, which transposes).

    Returns:
      SVDResult(u, s, v, sweeps, off_rel) with s descending.

    Differentiable: the solve carries a custom VJP/JVP rule
    (`svd_jacobi_tpu.grad`, routed by ``config.grad_rule``), so
    ``jax.grad``/``jax.jvp`` through this function use the safeguarded
    F-matrix SVD gradient on OUR kernels instead of failing in the sweep
    while_loop or falling back to `jnp.linalg.svd`. ``full_matrices=True``
    with m > n raises `grad.NonDifferentiableError` under differentiation
    (the orthonormal completion has no gradient rule); sigma-only solves
    differentiate through the factor-computing twin with the cheap
    no-F-matrix sigma gradient. See README "Differentiable solves".
    """
    if config is None:
        config = SVDConfig()
    a = jnp.asarray(a)
    if a.ndim != 2:
        raise ValueError(f"expected a 2-D matrix, got shape {a.shape}")
    m, n = a.shape
    if v0 is not None:
        v0 = _check_v0(v0, m, n)
        a = _apply_v0_jit(a, v0)
    if m < n:
        r = svd(a.T, compute_u=compute_v, compute_v=compute_u,
                full_matrices=full_matrices, config=config)
        return SVDResult(u=r.v, s=r.s, v=r.u, sweeps=r.sweeps,
                         off_rel=r.off_rel, status=r.status)

    def _make_runner(cu, cv):
        # The differentiable unit: plan + fused solve + (padded-entry)
        # refine, as one pure a -> (u, s, v, sweeps, off_rel, status)
        # function of the tall-oriented input. The grad rules wrap this
        # whole pipeline so the refinement's direct reads of ``a`` stay
        # INSIDE the custom-rule boundary (a half-wrapped pipeline would
        # mix rule gradients with AD-through-refine double counting).
        def _run(x):
            entry, solve, a_in, kwargs = _plan_entry(
                x, config, compute_u=cu, compute_v=cv,
                full_matrices=full_matrices)
            u, s, v, sweeps, off_rel, status = solve(a_in, **kwargs)
            if entry == "padded":
                refine = (config.sigma_refine
                          if config.sigma_refine is not None
                          else (u is not None or v is not None))
                if refine and (u is not None or v is not None):
                    # Parity with the Pallas path and the mesh solver:
                    # the XLA block solvers run on A directly, so the
                    # working matrix IS x (on a warm start, the
                    # pre-rotated A @ v0 — whose sigmas are A's own, v0
                    # being orthonormal).
                    u, s, v = _refine_xla_jit(x, u, s, v, n=n,
                                              with_u=u is not None,
                                              with_v=v is not None,
                                              full_u=bool(full_matrices))
            return u, s, v, sweeps, off_rel, status
        return _run

    mode = _grad.resolve_rule_mode(config)
    if mode == "off":
        run = _make_runner(compute_u, compute_v)
    elif full_matrices and m > n:
        run = _grad.uncovered(
            _make_runner(compute_u, compute_v),
            "svd(full_matrices=True) has no gradient rule: the (m, m) "
            "orthonormal completion of U is not a function of A alone "
            "(its trailing columns are an arbitrary null-space basis). "
            "Differentiate the economy solve (full_matrices=False) "
            "instead — it carries the full custom VJP/JVP rule.")
    else:
        rtol = _resolve_grad_rtol(config, n, m, a.dtype)
        run = _grad.differentiable(
            _make_runner, compute_u=compute_u, compute_v=compute_v,
            mode=mode, rtol=rtol)
    u, s, v, sweeps, off_rel, status = run(a)
    if v0 is not None and v is not None:
        v = _compose_v0_jit(v0, v)
    return SVDResult(u=u, s=s, v=v, sweeps=sweeps, off_rel=off_rel,
                     status=status)


@partial(jax.jit, static_argnames=("n", "with_u", "with_v", "full_u"))
def _refine_xla_jit(a, u, s, v, *, n, with_u, with_v, full_u):
    cols = u[:, :n] if with_u else None
    cols, s, v2 = _refine_from_work(a, cols, s, v if with_v else None)
    if with_u:
        u = u.at[:, :n].set(cols) if full_u and u.shape[1] > n else cols
    if with_v:
        v = v2
    return u, s, v


# ---------------------------------------------------------------------------
# Warm-started solves (ROADMAP "Two-phase lazy-vector serving + streaming
# updates"): seed the Jacobi loop with a prior right factor V0. The working
# matrix enters as B = A @ V0 — near-diagonal when V0 is (close to) A's
# right factor, so the per-round threshold skipping collapses the already-
# orthogonal subspace and the loop converges in 1-2 sweeps instead of the
# 10+ a cold solve pays (PROFILE.md item 4's quadratic-convergence data;
# item 27 measures the warm-vs-cold sweep counts). Factors compose
# EXACTLY: B = U S W^T gives A = B V0^T = U S (V0 W)^T, so V = V0 @ W —
# valid for every solve path on B (preconditioned or not), which is why
# the warm start is two matmuls around the existing entry points instead
# of a new solver mode.


@jax.jit
def _apply_v0_jit(a, v0):
    """The warm-start pre-rotation ``B = A @ V0`` at HIGHEST precision
    (V0 must be orthonormal — the factor composition below is exact only
    then; a prior solve's ``v`` is orthonormal to working precision)."""
    with scope("warm_start"):
        hi = jax.lax.Precision.HIGHEST
        acc = jnp.promote_types(a.dtype, jnp.float32)
        return jnp.matmul(a.astype(acc), v0.astype(acc),
                          precision=hi).astype(a.dtype)


@jax.jit
def _compose_v0_jit(v0, v):
    """The warm-start factor composition ``V = V0 @ W`` (see module
    comment above `_apply_v0_jit`)."""
    with scope("warm_start"):
        hi = jax.lax.Precision.HIGHEST
        acc = jnp.promote_types(v.dtype, jnp.float32)
        return jnp.matmul(v0.astype(acc), v.astype(acc),
                          precision=hi).astype(v.dtype)


def _check_v0(v0, m: int, n: int):
    """Validate a warm-start factor's shape/orientation (values are NOT
    checked — orthonormality is the caller's contract, and verifying it
    would cost the n^3 Gram product the warm start exists to avoid)."""
    v0 = jnp.asarray(v0)
    if v0.ndim != 2 or v0.shape != (n, n):
        raise ValueError(
            f"v0 must be the (n, n) = ({n}, {n}) right factor of a prior "
            f"solve of this problem, got shape {tuple(v0.shape)}")
    if m < n:
        raise ValueError(
            "v0 warm starts require a tall (m >= n) input; for a wide "
            "problem use svd_update(prior, a_new), which handles the "
            "orientation (the transposed problem warm-starts from "
            "prior.u)")
    return v0


def svd_update(
    prior: SVDResult,
    a_new,
    *,
    compute_u: bool = True,
    compute_v: bool = True,
    config: SVDConfig | None = None,
) -> SVDResult:
    """SVD of an UPDATED matrix, warm-started from a prior decomposition
    of a nearby one — the evolving-matrix workload (a user x feature
    matrix taking a rank-r update between solves). ``prior`` is the
    `SVDResult` of the previous solve (its right factor ``v`` — ``u``
    for wide inputs — must have been computed); ``a_new`` is the updated
    matrix of the SAME shape.

    The solve runs `svd(a_new, v0=prior_factor)`: the prior factor
    pre-rotates the input near-diagonal, the existing convergence
    criterion does the rest (correctness never depends on how near —
    a v0 from an unrelated matrix just converges cold-slow), and the
    per-round threshold skipping collapses the untouched subspace, so a
    rank-r-perturbed input converges in 1-2 sweeps instead of 10+
    (measured: PROFILE.md item 27; pinned by the warm-start sweep-count
    regression test)."""
    a_new = jnp.asarray(a_new)
    if a_new.ndim != 2:
        raise ValueError(f"expected a 2-D matrix, got shape {a_new.shape}")
    m, n = a_new.shape
    if m < n:
        r = svd_update(
            SVDResult(u=prior.v, s=prior.s, v=prior.u, sweeps=prior.sweeps,
                      off_rel=prior.off_rel, status=prior.status),
            a_new.T, compute_u=compute_v, compute_v=compute_u,
            config=config)
        return SVDResult(u=r.v, s=r.s, v=r.u, sweeps=r.sweeps,
                         off_rel=r.off_rel, status=r.status)
    if prior.v is None:
        raise ValueError(
            "svd_update needs the prior solve's right factor (prior.v is "
            "None — for a wide prior, its u); re-solve with compute_v=True "
            "or fall back to a cold svd()")
    return svd(a_new, compute_u=compute_u, compute_v=compute_v,
               config=config, v0=prior.v)


# ---------------------------------------------------------------------------
# Truncated top-k and tall-skinny solver lanes (ops/sketch.py): a Halko
# randomized range finder turns the O(n^3) full solve into O(mnl) for
# top-k requests, and a blocked TSQR makes genuinely tall m >> n inputs
# cost one small Jacobi solve on R. Both reuse the existing Jacobi core
# (the (n, l) / (n, n) projected problems dispatch through `svd()`), so
# tolerance/health/refinement semantics are the core's own.


@partial(jax.jit, static_argnames=("chunk",))
def _tsqr_jit(a, *, chunk=None):
    """Blocked TSQR of a tall (m, n) input: (q, r, nonfinite) with the
    factors cast back to the input dtype and the sketch-path health flag
    probed on the SMALL triangle (NaN/Inf input reaches R through every
    chunk's Householder chain)."""
    q, r = _sketch.tsqr(a, chunk=chunk)
    nf = ~jnp.all(jnp.isfinite(r))
    return q.astype(a.dtype), r.astype(a.dtype), nf


@partial(jax.jit, static_argnames=("chunk",))
def _tsqr_batched_jit(a, *, chunk=None):
    """`_tsqr_jit` vmapped over a (B, m, n) stack (the tall serve bucket
    family's coalesced dispatch); per-member (B,) nonfinite flags."""
    def one(x):
        q, r = _sketch.tsqr(x, chunk=chunk)
        return q.astype(x.dtype), r.astype(x.dtype), ~jnp.all(jnp.isfinite(r))

    return jax.vmap(one)(a)


@partial(jax.jit, static_argnames=("l", "power_iters", "chunk", "seed"))
def _sketch_project_jit(a, *, l, power_iters, chunk=None, seed=0):
    """The randomized range-finder stage (`ops.sketch.sketch_project`):
    (q (m, l), bt (n, l) = B^T, nonfinite). All knobs static — the
    serving layer resolves them once per bucket, so the jit key is the
    bucket, never the request."""
    return _sketch.sketch_project(a, l=l, power_iters=power_iters,
                                  chunk=chunk, seed=seed)


@partial(jax.jit, static_argnames=("l", "power_iters", "chunk", "seed"))
def _sketch_project_batched_jit(a, *, l, power_iters, chunk=None, seed=0):
    """`_sketch_project_jit` vmapped over a (B, m, n) stack (the top-k
    serve bucket family's coalesced dispatch)."""
    return jax.vmap(lambda x: _sketch.sketch_project(
        x, l=l, power_iters=power_iters, chunk=chunk, seed=seed))(a)


def _lift_q(q, z):
    """Factor lift through the range basis: U = Q @ Z at HIGHEST (Z is
    the core's small factor — (l, k) after truncation on the top-k lane,
    (n, n) on the tall lane)."""
    with scope("lift"):
        hi = jax.lax.Precision.HIGHEST
        acc = jnp.promote_types(q.dtype, jnp.float32)
        return jnp.matmul(q.astype(acc), z.astype(acc),
                          precision=hi).astype(q.dtype)


_lift_q_jit = jax.jit(_lift_q)
_lift_q_batched_jit = jax.jit(jax.vmap(_lift_q))


def _combine_sketch_status(nonfinite, status):
    """Sketch-stage health folded into the core's status word: a poisoned
    sketch reads NONFINITE whatever the small solve decoded (the core saw
    only the projection, which deflation can launder)."""
    return jnp.where(jnp.asarray(nonfinite),
                     jnp.int32(int(SolveStatus.NONFINITE)),
                     status).astype(jnp.int32)


def _resolve_sketch(config: SVDConfig, n: int, m: int, dtype,
                    k: Optional[int] = None):
    """(oversample, power_iters, tsqr_chunk) for one problem: explicit
    config values win; None resolves through the active tuning table
    (`tune.tables.resolve` with the k-class axis)."""
    if (config.oversample is not None and config.power_iters is not None
            and config.tsqr_chunk is not None):
        t = None
    else:
        from .tune import tables as _tables
        t = _tables.resolve(n, m=m, dtype=jnp.dtype(dtype).name, k=k)
    p = config.oversample if config.oversample is not None else t.oversample
    q = (config.power_iters if config.power_iters is not None
         else t.power_iters)
    chunk = (config.tsqr_chunk if config.tsqr_chunk is not None
             else (t.tsqr_chunk if t is not None else None))
    if p < 1:
        raise ValueError(f"oversample must be >= 1, got {p}")
    if q < 0:
        raise ValueError(f"power_iters must be >= 0, got {q}")
    if chunk is not None and chunk < 1:
        raise ValueError(f"tsqr_chunk must be None or >= 1, got {chunk}")
    return int(p), int(q), (None if chunk is None else int(chunk))


def svd_topk(
    a,
    k: int,
    *,
    compute_u: bool = True,
    compute_v: bool = True,
    config: SVDConfig | None = None,
) -> SVDResult:
    """Truncated top-k SVD via a randomized range finder: ``a ~= u[:, :k]
    @ diag(s[:k]) @ v[:, :k].T`` with the top-k factors computed in
    O(m n l) (l = k + oversample) instead of the full solve's O(n^3).

    Pipeline (Halko et al.): seeded sketch ``Y = A @ Omega``,
    ``power_iters`` TSQR-stabilized power iterations, blocked-TSQR range
    basis ``Q``, then the EXISTING Jacobi core on the small projected
    matrix ``B^T = A^T Q`` (n x l, dispatched through `svd()` with all
    its tolerance/health/refinement semantics) and the lift
    ``U = Q @ Z``. Deterministic: the sketch seed is fixed, so repeated
    calls agree bit-for-bit and nothing dynamic enters a jit key.

    Accuracy: the returned sigmas match the full solve's top k up to the
    randomized-range-finder tail term — tight for decaying spectra
    (improving with ``power_iters``), exact in VALUE for flat spectra
    (vectors are arbitrary within a tie). ``oversample`` /
    ``power_iters`` default through the tuning tables
    (`SVDConfig.oversample` / `power_iters`; generic 8 / 1). When
    ``k + oversample >= min(m, n)`` the sketch cannot be narrower than
    the problem and the call degrades to the full solve truncated to k
    — same contract, no speedup.

    Returns an `SVDResult` with ``s`` of length k, ``u`` (m, k), ``v``
    (n, k); ``status`` folds a sketch-stage NaN/Inf probe into the
    core's health word (poisoned input reads NONFINITE, never OK).
    """
    if config is None:
        config = SVDConfig()
    a = jnp.asarray(a)
    if a.ndim != 2:
        raise ValueError(f"expected a 2-D matrix, got shape {a.shape}")
    if k < 1:
        raise ValueError(f"top-k rank must be >= 1, got {k}")
    m, n = a.shape
    if m < n:
        r = svd_topk(a.T, k, compute_u=compute_v, compute_v=compute_u,
                     config=config)
        return SVDResult(u=r.v, s=r.s, v=r.u, sweeps=r.sweeps,
                         off_rel=r.off_rel, status=r.status)
    k = min(int(k), n)
    oversample, power_iters, chunk = _resolve_sketch(config, n, m,
                                                     a.dtype, k=k)
    l = min(k + oversample, n)
    if l >= n:
        # The sketch cannot be narrower than the problem: full solve,
        # truncated — correct (more accurate, no speedup).
        r = svd(a, compute_u=compute_u, compute_v=compute_v, config=config)
        return SVDResult(
            u=None if r.u is None else r.u[:, :k], s=r.s[:k],
            v=None if r.v is None else r.v[:, :k],
            sweeps=r.sweeps, off_rel=r.off_rel, status=r.status)
    mode = _grad.resolve_rule_mode(config)
    # The sketch pipeline below carries its OWN truncated-SVD rule (the
    # whole range-finder + projected solve + lift is one differentiable
    # unit), so the inner core solve runs rule-off — nesting svd's rule
    # inside this one would be dead weight in the trace.
    import dataclasses as _dc
    inner_cfg = (config if mode == "off"
                 else _dc.replace(config, grad_rule="off"))

    def _make_runner(cu, cv):
        def _run(x):
            q, bt, nf = _sketch_project_jit(x, l=l,
                                            power_iters=power_iters,
                                            chunk=chunk, seed=0)
            # Core on B^T (n, l): its U is A's right factor W, its V the
            # small rotation Z that lifts to A's left factor through Q.
            inner = svd(bt, compute_u=cv, compute_v=cu, config=inner_cfg)
            uu = _lift_q_jit(q, inner.v[:, :k]) if cu else None
            vv = inner.u[:, :k] if cv else None
            status = (None if inner.status is None
                      else _combine_sketch_status(nf, inner.status))
            return (uu, inner.s[:k], vv, inner.sweeps, inner.off_rel,
                    status)
        return _run

    if mode == "off":
        run = _make_runner(compute_u, compute_v)
    else:
        # Truncated thin-SVD rule: the (m, k)/(n, k) factors take BOTH
        # null-space correction terms (gradient of the idealized top-k
        # factorization — the range-finder tail term is treated as
        # converged, like the forward lane's own accuracy contract).
        rtol = _resolve_grad_rtol(config, n, m, a.dtype)
        run = _grad.differentiable(
            _make_runner, compute_u=compute_u, compute_v=compute_v,
            mode=mode, rtol=rtol)
    u, s, v, sweeps, off_rel, status = run(a)
    return SVDResult(u=u, s=s, v=v, sweeps=sweeps, off_rel=off_rel,
                     status=status)


def svd_tall(
    a,
    *,
    compute_u: bool = True,
    compute_v: bool = True,
    full_matrices: bool = False,
    config: SVDConfig | None = None,
) -> SVDResult:
    """Tall-skinny SVD: route m >= 8n inputs through blocked TSQR and
    run the full Jacobi core on the n x n triangle ``R`` only —
    ``A = Q R = (Q U_R) S V_R^T`` — so a genuinely rectangular solve
    costs one chunked QR (2mn^2) plus one SMALL square solve instead of
    a padded square one.

    Shapes below the tall threshold (m < 8n, including wide inputs whose
    transpose is not tall) and ``full_matrices`` requests (a full (m, m)
    U materializes the square factor the TSQR lane exists to avoid)
    delegate to `svd()` unchanged — `svd_tall` is always correct to call,
    and routes to the TSQR lane exactly when it pays.

    ``status`` folds the TSQR stage's NaN/Inf probe into the core's
    health word, like the top-k lane.
    """
    if config is None:
        config = SVDConfig()
    a = jnp.asarray(a)
    if a.ndim != 2:
        raise ValueError(f"expected a 2-D matrix, got shape {a.shape}")
    m, n = a.shape
    if m < n:
        r = svd_tall(a.T, compute_u=compute_v, compute_v=compute_u,
                     full_matrices=full_matrices, config=config)
        return SVDResult(u=r.v, s=r.s, v=r.u, sweeps=r.sweeps,
                         off_rel=r.off_rel, status=r.status)
    if m < _sketch.TALL_RATIO * n or full_matrices:
        return svd(a, compute_u=compute_u, compute_v=compute_v,
                   full_matrices=full_matrices, config=config)
    _, _, chunk = _resolve_sketch(config, n, m, a.dtype)
    mode = _grad.resolve_rule_mode(config)
    import dataclasses as _dc
    inner_cfg = (config if mode == "off"
                 else _dc.replace(config, grad_rule="off"))

    def _make_runner(cu, cv):
        def _run(x):
            q, r_tri, nf = _tsqr_jit(x, chunk=chunk)
            inner = svd(r_tri, compute_u=cu, compute_v=cv,
                        config=inner_cfg)
            uu = _lift_q_jit(q, inner.u) if cu else None
            status = (None if inner.status is None
                      else _combine_sketch_status(nf, inner.status))
            return (uu, inner.s, inner.v if cv else None, inner.sweeps,
                    inner.off_rel, status)
        return _run

    if mode == "off":
        run = _make_runner(compute_u, compute_v)
    else:
        # The TSQR lane computes the EXACT economy factorization, so it
        # takes the same economy rule as svd() (V is square — only the
        # left null-space correction applies, by shape).
        rtol = _resolve_grad_rtol(config, n, m, a.dtype)
        run = _grad.differentiable(
            _make_runner, compute_u=compute_u, compute_v=compute_v,
            mode=mode, rtol=rtol)
    u, s, v, sweeps, off_rel, status = run(a)
    return SVDResult(u=u, s=s, v=v, sweeps=sweeps, off_rel=off_rel,
                     status=status)


# ---------------------------------------------------------------------------
# Host-controlled sweep stepping — powers checkpoint/resume and per-sweep
# observability (utils/checkpoint.py, utils/profiling.py). The fused `svd`
# entry point runs its whole while_loop inside one jit; this API instead
# exposes one jitted sweep per call so the host can snapshot state at sweep
# boundaries (the reference has no checkpointing at all — SURVEY.md section 5)
# and record per-sweep metrics.


class SweepState(NamedTuple):
    """Device state between sweeps. ``vtop``/``vbot`` are zero-width when V
    is not accumulated."""

    top: jax.Array
    bot: jax.Array
    vtop: jax.Array
    vbot: jax.Array
    off_rel: jax.Array
    sweeps: jax.Array


class PhaseInfo(NamedTuple):
    """Public view of a stepper's CURRENT phase — what the next `step`
    will run. Consumed by `utils.profiling.instrumented_svd` and
    `utils.checkpoint` (which used to reach into `_stage`/`_phase`)."""

    stage: str       # "bulk" | "polish" | "single"
    method: str      # pair solver of the next sweep
    criterion: str   # "rel" | "abs"
    tol: float       # tolerance the next should_continue tests against


# Host-loop stop reason -> SolveStatus code: ONE decode table shared by
# the single and batched steppers (the two host loops must not drift).
_STATUS_BY_REASON = {
    "tol": SolveStatus.OK,
    "max_sweeps": SolveStatus.MAX_SWEEPS,
    "stall": SolveStatus.STAGNATED,
    "nonfinite": SolveStatus.NONFINITE,
    "deadline": SolveStatus.DEADLINE,
    "cancelled": SolveStatus.CANCELLED,
}


class _SweepControlMixin:
    """Host-side machinery shared by `SweepStepper` and
    `BatchedSweepStepper`: the cooperative request control and the hybrid
    stage -> (method, criterion, tol) phase map. Both steppers provide
    ``_stage``/``method``/``criterion``/``tol``/``abs_tol``."""

    _deadline: Optional[float]
    _should_cancel: Optional[Callable[[], bool]]

    def set_control(self, *, deadline: Optional[float] = None,
                    should_cancel: Optional[Callable[[], bool]] = None
                    ) -> None:
        """Install cooperative request control for this solve.

        ``deadline``: absolute `time.monotonic()` second past which
        `should_continue` returns False with stop reason "deadline"
        (-> `SolveStatus.DEADLINE`). The check runs between sweeps, so a
        request stops within one sweep of its deadline — the in-flight
        sweep always completes (no thread kills, device state stays
        consistent, `finish()` returns a loud PARTIAL result).
        ``should_cancel``: zero-arg predicate polled between sweeps
        (e.g. a `threading.Event.is_set` from the serving layer); True
        stops the loop with `SolveStatus.CANCELLED`. Cancellation wins
        over the deadline when both hold at the same boundary (the caller
        asked first). Pass None to clear either hook. On a BATCHED
        stepper the control is batch-level: the serving layer composes
        deadline = min over members and should_cancel = every member
        cancelled.
        """
        self._deadline = None if deadline is None else float(deadline)
        self._should_cancel = should_cancel

    def _control_stop(self) -> Optional[str]:
        """The cooperative-control stop reason, or None to keep going."""
        if self._should_cancel is not None and self._should_cancel():
            return "cancelled"
        if self._deadline is not None and time.monotonic() >= self._deadline:
            return "deadline"
        return None

    def _phase(self):
        """(method, criterion, tol) for the next sweep, per current stage.

        Three methods run as host-visible bulk+polish stages: "hybrid"
        (gram-eigh/abs bulk, qr-svd/rel polish — the XLA lane) and
        "block_rotation"/"resident" (eigh-accumulated block rounds
        against the abs statistic — per-round Gram panels vs the
        VMEM-resident group carry — with the pallas-kernel polish; the
        MXU lanes). All share the abs-criterion stall/tolerance
        machinery for the bulk stage."""
        if self._stage == "bulk":
            if self.method in ("block_rotation", "resident"):
                # The block lanes' measured bulk exit (see
                # `_BLOCK_BULK_TOL_FACTOR`): past ~10x the abs floor the
                # eigh factors' backward error re-perturbs structure.
                return (self.method, "abs",
                        _BLOCK_BULK_TOL_FACTOR * self.abs_tol)
            return "gram-eigh", "abs", self.abs_tol
        if self._stage == "polish":
            if self.method in ("block_rotation", "resident"):
                return "pallas", self.criterion, self.tol
            return "qr-svd", self.criterion, self.tol
        return self.method, self.criterion, self.tol

    def phase_info(self, state=None) -> "PhaseInfo":
        """Public view of the phase the next `step` will run.

        The stage machinery is host-side (it advances in `should_continue`),
        so ``state`` is accepted for call-site symmetry but unused today.
        This is the supported surface for instrumentation/checkpointing
        (`utils.profiling`, `utils.checkpoint`) — `_phase`/`_stage` are
        internals.
        """
        del state
        method, criterion, tol = self._phase()
        return PhaseInfo(stage=self._stage, method=method,
                         criterion=criterion, tol=float(tol))


class SweepStepper(_SweepControlMixin):
    """Run the solve one sweep at a time under host control.

    Usage:
        st = SweepStepper(a, config=cfg)
        state = st.init()
        while st.should_continue(state):
            state = st.step(state)      # one jitted sweep
        result = st.finish(state)

    Matches `svd()` semantics for m >= n (callers transpose wide inputs);
    the hybrid method's phase switch happens on host via `should_continue` /
    `step` consulting the current off-norm.
    """

    def __init__(self, a, *, compute_u: bool = True, compute_v: bool = True,
                 full_matrices: bool = False, config: SVDConfig | None = None,
                 v0=None):
        if config is None:
            config = SVDConfig()
        a = jnp.asarray(a)
        if a.ndim != 2:
            raise ValueError(f"expected a 2-D matrix, got shape {a.shape}")
        m, n = a.shape
        if m < n:
            raise ValueError("SweepStepper requires m >= n; pass a.T and "
                             "swap u/v (as svd() does)")
        # Warm start (see `_apply_v0_jit`): the stepper solves A @ v0 —
        # near-diagonal for a prior factor of a nearby matrix, so the
        # host loop exits after 1-2 sweeps — and `finish` composes v0
        # back into V exactly. The stepper's working input (and therefore
        # `input_digest` — checkpoint validation fingerprints what the
        # sweeps actually run on) is the PRE-ROTATED matrix.
        self._v0 = None
        if v0 is not None:
            self._v0 = _check_v0(v0, m, n)
            a = _apply_v0_jit(a, self._v0)
        self.a, self.m, self.n = a, m, n
        # Retained past a donate_input release (checkpoint fingerprints
        # and resume read the dtype after self.a is gone).
        self.input_dtype = a.dtype
        self.compute_u, self.compute_v = compute_u, compute_v
        self.full_matrices = full_matrices
        self.config = config
        b, k = _plan(n, 1, config, m=m, dtype=a.dtype)
        (self.tol, self.gram_dtype_name, self.method,
         self.criterion) = _resolve_options(a, config, compute_uv=compute_u)
        self._kernel_path = (self.method in _KERNEL_METHODS
                             and self._host_kernel_path())
        if self._kernel_path:
            # Host-stepped sweeps on the SAME compiled kernels as the
            # fused solve (`ops.rounds.sweep` once per step), so
            # checkpointed/instrumented runs no longer downgrade to the
            # ~5x-slower XLA block solvers (VERDICT r3 weak #3).
            if config.mixed_bulk or config.bulk_bf16:
                raise ValueError(
                    "mixed_bulk/bulk_bf16 are fused-solver modes; the "
                    "host-stepped SweepStepper runs plain f32 kernel "
                    "sweeps")
            if config.precondition == "double":
                raise ValueError(
                    "precondition='double' is not supported by the "
                    "host-stepped SweepStepper; use 'on'/'auto'/'off'")
            if b % 2:   # the self kernel splits blocks in half
                b += 1
                k = max(1, -(-n // (2 * b)))
            self._precondition = (
                _tuned(n, m, a.dtype).precondition == "on"
                if config.precondition == "auto"
                else config.precondition == "on")
            self._accumulate = (compute_u if self._precondition
                                else compute_v)
            # The block lanes' bulk GEMMs honor the resolved mixed-store
            # gate exactly like the fused planner (the stepper IS the
            # serving dispatch — fused and served solves of one bucket
            # must run the same arithmetic).
            self._apply_x3 = (
                self.method in ("block_rotation", "resident")
                and _resolve_mixed_store(config, n, m, a.dtype) != "f32")
            self._pc = None          # lazy (q1, order, work) cache
        else:
            # XLA block solvers for the non-kernel methods (and for mesh
            # subclasses, which keep the hybrid stepping).
            (self.tol, self.gram_dtype_name, self.method,
             self.criterion) = _resolve_xla_options(a, config,
                                                    compute_uv=compute_u)
        self.nblocks, self.n_pad = 2 * k, 2 * k * b
        # Residency depth of the resident lane's bulk sweeps — resolved
        # exactly like the fused planner so served and fused solves of
        # one bucket run the same group structure.
        self._r_rounds = (_resolve_rounds_resident(
            config, n, m, a.dtype, self.nblocks - 1)
            if self.method == "resident" else None)
        self.abs_tol = _abs_phase_tol(a.dtype)
        self._prev_off = float("inf")
        # Hybrid and the block lanes run as two host-visible stages:
        # "bulk" (abs statistic) then "polish" (rel criterion) — see
        # `_SweepControlMixin._phase`. Other methods have one stage.
        self._stage = ("bulk" if self.method in ("hybrid", "block_rotation",
                                                 "resident")
                       else "single")
        self._just_switched = False
        self._input_digest = None
        # Why the host loop stopped ("tol" | "stall" | "max_sweeps" |
        # "nonfinite" | "deadline" | "cancelled"); decoded into
        # SVDResult.status by finish().
        self._stop_reason = None
        # Per-sweep (off_rel, stage) pairs, appended from the scalar
        # should_continue ALREADY pulls for its stopping decision — the
        # perf observatory's convergence curve at zero extra device
        # readback (obs.perf.ConvergenceRecorder consumes it).
        self._off_history: List[Tuple[float, str]] = []
        # Request-level cooperative control (set_control): an absolute
        # monotonic deadline and a cancellation predicate, both checked
        # BETWEEN sweeps — never mid-kernel, never via thread kills.
        self._deadline: Optional[float] = None
        self._should_cancel: Optional[Callable[[], bool]] = None

    def _host_kernel_path(self) -> bool:
        """Whether this stepper runs the Pallas kernel sweeps directly
        (mesh subclasses override to keep their sharded XLA stepping)."""
        return True

    def _precond_state(self):
        """(q1, order, work) for the kernel path — computed lazily and
        cached so a resume-from-checkpoint (which never calls init())
        still recombines with the deterministic QR of the same input."""
        if self._pc is None:
            if self._precondition:
                q1, _, order, work = _precondition_qr_jit(self.a)
                self._pc = (q1, order, work)
            else:
                self._pc = (None, None, self.a)
        return self._pc

    def _release_input(self):
        """Free the input buffer after init (SVDConfig.donate_input): the
        stepped solve then holds only the block stacks (+ the QR factors
        when preconditioned) — the difference between fitting and
        RESOURCE_EXHAUSTED at the chip's largest sizes (30208^2 sigma-only
        needs it on 16 GB HBM; PROFILE.md item 19). The caller's array is
        invalidated. Incompatible with checkpoint digest validation
        (`input_digest` raises afterwards) and, on the unpreconditioned
        path, with sigma refinement (no working matrix survives to refine
        against)."""
        if self._kernel_path:
            self._precond_state()   # q1/order/work computed + cached first
            if not self._precondition:
                refine = (self.config.sigma_refine
                          if self.config.sigma_refine is not None
                          else (self.compute_u or self.compute_v))
                if refine:
                    raise ValueError(
                        "donate_input on the unpreconditioned stepper "
                        "cannot refine sigma (the working matrix is "
                        "released); set sigma_refine=False or "
                        "precondition='on'")
                # Zero-width surrogate keeps finish()'s shapes/dtype
                # without holding the m x n buffer.
                self._pc = (None, None,
                            jnp.zeros((self.m, 0), self.a.dtype))
        if isinstance(self.a, jax.Array):
            self.a.delete()
        self.a = None

    def input_digest(self) -> str:
        """Content hash of the input matrix, computed ONCE and cached (a
        full device->host transfer + SHA-256 per snapshot would rival the
        cost of the sweep being checkpointed at large sizes). For a
        non-fully-addressable (multi-host) input, hashes this process's
        OWN shards — each process then validates its per-process snapshot
        against the data it can actually see. Unavailable after
        `donate_input` released the input."""
        if self.a is None:
            raise ValueError("input buffer was released (donate_input); "
                             "no digest available for checkpoint "
                             "validation")
        if self._input_digest is None:
            import hashlib
            h = hashlib.sha256()
            if isinstance(self.a, jax.Array) and not self.a.is_fully_addressable:
                # Deliberate per-shard host read: the digest hashes the
                # bytes this process can see (documented above); not a
                # scalar readback, so _exec.host_scalar does not apply.
                shards = sorted(self.a.addressable_shards,  # graftcheck: ok GRAFT001
                                key=lambda s: str(s.index))
                for sh in shards:
                    h.update(str(sh.index).encode())
                    h.update(np.ascontiguousarray(
                        np.asarray(sh.data)).tobytes())
            else:
                h.update(np.ascontiguousarray(np.asarray(self.a)).tobytes())
            self._input_digest = h.hexdigest()
        return self._input_digest

    def fingerprint_extra(self) -> dict:
        """Extra identity fields for checkpoint validation (mesh shape for
        the sharded subclass)."""
        return {}

    def reshard(self, state: "SweepState") -> "SweepState":
        """Hook for subclasses to re-pin loaded snapshot arrays to their
        sharding; identity on a single device."""
        return state

    def init(self) -> SweepState:
        k = self.nblocks // 2
        if self._kernel_path:
            _, _, work = self._precond_state()
            top, bot = _blockify(work, self.n_pad, self.nblocks)
            accumulate = self._accumulate
        else:
            top, bot = _blockify(self.a, self.n_pad, self.nblocks)
            accumulate = self.compute_v
        if accumulate:
            vtop, vbot = _blockify(jnp.eye(self.n_pad, dtype=self.a.dtype),
                                   self.n_pad, self.nblocks)
        else:
            vtop = vbot = jnp.zeros((k, 0, top.shape[2]), self.a.dtype)
        if self.config.donate_input:
            self._release_input()
        return SweepState(top, bot, vtop, vbot,
                          jnp.float32(jnp.inf), jnp.int32(0))

    def restore_stage(self, stage: str) -> None:
        """Restore the host-side stage machinery to a snapshotted stage
        (the write-side counterpart of `phase_info`, used by
        `utils.checkpoint` on resume). Resets the stall comparator — the
        pre-snapshot off-norm history is gone with the process."""
        if stage not in ("bulk", "polish", "single"):
            raise ValueError(f"unknown solve stage {stage!r}")
        self._stage = stage
        self._prev_off = float("inf")
        self._just_switched = False

    @property
    def convergence_history(self) -> List[Tuple[float, str]]:
        """Per-sweep `(off_rel, stage)` pairs recorded by the host loop's
        own stopping reads — the perf observatory's convergence curve
        (off_rel decay, sweeps-to-tol) with no extra device readback."""
        return list(self._off_history)

    def step(self, state: SweepState) -> SweepState:
        method, criterion, _ = self._phase()
        if self._just_switched:
            # First sweep of the polish stage: the pre-sweep off_rel is on
            # the abs scale — do not use it as the stall comparator.
            self._prev_off = float("inf")
            self._just_switched = False
        else:
            self._prev_off = _host_scalar(state.off_rel)
        return self._run_sweep(state, method, criterion)

    def _run_sweep(self, state: SweepState, method, criterion) -> SweepState:
        """One jitted sweep — the only piece mesh subclasses override."""
        if self._kernel_path:
            if method == "block_rotation":
                # The blocked-rotation bulk stage: fully-solved 2b x 2b
                # subproblems applied as one GEMM per pair; the polish
                # stage falls through to the pallas step below. The skip
                # threshold is the stage tolerance `_phase` reports.
                top, bot, vtop, vbot, off = _sweep_step_block_jit(
                    state.top, state.bot, state.vtop, state.vbot,
                    jnp.float32(_BLOCK_BULK_TOL_FACTOR * self.abs_tol),
                    with_v=self._accumulate, apply_x3=self._apply_x3,
                    interpret=not pb.supported())
                return SweepState(top, bot, vtop, vbot, off,
                                  state.sweeps + 1)
            if method == "resident":
                # The resident bulk stage: grouped rounds against the
                # carried Gram, one VMEM-resident panel pass per group;
                # polish falls through to the pallas step below.
                top, bot, vtop, vbot, off = _sweep_step_resident_jit(
                    state.top, state.bot, state.vtop, state.vbot,
                    jnp.float32(_BLOCK_BULK_TOL_FACTOR * self.abs_tol),
                    r_rounds=self._r_rounds, with_v=self._accumulate,
                    apply_x3=self._apply_x3,
                    interpret=not pb.supported())
                return SweepState(top, bot, vtop, vbot, off,
                                  state.sweeps + 1)
            top, bot, vtop, vbot, off = _sweep_step_pallas_jit(
                state.top, state.bot, state.vtop, state.vbot,
                jnp.float32(self.tol), with_v=self._accumulate,
                polish=bool(self.config.kernel_polish),
                interpret=not pb.supported())
            return SweepState(top, bot, vtop, vbot, off, state.sweeps + 1)
        top, bot, vtop, vbot, off = _sweep_step_jit(
            state.top, state.bot, state.vtop, state.vbot,
            with_v=self.compute_v, precision=self.config.matmul_precision,
            gram_dtype_name=self.gram_dtype_name, method=method,
            criterion=criterion)
        return SweepState(top, bot, vtop, vbot, off, state.sweeps + 1)

    def should_continue(self, state: SweepState) -> bool:
        import math
        # Cooperative control — an expired deadline or a cancelled request
        # stops the loop even before the first sweep (a request popped off
        # a queue already past its deadline must not spend a single sweep).
        ctl = self._control_stop()
        sweeps = int(_host_scalar(state.sweeps))
        if sweeps == 0:
            if ctl is not None:
                self._stop_reason = ctl
                return False
            return True
        off = _host_scalar(state.off_rel)
        # One history point per completed sweep, poisoned values
        # included — a NaN in the curve is exactly what a postmortem
        # wants to see.
        self._off_history.append((float(off), self._stage))
        if not math.isfinite(off):
            # Fail fast on a poisoned statistic; finish() additionally
            # probes the stacks themselves (the deflation mask can hide
            # NaN columns from the masked stat).
            self._stop_reason = "nonfinite"
            return False
        _, criterion, tol = self._phase()
        if ctl is not None:
            # Tolerance wins over an expiring control, matching the
            # max_sweeps decode below: a solve that reached its FINAL
            # tolerance before the control fired is OK, not
            # DEADLINE/CANCELLED. The bulk stage of a hybrid solve is
            # excluded — its abs-phase tolerance is not the requested
            # convergence, so stopping there is still a partial result.
            if self._stage != "bulk" and off <= tol:
                self._stop_reason = "tol"
            else:
                self._stop_reason = ctl
            return False
        if sweeps >= self.config.max_sweeps:
            # Tolerance wins over budget exhaustion — a solve that
            # converged exactly on its last budgeted sweep is OK, matching
            # `_status_word`'s decode order on the fused paths.
            self._stop_reason = "tol" if off <= tol else "max_sweeps"
            return False
        go = bool(_should_continue(
            off, self._prev_off, sweeps,
            tol=tol, max_sweeps=self.config.max_sweeps,
            stall_detection=self.config.stall_detection, criterion=criterion))
        if not go and self._stage == "bulk":
            # End of the bulk stage (abs-converged or stalled) — switch to
            # the polish stage instead of terminating; its off-norm scale
            # is different, so reset the stall comparator.
            self._stage = "polish"
            self._prev_off = float("inf")
            self._just_switched = True
            return True
        if not go:
            self._stop_reason = "tol" if off <= tol else "stall"
        return go

    def _status(self, state: SweepState) -> jax.Array:
        """The host-stepped path's SolveStatus word: one device probe of
        the final stacks (`_nonfinite_probe_jit` — the deflation mask can
        hide NaN columns from off_rel, cf. `_status_word`) combined with
        the recorded host-loop stop reason."""
        import math
        sweeps = int(_host_scalar(state.sweeps))
        # Zero sweeps ran (a deadline/cancel stop before the first sweep):
        # off_rel still holds the init sentinel inf — probe only the
        # stacks, not the sentinel, or an untouched solve reads NONFINITE.
        off_probe = state.off_rel if sweeps > 0 else jnp.float32(0.0)
        nf = bool(_host_scalar(_nonfinite_probe_jit(
            state.top, state.bot, off_probe)))
        if nf:
            code = SolveStatus.NONFINITE
        else:
            reason = self._stop_reason
            if reason is None:
                # finish() before the loop ended (caller stopped early):
                # derive from the visible state.
                off = _host_scalar(state.off_rel)
                if math.isfinite(off) and off <= self.tol:
                    reason = "tol"
                elif sweeps >= self.config.max_sweeps:
                    reason = "max_sweeps"
                else:
                    reason = "stall"
            code = _STATUS_BY_REASON[reason]
        return jnp.int32(int(code))

    def finish(self, state: SweepState) -> SVDResult:
        status = self._status(state)
        if self._kernel_path:
            q1, order, work = self._precond_state()
            refine = (self.config.sigma_refine
                      if self.config.sigma_refine is not None
                      else (self.compute_u or self.compute_v))
            u, s, v = _finish_pallas_jit(
                state.top, state.bot, state.vtop, state.vbot, work,
                q1, order, n=self.n, compute_u=self.compute_u,
                compute_v=self.compute_v, full_u=self.full_matrices,
                precondition=self._precondition, refine=bool(refine))
        else:
            u, s, v = _finish_jit(
                state.top, state.bot, state.vtop, state.vbot, n=self.n,
                compute_u=self.compute_u, compute_v=self.compute_v,
                full_u=self.full_matrices)
            v = v if self.compute_v else None
        if self._v0 is not None and v is not None:
            v = _compose_v0_jit(self._v0, v)
        return SVDResult(u=u, s=s, v=v, sweeps=state.sweeps,
                         off_rel=state.off_rel, status=status)

    def sigma_finish(self, state: SweepState):
        """Sigma-first termination: the two-phase serving layer's cheap
        half. Returns ``(result, payload)`` — ``result`` is a sigma-only
        `SVDResult` (u/v None; sigma read straight off the converged
        stacks via `_sigma_from_state_jit`, skipping the finish stage's
        recombination/refinement matmuls entirely) and ``payload`` is
        everything `finish_from_payload` needs to resume THIS solve to
        full U/V later: the retained column/rotation stacks, the
        preconditioning factors, and the finish statics. ``payload
        ["promotable"]`` is False when the solve accumulated no rotation
        product (compute flags off — the brownout sigma-only rung),
        in which case promotion has nothing to resume from."""
        status = self._status(state)
        if self._kernel_path:
            q1, order, work = self._precond_state()
            path = "kernel"
        else:
            q1 = order = work = None
            path = "xla"
        s = _sigma_from_state_jit(state.top, state.bot, n=self.n)
        refine = (self.config.sigma_refine
                  if self.config.sigma_refine is not None
                  else (self.compute_u or self.compute_v))
        payload = dict(
            path=path, top=state.top, bot=state.bot, vtop=state.vtop,
            vbot=state.vbot, work=work, q1=q1, order=order, n=self.n,
            compute_u=self.compute_u, compute_v=self.compute_v,
            full_u=self.full_matrices,
            precondition=bool(getattr(self, "_precondition", False)),
            refine=bool(refine), v0=self._v0,
            promotable=bool(self.compute_u or self.compute_v),
            status=status, sweeps=state.sweeps, off_rel=state.off_rel)
        return (SVDResult(u=None, s=s, v=None, sweeps=state.sweeps,
                          off_rel=state.off_rel, status=status), payload)

    def aot_entries(self):
        """Every jit entry this stepper's solve loop will dispatch, as
        ``(entry_name, jit_fn, args, kwargs)`` with `jax.ShapeDtypeStruct`
        args — lowerable and compilable AHEAD OF TIME
        (``jit_fn.lower(*args, **kwargs).compile()``) without executing a
        single sweep. This is the serving entry registry's AOT lane
        (`serve.registry`): the enumeration must track the real dispatch
        sites of `init`/`step`/`finish`/`_status` exactly, so the shapes
        are derived with `jax.eval_shape` over the SAME helpers the live
        path runs (they cannot drift from the executed programs), and the
        statics are this stepper's own resolved values. ``entry_name``
        is the `config.RETRACE_BUDGETS` key of each jit."""
        f32s = jax.ShapeDtypeStruct((), jnp.float32)
        a_spec = jax.ShapeDtypeStruct((self.m, self.n), self.input_dtype)
        k = self.nblocks // 2
        entries = []
        if self._kernel_path:
            if self._precondition:
                entries.append(("solver._precondition_qr_jit",
                                _precondition_qr_jit, (a_spec,), {}))
                q1_s, _, order_s, work_s = jax.eval_shape(
                    _precondition_qr, a_spec)
            else:
                q1_s = order_s = None
                work_s = a_spec
            top_s, bot_s = jax.eval_shape(
                lambda w: _blockify(w, self.n_pad, self.nblocks), work_s)
            if self._accumulate:
                vtop_s, vbot_s = jax.eval_shape(
                    lambda: _blockify(
                        jnp.eye(self.n_pad, dtype=self.input_dtype),
                        self.n_pad, self.nblocks))
            else:
                vtop_s = vbot_s = jax.ShapeDtypeStruct(
                    (k, 0, top_s.shape[2]), self.input_dtype)
            if self.method == "block_rotation":
                # The block lane's bulk stage compiles its own sweep
                # entry; the polish stage's pallas entry follows below —
                # two sweep programs per bucket, like the hybrid XLA
                # lane's two phases.
                entries.append((
                    "solver._sweep_step_block_jit", _sweep_step_block_jit,
                    (top_s, bot_s, vtop_s, vbot_s, f32s),
                    dict(with_v=self._accumulate,
                         apply_x3=self._apply_x3,
                         interpret=not pb.supported())))
            if self.method == "resident":
                # The resident lane's bulk stage entry (the polish
                # stage's pallas entry follows below).
                entries.append((
                    "solver._sweep_step_resident_jit",
                    _sweep_step_resident_jit,
                    (top_s, bot_s, vtop_s, vbot_s, f32s),
                    dict(r_rounds=self._r_rounds,
                         with_v=self._accumulate,
                         apply_x3=self._apply_x3,
                         interpret=not pb.supported())))
            entries.append((
                "solver._sweep_step_pallas_jit", _sweep_step_pallas_jit,
                (top_s, bot_s, vtop_s, vbot_s, f32s),
                dict(with_v=self._accumulate,
                     polish=bool(self.config.kernel_polish),
                     interpret=not pb.supported())))
            refine = (self.config.sigma_refine
                      if self.config.sigma_refine is not None
                      else (self.compute_u or self.compute_v))
            entries.append((
                "solver._finish_pallas_jit", _finish_pallas_jit,
                (top_s, bot_s, vtop_s, vbot_s, work_s, q1_s, order_s),
                dict(n=self.n, compute_u=self.compute_u,
                     compute_v=self.compute_v, full_u=self.full_matrices,
                     precondition=self._precondition,
                     refine=bool(refine))))
        else:
            top_s, bot_s = jax.eval_shape(
                lambda: _blockify(
                    jnp.zeros((self.m, self.n), self.input_dtype),
                    self.n_pad, self.nblocks))
            if self.compute_v:
                vtop_s, vbot_s = jax.eval_shape(
                    lambda: _blockify(
                        jnp.eye(self.n_pad, dtype=self.input_dtype),
                        self.n_pad, self.nblocks))
            else:
                vtop_s = vbot_s = jax.ShapeDtypeStruct(
                    (k, 0, top_s.shape[2]), self.input_dtype)
            # The hybrid method compiles one sweep program per STAGE
            # (bulk gram-eigh/abs + polish qr-svd/rel are distinct static
            # keys) — mirror `_phase` over the stages the loop can visit.
            phases = ([("gram-eigh", "abs"), ("qr-svd", self.criterion)]
                      if self.method == "hybrid"
                      else [(self.method, self.criterion)])
            for method, criterion in phases:
                entries.append((
                    "solver._sweep_step_jit", _sweep_step_jit,
                    (top_s, bot_s, vtop_s, vbot_s),
                    dict(with_v=self.compute_v,
                         precision=self.config.matmul_precision,
                         gram_dtype_name=self.gram_dtype_name,
                         method=method, criterion=criterion)))
            entries.append((
                "solver._finish_jit", _finish_jit,
                (top_s, bot_s, vtop_s, vbot_s),
                dict(n=self.n, compute_u=self.compute_u,
                     compute_v=self.compute_v,
                     full_u=self.full_matrices)))
        entries.append(("solver._nonfinite_probe_jit",
                        _nonfinite_probe_jit, (top_s, bot_s, f32s), {}))
        # Two-phase serving's sigma-first extraction: a sigma-phase (or
        # factor-free) dispatch reads sigma off the converged stacks and
        # defers the finish stage, so the serve path requests this entry
        # instead of (or before) the finish jit — same bucket-shaped key.
        entries.append(("solver._sigma_from_state_jit",
                        _sigma_from_state_jit, (top_s, bot_s),
                        dict(n=self.n)))
        return tuple(entries)


@jax.jit
def _nonfinite_probe_jit(top, bot, off_rel):
    """One cheap reduction over the final stacks: the host-stepped paths'
    equivalent of the fused loops' in-graph health word (NaN/Inf anywhere
    in the work poisons the max-of-squares; the off-norm is checked too
    because an all-dead deflation mask can leave it finite)."""
    return jnp.logical_or(~jnp.isfinite(_global_dmax2(top, bot)),
                          ~jnp.isfinite(off_rel))


@partial(jax.jit, static_argnames=("with_v", "precision", "gram_dtype_name",
                                   "method", "criterion"))
def _sweep_step_jit(top, bot, vtop, vbot, *, with_v, precision,
                    gram_dtype_name, method, criterion):
    dmax2 = _global_dmax2(top, bot)
    top, bot, nvt, nvb, off = _sweep(
        top, bot, vtop if with_v else None, vbot if with_v else None,
        precision=precision, gram_dtype=jnp.dtype(gram_dtype_name),
        method=method, criterion=criterion, dmax2=dmax2)
    if with_v:
        vtop, vbot = nvt, nvb
    return top, bot, vtop, vbot, off


@partial(jax.jit, static_argnames=("n", "compute_u", "compute_v", "full_u"))
def _finish_jit(top, bot, vtop, vbot, *, n, compute_u, compute_v, full_u):
    a_work = _deblockify(top, bot)
    v_work = _deblockify(vtop, vbot)[:n, :] if compute_v else None
    u, s, v = _postprocess(a_work, v_work, n, compute_u=compute_u,
                           full_u=full_u, dtype=top.dtype)
    return u, s, v


@partial(jax.jit, static_argnames=("n",))
def _sigma_from_state_jit(top, bot, *, n):
    """Sigma straight off a converged sweep state's column stacks — the
    two-phase serving layer's sigma-first extraction (`serve.SVDService`
    with ``phase="sigma"``): the rotated columns' norms ARE the singular
    values, so sigma is served without the finish stage's factor
    recombination/refinement matmuls (those run later — on the SAME
    retained state — only if the client promotes). Padded columns are
    exactly zero and sort to the back; the [:n] slice drops them.
    Accuracy is the sweep loop's own (~sqrt(m)*eps class); the promoted
    result's sigma additionally gets the finish-stage compensated
    refinement."""
    with scope("postprocess"):
        a_work = _deblockify(top, bot)
        acc = jnp.promote_types(a_work.dtype, jnp.float32)
        s_all = jnp.linalg.norm(a_work.astype(acc), axis=0)
        s = -jnp.sort(-s_all)[:n]
        return s.astype(a_work.dtype)


@partial(jax.jit, static_argnames=("n",))
def _sigma_from_state_batched_jit(top, bot, *, n):
    """`_sigma_from_state_jit` vmapped over member-major (B, k, m, b)
    stacks (the coalesced sigma-phase dispatch; the service reshapes the
    kernel lane's stacked (B*k, m, b) layout to member-major first —
    the same reshape `_nonfinite_probe_batched_jit` takes)."""
    def one(t, b):
        with scope("postprocess"):
            a_work = _deblockify(t, b)
            acc = jnp.promote_types(a_work.dtype, jnp.float32)
            s_all = jnp.linalg.norm(a_work.astype(acc), axis=0)
            return (-jnp.sort(-s_all)[:n]).astype(a_work.dtype)

    return jax.vmap(one)(top, bot)


def finish_from_payload(payload: dict) -> SVDResult:
    """Resume a deferred finish stage (`SweepStepper.sigma_finish` /
    `BatchedSweepStepper.sigma_finish` payloads) to full U/Σ/V — the
    promotion half of two-phase serving. Runs the SAME already-compiled
    finish jits the full-phase dispatch would have (`_finish_pallas_jit`
    / `_finish_jit`, single-form: batched members arrive member-sliced),
    so promotion costs the finish-stage matmuls only — never a sweep,
    never a fresh solve. The terminal status/sweeps/off_rel are the
    retained sweep loop's own; a warm-started payload composes its v0
    back in exactly, like `SweepStepper.finish`."""
    p = payload
    if p["path"] == "kernel":
        u, s, v = _finish_pallas_jit(
            p["top"], p["bot"], p["vtop"], p["vbot"], p["work"], p["q1"],
            p["order"], n=p["n"], compute_u=p["compute_u"],
            compute_v=p["compute_v"], full_u=p["full_u"],
            precondition=p["precondition"], refine=p["refine"])
    else:
        u, s, v = _finish_jit(
            p["top"], p["bot"], p["vtop"], p["vbot"], n=p["n"],
            compute_u=p["compute_u"], compute_v=p["compute_v"],
            full_u=p["full_u"])
        v = v if p["compute_v"] else None
    if p.get("v0") is not None and v is not None:
        v = _compose_v0_jit(p["v0"], v)
    return SVDResult(u=u, s=s, v=v, sweeps=p["sweeps"],
                     off_rel=p["off_rel"], status=p["status"])


@partial(jax.jit, static_argnames=("with_v", "polish", "interpret"))
def _sweep_step_pallas_jit(top, bot, vtop, vbot, rtol, *, with_v, polish,
                           interpret):
    """One kernel-path sweep for the host-stepped API: the same
    `ops.rounds.sweep` the fused solver scans, with the per-sweep dmax2
    deflation scale recomputed here (mirroring `rounds.iterate_phase`)."""
    dmax2 = _global_dmax2(top, bot)
    top, bot, nvt, nvb, off = rounds.sweep(
        top, bot, vtop if with_v else None, vbot if with_v else None,
        dmax2, rtol, interpret=interpret, polish=polish, bf16_gram=False)
    if with_v:
        vtop, vbot = nvt, nvb
    return top, bot, vtop, vbot, off


@partial(jax.jit, static_argnames=("with_v", "apply_x3", "interpret"))
def _sweep_step_block_jit(top, bot, vtop, vbot, rtol, *, with_v, apply_x3,
                          interpret):
    """One blocked-rotation BULK sweep for the host-stepped API
    (`SweepStepper` with pair_solver="block_rotation", stage "bulk"):
    the same `ops.rounds.sweep_block` the fused solver loops, with the
    per-sweep dmax2 deflation scale recomputed here. ``rtol`` is the
    abs-statistic round-skip threshold (the stage's abs tolerance) and
    ``apply_x3`` the resolved mixed-store gate — the stepper resolves it
    exactly as the fused planner does, so fused and served solves of one
    bucket run the same arithmetic; the polish stage runs
    `_sweep_step_pallas_jit` unchanged."""
    dmax2 = _global_dmax2(top, bot)
    top, bot, nvt, nvb, off = rounds.sweep_block(
        top, bot, vtop if with_v else None, vbot if with_v else None,
        dmax2, rtol, interpret=interpret, apply_x3=apply_x3)
    if with_v:
        vtop, vbot = nvt, nvb
    return top, bot, vtop, vbot, off


@partial(jax.jit, static_argnames=("r_rounds", "with_v", "apply_x3",
                                   "interpret"))
def _sweep_step_resident_jit(top, bot, vtop, vbot, rtol, *, r_rounds, with_v,
                             apply_x3, interpret):
    """One VMEM-resident BULK sweep for the host-stepped API
    (`SweepStepper` with pair_solver="resident", stage "bulk"): the same
    `ops.pallas_resident.sweep_resident` the fused solver loops, with the
    per-sweep dmax2 deflation scale recomputed here. ``r_rounds`` is the
    residency depth R (rounds applied per VMEM visit); ``rtol`` /
    ``apply_x3`` as in `_sweep_step_block_jit`. The polish stage runs
    `_sweep_step_pallas_jit` unchanged."""
    dmax2 = _global_dmax2(top, bot)
    top, bot, nvt, nvb, off = _resident.sweep_resident(
        top, bot, vtop if with_v else None, vbot if with_v else None,
        dmax2, rtol, r_rounds=r_rounds, interpret=interpret,
        apply_x3=apply_x3)
    if with_v:
        vtop, vbot = nvt, nvb
    return top, bot, vtop, vbot, off


def _finish_pallas_one(top, bot, vtop, vbot, work, q1, order, *, n,
                       compute_u, compute_v, full_u, precondition, refine):
    """Kernel-path postprocessing + recombination (+ sigma refinement) for
    one member's stacks — identical factor bookkeeping to `_svd_pallas`
    (including the work-matrix-based refinement). Shared by the single
    and batched (vmapped) finish jits."""
    m = q1.shape[0] if precondition else work.shape[0]
    dtype = work.dtype
    accumulate = compute_u if precondition else compute_v
    want_cols = compute_v if precondition else compute_u
    a_work = _deblockify(top, bot)
    v_work = _deblockify(vtop, vbot)[:n, :] if accumulate else None
    cols, s, rot = _postprocess(a_work, v_work, n, compute_u=want_cols,
                                full_u=False, dtype=dtype)
    if refine:
        cols, s, rot = _refine_from_work(work, cols, s, rot)
    if precondition:
        u, v = _recombine_precondition(
            cols, rot, m=m, n=n, compute_u=compute_u, compute_v=compute_v,
            full_u=full_u, dtype=dtype, q1=q1, order=order)
    else:
        u, v = cols, rot
        if compute_u and full_u and m > n and u is not None:
            u = _complete_orthonormal(u, n, dtype)
    return u, s, v


@partial(jax.jit, static_argnames=("n", "compute_u", "compute_v", "full_u",
                                   "precondition", "refine"))
def _finish_pallas_jit(top, bot, vtop, vbot, work, q1, order, *, n,
                       compute_u, compute_v, full_u, precondition, refine):
    return _finish_pallas_one(top, bot, vtop, vbot, work, q1, order, n=n,
                              compute_u=compute_u, compute_v=compute_v,
                              full_u=full_u, precondition=precondition,
                              refine=refine)


# ---------------------------------------------------------------------------
# Batched host-controlled stepping — the serving layer's coalesced-dispatch
# lane (`serve.SVDService` with max_batch > 1): B same-bucket requests stack
# into ONE solve whose sweeps the host steps, so per-request deadlines /
# cancellation stay cooperative (checked between sweeps) while the device
# work amortizes across the batch. Mirrors `SweepStepper`'s API with
# per-MEMBER convergence bookkeeping on the host.


_precondition_qr_batched_jit = jax.jit(jax.vmap(_precondition_qr))


@partial(jax.jit, static_argnames=("batch", "with_v", "polish", "interpret"))
def _sweep_step_pallas_batched_jit(top, bot, vtop, vbot, rtol, *, batch,
                                   with_v, polish, interpret):
    """One kernel-path sweep of a stacked (B*k, m, b) batch: the same
    `ops.rounds.sweep` as the single stepper with the block-diagonal
    batched schedule; per-member (B,) dmax2/off vectors."""
    dmax2 = _global_dmax2(top, bot, batch=batch)
    top, bot, nvt, nvb, off = rounds.sweep(
        top, bot, vtop if with_v else None, vbot if with_v else None,
        dmax2, rtol, interpret=interpret, polish=polish, bf16_gram=False,
        batch=batch)
    if with_v:
        vtop, vbot = nvt, nvb
    return top, bot, vtop, vbot, off


@partial(jax.jit, static_argnames=("batch", "with_v", "apply_x3",
                                   "interpret"))
def _sweep_step_block_batched_jit(top, bot, vtop, vbot, rtol, *, batch,
                                  with_v, apply_x3, interpret):
    """One blocked-rotation bulk sweep of a stacked (B*k, m, b) batch
    (`BatchedSweepStepper` stage "bulk"): `rounds.sweep_block` with the
    block-diagonal batched schedule; per-member (B,) dmax2/off vectors
    on the ABS statistic. ``apply_x3``: the resolved mixed-store gate
    (see `_sweep_step_block_jit`)."""
    dmax2 = _global_dmax2(top, bot, batch=batch)
    top, bot, nvt, nvb, off = rounds.sweep_block(
        top, bot, vtop if with_v else None, vbot if with_v else None,
        dmax2, rtol, interpret=interpret, apply_x3=apply_x3, batch=batch)
    if with_v:
        vtop, vbot = nvt, nvb
    return top, bot, vtop, vbot, off


@partial(jax.jit, static_argnames=("batch", "r_rounds", "with_v", "apply_x3",
                                   "interpret"))
def _sweep_step_resident_batched_jit(top, bot, vtop, vbot, rtol, *, batch,
                                     r_rounds, with_v, apply_x3, interpret):
    """One VMEM-resident bulk sweep of a stacked (B*k, m, b) batch
    (`BatchedSweepStepper` stage "bulk"): `pallas_resident.sweep_resident`
    with the block-diagonal batched schedule; per-member (B,) dmax2/off
    vectors on the ABS statistic. ``r_rounds`` / ``apply_x3``: see
    `_sweep_step_resident_jit`."""
    dmax2 = _global_dmax2(top, bot, batch=batch)
    top, bot, nvt, nvb, off = _resident.sweep_resident(
        top, bot, vtop if with_v else None, vbot if with_v else None,
        dmax2, rtol, r_rounds=r_rounds, interpret=interpret,
        apply_x3=apply_x3, batch=batch)
    if with_v:
        vtop, vbot = nvt, nvb
    return top, bot, vtop, vbot, off


@partial(jax.jit, static_argnames=("with_v", "precision", "gram_dtype_name",
                                   "method", "criterion"))
def _sweep_step_xla_batched_jit(top, bot, vtop, vbot, *, with_v, precision,
                                gram_dtype_name, method, criterion):
    """One XLA-block-solver sweep vmapped over (B, k, m, b) member stacks
    (the f64 / tiny-n serving buckets); per-member (B,) off vector."""
    def one(t, b, vt, vb):
        dmax2 = _global_dmax2(t, b)
        t, b, nvt, nvb, off = _sweep(
            t, b, vt if with_v else None, vb if with_v else None,
            precision=precision, gram_dtype=jnp.dtype(gram_dtype_name),
            method=method, criterion=criterion, dmax2=dmax2)
        if not with_v:
            nvt, nvb = vt, vb
        return t, b, nvt, nvb, off

    return jax.vmap(one)(top, bot, vtop, vbot)


@partial(jax.jit, static_argnames=("batch", "n", "compute_u", "compute_v",
                                   "precondition", "refine"))
def _finish_pallas_batched_jit(top, bot, vtop, vbot, work, q1, order, *,
                               batch, n, compute_u, compute_v, precondition,
                               refine):
    """Kernel-path finish vmapped over the members of a stacked batch —
    the exact single-member bookkeeping (`_finish_pallas_one`) per
    member. full_u is not offered on the batched lane."""
    def seg(x):
        return x.reshape((batch, x.shape[0] // batch) + x.shape[1:])

    def one(t, b, vt, vb, wk, qq, oo):
        return _finish_pallas_one(t, b, vt, vb, wk, qq, oo, n=n,
                                  compute_u=compute_u, compute_v=compute_v,
                                  full_u=False, precondition=precondition,
                                  refine=refine)

    return jax.vmap(one)(seg(top), seg(bot), seg(vtop), seg(vbot), work,
                         q1, order)


@partial(jax.jit, static_argnames=("n", "compute_u", "compute_v"))
def _finish_xla_batched_jit(top, bot, vtop, vbot, *, n, compute_u,
                            compute_v):
    def one(t, b, vt, vb):
        a_work = _deblockify(t, b)
        v_work = _deblockify(vt, vb)[:n, :] if compute_v else None
        return _postprocess(a_work, v_work, n, compute_u=compute_u,
                            full_u=False, dtype=t.dtype)

    return jax.vmap(one)(top, bot, vtop, vbot)


@jax.jit
def _nonfinite_probe_batched_jit(top, bot, off_rel):
    """(B,) per-member nonfinite probe over (B, k, m, b) member stacks —
    the batched twin of `_nonfinite_probe_jit` (a poisoned member's NaNs
    stay inside its own segment, so the probe is per-member exact)."""
    def one(t, b, o):
        return jnp.logical_or(~jnp.isfinite(_global_dmax2(t, b)),
                              ~jnp.isfinite(o))

    return jax.vmap(one)(top, bot, off_rel)


class BatchSweepState(NamedTuple):
    """Device state of a batched host-stepped solve. The stacks are
    (B*k, m, b) member-major on the kernel path and (B, k, m, b) on the
    XLA path; ``off_rel`` is the per-member (B,) coupling after the last
    sweep and ``sweeps`` the scalar count of sweeps run on the stack."""

    top: jax.Array
    bot: jax.Array
    vtop: jax.Array
    vbot: jax.Array
    off_rel: jax.Array
    sweeps: jax.Array


class BatchedSweepStepper(_SweepControlMixin):
    """Run B same-shaped solves as one host-stepped batch.

    Usage matches `SweepStepper` with ``a`` of shape (B, m, n):

        st = BatchedSweepStepper(a, config=cfg)
        state = st.init()
        while st.should_continue(state):
            state = st.step(state)
        result = st.finish(state)     # batched factors + (B,) vectors

    Convergence is tracked PER MEMBER on the host: each sweep's (B,)
    off-norm vector is decoded with the same criterion/stall logic as the
    single stepper, a member that converges / stalls / goes non-finite
    freezes its off/sweeps at that boundary (its blocks keep riding the
    stacked sweeps — near-identity rotations — so neighbors lose
    nothing), and the loop ends when every member is done. `set_control`
    carries the BATCH-level cooperative control the serving layer
    composes for a coalesced dispatch: the effective deadline is the min
    over members (no member may be served past its own promise — the
    whole batch stops within one sweep of the earliest deadline, members
    already converged decode OK, the rest DEADLINE) and cancellation
    fires when every member cancelled (an individual member's cancel is
    the service's finalize-time concern, not the solve's).

    The hybrid XLA method's bulk->polish stage switch is batch-level: the
    polish begins once NO live member's bulk phase wants another sweep
    (early finishers run extra bulk sweeps, which only tighten them).
    """

    def __init__(self, a, *, compute_u: bool = True, compute_v: bool = True,
                 config: SVDConfig | None = None):
        if config is None:
            config = SVDConfig()
        a = jnp.asarray(a)
        if a.ndim != 3:
            raise ValueError(f"expected a (B, m, n) stack, got {a.shape}")
        bsz, m, n = (int(d) for d in a.shape)
        if m < n:
            raise ValueError("BatchedSweepStepper requires m >= n; pass "
                             "the transposed stack and swap u/v (as "
                             "svd_batched() does)")
        if config.donate_input:
            raise ValueError("donate_input is not supported on the "
                             "batched stepper")
        self.a, self.batch, self.m, self.n = a, bsz, m, n
        self.input_dtype = a.dtype
        self.compute_u, self.compute_v = compute_u, compute_v
        self.config = config
        b, k = _plan(n, 1, config, m=m, dtype=a.dtype)
        (self.tol, self.gram_dtype_name, self.method,
         self.criterion) = _resolve_options(a[0], config,
                                            compute_uv=compute_u)
        self._kernel_path = self.method in _KERNEL_METHODS
        if self._kernel_path:
            if config.mixed_bulk or config.bulk_bf16:
                raise ValueError("mixed_bulk/bulk_bf16 are fused-solver "
                                 "modes; the batched stepper runs plain "
                                 "f32 kernel sweeps")
            if config.precondition == "double":
                raise ValueError("precondition='double' is not supported "
                                 "by the batched stepper; use "
                                 "'on'/'auto'/'off'")
            if b % 2:   # the self kernel splits blocks in half
                b += 1
                k = max(1, -(-n // (2 * b)))
            self._precondition = (
                _tuned(n, m, a.dtype).precondition == "on"
                if config.precondition == "auto"
                else config.precondition == "on")
            self._accumulate = (compute_u if self._precondition
                                else compute_v)
            # Resolved mixed-store gate for the block lane's bulk GEMMs
            # (cf. SweepStepper.__init__).
            self._apply_x3 = (
                self.method in ("block_rotation", "resident")
                and _resolve_mixed_store(config, n, m, a.dtype) != "f32")
            self._pc = None
        else:
            (self.tol, self.gram_dtype_name, self.method,
             self.criterion) = _resolve_xla_options(a[0], config,
                                                    compute_uv=compute_u)
        self.nblocks, self.n_pad = 2 * k, 2 * k * b
        self._r_rounds = (
            _resolve_rounds_resident(config, n, m, a.dtype, self.nblocks - 1)
            if self.method == "resident" else None)
        self.abs_tol = _abs_phase_tol(a.dtype)
        self._stage = ("bulk" if self.method in ("hybrid", "block_rotation",
                                                 "resident")
                       else "single")
        self._just_switched = False
        # Per-member host bookkeeping: stop reason (None = live), frozen
        # sweep count and off-norm at the member's stopping boundary.
        # The stack's sweep count is ALSO tracked host-side (_sweeps_host
        # increments per step()) so the per-sweep loop never pays a
        # device->host scalar sync for it — at tiny buckets that sync was
        # a measurable slice of the whole dispatch.
        self._prev_off = np.full(bsz, np.inf)
        self._done: list = [None] * bsz
        self._done_sweeps = np.zeros(bsz, np.int64)
        self._sweeps_host = 0
        self._stop_reason: Optional[str] = None   # batch-level control
        self._deadline: Optional[float] = None
        self._should_cancel: Optional[Callable[[], bool]] = None

    # -- state (control + phase machinery: _SweepControlMixin) --------------

    def _precond_state(self):
        if self._pc is None:
            if self._precondition:
                q1, _, order, work = _precondition_qr_batched_jit(self.a)
                self._pc = (q1, order, work)
            else:
                self._pc = (None, None, self.a)
        return self._pc

    def init(self) -> BatchSweepState:
        k = self.nblocks // 2
        if self._kernel_path:
            _, _, work = self._precond_state()
            top, bot = map(_stack_members,
                           _blockify_batched(work, self.n_pad,
                                             self.nblocks))
            if self._accumulate:
                eye = jnp.broadcast_to(
                    jnp.eye(self.n_pad, dtype=self.input_dtype),
                    (self.batch, self.n_pad, self.n_pad))
                vtop, vbot = map(_stack_members,
                                 _blockify_batched(eye, self.n_pad,
                                                   self.nblocks))
            else:
                vtop = vbot = jnp.zeros((self.batch * k, 0, top.shape[2]),
                                        self.input_dtype)
        else:
            top, bot = _blockify_batched(self.a, self.n_pad, self.nblocks)
            if self.compute_v:
                eye = jnp.broadcast_to(
                    jnp.eye(self.n_pad, dtype=self.input_dtype),
                    (self.batch, self.n_pad, self.n_pad))
                vtop, vbot = _blockify_batched(eye, self.n_pad,
                                               self.nblocks)
            else:
                vtop = vbot = jnp.zeros((self.batch, k, 0, top.shape[3]),
                                        self.input_dtype)
        return BatchSweepState(top, bot, vtop, vbot,
                               jnp.full((self.batch,), jnp.inf,
                                        jnp.float32), jnp.int32(0))

    def step(self, state: BatchSweepState) -> BatchSweepState:
        method, criterion, _ = self._phase()
        if self._just_switched:
            self._prev_off = np.full(self.batch, np.inf)
            self._just_switched = False
        else:
            off = np.asarray(state.off_rel, np.float64)
            live = np.array([r is None for r in self._done])
            self._prev_off = np.where(live, off, self._prev_off)
        if self._kernel_path and method == "block_rotation":
            top, bot, vtop, vbot, off = _sweep_step_block_batched_jit(
                state.top, state.bot, state.vtop, state.vbot,
                jnp.float32(_BLOCK_BULK_TOL_FACTOR * self.abs_tol),
                batch=self.batch, with_v=self._accumulate,
                apply_x3=self._apply_x3, interpret=not pb.supported())
        elif self._kernel_path and method == "resident":
            top, bot, vtop, vbot, off = _sweep_step_resident_batched_jit(
                state.top, state.bot, state.vtop, state.vbot,
                jnp.float32(_BLOCK_BULK_TOL_FACTOR * self.abs_tol),
                batch=self.batch, r_rounds=self._r_rounds,
                with_v=self._accumulate, apply_x3=self._apply_x3,
                interpret=not pb.supported())
        elif self._kernel_path:
            top, bot, vtop, vbot, off = _sweep_step_pallas_batched_jit(
                state.top, state.bot, state.vtop, state.vbot,
                jnp.float32(self.tol), batch=self.batch,
                with_v=self._accumulate,
                polish=bool(self.config.kernel_polish),
                interpret=not pb.supported())
        else:
            top, bot, vtop, vbot, off = _sweep_step_xla_batched_jit(
                state.top, state.bot, state.vtop, state.vbot,
                with_v=self.compute_v,
                precision=self.config.matmul_precision,
                gram_dtype_name=self.gram_dtype_name, method=method,
                criterion=criterion)
        self._sweeps_host += 1
        return BatchSweepState(top, bot, vtop, vbot, off, state.sweeps + 1)

    def _mark(self, i: int, reason: str, sweeps: int) -> None:
        self._done[i] = reason
        self._done_sweeps[i] = sweeps

    def should_continue(self, state: BatchSweepState) -> bool:
        import math
        ctl = self._control_stop()
        sweeps = self._sweeps_host
        if sweeps == 0:
            if ctl is not None:
                self._stop_reason = ctl
                return False
            return True
        off = np.asarray(state.off_rel, np.float64)
        _, criterion, tol = self._phase()
        bulk_pending = False
        for i in range(self.batch):
            if self._done[i] is not None:
                continue
            o = float(off[i])
            if not math.isfinite(o):
                self._mark(i, "nonfinite", sweeps)
                continue
            if self._stage != "bulk" and o <= tol:
                # Tolerance wins over budget/stall/control, matching the
                # single stepper's decode order.
                self._mark(i, "tol", sweeps)
                continue
            if sweeps >= self.config.max_sweeps:
                # Same decode as the single stepper's budget-exhaustion
                # branch: the PHASE tolerance wins even mid-bulk.
                self._mark(i, "tol" if o <= tol else "max_sweeps", sweeps)
                continue
            go = bool(_should_continue(
                o, float(self._prev_off[i]), sweeps, tol=tol,
                max_sweeps=self.config.max_sweeps,
                stall_detection=self.config.stall_detection,
                criterion=criterion))
            if self._stage == "bulk":
                # Bulk members are never marked done by convergence/stall
                # — the whole batch switches to polish once no live
                # member's bulk wants another sweep.
                bulk_pending = bulk_pending or go
                continue
            if not go:
                self._mark(i, "stall", sweeps)
        if ctl is not None:
            self._stop_reason = ctl
            return False
        live = any(r is None for r in self._done)
        if self._stage == "bulk" and live and not bulk_pending:
            self._stage = "polish"
            self._prev_off = np.full(self.batch, np.inf)
            self._just_switched = True
        return live

    def _member_statuses(self, state: BatchSweepState) -> np.ndarray:
        import math
        sweeps = self._sweeps_host
        off_probe = (state.off_rel if sweeps > 0
                     else jnp.zeros((self.batch,), jnp.float32))
        top, bot = state.top, state.bot
        if self._kernel_path:
            kp = top.shape[0] // self.batch
            top = top.reshape((self.batch, kp) + top.shape[1:])
            bot = bot.reshape((self.batch, kp) + bot.shape[1:])
        nf = np.asarray(_nonfinite_probe_batched_jit(top, bot, off_probe))
        off = np.asarray(state.off_rel, np.float64)
        codes = np.zeros(self.batch, np.int32)
        for i in range(self.batch):
            if bool(nf[i]):
                codes[i] = int(SolveStatus.NONFINITE)
                continue
            reason = self._done[i]
            if reason is None:
                # The batch stopped before this member did: control stop,
                # or finish() called early. Tolerance wins.
                o = off[i]
                if sweeps > 0 and math.isfinite(o) and o <= self.tol:
                    reason = "tol"
                elif self._stop_reason is not None:
                    reason = self._stop_reason
                elif sweeps >= self.config.max_sweeps:
                    reason = "max_sweeps"
                else:
                    reason = "stall"
            codes[i] = int(_STATUS_BY_REASON[reason])
        return codes

    def member_sweeps(self, state: BatchSweepState) -> np.ndarray:
        """Per-member sweep counts: frozen at each member's stopping
        boundary, the stack count for members that rode to the end."""
        del state
        done = np.array([r is not None for r in self._done])
        return np.where(done, self._done_sweeps,
                        self._sweeps_host).astype(np.int64)

    def finish(self, state: BatchSweepState) -> SVDResult:
        status = jnp.asarray(self._member_statuses(state))
        sweeps_vec = jnp.asarray(self.member_sweeps(state), jnp.int32)
        if self._kernel_path:
            q1, order, work = self._precond_state()
            refine = (self.config.sigma_refine
                      if self.config.sigma_refine is not None
                      else (self.compute_u or self.compute_v))
            u, s, v = _finish_pallas_batched_jit(
                state.top, state.bot, state.vtop, state.vbot, work, q1,
                order, batch=self.batch, n=self.n,
                compute_u=self.compute_u, compute_v=self.compute_v,
                precondition=self._precondition, refine=bool(refine))
            return SVDResult(u=u, s=s, v=v, sweeps=sweeps_vec,
                             off_rel=state.off_rel, status=status)
        u, s, v = _finish_xla_batched_jit(
            state.top, state.bot, state.vtop, state.vbot, n=self.n,
            compute_u=self.compute_u, compute_v=self.compute_v)
        return SVDResult(u=u, s=s, v=(v if self.compute_v else None),
                         sweeps=sweeps_vec, off_rel=state.off_rel,
                         status=status)

    def sigma_finish(self, state: BatchSweepState):
        """Batched sigma-first termination (cf. `SweepStepper.
        sigma_finish`): returns ``(result, payloads)`` — a sigma-only
        batched `SVDResult` plus ONE deferred-finish payload PER MEMBER,
        each member-sliced into the SINGLE stepper's state form so
        `finish_from_payload` resumes it through the single finish jits
        (already bucket-compiled by the uncoalesced dispatch path; the
        batched preconditioning factors slice per member the same way)."""
        status_codes = self._member_statuses(state)
        sweeps_vec = self.member_sweeps(state)
        off = state.off_rel
        if self._kernel_path:
            q1, order, work = self._precond_state()
            kp = state.top.shape[0] // self.batch
            top_m = state.top.reshape((self.batch, kp) + state.top.shape[1:])
            bot_m = state.bot.reshape((self.batch, kp) + state.bot.shape[1:])
            kv = state.vtop.shape[0] // self.batch if self._accumulate else 0
            if self._accumulate:
                vtop_m = state.vtop.reshape(
                    (self.batch, kv) + state.vtop.shape[1:])
                vbot_m = state.vbot.reshape(
                    (self.batch, kv) + state.vbot.shape[1:])
            else:
                vtop_m = vbot_m = None
            path = "kernel"
        else:
            q1 = order = work = None
            top_m, bot_m = state.top, state.bot
            vtop_m, vbot_m = state.vtop, state.vbot
            path = "xla"
        s = _sigma_from_state_batched_jit(top_m, bot_m, n=self.n)
        refine = (self.config.sigma_refine
                  if self.config.sigma_refine is not None
                  else (self.compute_u or self.compute_v))
        promotable = bool(self.compute_u or self.compute_v)
        payloads = []
        for j in range(self.batch):
            if path == "kernel" and not self._accumulate:
                k = self.nblocks // 2
                vt = vb = jnp.zeros((k, 0, top_m.shape[-1]),
                                    self.input_dtype)
            else:
                vt, vb = vtop_m[j], vbot_m[j]
            payloads.append(dict(
                path=path, top=top_m[j], bot=bot_m[j], vtop=vt, vbot=vb,
                work=None if work is None else work[j],
                q1=None if q1 is None else q1[j],
                order=None if order is None else order[j],
                n=self.n, compute_u=self.compute_u,
                compute_v=self.compute_v, full_u=False,
                precondition=bool(getattr(self, "_precondition", False)),
                refine=bool(refine), v0=None, promotable=promotable,
                status=jnp.int32(int(status_codes[j])),
                sweeps=jnp.int32(int(sweeps_vec[j])), off_rel=off[j]))
        return (SVDResult(u=None, s=s, v=None,
                          sweeps=jnp.asarray(sweeps_vec, jnp.int32),
                          off_rel=off,
                          status=jnp.asarray(status_codes)), payloads)

    def aot_entries(self):
        """Batched twin of `SweepStepper.aot_entries`: the jit entries of
        one coalesced (B, m, n) dispatch as ``(entry_name, jit_fn, args,
        kwargs)`` with `jax.ShapeDtypeStruct` args — ahead-of-time
        lowerable/compilable without running a sweep (the serving entry
        registry's AOT lane). Shapes follow `init`/`step`/`finish`/
        `_member_statuses` via `jax.eval_shape` over the live helpers."""
        f32s = jax.ShapeDtypeStruct((), jnp.float32)
        offv = jax.ShapeDtypeStruct((self.batch,), jnp.float32)
        a_spec = jax.ShapeDtypeStruct((self.batch, self.m, self.n),
                                      self.input_dtype)
        k = self.nblocks // 2
        entries = []
        if self._kernel_path:
            if self._precondition:
                entries.append(("solver._precondition_qr_batched_jit",
                                _precondition_qr_batched_jit, (a_spec,),
                                {}))
                q1_s, _, order_s, work_s = jax.eval_shape(
                    jax.vmap(_precondition_qr), a_spec)
            else:
                q1_s = order_s = None
                work_s = a_spec
            top_s, bot_s = jax.eval_shape(
                lambda w: tuple(map(_stack_members, _blockify_batched(
                    w, self.n_pad, self.nblocks))), work_s)
            if self._accumulate:
                vtop_s, vbot_s = jax.eval_shape(
                    lambda: tuple(map(_stack_members, _blockify_batched(
                        jnp.broadcast_to(
                            jnp.eye(self.n_pad, dtype=self.input_dtype),
                            (self.batch, self.n_pad, self.n_pad)),
                        self.n_pad, self.nblocks))))
            else:
                vtop_s = vbot_s = jax.ShapeDtypeStruct(
                    (self.batch * k, 0, top_s.shape[2]), self.input_dtype)
            if self.method == "block_rotation":
                # Bulk-stage sweep entry of the block lane (the polish
                # stage's pallas entry follows) — cf. the single
                # stepper's aot_entries.
                entries.append((
                    "solver._sweep_step_block_batched_jit",
                    _sweep_step_block_batched_jit,
                    (top_s, bot_s, vtop_s, vbot_s, f32s),
                    dict(batch=self.batch, with_v=self._accumulate,
                         apply_x3=self._apply_x3,
                         interpret=not pb.supported())))
            if self.method == "resident":
                # Bulk-stage sweep entry of the resident lane (the
                # polish stage's pallas entry follows).
                entries.append((
                    "solver._sweep_step_resident_batched_jit",
                    _sweep_step_resident_batched_jit,
                    (top_s, bot_s, vtop_s, vbot_s, f32s),
                    dict(batch=self.batch, r_rounds=self._r_rounds,
                         with_v=self._accumulate,
                         apply_x3=self._apply_x3,
                         interpret=not pb.supported())))
            entries.append((
                "solver._sweep_step_pallas_batched_jit",
                _sweep_step_pallas_batched_jit,
                (top_s, bot_s, vtop_s, vbot_s, f32s),
                dict(batch=self.batch, with_v=self._accumulate,
                     polish=bool(self.config.kernel_polish),
                     interpret=not pb.supported())))
            refine = (self.config.sigma_refine
                      if self.config.sigma_refine is not None
                      else (self.compute_u or self.compute_v))
            entries.append((
                "solver._finish_pallas_batched_jit",
                _finish_pallas_batched_jit,
                (top_s, bot_s, vtop_s, vbot_s, work_s, q1_s, order_s),
                dict(batch=self.batch, n=self.n, compute_u=self.compute_u,
                     compute_v=self.compute_v,
                     precondition=self._precondition,
                     refine=bool(refine))))
            # The per-member status probe reshapes the stacked pairs back
            # to member-major (B, k, m, b) — mirror `_member_statuses`.
            kp = top_s.shape[0] // self.batch
            ptop = jax.ShapeDtypeStruct((self.batch, kp) + top_s.shape[1:],
                                        self.input_dtype)
            pbot = jax.ShapeDtypeStruct((self.batch, kp) + bot_s.shape[1:],
                                        self.input_dtype)
            entries.append(("solver._nonfinite_probe_batched_jit",
                            _nonfinite_probe_batched_jit,
                            (ptop, pbot, offv), {}))
            # Two-phase serving's batched sigma-first extraction: the
            # sigma-phase dispatch reads sigma off the member-major
            # stacks and defers the finish stage (cf. the single
            # stepper's aot_entries).
            entries.append(("solver._sigma_from_state_batched_jit",
                            _sigma_from_state_batched_jit, (ptop, pbot),
                            dict(n=self.n)))
        else:
            top_s, bot_s = jax.eval_shape(
                lambda: _blockify_batched(
                    jnp.zeros((self.batch, self.m, self.n),
                              self.input_dtype),
                    self.n_pad, self.nblocks))
            if self.compute_v:
                vtop_s, vbot_s = jax.eval_shape(
                    lambda: _blockify_batched(
                        jnp.broadcast_to(
                            jnp.eye(self.n_pad, dtype=self.input_dtype),
                            (self.batch, self.n_pad, self.n_pad)),
                        self.n_pad, self.nblocks))
            else:
                vtop_s = vbot_s = jax.ShapeDtypeStruct(
                    (self.batch, k, 0, top_s.shape[3]), self.input_dtype)
            phases = ([("gram-eigh", "abs"), ("qr-svd", self.criterion)]
                      if self.method == "hybrid"
                      else [(self.method, self.criterion)])
            for method, criterion in phases:
                entries.append((
                    "solver._sweep_step_xla_batched_jit",
                    _sweep_step_xla_batched_jit,
                    (top_s, bot_s, vtop_s, vbot_s),
                    dict(with_v=self.compute_v,
                         precision=self.config.matmul_precision,
                         gram_dtype_name=self.gram_dtype_name,
                         method=method, criterion=criterion)))
            entries.append((
                "solver._finish_xla_batched_jit", _finish_xla_batched_jit,
                (top_s, bot_s, vtop_s, vbot_s),
                dict(n=self.n, compute_u=self.compute_u,
                     compute_v=self.compute_v)))
            entries.append(("solver._nonfinite_probe_batched_jit",
                            _nonfinite_probe_batched_jit,
                            (top_s, bot_s, offv), {}))
            # Batched sigma-first extraction (the XLA batched stacks are
            # member-major (B, k, m, b) already — no reshape).
            entries.append(("solver._sigma_from_state_batched_jit",
                            _sigma_from_state_batched_jit, (top_s, bot_s),
                            dict(n=self.n)))
        return tuple(entries)
