"""Version shims for JAX APIs the solver uses across jax releases.

The mesh solver is written against the current `jax.shard_map` /
`jax.lax.pcast` surface; older jaxlibs (0.4.x) ship the same machinery as
`jax.experimental.shard_map.shard_map` without the varying-axes (vma)
type system. One import site per symbol keeps every caller
version-agnostic:

  * `shard_map(f, mesh=..., in_specs=..., out_specs=...)` — the public
    `jax.shard_map` when it exists; otherwise the experimental one with
    replication checking disabled (check_rep predates pcast/pvary, so
    replicated loop-carry inits would be rejected for the exact reason
    pcast was later added).
  * `pcast(x, axes, to=...)` — `jax.lax.pcast` when it exists; identity
    otherwise (no vma checker to satisfy).
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(f, *, mesh, in_specs, out_specs):
        return _shard_map_old(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=False)

if hasattr(jax.lax, "pcast"):
    pcast = jax.lax.pcast
else:
    def pcast(x, axes, *, to=None):
        del axes, to
        return x


def vma(x) -> frozenset:
    """Varying-axes set of a traced value; empty on jaxes without vma."""
    if hasattr(jax, "typeof"):
        return getattr(jax.typeof(x), "vma", frozenset())
    return frozenset()


def enable_cpu_collectives() -> None:
    """Multi-process CPU runs need a cross-process collectives backend.
    Newer jaxes default `jax_cpu_collectives_implementation` to "gloo";
    0.4.x defaults it to "none" and cross-host psum/pmax then fail with
    "Multiprocess computations aren't implemented on the CPU backend".
    Must run before the CPU client is created; harmless on TPU."""
    try:
        if jax.config._read("jax_cpu_collectives_implementation") in (
                None, "none"):
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass  # flag renamed/absent: the default is already a real backend


def distributed_is_initialized() -> bool:
    """`jax.distributed.is_initialized()` where it exists; on older jaxes
    fall back to the global distributed state's coordination client."""
    if hasattr(jax.distributed, "is_initialized"):
        return bool(jax.distributed.is_initialized())
    try:
        from jax._src.distributed import global_state
        return global_state.client is not None
    except Exception:
        return False
