"""svd_jacobi_tpu — a TPU-native one-sided block-Jacobi SVD framework.

Brand-new JAX/XLA/Pallas implementation of the capabilities of
acastellanos95/SVD-Jacobi-MPI-CUDA (an MPI+OpenMP+CUDA one-sided Jacobi SVD,
see SURVEY.md): dense SVD via tournament-ordered block-Jacobi sweeps, batched
on the MXU, sharded over TPU meshes with ICI collectives, with a
LAPACK-gesvd-style API, bench/validation harness, and checkpointing.
"""

from . import grad, obs, resilience, serve, tune
from .config import SVDConfig
from .solver import (SolveStatus, SVDResult, svd, svd_batched, svd_tall,
                     svd_topk)

__version__ = "0.1.0"

__all__ = ["svd", "svd_batched", "svd_tall", "svd_topk", "SVDConfig",
           "SVDResult", "SolveStatus", "grad", "obs", "resilience", "serve",
           "tune", "__version__"]
