"""Multi-host bootstrap — the TPU-native successor of the reference's MPI
process management and SLURM launch scripts.

The reference bootstraps with `MPI_Init`/`MPI_Comm_rank`/`MPI_Comm_size`
(reference: main.cu:1427-1442) and is launched by three SLURM scripts
(build/buildSVDMPICUDA.slurm, build/runSVDMPICUDA.slurm,
build/runSVDMPICUDAWithoutCMake.slurm: 2 nodes x 1 GPU, `mpiexec
--map-by ppr:1:node`). On TPU the same roles are played by
`jax.distributed.initialize()` (process bootstrap over DCN), a `Mesh` over
`jax.devices()` (global device topology — ICI within a host, DCN across
hosts), and host-sharded input generation (each process materializes only
its addressable shards). See scripts/run_multihost.sh for the launch recipe
replacing the SLURM files.

Typical multi-host program:

    from svd_jacobi_tpu.parallel import launch, sharded
    ctx = launch.initialize()              # no-op on a single process
    mesh = sharded.make_mesh()             # all devices across all hosts
    a = launch.sharded_input(16384, 16384, mesh)
    r = sharded.svd(a, mesh=mesh)

On TPU pods the coordinator/process-id/process-count arguments are
auto-detected from the TPU metadata; on CPU/GPU clusters (or for tests)
pass them explicitly or via the standard JAX_* environment variables.
"""

from __future__ import annotations

import dataclasses
import os
import random
import time
from typing import Optional

import jax

from .. import _compat
from ..utils import matgen

# Monkeypatch seam for the retry tests (and anyone who wants virtual time).
_sleep = time.sleep
# Jitter source — module-level so tests can seed/replace it. Deliberately
# NOT seeded from anything process-deterministic: the whole point is that
# two processes of the same fleet draw DIFFERENT delays.
_rng = random.Random()


def _backoff_delay(base_s: float, prev_s: float,
                   cap_s: float = 30.0) -> float:
    """Decorrelated-jitter backoff delay (the AWS Architecture Blog
    recipe): uniform in ``[base_s, min(cap_s, 3 * prev_s)]``.

    Fixed-multiple exponential backoff synchronizes a FLEET: when N
    worker processes are restarted together and all fail their first
    coordinator connect, ``base * 2^k`` has all N retry at the same
    instants — a thundering herd that can re-knock-over the coordinator
    it is waiting for. Decorrelated jitter keeps the expected growth
    (each delay ranges up to 3x the previous one) while spreading the
    N retries uniformly across the window, and ``cap_s`` bounds the
    worst-case single wait."""
    hi = min(float(cap_s), 3.0 * max(float(prev_s), float(base_s)))
    return _rng.uniform(float(base_s), hi)

# RuntimeError texts worth retrying: transient coordinator bring-up races
# (refused/unreachable/deadline). Anything else — wrong address, mismatched
# process counts, plugin errors — is permanent and must surface immediately,
# not after seconds of misleading backoff.
_TRANSIENT_CONNECT = ("connect", "refused", "unavailable", "deadline",
                      "timed out", "timeout")


@dataclasses.dataclass(frozen=True)
class DistributedContext:
    """What the reference read back from MPI_Comm_rank/size (main.cu:1441-1442)."""

    process_index: int
    process_count: int
    local_device_count: int
    global_device_count: int

    @property
    def is_coordinator(self) -> bool:
        """True on the process that owns coordination duties (reference:
        ROOT_RANK, lib/global.cuh:11 — but unlike the reference's root, no
        data funnels through it; it only prints/writes reports)."""
        return self.process_index == 0


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[list] = None,
    connect_retries: int = 4,
    connect_backoff_s: float = 0.5,
    connect_backoff_cap_s: float = 30.0,
) -> DistributedContext:
    """Bootstrap multi-host JAX; safe to call on a single process.

    Replaces `MPI_Init` (main.cu:1427). Auto-detects cluster parameters on
    TPU pods / SLURM / Cloud TPU environments via JAX's cluster detection;
    explicit arguments (or JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
    JAX_PROCESS_ID env vars) override. When no cluster environment is
    present and no arguments are given, this is a no-op single-process
    context — the same code path then runs single-host, like the reference
    run with `mpiexec -np 1`.

    Coordinator connection is retried with DECORRELATED-JITTER backoff
    (``connect_retries`` retries; each delay uniform in
    ``[connect_backoff_s, min(connect_backoff_cap_s, 3 * previous)]`` —
    see `_backoff_delay`): on cold pod bring-up the coordinator process
    routinely comes up seconds after its workers, and the first connect
    used to fail the whole job on one transient refusal; the jitter
    de-synchronizes a multi-process fleet restart so N workers do not
    thundering-herd the coordinator at fixed multiples. "Already
    initialized" errors are never retried — they are a programming-order
    problem, not a transient one.
    """
    explicit = (coordinator_address is not None
                or num_processes is not None
                or bool(os.environ.get("JAX_COORDINATOR_ADDRESS"))
                or bool(os.environ.get("JAX_NUM_PROCESSES")))
    if ((explicit or _cluster_env_present())
            and not _compat.distributed_is_initialized()):
        _compat.enable_cpu_collectives()
        attempt = 0
        prev_delay = connect_backoff_s
        while True:
            try:
                jax.distributed.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes,
                    process_id=process_id,
                    local_device_ids=local_device_ids,
                )
                break
            except RuntimeError as e:
                # The backend was already initialized before we ran — the
                # bootstrap cannot take effect and a multi-node job would
                # degrade to independent single-host solves. Raise for
                # explicit requests; warn LOUDLY for auto-detected cluster
                # envs (which can also be false positives, e.g. a non-JAX
                # SLURM allocation). Double-init is handled by the
                # is_initialized() guard above, not exception sniffing.
                if "must be called before" in str(e):
                    if not explicit:
                        import warnings
                        warnings.warn(
                            "jax.distributed.initialize was skipped because "
                            "the XLA backend is already initialized (a JAX "
                            "call ran before launch.initialize()). If this "
                            "is a multi-process job, each process is now "
                            "running an INDEPENDENT solve — call "
                            "launch.initialize() before any other JAX use.",
                            RuntimeWarning, stacklevel=2)
                        break
                    raise
                # Transient coordinator-connect failure (refused/timed out
                # during bring-up): bounded exponential backoff. Permanent
                # errors (bad address, plugin failures) raise immediately.
                msg = str(e).lower()
                if not any(t in msg for t in _TRANSIENT_CONNECT):
                    raise
                if attempt >= connect_retries:
                    raise RuntimeError(
                        f"coordinator connect failed after {attempt + 1} "
                        f"attempt(s): {e}") from e
                delay = _backoff_delay(connect_backoff_s, prev_delay,
                                       connect_backoff_cap_s)
                prev_delay = delay
                import warnings
                warnings.warn(
                    f"coordinator connect attempt {attempt + 1} failed "
                    f"({e}); retrying in {delay:.1f}s",
                    RuntimeWarning, stacklevel=2)
                _sleep(delay)
                attempt += 1
    return DistributedContext(
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        local_device_count=jax.local_device_count(),
        global_device_count=jax.device_count(),
    )


def _cluster_env_present() -> bool:
    """True when a known MULTI-process cluster environment advertises itself
    (TPU pod metadata with >1 worker, SLURM with >1 node, Open MPI with >1
    rank). Single-worker values — e.g. a dev attachment exporting
    TPU_WORKER_HOSTNAMES=localhost — do not count."""
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return bool(
        "," in hostnames
        or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")
        or (os.environ.get("SLURM_JOB_NUM_NODES")
            and int(os.environ["SLURM_JOB_NUM_NODES"]) > 1)
        or int(os.environ.get("OMPI_COMM_WORLD_SIZE", "1")) > 1
    )


def sharded_input(m: int, n: int, mesh, *, seed: int = matgen.DEFAULT_SEED,
                  dtype=None, kind: str = "dense"):
    """Generate the benchmark input directly into the solver's sharding.

    Host-sharded replacement for the reference's root-rank generation +
    scatter (main.cu:1548-1567): each process materializes only its
    addressable column blocks, so no host ever holds the full matrix.
    ``kind``: "dense" (uniform) or "triangular" (the reference's benchmark
    input, upper-triangular — only valid square).
    """
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    if dtype is None:
        dtype = jnp.float32
    (axis_name,) = mesh.axis_names
    sharding = NamedSharding(mesh, P(None, axis_name))  # column-block
    if kind == "triangular":
        if m != n:
            raise ValueError("triangular input requires m == n")
        return matgen.sharded_random(m, n, sharding, seed=seed, dtype=dtype,
                                     triangular=True)
    return matgen.sharded_random(m, n, sharding, seed=seed, dtype=dtype)
