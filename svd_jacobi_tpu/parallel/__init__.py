"""Parallelism: tournament schedule, device meshes, sharded sweeps."""

from . import schedule  # noqa: F401
