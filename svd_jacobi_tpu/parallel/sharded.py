"""Distributed one-sided block-Jacobi SVD over a TPU device mesh.

TPU-native replacement for the reference's MPI distribution engine and
distributed solver (reference: `omp_mpi_cuda_dgesvd_local_matrices`,
lib/JacobiMethods.cu:191-1175, and its root-centric scatter/gather transport,
lib/JacobiMethods.cu:334-432 distribute, 606-688 gather, 694 barrier). The
reference moves every column through rank 0 with blocking MPI_Send/MPI_Recv
four times per round; here the matrix is *persistently* sharded column-block
over a 1D mesh and never leaves the devices:

  * each device owns a contiguous slab of pair slots (``k_loc`` "top" and
    ``k_loc`` "bot" column blocks of A, and the matching V blocks);
  * a round orthogonalizes every local block pair — batched matmuls on the
    MXU (ops/blockwise.py);
  * the tournament rotation moves exactly ONE block to each neighbor —
    two `lax.ppermute` hops on the ICI ring per round, the minimum possible
    communication (vs. the reference's O(n) columns through root per round);
  * convergence is a `lax.pmax` over the mesh of the per-device scaled
    coupling, driving a `lax.while_loop` over sweeps — replacing the
    reference's discarded convergence estimate + hard-coded single sweep
    (lib/JacobiMethods.cu:234, 462) and its per-round MPI_Barrier (the
    collectives are the synchronization).

The ring schedule is the circle method of parallel/schedule.py restricted to
shards: position top[0] (device 0) is the fixed player; every other slot
cycles ``bot[0] -> top[1] -> ... -> top[k-1] -> bot[k-1] -> ... -> bot[0]``.
The property tests in tests/test_schedule.py prove every block pair meets
exactly once per sweep; tests/test_sharded.py proves the sharded traversal
is equivalent to the single-device one.

Multi-host: build the mesh from `jax.devices()` after
`jax.distributed.initialize()` — the same code runs over ICI within a host
and DCN across hosts; `utils.matgen.sharded_random` generates inputs directly
into the sharding so no host ever materializes the full matrix.

Relation to sequence/context parallelism (SURVEY.md section 5): this
ppermute round-robin is structurally the same ring algorithm as ring
attention — each device holds resident blocks (column blocks here, Q blocks
there), a rotating set of partner blocks rides the ICI ring one neighbor
per step, and every resident/visitor pair interacts exactly once per
cycle. Column-block sharding of the n axis is this workload's analogue of
sharding the sequence axis; scaling N is the long-axis scaling story.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import _compat
from ..config import SVDConfig
from ..grad import rules as _grad
from ..obs import metrics
from ..ops import blockwise
from ..resilience import chaos as _chaos
from . import schedule as sched
from .. import solver as _single

AXIS = "blocks"


def make_mesh(devices=None, axis_name: str = AXIS) -> Mesh:
    """1D mesh over all (or the given) devices.

    Replaces the reference's process bootstrap (MPI_Init/rank/size,
    main.cu:1427-1442): mesh construction is the only topology setup needed;
    on multi-host, call `jax.distributed.initialize()` first.
    """
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (axis_name,))


def _ring_exchange(top, bot, *, axis_name: str, n_devices: int):
    """One tournament rotation of block stacks sharded over ``axis_name``.

    Local view: ``top``/``bot`` are (k_loc, m, b) with k_loc >= 2. Globally
    this implements exactly `schedule.rotate_blocks`:
      new_top = [top[0], bot[0], top[1:-1]]   (slot 0 fixed, top shifts right)
      new_bot = [bot[1:], top[-1]]            (bot shifts left)
    The only non-local moves are one block to each neighbor:
      * ``top[-1]`` rides right  (device d -> d+1), entering the neighbor's
        top stream;
      * ``bot[0]`` rides left    (device d -> d-1), entering the neighbor's
        bot stream;
    which become two `lax.ppermute` hops over the ICI ring — the TPU-native
    form of the reference's per-round column transport
    (lib/JacobiMethods.cu:334-432, 606-688).
    """
    if n_devices == 1:
        return sched.rotate_blocks(top, bot)  # has the k == 1 fixed point

    right = [(d, d + 1) for d in range(n_devices - 1)]
    left = [(d, d - 1) for d in range(1, n_devices)]
    t_in = lax.ppermute(top[-1:], axis_name, right)   # from left neighbor
    b_in = lax.ppermute(bot[:1], axis_name, left)     # from right neighbor

    d = lax.axis_index(axis_name)
    # Device 0: slot 0 is the fixed player; bot[0] enters top locally.
    top_first = jnp.concatenate([top[:1], bot[:1], top[1:-1]], axis=0)
    top_rest = jnp.concatenate([t_in, top[:-1]], axis=0)
    new_top = jnp.where(d == 0, top_first, top_rest)
    # Last device: top[-1] enters bot locally (end of the ring).
    bot_last = jnp.concatenate([bot[1:], top[-1:]], axis=0)
    bot_rest = jnp.concatenate([bot[1:], b_in], axis=0)
    new_bot = jnp.where(d == n_devices - 1, bot_last, bot_rest)
    return new_top, new_bot


def _identity_blocks(k: int, n_pad: int, dtype, *, axis_name, local_shape):
    """Per-shard construction of this device's blocks of V = I.

    Device d owns pair slots [d*k_loc, (d+1)*k_loc); its top blocks are the
    global column blocks of the same index and its bot blocks are offset by
    ``k``. Building the identity blocks from iota *inside* shard_map means no
    device ever materializes the full replicated n_pad x n_pad identity the
    way a naive `jnp.eye` init would (at 65536^2 f32 that is 16 GB).
    """
    k_loc, _, b = local_shape
    d = lax.axis_index(axis_name)
    shape = (k_loc, n_pad, b)
    rows = lax.broadcasted_iota(jnp.int32, shape, 1)
    cols = lax.broadcasted_iota(jnp.int32, shape, 2)
    blk = lax.broadcasted_iota(jnp.int32, shape, 0) + d * k_loc
    vtop = (rows == blk * b + cols).astype(dtype)
    vbot = (rows == (blk + k) * b + cols).astype(dtype)
    return vtop, vbot


def _sweep_sharded(top, bot, vtop, vbot, *, axis_name, n_devices, n_rounds,
                   precision, gram_dtype, method, criterion, with_v):
    """One full sharded sweep (runs under shard_map): scan over the ring
    tournament's rounds, pmax'd convergence statistic. Shared by the fused
    solve (`_sharded_jacobi`) and the host-stepped `SweepStepper`.

    Also returns the sweep's health word ``nonfinite`` — derived from the
    ALREADY pmax'd dmax2/off-norm reductions, so the in-graph health adds
    zero collectives to the round loop (the HLO001 budget is unchanged;
    see config.COLLECTIVE_BUDGET)."""

    def round_body(carry, _, *, dmax2):
        top, bot, vtop, vbot, max_rel = carry
        top, bot, nvt, nvb, rel, _ = blockwise.orthogonalize_pairs(
            top, bot, vtop if with_v else None, vbot if with_v else None,
            precision=precision, gram_dtype=gram_dtype, method=method,
            criterion=criterion, dmax2=dmax2, axis_name=axis_name)
        if with_v:
            vtop, vbot = nvt, nvb
        top, bot = _ring_exchange(top, bot, axis_name=axis_name,
                                  n_devices=n_devices)
        if with_v:
            vtop, vbot = _ring_exchange(vtop, vbot, axis_name=axis_name,
                                        n_devices=n_devices)
        max_rel = jnp.maximum(max_rel, rel.astype(jnp.float32))
        return (top, bot, vtop, vbot, max_rel), None

    # Global max squared column norm for the deflation gates: column norms
    # drift only slowly across a sweep (they converge to the sigmas), so
    # one pmax per sweep is enough.
    dmax2 = lax.pmax(_single._global_dmax2(top, bot), axis_name)
    init = (top, bot, vtop, vbot,
            _compat.pcast(jnp.zeros((), jnp.float32), (axis_name,),
                      to="varying"))
    (top, bot, vtop, vbot, local_rel), _ = lax.scan(
        partial(round_body, dmax2=dmax2), init, None, length=n_rounds)
    # Global convergence statistic: pmax over the mesh — the TPU-native
    # form of the reduction the reference never does (its per-pair
    # convergence_value is computed and discarded, lib/JacobiMethods.cu:462).
    off_rel = lax.pmax(local_rel, axis_name)
    nonfinite = jnp.logical_or(~jnp.isfinite(dmax2), ~jnp.isfinite(off_rel))
    return top, bot, vtop, vbot, off_rel, nonfinite


def _sweep_sharded_pallas(top, bot, vtop, vbot, *, axis_name, n_devices,
                          n_rounds, rtol, with_v, interpret, polish):
    """One sharded sweep on the Pallas kernel path (runs under shard_map):
    `ops.rounds.sweep` with the mesh axis set and the ICI ring exchange as
    the between-rounds rotation."""
    from ..ops import rounds as _rounds

    dmax2 = lax.pmax(_single._global_dmax2(top, bot), axis_name)
    exchange = partial(_ring_exchange, axis_name=axis_name,
                       n_devices=n_devices)
    top, bot, nvt, nvb, off = _rounds.sweep(
        top, bot, vtop if with_v else None, vbot if with_v else None,
        dmax2, rtol, interpret=interpret, polish=polish, bf16_gram=False,
        axis_name=axis_name, n_rounds=n_rounds, exchange=exchange)
    if with_v:
        vtop, vbot = nvt, nvb
    # Health word off the reductions this sweep already pays for (cf.
    # `_sweep_sharded`): zero extra collectives.
    nonfinite = jnp.logical_or(~jnp.isfinite(dmax2), ~jnp.isfinite(off))
    return top, bot, vtop, vbot, off, nonfinite


def _sharded_jacobi(top, bot, *, axis_name, n_devices, n_rounds,
                    tol, max_sweeps, precision, gram_dtype_name, method,
                    criterion, with_v, n_pad, nblocks, stall_detection=True,
                    kernel_polish=True, telemetry=False, replicas=1,
                    chaos_nan_sweep=None):
    """Body run under shard_map: while_loop(sweeps) of scan(rounds).

    The while carry includes the in-graph health word ``nonfinite`` (see
    `_sweep_sharded`) — the loop stops early on poisoned state and the
    flag is returned so `_svd_sharded_jit` can decode `SolveStatus`.
    ``chaos_nan_sweep`` (static): `resilience.chaos` NaN-injection hook;
    None (production) traces no injection code.

    ``telemetry`` (static): emit one `obs.metrics` "sweep" event per loop
    iteration with the pmax'd (mesh-replicated) off-norm. The callback
    fires once per LOCAL device with identical values; ``replicas`` (the
    local device count of the mesh) lets the host dispatcher forward each
    event exactly once, and only process 0 records — so a multi-chip solve
    reports each sweep once. Off by default: the disabled trace is
    byte-identical to the untelemetered one.
    """
    gram_dtype = jnp.dtype(gram_dtype_name)
    if with_v:
        vtop, vbot = _identity_blocks(nblocks // 2, n_pad, top.dtype,
                                      axis_name=axis_name,
                                      local_shape=top.shape)
    else:
        # Zero-width placeholders keep one traced signature (cf. solver.py).
        vtop = vbot = _compat.pcast(
            jnp.zeros((top.shape[0], 0, top.shape[2]), top.dtype),
            (axis_name,), to="varying")

    def sweep(top, bot, vtop, vbot, mth, crit):
        return _sweep_sharded(top, bot, vtop, vbot, axis_name=axis_name,
                              n_devices=n_devices, n_rounds=n_rounds,
                              precision=precision, gram_dtype=gram_dtype,
                              method=mth, criterion=crit, with_v=with_v)

    def iterate(top, bot, vtop, vbot, mth, crit, t, budget, stage,
                nf0=None):
        def cond(state):
            _, _, _, _, off_rel, prev_off, sweeps, nonfinite = state
            return _single._should_continue(off_rel, prev_off, sweeps,
                                            tol=t, max_sweeps=budget,
                                            stall_detection=stall_detection,
                                            criterion=crit,
                                            nonfinite=nonfinite)

        def body(state):
            top, bot, vtop, vbot, prev_off, _, sweeps, nonfinite = state
            if chaos_nan_sweep is not None:
                top = _chaos.poison(top, sweeps, chaos_nan_sweep)
            top, bot, vtop, vbot, off_rel, nf = sweep(top, bot, vtop, vbot,
                                                      mth, crit)
            nonfinite = nonfinite | nf
            if telemetry:
                # off_rel is pmax'd -> identical on every device; the
                # dispatcher collapses the per-device deliveries.
                metrics.emit("sweep",
                             meta={"path": "sharded", "stage": stage,
                                   "method": mth, "devices": n_devices},
                             replicas=replicas,
                             sweep=sweeps + 1, off_rel=off_rel)
            return (top, bot, vtop, vbot, off_rel, prev_off, sweeps + 1,
                    nonfinite)

        inf = jnp.float32(jnp.inf)
        nf_init = jnp.zeros((), jnp.bool_) if nf0 is None else nf0
        state = (top, bot, vtop, vbot, inf, inf, jnp.int32(0), nf_init)
        return lax.while_loop(cond, body, state)

    if method == "pallas":
        # The device-kernel path (the same kernels as the single-chip
        # solver) sharded over the mesh: self/cross rounds run per device,
        # the tournament rides the ICI ring, and the round-skip predicate
        # is pmax-replicated.
        def sweep_pallas(top, bot, vtop, vbot, _mth, _crit):
            from ..ops import pallas_blocks as pb
            return _sweep_sharded_pallas(
                top, bot, vtop, vbot, axis_name=axis_name,
                n_devices=n_devices, n_rounds=n_rounds, rtol=tol,
                with_v=with_v, interpret=not pb.supported(),
                polish=kernel_polish)

        sweep = sweep_pallas

    if method == "hybrid":
        # See solver._svd_padded: abs-converged bulk phase, then a short
        # relative-criterion polish phase for U orthogonality.
        top, bot, vtop, vbot, off1, _, s1, nf1 = iterate(
            top, bot, vtop, vbot, "gram-eigh", "abs",
            _single._abs_phase_tol(top.dtype), max_sweeps, "bulk")
        if telemetry:
            metrics.emit("stage",
                         meta={"path": "sharded", "stage": "bulk"},
                         replicas=replicas, sweeps=s1, off_rel=off1)
        top, bot, vtop, vbot, off2, _, s2, nf2 = iterate(
            top, bot, vtop, vbot, "qr-svd", criterion, tol, max_sweeps - s1,
            "polish", nf0=nf1)
        # Zero-iteration polish leaves its init off = inf; see solver.py.
        off_rel = jnp.where(s2 > 0, off2, off1)
        return top, bot, vtop, vbot, off_rel, s1 + s2, nf2
    top, bot, vtop, vbot, off_rel, _, sweeps, nonfinite = iterate(
        top, bot, vtop, vbot, method, criterion, tol, max_sweeps, "single")
    return top, bot, vtop, vbot, off_rel, sweeps, nonfinite


def svd(
    a,
    *,
    mesh: Optional[Mesh] = None,
    compute_u: bool = True,
    compute_v: bool = True,
    full_matrices: bool = False,
    config: Optional[SVDConfig] = None,
) -> _single.SVDResult:
    """Distributed one-sided block-Jacobi SVD: ``a = u @ diag(s) @ v.T``.

    Drop-in distributed form of `svd_jacobi_tpu.svd` (same result contract);
    public API surface mirrors the reference's distributed entry point
    `omp_mpi_cuda_dgesvd_local_matrices` (lib/JacobiMethods.cuh:44-52) with
    jobu/jobv expressed as compute_u/compute_v (see lapack.gesvd for the
    SVD_OPTIONS-shaped surface).

    Args:
      a: (m, n) real matrix. May be an already-sharded jax.Array (e.g. from
        `utils.matgen.sharded_random`) or a host array to be distributed.
      mesh: 1D device mesh; defaults to all local devices.
    """
    if config is None:
        config = SVDConfig()
    # Single-device-only config modes are REJECTED here rather than
    # silently ignored (recording them in reports as if applied).
    if config.precondition not in ("auto", "on", "off", "double"):
        raise ValueError(f"unknown precondition mode: {config.precondition!r}")
    if config.precondition == "double":
        raise ValueError(
            "precondition='double' (dgejsv's second QR) is not supported by "
            "the mesh solver; use 'on'/'auto' (single QR) or the "
            "single-device svd()")
    if config.mixed_bulk:
        raise ValueError(
            "mixed_bulk is a single-device mode (the mesh solver runs "
            "full-precision sweeps); leave it None/False for mesh solves")
    a = jnp.asarray(a)
    if a.ndim != 2:
        raise ValueError(f"expected a 2-D matrix, got shape {a.shape}")
    m, n = a.shape
    if m < n:
        r = svd(a.T, mesh=mesh, compute_u=compute_v, compute_v=compute_u,
                full_matrices=full_matrices, config=config)
        return _single.SVDResult(u=r.v, s=r.s, v=r.u, sweeps=r.sweeps,
                                 off_rel=r.off_rel, status=r.status)

    if mesh is None:
        mesh = make_mesh()
    kwargs = _plan_entry(a, mesh, config, compute_u=compute_u,
                         compute_v=compute_v, full_matrices=full_matrices)
    run = lambda x: _svd_sharded_jit(x, **kwargs)
    if _grad.resolve_rule_mode(config) != "off":
        # No gradient rule on the mesh entry yet (a rule would need the
        # recombination/refine stages threaded per shard — the ROADMAP
        # remainder of the differentiable-solver item); fail loudly with
        # the supported spelling instead of the while_loop error.
        run = _grad.uncovered(
            run,
            "parallel.sharded.svd has no gradient rule yet; "
            "differentiate the single-device solver.svd (it carries "
            "custom VJP/JVP rules) and shard the surrounding "
            "computation, or run the mesh solve outside the "
            "differentiated region")
    u, s, v, sweeps, off_rel, status = run(a)
    return _single.SVDResult(u=u, s=s, v=v, sweeps=sweeps, off_rel=off_rel,
                             status=status)


def _plan_entry(a, mesh: Mesh, config: SVDConfig, *, compute_u: bool = True,
                compute_v: bool = True, full_matrices: bool = False) -> dict:
    """Resolve the kwargs of the ONE fused mesh entry point
    (`_svd_sharded_jit(a, **kwargs)`) for this (input, mesh, config) —
    exactly the call `svd()` makes. Shared with `svd_jacobi_tpu.analysis`
    (entries.py): the collective-budget and telemetry-equivalence HLO
    passes must lower the very program production dispatches, geometry
    fix-ups (even-b kernel adjustment, per-device pair slots) included."""
    (axis_name,) = mesh.axis_names
    n_devices = mesh.size
    n = a.shape[1]
    # m/dtype refine the tuning-table width lookup (aspect/dtype rows) —
    # the mesh plan must agree with the single-device plan for the same
    # input, or the two lanes solve the same problem at different widths.
    b, k = _single._plan(n, n_devices, config, m=a.shape[0], dtype=a.dtype)
    tol, gram_dtype_name, method, criterion = _single._resolve_options(
        a, config, compute_uv=compute_u)
    if method == "block_rotation":
        # The blocked-rotation lane is single-device (its bulk/polish
        # phase loops are not threaded through the ring exchange, and its
        # subproblem eigh would run per shard-local panel set): the mesh
        # keeps the pallas kernel lane — the documented fallback, same
        # accuracy class and the same tol/criterion resolution (both are
        # _KERNEL_METHODS), so a table row pinning block_rotation can
        # never break a sharded solve. Collective budgets are unchanged.
        method = "pallas"
    if method == "pallas" and b % 2:
        # The self kernel halves blocks: b must be even (keep k a multiple
        # of the device count).
        b += 1
    n_pad = 2 * k * b
    # QR preconditioning (sweep parity with the single-chip solver — the
    # round-3 mesh path ran Jacobi on raw A and paid ~4 extra sweeps):
    # only the Pallas/qr-svd methods read U off the rotated columns with
    # the inverted bookkeeping the recombination needs; gram-eigh/hybrid
    # keep their own convergence structure, and an explicit "on" there is
    # rejected by the single-device solver too.
    precondition = (config.precondition == "auto" and method == "pallas"
                    and _single._tuned(n, a.shape[0], a.dtype).precondition
                    == "on") or config.precondition == "on"
    if config.precondition == "on" and method != "pallas":
        raise ValueError(
            f"precondition='on' requires the Pallas kernel path; this "
            f"solve resolved to pair_solver={method!r}")

    refine = (config.sigma_refine if config.sigma_refine is not None
              else (compute_u or compute_v))
    return dict(
        mesh=mesh, axis_name=axis_name, n=n, n_pad=n_pad, nblocks=2 * k,
        n_devices=n_devices, compute_u=compute_u, compute_v=compute_v,
        full_u=full_matrices, tol=tol, max_sweeps=int(config.max_sweeps),
        precision=config.matmul_precision,
        gram_dtype_name=gram_dtype_name, method=method, criterion=criterion,
        precondition=bool(precondition), refine=bool(refine),
        stall_detection=bool(config.stall_detection),
        kernel_polish=bool(config.kernel_polish),
        telemetry=bool(metrics.enabled()),
        chaos_nan_sweep=_chaos.consume_nan_sweep())


@partial(jax.jit, static_argnames=(
    "mesh", "axis_name", "n", "n_pad", "nblocks", "n_devices", "compute_u",
    "compute_v", "full_u", "tol", "max_sweeps", "precision",
    "gram_dtype_name", "method", "criterion", "precondition", "refine",
    "stall_detection", "kernel_polish", "telemetry", "chaos_nan_sweep"))
def _svd_sharded_jit(a, *, mesh, axis_name, n, n_pad, nblocks, n_devices,
                     compute_u, compute_v, full_u, tol, max_sweeps, precision,
                     gram_dtype_name, method, criterion, precondition=False,
                     refine=False, stall_detection=True, kernel_polish=True,
                     telemetry=False, chaos_nan_sweep=None):
    m = a.shape[0]
    dtype = a.dtype
    block_spec = P(axis_name, None, None)  # shard the pair-slot axis

    if precondition:
        # Drmac-style QR preconditioning, single-controller semantics (the
        # QR and the recombination matmuls run under GSPMD outside the
        # shard_map loop; the sweep loop then works on the n x n triangle
        # L = R^T — SMALLER stacks than raw A for tall inputs). The
        # factorization and recombination are the single-device solver's
        # own helpers, so the two paths cannot drift.
        q1, _, order, work = _single._precondition_qr(a)
        accumulate = compute_u        # rotations -> U
    else:
        work = a
        accumulate = compute_v

    top, bot = _single._blockify(work, n_pad, nblocks)
    top = lax.with_sharding_constraint(top, NamedSharding(mesh, block_spec))
    bot = lax.with_sharding_constraint(bot, NamedSharding(mesh, block_spec))

    # The sweep-event callback fires once per device this process runs;
    # the host dispatcher needs that count to forward each event once.
    replicas = sum(1 for d in mesh.devices.flat
                   if d.process_index == jax.process_index())
    jacobi = _compat.shard_map(
        partial(_sharded_jacobi, axis_name=axis_name, n_devices=n_devices,
                n_rounds=sched.num_rounds(nblocks), tol=tol, max_sweeps=max_sweeps,
                precision=precision, gram_dtype_name=gram_dtype_name,
                method=method, criterion=criterion, with_v=accumulate,
                n_pad=n_pad, nblocks=nblocks,
                stall_detection=stall_detection, kernel_polish=kernel_polish,
                telemetry=telemetry, replicas=max(1, replicas),
                chaos_nan_sweep=chaos_nan_sweep),
        mesh=mesh,
        in_specs=(block_spec,) * 2,
        out_specs=(block_spec,) * 4 + (P(), P(), P()),
    )
    top, bot, vtop, vbot, off_rel, sweeps, nonfinite = jacobi(top, bot)
    status = _single._status_word(off_rel, sweeps, nonfinite, tol=tol,
                                  max_sweeps=max_sweeps)

    a_work = _single._deblockify(top, bot)
    v_work = _single._deblockify(vtop, vbot)[:n, :] if accumulate else None
    if precondition:
        cols, s, rot = _single._postprocess(
            a_work, v_work, n, compute_u=compute_v, full_u=False, dtype=dtype)
        if refine:
            # Against the n x n triangle (sigma(L) = sigma(A)); runs under
            # GSPMD outside the shard_map loop like the preconditioner.
            cols, s, rot = _single._refine_from_work(work, cols, s, rot)
        u, v = _single._recombine_precondition(
            cols, rot, m=m, n=n, compute_u=compute_u, compute_v=compute_v,
            full_u=full_u, dtype=dtype, q1=q1, order=order)
        return u, s, v, sweeps, off_rel, status
    cols, s, rot = _single._postprocess(a_work, v_work, n,
                                        compute_u=compute_u,
                                        full_u=False, dtype=dtype)
    if refine:
        cols, s, rot = _single._refine_from_work(work, cols, s, rot)
    u, v = cols, rot
    if compute_u and full_u and m > n and u is not None:
        u = _single._complete_orthonormal(u, n, dtype)
    return u, s, v, sweeps, off_rel, status


# ---------------------------------------------------------------------------
# Host-controlled sharded sweep stepping — powers checkpoint/resume and
# per-sweep observability for MESH solves (utils/checkpoint.py,
# utils/profiling.py), closing the round-2 gap where the runs big enough to
# need checkpointing were exactly the ones that could not use it. Single-
# controller scope: state snapshots use fully-addressable arrays.


@partial(jax.jit, static_argnames=(
    "mesh", "axis_name", "n_devices", "nblocks", "with_v", "rtol",
    "polish", "interpret"))
def _sweep_step_sharded_pallas_jit(top, bot, vtop, vbot, *, mesh, axis_name,
                                   n_devices, nblocks, with_v, rtol, polish,
                                   interpret):
    """One kernel-path sweep for the host-stepped MESH API: the same
    `_sweep_sharded_pallas` the fused mesh solver while_loops, under one
    jitted shard_map per host step (mirroring the single-device
    `solver._sweep_step_pallas_jit`) — so checkpointed/instrumented mesh
    solves no longer downgrade to the ~5x-slower XLA block stepping."""
    block_spec = P(axis_name, None, None)
    sharding = NamedSharding(mesh, block_spec)
    top = lax.with_sharding_constraint(top, sharding)
    bot = lax.with_sharding_constraint(bot, sharding)
    vtop = lax.with_sharding_constraint(vtop, sharding)
    vbot = lax.with_sharding_constraint(vbot, sharding)

    def body(top, bot, vtop, vbot):
        # The trailing health word is dropped: the host-stepped path
        # probes the final stacks once in finish() instead
        # (solver._nonfinite_probe_jit).
        t, b, nvt, nvb, off, _ = _sweep_sharded_pallas(
            top, bot, vtop if with_v else None, vbot if with_v else None,
            axis_name=axis_name, n_devices=n_devices,
            n_rounds=sched.num_rounds(nblocks), rtol=rtol, with_v=with_v,
            interpret=interpret, polish=polish)
        if with_v:
            vtop, vbot = nvt, nvb
        return t, b, vtop, vbot, off

    step = _compat.shard_map(body, mesh=mesh,
                         in_specs=(block_spec,) * 4,
                         out_specs=(block_spec,) * 4 + (P(),))
    return step(top, bot, vtop, vbot)


@partial(jax.jit, static_argnames=(
    "mesh", "axis_name", "n_devices", "nblocks", "with_v", "precision",
    "gram_dtype_name", "method", "criterion"))
def _sweep_step_sharded_jit(top, bot, vtop, vbot, *, mesh, axis_name,
                            n_devices, nblocks, with_v, precision,
                            gram_dtype_name, method, criterion):
    block_spec = P(axis_name, None, None)
    sharding = NamedSharding(mesh, block_spec)
    top = lax.with_sharding_constraint(top, sharding)
    bot = lax.with_sharding_constraint(bot, sharding)
    vtop = lax.with_sharding_constraint(vtop, sharding)
    vbot = lax.with_sharding_constraint(vbot, sharding)
    def body(top, bot, vtop, vbot):
        # Health word dropped here too — see the pallas step body above.
        t, b, vt, vb, off, _ = _sweep_sharded(
            top, bot, vtop, vbot, axis_name=axis_name, n_devices=n_devices,
            n_rounds=sched.num_rounds(nblocks),
            precision=precision, gram_dtype=jnp.dtype(gram_dtype_name),
            method=method, criterion=criterion, with_v=with_v)
        return t, b, vt, vb, off

    step = _compat.shard_map(
        body,
        mesh=mesh,
        in_specs=(block_spec,) * 4,
        out_specs=(block_spec,) * 4 + (P(),),
    )
    return step(top, bot, vtop, vbot)


class SweepStepper(_single.SweepStepper):
    """`solver.SweepStepper` over a device mesh: one jitted shard_map sweep
    per host step. Same stage machinery, same SweepState contract — so
    `utils.checkpoint` and `utils.profiling.instrumented_svd` work on
    sharded solves unchanged. On the Pallas path the host steps the SAME
    sharded kernel sweep the fused mesh solver runs
    (`_sweep_sharded_pallas` under one shard_map per step), with the fused
    path's QR-preconditioned bookkeeping; other methods keep the sharded
    XLA hybrid stepping."""

    def __init__(self, a, *, mesh: Optional[Mesh] = None,
                 compute_u: bool = True, compute_v: bool = True,
                 full_matrices: bool = False,
                 config: Optional[SVDConfig] = None):
        if config is None:
            config = SVDConfig()
        if mesh is None:
            mesh = make_mesh()
        self.mesh = mesh
        (self.axis_name,) = mesh.axis_names
        self.n_devices = mesh.size
        super().__init__(a, compute_u=compute_u, compute_v=compute_v,
                         full_matrices=full_matrices, config=config)
        if self.method == "block_rotation":
            # Mesh fallback, mirroring `sharded._plan_entry`: the
            # blocked-rotation bulk is single-device, so the mesh steps
            # the pallas kernel sweeps — with the SINGLE-stage pallas
            # machinery (without this, the base class's bulk/polish
            # stage machine would drive abs-criterion bookkeeping over
            # rel-statistic sharded pallas sweeps: wrong stall
            # constants, and a control stop in the phantom "bulk" stage
            # would decode DEADLINE/CANCELLED past final tolerance).
            # tol/criterion are already the kernel lanes' shared
            # resolution — nothing else changes.
            self.method = "pallas"
            self._stage = "single"
        # Re-plan with the mesh's device count (the base class planned for
        # 1), mirroring `sharded.svd`'s geometry exactly (including the
        # even-b adjustment for the self kernel and the same m/dtype
        # tuning-table lookup the base class just resolved).
        b, k = _single._plan(self.n, self.n_devices, config,
                             m=self.m, dtype=self.input_dtype)
        if self._kernel_path and b % 2:
            b += 1
        self.nblocks, self.n_pad = 2 * k, 2 * k * b
        self._sharding = NamedSharding(mesh, P(self.axis_name, None, None))

    def fingerprint_extra(self) -> dict:
        return {"mesh": list(self.mesh.devices.shape),
                "n_devices": self.n_devices}

    def init(self):
        """Sharded init: block stacks via blockify + device_put, V/G blocks
        via the per-shard identity construction (`_identity_blocks` under
        shard_map) — no device ever materializes the replicated
        n_pad x n_pad identity the base class would build (16 GB at
        65536^2 f32, exactly the scale this stepper exists for). On the
        kernel path the stacks hold the QR triangle L = R^T and the
        identity accumulates the ROTATION product (fused-path
        bookkeeping); otherwise A and V."""
        if self._kernel_path:
            _, _, work = self._precond_state()
            top, bot = _single._blockify(work, self.n_pad, self.nblocks)
            accumulate = self._accumulate
        else:
            top, bot = _single._blockify(self.a, self.n_pad, self.nblocks)
            accumulate = self.compute_v
        top = jax.device_put(top, self._sharding)
        bot = jax.device_put(bot, self._sharding)
        k = self.nblocks // 2
        if accumulate:
            block_spec = P(self.axis_name, None, None)
            build = jax.jit(_compat.shard_map(
                partial(_identity_blocks, k, self.n_pad, self.a.dtype,
                        axis_name=self.axis_name,
                        local_shape=(k // self.n_devices, self.n_pad,
                                     self.n_pad // self.nblocks)),
                mesh=self.mesh, in_specs=(), out_specs=(block_spec,) * 2))
            vtop, vbot = build()
        else:
            vtop = vbot = jnp.zeros((k, 0, top.shape[2]), self.a.dtype)
        if self.config.donate_input:
            self._release_input()
        return _single.SweepState(top, bot, vtop, vbot,
                                  jnp.float32(jnp.inf), jnp.int32(0))

    def reshard(self, state):
        """Pin the block stacks to the mesh sharding (used after init and
        after loading a checkpoint snapshot from host arrays)."""
        put = lambda x: jax.device_put(x, self._sharding)
        return _single.SweepState(
            top=put(state.top), bot=put(state.bot),
            vtop=put(state.vtop), vbot=put(state.vbot),
            off_rel=state.off_rel, sweeps=state.sweeps)

    def _run_sweep(self, state, method, criterion):
        if self._kernel_path:
            from ..ops import pallas_blocks as pb
            top, bot, vtop, vbot, off = _sweep_step_sharded_pallas_jit(
                state.top, state.bot, state.vtop, state.vbot,
                mesh=self.mesh, axis_name=self.axis_name,
                n_devices=self.n_devices, nblocks=self.nblocks,
                with_v=self._accumulate, rtol=float(self.tol),
                polish=bool(self.config.kernel_polish),
                interpret=not pb.supported())
            return _single.SweepState(top, bot, vtop, vbot, off,
                                      state.sweeps + 1)
        top, bot, vtop, vbot, off = _sweep_step_sharded_jit(
            state.top, state.bot, state.vtop, state.vbot,
            mesh=self.mesh, axis_name=self.axis_name,
            n_devices=self.n_devices, nblocks=self.nblocks,
            with_v=self.compute_v, precision=self.config.matmul_precision,
            gram_dtype_name=self.gram_dtype_name, method=method,
            criterion=criterion)
        return _single.SweepState(top, bot, vtop, vbot, off, state.sweeps + 1)
