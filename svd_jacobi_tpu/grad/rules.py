"""Custom VJP/JVP rules — the differentiable-solver attachment layer.

`jax.grad` through a `lax.while_loop` is undefined (JAX raises its
opaque "Reverse-mode differentiation does not work for
lax.while_loop" deep inside the sweep machinery), so before this module
existed every training loop that touched `solver.svd` either died there
or silently reached for `jnp.linalg.svd` — losing every kernel lane this
package builds. This module gives the solve entry points first-class
rules instead:

  * ``mode="jvp"`` (the ``grad_rule="auto"`` default): one
    `jax.custom_jvp` rule carrying the standard full-SVD tangent
    (F-matrix terms safeguarded by `grad.fmatrix` — degenerate/clustered
    pairs masked, never Inf/NaN, plus the thin-SVD null-space correction
    terms for rectangular/truncated factors). The tangent computation is
    LINEAR in the input tangent, so JAX derives reverse mode by
    transposition — ONE rule serves both `jax.jvp` and `jax.grad`, and
    composes under jit/vmap/scan.
  * ``mode="vjp"`` (``grad_rule="vjp"``): an explicit `jax.custom_vjp`
    pair — the textbook cotangent formula in ``_svd_vjp`` — whose
    backward pass additionally SANITIZES non-finite cotangents (a NaN
    cotangent contributes exactly zero instead of poisoning the whole
    gradient; nonlinear in the cotangent, which is precisely what a
    custom_vjp may do and a transposable JVP rule may not). Forward-mode
    `jax.jvp` through this mode raises JAX's standard custom_vjp error.
  * sigma-only solves (``compute_uv=False`` / the sigma-phase serving
    lane) get the cheap sigma gradient ``dsigma = diag(U^T dA V)`` /
    ``A_bar = U diag(s_bar) V^T`` — no F-matrix at all. The factors it
    needs come from running the factor-computing twin of the solve
    UNDER DIFFERENTIATION ONLY (the plain forward call stays the cheap
    sigma-only program).
  * uncovered paths (``full_matrices=True`` with m > n, `svd_batched`,
    the resilience escalation ladder) raise a loud
    `NonDifferentiableError` naming the supported alternative, instead
    of the while_loop failure.

The gradient math runs through module-level jitted entries
(``grad._svd_jvp_jit`` etc.), each enumerated in
`config.RETRACE_BUDGETS` and `serve.registry.jit_entries` so the AOT001
two-way compile ledger stays exact; the GRAD001 analysis pass
(`analysis.grad_checks`) proves the grad traces contain our solver's
sweep loop, no full-shape `jnp.linalg.svd` fallback, and no host
callbacks.

Degenerate-sigma contract: within a sigma cluster (gap below
``grad_degenerate_rtol * sigma_max^2``) individual singular vectors are
mathematically arbitrary, so their coupled gradient terms are MASKED —
the returned gradient is exact for cluster-invariant losses (nuclear
norm, subspace projectors, reconstruction losses) and finite for all.

Diagnostics contract: the convergence diagnostics (``sweeps``,
``off_rel``, ``status``) carry STOP-GRADIENT semantics — their tangents
are zero and their cotangents are dropped. They describe how the
ITERATION ran, not a smooth function of the input (sweep counts are
integer-valued; the off-norm statistic is a max over a discrete
tournament — its true derivative is a subgradient of no training
value), so a loss term built on them contributes nothing to the
gradient. Differentiate through ``u``/``s``/``v`` only.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.scopes import scope
from .fmatrix import _acc, fmatrix, sigma_recip


class NonDifferentiableError(NotImplementedError):
    """Differentiation was requested through a path that has no gradient
    rule. The message names the supported alternative — this error
    replaces JAX's opaque reverse-mode-through-while_loop failure."""


_MODES = ("auto", "jvp", "vjp", "off")


def resolve_rule_mode(config) -> str:
    """The concrete rule mode of a config's ``grad_rule`` knob:
    "jvp" (the "auto" resolution — one transposable rule, both AD
    directions), "vjp" (explicit reverse rule + cotangent sanitizer,
    reverse mode only), or "off" (no rule attached — the historical
    opaque-failure behavior, kept as an escape hatch)."""
    mode = getattr(config, "grad_rule", "auto")
    if mode not in _MODES:
        raise ValueError(f"unknown grad_rule mode: {mode!r} "
                         f"(known: {_MODES})")
    return "jvp" if mode == "auto" else mode


# ---------------------------------------------------------------------------
# The gradient math, as budgeted jitted entries. ``rtol`` rides as a
# TRACED scalar operand (not a static arg), so the jit key is the factor
# shapes alone — one compile per problem key, never per knob value.


def _svd_jvp(u, s, v, da, rtol):
    """Full-SVD tangent (dU, ds, dV) from economy/truncated factors:
    the Townsend F-matrix formula plus the thin-SVD null-space
    corrections — the left term whenever U is rectangular (m > r: dA
    components outside range(U)), the right term whenever V is
    (n > r, the truncated lanes: dA^T components outside range(V))."""
    m, r = u.shape
    n = v.shape[0]
    hi = jax.lax.Precision.HIGHEST
    uu, ss, vv, dda = _acc(u), _acc(s), _acc(v), _acc(da)
    dp = jnp.matmul(jnp.matmul(uu.T, dda, precision=hi), vv, precision=hi)
    ds = jnp.diagonal(dp)
    f = fmatrix(ss, rtol)
    dss = dp * ss[None, :]               # dP @ Sigma
    sds = dp * ss[:, None]               # Sigma @ dP
    du = jnp.matmul(uu, f * (dss + dss.T), precision=hi)
    dv = jnp.matmul(vv, f * (sds + sds.T), precision=hi)
    sinv = sigma_recip(ss, rtol)
    if m > r:
        dav = jnp.matmul(dda, vv, precision=hi)
        proj = jnp.matmul(uu, jnp.matmul(uu.T, dav, precision=hi),
                          precision=hi)
        du = du + (dav - proj) * sinv[None, :]
    if n > r:
        dau = jnp.matmul(dda.T, uu, precision=hi)
        proj = jnp.matmul(vv, jnp.matmul(vv.T, dau, precision=hi),
                          precision=hi)
        dv = dv + (dau - proj) * sinv[None, :]
    return du.astype(u.dtype), ds.astype(s.dtype), dv.astype(v.dtype)


def _svd_vjp(u, s, v, ubar, sbar, vbar, rtol):
    """Full-SVD cotangent A_bar — the exact transpose of `_svd_jvp`:

        A_bar = U [diag(s_bar) + (F o (U^T U_bar - U_bar^T U)) Sigma
                   + Sigma (F o (V^T V_bar - V_bar^T V))] V^T
                + (I - U U^T) U_bar Sigma^{-1} V^T          (m > r)
                + U Sigma^{-1} V_bar^T (I - V V^T)          (n > r)

    with the same masked F matrix and safe reciprocal."""
    with scope("grad_cotangent"):
        m, r = u.shape
        n = v.shape[0]
        hi = jax.lax.Precision.HIGHEST
        uu, ss, vv = _acc(u), _acc(s), _acc(v)
        ub, sb, vb = _acc(ubar), _acc(sbar), _acc(vbar)
        f = fmatrix(ss, rtol)
        utu = jnp.matmul(uu.T, ub, precision=hi)
        vtv = jnp.matmul(vv.T, vb, precision=hi)
        core = ((f * (utu - utu.T)) * ss[None, :]
                + (f * (vtv - vtv.T)) * ss[:, None]
                + jnp.diag(sb))
        abar = jnp.matmul(jnp.matmul(uu, core, precision=hi), vv.T,
                          precision=hi)
        sinv = sigma_recip(ss, rtol)
        if m > r:
            proj = jnp.matmul(uu, jnp.matmul(uu.T, ub, precision=hi),
                              precision=hi)
            abar = abar + jnp.matmul((ub - proj) * sinv[None, :], vv.T,
                                     precision=hi)
        if n > r:
            proj = jnp.matmul(vv, jnp.matmul(vv.T, vb, precision=hi),
                              precision=hi)
            abar = abar + jnp.matmul(uu * sinv[None, :], (vb - proj).T,
                                     precision=hi)
        return abar.astype(u.dtype)


def _sigma_jvp(u, v, da):
    """The sigma-only tangent ``ds_j = u_j^T dA v_j`` — a diagonal read,
    no F-matrix, no null-space projections (sigma is differentiable
    through clusters; only the vectors are not)."""
    hi = jax.lax.Precision.HIGHEST
    uu, vv, dda = _acc(u), _acc(v), _acc(da)
    dav = jnp.matmul(dda, vv, precision=hi)
    return jnp.einsum("mj,mj->j", uu, dav, precision=hi).astype(u.dtype)


def _sigma_vjp(u, v, sbar):
    """The sigma-only cotangent ``A_bar = U diag(s_bar) V^T`` (one
    rank-r recombination — the transpose of `_sigma_jvp`)."""
    with scope("grad_sigma"):
        hi = jax.lax.Precision.HIGHEST
        uu, vv, sb = _acc(u), _acc(v), _acc(sbar)
        return jnp.matmul(uu * sb[None, :], vv.T,
                          precision=hi).astype(u.dtype)


_svd_jvp_jit = jax.jit(_svd_jvp)
_svd_vjp_jit = jax.jit(_svd_vjp)
_sigma_jvp_jit = jax.jit(_sigma_jvp)
_sigma_vjp_jit = jax.jit(_sigma_vjp)


def jit_entries():
    """``entry name -> live jit object`` for the grad subsystem — merged
    into `serve.registry.jit_entries` so AOT001's two-way ledger covers
    the gradient math like every other compile surface."""
    return {
        "grad._svd_jvp_jit": _svd_jvp_jit,
        "grad._svd_vjp_jit": _svd_vjp_jit,
        "grad._sigma_jvp_jit": _sigma_jvp_jit,
        "grad._sigma_vjp_jit": _sigma_vjp_jit,
    }


# ---------------------------------------------------------------------------
# Rule attachment.


def _zero_tangent(x):
    """A zero tangent matching ``x``: same-shape zeros for inexact
    outputs, float0 zeros for the integer diagnostics (sweeps/status) —
    the dtype JAX requires for non-differentiable primal outputs. A None
    primal (an absent optional output) takes a None tangent (the empty
    pytree node)."""
    if x is None:
        return None
    aval = jax.core.get_aval(x)
    if jnp.issubdtype(aval.dtype, jnp.inexact):
        return jnp.zeros(aval.shape, aval.dtype)
    return np.zeros(aval.shape, jax.dtypes.float0)


def _sanitize_cotangent(ct, ref):
    """vjp-mode chaos guard: a missing cotangent is zero, and NON-FINITE
    cotangent entries are zeroed — a NaN flowing back from a poisoned
    loss contributes nothing instead of wiping the whole gradient (the
    zeroed contribution is the sentinel; forward-solve poison is still
    reported loudly by ``SVDResult.status``, never laundered here)."""
    if ct is None:
        return jnp.zeros_like(ref)
    ct = jnp.asarray(ct)
    return jnp.where(jnp.isfinite(ct), ct,
                     jnp.zeros((), ct.dtype)).astype(ref.dtype)


def differentiable(make_runner: Callable, *, compute_u: bool,
                   compute_v: bool, mode: str, rtol: float):
    """Wrap a solve pipeline with its AD rule.

    ``make_runner(cu, cv)`` returns the pipeline as a pure function
    ``a -> (u, s, v, sweeps, off_rel, status)`` with the given job
    options (Nones for factors not computed). The returned function has
    the same signature as ``make_runner(compute_u, compute_v)`` and
    carries the ``mode`` rule ("jvp" or "vjp" — resolve via
    `resolve_rule_mode` first; "off" never reaches here).

    When the caller requested fewer than both factors, the rule runs the
    FACTOR-COMPUTING twin of the pipeline under differentiation (the
    gradient needs U and V whatever the job options; the plain forward
    call keeps the cheap program), and the sigma-only job gets the
    F-matrix-free sigma gradient.
    """
    primal = make_runner(compute_u, compute_v)
    both = compute_u and compute_v
    with_factors = primal if both else make_runner(True, True)
    sigma_only = not (compute_u or compute_v)

    def _mask(out):
        u, s, v, sweeps, off_rel, status = out
        return (u if compute_u else None, s, v if compute_v else None,
                sweeps, off_rel, status)

    if mode == "jvp":

        @jax.custom_jvp
        def fn(x):
            return primal(x)

        @fn.defjvp
        def fn_jvp(primals, tangents):
            (x,), (dx,) = primals, tangents
            u, s, v, sweeps, off_rel, status = with_factors(x)
            if sigma_only:
                du = dv = None
                ds = _sigma_jvp_jit(u, v, dx)
            else:
                du, ds, dv = _svd_jvp_jit(u, s, v, dx, rtol)
            out = _mask((u, s, v, sweeps, off_rel, status))
            tans = (du if compute_u else None, ds,
                    dv if compute_v else None, _zero_tangent(sweeps),
                    _zero_tangent(off_rel), _zero_tangent(status))
            return out, tans

        return fn

    if mode != "vjp":
        raise ValueError(f"differentiable() takes mode 'jvp'/'vjp', "
                         f"got {mode!r}")

    @jax.custom_vjp
    def fn(x):
        return primal(x)

    def fn_fwd(x):
        u, s, v, sweeps, off_rel, status = with_factors(x)
        return _mask((u, s, v, sweeps, off_rel, status)), (u, s, v)

    def fn_bwd(res, cts):
        u, s, v = res
        ubar, sbar, vbar = cts[0], cts[1], cts[2]
        sbar = _sanitize_cotangent(sbar, s)
        if sigma_only:
            abar = _sigma_vjp_jit(u, v, sbar)
        else:
            ubar = _sanitize_cotangent(ubar, u)
            vbar = _sanitize_cotangent(vbar, v)
            abar = _svd_vjp_jit(u, s, v, ubar, sbar, vbar, rtol)
        return (abar,)

    fn.defvjp(fn_fwd, fn_bwd)
    return fn


def uncovered(fn: Callable, message: str):
    """Wrap a pipeline whose gradient is NOT defined: the plain forward
    call is unchanged, but any differentiation raises a clear
    `NonDifferentiableError` carrying ``message`` (which must name the
    supported alternative) instead of JAX's opaque while_loop failure."""

    @jax.custom_jvp
    def guard(x):
        return fn(x)

    @guard.defjvp
    def guard_jvp(primals, tangents):
        raise NonDifferentiableError(message)

    return guard
