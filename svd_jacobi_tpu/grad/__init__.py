"""Differentiable solver subsystem — custom VJP/JVP rules for the fast
Jacobi SVD, so `solver.svd`, `solver.svd_topk`, and `solver.svd_tall`
sit inside training loops (`jax.grad` / `jax.jvp` / `jax.vjp`) instead
of dying in JAX's reverse-mode-through-`while_loop` error or silently
falling back to `jnp.linalg.svd`'s rule.

Layout:

  * `fmatrix` — the safeguarded F-matrix terms ``1/(sigma_i^2 -
    sigma_j^2)``: degenerate/clustered pairs are MASKED the way the
    sweep loop's deflation classifier masks sub-floor couplings (gap
    measured against the global sigma_max^2 scale), never Inf/NaN. The
    band is the ``SVDConfig.grad_degenerate_rtol`` knob, resolved
    through the same per-dtype tuning-table rows as every other knob.
  * `rules` — the rule machinery: the transposable `jax.custom_jvp`
    rule (the "auto" mode — one rule, both AD directions), the explicit
    `jax.custom_vjp` pair with the non-finite-cotangent chaos guard,
    the F-matrix-free sigma-only fast path, the thin-SVD null-space
    corrections for rectangular/truncated factors, and the
    `NonDifferentiableError` loud-failure wrapper for uncovered paths.

The rules attach inside the solver entry points (`solver.svd` et al.
route every solve through them unless ``grad_rule="off"``); this package
holds no entry points of its own. Contract checks live in
`analysis.grad_checks` (GRAD001) and the jitted gradient math is
enumerated in `config.RETRACE_BUDGETS` / `serve.registry.jit_entries`
like every other compile surface.
"""

from .fmatrix import degenerate_band, degenerate_mask, fmatrix, sigma_recip
from .rules import (NonDifferentiableError, differentiable, jit_entries,
                    resolve_rule_mode, uncovered)

__all__ = [
    "NonDifferentiableError",
    "degenerate_band",
    "degenerate_mask",
    "differentiable",
    "fmatrix",
    "jit_entries",
    "resolve_rule_mode",
    "sigma_recip",
    "uncovered",
]
