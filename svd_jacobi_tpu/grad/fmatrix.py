"""Safeguarded F-matrix terms of the SVD gradient.

The standard SVD differentiation formulas (Townsend, *Differentiating the
Singular Value Decomposition*, 2016; Ionescu et al., ICCV 2015) couple
singular-vector perturbations through

    F_ij = 1 / (sigma_j^2 - sigma_i^2)        (i != j, zero diagonal)

which is singular exactly where one-sided Jacobi's own deflation
machinery already knows the spectrum is degenerate: pairs whose
sigma^2 gap sits at or below the roundoff band of the GLOBAL scale
sigma_max^2 (the same normalization `ops.rounds.panel_stats` deflates
its coupling statistic against — a gap measured relative to anything
smaller is noise). A naive 1/(s_i^2 - s_j^2) there produces Inf/NaN that
poisons the whole gradient; dividing by a "regularized" gap instead
produces a finite but enormous garbage rotation.

This module takes the deflation classifier's answer: CLUSTERED PAIRS ARE
MASKED (F_ij = 0), never inverted. The masked gradient is exact for every
loss that is invariant under rotations within a degenerate subspace —
the only class of loss whose gradient is mathematically well-defined
there (individual singular vectors of a tied sigma are arbitrary within
the cluster, so no rule could do better). The band is the
``grad_degenerate_rtol`` knob: explicit on `SVDConfig`, else the
per-dtype tuning-table row (f32 needs a wider band than f64 — its
sigma^2 differences carry ~eps_f32 * sigma_max^2 of solve noise), else
``8 * eps`` of the accumulation dtype.

Everything here is traced library code (jit/vmap-safe, no host reads) and
is exercised through `grad.rules`' jitted entry points.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..obs.scopes import scope


def _acc(x):
    """The accumulation dtype of the gradient math — the same
    promote_types(input, float32) boundary every other solve stage
    declares (`config.MIXED_PRECISION_BOUNDARIES`)."""
    return x.astype(jnp.promote_types(x.dtype, jnp.float32))


def degenerate_band(s, rtol):
    """The absolute sigma^2-gap band below which a pair is classified
    degenerate/clustered: ``rtol * sigma_max^2`` (the global deflation
    scale — one matrix-wide normalization, exactly like the dmax2 scale
    the sweep-loop deflation mask uses, so a cluster of small sigmas in
    a large-sigma matrix is classified by the matrix's scale, not its
    own). Safe for the all-zero matrix (band floors at ``tiny``)."""
    s = _acc(s)
    s2max = jnp.max(s * s)
    return rtol * jnp.maximum(s2max, jnp.finfo(s.dtype).tiny)


def degenerate_mask(s, rtol):
    """Boolean (r, r) mask: True where the pair (i, j) is SAFE to invert
    (its sigma^2 gap clears the band). The diagonal is always False (a
    sigma's gap to itself is zero)."""
    s = _acc(s)
    s2 = s * s
    diff = s2[None, :] - s2[:, None]
    return jnp.abs(diff) > degenerate_band(s, rtol)


def fmatrix(s, rtol):
    """The safeguarded F matrix: ``F_ij = 1/(s_j^2 - s_i^2)`` where the
    pair's gap clears the degenerate band, 0 elsewhere (diagonal
    included). Never Inf/NaN, for any input spectrum — including exact
    ties, padded zero sigmas, and the all-zero matrix."""
    with scope("grad_fmatrix"):
        s = _acc(s)
        s2 = s * s
        diff = s2[None, :] - s2[:, None]
        # ONE classifier: the mask here and the exported degenerate_mask
        # (what the tests pin) are the same function — they cannot drift.
        ok = degenerate_mask(s, rtol)
        # Masked denominator: the unsafe entries divide 1 (then zeroed),
        # so no Inf is ever materialized for jnp.where to launder.
        return jnp.where(ok, 1.0 / jnp.where(ok, diff, 1.0),
                         jnp.zeros((), s.dtype))


def sigma_recip(s, rtol):
    """Safe ``1/sigma`` for the thin-SVD null-space projection terms:
    sigmas whose SQUARE sits inside the degenerate band (i.e. the sigma
    is not separated from zero any better than a clustered pair is from
    its neighbor — the same classification, applied to the pair
    (sigma_i, 0)) contribute 0 instead of an exploding reciprocal."""
    s = _acc(s)
    ok = s * s > degenerate_band(s, rtol)
    return jnp.where(ok, 1.0 / jnp.where(ok, s, 1.0),
                     jnp.zeros((), s.dtype))
