"""Retry/escalation: `resilient_svd`, the self-healing solve orchestrator.

A solve that surfaces a bad health word (`SVDResult.status` other than
``OK``) is re-run through a bounded, configurable escalation ladder of
progressively more conservative configurations:

    base config
      -> matmul_precision="highest"   (kill bf16-pass matmul noise)
      -> widened gram_dtype + hybrid  (f32 grams -> f64; the XLA block
                                       solvers, where gram_dtype bites)
      -> pair_solver="qr-svd"         (gesvj-class relative accuracy,
                                       the most robust Jacobi regime)
      -> lapack-class gesvd fallback  (jnp.linalg.svd — a DIFFERENT
                                       algorithm entirely, the last word)

Rungs that cannot apply (f64 gram widening without x64, a rung equal to a
configuration already tried) are skipped, so the ladder is bounded by
construction. Every attempt is recorded, and with ``manifest_path`` the
whole episode is appended as one schema-versioned ``"retry"`` record via
`obs.manifest` — solves that needed escalation are visible in the same
stream as ordinary runs.

Inputs are guarded before the first attempt (`resilience.guard`):
non-finite inputs raise `NonFiniteInputError` immediately (no ladder can
fix data), and extreme-scale inputs are power-of-two pre-scaled with the
scale undone on the returned sigmas.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Optional, Sequence, Tuple

DEFAULT_RUNGS = ("precision_highest", "wide_gram", "qr_svd", "lapack_gesvd")


def _rung_config(rung: str, cfg, dtype):
    """The configuration a ladder rung escalates ``cfg`` to, or None when
    the rung cannot apply (it is skipped). Transforms are cumulative: each
    rung starts from the previous rung's configuration."""
    import jax

    if rung == "precision_highest":
        return dataclasses.replace(cfg, matmul_precision="highest")
    if rung == "wide_gram":
        wide = {"bfloat16": "float32", "float16": "float32",
                "float32": "float64"}.get(str(dtype))
        if wide is None:
            return None  # f64 input: no wider gram exists
        if wide == "float64" and not jax.config.jax_enable_x64:
            return None
        # gram_dtype only bites on the XLA block solvers; route there and
        # clear the Pallas-only modes that would be rejected.
        return dataclasses.replace(
            cfg, gram_dtype=wide, pair_solver="hybrid", precondition="auto",
            mixed_bulk=None, bulk_bf16=None, mixed_store="auto")
    if rung == "qr_svd":
        return dataclasses.replace(
            cfg, pair_solver="qr-svd", precondition="auto",
            mixed_bulk=None, bulk_bf16=None, mixed_store="auto")
    raise ValueError(f"unknown escalation rung {rung!r}")


def _lapack_fallback(a, compute_u, compute_v, full_matrices):
    """Final rung: LAPACK-class gesvd via `jnp.linalg.svd` — a different
    algorithm (bidiagonalization-based), the strongest possible fallback
    when every Jacobi regime failed. Health word computed from the
    outputs (a NaN factor must still read NONFINITE, never OK). Wide
    inputs go through the same transpose-and-swap as `solver.svd`, so the
    factor shapes match whatever Jacobi rung might have succeeded."""
    import jax.numpy as jnp

    from ..solver import SolveStatus, SVDResult

    if a.shape[0] < a.shape[1]:
        r = _lapack_fallback(a.T, compute_v, compute_u, full_matrices)
        return SVDResult(u=r.v, s=r.s, v=r.u, sweeps=r.sweeps,
                         off_rel=r.off_rel, status=r.status)
    k = min(a.shape)
    if compute_u or compute_v:
        u, s, vt = jnp.linalg.svd(a, full_matrices=bool(full_matrices))
        v = vt[:k, :].T
        finite = (jnp.isfinite(s).all() & jnp.isfinite(u).all()
                  & jnp.isfinite(v).all())
    else:
        s = jnp.linalg.svd(a, compute_uv=False)
        u = v = None
        finite = jnp.isfinite(s).all()
    status = jnp.where(finite, jnp.int32(int(SolveStatus.OK)),
                       jnp.int32(int(SolveStatus.NONFINITE)))
    return SVDResult(u=u if compute_u else None, s=s,
                     v=v if compute_v else None, sweeps=jnp.int32(0),
                     off_rel=jnp.float32(0.0), status=status)


def resilient_svd(
    a,
    *,
    compute_u: bool = True,
    compute_v: bool = True,
    full_matrices: bool = False,
    config=None,
    mesh=None,
    rungs: Sequence[str] = DEFAULT_RUNGS,
    max_attempts: Optional[int] = None,
    manifest_path=None,
    return_report: bool = False,
    watchdog_s: Optional[float] = None,
    on_overrun=None,
):
    """`svd()` with guarded inputs and a bounded escalation ladder.

    Runs the base configuration first; on a non-``OK`` status walks the
    ``rungs`` ladder (skipping inapplicable/duplicate configurations)
    until a solve reports ``OK`` or the ladder is exhausted — the LAST
    attempt's result is returned either way, its ``status`` telling the
    caller the truth. ``max_attempts`` bounds the total attempt count
    (base attempt included). ``mesh`` routes the Jacobi rungs through
    `parallel.sharded.svd`.

    ``manifest_path``: append one ``"retry"`` record (`obs.manifest`)
    describing every attempt. ``return_report``: also return the episode
    report dict ``{"attempts": [...], "final_status": ..., "scale_pow2",
    "watchdog_overrun"}``.

    ``watchdog_s``: wall-clock overrun watchdog. The ladder runs FUSED
    entry points and is uncancellable once entered — nothing here can
    abort a compiled solve mid-flight — so the watchdog's job is to make
    an overrun LOUD AND ACTIONABLE instead of a silent hang: when the
    episode runs past ``watchdog_s`` a daemon timer fires ONCE,
    appending a ``ladder_overrun`` fleet-schema manifest record (when
    ``manifest_path`` is set) and calling ``on_overrun(info)`` with
    ``{"elapsed_s", "budget_s", "m", "n"}``. The serving fleet passes an
    ``on_overrun`` that marks the dispatching lane unhealthy, so the
    supervisor evicts the lane and rescues its queued requests rather
    than the whole service blocking behind the ladder (e.g. in
    ``stop(drain=False)``). The ladder itself continues and still
    returns its result; ``report["watchdog_overrun"]`` says whether the
    watchdog fired.
    """
    import threading

    import jax
    import jax.numpy as jnp

    from .. import obs
    from ..config import SVDConfig
    from ..grad.rules import NonDifferentiableError
    from ..solver import SolveStatus
    from ..utils._exec import host_scalar
    from . import guard

    if isinstance(a, jax.core.Tracer):
        # The escalation ladder is a HOST loop: it reads each attempt's
        # health word between solves and decides the next rung from it —
        # structure no trace can capture, and gradients through "the
        # config that happened to converge" would be ill-defined anyway.
        # Fail with the supported spelling instead of a deep tracer leak.
        raise NonDifferentiableError(
            "resilient_svd cannot run under jax transforms (jit/grad/"
            "vmap): its escalation ladder reads solve health on the host "
            "between attempts. Differentiate solver.svd / svd_topk / "
            "svd_tall directly — they carry custom VJP/JVP rules — and "
            "keep resilient_svd for the host-side serving path.")
    if config is None:
        config = SVDConfig()
    a = jnp.asarray(a)
    if a.ndim != 2:
        raise ValueError(f"expected a 2-D matrix, got shape {a.shape}")
    a_s, scale_p = guard.prescale(a)

    def run(cfg):
        if mesh is not None:
            from ..parallel import sharded
            return sharded.svd(a_s, mesh=mesh, compute_u=compute_u,
                               compute_v=compute_v,
                               full_matrices=full_matrices, config=cfg)
        from ..solver import svd
        return svd(a_s, compute_u=compute_u, compute_v=compute_v,
                   full_matrices=full_matrices, config=cfg)

    # Build the bounded attempt plan: base + applicable rungs, dedup'd.
    plan = [("base", config)]
    cfg = config
    for rung in rungs:
        if rung == "lapack_gesvd":
            plan.append((rung, None))
            continue
        nxt = _rung_config(rung, cfg, a.dtype)
        if nxt is None:
            continue
        cfg = nxt
        if all(nxt != c for _, c in plan if c is not None):
            plan.append((rung, nxt))
    if max_attempts is not None:
        plan = plan[:max(1, int(max_attempts))]

    # Wall-clock overrun watchdog (see docstring): a one-shot daemon
    # timer — the ladder cannot be aborted, but an overrun must be
    # recorded and reported the moment it happens, not after the fused
    # solve deigns to return.
    overrun = {"fired": False}
    t_episode = time.monotonic()

    def _watchdog_fire():
        overrun["fired"] = True
        info = {"elapsed_s": time.monotonic() - t_episode,
                "budget_s": float(watchdog_s),
                "m": int(a.shape[0]), "n": int(a.shape[1])}
        if manifest_path is not None:
            try:
                obs.manifest.append(manifest_path, obs.manifest.build_fleet(
                    event="ladder_overrun", lane=None, **info))
            except Exception:
                pass  # the watchdog must never raise into the timer thread
        if on_overrun is not None:
            try:
                on_overrun(info)
            except Exception:
                pass

    timer = None
    if watchdog_s is not None:
        timer = threading.Timer(float(watchdog_s), _watchdog_fire)
        timer.daemon = True
        timer.start()

    try:
        attempts = []
        result = None
        for rung, cfg_i in plan:
            t0 = time.perf_counter()
            if cfg_i is None:
                result = _lapack_fallback(a_s, compute_u, compute_v,
                                          full_matrices)
            else:
                result = run(cfg_i)
            status = SolveStatus(int(host_scalar(result.status)))
            off = float(host_scalar(result.off_rel))
            attempts.append({
                "rung": rung,
                "status": status.name,
                "time_s": time.perf_counter() - t0,
                "sweeps": int(host_scalar(result.sweeps)),
                "off_norm": off if math.isfinite(off) else None,
                "config_sha256": (obs.manifest.config_hash(cfg_i)
                                  if cfg_i is not None else None),
            })
            if status == SolveStatus.OK:
                break
    finally:
        if timer is not None:
            timer.cancel()

    if scale_p:
        result = result._replace(s=guard.unscale_sigma(result.s, scale_p))
    report = {"attempts": attempts,
              "final_status": attempts[-1]["status"],
              "scale_pow2": scale_p,
              "watchdog_overrun": overrun["fired"]}
    if manifest_path is not None:
        record = obs.manifest.build_retry(
            m=a.shape[0], n=a.shape[1], dtype=str(a.dtype), config=config,
            attempts=attempts, final_status=report["final_status"],
            scale_pow2=scale_p, watchdog_overrun=overrun["fired"])
        obs.manifest.append(manifest_path, record)
    return (result, report) if return_report else result
