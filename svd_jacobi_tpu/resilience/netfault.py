"""Fault-injecting in-process TCP proxy: the network-chaos twin of
`resilience.chaos` (which injects faults INSIDE a replica; this module
injects them BETWEEN replicas).

`FaultyProxy` sits between an `HttpReplica` client and an
`HttpReplicaServer` (or any TCP upstream) on a loopback port and
applies armed faults per FORWARDED REQUEST, following the chaos
module's armed-shot discipline — a test arms N shots of one fault kind,
the proxy consumes them deterministically, unconsumed shots are a test
bug the drill can assert on:

  * ``drop``       — accept the connection, read the request, close
                     without forwarding (the submit never happened;
                     the client sees a reset -> retry -> idempotency);
  * ``delay``      — forward after sleeping ``delay_s`` (timeout /
                     deadline-budget pressure);
  * ``duplicate``  — forward the SAME request to the upstream twice,
                     return the first response (at-least-once delivery;
                     the receiver's dedupe must make it exactly-once);
  * ``blackhole_reply`` — forward the request, swallow the upstream's
                     response, close (the LOST-ACK case: the work
                     happened, the client cannot know);
  * ``partition``  — while engaged (`partition()` / `heal()`), every
                     connection is accepted and dropped without
                     forwarding: a full bidirectional partition. Not
                     shot-counted — it is a STATE, flipped by the test
                     (``flap`` = partition for a duration).

Single-connection HTTP only (the stdlib client sends
``Connection: close``), which keeps "one connection == one request ==
one fault decision" exact."""

from __future__ import annotations

import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

_FAULT_KINDS = ("drop", "delay", "duplicate", "blackhole_reply")


def _read_http_request(conn: socket.socket,
                       timeout: float = 5.0) -> bytes:
    """Read ONE full HTTP request (headers + Content-Length body) off
    the connection; empty bytes when the client vanished first."""
    conn.settimeout(timeout)
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = conn.recv(65536)
        if not chunk:
            return b""
        buf = buf + chunk
    head, _, rest = buf.partition(b"\r\n\r\n")
    length = 0
    for line in head.split(b"\r\n")[1:]:
        name, _, val = line.partition(b":")
        if name.strip().lower() == b"content-length":
            try:
                length = int(val.strip())
            except ValueError:
                length = 0
    while len(rest) < length:
        chunk = conn.recv(65536)
        if not chunk:
            break
        rest = rest + chunk
    return head + b"\r\n\r\n" + rest


class FaultyProxy:
    """See module docstring. ``upstream`` is ``(host, port)``; the
    proxy listens on an ephemeral loopback port (`address`). Faults are
    armed per kind with shot counters (`arm`); `partition()` is a state
    toggle; `stats` counts what actually happened."""

    def __init__(self, upstream: Tuple[str, int],
                 host: str = "127.0.0.1"):
        self.upstream = (str(upstream[0]), int(upstream[1]))
        self._lock = threading.Lock()
        self._armed: Dict[str, dict] = {}       # kind -> {shots, value}
        self._partitioned = False
        self.stats: Dict[str, int] = {"forwarded": 0}
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((host, 0))
        self._listener.listen(64)
        self.address: Tuple[str, int] = self._listener.getsockname()[:2]
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._flap_threads: List[threading.Thread] = []

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "FaultyProxy":
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="svdj-netfault",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(2.0)
        for t in self._flap_threads:
            t.join(2.0)

    def __enter__(self) -> "FaultyProxy":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- fault arming -------------------------------------------------------

    def arm(self, kind: str, shots: int = 1,
            value: float = 0.0) -> None:
        """Arm ``shots`` shots of one fault kind (``value`` is the
        delay for ``delay``). Unknown kinds are a loud test bug."""
        if kind not in _FAULT_KINDS:
            raise ValueError(f"unknown net fault {kind!r} "
                             f"(one of {_FAULT_KINDS})")
        with self._lock:
            self._armed[kind] = {"shots": int(shots),
                                 "value": float(value)}

    def unconsumed(self) -> Dict[str, int]:
        """Remaining armed shots per kind (a drill asserting {} proves
        every armed fault actually fired)."""
        with self._lock:
            return {k: v["shots"] for k, v in self._armed.items()
                    if v["shots"] > 0}

    def _consume(self) -> Optional[Tuple[str, float]]:
        """Consume at most ONE armed fault for this request, in
        deterministic kind order."""
        with self._lock:
            for kind in _FAULT_KINDS:
                slot = self._armed.get(kind)
                if slot is not None and slot["shots"] > 0:
                    slot["shots"] -= 1
                    return kind, slot["value"]
        return None

    # -- partition state ----------------------------------------------------

    def partition(self) -> None:
        """Engage a full bidirectional partition (every connection is
        dropped without forwarding) until `heal`."""
        with self._lock:
            self._partitioned = True

    def heal(self) -> None:
        with self._lock:
            self._partitioned = False

    def partitioned(self) -> bool:
        with self._lock:
            return self._partitioned

    def flap(self, down_s: float) -> threading.Thread:
        """Partition NOW, heal after ``down_s`` — the mid-rescue flap
        drill. Returns the healing thread (joinable)."""
        self.partition()
        t = threading.Thread(
            target=lambda: (time.sleep(down_s), self.heal()),
            name="svdj-netfault-flap", daemon=True)
        t.start()
        self._flap_threads.append(t)
        return t

    # -- the proxy loop -----------------------------------------------------

    def _bump(self, key: str) -> None:
        with self._lock:
            self.stats[key] = self.stats.get(key, 0) + 1

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return      # listener closed
            threading.Thread(target=self._serve_one, args=(conn,),
                             daemon=True).start()

    def _forward_once(self, request: bytes) -> bytes:
        """One upstream exchange: connect, send, read the full response
        until the upstream closes (the server replies Connection:
        close per the stdlib client's request header)."""
        up = socket.create_connection(self.upstream, timeout=10.0)
        try:
            up.sendall(request)
            up.settimeout(10.0)
            resp = b""
            while True:
                chunk = up.recv(65536)
                if not chunk:
                    return resp
                resp = resp + chunk
        finally:
            up.close()

    def _serve_one(self, conn: socket.socket) -> None:
        try:
            if self.partitioned():
                # Full partition: read nothing, forward nothing. The
                # abrupt close is what a blackholed SYN looks like to a
                # short-timeout client: connection error.
                self._bump("partition_dropped")
                return
            request = _read_http_request(conn)
            if not request:
                return
            fault = self._consume()
            if fault is not None:
                kind, value = fault
                self._bump(kind)
                if kind == "drop":
                    return      # request read, never forwarded
                if kind == "delay":
                    time.sleep(value)
                if kind == "duplicate":
                    # At-least-once delivery: the upstream sees the
                    # SAME request twice; the client sees one reply.
                    first = self._forward_once(request)
                    try:
                        self._forward_once(request)
                    except OSError:
                        pass
                    conn.sendall(first)
                    self._bump("forwarded")
                    return
                if kind == "blackhole_reply":
                    # The LOST ACK: the work happens upstream, the
                    # reply dies here.
                    try:
                        self._forward_once(request)
                    except OSError:
                        pass
                    return
            resp = self._forward_once(request)
            if resp:
                conn.sendall(resp)
                self._bump("forwarded")
        except OSError:
            self._bump("proxy_errors")
        finally:
            try:
                conn.close()
            except OSError:
                pass
