"""Deterministic fault injection — the chaos half of the resilience layer.

The reference has no failure handling to test against (MPI errors are
printed and execution carries on, reference lib/JacobiMethods.cu:359-370,
614-616); this module provides the REPRODUCIBLE faults that prove the
detection/recovery machinery of this repo actually works:

  * `nan_at_sweep(k)` — arm an in-graph NaN payload: the next ``shots``
    fused solve dispatches poison one element of the working block stacks
    at the start of sweep ``k``. The hook is threaded through the fused
    entry points as a STATIC jit argument (`chaos_nan_sweep`, resolved by
    `solver._plan_entry` / `parallel.sharded._plan_entry` exactly like the
    telemetry flag), so the unarmed trace contains no injection code at
    all — `analysis.hlo_checks` rule HLO004 pins that property.
  * `sigterm_at_sweep(k)` — arm a SIGTERM delivered to THIS process at the
    end of checkpointed sweep ``k`` (`utils.checkpoint.svd_checkpointed`
    consults the hook once per sweep), driving the kill-then-resume lane.
  * `corrupt_checkpoint(path, mode)` — host-side snapshot corruption
    (truncation, byte flip, zeroing) for the checkpoint-hardening tests.
  * `slow_solve(per_sweep_s)` — arm a deterministic host-side delay per
    sweep for the next ``shots`` SERVED solve dispatches
    (`serve.SVDService` consults the hook once per dispatch and sleeps
    between sweeps), driving the deadline/brownout lanes: a slowed solve
    must cross its deadline at a sweep boundary and surface
    ``SolveStatus.DEADLINE``, never hang.
  * `stuck_backend()` — arm a wedged-backend stall: the next ``shots``
    served dispatches BLOCK before their first sweep, polling the
    request's cooperative deadline/cancel control, bounded by
    ``max_stall_s`` (a chaos hook must never be able to hang an
    un-deadlined test forever). Drives the circuit-breaker lane: stuck
    requests time out, consecutive timeouts trip the breaker, and
    recovery runs through the escalation ladder.
  * **lane injectors** (`kill_lane` / `wedge_lane` / `poison_lane`) —
    fleet-mode faults targeted at ONE solve lane of a multi-lane
    `serve.SVDService` (`ServeConfig.lanes > 1`), driving the lane
    supervisor's whole eviction -> rescue -> probe-recovery ladder:
      - `kill_lane(lane)`: the lane's worker thread raises `LaneKilled`
        (a BaseException, so no per-dispatch handler can swallow it) at
        its next dispatch and DIES with the request still in flight —
        the supervisor must detect the dead thread, quarantine the
        lane, and rescue the stranded request onto a healthy lane;
      - `wedge_lane(lane, wedge_s)`: the lane blocks NON-cooperatively
        (no heartbeat, control ignored) for up to ``wedge_s`` at its
        next dispatch — the heartbeat watchdog must evict it; the bound
        exists so an undetected wedge cannot hang a test forever;
      - `poison_lane(lane, shots)`: the lane's next ``shots`` dispatches
        solve NaN-poisoned working sets and surface
        ``SolveStatus.NONFINITE`` — repeated bad outcomes must evict
        the lane, and once the shots are exhausted a recovery probe
        solves clean and returns it to ACTIVE.

  * **replica injectors** (`kill_replica` / `wedge_replica`) — the lane
    injectors one fault-domain ring up: federation faults targeted at
    ONE replica of a `serve.router.ReplicaRouter`, driving the replica
    supervisor's eviction -> journal-rescue -> probe-recovery ladder
    (consumed per ROUTED submit, so two replicas sharing lane indices
    in one test process cannot cross-consume).

  * `sigkill_at_dispatch(k)` — arm a REAL SIGKILL to this process at its
    k-th next served dispatch, delivered after the dispatch is journaled
    (`serve.journal`) — the process-loss fault the restart-survivability
    lane (journal replay + persistent executable cache) exists to
    survive; subprocess tests only, nothing in-process can catch it.
  * `corrupt_compile_cache(dir, mode)` — corrupt one persistent
    compile-cache entry on disk (`serve.registry`'s executable cache),
    proving a corrupt entry degrades to a loud fresh compile, never a
    crash or a garbage executable.
  * `adversarial_tenant(mode)` — seeded adversarial multi-tenant
    traffic schedules (flooding, bursty, byte-identical-resubmit-heavy,
    deadline-abusing) for the fairness drills: the well-behaved
    tenant's goodput and p99 must hold (asserted from validated serve
    records, not timers) while the abuser is rate-limited/browned-out.

Everything here is deterministic: a hook fires at an exact sweep index /
byte offset, never at random, so chaos-lane failures replay exactly.
"""

from __future__ import annotations

import contextlib
import os
import signal
import threading
from pathlib import Path
from typing import Optional

_lock = threading.Lock()
# Armed state: {"sweep": int, "shots": int} or None. Shots bound how many
# solve DISPATCHES consume the payload (an escalation retry of the same
# matrix must be able to run clean — the point of the recovery test).
_nan_state: Optional[dict] = None
_sigterm_sweep: Optional[int] = None
# Serving-layer faults, one {"value": float, "shots": int} slot per kind
# ("slow": per-sweep delay seconds; "stuck": stall bound seconds) — both
# follow the same arm-context-manager / consume-one-shot protocol
# (`_armed` / `_consume`).
_serve_faults: dict = {"slow": None, "stuck": None}
# Lane-targeted fleet faults: {"lane": int, "value": float, "shots": int}
# per kind — consumed only by dispatches of the TARGETED lane, so a
# multi-lane test hits exactly the lane it armed for.
_lane_faults: dict = {"kill": None, "wedge": None, "poison": None}
# Replica-targeted federation faults (`serve.router`): one ring above
# the lane injectors — the ROUTER consumes these per routed submit, so
# a fault armed for replica 1 is invisible to replica 0 even though
# both replicas' lanes share lane indices in one test process.
_replica_faults: dict = {"kill": None, "wedge": None}


class LaneKilled(BaseException):
    """Raised inside a lane worker by an armed `kill_lane` hook.

    Deliberately a BaseException: the dispatch loop's last-ditch
    ``except Exception`` handlers must NOT catch it — the point of the
    injector is a worker thread that dies with its request stranded in
    flight, which only the fleet supervisor's dead-lane rescue can then
    save (the property under test)."""


@contextlib.contextmanager
def nan_at_sweep(sweep: int, shots: int = 1):
    """Arm the in-graph NaN payload for the next ``shots`` fused solves.

    ``sweep`` is the 0-based sweep-loop counter at whose body start the
    payload lands (the hybrid XLA solver restarts its counter per phase
    loop; the kernel path counts globally across bulk+polish). Detection
    is the health word's job: a poisoned solve must surface
    ``SolveStatus.NONFINITE``, never a silent ``OK``.
    """
    global _nan_state
    with _lock:
        prev = _nan_state
        _nan_state = {"sweep": int(sweep), "shots": int(shots)}
    try:
        yield
    finally:
        with _lock:
            _nan_state = prev


def consume_nan_sweep() -> Optional[int]:
    """One solve dispatch's view of the NaN hook: the armed sweep index
    (decrementing the shot budget) or None. Called by the entry planners;
    the returned value is part of the jit cache key, so an armed dispatch
    compiles a distinct (instrumented) program."""
    global _nan_state
    with _lock:
        st = _nan_state
        if st is None or st["shots"] <= 0:
            return None
        st["shots"] -= 1
        return st["sweep"]


def poison(x, sweeps, sweep_index: int):
    """Traced helper: overwrite one element of ``x`` with NaN when the
    loop counter ``sweeps`` equals the armed ``sweep_index`` (identity on
    every other sweep). Only ever traced when a dispatch consumed an armed
    hook — production programs never contain this op."""
    import jax.numpy as jnp
    idx = (0,) * x.ndim
    payload = jnp.where(sweeps == sweep_index,
                        jnp.asarray(jnp.nan, x.dtype), x[idx])
    return x.at[idx].set(payload)


@contextlib.contextmanager
def _armed(kind: str, value: float, shots: int):
    """Shared arm/restore protocol of the serving-layer fault slots."""
    with _lock:
        prev = _serve_faults[kind]
        _serve_faults[kind] = {"value": float(value), "shots": int(shots)}
    try:
        yield
    finally:
        with _lock:
            _serve_faults[kind] = prev


def _consume(kind: str) -> Optional[float]:
    """One served dispatch's view of a fault slot: the armed value
    (decrementing the shot budget) or None."""
    with _lock:
        st = _serve_faults[kind]
        if st is None or st["shots"] <= 0:
            return None
        st["shots"] -= 1
        return st["value"]


def slow_solve(per_sweep_s: float, shots: int = 1):
    """Arm a deterministic per-sweep host delay for the next ``shots``
    served solve dispatches. The serving worker consumes the hook once
    per dispatch (`consume_slow`) and sleeps ``per_sweep_s`` before each
    sweep of that dispatch — so the solve crosses any deadline at a sweep
    boundary, exactly where the cooperative control checks run. Pure
    host-side: the compiled program is untouched."""
    return _armed("slow", per_sweep_s, shots)


def consume_slow() -> Optional[float]:
    """The slow-solve hook's per-sweep delay in seconds, or None."""
    return _consume("slow")


def stuck_backend(shots: int = 1, max_stall_s: float = 30.0):
    """Arm a wedged-backend stall for the next ``shots`` served solve
    dispatches: each armed dispatch blocks before its first sweep,
    cooperatively polling the request's deadline/cancel control, for at
    most ``max_stall_s`` seconds (the bound exists so an un-deadlined
    test cannot hang forever — a real wedged backend has no such mercy,
    which is what deadlines are for). A deadlined stuck request surfaces
    ``SolveStatus.DEADLINE`` through the production control path."""
    return _armed("stuck", max_stall_s, shots)


def consume_stuck() -> Optional[float]:
    """The stuck-backend hook's stall bound in seconds, or None."""
    return _consume("stuck")


@contextlib.contextmanager
def _indexed_armed(table: dict, index_key: str, kind: str, index: int,
                   value: float, shots: int):
    """THE arm/restore protocol of every index-targeted fault slot
    (lane- and replica-scoped share it): save the previous slot, arm
    {index, value, shots}, restore on exit."""
    with _lock:
        prev = table[kind]
        table[kind] = {index_key: int(index), "value": float(value),
                       "shots": int(shots)}
    try:
        yield
    finally:
        with _lock:
            table[kind] = prev


def _indexed_consume(table: dict, index_key: str, kind: str,
                     index: int) -> Optional[float]:
    """One dispatch's view of an index-targeted fault slot: the armed
    value (decrementing the shot budget) when THIS index is the target,
    else None — a fault armed for lane/replica 1 is invisible to 0."""
    with _lock:
        st = table[kind]
        if (st is None or st["shots"] <= 0
                or st[index_key] != int(index)):
            return None
        st["shots"] -= 1
        return st["value"]


def _lane_armed(kind: str, lane: int, value: float, shots: int):
    """Lane-targeted fault slots (see `_indexed_armed`)."""
    return _indexed_armed(_lane_faults, "lane", kind, lane, value, shots)


def _lane_consume(kind: str, lane: int) -> Optional[float]:
    return _indexed_consume(_lane_faults, "lane", kind, lane)


def kill_lane(lane: int, shots: int = 1):
    """Arm a lane-worker kill: the targeted lane raises `LaneKilled` at
    its next ``shots`` dispatches, AFTER publishing the popped request as
    in-flight — the worker thread dies with the request stranded, the
    exact failure shape of a process/device loss mid-solve. Recovery is
    entirely the fleet supervisor's job (dead-thread detection ->
    quarantine -> rescue -> probe respawn)."""
    return _lane_armed("kill", lane, 0.0, shots)


def consume_kill(lane: int) -> bool:
    """True when this lane's dispatch must raise `LaneKilled`."""
    return _lane_consume("kill", lane) is not None


def wedge_lane(lane: int, wedge_s: float = 10.0, shots: int = 1):
    """Arm a non-cooperative lane wedge: the targeted lane blocks for up
    to ``wedge_s`` seconds at its next ``shots`` dispatches WITHOUT
    heartbeating or polling any control — indistinguishable from a hung
    device to the supervisor, which must evict it on heartbeat
    staleness. Bounded so an undetected wedge cannot hang a test; a
    wedged worker that finally wakes finds its lane generation stale and
    exits without touching the (already rescued) request."""
    return _lane_armed("wedge", lane, wedge_s, shots)


def consume_wedge(lane: int) -> Optional[float]:
    """The wedge bound in seconds for this lane's dispatch, or None."""
    return _lane_consume("wedge", lane)


def _replica_armed(kind: str, replica: int, value: float, shots: int):
    """Replica-targeted fault slots ride the SAME arm/restore/consume
    protocol as the lane slots (`_indexed_armed` — one copy of the
    lock/prev-save/shot-decrement dance), just against the replica
    table."""
    return _indexed_armed(_replica_faults, "replica", kind, replica,
                          value, shots)


def _replica_consume(kind: str, replica: int) -> Optional[float]:
    return _indexed_consume(_replica_faults, "replica", kind, replica)


def kill_replica(replica: int, shots: int = 1):
    """Arm a replica death for the federated router (`serve.router`):
    the targeted replica 'dies' right after its next ``shots`` routed
    submits land (the request is already write-ahead journaled — the
    exact durable state a process loss strands). For an in-process
    replica handle this is the simulated-SIGKILL lane
    (`SVDService._chaos_kill`: workers exit without serving, finalizing,
    or rescuing; queued requests stay as journal debt); the REAL
    process-loss twin is the subprocess drill's actual SIGKILL
    (tests/_router_worker.py). Recovery is entirely the router
    supervisor's job: dead-replica detection -> quarantine -> break the
    dead journal's lock -> rescue its debt onto a healthy replica ->
    probe the replica back to ACTIVE."""
    return _replica_armed("kill", replica, 0.0, shots)


def consume_replica_kill(replica: int) -> bool:
    """True when this replica must simulate death after this submit."""
    return _replica_consume("kill", replica) is not None


def wedge_replica(replica: int, wedge_s: float = 10.0, shots: int = 1):
    """Arm a replica wedge: the targeted replica's heartbeat FREEZES for
    up to ``wedge_s`` seconds starting at its next routed submit —
    indistinguishable from a hung process to the router supervisor,
    which must evict it on two-tier heartbeat staleness and rescue its
    journal debt. Bounded so an undetected wedge cannot hang a test; the
    underlying replica keeps running, so a post-wedge probe succeeds and
    the replica returns to ACTIVE (first-writer-wins absorbs anything it
    finished meanwhile, exactly like a woken wedged lane)."""
    return _replica_armed("wedge", replica, wedge_s, shots)


def consume_replica_wedge(replica: int) -> Optional[float]:
    """The wedge bound in seconds for this replica's submit, or None."""
    return _replica_consume("wedge", replica)


def poison_lane(lane: int, shots: int = 1):
    """Arm lane-scoped solve poison: the targeted lane's next ``shots``
    dispatches NaN-poison their padded working set before the stepper is
    built, so the solve surfaces ``SolveStatus.NONFINITE`` through the
    production health word (never a shortcut status). Drives the
    bad-outcome eviction ladder; once the shots run out, a recovery
    probe on the same lane solves clean."""
    return _lane_armed("poison", lane, 0.0, shots)


def consume_poison(lane: int) -> bool:
    """True when this lane's dispatch must poison its working set."""
    return _lane_consume("poison", lane) is not None


# Armed SIGKILL: {"after": int} — decremented once per SERVED dispatch
# (serve.SVDService consults `maybe_sigkill` right after a popped batch
# is published in flight and journaled as dispatched); at zero the
# process gets a REAL SIGKILL. No context manager: nothing survives to
# restore state, which is the point.
_sigkill_state: Optional[dict] = None


def sigkill_at_dispatch(after: int = 1) -> None:
    """Arm a SIGKILL to THIS process at its ``after``-th next served
    dispatch — the process-loss twin of `kill_lane` (which kills one
    worker THREAD and lets the fleet supervisor recover it; this kills
    the whole process so nothing in-memory survives). Delivered after
    the dispatch is journaled (`serve.journal`), so the durable state a
    restarted service replays is exactly "this request was in flight
    when the process died" — the restart-survivability lane's fixture.
    SIGKILL cannot be caught, so only subprocess tests
    (tests/test_restart.py) may arm this."""
    global _sigkill_state
    with _lock:
        _sigkill_state = {"after": int(after)}


def maybe_sigkill() -> None:
    """Deliver the armed SIGKILL when its dispatch countdown hits zero.
    A real `os.kill(..., SIGKILL)` — no handler, no cleanup, no final
    snapshot: the process vanishes mid-serve, exactly what the journal
    exists to survive."""
    global _sigkill_state
    with _lock:
        st = _sigkill_state
        if st is None:
            return
        st["after"] -= 1
        if st["after"] > 0:
            return
        _sigkill_state = None
    os.kill(os.getpid(), signal.SIGKILL)


def corrupt_compile_cache(cache_dir, mode: str = "flip") -> Path:
    """Deterministically corrupt one persistent-compile-cache entry (the
    largest executable file in ``cache_dir``, recursively — skipping the
    registry's ``CACHE_MANIFEST.json`` identity file, which has its own
    quarantine lane in `serve.registry.verify_cache`). Modes are
    `corrupt_checkpoint`'s. The contract under test: JAX degrades a
    corrupt cache ENTRY to a fresh compile with a loud warning — never a
    crash, never a deserialized garbage executable. Returns the
    corrupted path."""
    cache_dir = Path(cache_dir)
    entries = [p for p in cache_dir.rglob("*")
               if p.is_file() and p.name != "CACHE_MANIFEST.json"]
    if not entries:
        raise ValueError(f"no cache entries under {cache_dir} to corrupt")
    target = max(entries, key=lambda p: p.stat().st_size)
    return corrupt_checkpoint(target, mode)


@contextlib.contextmanager
def sigterm_at_sweep(sweep: int):
    """Arm a SIGTERM to THIS process at the end of checkpointed sweep
    ``sweep`` (1-based, matching `SweepState.sweeps`). One-shot."""
    global _sigterm_sweep
    with _lock:
        prev = _sigterm_sweep
        _sigterm_sweep = int(sweep)
    try:
        yield
    finally:
        with _lock:
            _sigterm_sweep = prev


def maybe_sigterm(sweeps_done: int) -> None:
    """Deliver the armed SIGTERM when the checkpoint loop reaches the
    armed sweep. Sends a REAL signal (os.kill to self) so the production
    SIGTERM machinery — handler, final snapshot, re-raise — is what gets
    exercised, not a shortcut."""
    global _sigterm_sweep
    with _lock:
        armed = _sigterm_sweep
        if armed is None or int(sweeps_done) != armed:
            return
        _sigterm_sweep = None
    os.kill(os.getpid(), signal.SIGTERM)


def corrupt_checkpoint(path, mode: str = "truncate") -> Path:
    """Deterministically corrupt a snapshot file in place.

    ``mode``:
      * "truncate" — keep only the first half of the file (torn write);
      * "flip"     — XOR one byte in the middle (bit rot / bad sector;
        defeats both the zip CRC and the payload checksum);
      * "zero"     — zero out a 64-byte span in the middle.
    Returns the path for chaining.
    """
    path = Path(path)
    data = bytearray(path.read_bytes())
    if not data:
        raise ValueError(f"cannot corrupt empty file {path}")
    mid = len(data) // 2
    if mode == "truncate":
        data = data[:mid]
    elif mode == "flip":
        data[mid] ^= 0xFF
    elif mode == "zero":
        data[mid:mid + 64] = bytes(min(64, len(data) - mid))
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    path.write_bytes(bytes(data))
    return path


def net_proxy(upstream, **faults):
    """Convenience handle on the network-chaos lane: a started
    `resilience.netfault.FaultyProxy` in front of ``upstream`` with
    ``faults`` pre-armed (kind -> shots, e.g. ``drop=2``; pass
    ``delay=(shots, seconds)`` for valued faults). The caller owns
    `stop()` — use it as a context manager::

        with chaos.net_proxy(server.address, drop=1) as proxy:
            replica = HttpReplica(0, proxy.address, journal_path)
    """
    from .netfault import FaultyProxy
    proxy = FaultyProxy(upstream)
    for kind, spec in faults.items():
        if isinstance(spec, tuple):
            shots, value = spec
            proxy.arm(kind, shots=int(shots), value=float(value))
        else:
            proxy.arm(kind, shots=int(spec))
    return proxy


# -- adversarial multi-tenant traffic mixes ---------------------------------

# The recognized adversary behaviours for `adversarial_tenant`:
#   * "flood"          — the abuser submits far faster than its fair
#     share, evenly spaced (steady-state overload: the rate limiter and
#     WFQ must hold the victim's goodput/p99).
#   * "burst"          — the same excess volume delivered in dense
#     bursts with quiet gaps (token-bucket burst credit + queue-depth
#     pressure: brownout pricing must shed the abuser first).
#   * "resubmit"       — byte-identical resubmit-heavy traffic (every
#     abuser submit reuses one matrix seed): with per-tenant cache keys
#     the abuser gets NO cross-tenant hits and keeps paying admission.
#   * "deadline_abuse" — every abuser request carries a huge deadline,
#     trying to exhaust the shared deadline budget; per-tenant budget
#     shares must keep the victim admitting.
ADVERSARY_MODES = ("flood", "burst", "resubmit", "deadline_abuse")


def adversarial_tenant(mode, *, n_victim=20, abuse_factor=5,
                       seed=0, abuser="mallory", victim="alice",
                       victim_interval_s=0.02,
                       abuse_deadline_s=3600.0):
    """Deterministic adversarial-tenant traffic schedule (the fairness
    drills' single source of truth — tests and `cli.py serve-demo
    --adversary` replay the SAME schedule for a given seed).

    Returns a list of submit events sorted by ``at_s`` (seconds from
    drill start), each a dict::

        {"at_s": float, "tenant": str, "mat_seed": int,
         "deadline_s": Optional[float], "resubmit": bool}

    ``mat_seed`` keys the matrix generator, so byte-identical resubmits
    are expressed as repeated seeds (``resubmit=True`` marks them); the
    driver owns actual matrix generation and submission. Determinism:
    same (mode, kwargs) -> same schedule, no randomness at fire time.
    """
    import random
    if mode not in ADVERSARY_MODES:
        raise ValueError(f"unknown adversary mode {mode!r} "
                         f"(known: {ADVERSARY_MODES})")
    rng = random.Random(int(seed))
    n_victim = int(n_victim)
    n_abuse = n_victim * int(abuse_factor)
    span = n_victim * float(victim_interval_s)
    events = []
    for i in range(n_victim):
        events.append({"at_s": i * float(victim_interval_s),
                       "tenant": str(victim),
                       "mat_seed": 10_000 + i,
                       "deadline_s": None, "resubmit": False})
    if mode == "flood":
        step = span / max(1, n_abuse)
        for j in range(n_abuse):
            events.append({"at_s": j * step, "tenant": str(abuser),
                           "mat_seed": 20_000 + j,
                           "deadline_s": None, "resubmit": False})
    elif mode == "burst":
        # Bursts of ~10 land together, gaps in between; the jitter
        # inside a burst is seeded, not timed.
        burst = 10
        n_bursts = max(1, n_abuse // burst)
        for b in range(n_bursts):
            t0 = (b + 0.5) * span / n_bursts
            for j in range(burst):
                events.append({"at_s": t0 + rng.uniform(0.0, 1e-3),
                               "tenant": str(abuser),
                               "mat_seed": 20_000 + b * burst + j,
                               "deadline_s": None, "resubmit": False})
    elif mode == "resubmit":
        step = span / max(1, n_abuse)
        for j in range(n_abuse):
            events.append({"at_s": j * step, "tenant": str(abuser),
                           "mat_seed": 20_000,       # SAME bytes each time
                           "deadline_s": None, "resubmit": j > 0})
    else:   # deadline_abuse
        step = span / max(1, n_abuse)
        for j in range(n_abuse):
            events.append({"at_s": j * step, "tenant": str(abuser),
                           "mat_seed": 20_000 + j,
                           "deadline_s": float(abuse_deadline_s),
                           "resubmit": False})
    events.sort(key=lambda e: (e["at_s"], e["tenant"], e["mat_seed"]))
    return events
