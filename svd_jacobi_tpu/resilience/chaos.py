"""Deterministic fault injection — the chaos half of the resilience layer.

The reference has no failure handling to test against (MPI errors are
printed and execution carries on, reference lib/JacobiMethods.cu:359-370,
614-616); this module provides the REPRODUCIBLE faults that prove the
detection/recovery machinery of this repo actually works:

  * `nan_at_sweep(k)` — arm an in-graph NaN payload: the next ``shots``
    fused solve dispatches poison one element of the working block stacks
    at the start of sweep ``k``. The hook is threaded through the fused
    entry points as a STATIC jit argument (`chaos_nan_sweep`, resolved by
    `solver._plan_entry` / `parallel.sharded._plan_entry` exactly like the
    telemetry flag), so the unarmed trace contains no injection code at
    all — `analysis.hlo_checks` rule HLO004 pins that property.
  * `sigterm_at_sweep(k)` — arm a SIGTERM delivered to THIS process at the
    end of checkpointed sweep ``k`` (`utils.checkpoint.svd_checkpointed`
    consults the hook once per sweep), driving the kill-then-resume lane.
  * `corrupt_checkpoint(path, mode)` — host-side snapshot corruption
    (truncation, byte flip, zeroing) for the checkpoint-hardening tests.
  * `slow_solve(per_sweep_s)` — arm a deterministic host-side delay per
    sweep for the next ``shots`` SERVED solve dispatches
    (`serve.SVDService` consults the hook once per dispatch and sleeps
    between sweeps), driving the deadline/brownout lanes: a slowed solve
    must cross its deadline at a sweep boundary and surface
    ``SolveStatus.DEADLINE``, never hang.
  * `stuck_backend()` — arm a wedged-backend stall: the next ``shots``
    served dispatches BLOCK before their first sweep, polling the
    request's cooperative deadline/cancel control, bounded by
    ``max_stall_s`` (a chaos hook must never be able to hang an
    un-deadlined test forever). Drives the circuit-breaker lane: stuck
    requests time out, consecutive timeouts trip the breaker, and
    recovery runs through the escalation ladder.

Everything here is deterministic: a hook fires at an exact sweep index /
byte offset, never at random, so chaos-lane failures replay exactly.
"""

from __future__ import annotations

import contextlib
import os
import signal
import threading
from pathlib import Path
from typing import Optional

_lock = threading.Lock()
# Armed state: {"sweep": int, "shots": int} or None. Shots bound how many
# solve DISPATCHES consume the payload (an escalation retry of the same
# matrix must be able to run clean — the point of the recovery test).
_nan_state: Optional[dict] = None
_sigterm_sweep: Optional[int] = None
# Serving-layer faults, one {"value": float, "shots": int} slot per kind
# ("slow": per-sweep delay seconds; "stuck": stall bound seconds) — both
# follow the same arm-context-manager / consume-one-shot protocol
# (`_armed` / `_consume`).
_serve_faults: dict = {"slow": None, "stuck": None}


@contextlib.contextmanager
def nan_at_sweep(sweep: int, shots: int = 1):
    """Arm the in-graph NaN payload for the next ``shots`` fused solves.

    ``sweep`` is the 0-based sweep-loop counter at whose body start the
    payload lands (the hybrid XLA solver restarts its counter per phase
    loop; the kernel path counts globally across bulk+polish). Detection
    is the health word's job: a poisoned solve must surface
    ``SolveStatus.NONFINITE``, never a silent ``OK``.
    """
    global _nan_state
    with _lock:
        prev = _nan_state
        _nan_state = {"sweep": int(sweep), "shots": int(shots)}
    try:
        yield
    finally:
        with _lock:
            _nan_state = prev


def consume_nan_sweep() -> Optional[int]:
    """One solve dispatch's view of the NaN hook: the armed sweep index
    (decrementing the shot budget) or None. Called by the entry planners;
    the returned value is part of the jit cache key, so an armed dispatch
    compiles a distinct (instrumented) program."""
    global _nan_state
    with _lock:
        st = _nan_state
        if st is None or st["shots"] <= 0:
            return None
        st["shots"] -= 1
        return st["sweep"]


def poison(x, sweeps, sweep_index: int):
    """Traced helper: overwrite one element of ``x`` with NaN when the
    loop counter ``sweeps`` equals the armed ``sweep_index`` (identity on
    every other sweep). Only ever traced when a dispatch consumed an armed
    hook — production programs never contain this op."""
    import jax.numpy as jnp
    idx = (0,) * x.ndim
    payload = jnp.where(sweeps == sweep_index,
                        jnp.asarray(jnp.nan, x.dtype), x[idx])
    return x.at[idx].set(payload)


@contextlib.contextmanager
def _armed(kind: str, value: float, shots: int):
    """Shared arm/restore protocol of the serving-layer fault slots."""
    with _lock:
        prev = _serve_faults[kind]
        _serve_faults[kind] = {"value": float(value), "shots": int(shots)}
    try:
        yield
    finally:
        with _lock:
            _serve_faults[kind] = prev


def _consume(kind: str) -> Optional[float]:
    """One served dispatch's view of a fault slot: the armed value
    (decrementing the shot budget) or None."""
    with _lock:
        st = _serve_faults[kind]
        if st is None or st["shots"] <= 0:
            return None
        st["shots"] -= 1
        return st["value"]


def slow_solve(per_sweep_s: float, shots: int = 1):
    """Arm a deterministic per-sweep host delay for the next ``shots``
    served solve dispatches. The serving worker consumes the hook once
    per dispatch (`consume_slow`) and sleeps ``per_sweep_s`` before each
    sweep of that dispatch — so the solve crosses any deadline at a sweep
    boundary, exactly where the cooperative control checks run. Pure
    host-side: the compiled program is untouched."""
    return _armed("slow", per_sweep_s, shots)


def consume_slow() -> Optional[float]:
    """The slow-solve hook's per-sweep delay in seconds, or None."""
    return _consume("slow")


def stuck_backend(shots: int = 1, max_stall_s: float = 30.0):
    """Arm a wedged-backend stall for the next ``shots`` served solve
    dispatches: each armed dispatch blocks before its first sweep,
    cooperatively polling the request's deadline/cancel control, for at
    most ``max_stall_s`` seconds (the bound exists so an un-deadlined
    test cannot hang forever — a real wedged backend has no such mercy,
    which is what deadlines are for). A deadlined stuck request surfaces
    ``SolveStatus.DEADLINE`` through the production control path."""
    return _armed("stuck", max_stall_s, shots)


def consume_stuck() -> Optional[float]:
    """The stuck-backend hook's stall bound in seconds, or None."""
    return _consume("stuck")


@contextlib.contextmanager
def sigterm_at_sweep(sweep: int):
    """Arm a SIGTERM to THIS process at the end of checkpointed sweep
    ``sweep`` (1-based, matching `SweepState.sweeps`). One-shot."""
    global _sigterm_sweep
    with _lock:
        prev = _sigterm_sweep
        _sigterm_sweep = int(sweep)
    try:
        yield
    finally:
        with _lock:
            _sigterm_sweep = prev


def maybe_sigterm(sweeps_done: int) -> None:
    """Deliver the armed SIGTERM when the checkpoint loop reaches the
    armed sweep. Sends a REAL signal (os.kill to self) so the production
    SIGTERM machinery — handler, final snapshot, re-raise — is what gets
    exercised, not a shortcut."""
    global _sigterm_sweep
    with _lock:
        armed = _sigterm_sweep
        if armed is None or int(sweeps_done) != armed:
            return
        _sigterm_sweep = None
    os.kill(os.getpid(), signal.SIGTERM)


def corrupt_checkpoint(path, mode: str = "truncate") -> Path:
    """Deterministically corrupt a snapshot file in place.

    ``mode``:
      * "truncate" — keep only the first half of the file (torn write);
      * "flip"     — XOR one byte in the middle (bit rot / bad sector;
        defeats both the zip CRC and the payload checksum);
      * "zero"     — zero out a 64-byte span in the middle.
    Returns the path for chaining.
    """
    path = Path(path)
    data = bytearray(path.read_bytes())
    if not data:
        raise ValueError(f"cannot corrupt empty file {path}")
    mid = len(data) // 2
    if mode == "truncate":
        data = data[:mid]
    elif mode == "flip":
        data[mid] ^= 0xFF
    elif mode == "zero":
        data[mid:mid + 64] = bytes(min(64, len(data) - mid))
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    path.write_bytes(bytes(data))
    return path
