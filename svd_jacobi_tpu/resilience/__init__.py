"""svd_jacobi_tpu.resilience — fail loudly, degrade gracefully, survive.

The resilience layer on top of the solver (PR 1 built the observability it
reports through, PR 2 the contract checks that keep it honest):

  * in-graph solve health — the fused sweep loops carry a cheap health
    word (non-finite detection riding the existing dmax2/off-norm
    reductions) that `solver._status_word` decodes into
    `SVDResult.status` / `SolveStatus` (``OK | MAX_SWEEPS | STAGNATED |
    NONFINITE``); a NaN-poisoned solve can no longer masquerade as a
    converged one (the deflation mask silently drops NaN columns from the
    convergence statistic — exactly the failure this closes);
  * `guard` — pre-solve input screening + exact power-of-two pre-scaling
    for extreme-scale inputs (the Gram path squares the data scale);
  * `resilient_svd` (`escalate`) — bounded retry/escalation ladder
    reacting to a bad status, recorded as ``"retry"`` manifest records;
  * `chaos` — deterministic fault injection (in-graph NaN payloads,
    checkpoint corruption, SIGTERM mid-solve) powering the ``-m chaos``
    pytest lane that proves detection, recovery, and kill-then-resume
    end-to-end.

This module is import-light (the escalation orchestrator pulls the solver
in lazily) because `solver` itself imports `chaos` to thread the
fault-injection jit key.
"""

from __future__ import annotations

from . import chaos  # noqa: F401  (import-light; solver depends on it)

_LAZY = {
    "resilient_svd": ("escalate", "resilient_svd"),
    "DEFAULT_RUNGS": ("escalate", "DEFAULT_RUNGS"),
    "screen": ("guard", "screen"),
    "prescale": ("guard", "prescale"),
    "unscale_sigma": ("guard", "unscale_sigma"),
    "NonFiniteInputError": ("guard", "NonFiniteInputError"),
}


def __getattr__(name: str):
    if name == "SolveStatus":
        from ..solver import SolveStatus
        return SolveStatus
    if name in _LAZY:
        import importlib
        mod, attr = _LAZY[name]
        return getattr(importlib.import_module(f".{mod}", __package__), attr)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = ["chaos", "resilient_svd", "DEFAULT_RUNGS", "screen", "prescale",
           "unscale_sigma", "NonFiniteInputError", "SolveStatus"]
