"""Guarded inputs: pre-solve screening and power-of-two pre-scaling.

The solver's convergence statistics are built from Gram couplings, which
are measured against sigma_max^2 — so an f32 input whose entries sit near
2^60 overflows the Gram path (column norms square to inf) and an input
near 2^-60 underflows the deflation floor (``dmax2 * (n*eps)^2`` rounds
to zero and the null-column mask misfires). Both regimes are PERFECTLY
conditioned problems that merely live at a bad absolute scale.

The guard fixes scale without touching conditioning: multiply the input
by an exact power of two chosen so ``max|a_ij|`` lands near 1.0, solve,
and undo the scale on the returned sigmas. A power-of-two multiply is
exact in every binary float format — U and V are bit-identical to the
unscaled solve's factors and sigma is exactly ``2^p`` times off, so the
undo is lossless.

Screening rejects non-finite inputs up front (`NonFiniteInputError`): a
NaN/Inf payload in the input can never be recovered by re-running, so the
escalation ladder must fail fast instead of burning four solves.
"""

from __future__ import annotations

import math
from typing import Tuple


class NonFiniteInputError(ValueError):
    """The input matrix contains NaN/Inf — no solver configuration can
    recover this; fix the producer (the screening happens BEFORE any
    solve is spent)."""


def _safe_exp(dtype) -> int:
    """|log2(max|a|)| above which the Gram path is at risk in ``dtype``:
    couplings square the data scale and carry an ~n factor, so keep
    sigma_max^2 comfortably inside the exponent range (one third of
    maxexp leaves headroom for both the square and the deflation
    floor's (n*eps)^2 factor)."""
    import jax.numpy as jnp
    return int(jnp.finfo(jnp.dtype(dtype)).maxexp) // 3


def _pow2(p: int, dtype):
    """2.0**p as an exact ``dtype`` scalar (p within the dtype's range)."""
    import jax.numpy as jnp
    return jnp.asarray(2.0, jnp.dtype(dtype)) ** p


def _apply_pow2(x, p: int):
    """x * 2^p in two half-steps so neither intermediate scalar leaves
    the dtype's normal range (2^-127 is subnormal in f32; splitting the
    exponent keeps every factor normal and the product exact)."""
    if p == 0:
        return x
    h = p // 2
    return (x * _pow2(h, x.dtype)) * _pow2(p - h, x.dtype)


def screen(a) -> dict:
    """Pre-solve health report of an input matrix (host-side, one pass):
    ``{"finite": bool, "amax": float, "scale_pow2": int}`` where
    ``scale_pow2`` is the exact power-of-two exponent `prescale` would
    apply (0 when the input's scale is already safe)."""
    import jax.numpy as jnp

    from ..utils._exec import host_scalar

    finite = bool(host_scalar(jnp.isfinite(a).all()))
    amax = float(host_scalar(jnp.max(jnp.abs(a)))) if finite else math.inf
    scale = 0
    if finite and amax > 0.0:
        # frexp: amax = frac * 2^e with frac in [0.5, 1) — e is the
        # power-of-two bucket of the data scale.
        e = math.frexp(amax)[1]
        if abs(e) > _safe_exp(a.dtype):
            scale = -e
    return {"finite": finite, "amax": amax, "scale_pow2": scale}


def prescale(a, *, require_finite: bool = True) -> Tuple[object, int]:
    """Screen ``a`` and return ``(a_scaled, p)`` with
    ``a_scaled = a * 2^p`` brought to a Gram-safe scale (``p = 0`` and
    ``a`` returned untouched when already safe). Raises
    `NonFiniteInputError` on NaN/Inf input unless ``require_finite`` is
    False."""
    rep = screen(a)
    if require_finite and not rep["finite"]:
        raise NonFiniteInputError(
            "input matrix contains non-finite entries (NaN/Inf); no solver "
            "escalation can recover this — screen or repair the input")
    p = rep["scale_pow2"]
    return (_apply_pow2(a, p) if p else a), p


def unscale_sigma(s, p: int):
    """Undo `prescale` on the returned singular values: the factors of
    ``2^p * A`` equal those of ``A`` exactly, and sigma is exactly
    ``2^p`` scaled — multiply by ``2^-p`` (exact)."""
    return _apply_pow2(s, -p) if p else s
