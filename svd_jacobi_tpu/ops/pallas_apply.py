"""Fused pair-apply (+ tournament exchange) Pallas TPU kernel.

The XLA form of a cross round's update is a chain of full-stack HBM
round-trips per rotation round:

    x  = concat([top, bot], -1)      # write + read a (k, m, 2b) copy
    xn = x @ q                       # the only real work
    top, bot = xn[..:b], xn[..b:]    # read + write two more copies
    top, bot = rotate_blocks(...)    # read + write two more copies

measured at 8192^2 f32 as ~190 ms/sweep of data movement against ~45 ms of
matmul FLOPs (PROFILE.md item 8); splitting the concat into four XLA block
matmuls makes it WORSE (the adds cannot fuse into dot epilogues — measured
26% slower end-to-end). This kernel fuses the whole chain: each grid step
reads the two source blocks of one output slot, computes

    new_top[i] = top[pt(i)] @ qt[i][:b] + bot[pt(i)] @ qt[i][b:]
    new_bot[i] = top[pb(i)] @ qb[i][:b] + bot[pb(i)] @ qb[i][b:]

with both adds in VMEM, and writes each result DIRECTLY into its
post-exchange slot — the (pt, pb, strip) maps encode the tournament
rotation (parallel/schedule.py:rotate_blocks), so the separate permute
copies disappear as well. HBM traffic per round drops from ~8 full-stack
reads + 8 writes to 2 reads + 1 write (the two-source reads overlap).

Reference lineage: this is the TPU replacement for the reference's
per-rotation column update `jacobi_rotation` + host bookkeeping
(lib/JacobiMethods.cu:479-510) at block granularity; the exchange fusion
replaces its per-round re-distribution of columns (lib/JacobiMethods.cu:
334-432) with an index-map permutation inside one kernel launch.

Compiled paths only: the single-device solver fuses apply AND exchange;
the compiled mesh solver fuses the apply (``exchange=False``) and keeps
its exchange as the `lax.ppermute` ICI hop outside the kernel. Interpreter
backends use the jnp reference semantics in ops/rounds.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

HI = jax.lax.Precision.HIGHEST


class VmemBudgetError(ValueError):
    """A Pallas lane's per-grid-step working set exceeds the scoped-VMEM
    budget for the requested geometry.

    Subclasses ValueError so pre-existing handlers keep working, but
    carries enough structure (``lane``, ``fallback``) for the serve
    dispatch to treat it as a RETRYABLE capability miss — route the
    request down the escalation ladder onto ``fallback`` instead of
    failing the request with ERROR."""

    def __init__(self, message: str, *, lane: str, fallback: str):
        super().__init__(message)
        self.lane = lane
        self.fallback = fallback


def _perm_maps(k: int, exchange: bool, batch: int = 1):
    """(pair_t, top_half_t, pair_b, top_half_b) for output slots i in [0, k).

    With ``exchange``, output slot maps encode one tournament rotation
    (schedule.rotate_blocks): new_top[0] = old pair 0's top result,
    new_top[1] = old pair 0's bottom result, new_top[i>=2] = pair i-1's top,
    new_bot[i<=k-2] = pair i+1's bottom, new_bot[k-1] = pair k-1's top.
    Without it, slot i is just pair i's (top, bottom) result.

    ``batch``: the stack holds ``batch`` matrices' slots back to back
    (``k = batch * k_per``) and the rotation is block-diagonal per matrix
    — each segment rotates within itself, exactly
    `schedule.rotate_blocks(..., batch)`. The ``batch == 1`` maps are the
    same formulas with a single segment.
    """
    idx = np.arange(k)
    kp = k // batch
    if not exchange or kp == 1:
        return idx, np.ones(k, bool), idx, np.zeros(k, bool)
    j = idx % kp
    pair_t = np.where(j <= 1, idx - j, idx - 1)
    top_half_t = j != 1
    pair_b = np.where(j <= kp - 2, idx + 1, idx)
    top_half_b = j == kp - 1
    return pair_t, top_half_t, pair_b, top_half_b


def _kernel(xtt_ref, xbt_ref, xtb_ref, xbb_ref, qt_ref, qb_ref,
            out_t_ref, out_b_ref, *refs, b, x3, with_gram=False,
            gram_bf16=False):
    f32 = jnp.float32
    bf16 = jnp.bfloat16

    def raw(x, w, prec):
        return jax.lax.dot_general(x, w, (((1,), (0,)), ((), ())),
                                   precision=prec, preferred_element_type=f32)

    def split(x):
        # BIT-MASK the low mantissa half, like rounds._split_bf16: the
        # naive cast-round-trip form is folded to zero by XLA (verified
        # on-chip) and nothing stops Mosaic from learning the same
        # simplification.
        bits = jax.lax.bitcast_convert_type(x, jnp.uint32)
        hi = jax.lax.bitcast_convert_type(
            bits & jnp.uint32(0xFFFF0000), f32)
        return hi.astype(bf16), (x - hi).astype(bf16)

    if xtt_ref.dtype == bf16:
        if x3 and qt_ref.dtype == f32:
            # bf16-STORED stacks under the mixed regime: the stack side
            # already paid its eps_bf16 storage rounding, but the q side
            # must NOT — a bf16-cast q floors every rotation angle at
            # eps_bf16 and stalls the bulk at ~5e-3 coupling (measured:
            # the bulk then hands the polish 8 sweeps instead of 4). Split
            # the f32 q into hi+lo bf16 halves: two native passes, q error
            # ~eps_bf16^2, angle accuracy restored.
            def mm(x, w):
                wh, wl = split(w)
                return raw(x, wh, None) + raw(x, wl, None)
        else:
            # Plain bf16 solves (bf16 INPUT dtype, bf16-class accuracy):
            # one native bf16-in/f32-acc pass (HIGHEST is an f32-operand
            # notion — Mosaic rejects it on bf16).
            mm = lambda x, w: raw(x, w, None)
    elif x3:
        # bf16x3 split product (the f32-stored mixed-bulk regime):
        # ~eps_bf16^2 error at 3 native passes — rotations applied this
        # way keep the accumulated product orthogonal to ~1e-4 over a
        # whole solve.
        def mm(x, w):
            xh, xl = split(x)
            wh, wl = split(w)
            return raw(xh, wh, None) + (raw(xl, wh, None) + raw(xh, wl, None))
    else:
        mm = lambda x, w: raw(x.astype(f32), w, HI)

    def dot2(xt, xb, q):
        return mm(xt, q[:b]) + mm(xb, q[b:])

    new_t = dot2(xtt_ref[0], xbt_ref[0], qt_ref[0])     # (mc, b) f32
    new_b = dot2(xtb_ref[0], xbb_ref[0], qb_ref[0])
    out_t_ref[0] = new_t.astype(out_t_ref.dtype)
    out_b_ref[0] = new_b.astype(out_b_ref.dtype)

    if with_gram:
        # Epilogue: accumulate the NEXT round's Gram panel for output pair
        # i from the freshly rotated chunks already in VMEM — this deletes
        # the separate gram kernel's full-stack read (ops/pallas_gram.py
        # semantics: f32 accumulators resident across the trailing
        # row-chunk grid axis, which TPU iterates innermost).
        from jax.experimental import pallas as pl

        gxx_ref, gxy_ref, gyy_ref = refs
        mi = pl.program_id(1)

        @pl.when(mi == 0)
        def _init():
            gxx_ref[...] = jnp.zeros_like(gxx_ref)
            gxy_ref[...] = jnp.zeros_like(gxy_ref)
            gyy_ref[...] = jnp.zeros_like(gyy_ref)

        if gram_bf16:
            gt, gb = new_t.astype(bf16), new_b.astype(bf16)
            gprec = None
        else:
            gt, gb = new_t, new_b
            gprec = HI
        gdot = lambda p, r: jax.lax.dot_general(
            p, r, (((0,), (0,)), ((), ())), precision=gprec,
            preferred_element_type=f32)[None]
        gxx_ref[...] += gdot(gt, gt)
        gxy_ref[...] += gdot(gt, gb)
        gyy_ref[...] += gdot(gb, gb)


def _chunk_limit(b: int, row_blocks: int = 6, fixed_bytes: int = None) -> int:
    """Row-chunk cap so one grid step fits scoped VMEM (~13 MB usable,
    halved for Mosaic double-buffering). The apply kernel holds 6 (mc, b)
    x/out blocks plus 2 (2b, b) q strips per step; the gram kernel
    (ops/pallas_gram.py) passes its own smaller footprint. Shrinks with
    the panel width the way pallas_blocks._pick_block_k does — a user
    block_size of 512+ must not push a kernel over the budget the unfused
    path respects."""
    if fixed_bytes is None:
        fixed_bytes = 2 * (2 * b) * b * 4          # the two q strips
    budget = (13 << 20) // 2
    per_row = row_blocks * b * 4
    return max(0, min(1024, (budget - fixed_bytes) // per_row)) // 8 * 8


def _pick_chunk(m: int, b: int, row_blocks: int = 6,
                fixed_bytes: int = None) -> int:
    """Largest sublane-aligned divisor of m within the VMEM chunk limit
    (the kernel grids over row chunks; a divisor avoids relying on masked
    partial blocks). 0 if none is usable."""
    best = 0
    limit = _chunk_limit(b, row_blocks, fixed_bytes)
    for c in range(8, min(m, limit) + 1, 8):
        if m % c == 0:
            best = c
    return best


def _gram_fixed_bytes(b: int) -> int:
    # q strips + the 3 f32 gram accumulators of the with_gram epilogue.
    return 2 * (2 * b) * b * 4 + 3 * b * b * 4


def supported(m: int, b: int) -> bool:
    """The fused kernel needs lane-sized panels and a usable row chunk
    (gated on the LARGER with_gram footprint so one gate covers both
    call forms)."""
    return b % 128 == 0 and _pick_chunk(m, b, 6, _gram_fixed_bytes(b)) >= 128


@functools.partial(jax.jit, static_argnames=("exchange", "interpret", "vma",
                                             "x3", "with_gram", "gram_bf16",
                                             "batch"))
def apply_exchange(top, bot, q, *, exchange: bool = True,
                   interpret: bool = False, vma=None, x3: bool = False,
                   with_gram: bool = False, gram_bf16: bool = False,
                   batch: int = 1):
    """(new_top, new_bot[, g]) = post-exchange stacks of ([top|bot] @ q).

    top/bot: (k, m, b) column stacks; q: (k, 2b, 2b) orthogonal panels.
    Equivalent (tested) to the concat/matmul/slice + rotate_blocks chain.

    ``batch`` (static): the stacks hold ``batch`` matrices back to back
    (``k = batch * k_per``) and the in-kernel exchange is block-diagonal
    per matrix (the batched-solve lane) — same kernel body, the index
    maps pick the per-segment sources. NO new grid dimension: the pairs of
    every matrix ride the existing pair axis, so B matrices cost one
    kernel launch and one latency chain, not B.

    ``with_gram`` (requires ``exchange``): additionally return the
    (k, 2b, 2b) Gram panels of the POST-exchange pairs, accumulated in the
    kernel's epilogue from the chunks already in VMEM — the next round's
    panels at no extra HBM reads (``gram_bf16``: single-pass bf16
    contraction, the mixed-bulk regime).

    ``vma``: mesh axes the outputs vary over — required when called on
    LOCAL stacks inside a compiled shard_map region (the mesh solver uses
    ``exchange=False`` there: its exchange is a ppermute ICI hop that runs
    outside the kernel). Mirrors the convention of ops/pallas_blocks.py.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if with_gram and not exchange:
        raise ValueError("with_gram accumulates the post-EXCHANGE pairs' "
                         "panels; it requires exchange=True")
    k, m, b = top.shape
    if batch < 1 or k % batch:
        raise ValueError(f"stack of {k} pair slots does not divide into "
                         f"batch={batch} equal segments")
    mc = _pick_chunk(m, b, 6,
                     _gram_fixed_bytes(b) if with_gram else None)
    if mc == 0:
        raise VmemBudgetError(
            f"no usable VMEM row chunk for the 'pallas_apply."
            f"apply_exchange' kernel lane at (m, b) = ({m}, {b}) "
            f"with_gram={with_gram} — the per-step footprint exceeds the "
            f"scoped-VMEM budget; gate callers on "
            f"pallas_apply.supported() or fall back to "
            f"pair_solver='block_rotation'",
            lane="pallas_apply.apply_exchange", fallback="block_rotation")
    pair_t, top_half_t, pair_b, top_half_b = _perm_maps(k, exchange, batch)
    # Per-output-slot (2b, b) strips of q, gathered OUTSIDE the kernel
    # (q is (k, 2b, 2b) — tiny next to the stacks).
    ql, qr = q[..., :b], q[..., b:]
    # Match the q strips to the stacks' compute dtype (see _kernel): bf16
    # for plain bf16 solves, but f32 for bf16-STORED stacks under x3 — the
    # kernel splits that q into two bf16 passes (qx2) to keep rotation
    # angles at eps_bf16^2 accuracy.
    qdt = (jnp.bfloat16 if top.dtype == jnp.bfloat16 and not x3
           else jnp.float32)
    qt = jnp.where(jnp.asarray(top_half_t)[:, None, None],
                   jnp.take(ql, jnp.asarray(pair_t), axis=0),
                   jnp.take(qr, jnp.asarray(pair_t), axis=0)).astype(qdt)
    qb = jnp.where(jnp.asarray(top_half_b)[:, None, None],
                   jnp.take(ql, jnp.asarray(pair_b), axis=0),
                   jnp.take(qr, jnp.asarray(pair_b), axis=0)).astype(qdt)

    # Closed-form slot maps (index maps run as scalar-core programs; no
    # table gathers): with exchange, pt(i) = 0 for i <= 1 else i - 1 and
    # pb(i) = min(i + 1, k - 1); identity otherwise. Batched stacks use
    # the segment-local forms (j = i mod k_per picks the position inside
    # the slot's own matrix; the batch == 1 branch keeps the original
    # spelling so existing lowerings are untouched).
    kp = k // batch
    if exchange and kp > 1:
        if batch == 1:
            pt_fn = lambda i: jnp.where(i <= 1, 0, i - 1)
            pb_fn = lambda i: jnp.minimum(i + 1, k - 1)
        else:
            pt_fn = lambda i: jnp.where(i % kp <= 1, (i // kp) * kp, i - 1)
            pb_fn = lambda i: jnp.where(i % kp == kp - 1, i, i + 1)
    else:
        pt_fn = pb_fn = lambda i: i
    x_spec = lambda pair_fn: pl.BlockSpec(
        (1, mc, b), lambda i, mi: (pair_fn(i), mi, 0),
        memory_space=pltpu.VMEM)
    q_spec = pl.BlockSpec((1, 2 * b, b), lambda i, mi: (i, 0, 0),
                          memory_space=pltpu.VMEM)
    o_spec = pl.BlockSpec((1, mc, b), lambda i, mi: (i, mi, 0),
                          memory_space=pltpu.VMEM)
    from .pallas_blocks import _out_struct
    out = _out_struct((k, m, b), top.dtype, vma)
    out_specs = [o_spec, o_spec]
    out_shapes = [out, out]
    if with_gram:
        g_spec = pl.BlockSpec((1, b, b), lambda i, mi: (i, 0, 0),
                              memory_space=pltpu.VMEM)
        g_out = _out_struct((k, b, b), jnp.float32, vma)
        out_specs += [g_spec] * 3
        out_shapes += [g_out] * 3
    results = pl.pallas_call(
        functools.partial(_kernel, b=b, x3=x3, with_gram=with_gram,
                          gram_bf16=gram_bf16),
        grid=(k, m // mc),
        in_specs=[x_spec(pt_fn), x_spec(pt_fn), x_spec(pb_fn), x_spec(pb_fn),
                  q_spec, q_spec],
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=interpret,
    )(top, bot, top, bot, qt, qb)
    if not with_gram:
        return results[0], results[1]
    new_top, new_bot, gxx, gxy, gyy = results
    top_row = jnp.concatenate([gxx, gxy], axis=-1)
    bot_row = jnp.concatenate([gxy.transpose(0, 2, 1), gyy], axis=-1)
    g = jnp.concatenate([top_row, bot_row], axis=-2)
    return new_top, new_bot, g
