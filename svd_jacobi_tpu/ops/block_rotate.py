"""MXU-native blocked-rotation accumulate + apply (pair_solver="block_rotation").

The rotation kernel of the Pallas lane is latency-bound (PROFILE.md
item 1): every tournament round pays a sequential chain of b elementwise
rotation steps whose per-step cost is ~constant whatever the panel count,
so at 2048^2 f32 the MXU sees ~1.7% utilization (BENCH_r04). This module
restructures the round the way cuSOLVER's gesvdj and the Brent-Luk
blocked-Jacobi formulation do:

  * `accumulate` — solve each block pair's FULL 2b x 2b Gram subproblem
    on-chip and accumulate every rotation of the inner cycle into ONE
    orthogonal 2b x 2b factor J. The inner cycle is delegated to the
    batched symmetric eigendecomposition: on TPU, XLA's `eigh` IS a
    cyclic Jacobi iteration (matmul-heavy MXU work), i.e. the full inner
    Jacobi cycle run to convergence with the rotations accumulated into
    the eigenvector factor. J is then permuted/sign-fixed nearest to the
    identity (the small-angle condition that keeps the outer tournament
    convergent — see `ops.blockwise._nearest_identity_order`) and
    re-orthogonalized to the f32 floor with one Newton-Schulz step, so
    hundreds of applied factors cannot erode U/V.
  * `apply_factor` — apply J to the two m x b column panels (and the
    matching V panels) as ONE rank-2b matmul per pair, batched along the
    pair axis: the MXU sees (m, 2b) x (2b, 2b) GEMMs stacked over all
    n/(2b) pairs of the round, instead of 2b-1 latency-bound rotation
    steps each touching the panel. The contraction honors the mixed-store
    gate: ``x3`` runs the bf16x3 split product (3 native bf16 passes,
    ~eps_bf16^2 error — safe in the bulk phase, whose state the f32
    polish re-converges) so bf16 accumulation composes.

Because the subproblem solve is eigh-quality it converges only to the
ABSOLUTE (sigma_max-relative) class — couplings between small-norm
columns are left at the eigh floor. The lane therefore runs these rounds
as a BULK phase against the abs statistic and hands the endgame to the
existing scalar-accurate rotation kernel (`ops.rounds.iterate` — the
fallback lane), which restores dgesvj-class relative accuracy; the sweep
machinery lives in `ops.rounds.sweep_block` / `iterate_block`.

Numerically SINGULAR input caveat (shared with the abs-class XLA lanes —
hybrid/gram-eigh/qr-svd, whose column-read factor shows the same
property): the factor read off the rotated COLUMNS (V on the
preconditioned path) is orthonormal on numerically-LIVE columns only.
The bulk's large-angle factors are applied as f32 GEMMs, and a
dead-column output (true content below ~eps*sigma_max of its panel) is
the cancellation residue of large terms — noise whose common component
parallels the dead columns; the pallas lane's exactly-scaled tiny
angles never cancel, which is why it alone keeps dead columns
orthonormal. Sigma accuracy, the residual, U (the rotation-product
side), and live-column V orthogonality are unaffected —
`utils.validation`'s `v_orth_live`/`u_orth_live` are the meaningful
metrics there, exactly as documented for the XLA lanes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _apply_einsum(x, q, *, x3=False):
    """The rank-2b panel contraction ``x @ q`` at one of two regimes:
    f32 HIGHEST (the default), or the bf16x3 split product
    hi@hi + lo@hi + hi@lo (~eps_bf16^2 error, 3 native MXU passes — the
    mixed-store composition regime). The split is `rounds._split_bf16`
    (ONE copy of the numerically subtle bit-mask construction; imported
    lazily — rounds imports this module at its own top level)."""
    if x3:
        from .rounds import _split_bf16
        xh, xl = _split_bf16(x.astype(jnp.float32))
        qh, ql = _split_bf16(q.astype(jnp.float32))
        f = lambda p, w: jnp.einsum("kmi,kij->kmj", p, w,
                                    preferred_element_type=jnp.float32)
        return f(xh, qh) + (f(xl, qh) + f(xh, ql))
    return jnp.einsum("kmi,kij->kmj", x, q,
                      precision=jax.lax.Precision.HIGHEST,
                      preferred_element_type=jnp.float32)


def accumulate(g: jax.Array) -> jax.Array:
    """Accumulated orthogonal factors J of a round's Gram panel stack.

    ``g``: (k, 2b, 2b) symmetric Gram panels (one per block pair).
    Returns (k, 2b, 2b) f32 J with ``X @ J`` exactly orthogonalizing each
    pair's 2b columns to the subproblem solve's accuracy: the full inner
    Jacobi cycle on the Gram subproblem (batched `eigh` — XLA's TPU eigh
    is a cyclic Jacobi iteration accumulating rotations into the
    eigenvector factor), nearest-identity ordered (small-angle outer
    convergence; descending eigenvalues embed de-Rijk norm sorting before
    the reorder) and Newton-Schulz re-orthogonalized to the f32 floor.
    """
    from ..obs.scopes import scope
    from . import blockwise
    with scope("block_solve"):
        _, q = jnp.linalg.eigh(g.astype(jnp.float32))
        q = blockwise._nearest_identity_order(q)
        return blockwise._newton_schulz_polish(
            q, jax.lax.Precision.HIGHEST)


def apply_factor(top, bot, vtop, vbot, q, *, x3=False):
    """Apply one round's accumulated factors to the panel stacks as ONE
    rank-2b GEMM per pair: ``[top|bot] @ q`` (and the V stacks alongside),
    batched along the pair axis. ``vtop``/``vbot`` may be None (NoVec).
    This is the whole point of the lane: the 2b-1 rotation steps of the
    inner cycle never touch the m-height panels — the panels see exactly
    one matmul per pair per round."""
    b = top.shape[-1]
    xn = _apply_einsum(jnp.concatenate([top, bot], axis=-1), q,
                       x3=x3).astype(top.dtype)
    top, bot = xn[..., :b], xn[..., b:]
    if vtop is not None:
        vn = _apply_einsum(jnp.concatenate([vtop, vbot], axis=-1), q,
                           x3=x3).astype(vtop.dtype)
        vtop, vbot = vn[..., :b], vn[..., b:]
    return top, bot, vtop, vbot
