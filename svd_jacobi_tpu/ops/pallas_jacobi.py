"""Pallas TPU kernel: per-round Jacobi rotation generation on Gram panels.

This is the framework's device kernel — the TPU-native replacement for the
reference's CUDA `jacobi_rotation` (reference: lib/JacobiMethods.cu:1483-1491,
launched per column pair with 8 host<->device memcpys around it,
lib/JacobiMethods.cu:479-510). Design (SURVEY.md section 7 step 3):

The outer block-Jacobi round hands each paired column panel's Gram matrix
``G = X^T X`` (shape (n2, n2), n2 = 2b) to this kernel. The kernel runs a
FULL inner tournament — n2-1 steps of b2 = n2/2 disjoint scalar Givens
rotations, each from the Rutishauser formula the reference uses
(lib/JacobiMethods.cu:466-478) — applying them two-sidedly to G (a congruence
G <- J^T G J, which tracks exactly what the rotations do to the columns'
inner products) while accumulating the orthogonal transform Q. One kernel
invocation therefore rotates EVERY column pair inside the panel exactly once,
entirely in VMEM with no XLA-op dispatch per step; the caller applies the
single accumulated Q to the tall column panel (and V) on the MXU.

Why not `jnp.linalg.eigh`/`svd` on the panels (round 1's approach):
  * XLA's TPU eigh/svd lower through QDWH with internal while-loops whose
    convergence flags are replicated scan carries — inside `shard_map` with
    variance checking they fail to lower at all (the round-1 reason for
    `check_vma=False`);
  * they converge to an absolute tolerance, so couplings between
    small-norm columns come back unresolved and the outer loop stalls —
    round 1 needed a hybrid polish phase + a sequential scalar cleanup scan;
  * measured on chip, the batched small eigh/svd dominate round time while
    doing no MXU work.
Scalar rotations computed directly from (alpha, beta, gamma) are accurate at
ANY scale (the reason sgesvj delivers high relative accuracy), every 2x2 is
exactly orthogonal, and Q is their product — no Newton-Schulz polish, no
cleanup sweep, one method for bulk and endgame.

The tournament inside the kernel is the same circle-method rotation as
parallel/schedule.py (data moves, pairing is fixed at slots (i, b2+i)); after
n2-1 steps the layout returns to the initial order, so Q maps original slots
to original slots (property-tested in tests/test_pallas.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _rotate_cols(top, bot):
    """Circle-method rotation (slot 0 fixed) on the last axis."""
    if top.shape[-1] == 1:
        return top, bot
    new_top = jnp.concatenate([top[..., :1], bot[..., :1], top[..., 1:-1]], axis=-1)
    new_bot = jnp.concatenate([bot[..., 1:], top[..., -1:]], axis=-1)
    return new_top, new_bot


def _rotate_rows(top, bot):
    """The same rotation on the first axis (rows of the Gram panel)."""
    if top.shape[0] == 1:
        return top, bot
    new_top = jnp.concatenate([top[:1], bot[:1], top[1:-1]], axis=0)
    new_bot = jnp.concatenate([bot[1:], top[-1:]], axis=0)
    return new_top, new_bot


def _kernel_body(g, dmax2, *, n_steps: int):
    """Pure-jnp inner tournament on one Gram panel -> (q, max_rel).

    Runs both inside the Pallas kernel (on VMEM-resident values) and under
    the Pallas interpreter as the CPU reference implementation.
    """
    n2 = g.shape[-1]
    b2 = n2 // 2
    f32 = jnp.float32
    g = g.astype(f32)
    eps = jnp.finfo(f32).eps
    tiny = jnp.finfo(f32).tiny
    null_thresh = dmax2.astype(f32) * (n2 * eps) ** 2

    rows = jax.lax.broadcasted_iota(jnp.int32, (n2, n2), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (n2, n2), 1)
    q0 = (rows == cols).astype(f32)
    diag_mask = (jax.lax.broadcasted_iota(jnp.int32, (b2, b2), 0)
                 == jax.lax.broadcasted_iota(jnp.int32, (b2, b2), 1)).astype(f32)

    def step(_, carry):
        g, q, max_rel = carry
        # Pair i couples slots (i, b2+i): alpha sits on the diagonal of the
        # top-right coupling block, beta/gamma on the main diagonal.
        alpha = jnp.sum(g[:b2, b2:] * diag_mask, axis=0)[None, :]   # (1, b2)
        beta = jnp.sum(g[:b2, :b2] * diag_mask, axis=0)[None, :]
        gamma = jnp.sum(g[b2:, b2:] * diag_mask, axis=0)[None, :]

        # Convergence statistic: scaled coupling of LIVE pairs, measured
        # before this step's rotation (the quantity the reference computes
        # per pair and discards, lib/JacobiMethods.cu:462).
        denom = (jnp.sqrt(jnp.maximum(beta, tiny))
                 * jnp.sqrt(jnp.maximum(gamma, tiny)))
        rel = jnp.abs(alpha) / jnp.maximum(denom, tiny)
        live = (beta > null_thresh) & (gamma > null_thresh)
        max_rel = jnp.maximum(max_rel,
                              jnp.max(jnp.where(live, rel, f32(0.0))))

        # Rutishauser small-angle rotation (lib/JacobiMethods.cu:466-478);
        # identity on numerically-null couplings.
        safe_a = jnp.where(jnp.abs(alpha) > tiny, alpha, jnp.ones_like(alpha))
        tau = (gamma - beta) / (2.0 * safe_a)
        sgn = jnp.where(tau >= 0, f32(1.0), f32(-1.0))
        t = sgn / (jnp.abs(tau) + jnp.sqrt(1.0 + tau * tau))
        c = jax.lax.rsqrt(1.0 + t * t)
        s = t * c
        rot = jnp.abs(alpha) > tiny
        c = jnp.where(rot, c, f32(1.0))                            # (1, b2)
        s = jnp.where(rot, s, f32(0.0))

        # Congruence G <- J^T G J with J = direct sum of the b2 rotations
        # (J[p,p]=c, J[q,p]=-s, J[p,q]=s, J[q,q]=c in each (p, q) plane),
        # then the same column transform accumulates into Q.
        g = jnp.concatenate(
            [c * g[:, :b2] - s * g[:, b2:], s * g[:, :b2] + c * g[:, b2:]],
            axis=1)
        cT, sT = c.T, s.T                                          # (b2, 1)
        g = jnp.concatenate(
            [cT * g[:b2] - sT * g[b2:], sT * g[:b2] + cT * g[b2:]],
            axis=0)
        q = jnp.concatenate(
            [c * q[:, :b2] - s * q[:, b2:], s * q[:, :b2] + c * q[:, b2:]],
            axis=1)

        # Tournament data rotation: G columns, G rows, and Q columns move
        # identically, so the pairing stays fixed at slots (i, b2+i).
        gt, gb = _rotate_cols(g[:, :b2], g[:, b2:])
        g = jnp.concatenate([gt, gb], axis=1)
        gt, gb = _rotate_rows(g[:b2], g[b2:])
        g = jnp.concatenate([gt, gb], axis=0)
        qt, qb = _rotate_cols(q[:, :b2], q[:, b2:])
        q = jnp.concatenate([qt, qb], axis=1)
        return g, q, max_rel

    _, q, max_rel = jax.lax.fori_loop(
        0, n_steps, step, (g, q0, jnp.zeros((), f32)))
    return q, max_rel


def _pallas_kernel(g_ref, dmax2_ref, q_ref, stat_ref, *, n_steps):
    from jax.experimental import pallas as pl

    q, max_rel = _kernel_body(g_ref[0], dmax2_ref[0], n_steps=n_steps)
    q_ref[0] = q.astype(q_ref.dtype)
    # Whole-array SMEM output: TPU grid steps run sequentially, each writes
    # its own slot (rank-1 SMEM cannot be blocked per grid step).
    stat_ref[pl.program_id(0)] = max_rel


@functools.partial(jax.jit, static_argnames=("interpret",))
def _rotations_call(g, dmax2, *, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    k, n2, _ = g.shape
    n_steps = max(n2 - 1, 1)
    kernel = functools.partial(_pallas_kernel, n_steps=n_steps)
    q, stat = pl.pallas_call(
        kernel,
        grid=(k,),
        in_specs=[
            pl.BlockSpec((1, n2, n2), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, n2, n2), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k, n2, n2), jnp.float32),
            jax.ShapeDtypeStruct((k,), jnp.float32),
        ],
        interpret=interpret,
    )(g.astype(jnp.float32), jnp.reshape(dmax2.astype(jnp.float32), (1,)))
    return q, jnp.max(stat)


def supported(platform: str | None = None) -> bool:
    """True when the Pallas TPU path can run on the current backend."""
    if platform is None:
        platform = jax.default_backend()
    return platform in ("tpu", "axon")


def rotations(g: jax.Array, dmax2: jax.Array, *, interpret: bool | None = None):
    """Inner-tournament rotation generation for a stack of Gram panels.

    Args:
      g: (k, n2, n2) symmetric Gram panels (n2 even).
      dmax2: scalar — GLOBAL max squared column norm (deflation gate scale;
        pmax'd by mesh callers).
      interpret: run the Pallas interpreter (CPU testing). Default: real
        kernel on TPU backends, interpreter elsewhere.

    Returns:
      (q, max_rel): q (k, n2, n2) float32 orthogonal — the accumulated
      product of all n2-1 rounds of pairwise rotations; max_rel — the
      largest LIVE scaled coupling |g_ij|/sqrt(g_ii g_jj) observed across
      every pair met in the tournament (before that pair's rotation).
    """
    if g.ndim != 3 or g.shape[-1] != g.shape[-2] or g.shape[-1] % 2:
        raise ValueError(f"expected (k, n2, n2) panels with even n2, got {g.shape}")
    if interpret is None:
        interpret = not supported()
    return _rotations_call(g, dmax2, interpret=bool(interpret))
